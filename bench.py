"""Headline benchmark: dense PIR queries/sec/chip at a 2^20 x 256B database.

Prints ONE JSON line {"metric", "value", "unit", "vs_baseline"} on stdout —
always, even when the TPU backend cannot be initialized (then with
``"value": 0`` and an ``"error"`` field instead of a crash).

Baseline: the reference's single-threaded AES-NI CPU path
(`experiments/README.md`, see BASELINE.md). A dense PIR query over 2^20
records costs the reference a full-domain expansion of 2^20 128-bit
selection blocks (~2 fixed-key AES ops per block node, `ExpandSeeds`,
`dpf/distributed_point_function.cc:289-372`) plus a 256MB XOR inner product
(`pir/internal/inner_product_hwy.cc`). From the published 2^20-point
direct-eval time (0.67s, ~20 AES levels/point) the per-AES cost is
~16ns/hash single-threaded; expansion ~2*2^20 hashes ~= 34ms, inner
product ~256MB at ~10GB/s ~= 26ms, about 60ms/query => ~16 queries/sec.
BASELINE_QPS encodes that derived figure.

Our server answers the same queries with a fused batched pipeline that
expands only the 2^13 selection blocks that carry bits (see
`distributed_point_functions_tpu/pir/dense_eval.py`) and one database pass
per query batch. The inner product runs through the Pallas packed-bits
kernel (`ops/inner_product_pallas.py`) after an on-device bit-identity
cross-check against the jnp path; it falls back to the jnp path if the
kernel fails to compile or mismatches.

Secondary metrics (stderr + benchmarks/results/bench_extra.json): the
inner-product effective HBM bandwidth in GB/s, and the DPF full-domain
evaluation ns/leaf at log-domain 20 (uint64 values) — the BASELINE
north-star's second metric
(`dpf/distributed_point_function_benchmark.cc:43-95`).

Environment knobs: BENCH_RECORDS (default 2^20), BENCH_RECORD_BYTES (256),
BENCH_QUERIES (128), BENCH_ITERS (16, min 1), BENCH_NO_PALLAS=1 /
BENCH_NO_PALLAS2=1 / BENCH_NO_BITPLANE=1 to skip inner-product tiers,
BENCH_EXPANSION=planes|limb|both|v2 (default planes — the measured-best
single config; "both" restores the A/B; planes/both/v2 also compile the
key-major bitrev-staged v2 rewrite unless BENCH_NO_V2=1), BENCH_NSLEAF=1
to add the
slow-compiling ns/leaf secondary metric, BENCH_ONLY_NSLEAF=1 to run only
it, BENCH_PLATFORM=cpu for a hermetic CPU run, BENCH_INIT_BUDGET to pin
the TOTAL backend-init retry budget (default: adaptive — the watchdog
window minus BENCH_MEASURE_MARGIN [600 s], floored at 300 s, so a tunnel
that answers late in the driver's window still yields a measurement),
and BENCH_TIMEOUT (default 1500 s) for the stall watchdog.
"""

from __future__ import annotations

import json
import math
import os
import signal
import sys
import threading
import time

import numpy as np

BASELINE_QPS = 16.0
# Best driver-reproducible capture committed this round, referenced by
# failure-path error messages so a tunnel outage at bench time cannot
# erase the round's measured result. Update alongside new captures.
# The numeric value travels separately as the machine-readable
# "last_good" field on infra-error emissions (BENCH_r05 lesson: a hung
# init emitted value 0.0 with the real number buried in prose, so the
# regression gate and dashboards conflated a tunnel outage with a
# catastrophic regression).
LAST_CAPTURE_QPS = 7203.53
LAST_CAPTURE_NOTE = (
    "last captured rc=0 run (2026-08-01): 7203.53 q/s at q128 "
    "(benchmarks/results/bench_cold_20260801_082955.json)"
)
# Derived single-thread CPU figure for full-domain eval at 2^20 leaves:
# ~2^21 fixed-key AES ops at ~16 ns plus leaf hashing => ~50 ns/leaf.
BASELINE_NS_PER_LEAF = 50.0


def _log(msg):
    print(f"[bench {time.strftime('%H:%M:%S')}] {msg}", file=sys.stderr,
          flush=True)


_EMIT_LOCK = threading.Lock()


def _nsleaf_ld():
    # Parsed leniently: this runs on the watchdog emitter path, where a
    # malformed env value must not be able to kill the JSON emission.
    try:
        return int(os.environ.get("BENCH_NSLEAF_LD", "20").strip())
    except ValueError:
        return 20


class _SkipSplit(Exception):
    """Control flow: the chosen candidate has no expansion/inner-product
    split to time (the streaming scan fuses them)."""


def _metric_name():
    num_records = int(os.environ.get("BENCH_RECORDS", 1 << 20))
    record_bytes = int(os.environ.get("BENCH_RECORD_BYTES", 256))
    return f"dense_pir_queries_per_sec_chip_{num_records}x{record_bytes}B"


def _default_metric_unit():
    # BENCH_ONLY_NSLEAF / BENCH_SERVING / BENCH_HEAVY_HITTERS runs
    # report their own metric shape from every emitter — including the
    # watchdog thread — so the tee'd file never mixes metric shapes.
    if os.environ.get("BENCH_HEAVY_HITTERS", "") == "1":
        return "heavy_hitters_sweep_lanes_per_sec", "lanes/s"
    if os.environ.get("BENCH_SERVING", "") == "1":
        return "serving_closed_loop_queries_per_sec", "queries/s"
    if os.environ.get("BENCH_OVERLOAD", "") == "1":
        return "serving_overload_goodput_queries_per_sec", "queries/s"
    if os.environ.get("BENCH_ONLY_NSLEAF", "") == "1":
        ld = _nsleaf_ld()
        return f"dpf_full_domain_eval_ns_per_leaf_ld{ld}_u64", "ns/leaf"
    return _metric_name(), "queries/s"


def _emit(value, vs_baseline, error=None, status=None, last_good=None):
    """Print the single JSON result line and append it to the
    trajectory store (`benchmarks/results/history.jsonl`) that
    `benchmarks/regression_gate.py` enforces.

    `status` partitions failures for the gate: "ok" (a real
    measurement; the default without an error), "infra_error" (the
    harness/tunnel failed — hung init, watchdog stall with nothing
    banked; never enters the gate's rolling median), and "error"
    (the bench itself failed; also excluded from the median). On
    non-ok emissions `last_good` carries the previous capture's value
    machine-readably instead of stuffing it into the error prose.
    """
    metric, unit = _default_metric_unit()
    if status is None:
        status = "ok" if not error else "error"
    line = {
        "metric": metric,
        "value": round(float(value), 2),
        "unit": unit,
        "vs_baseline": round(float(vs_baseline), 2),
        "status": status,
    }
    if error:
        line["error"] = str(error)[:400]
    if last_good is not None:
        line["last_good"] = round(float(last_good), 2)
    # Single-shot under a lock: the watchdog thread and the main thread
    # both funnel through here, and exactly one JSON line may print.
    with _EMIT_LOCK:
        if _PROGRESS["done"]:
            return
        _PROGRESS["done"] = True
        print(json.dumps(line), flush=True)
    _append_history(line)


def _append_history(line):
    """Best-effort history append — runs on the watchdog thread too
    (before its os._exit), so it must never raise and never block on
    device state. BENCH_HISTORY=0 disables; BENCH_HISTORY_PATH
    overrides the store location."""
    if os.environ.get("BENCH_HISTORY", "1") == "0":
        return
    try:
        from benchmarks.regression_gate import append_record, git_rev

        record = dict(line)
        record["git_rev"] = git_rev()
        record["device"] = _PROGRESS.get("device", "unknown")
        record["topology"] = _PROGRESS.get("topology", "unknown")
        _stamp_stack(record)
        append_record(
            record,
            path=os.environ.get(
                "BENCH_HISTORY_PATH", "benchmarks/results/history.jsonl"
            ),
        )
    except Exception as e:  # noqa: BLE001 - history must not break a bench
        try:
            _log(f"history append failed (non-fatal): {e}")
        except Exception:
            pass


def _stamp_stack(record):
    """Stamp the software/hardware stack onto a history record so the
    gate's rolling median never mixes runs from different stacks.
    `jax.__version__` is a cheap module attribute; the backend comes
    from `_PROGRESS` (set by `_ensure_backend`) because calling
    `jax.default_backend()` here could trigger backend init from the
    watchdog thread."""
    try:
        import jax

        record["jax_version"] = jax.__version__
    except Exception:  # noqa: BLE001 - stamps are best-effort
        pass
    backend = _PROGRESS.get("device")
    if backend and backend != "unknown":
        record["backend"] = backend


def _append_latency_record(metric, p50_ms, p99_ms=None, samples=1):
    """Append one latency record (direction: lower, unit ms) to the
    history store — the latency half of the regression gate's evidence.
    Best-effort, like all history appends."""
    if os.environ.get("BENCH_HISTORY", "1") == "0":
        return
    try:
        from benchmarks.regression_gate import append_record, git_rev

        record = {
            "metric": metric,
            "value": round(float(p50_ms), 4),
            "unit": "ms",
            "direction": "lower",
            "samples": int(samples),
            "status": "ok",
            "git_rev": git_rev(),
            "device": _PROGRESS.get("device", "unknown"),
            "topology": _PROGRESS.get("topology", "unknown"),
        }
        if p99_ms is not None:
            record["p99"] = round(float(p99_ms), 4)
        _stamp_stack(record)
        append_record(
            record,
            path=os.environ.get(
                "BENCH_HISTORY_PATH", "benchmarks/results/history.jsonl"
            ),
        )
    except Exception as e:  # noqa: BLE001 - history must not break a bench
        try:
            _log(f"latency history append failed (non-fatal): {e}")
        except Exception:
            pass


def _emit_latency_records(source: str):
    """Append the phase waterfall accumulated by the process-wide
    `PhaseRecorder` over everything the bench ran: one end-to-end
    record per role plus one per (role, phase), p50 as the judged
    value with p99 alongside."""
    try:
        from distributed_point_functions_tpu.observability import (
            default_phase_recorder,
        )

        waterfall = default_phase_recorder().waterfall()
    except Exception:  # noqa: BLE001 - observability only
        return
    for role, summary in waterfall.items():
        e2e = summary["end_to_end_ms"]
        if e2e["count"]:
            _append_latency_record(
                f"{source}_{role}_e2e_ms", e2e["p50_ms"],
                p99_ms=e2e["p99_ms"], samples=e2e["count"],
            )
        for phase, entry in summary["phases"].items():
            if entry["count"]:
                _append_latency_record(
                    f"{source}_{role}_phase_{phase}_ms", entry["p50_ms"],
                    p99_ms=entry["p99_ms"], samples=entry["count"],
                )


def _emit_critical_path_records(source: str):
    """Append the cross-party critical-path profile accumulated by the
    process-wide `CriticalPathAnalyzer`: one record per (party, phase)
    that the merged two-party timelines charged critical time to
    (direction: lower — the regression gate watches where the p99 goes,
    e.g. `hh_critical_helper_helper_net_ms` creeping up means the wire
    leg is eating the budget)."""
    try:
        from distributed_point_functions_tpu.observability import (
            default_analyzer,
        )

        profile = default_analyzer().export()["profile"]
    except Exception:  # noqa: BLE001 - observability only
        return
    for party, phases in profile.items():
        for phase, entry in phases.items():
            if entry["count"]:
                _append_latency_record(
                    f"{source}_critical_{party}_{phase}_ms",
                    entry["p50_ms"],
                    p99_ms=entry["p99_ms"],
                    samples=entry["count"],
                )


class _InitTimeout(RuntimeError):
    pass


def _init_budget_secs(timeout=None):
    """Total backend-init retry budget in seconds.

    An explicit BENCH_INIT_BUDGET wins (capture queues set 120 s and gate
    stages on their own tunnel probe). Otherwise the budget is adaptive:
    everything the watchdog window allows minus the margin a warm-cache
    compile+measure+emit needs (BENCH_MEASURE_MARGIN, default 600 s) —
    r04 lesson (BENCH_r04.json): the fixed 300 s budget gave up on a
    tunnel that the 1500 s watchdog would have allowed to answer at
    minute 10 of the driver's window and still produce a measurement.
    """
    explicit = os.environ.get("BENCH_INIT_BUDGET", "").strip()
    if explicit:
        try:
            return float(explicit)
        except ValueError:
            _log(
                f"WARNING: unparsable BENCH_INIT_BUDGET={explicit!r} "
                "ignored; using the adaptive budget"
            )
    if timeout is None:
        try:
            timeout = float(os.environ.get("BENCH_TIMEOUT", 1500))
        except ValueError:
            timeout = 1500.0
    try:
        margin = float(os.environ.get("BENCH_MEASURE_MARGIN", 600))
    except ValueError:
        margin = 600.0
    # Floored at 300 s for sane timeouts, but never allowed to outlive
    # the global watchdog itself (small BENCH_TIMEOUT values cap below
    # the floor: e.g. 350 s timeout -> 230 s init budget).
    budget = max(300.0, timeout - margin)
    return min(budget, max(60.0, timeout - 120))


# Shared progress state for the global watchdog: the main thread records
# the current stage (and the headline figure once measured); if the TPU
# tunnel stalls mid-run — observed 2026-07-30: an execution that normally
# takes 30 ms simply never returns, stuck inside block_until_ready where
# no Python signal handler can fire — a daemon thread emits the JSON line
# (best-known value, error noting the stage) and hard-exits the process.
_PROGRESS = {"stage": "startup", "qps": None, "done": False}


def _start_watchdog():
    # Default must exceed _ensure_backend's total budget (adaptive,
    # timeout - BENCH_MEASURE_MARGIN) plus one cold compile of the single
    # headline config (~320s worst observed) with headroom, while staying
    # well inside the driver's window.
    timeout = float(os.environ.get("BENCH_TIMEOUT", 1500))
    # A hung `jax.devices()` blocks the main thread inside a C call where
    # neither SIGALRM handlers nor the retry loop can run (observed r02:
    # the 240 s alarm fired at 1502 s), so the init stage gets its own
    # thread-enforced deadline: total init budget + jax-import slack.
    init_budget = _init_budget_secs(timeout)
    init_deadline = time.monotonic() + init_budget + 120

    _PROGRESS["deadline"] = time.monotonic() + timeout

    def watch():
        deadline = _PROGRESS["deadline"]
        while time.monotonic() < deadline:
            time.sleep(5)
            if _PROGRESS["done"]:
                return
            if (
                _PROGRESS["stage"] == "backend-init"
                and time.monotonic() > init_deadline
            ):
                _log(
                    "WATCHDOG: backend init exceeded its "
                    f"{init_budget:.0f}s budget (hung device call); "
                    "emitting and exiting"
                )
                _emit(
                    0.0,
                    0.0,
                    error=(
                        f"TPU backend init hung past {init_budget:.0f}s "
                        "budget (tunnel down?); " + LAST_CAPTURE_NOTE
                    ),
                    status="infra_error",
                    last_good=LAST_CAPTURE_QPS,
                )
                os._exit(1)
        if _PROGRESS["done"]:
            return
        qps = _PROGRESS["qps"]
        _log(
            f"WATCHDOG: no completion after {timeout:.0f}s "
            f"(stage: {_PROGRESS['stage']}); emitting and exiting"
        )
        # A banked qps is a real (if early) measurement: emit it as ok
        # so the gate judges it. Nothing banked means the harness never
        # got far enough to measure — an infra error, not a zero.
        _emit(
            qps or 0.0,
            (qps or 0.0) / BASELINE_QPS,
            error=f"watchdog timeout after {timeout:.0f}s during "
            f"stage '{_PROGRESS['stage']}' (TPU tunnel stall?); "
            + LAST_CAPTURE_NOTE,
            status="ok" if qps else "infra_error",
            last_good=None if qps else LAST_CAPTURE_QPS,
        )
        os._exit(1 if qps is None else 0)

    t = threading.Thread(target=watch, daemon=True, name="bench-watchdog")
    t.start()


def _ensure_backend(jax, total_budget_secs=None, per_attempt_secs=150):
    """Initialize the JAX backend with bounded retries and a watchdog.

    Round-1 failure mode (BENCH_r01.json): the axon TPU backend raised
    `RuntimeError: Unable to initialize backend` at the first device op and
    the bench crashed without emitting its JSON line. Backend init can also
    *hang* over the tunnel, so each attempt runs under a SIGALRM watchdog.
    Round-2 failure mode (BENCH_r02.json): five 240 s attempts plus backoff
    serialized to ~28 min and blew the driver's budget — so the retry loop
    runs under one TOTAL wall-clock budget (_init_budget_secs: explicit
    BENCH_INIT_BUDGET, or adaptively the watchdog window minus the
    measure margin — r04 lesson: a fixed 300 s budget wasted tunnels that
    answered later in the driver's window). On exhaustion: emit the JSON
    line, point at the committed capture.
    Returns (devices, None) or (None, last_error).
    """
    if total_budget_secs is None:
        total_budget_secs = _init_budget_secs()
    deadline = time.monotonic() + total_budget_secs
    last_err = None
    delay = 15
    attempt = 0
    while True:
        attempt += 1
        remaining = deadline - time.monotonic()
        if remaining <= 5:
            break
        attempt_secs = int(min(per_attempt_secs, remaining))
        def _on_alarm(signum, frame):
            raise _InitTimeout(
                f"backend init timed out after {attempt_secs}s"
            )

        old_handler = signal.signal(signal.SIGALRM, _on_alarm)
        signal.alarm(attempt_secs)
        t0 = time.perf_counter()
        try:
            devs = jax.devices()
            # Touch the device so lazy init really completed.
            jax.device_put(np.zeros(8, np.uint32)).block_until_ready()
            signal.alarm(0)
            _log(
                f"backend ok in {time.perf_counter() - t0:.1f}s: "
                f"{[str(d) for d in devs]}"
            )
            # Topology for the history record (read by _append_history
            # on every later emit, including the watchdog's).
            _PROGRESS["device"] = getattr(devs[0], "platform", "unknown")
            _PROGRESS["topology"] = f"{len(devs)}x{jax.process_count()}"
            return devs, None
        except Exception as e:  # noqa: BLE001 - must never crash the bench
            last_err = e
            _log(
                f"backend init attempt {attempt} failed after "
                f"{time.perf_counter() - t0:.1f}s "
                f"({deadline - time.monotonic():.0f}s of budget left): "
                f"{str(e).splitlines()[0]}"
            )
            # Clear JAX's cached init failure so the next attempt retries
            # from scratch.
            try:
                from jax._src import xla_bridge

                xla_bridge._clear_backends()
            except Exception:
                pass
        finally:
            signal.alarm(0)
            signal.signal(signal.SIGALRM, old_handler)
        remaining = deadline - time.monotonic()
        if remaining <= 5:
            break
        # Always pause between attempts (clamped to the budget) so a
        # fast-failing backend can't spin thousands of attempts into the
        # tail of the budget window.
        time.sleep(min(delay, remaining - 5))
        delay = min(delay * 2, 60)
    return None, last_err


_WARM_CHILD_CODE = """
import os, signal, time
import jax
p = os.environ.get("BENCH_PLATFORM")
if p:
    jax.config.update("jax_platforms", p)
cache = os.environ.get("BENCH_CACHE_DIR")
if cache:
    try:
        jax.config.update("jax_compilation_cache_dir", cache)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    except Exception:
        pass
# A second-client init hang on the single-client tunnel must kill the
# child FAST (SIGALRM's default action terminates even inside a C
# call), so the parent reads a quick 'env' instead of burning the
# whole warm timeout before warming in-process anyway.
try:
    signal.alarm(int(float(os.environ.get("BENCH_WARM_INIT_BUDGET", "120"))))
except Exception:
    pass
import numpy as np
jax.device_put(np.zeros(4, np.uint32)).block_until_ready()
signal.alarm(0)
m = os.environ.get("BENCH_WARM_MARKER")
if m:
    open(m, "w").write("warm")
if os.environ.get("DPF_TPU_FAULT_WARM_HANG", "") == "1":
    time.sleep(3600)  # test-only: simulate a hung self-check compile
from distributed_point_functions_tpu.pir import dense_eval_planes as dep
dep.warm_level_kernels()
"""


def _run_bounded_child(argv, extra_env, marker_env, timeout_var):
    """Run a child process under a hard timeout with the marker
    discipline shared by the kernel-warm and serving-vet stages: the
    child writes the marker file when it reaches its dangerous stage, so
    the parent can tell compile-stage evidence from environment
    ambiguity. Returns (status, returncode, marker_seen, seconds) with
    status in {"done", "timeout", "error"}."""
    import subprocess
    import tempfile

    try:
        timeout = float(os.environ.get(timeout_var, 900))
    except ValueError:
        timeout = 900.0
    remaining = _PROGRESS.get("deadline", 0) - time.monotonic()
    timeout = max(10.0, min(timeout, remaining - 300))
    marker = os.path.join(
        tempfile.gettempdir(), f"{marker_env.lower()}_{os.getpid()}.marker"
    )
    try:
        os.unlink(marker)
    except OSError:
        pass
    env = dict(os.environ, **extra_env)
    env[marker_env] = marker
    t0 = time.perf_counter()
    status, rc = "done", None
    try:
        proc = subprocess.run(
            argv, env=env, timeout=timeout, capture_output=True
        )
        rc = proc.returncode
    except subprocess.TimeoutExpired:
        status = "timeout"
    except Exception as e:  # noqa: BLE001 - child vetting is best-effort
        first = (str(e).splitlines() or ["<no message>"])[0]
        _log(f"bounded child unavailable ({first})")
        status = "error"
    marker_seen = os.path.exists(marker)
    try:
        os.unlink(marker)
    except OSError:
        pass
    return status, rc, marker_seen, time.perf_counter() - t0


def _warm_kernels_subprocess():
    """Run the first kernel warmup (self-check Mosaic compiles) in a
    killable child. Verdicts persist via the shared cache file, so a
    successful child makes the parent's in-process warm free. Returns
    "ok", "env" (the child never reached the self-check stage — tunnel
    ambiguity, parent warms in-process as before), or "hang" (the child
    reached it and went silent OR died abnormally there — the parent
    must NOT repeat those compiles in-process)."""
    status, rc, marker_seen, secs = _run_bounded_child(
        [sys.executable, "-c", _WARM_CHILD_CODE], {},
        "BENCH_WARM_MARKER", "BENCH_WARM_TIMEOUT",
    )
    if status == "error":
        verdict = "env"
    elif status == "timeout" or rc != 0:
        # A timeout or an abnormal death (segfaulting Mosaic compile)
        # after the marker is compile-stage evidence: re-running the
        # same compiles in-process could kill the parent before the
        # banked JSON ever prints.
        verdict = "hang" if marker_seen else "env"
    else:
        verdict = "ok"
    _log(f"kernel warmup child: {verdict} ({secs:.0f}s, rc={rc})")
    return verdict


def _slope_time(fn, iters, reps=3):
    """Min-of-reps slope timing: time(1 call) vs time(1+N calls) with one
    host readback each; the slope isolates device time per call under the
    remote-TPU tunnel's ~60ms readback latency (execution is in-order)."""

    def timed(n):
        t0 = time.perf_counter()
        out = None
        for _ in range(n):
            out = fn()
        np.asarray(out)
        return time.perf_counter() - t0

    for attempt_iters in (iters, 4 * iters):
        t_small = min(timed(1) for _ in range(reps))
        t_big = min(timed(1 + attempt_iters) for _ in range(reps))
        if t_big > t_small:
            return (t_big - t_small) / attempt_iters, t_small
        _log(
            f"WARNING: non-positive slope (t1={t_small * 1e3:.1f} ms, "
            f"tN={t_big * 1e3:.1f} ms); retrying with more iterations"
        )
    return None, t_small


def _ns_per_leaf(jax, extra):
    """Secondary metric: single-key full-domain eval ns/leaf, uint64
    values (reference: `distributed_point_function_benchmark.cc:43-95`).
    BENCH_NSLEAF_LD picks the log-domain (default 20; measure 24 too so
    the number isn't a small-domain artifact — VERDICT r02 item 7)."""
    from distributed_point_functions_tpu.dpf import (
        DistributedPointFunction,
        DpfParameters,
    )
    from distributed_point_functions_tpu.value_types import IntType

    log_domain = _nsleaf_ld()
    dpf = DistributedPointFunction.create(
        DpfParameters(log_domain_size=log_domain, value_type=IntType(64))
    )
    key0, _ = dpf.generate_keys(12345 % (1 << log_domain), 42)

    def run():
        ctx = dpf.create_evaluation_context(key0)
        return dpf.evaluate_next([], ctx)

    _log(f"ns/leaf: compiling full-domain eval (log domain {log_domain}, uint64)")
    t0 = time.perf_counter()
    out = run()
    np.asarray(out)
    _log(f"ns/leaf: first run {time.perf_counter() - t0:.1f}s")
    per_call, _ = _slope_time(run, 4)
    if per_call is None:
        _log("ns/leaf: degenerate slope; skipping")
        return
    leaves = 1 << log_domain
    ns = per_call / leaves * 1e9
    extra[f"dpf_full_domain_eval_ns_per_leaf_ld{log_domain}_u64"] = {
        "value": round(ns, 3),
        "unit": "ns/leaf",
        "vs_baseline_cpu": round(BASELINE_NS_PER_LEAF / ns, 2)
        if ns > 0
        else 0.0,
    }
    _log(
        f"ns/leaf: {ns:.2f} ns/leaf "
        f"({BASELINE_NS_PER_LEAF / ns:.1f}x the derived CPU figure)"
    )


def main():
    num_records = int(os.environ.get("BENCH_RECORDS", 1 << 20))
    record_bytes = int(os.environ.get("BENCH_RECORD_BYTES", 256))
    # 128-query batches measured fastest per query on hardware
    # (2026-07-31: q64 5601, q128 6602, q256 5065 q/s at 2^20 x 256 B).
    num_queries = int(os.environ.get("BENCH_QUERIES", 128))
    iters = max(1, int(os.environ.get("BENCH_ITERS", 16)))

    # Reset shared progress state: main() runs once per process in
    # production, but in-process callers (the ladder tests) invoke it
    # repeatedly and a stale done=True would suppress _emit entirely.
    _PROGRESS.update(
        stage="startup", qps=None, done=False,
        device=os.environ.get("BENCH_PLATFORM", "") or "unknown",
        topology="unknown",
    )
    # BENCH_VET_ONLY=1: child mode for the wedge-proof serving vet —
    # compile ONLY the auto planes candidate and exit. Exit codes: 0
    # compile landed, 1 compile errored, 2 environment failure (backend
    # init — e.g. the single-client tunnel refusing a second client);
    # only a hang AFTER the BENCH_VET_MARKER file appears counts as
    # compile-stage evidence for the parent.
    vet_mode = os.environ.get("BENCH_VET_ONLY", "") == "1"
    _start_watchdog()
    _PROGRESS["stage"] = "backend-init"

    import jax

    # The environment's sitecustomize forces jax_platforms="axon,cpu" at
    # interpreter startup, overriding a plain JAX_PLATFORMS=cpu env var.
    # BENCH_PLATFORM wins over both (config updates after import do), so a
    # hermetic CPU run is possible while the tunnel is down.
    platform = os.environ.get("BENCH_PLATFORM", "")
    if platform:
        jax.config.update("jax_platforms", platform)

    # Persistent compilation cache: repeat bench runs skip the (large)
    # bitsliced-AES XLA compile.
    cache_dir = os.environ.get(
        "BENCH_CACHE_DIR", os.path.expanduser("~/.cache/jax_bench")
    )
    try:
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    except Exception:
        pass

    if os.environ.get("BENCH_HEAVY_HITTERS", "") == "1":
        # Heavy-hitters sweep benchmark (BENCH_HEAVY_HITTERS=1): full
        # two-server sweeps across a clients x domain x threshold grid,
        # each point checked against the plaintext oracle; the headline
        # value is fused (key, prefix) evaluation lanes per second and
        # vs_baseline is the cut-state-resume speedup over re-expanding
        # every level from the root. CPU-scale like BENCH_SERVING, so it
        # runs before _ensure_backend.
        _PROGRESS["stage"] = "heavy-hitters-bench"
        try:
            from benchmarks.heavy_hitters_bench import (
                run_heavy_hitters_bench,
            )

            report = run_heavy_hitters_bench()
            _emit(
                report["best_lanes_per_sec"],
                report.get("resume_speedup") or 0.0,
                error=None
                if report["correctness_ok"]
                else "private sweep diverged from the plaintext oracle",
            )
            _emit_latency_records("hh")
            _emit_critical_path_records("hh")
        except Exception as e:  # noqa: BLE001 - the JSON line must print
            _emit(
                0.0, 0.0,
                error=f"heavy-hitters bench failed: "
                f"{str(e).splitlines()[0][:200]}",
            )
        return

    if os.environ.get("BENCH_SERVING", "") == "1":
        # Closed-loop serving benchmark (BENCH_SERVING=1): drive the
        # serving/ runtime's dynamic batcher against the serialized
        # one-request-at-a-time baseline and emit ONE JSON line in the
        # headline format; vs_baseline is the batched/unbatched speedup.
        # Runs before _ensure_backend: it is a CPU-scale sweep
        # (BENCH_PLATFORM=cpu is the intended setting) and must not
        # depend on the TPU tunnel.
        _PROGRESS["stage"] = "serving-bench"
        try:
            from benchmarks.serving_bench import run_serving_bench

            report = run_serving_bench()
            best = report["best_batched_qps"]
            base = report["best_unbatched_qps"]
            _emit(
                best,
                (best / base) if base else 0.0,
                error=None
                if report["correctness_ok"]
                else "batched responses diverged from the unbatched oracle",
            )
            _emit_latency_records("serving")
            _emit_critical_path_records("serving")
        except Exception as e:  # noqa: BLE001 - the JSON line must print
            _emit(
                0.0, 0.0,
                error=f"serving bench failed: "
                f"{str(e).splitlines()[0][:200]}",
            )
        return

    if os.environ.get("BENCH_OVERLOAD", "") == "1":
        # Overload benchmark (BENCH_OVERLOAD=1): offered-load ladder
        # through cost-aware admission; headline is goodput at the
        # highest over-capacity point (direction: higher — a drop means
        # the shed-early contract regressed into queue collapse).
        # vs_baseline is goodput retention vs the same run's saturation
        # point. CPU-scale, runs before _ensure_backend like serving.
        _PROGRESS["stage"] = "overload-bench"
        try:
            from benchmarks.overload_bench import run_overload_bench

            report = run_overload_bench()
            _emit(
                report["overloaded_goodput_qps"],
                report["goodput_retention"],
                error=None
                if report["correctness_ok"]
                else "responses under overload diverged from the oracle",
            )
        except Exception as e:  # noqa: BLE001 - the JSON line must print
            _emit(
                0.0, 0.0,
                error=f"overload bench failed: "
                f"{str(e).splitlines()[0][:200]}",
            )
        return

    # Pre-warm the backend BEFORE building the 256MB host database, with
    # retries; on failure emit the JSON line instead of crashing. The
    # error references the last driver-reproducible capture committed in
    # benchmarks/results/ so a tunnel outage at bench time doesn't erase
    # the round's measured result.
    devs, err = _ensure_backend(jax)
    if devs is None:
        if vet_mode:
            # Environment failure, not kernel evidence: the parent must
            # not read this as a compile verdict.
            _PROGRESS["done"] = True
            os._exit(2)
        _emit(
            0.0,
            0.0,
            error=(
                f"TPU backend unreachable "
                f"({str(err).splitlines()[0][:160]}); " + LAST_CAPTURE_NOTE
            ),
            status="infra_error",
            last_good=LAST_CAPTURE_QPS,
        )
        return

    if os.environ.get("BENCH_ONLY_NSLEAF", "") == "1":
        # Capture-window helper: just the secondary metric, emitted
        # through _emit so the watchdog's single-line guarantee (and the
        # ns/leaf metric shape, via _default_metric_unit) still holds.
        _PROGRESS["stage"] = "ns-leaf"
        extra = {}
        err = None
        try:
            _ns_per_leaf(jax, extra)
        except Exception as e:  # noqa: BLE001
            err = f"ns/leaf failed: {str(e).splitlines()[0][:200]}"
        m = extra.get(
            f"dpf_full_domain_eval_ns_per_leaf_ld{_nsleaf_ld()}_u64"
        )
        if m is None and err is None:
            err = "ns/leaf slope degenerate; no measurement"
        _emit(
            m["value"] if m else 0.0,
            m["vs_baseline_cpu"] if m else 0.0,
            error=err,
        )
        return

    from distributed_point_functions_tpu.ops.inner_product import (
        xor_inner_product,
        xor_inner_product_bitplane,
    )
    from distributed_point_functions_tpu.ops.inner_product_pallas import (
        permute_db_bitmajor,
        xor_inner_product_pallas2_staged,
        xor_inner_product_pallas_staged,
    )
    from distributed_point_functions_tpu.pir.client import DenseDpfPirClient
    from distributed_point_functions_tpu.pir.dense_eval import (
        evaluate_selection_blocks,
        stage_keys,
    )

    rng = np.random.default_rng(7)
    _PROGRESS["stage"] = "build-db"

    # Database straight to device (skip host record packing for 256MB).
    num_padded = ((num_records + 127) // 128) * 128
    num_words = record_bytes // 4
    db_host = rng.integers(
        0, 1 << 32, (num_padded, num_words), dtype=np.uint32
    )
    db_words = jax.device_put(db_host)

    num_blocks = num_padded // 128
    total_levels = max(0, math.ceil(math.log2(num_records)))
    expand_levels = min(max(0, (num_blocks - 1).bit_length()), total_levels)
    walk_levels = total_levels - expand_levels

    client = DenseDpfPirClient.create(num_records, lambda pt, ci: pt)
    indices = [int(i) for i in rng.integers(0, num_records, num_queries)]
    keys0, keys1 = client._generate_key_pairs(indices)
    # Host-side zeros-walk during staging (mirrors serving's default;
    # DPF_TPU_HOST_WALK=0 restores the on-device walk). Serving pays the
    # walk per fresh key batch, so the reported q/s includes its host
    # cost even though it runs outside the device step.
    from distributed_point_functions_tpu.utils.runtime import (
        host_walk_enabled,
    )

    host_walk = walk_levels if host_walk_enabled() else 0
    host_walk_s = 0.0
    if host_walk:
        from distributed_point_functions_tpu.pir.dense_eval import (
            _walk_zeros_host,
        )

        plain = [np.asarray(a) for a in stage_keys(keys0)]
        reps = []
        for _ in range(5):
            t0 = time.perf_counter()
            _walk_zeros_host(
                plain[0], plain[1], plain[2], plain[3], plain[4], host_walk
            )
            reps.append(time.perf_counter() - t0)
        host_walk_s = min(reps)
        _log(
            f"host zeros-walk: {host_walk} levels in "
            f"{host_walk_s * 1e3:.3f} ms per {num_queries}-key batch "
            "(counted in q/s)"
        )
    staged = stage_keys(keys0, host_walk_levels=host_walk)
    walk_levels -= host_walk

    # Choose the inner-product path: the Pallas packed-bits kernel if it
    # compiles and is bit-identical to the jnp path on this device.
    def verify_ip(name, fn, staged_layout):
        """Cross-check a candidate inner product against the XOR path on
        a small on-device instance; returns True when bit-identical."""
        try:
            check_db = jax.device_put(
                rng.integers(0, 1 << 32, (4096, num_words), dtype=np.uint32)
            )
            check_sel = jax.device_put(
                rng.integers(0, 1 << 32, (4, 32, 4), dtype=np.uint32)
            )
            arg = (
                permute_db_bitmajor(check_db) if staged_layout else check_db
            )
            got = np.asarray(fn(arg, check_sel))
            want = np.asarray(xor_inner_product(check_db, check_sel))
            if not np.array_equal(got, want):
                raise RuntimeError(f"{name}/jnp mismatch on device")
            _log(f"inner product: {name} path (verified)")
            return True
        except Exception as e:  # noqa: BLE001
            _log(
                f"inner product: {name} path unavailable "
                f"({str(e).splitlines()[0]})"
            )
            return False

    _PROGRESS["stage"] = "ip-check"
    no_pallas = os.environ.get("BENCH_NO_PALLAS", "") == "1"
    use_pallas2 = (
        not no_pallas
        and os.environ.get("BENCH_NO_PALLAS2", "") != "1"
        and verify_ip(
            "pallas2", xor_inner_product_pallas2_staged, staged_layout=True
        )
    )
    use_pallas = (
        not use_pallas2
        and not no_pallas
        and verify_ip(
            "pallas", xor_inner_product_pallas_staged, staged_layout=True
        )
    )
    # Bit-plane jnp path (same MXU math as Pallas, no Mosaic): the middle
    # choice when the Pallas kernels fail on this device/backend.
    use_bitplane = (
        not (use_pallas2 or use_pallas)
        and jax.default_backend() == "tpu"
        and os.environ.get("BENCH_NO_BITPLANE", "") != "1"
        and verify_ip(
            "bitplane", xor_inner_product_bitplane, staged_layout=True
        )
    )
    ip_name = (
        "pallas2" if use_pallas2
        else "pallas" if use_pallas
        else "bitplane" if use_bitplane
        else "jnp"
    )
    if ip_name != "jnp":
        # Stage the bit-major layout once (the serving path does the same).
        db_words = jax.block_until_ready(permute_db_bitmajor(db_words))
        inner_product = {
            "pallas2": xor_inner_product_pallas2_staged,
            "pallas": xor_inner_product_pallas_staged,
            "bitplane": xor_inner_product_bitplane,
        }[ip_name]
    else:
        inner_product = xor_inner_product

    def make_pir_step(expand_fn):
        @jax.jit
        def pir_step(s0, c0, cw_s, cw_l, cw_r, vc, db):
            selections = expand_fn(
                s0, c0, cw_s, cw_l, cw_r, vc,
                walk_levels=walk_levels,
                expand_levels=expand_levels,
                num_blocks=num_blocks,
            )
            return inner_product(db, selections)

        return pir_step

    # Expansion A/B: the per-level limb kernel vs the plane-resident
    # expansion (BENCH_EXPANSION={both,limb,planes}); both are timed and
    # the faster serves the headline. Outputs are verified identical on
    # device before either is trusted.
    from distributed_point_functions_tpu.pir.dense_eval_planes import (
        evaluate_selection_blocks_planes,
    )

    # Default to the single known-best serving config (planes expansion at
    # q128 — 6,601.9 q/s on 2026-07-31 hardware) so a driver run compiles
    # exactly one pipeline; the limb path stays available as a fallback and
    # the A/B moves behind BENCH_EXPANSION=both.
    expand_mode = os.environ.get("BENCH_EXPANSION", "planes")
    if expand_mode not in ("both", "limb", "planes", "v2"):
        _emit(0.0, 0.0, error=f"invalid BENCH_EXPANSION={expand_mode!r} "
              "(expected both|limb|planes|v2)", status="infra_error")
        return
    import functools

    candidate_defs = {}
    if expand_mode in ("both", "limb"):
        candidate_defs["limb"] = make_pir_step(evaluate_selection_blocks)
    if expand_mode in ("both", "planes"):
        # force_planes: the A/B must really time the planes kernel (the
        # small-batch padding guard would silently reroute tiny query
        # counts to the limb kernel and mislabel the timing).
        candidate_defs["planes"] = make_pir_step(
            functools.partial(
                evaluate_selection_blocks_planes, force_planes=True
            )
        )

    _PROGRESS["stage"] = "compile"
    _log(
        f"compiling: {num_records} records x {record_bytes}B, "
        f"{num_queries} queries, walk={walk_levels}(+{host_walk} host) "
        f"expand={expand_levels}"
    )
    timings = {}
    latencies = {}
    outputs = {}
    candidates = {}
    # Per-candidate database override: the v2 bitrev-staged pipeline
    # serves against its own block-permuted staging of the same records.
    db_for = {}

    def _db(name):
        return db_for.get(name, db_words)

    # Lazily-built party-1 staging for the share-correctness check.
    share_state = {}

    def _try_compile(name, step):
        t_c = time.perf_counter()
        try:
            outputs[name] = np.asarray(step(*staged, _db(name)))
        except Exception as e:  # noqa: BLE001
            _log(f"expansion[{name}] failed to compile/run: "
                 f"{str(e).splitlines()[0]}")
            return False
        candidates[name] = step
        _log(
            f"expansion[{name}]: compile+first run "
            f"{time.perf_counter() - t_c:.1f}s"
        )
        return True

    def _share_check(name):
        # End-to-end share-correctness at serving shape (replaces the
        # limb/planes cross-check the single-config default no longer
        # runs): the same compiled step answers party 1's keys, and the
        # XOR of the two parties' responses must equal the queried
        # records bit-exactly. Cost: one execution per candidate.
        try:
            if not share_state:
                share_state["staged1"] = stage_keys(
                    keys1, host_walk_levels=host_walk
                )
                share_state["want"] = db_host[np.asarray(indices)]
            resp1 = np.asarray(
                candidates[name](*share_state["staged1"], _db(name))
            )
            ok = np.array_equal(
                outputs[name] ^ resp1, share_state["want"]
            )
        except Exception as e:  # noqa: BLE001
            _log(f"share-correctness[{name}] failed to run: "
                 f"{str(e).splitlines()[0]}")
            return True  # don't drop a path over a check-infra error
        if ok:
            _log(f"share-correctness[{name}]: ok "
                 f"({num_queries} queries reconstructed exactly)")
            share_state.setdefault("checked", set()).add(name)
        else:
            _log(f"WARNING: {name} pipeline fails share-correctness "
                 "on device; dropping")
            del candidates[name]
            # Drop any banked measurement with it: a stale timings entry
            # would let `best = min(timings)` select a candidate that no
            # longer exists and KeyError at serving-selection time.
            timings.pop(name, None)
            latencies.pop(name, None)
        return ok

    def _bank(name):
        # Measure a candidate the moment it is trusted and record the
        # provisional q/s, so the stall watchdog always has the best
        # measured figure to emit — r04 stage-1 lesson: a valid limb
        # measurement existed, yet the watchdog reported 0.0 because
        # nothing was banked until after the (never-finished) retry.
        per, lat = _slope_time(
            lambda: candidates[name](*staged, _db(name)), iters
        )
        if per is not None:
            timings[name] = per
            latencies[name] = lat
            qps = num_queries / (per + host_walk_s)
            _log(
                f"expansion[{name}]: per-batch {per * 1e3:.3f} ms "
                f"({qps:.0f} q/s) [banked]"
            )
            if qps > (_PROGRESS["qps"] or 0.0):
                _PROGRESS["qps"] = qps

    auto_mode = os.environ.get("DPF_TPU_LEVEL_KERNEL", "auto") == "auto"
    if auto_mode and "planes" in candidate_defs and not vet_mode:
        # Bank the proven-reliable mode FIRST: planes expansion on the
        # plain XLA levels (the r02 headline mode, 6,601.9 q/s) compiles
        # and measures before any Pallas self-check or auto-pipeline
        # compile spends the budget — on r04 hardware the auto pipeline
        # failed Mosaic compile at serving shape after its self-checks
        # passed, and the old try-fancy-first order left the watchdog
        # with nothing. The auto candidate still runs below and serves
        # the headline if it measures faster.
        _PROGRESS["stage"] = "compile-xla-first"
        os.environ["DPF_TPU_LEVEL_KERNEL"] = "xla"
        try:
            step_xla = make_pir_step(
                functools.partial(
                    evaluate_selection_blocks_planes, force_planes=True
                )
            )
            if _try_compile("planes_xla", step_xla) and _share_check(
                "planes_xla"
            ):
                _bank("planes_xla")
        finally:
            os.environ["DPF_TPU_LEVEL_KERNEL"] = "auto"

    if (
        expand_mode in ("both", "planes", "v2")
        and os.environ.get("BENCH_NO_V2", "") != "1"
        and not vet_mode
    ):
        # The key-major layout-clean XLA rewrite (r05): native correction
        # broadcasts in the level loop and a gather-free exit against a
        # bitrev-block-staged database. Compiled, share-checked, and
        # banked right after the proven XLA candidate so the headline is
        # a measured max over {planes_xla, planes_v2, auto planes}.
        _PROGRESS["stage"] = "compile-v2"
        try:
            from distributed_point_functions_tpu.pir.dense_eval_planes_v2 import (  # noqa: E501
                bitrev_block_permute_records,
                evaluate_selection_blocks_planes_v2,
            )

            # Stage the same records with their 128-record blocks
            # bit-reversal-permuted (padded to the tree's leaf capacity
            # first): the v2 expansion then hands its doubling-order
            # leaves straight to the inner product.
            w_cap_rows = (1 << expand_levels) * 128
            db2_rows = db_host
            if w_cap_rows > num_padded:
                db2_rows = np.concatenate(
                    [db_host,
                     np.zeros((w_cap_rows - num_padded, num_words),
                              np.uint32)]
                )
            db2 = jax.device_put(bitrev_block_permute_records(db2_rows))
            del db2_rows
            if ip_name != "jnp":
                db2 = jax.block_until_ready(permute_db_bitmajor(db2))
            db_for["planes_v2"] = db2

            @jax.jit
            def step_v2(s0, c0, cw_s, cw_l, cw_r, vc, db):
                selections = evaluate_selection_blocks_planes_v2(
                    s0, c0, cw_s, cw_l, cw_r, vc,
                    walk_levels=walk_levels,
                    expand_levels=expand_levels,
                    num_blocks=num_blocks,
                    bitrev_leaves=True,
                )
                return inner_product(db, selections)

            if _try_compile("planes_v2", step_v2) and _share_check(
                "planes_v2"
            ):
                _bank("planes_v2")
        except Exception as e:  # noqa: BLE001 - candidate is optional
            _log(f"planes_v2 staging failed: {str(e).splitlines()[0]}")

    if (
        os.environ.get("BENCH_NO_STREAMING", "") != "1"
        and not vet_mode
        and expand_levels > 0
        and (1 << expand_levels) >= num_blocks
    ):
        # Streaming fused expand->inner-product scan: the serving plan
        # for batches whose selection matrix outgrows HBM. At configs
        # where the matrix fits, the planner run here (DPF_TPU_STREAMING
        # forced on) still picks its real split under the real budget —
        # typically cut=0, a one-step scan — so the candidate measures
        # the streaming machinery at the headline shape; the headline
        # stays the max over all banked candidates.
        _PROGRESS["stage"] = "compile-streaming"
        try:
            from distributed_point_functions_tpu.ops.inner_product_pallas import (  # noqa: E501
                stage_db_chunks_bitmajor,
            )
            from distributed_point_functions_tpu.pir.dense_eval_planes_v2 import (  # noqa: E501
                streaming_block_permute_records,
                streaming_pir_inner_products_v2,
            )
            from distributed_point_functions_tpu.pir.planner import (
                plan_dense_serving,
            )

            stream_ip = "pallas2" if ip_name == "pallas2" else "jnp"
            saved_env = os.environ.get("DPF_TPU_STREAMING")
            os.environ["DPF_TPU_STREAMING"] = "1"
            try:
                plan = plan_dense_serving(
                    num_keys=num_queries,
                    num_blocks=num_blocks,
                    expand_levels=expand_levels,
                    serving_bitrev=True,
                    force_ip=stream_ip,
                )
            finally:
                if saved_env is None:
                    os.environ.pop("DPF_TPU_STREAMING", None)
                else:
                    os.environ["DPF_TPU_STREAMING"] = saved_env
            assert plan.mode == "streaming"
            _log(
                f"streaming plan: cut={plan.cut_levels} "
                f"chunk={plan.chunk_levels} ({plan.num_chunks} chunks, "
                f"peak {plan.selection_bytes_peak >> 20} MiB of "
                f"{plan.budget_bytes >> 20} MiB budget, ip={stream_ip})"
            )
            rows_s = db_host
            w_cap_rows = (1 << expand_levels) * 128
            if w_cap_rows > num_padded:
                rows_s = np.concatenate(
                    [db_host,
                     np.zeros((w_cap_rows - num_padded, num_words),
                              np.uint32)]
                )
            host_s = streaming_block_permute_records(
                rows_s, plan.cut_levels
            )
            del rows_s
            if stream_ip == "pallas2":
                db_s = jax.block_until_ready(
                    stage_db_chunks_bitmajor(
                        jax.device_put(host_s), plan.num_chunks
                    )
                )
            else:
                db_s = jax.device_put(
                    host_s.reshape(plan.num_chunks, -1, num_words)
                )
            del host_s
            db_for["streaming"] = db_s

            def step_streaming(s0, c0, cw_s, cw_l, cw_r, vc, db):
                return streaming_pir_inner_products_v2(
                    s0, c0, cw_s, cw_l, cw_r, vc, db,
                    walk_levels=walk_levels,
                    cut_levels=plan.cut_levels,
                    chunk_levels=plan.chunk_levels,
                    ip=stream_ip,
                )

            if _try_compile("streaming", step_streaming) and _share_check(
                "streaming"
            ):
                _bank("streaming")
        except Exception as e:  # noqa: BLE001 - candidate is optional
            _log(f"streaming staging failed: {str(e).splitlines()[0]}")

    _PROGRESS["stage"] = "pallas-check"
    # Run the level-kernel self-checks EAGERLY before anything traces the
    # expansion: inside jax.jit the check cannot run, and a fresh process
    # would silently serve the XLA levels (this is why the r02 headline
    # never engaged the fused kernels despite auto mode). On TPU the
    # FIRST warmup runs in a killable child (same marker discipline as
    # the serving vet): the self-checks are Mosaic compiles under a
    # rotated verdict-cache key, and a silent hang there would otherwise
    # eat the window in-process. A successful child persists its
    # verdicts, so the in-process warm below is pure cache loads.
    eager_kernel_mode = None
    skip_warm = False
    if (
        not vet_mode
        and jax.default_backend() == "tpu"
        and os.environ.get("BENCH_NO_VET", "") != "1"
    ):
        skip_warm = _warm_kernels_subprocess() == "hang"
        if skip_warm:
            _log("kernel warmup hung in the bounded child; serving "
                 "without kernel tiers this run")
    if not skip_warm:
        try:
            from distributed_point_functions_tpu.pir import (
                dense_eval_planes as _dep,
            )

            eager_kernel_mode = _dep.warm_level_kernels()
            _log(f"level kernels: eager mode={eager_kernel_mode!r}")
        except Exception as e:  # noqa: BLE001 - observability only
            _log(
                "level-kernel warmup failed: "
                f"{(str(e).splitlines() or ['<no message>'])[0]}"
            )
    if (
        auto_mode
        and "planes_xla" in candidates
        and not eager_kernel_mode
        and "planes" in candidate_defs
    ):
        # Every kernel tier demoted (or never verified): the auto planes
        # pipeline would trace the exact XLA-levels HLO already compiled
        # and banked as planes_xla under a different jit identity —
        # skip the redundant multi-minute compile.
        _log("auto planes == XLA levels (kernels demoted); "
             "skipping duplicate compile")
        del candidate_defs["planes"]

    if vet_mode:
        # Child: one compile of the auto planes candidate, nothing else.
        # The marker file tells the parent the child REACHED the compile
        # stage — a later hang is then compile evidence, while a hang
        # before it (init, staging) is environment-ambiguous.
        _PROGRESS["stage"] = "vet-compile"
        marker = os.environ.get("BENCH_VET_MARKER", "")
        if marker:
            try:
                with open(marker, "w") as f:
                    f.write("compile")
            except Exception:  # noqa: BLE001 - marker is advisory
                pass
        if os.environ.get("DPF_TPU_FAULT_COMPILE_HANG", "") == "1":
            # Test-only fault injection: simulate a Mosaic compile that
            # goes silent (r04 window3: 23+ min, no error). Lives in the
            # vet child only — never in the serving dispatch path.
            time.sleep(3600)
        ok = bool(candidate_defs.get("planes")) and _try_compile(
            "planes", candidate_defs["planes"]
        )
        _PROGRESS["done"] = True  # silence the watchdog emitter
        os._exit(0 if ok else 1)

    if (
        auto_mode
        and "planes" in candidate_defs
        and eager_kernel_mode
        and os.environ.get("BENCH_NO_VET", "") != "1"
    ):
        # Wedge-proof the auto pipeline's first compile (VERDICT r04
        # item 10): a doomed Mosaic compile can go SILENT for 20+
        # minutes (window3) — in-process that eats the driver's window
        # even though the watchdog emits the banked number. Run the
        # first compile in a killable subprocess: on success the
        # persistent compile cache makes the in-process compile a cache
        # load; on a hang, kill the child, skip the candidate, and
        # persist the engaged tier's failure ONLY if the backend still
        # answers (a dead tunnel must not burn kernel verdicts).
        _PROGRESS["stage"] = "vet"
        # The child dials the same single-client tunnel the parent
        # holds; if the backend refuses a second client it must fail
        # FAST as rc=2, so pin a small init budget unless the caller
        # already did.
        vet_env = {"BENCH_VET_ONLY": "1"}
        if not os.environ.get("BENCH_INIT_BUDGET"):
            vet_env["BENCH_INIT_BUDGET"] = "120"
        status, rc, marker_seen, secs = _run_bounded_child(
            [sys.executable, os.path.abspath(__file__)], vet_env,
            "BENCH_VET_MARKER", "BENCH_VET_TIMEOUT",
        )
        if status == "error":
            verdict = "ok"  # vet unavailable: compile in-process
        elif status == "timeout":
            # Only a hang AFTER the child reached its compile stage is
            # kernel evidence; an init/staging hang (wedged tunnel, or
            # the backend serializing the second client) is ambiguous
            # and must neither demote a tier nor skip the candidate.
            verdict = "hang" if marker_seen else "env-hang"
        elif rc == 0:
            verdict = "ok"
        elif rc == 2:
            verdict = "env-fail"
        elif rc < 0 and marker_seen:
            # Killed by a signal mid-compile (segfaulting Mosaic):
            # repeating it in-process could kill the parent before the
            # banked JSON prints — treat like a hang.
            verdict = "hang"
        else:
            verdict = "fail"
        _log(f"serving vet: {verdict} ({secs:.0f}s, rc={rc}, mode="
             f"{eager_kernel_mode!r})")
        if verdict in ("env-fail", "env-hang"):
            # The vet could not run in this environment (most likely
            # the single-client tunnel): the in-process compile below
            # proceeds unvetted — the same exposure as before the vet
            # existed, still covered by the bank-first watchdog.
            _log("vet environment failure; proceeding with the "
                 "in-process compile (unvetted)")
        if verdict == "hang":
            del candidate_defs["planes"]
            try:
                import subprocess

                from distributed_point_functions_tpu.pir import (
                    dense_eval_planes as _dep,
                )

                alive = subprocess.run(
                    [sys.executable, "-c",
                     "import os, jax, numpy as np; "
                     "p = os.environ.get('BENCH_PLATFORM'); "
                     "p and jax.config.update('jax_platforms', p); "
                     "jax.device_put(np.zeros(4, np.uint32))"
                     ".block_until_ready()"],
                    timeout=90, capture_output=True,
                ).returncode == 0
                if alive:
                    flag = {
                        "walk": "_WALK_KERNEL_FAILED",
                        "tail": "_TAIL_KERNEL_FAILED",
                        "head": "_HEAD_KERNEL_FAILED",
                    }.get(eager_kernel_mode)
                    if flag:
                        setattr(_dep, flag, True)
                        _dep.record_kernel_verdicts()
                    else:
                        _dep._remember_level_kernel_failure()
                    _log(f"vet hang attributed to the "
                         f"{eager_kernel_mode} tier (backend alive); "
                         "verdict persisted")
                else:
                    _log("vet hang NOT attributed (backend also down); "
                         "skipping the candidate this run only")
            except Exception:  # noqa: BLE001 - observability only
                pass
        # verdict == "fail": the child's compile errored promptly — the
        # in-process attempt below re-raises it cheaply (error paths
        # return within minutes) and the demotion ladder attributes it.

    _PROGRESS["stage"] = "compile"
    for name, step in candidate_defs.items():
        ok = _try_compile(name, step)
        if ok or name != "planes" or not auto_mode:
            continue
        # Evidence-based kernel demotion at the OUTER jit level: the
        # eager degradation chain inside evaluate_selection_blocks_planes
        # cannot catch compile failures here (the inner jit traces inline
        # and the Mosaic failure surfaces at the outer jit's compile), so
        # the auto pipeline's failure teaches nothing by itself. Retry
        # head-off, then per-level-only; the first success attributes the
        # failure and persists the verdict for later processes. Each
        # doomed attempt costs minutes of remote compile, so the ladder
        # only runs while enough watchdog budget remains.
        try:
            from distributed_point_functions_tpu.pir import (
                dense_eval_planes as _dep,
            )
        except Exception:  # noqa: BLE001
            continue
        # Tiers demote cumulatively (each retry keeps the earlier
        # demotions): the original attempt already proved the full
        # composition fails, so stripping tiers until a compile lands
        # both attributes the failure and leaves serving on the best
        # surviving tier. The next tier is re-chosen from live status
        # each round (demoting walk re-warms the self-checks, which can
        # newly verify the tail tier). Verdicts persist ONLY with
        # evidence: a landing records the removed tiers; exhausting
        # every tier records the family failure; a budget abort resets
        # the speculative flags and records nothing.
        tried = []
        landed = exhausted = False
        # The whole speculative region runs with verdict recording
        # suspended: the retries themselves re-enter
        # _level_kernel_enabled/warm_level_kernels, which would
        # otherwise persist the speculative FAILED flags even when the
        # ladder later aborts without evidence.
        with _dep.suspend_verdict_recording():
            while True:
                remaining = (
                    _PROGRESS.get("deadline", 0) - time.monotonic()
                )
                if remaining < 420:
                    _log("kernel-demotion ladder stopped "
                         "(watchdog budget)")
                    break
                status = _dep.level_kernel_status()
                if status["walk_verified"] and not status["walk_failed"]:
                    tier, flag = "walk", "_WALK_KERNEL_FAILED"
                elif status["head_verified"] and not status["head_failed"]:
                    tier, flag = "head", "_HEAD_KERNEL_FAILED"
                elif status["tail_verified"] and not status["tail_failed"]:
                    tier, flag = "tail", "_TAIL_KERNEL_FAILED"
                else:
                    exhausted = True
                    break
                setattr(_dep, flag, True)
                tried.append(flag)
                if tier == "walk":
                    # Walk won auto before the tail self-check ever
                    # ran; re-warm so the traced retry can resolve to
                    # a newly verified tail instead of skipping it.
                    try:
                        _dep.warm_level_kernels()
                    except Exception:  # noqa: BLE001
                        pass
                retry_ok = _try_compile(
                    "planes", make_pir_step(functools.partial(
                        evaluate_selection_blocks_planes,
                        force_planes=True,
                    ))
                )
                if retry_ok:
                    _log(f"auto pipeline compiles without the {tier} "
                         "tier; demotion persisted")
                    landed = True
                    break
        if landed:
            _dep.record_kernel_verdicts()
        elif exhausted:
            # Reached with tried empty when the original failing
            # attempt was already the bare per-level composition.
            # Every composition failed: the per-level family itself
            # is unusable at this serving shape.
            _dep._remember_level_kernel_failure()
            _log("no kernel composition compiles at serving shape; "
                 "level-kernel family demoted (persisted)")
            _dep.record_kernel_verdicts()
        else:
            # No attribution evidence (budget abort, or nothing to
            # try): a tier must not stay demoted on zero evidence.
            for flag in tried:
                setattr(_dep, flag, False)
    try:
        from distributed_point_functions_tpu.pir.dense_eval_planes import (
            level_kernel_status,
        )

        _log(f"level kernels: {level_kernel_status()}")
    except Exception:  # noqa: BLE001 - observability only
        pass
    if "limb" in outputs and "planes" in outputs and not np.array_equal(
        outputs["limb"], outputs["planes"]
    ):
        _log("WARNING: planes/limb outputs differ on device; "
             "dropping planes")
        del candidates["planes"]

    _PROGRESS["stage"] = "share-check"
    for name in list(candidates):
        if name not in share_state.get("checked", set()):
            _share_check(name)
    if not candidates and "limb" not in candidate_defs:
        # The default single-config run must not die with the planes
        # kernel — whether it failed to compile or failed the share
        # check, retry on the limb path before giving up.
        _log("planes expansion unusable; falling back to the limb path")
        if _try_compile("limb", make_pir_step(evaluate_selection_blocks)):
            _share_check("limb")
    if not candidates:
        _emit(0.0, 0.0, error="no expansion path compiled and passed "
              "share-correctness")
        return

    # BENCH_XPROF=<dir>: capture an xprof device trace of a few serving
    # batches (per expansion path) before the timed measurement, so every
    # capture window can dissect where the batch time goes. The trace is
    # outside the timed region and costs a few extra executions only.
    xprof_dir = os.environ.get("BENCH_XPROF", "")
    if xprof_dir:
        _PROGRESS["stage"] = "xprof"
        try:
            from distributed_point_functions_tpu.utils.profiling import (
                annotate,
                trace,
            )

            with trace(xprof_dir):
                for name, step in candidates.items():
                    with annotate(f"pir_step_{name}"):
                        np.asarray(step(*staged, _db(name)))
            _log(f"xprof trace captured to {xprof_dir}")
        except Exception as e:  # noqa: BLE001
            _log(f"xprof capture failed: {str(e).splitlines()[0]}")

    _PROGRESS["stage"] = "measure"
    for name, step in candidates.items():
        if name in timings:
            # Already banked during the compile stage (xla-first bank);
            # re-measuring would spend a second _slope_time run of the
            # hardware window on a figure we already hold.
            _log(f"expansion[{name}]: keeping banked "
                 f"{timings[name] * 1e3:.3f} ms")
            continue
        per, lat = _slope_time(lambda s=step: s(*staged, _db(name)), iters)
        if per is not None:
            timings[name] = per
            latencies[name] = lat
            qps = num_queries / (per + host_walk_s)
            _log(f"expansion[{name}]: per-batch {per * 1e3:.3f} ms")
            if qps > (_PROGRESS["qps"] or 0.0):
                _PROGRESS["qps"] = qps
    if not timings:
        # Refuse to report an inflated figure from a degenerate slope.
        _log("ERROR: slope still non-positive; reporting value 0")
        _emit(0.0, 0.0, error="degenerate timing slope")
        return
    best = min(timings, key=timings.get)
    per_batch = timings[best]
    # The xla-first bank above replaced the old below-floor XLA retry:
    # in auto mode the XLA-level candidate is always compiled and
    # measured up front, so the headline is a measured max over
    # {planes_xla, auto planes, limb} rather than hope plus insurance.
    # When the XLA candidate wins, every later measurement of it (split
    # timing, ns/leaf) must keep dispatching under the XLA mode —
    # leaving "auto" would silently re-enable the kernels for the very
    # path the headline just rejected.
    if auto_mode and best == "planes_xla":
        os.environ["DPF_TPU_LEVEL_KERNEL"] = "xla"
    # Free the losing candidates' device databases (the v2 bitrev copy
    # is a second full-database staging in HBM); only the winner serves
    # from here on.
    for name in list(db_for):
        if name != best:
            del db_for[name]
    if best in db_for:
        db_words = db_for[best]

    latency = latencies[best]
    pir_step = candidates[best]
    if best == "planes_v2":
        from distributed_point_functions_tpu.pir.dense_eval_planes_v2 import (  # noqa: E501
            evaluate_selection_blocks_planes_v2,
        )

        evaluate_selection_blocks_best = evaluate_selection_blocks_planes_v2
    elif best.startswith("planes"):
        evaluate_selection_blocks_best = evaluate_selection_blocks_planes
    else:
        evaluate_selection_blocks_best = evaluate_selection_blocks
    _log(
        f"latency {latency * 1e3:.1f} ms, per-batch {per_batch * 1e3:.3f} "
        f"ms (expansion: {best})"
    )
    _PROGRESS["qps"] = num_queries / (per_batch + host_walk_s)
    _PROGRESS["stage"] = "split-timing"

    # Split timing: the inner product alone on precomputed selections, so
    # the log shows how the batch divides between DPF expansion and the
    # database pass.
    ip_ms = None
    ip_alt_ms = None
    if best == "streaming":
        # The fused scan has no materialized-selection boundary to time
        # in isolation; the per-batch figure IS the fused cost.
        _log("split timing skipped: streaming fuses expansion into the "
             "inner product")
        extra_skip_split = True
    else:
        extra_skip_split = False
    try:
        if extra_skip_split:
            raise _SkipSplit()
        # force_planes mirrors the candidate definition: without it the
        # small-batch padding guard could reroute tiny query counts to
        # the limb kernel and mislabel the split as the planes path.
        # v2 instead mirrors its serving mode (bitrev leaves, own db).
        if best == "planes_v2":
            expand_kwargs = {"bitrev_leaves": True}
        elif best.startswith("planes"):
            expand_kwargs = {"force_planes": True}
        else:
            expand_kwargs = {}
        expand_only = jax.jit(
            lambda s0, c0, cs, cl, cr, vc: evaluate_selection_blocks_best(
                s0, c0, cs, cl, cr, vc,
                walk_levels=walk_levels,
                expand_levels=expand_levels,
                num_blocks=num_blocks,
                **expand_kwargs,
            )
        )
        sel_fixed = jax.block_until_ready(expand_only(*staged))
        db_best = _db(best)
        jax.block_until_ready(inner_product(db_best, sel_fixed))
        per_ip, _ = _slope_time(
            lambda: inner_product(db_best, sel_fixed), iters
        )
        if per_ip is not None:
            ip_ms = per_ip * 1e3
            _log(
                f"split: inner product {ip_ms:.2f} ms "
                f"({num_padded * num_words * 4 / per_ip / 1e9:.0f} GB/s), "
                f"expansion ~{per_batch * 1e3 - ip_ms:.2f} ms"
            )
        if use_pallas2 or use_pallas:
            # Record the alternates on the same staged layout so the
            # capture shows how the tiers compare on this hardware.
            alts = {"bitplane": xor_inner_product_bitplane}
            if use_pallas2:
                alts["pallas_v1"] = xor_inner_product_pallas_staged
            for alt_name, alt_fn in alts.items():
                try:
                    jax.block_until_ready(alt_fn(db_best, sel_fixed))
                    per_alt, _ = _slope_time(
                        lambda f=alt_fn: f(db_best, sel_fixed), iters
                    )
                    if per_alt is not None:
                        if alt_name == "bitplane":
                            ip_alt_ms = per_alt * 1e3
                        _log(
                            f"split: {alt_name} alternate "
                            f"{per_alt * 1e3:.2f} ms"
                        )
                except Exception as e:  # noqa: BLE001
                    _log(f"{alt_name} alternate timing failed: {e}")
    except _SkipSplit:
        pass
    except Exception as e:  # noqa: BLE001
        _log(f"split timing failed: {e}")

    # Per-batch serving cost = device step + the host zeros-walk that
    # serving pays per fresh key batch.
    qps = num_queries / (per_batch + host_walk_s)
    db_gb = num_padded * num_words * 4 / 1e9
    gbps = db_gb / per_batch
    _log(
        f"effective db read bandwidth {gbps:.1f} GB/s "
        f"({db_gb * 1e3:.0f} MB per batch pass)"
    )

    extra = {
        "inner_product_effective_gbps": round(gbps, 2),
        "inner_product_path": ip_name,
        "inner_product_bitplane_alt_ms": (
            round(ip_alt_ms, 3) if ip_alt_ms else None
        ),
        "expansion_path": best,
        "expansion_per_batch_ms": {
            k: round(v * 1e3, 3) for k, v in timings.items()
        },
        "per_batch_ms": round(per_batch * 1e3, 3),
        "inner_product_only_ms": round(ip_ms, 3) if ip_ms else None,
        "num_queries": num_queries,
    }

    def _dump_extra():
        try:
            # Refresh the observability aggregates at each dump: the
            # per-stage span summary and the planner-tier counters
            # accumulated over everything the bench ran so far.
            from distributed_point_functions_tpu.observability import (
                tracing,
            )

            extra["stage_spans"] = tracing.stage_summary()
            extra["runtime_counters"] = tracing.runtime_counters.export()
        except Exception:  # noqa: BLE001 - observability only
            pass
        try:
            os.makedirs("benchmarks/results", exist_ok=True)
            with open("benchmarks/results/bench_extra.json", "w") as f:
                json.dump(extra, f, indent=2)
        except Exception:  # noqa: BLE001 - observability only
            pass

    # Persist the split metrics BEFORE the (slow, optional) ns/leaf stage
    # so a watchdog kill mid-ns/leaf can't discard measurements already
    # made; the dump reruns after ns/leaf to append its entry.
    _dump_extra()
    # ns/leaf is opt-in for driver runs (BENCH_NSLEAF=1): its cold compile
    # alone ran 588 s on hardware, which is exactly the kind of tail that
    # killed BENCH_r02. Capture scripts set the flag explicitly (and must
    # raise BENCH_TIMEOUT accordingly).
    if (
        os.environ.get("BENCH_NSLEAF", "") == "1"
        and os.environ.get("BENCH_SKIP_NSLEAF", "") != "1"
    ):
        _PROGRESS["stage"] = "ns-leaf"
        try:
            _ns_per_leaf(jax, extra)
        except Exception as e:  # noqa: BLE001
            _log(f"ns/leaf metric failed: {e}")
    _dump_extra()

    _emit(qps, qps / BASELINE_QPS)
    # Latency evidence for the gate: the measured per-batch device step
    # is the headline's end-to-end latency (direction: lower), plus
    # whatever phase waterfall accumulated (populated when the bench
    # exercised the serving path).
    _append_latency_record(
        f"dense_pir_batch_{num_queries}q_ms", per_batch * 1e3,
        samples=iters,
    )
    _emit_latency_records("dense")


if __name__ == "__main__":
    try:
        main()
    except Exception as e:  # noqa: BLE001 - the JSON line must always print
        import traceback

        traceback.print_exc()
        # A crash after a successful bank must still report the banked
        # figure (same contract as the watchdog): a transient fault in a
        # later stage must not zero out a valid earlier measurement.
        banked = _PROGRESS["qps"] or 0.0
        _emit(banked, banked / BASELINE_QPS, error=e)
