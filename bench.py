"""Headline benchmark: dense PIR queries/sec/chip at a 2^20 x 256B database.

Prints ONE JSON line {"metric", "value", "unit", "vs_baseline"}.

Baseline: the reference's single-threaded AES-NI CPU path
(`experiments/README.md`, see BASELINE.md). A dense PIR query over 2^20
records costs the reference a full-domain expansion of 2^20 128-bit
selection blocks (~2 fixed-key AES ops per block node, `ExpandSeeds`,
`dpf/distributed_point_function.cc:289-372`) plus a 256MB XOR inner product
(`pir/internal/inner_product_hwy.cc`). From the published 2^20-point
direct-eval time (0.67s, ~20 AES levels/point) the per-AES cost is
~16ns/hash single-threaded; expansion ~2*2^20 hashes ~= 34ms, inner
product ~256MB at ~10GB/s ~= 26ms, about 60ms/query => ~16 queries/sec.
BASELINE_QPS encodes that derived figure.

Our server answers the same queries with a fused batched pipeline that
expands only the 2^13 selection blocks that carry bits (see
`distributed_point_functions_tpu/pir/dense_eval.py`) and one database pass
per query batch.

Environment knobs: BENCH_RECORDS (default 2^20), BENCH_RECORD_BYTES (256),
BENCH_QUERIES (64), BENCH_ITERS (16, min 1).
"""

from __future__ import annotations

import json
import math
import os
import time

import numpy as np

BASELINE_QPS = 16.0


def _log(msg):
    import sys
    import time as _t

    print(f"[bench {_t.strftime('%H:%M:%S')}] {msg}", file=sys.stderr, flush=True)


def main():
    num_records = int(os.environ.get("BENCH_RECORDS", 1 << 20))
    record_bytes = int(os.environ.get("BENCH_RECORD_BYTES", 256))
    num_queries = int(os.environ.get("BENCH_QUERIES", 64))
    iters = max(1, int(os.environ.get("BENCH_ITERS", 16)))

    import jax

    # Persistent compilation cache: repeat bench runs skip the (large)
    # bitsliced-AES XLA compile.
    cache_dir = os.environ.get(
        "BENCH_CACHE_DIR", os.path.expanduser("~/.cache/jax_bench")
    )
    try:
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    except Exception:
        pass

    from distributed_point_functions_tpu.ops.inner_product import (
        xor_inner_product,
    )
    from distributed_point_functions_tpu.pir.client import DenseDpfPirClient
    from distributed_point_functions_tpu.pir.dense_eval import (
        evaluate_selection_blocks,
        stage_keys,
    )

    rng = np.random.default_rng(7)

    # Database straight to device (skip host record packing for 256MB).
    num_padded = ((num_records + 127) // 128) * 128
    num_words = record_bytes // 4
    db_host = rng.integers(
        0, 1 << 32, (num_padded, num_words), dtype=np.uint32
    )
    db_words = jax.device_put(db_host)

    num_blocks = num_padded // 128
    total_levels = max(0, math.ceil(math.log2(num_records)))
    expand_levels = min(max(0, (num_blocks - 1).bit_length()), total_levels)
    walk_levels = total_levels - expand_levels

    client = DenseDpfPirClient.create(num_records, lambda pt, ci: pt)
    indices = [int(i) for i in rng.integers(0, num_records, num_queries)]
    keys0, _ = client._generate_key_pairs(indices)
    staged = stage_keys(keys0)

    @jax.jit
    def pir_step(seeds0, control0, cw_seeds, cw_left, cw_right, last_vc, db):
        selections = evaluate_selection_blocks(
            seeds0,
            control0,
            cw_seeds,
            cw_left,
            cw_right,
            last_vc,
            walk_levels=walk_levels,
            expand_levels=expand_levels,
            num_blocks=num_blocks,
        )
        return xor_inner_product(db, selections)

    # Warmup / compile.
    _log(
        f"compiling: {num_records} records x {record_bytes}B, "
        f"{num_queries} queries, walk={walk_levels} expand={expand_levels}"
    )
    t_c = time.perf_counter()
    out = pir_step(*staged, db_words)
    out.block_until_ready()
    _log(f"compile+first run {time.perf_counter() - t_c:.1f}s")

    # Slope-based timing: over the remote-TPU tunnel `block_until_ready`
    # returns before device completion and a full host readback costs a
    # ~60-70ms round trip, so time(N calls + readback) = latency + N*step.
    # TPU execution is in-order, so reading back call N's result implies
    # calls 1..N-1 finished; the slope isolates true device time per batch.
    def timed(n):
        t0 = time.perf_counter()
        for _ in range(n):
            out = pir_step(*staged, db_words)
        np.asarray(out)
        return time.perf_counter() - t0

    reps = 3
    for attempt_iters in (iters, 4 * iters):
        t_small = min(timed(1) for _ in range(reps))
        t_big = min(timed(1 + attempt_iters) for _ in range(reps))
        if t_big > t_small:
            break
        _log(
            f"WARNING: non-positive slope (t1={t_small * 1e3:.1f} ms, "
            f"t{1 + attempt_iters}={t_big * 1e3:.1f} ms); tunnel jitter "
            "swamped the measurement — retrying with more iterations"
        )
    if t_big <= t_small:
        # Refuse to report an inflated figure from a degenerate slope.
        _log("ERROR: slope still non-positive; reporting value 0")
        print(
            json.dumps(
                {
                    "metric": (
                        "dense_pir_queries_per_sec_chip_"
                        f"{num_records}x{record_bytes}B"
                    ),
                    "value": 0.0,
                    "unit": "queries/s",
                    "vs_baseline": 0.0,
                }
            )
        )
        return
    per_batch = (t_big - t_small) / attempt_iters
    _log(
        f"latency {t_small * 1e3:.1f} ms, per-batch {per_batch * 1e3:.3f} ms"
    )

    qps = num_queries / per_batch
    print(
        json.dumps(
            {
                "metric": f"dense_pir_queries_per_sec_chip_{num_records}x{record_bytes}B",
                "value": round(qps, 2),
                "unit": "queries/s",
                "vs_baseline": round(qps / BASELINE_QPS, 2),
            }
        )
    )


if __name__ == "__main__":
    main()
