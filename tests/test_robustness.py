"""Unit coverage for the robustness layer: failpoints, breaker,
checkpoint store.

Everything here is stdlib-speed — no jax, no sockets. The chaos
end-to-end schedules that drive these pieces through the real serving
stack live in `test_chaos.py`.
"""

import json
import os
import threading

import pytest

from distributed_point_functions_tpu.robustness import (
    CheckpointError,
    CheckpointStore,
    CircuitBreaker,
    FailpointError,
    FailpointRegistry,
    SimulatedResourceExhausted,
    failpoints,
)
from distributed_point_functions_tpu.robustness.breaker import STATE_CODES


# ---------------------------------------------------------------------------
# Failpoint registry
# ---------------------------------------------------------------------------


def test_disarmed_registry_is_a_no_op():
    reg = FailpointRegistry(env=False)
    reg.fire("any.site")  # nothing armed: returns silently
    assert reg.mutate("any.site", b"data") == b"data"
    assert reg.export()["armed"] is False


def test_error_action_uses_site_native_exception_type():
    reg = FailpointRegistry(env=False)

    class MyTransportError(Exception):
        pass

    reg.arm("t.send", "error", message="boom")
    with pytest.raises(MyTransportError, match="boom"):
        reg.fire("t.send", error=MyTransportError)
    # times=1 default: second hit passes clean.
    reg.fire("t.send", error=MyTransportError)


def test_error_action_defaults_to_failpoint_error():
    reg = FailpointRegistry(env=False)
    reg.arm("x", "error")
    with pytest.raises(FailpointError, match="injected fault at x"):
        reg.fire("x")


def test_oom_action_reads_as_resource_exhausted():
    reg = FailpointRegistry(env=False)
    reg.arm("device.dispatch", "oom")
    with pytest.raises(SimulatedResourceExhausted, match="RESOURCE_EXHAUSTED"):
        reg.fire("device.dispatch")


def test_times_after_schedule():
    reg = FailpointRegistry(env=False)
    spec = reg.arm("s", "error", times=2, after=1)
    outcomes = []
    for _ in range(5):
        try:
            reg.fire("s")
            outcomes.append("ok")
        except FailpointError:
            outcomes.append("boom")
    # Hit 1 skipped (after=1), hits 2-3 fire (times=2), rest pass.
    assert outcomes == ["ok", "boom", "boom", "ok", "ok"]
    assert spec.hits == 5
    assert spec.fired == 2


def test_probability_schedule_is_seed_deterministic():
    def run(seed):
        reg = FailpointRegistry(seed=seed, env=False)
        reg.arm("p", "error", times=None, probability=0.5)
        out = []
        for _ in range(20):
            try:
                reg.fire("p")
                out.append(0)
            except FailpointError:
                out.append(1)
        return out

    assert run(7) == run(7)
    assert 0 < sum(run(7)) < 20


def test_corrupt_mutation_flips_exactly_one_byte():
    reg = FailpointRegistry(seed=3, env=False)
    reg.arm("frame", "corrupt")
    data = bytes(range(64))
    out = reg.mutate("frame", data)
    assert len(out) == len(data)
    diff = [i for i in range(64) if out[i] != data[i]]
    assert len(diff) == 1
    # Disarmed after times=1.
    assert reg.mutate("frame", data) == data


def test_truncate_mutation_shortens_frame():
    reg = FailpointRegistry(seed=11, env=False)
    reg.arm("frame", "truncate")
    data = bytes(range(64))
    out = reg.mutate("frame", data)
    assert len(out) < len(data)
    assert out == data[: len(out)]


def test_mutate_action_reached_via_fire_is_an_arming_error():
    reg = FailpointRegistry(env=False)
    reg.arm("site", "corrupt")
    with pytest.raises(FailpointError, match="mutate action"):
        reg.fire("site")


def test_arm_from_string_env_format():
    reg = FailpointRegistry(env=False)
    reg.arm_from_string(
        "transport.tcp.recv=error:times=2;"
        "batcher.evaluate=delay:delay_ms=5;"
        "frame=corrupt:p=0.5:after=1"
    )
    assert reg.spec("transport.tcp.recv").times == 2
    assert reg.spec("batcher.evaluate").action == "delay"
    assert reg.spec("batcher.evaluate").delay_ms == 5.0
    assert reg.spec("frame").probability == 0.5
    assert reg.spec("frame").after == 1


def test_env_activation(monkeypatch):
    monkeypatch.setenv("DPF_TPU_FAILPOINTS", "a.site=error:times=3")
    monkeypatch.setenv("DPF_TPU_FAILPOINTS_SEED", "42")
    reg = FailpointRegistry()
    assert reg.seed == 42
    assert reg.armed
    assert reg.spec("a.site").times == 3


def test_unknown_action_and_option_rejected():
    reg = FailpointRegistry(env=False)
    with pytest.raises(ValueError, match="unknown failpoint action"):
        reg.arm("s", "explode")
    with pytest.raises(ValueError, match="unknown failpoint option"):
        reg.arm_from_string("s=error:frequency=1")


def test_module_level_helpers_use_default_registry():
    reg = FailpointRegistry(env=False)
    old = failpoints.default_failpoints()
    failpoints.set_default_failpoints(reg)
    try:
        failpoints.fire("anything")  # disarmed fast path
        reg.arm("hot", "error")
        with pytest.raises(FailpointError):
            failpoints.fire("hot")
    finally:
        failpoints.set_default_failpoints(old)


def test_export_reports_schedule_state():
    reg = FailpointRegistry(seed=5, env=False)
    reg.arm("a", "delay", delay_ms=1.0, times=None)
    reg.fire("a")
    snap = reg.export()
    assert snap["seed"] == 5
    assert snap["sites"]["a"]["hits"] == 1
    assert snap["sites"]["a"]["fired"] == 1
    reg.clear()
    assert reg.export() == {"armed": False, "seed": 5, "sites": {}}


# ---------------------------------------------------------------------------
# Circuit breaker
# ---------------------------------------------------------------------------


class FakeClock:
    def __init__(self):
        self.now = 0.0

    def __call__(self):
        return self.now


def make_breaker(threshold=3, reset_ms=1000.0):
    clock = FakeClock()
    b = CircuitBreaker(
        failure_threshold=threshold,
        reset_timeout_ms=reset_ms,
        name="test",
        clock=clock,
    )
    return b, clock


def test_breaker_opens_after_consecutive_failures_only():
    b, _ = make_breaker(threshold=3)
    b.record_failure()
    b.record_failure()
    b.record_success()  # resets the consecutive count
    b.record_failure()
    b.record_failure()
    assert b.state == "closed"
    b.record_failure()
    assert b.state == "open"
    assert not b.allow()


def test_breaker_half_open_probe_success_closes():
    b, clock = make_breaker(threshold=1, reset_ms=1000.0)
    b.record_failure()
    assert b.state == "open"
    assert not b.allow()  # within the reset window: fast-fail
    clock.now += 1.1
    assert b.allow()  # the single half-open probe
    assert b.state == "half_open"
    assert not b.allow()  # second caller fast-fails while probing
    b.record_success()
    assert b.state == "closed"
    assert b.allow()


def test_breaker_half_open_probe_failure_reopens():
    b, clock = make_breaker(threshold=1, reset_ms=1000.0)
    b.record_failure()
    clock.now += 1.1
    assert b.allow()
    b.record_failure()
    assert b.state == "open"
    assert not b.allow()
    clock.now += 1.1
    assert b.allow()  # next window: probe again


def test_breaker_vanished_probe_unblocks_after_another_window():
    b, clock = make_breaker(threshold=1, reset_ms=1000.0)
    b.record_failure()
    clock.now += 1.1
    assert b.allow()  # probe taken, never reports back
    clock.now += 1.1
    assert b.allow()  # replacement probe rather than wedging


def test_breaker_transitions_notify_listeners():
    b, clock = make_breaker(threshold=2, reset_ms=100.0)
    seen = []
    b.on_transition(lambda old, new: seen.append((old, new)))
    b.record_failure()
    b.record_failure()
    clock.now += 0.2
    b.allow()
    b.record_success()
    assert seen == [
        ("closed", "open"),
        ("open", "half_open"),
        ("half_open", "closed"),
    ]


def test_breaker_export_and_codes():
    b, clock = make_breaker(threshold=1, reset_ms=1000.0)
    assert STATE_CODES == {"closed": 0, "half_open": 1, "open": 2}
    assert b.state_code() == 0
    b.record_failure()
    b.allow()
    b.allow()
    clock.now += 0.5
    snap = b.export()
    assert snap["state"] == "open"
    assert snap["state_code"] == 2
    assert snap["opens"] == 1
    assert snap["fast_fails"] == 2
    assert snap["open_for_s"] == pytest.approx(0.5)
    assert snap["failure_threshold"] == 1


def test_breaker_threshold_validation():
    with pytest.raises(ValueError):
        CircuitBreaker(failure_threshold=0)


def test_breaker_is_thread_safe_under_contention():
    b, _ = make_breaker(threshold=1000)

    def hammer():
        for _ in range(200):
            b.allow()
            b.record_failure()
            b.record_success()

    threads = [threading.Thread(target=hammer) for _ in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert b.state in ("closed", "open")


# ---------------------------------------------------------------------------
# Checkpoint store
# ---------------------------------------------------------------------------


def test_checkpoint_save_load_roundtrip(tmp_path):
    store = CheckpointStore(str(tmp_path / "sweep.json"))
    assert store.load() is None
    payload = {"round_index": 3, "frontier": [1, 2, 3]}
    store.save(payload)
    assert store.load() == payload
    # The tmp staging file never lingers.
    assert not os.path.exists(store.path + ".tmp")


def test_checkpoint_creates_parent_directories(tmp_path):
    store = CheckpointStore(str(tmp_path / "a" / "b" / "sweep.json"))
    store.save({"x": 1})
    assert store.load() == {"x": 1}


def test_checkpoint_corrupt_file_raises_not_silently_restarts(tmp_path):
    path = tmp_path / "sweep.json"
    store = CheckpointStore(str(path))
    store.save({"x": 1})
    path.write_text(json.dumps({"x": 1})[:-4])  # torn copy
    with pytest.raises(CheckpointError, match="unreadable checkpoint"):
        store.load()


def test_checkpoint_delete_is_idempotent(tmp_path):
    store = CheckpointStore(str(tmp_path / "sweep.json"))
    store.save({"x": 1})
    store.delete()
    assert store.load() is None
    store.delete()  # already gone: no error
