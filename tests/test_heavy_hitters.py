"""End-to-end and unit coverage for the heavy-hitters subsystem.

The acceptance bar: with keys drawn from a known value multiset, the
reconstructed heavy-hitter set at threshold t exactly equals the
plaintext answer — on the in-process transport AND over real TCP — and
budget-chunked evaluation is bit-identical to unchunked (lanes are
independent, so chunking must be invisible).

One module-scoped fixture generates the client key pairs once; every
test builds its own (cheap) servers over them so sweep state never
leaks between cases.
"""

import numpy as np
import pytest

from distributed_point_functions_tpu import heavy_hitters as hh
from distributed_point_functions_tpu.serving.metrics import MetricsRegistry
from distributed_point_functions_tpu.serving.transport import (
    FramedTcpServer,
    InProcessTransport,
    TcpTransport,
)

# 8-bit domain, two 4-bit levels: frontier 16 wide at round 0, tiny jit
# shapes, and non-trivial pruning. 3 appears 3x, 77 and 9 twice, the
# rest once — heavy hitters at t=2 are {3: 3, 77: 2, 9: 2}.
VALUES = [3, 3, 3, 77, 77, 9, 9, 200]
CONFIG = hh.HeavyHittersConfig(domain_bits=8, level_bits=4, threshold=2)


@pytest.fixture(scope="module")
def key_pairs():
    client = hh.HeavyHittersClient(CONFIG)
    pairs = [client.generate_report(v) for v in VALUES]
    return [p[0] for p in pairs], [p[1] for p in pairs]


def _servers(key_pairs, config=CONFIG, **kwargs):
    keys0, keys1 = key_pairs
    return (
        hh.HeavyHittersServer(config, keys0, **kwargs),
        hh.HeavyHittersServer(config, keys1, **kwargs),
    )


def test_run_protocol_matches_plaintext_oracle(key_pairs):
    s0, s1 = _servers(key_pairs)
    result = hh.run_protocol(s0, s1)
    want = hh.plaintext_heavy_hitters(VALUES, CONFIG)
    assert result.as_dict() == want == {3: 3, 77: 2, 9: 2}
    # Round 0 counted 16 candidate prefixes; only prefixes of surviving
    # values descend.
    assert result.rounds[0].frontier_width == 16
    assert result.rounds[0].survivors == len(
        {v >> 4 for v in (3, 77, 9)}
    )


def test_leader_helper_in_process_matches_oracle(key_pairs):
    s0, s1 = _servers(key_pairs)
    metrics = MetricsRegistry()
    leader = hh.HeavyHittersLeader(
        s0,
        InProcessTransport(hh.HeavyHittersHelper(s1).handle_wire),
        metrics=metrics,
    )
    result = leader.run()
    assert result.as_dict() == hh.plaintext_heavy_hitters(VALUES, CONFIG)
    snap = metrics.snapshot()
    assert snap["counters"]["hh.rounds"] == len(result.rounds) == 2
    assert snap["counters"]["hh.bytes_sent"] == sum(
        st.bytes_sent for st in result.rounds
    )
    assert snap["gauges"]["hh.keys_live"] == len(VALUES)


def test_leader_helper_over_tcp_matches_oracle(key_pairs):
    s0, s1 = _servers(key_pairs)
    helper = hh.HeavyHittersHelper(s1)
    with FramedTcpServer(helper.handle_wire, port=0, name="hh-test") as srv:
        with TcpTransport("localhost", srv.port) as transport:
            leader = hh.HeavyHittersLeader(
                s0, transport, round_timeout_ms=120_000.0
            )
            result = leader.run()
    assert result.as_dict() == hh.plaintext_heavy_hitters(VALUES, CONFIG)


def test_chunked_evaluation_bit_identical_to_unchunked(key_pairs):
    keys0, _ = key_pairs
    dpf = CONFIG.make_dpf()
    whole = hh.LevelAggregator(dpf, keys0)
    # Budget that fits only 2 prefix lanes per chunk: the 16-wide round-0
    # frontier runs as 8 fused programs instead of 1.
    tiny = hh.LevelAggregator(
        dpf,
        keys0,
        budget_bytes=len(keys0) * 2 * hh.lane_bytes(16, 1),
    )
    frontier0 = list(range(16))
    a = whole.evaluate_level(0, frontier0)
    b = tiny.evaluate_level(0, frontier0)
    np.testing.assert_array_equal(a, b)

    # The merged chunked cut-state must serve the next level identically
    # to the unchunked cache (non-power-of-two 48-wide frontier).
    frontier1 = sorted((p << 4) | c for p in (0, 4, 12) for c in range(16))
    np.testing.assert_array_equal(
        whole.evaluate_level(1, frontier1),
        tiny.evaluate_level(1, frontier1),
    )


def test_level_plan_respects_budget():
    plan = hh.plan_level(
        num_keys=100, num_prefixes=1000, walk_levels=10, value_blocks=1,
        budget_bytes=1 << 20,
    )
    assert plan.chunk_prefixes & (plan.chunk_prefixes - 1) == 0
    assert plan.bytes_peak <= plan.budget_bytes
    assert plan.num_chunks * plan.chunk_prefixes >= plan.num_prefixes
    # A budget too small for even one lane still makes progress.
    floor = hh.plan_level(100, 1000, 10, 1, budget_bytes=1)
    assert floor.chunk_prefixes == 1


def test_sharded_key_sum_matches_single_device(key_pairs):
    from distributed_point_functions_tpu.parallel.sharded import (
        make_mesh,
        sum_shares_over_keys,
    )

    mesh = make_mesh(8)
    rng = np.random.default_rng(3)
    values = rng.integers(0, 1 << 32, size=(8, 6), dtype=np.uint32)
    got = np.asarray(sum_shares_over_keys(values, mesh))
    np.testing.assert_array_equal(
        got, values.astype(np.uint64).sum(axis=0) & 0xFFFFFFFF
    )

    # Through the aggregator: mesh-sharded shares equal the plain path
    # (8 keys over 8 virtual devices).
    keys0, _ = key_pairs
    dpf = CONFIG.make_dpf()
    plain = hh.LevelAggregator(dpf, keys0[:8])
    sharded = hh.LevelAggregator(dpf, keys0[:8], mesh=mesh)
    frontier = list(range(16))
    np.testing.assert_array_equal(
        plain.evaluate_level(0, frontier),
        sharded.evaluate_level(0, frontier),
    )


def test_frontier_sweep_early_exit_when_nothing_survives(key_pairs):
    config = hh.HeavyHittersConfig(
        domain_bits=8, level_bits=4, threshold=100
    )
    s0, s1 = _servers(key_pairs, config=config)
    result = hh.run_protocol(s0, s1)
    assert result.as_dict() == {}
    assert len(result.rounds) == 1  # pruned to nothing, never descended
    assert result.rounds[0].survivors == 0


def test_round_order_is_enforced(key_pairs):
    s0, _ = _servers(key_pairs)
    with pytest.raises(hh.ProtocolError, match="out of order"):
        s0.evaluate_round(1, [0])
    s0.evaluate_round(0, list(range(16)))
    with pytest.raises(hh.ProtocolError, match="out of order"):
        s0.evaluate_round(0, list(range(16)))
    s0.reset()
    s0.evaluate_round(0, list(range(16)))


def test_wire_codec_roundtrip_and_rejection():
    frontier = np.array([0, 5, 1 << 40], dtype=np.uint64)
    req = hh.encode_eval_request(3, frontier)
    r, decoded = hh.decode_eval_request(req)
    assert r == 3
    np.testing.assert_array_equal(decoded, frontier)

    shares = np.array([7, 0, 0xFFFFFFFF], dtype=np.uint32)
    resp = hh.encode_eval_response(3, shares)
    r, decoded = hh.decode_eval_response(resp)
    assert r == 3
    np.testing.assert_array_equal(decoded, shares)

    with pytest.raises(hh.ProtocolError, match="magic"):
        hh.decode_eval_request(b"XXXX" + req[4:])
    with pytest.raises(hh.ProtocolError, match="kind"):
        hh.decode_eval_request(resp)
    with pytest.raises(hh.ProtocolError, match="body"):
        hh.decode_eval_request(req[:-3])


def test_value_encoding():
    assert hh.encode_value(b"ab", 16) == 0x6162
    assert hh.encode_value("ab", 16) == 0x6162
    assert hh.decode_value(0x6162, 16) == b"ab"
    assert hh.encode_value(200, 8) == 200
    with pytest.raises(ValueError, match="bytes"):
        hh.encode_value(b"abc", 16)
    with pytest.raises(ValueError, match="domain"):
        hh.encode_value(256, 8)
    with pytest.raises(ValueError):
        hh.HeavyHittersConfig(domain_bits=128)


def test_metrics_snapshot_reset_isolation():
    registry = MetricsRegistry()
    held = registry.counter("hh.rounds")
    held.inc(5)
    registry.gauge("hh.keys_live").set(8)
    registry.histogram("hh.round_ms").observe(1.5)
    snap = registry.snapshot()
    assert snap["counters"]["hh.rounds"] == 5
    registry.reset()
    # Instruments zero IN PLACE: they stay registered (no orphans) with
    # all values dropped.
    clean = registry.snapshot()
    assert clean["counters"]["hh.rounds"] == 0
    assert clean["gauges"]["hh.keys_live"] == 0.0
    assert clean["histograms"]["hh.round_ms"]["count"] == 0
    assert clean["histograms"]["hh.round_ms"]["p99"] is None
    # A reference held across the reset keeps writing to the SAME live
    # object the registry serves by name.
    held.inc(1)
    assert registry.counter("hh.rounds") is held
    assert registry.snapshot()["counters"]["hh.rounds"] == 1


def test_wire_v3_checksum_epoch_and_downgrade_compat():
    frontier = np.array([0, 5, 1 << 40], dtype=np.uint64)
    shares = np.array([7, 0, 0xFFFFFFFF], dtype=np.uint32)

    # v4 round trip carries the helper epoch, a frame checksum, and the
    # critical-path digest (recv/send timestamps + compute ms).
    resp = hh.encode_eval_response(
        2,
        shares,
        helper_ms=1.5,
        epoch=42,
        recv_ms=100.25,
        send_ms=101.75,
        compute_ms=1.125,
    )
    (
        r,
        decoded,
        version,
        helper_ms,
        epoch,
        timing,
    ) = hh.decode_eval_response_full(resp)
    assert (r, version, epoch) == (2, 4, 42)
    assert helper_ms == pytest.approx(1.5)
    assert timing == {
        "recv_ms": pytest.approx(100.25),
        "send_ms": pytest.approx(101.75),
        "compute_ms": pytest.approx(1.125),
    }
    np.testing.assert_array_equal(decoded, shares)

    # A flipped byte in the body fails the checksum as a typed
    # IntegrityError — which IS a ProtocolError, so every existing
    # handler that catches ProtocolError also catches damaged frames.
    assert issubclass(hh.IntegrityError, hh.ProtocolError)
    corrupt = bytearray(resp)
    corrupt[-1] ^= 0xFF
    with pytest.raises(hh.IntegrityError, match="checksum"):
        hh.decode_eval_response_full(bytes(corrupt))

    req = hh.encode_eval_request(1, frontier, trace_id="ab" * 8)
    r, decoded, version, trace_id = hh.decode_eval_request_full(req)
    assert (r, version, trace_id) == (1, 4, "ab" * 8)
    corrupt = bytearray(req)
    corrupt[len(corrupt) // 2] ^= 0x01
    with pytest.raises(hh.IntegrityError, match="checksum"):
        hh.decode_eval_request_full(bytes(corrupt))

    # Older wire versions still decode (no checksum to verify on
    # v1/v2, no critical-path digest below v4).
    for old in (1, 2, 3):
        old_resp = hh.encode_eval_response(2, shares, version=old)
        (
            r,
            decoded,
            version,
            _,
            epoch,
            timing,
        ) = hh.decode_eval_response_full(old_resp)
        assert version == old
        assert r == 2
        assert timing is None
        np.testing.assert_array_equal(decoded, shares)


def test_helper_replay_cache_makes_resends_idempotent(key_pairs):
    keys0, _ = key_pairs
    server = hh.HeavyHittersServer(CONFIG, keys0, allow_resume=True)
    sweep = hh.FrontierSweep(CONFIG)
    frontier = sweep.frontier
    first = server.evaluate_round(0, frontier)
    replay = server.evaluate_round(0, frontier)  # resend after a fault
    np.testing.assert_array_equal(first, replay)
    # A replay with a DIFFERENT frontier is not a resend — reject it.
    with pytest.raises(hh.ProtocolError, match="different frontier"):
        server.evaluate_round(0, frontier[:-1])
    # Without allow_resume the PR 2 contract stands: strict order.
    strict = hh.HeavyHittersServer(CONFIG, keys0)
    strict.evaluate_round(0, frontier)
    with pytest.raises(hh.ProtocolError, match="out of order"):
        strict.evaluate_round(0, frontier)
