"""EventJournal: ring bound, ordering, coalescing, concurrent emit,
failpoint watching, and the library emit sites that are cheap to drive
(SLO burn/recovery).

JAX-free on purpose: the journal is stdlib + tracing, and these tests
must stay fast enough for `make test-fast`.
"""

import threading

import pytest

from distributed_point_functions_tpu.observability import tracing
from distributed_point_functions_tpu.observability.events import (
    EventJournal,
    default_journal,
    emit,
    set_default_journal,
    watch_failpoints,
)
from distributed_point_functions_tpu.observability.slo import (
    SloObjective,
    SloTracker,
)
from distributed_point_functions_tpu.robustness.failpoints import (
    FailpointRegistry,
)
from distributed_point_functions_tpu.serving.metrics import MetricsRegistry


class FakeClock:
    def __init__(self):
        self.now = 1000.0

    def __call__(self):
        return self.now

    def advance(self, s):
        self.now += s


# -- core ring semantics ------------------------------------------------------


def test_emit_assigns_monotone_seq_and_fields():
    j = EventJournal(capacity=8)
    first = j.emit("a.one", "hello", severity="info", extra=42)
    second = j.emit("a.two", "world", severity="error")
    assert first["seq"] == 1 and second["seq"] == 2
    events = j.tail()
    assert [e["kind"] for e in events] == ["a.one", "a.two"]
    assert events[0]["extra"] == 42
    assert events[0]["t_mono"] <= events[1]["t_mono"]
    assert events[1]["severity"] == "error"


def test_ring_bound_evicts_oldest_and_counts_dropped():
    j = EventJournal(capacity=4)
    for i in range(10):
        j.emit("k", str(i))
    events = j.tail()
    assert len(events) == 4
    assert [e["message"] for e in events] == ["6", "7", "8", "9"]
    # Seq numbers keep counting across eviction: ordering stays provable.
    assert [e["seq"] for e in events] == [7, 8, 9, 10]
    export = j.export()
    assert export["emitted"] == 10
    assert export["dropped"] == 6


def test_bad_severity_and_capacity_rejected():
    with pytest.raises(ValueError):
        EventJournal(capacity=0)
    j = EventJournal()
    with pytest.raises(ValueError):
        j.emit("k", severity="fatal")


def test_tail_filters_kind_prefix_and_severity():
    j = EventJournal()
    j.emit("prober.mismatch", severity="error")
    j.emit("prober.recovered", severity="info")
    j.emit("breaker.transition", severity="warning")
    assert [e["kind"] for e in j.tail(kind="prober")] == [
        "prober.mismatch",
        "prober.recovered",
    ]
    # Exact match works too, and prefixes do not cross dots.
    assert len(j.tail(kind="breaker.transition")) == 1
    assert j.tail(kind="brea") == []
    errors = j.tail(min_severity="warning")
    assert [e["kind"] for e in errors] == [
        "prober.mismatch",
        "breaker.transition",
    ]
    assert len(j.tail(n=1)) == 1
    assert j.kinds() == {
        "breaker.transition": 1,
        "prober.mismatch": 1,
        "prober.recovered": 1,
    }


def test_coalescing_bumps_repeats_within_window():
    clock = FakeClock()
    j = EventJournal(clock=clock)
    for _ in range(5):
        j.emit("admission.shed", "t1", coalesce_key="shed:t1", coalesce_s=5.0)
    events = j.tail()
    assert len(events) == 1
    assert events[0]["repeats"] == 4
    # Past the window the next emit is a fresh event.
    clock.advance(6.0)
    j.emit("admission.shed", "t1", coalesce_key="shed:t1", coalesce_s=5.0)
    assert len(j.tail()) == 2
    assert j.export()["coalesced"] == 4


def test_concurrent_emit_keeps_seq_dense_and_unique():
    j = EventJournal(capacity=4096)
    threads = [
        threading.Thread(
            target=lambda: [j.emit("race", str(i)) for i in range(100)]
        )
        for _ in range(8)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    events = j.tail()
    seqs = [e["seq"] for e in events]
    assert len(events) == 800
    assert seqs == list(range(1, 801))


def test_trace_id_attached_when_tracing():
    j = EventJournal()
    with tracing.trace_request("evt.test", role="test") as trace:
        j.emit("traced.kind")
    j.emit("untraced.kind")
    traced, untraced = j.tail()
    assert traced["trace_id"] == trace.trace_id
    assert untraced["trace_id"] is None


def test_clear_keeps_seq_counting():
    j = EventJournal()
    j.emit("a")
    j.clear()
    assert j.tail() == []
    assert j.emit("b")["seq"] == 2


def test_default_journal_swap_and_module_emit():
    original = default_journal()
    mine = EventJournal()
    try:
        set_default_journal(mine)
        emit("swapped.kind", "here")
        assert [e["kind"] for e in mine.tail()] == ["swapped.kind"]
        assert original.tail(kind="swapped") == []
    finally:
        set_default_journal(original)


# -- subscriptions ------------------------------------------------------------


def test_watch_failpoints_emits_arm_disarm_and_retroactive():
    reg = FailpointRegistry(env=False)
    reg.arm("pre.armed", "delay", delay_ms=0.0)
    j = EventJournal()
    watch_failpoints(registry=reg, journal=j)
    # The already-armed site shows up retroactively.
    kinds = [e["kind"] for e in j.tail()]
    assert kinds == ["failpoint.armed"]
    assert j.tail()[0]["site"] == "pre.armed"
    reg.arm("transport.response", "corrupt", times=None)
    reg.disarm("transport.response")
    reg.clear()
    kinds = [(e["kind"], e["site"]) for e in j.tail()]
    assert kinds == [
        ("failpoint.armed", "pre.armed"),
        ("failpoint.armed", "transport.response"),
        ("failpoint.disarmed", "transport.response"),
        ("failpoint.disarmed", "pre.armed"),
    ]


def test_slo_burn_and_recovery_emit_events():
    original = default_journal()
    j = EventJournal()
    reg = MetricsRegistry()
    tracker = SloTracker(
        [
            SloObjective(
                name="ceiling",
                kind="gauge_max",
                metric="g",
                threshold=10.0,
                severity="hard",
            )
        ],
        registry=reg,
    )
    try:
        set_default_journal(j)
        reg.gauge("g").set(50.0)
        tracker.evaluate()
        tracker.evaluate()  # continuing breach: no second burn event
        reg.gauge("g").set(1.0)
        tracker.evaluate()
        kinds = [e["kind"] for e in j.tail()]
        assert kinds == ["slo.burn", "slo.recovered"]
        burn, recovered = j.tail()
        assert burn["severity"] == "error"
        assert burn["objective"] == "ceiling"
        assert recovered["objective"] == "ceiling"
    finally:
        set_default_journal(original)
