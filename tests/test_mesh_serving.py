"""Pod-scale mesh serving tests on the virtual 8-device CPU mesh.

The tentpole invariant: serving from a 2-D device mesh (database-shard
axis x key-batch axis) is bit-identical to the single-device oracle on
every path — materialized and streaming oracle tiers, non-power-of-two
key batches padded onto the key axis, and across a snapshot rotation
under live traffic (all shards flip at one batch boundary, never a
partial flip). Plus the perf contracts: pre-partitioned dispatch adds no
host relayout (per-request copy counts no higher than single-device)
and the donated selection scratch stages once, not per request.
"""

import threading

import jax
import numpy as np
import pytest

from distributed_point_functions_tpu.capacity.model import (
    CapacityModel,
    ThroughputCalibration,
    default_capacity_model,
)
from distributed_point_functions_tpu.observability.device import (
    default_telemetry,
)
from distributed_point_functions_tpu.observability.events import EventJournal
from distributed_point_functions_tpu.parallel.sharded import (
    ShardedServingPlan,
    make_mesh2d,
)
from distributed_point_functions_tpu.pir import messages
from distributed_point_functions_tpu.pir.client import DenseDpfPirClient
from distributed_point_functions_tpu.pir.database import DenseDpfPirDatabase
from distributed_point_functions_tpu.pir.server import (
    DenseDpfPirServer,
    clear_tier_floor,
    set_tier_floor,
)
from distributed_point_functions_tpu.prng import xor_bytes
from distributed_point_functions_tpu.serving import (
    PlainSession,
    ServingConfig,
    SnapshotManager,
)

NUM_RECORDS = 2000  # pads to 2048 blocks-worth: 16 selection blocks
RECORD_BYTES = 24
RNG = np.random.default_rng(1301)

RECORDS0 = [
    bytes(RNG.integers(0, 256, RECORD_BYTES, dtype=np.uint8))
    for _ in range(NUM_RECORDS)
]
# Generation 1 differs at every index so a torn (cross-generation) read
# can never accidentally equal either oracle.
RECORDS1 = [bytes(b ^ 0x5A for b in r) for r in RECORDS0]


def require_mesh2d(shards=4, key_devices=2):
    if len(jax.devices()) < shards * key_devices:
        pytest.skip(f"needs {shards * key_devices} devices")
    return make_mesh2d(shards, key_devices)


def build_db(records):
    builder = DenseDpfPirDatabase.Builder()
    for r in records:
        builder.insert(r)
    return builder.build()


def plain_request(keys):
    return messages.PirRequest(
        plain_request=messages.PlainRequest(dpf_keys=list(keys))
    )


def serve(server, keys):
    return server.handle_request(
        plain_request(keys)
    ).dpf_pir_response.masked_response


@pytest.fixture(autouse=True)
def reset_process_state():
    yield
    clear_tier_floor()
    default_capacity_model().configure_mesh(None)


# ---------------------------------------------------------------------------
# Bit-identity against the single-device oracle
# ---------------------------------------------------------------------------


def test_mesh_bit_identity_vs_materialized_and_streaming_oracle():
    """Both parties' mesh responses are byte-identical to the
    single-device server in its materialized AND streaming tiers, with
    a non-power-of-two key batch (3 keys onto a key axis of 2)."""
    mesh = require_mesh2d()
    oracle = DenseDpfPirServer.create_plain(build_db(RECORDS0))
    meshed = DenseDpfPirServer.create_plain(build_db(RECORDS0), mesh=mesh)

    client = DenseDpfPirClient.create(NUM_RECORDS, lambda pt, ci: pt)
    indices = [3, 1999, 777]
    keys0, keys1 = client._generate_key_pairs(indices)

    mesh_responses = {}
    for party, keys in enumerate((keys0, keys1)):
        got = serve(meshed, keys)
        assert got == serve(oracle, keys)
        mesh_responses[party] = got
    # The mesh actually served (no silent single-device fallback).
    assert meshed._mesh_plan is not None
    assert meshed._mesh_plan.requests >= 2

    # Same bytes against the streaming oracle tier.
    set_tier_floor("streaming")
    try:
        streaming_oracle = DenseDpfPirServer.create_plain(
            build_db(RECORDS0)
        )
        for party, keys in enumerate((keys0, keys1)):
            assert mesh_responses[party] == serve(streaming_oracle, keys)
    finally:
        clear_tier_floor()

    # And the two parties' mesh shares combine to the records.
    for q, idx in enumerate(indices):
        assert (
            xor_bytes(mesh_responses[0][q], mesh_responses[1][q])
            == RECORDS0[idx]
        )


def test_mesh_stage_keys_pads_onto_key_axis():
    """A non-power-of-two key batch pads to a multiple of the key-axis
    size at staging, pre-partitioned (no gather at dispatch)."""
    from distributed_point_functions_tpu.pir.dense_eval import (
        stage_keys_host,
    )

    mesh = require_mesh2d()
    meshed = DenseDpfPirServer.create_plain(build_db(RECORDS0), mesh=mesh)
    client = DenseDpfPirClient.create(NUM_RECORDS, lambda pt, ci: pt)
    keys0, _ = client._generate_key_pairs([1, 2, 3])
    plan = meshed._ensure_mesh_plan(3)
    assert plan is not None
    staged = plan.stage_keys(stage_keys_host(list(keys0)))
    assert staged[0].shape[0] == 4  # 3 keys -> key-axis multiple of 2
    assert staged[0].shape[0] % plan.num_key_devices == 0
    # Partitioned over the key axis (each device holds nq/K rows) and
    # spread across the whole mesh, not parked on one device.
    assert (
        staged[0].sharding.shard_shape(staged[0].shape)[0]
        == staged[0].shape[0] // plan.num_key_devices
    )
    assert len(staged[0].sharding.device_set) == (
        plan.num_key_devices * plan.num_shards
    )


# ---------------------------------------------------------------------------
# Rotation: all shards flip at one batch boundary
# ---------------------------------------------------------------------------


def test_mesh_rotation_under_traffic_never_tears():
    """Snapshot rotation on a mesh session under live traffic: every
    combined answer is entirely generation 0 or entirely generation 1
    (RECORDS1 differs at every byte, so a partial-shard flip would
    produce bytes matching neither), and the staged flip itself
    transfers nothing (prestage made it a cache hit)."""
    mesh = require_mesh2d()
    config = ServingConfig(max_batch_size=8, max_wait_ms=1.0)
    with PlainSession(
        build_db(RECORDS0), config, mesh=mesh
    ) as session:
        manager = SnapshotManager(session, journal=EventJournal())
        client = DenseDpfPirClient(NUM_RECORDS, lambda pt, info: pt)

        def query(indices):
            req0, req1 = client.create_plain_requests(indices)
            r0 = session.handle_request(req0)
            r1 = session.handle_request(req1)
            return [
                xor_bytes(a, b)
                for a, b in zip(
                    r0.dpf_pir_response.masked_response,
                    r1.dpf_pir_response.masked_response,
                )
            ]

        assert query([3, 77])[0] == RECORDS0[3]
        assert session.server._mesh_plan is not None
        # Warm every bucket shape the traffic below can form (3 keys
        # -> bucket 4; two coalesced workers -> bucket 8): a
        # first-shape mesh compile takes longer than the flip timeout
        # on CPU, and a compiling batch holds the batch boundary open.
        query([5, 123, 1500])
        query([5, 123, 1500, 6, 7, 8])

        stop = threading.Event()
        torn = []

        def traffic():
            while not stop.is_set():
                for got, idx in zip(query([5, 123, 1500]), (5, 123, 1500)):
                    if got not in (RECORDS0[idx], RECORDS1[idx]):
                        torn.append((idx, got))
                # Leave a gap so the flip's zero-inflight batch
                # boundary actually occurs under load.
                stop.wait(0.02)

        workers = [threading.Thread(target=traffic) for _ in range(2)]
        for w in workers:
            w.start()
        try:
            builder = DenseDpfPirDatabase.Builder()
            for i, r in enumerate(RECORDS1):
                builder.update(i, r)
            db1 = builder.build_from(session.server.database)
            ledger = default_telemetry().transfers
            staged = manager.stage(db1)
            assert staged > 0  # mesh-sharded staging moved real bytes
            copies_before_flip = ledger.copies("db_staging")
            manager.flip(timeout=30.0)
            # The flip re-used the prestaged mesh staging: zero new
            # db_staging uploads at the boundary.
            assert ledger.copies("db_staging") == copies_before_flip
        finally:
            stop.set()
            for w in workers:
                w.join()

        assert torn == []
        assert manager.serving_generation() == 1
        assert query([3])[0] == RECORDS1[3]
        # Still mesh-served after the rotation.
        assert session.server._mesh_plan is not None


def test_mesh_unbatched_probe_races_batched_traffic():
    """An unbatched direct `handle_plain_request` (the prober's probe
    path) racing batched traffic must serialize on the mesh execution
    lock: two shard_map programs interleaving their cross-shard psum
    rendezvous on the same device set deadlock. Regression test — this
    hung before `_mesh_exec_lock` existed; with it, both paths complete
    and stay bit-identical."""
    mesh = require_mesh2d()
    config = ServingConfig(max_batch_size=4, max_wait_ms=0.5)
    with PlainSession(
        build_db(RECORDS0), config, mesh=mesh
    ) as session:
        client = DenseDpfPirClient(NUM_RECORDS, lambda pt, info: pt)
        req0, req1 = client.create_plain_requests([9, 411])
        # Warm both shapes once so the race below is over execution,
        # not compiles.
        session.handle_request(req0)
        session.server.handle_plain_request(req0)
        assert session.server._mesh_plan is not None

        errors = []
        results = {"batched": [], "unbatched": []}

        def batched():
            try:
                for _ in range(6):
                    a = session.handle_request(req0)
                    b = session.handle_request(req1)
                    results["batched"].append(
                        xor_bytes(
                            a.dpf_pir_response.masked_response[0],
                            b.dpf_pir_response.masked_response[0],
                        )
                    )
            except Exception as e:  # noqa: BLE001 - surfaced below
                errors.append(e)

        def unbatched():
            try:
                for _ in range(6):
                    a = session.server.handle_plain_request(req0)
                    b = session.server.handle_plain_request(req1)
                    results["unbatched"].append(
                        xor_bytes(
                            a.dpf_pir_response.masked_response[0],
                            b.dpf_pir_response.masked_response[0],
                        )
                    )
            except Exception as e:  # noqa: BLE001 - surfaced below
                errors.append(e)

        threads = [
            threading.Thread(target=batched),
            threading.Thread(target=unbatched),
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=120.0)
        assert not any(t.is_alive() for t in threads), (
            "mesh execution deadlocked: concurrent shard_map programs "
            "interleaved their collectives"
        )
        assert errors == []
        assert results["batched"] == [RECORDS0[9]] * 6
        assert results["unbatched"] == [RECORDS0[9]] * 6


def test_mesh_swap_database_requires_full_staging_before_flip():
    """Server-level flip atomicity: prestage_database stages the new
    generation per-shard; swap_database then swaps one fully-assembled
    staging reference (a cache hit — no transfer at the flip)."""
    mesh = require_mesh2d()
    meshed = DenseDpfPirServer.create_plain(build_db(RECORDS0), mesh=mesh)
    client = DenseDpfPirClient.create(NUM_RECORDS, lambda pt, ci: pt)
    keys0, keys1 = client._generate_key_pairs([42])
    assert (
        xor_bytes(serve(meshed, keys0)[0], serve(meshed, keys1)[0])
        == RECORDS0[42]
    )

    builder = DenseDpfPirDatabase.Builder()
    for i, r in enumerate(RECORDS1):
        builder.update(i, r)
    db1 = builder.build_from(meshed.database)
    staged = meshed.prestage_database(db1)
    assert staged > 0
    assert meshed.prestage_database(db1) == 0  # idempotent: cache hit
    ledger = default_telemetry().transfers
    before = ledger.copies("db_staging")
    meshed.swap_database(db1)
    assert ledger.copies("db_staging") == before
    assert (
        xor_bytes(serve(meshed, keys0)[0], serve(meshed, keys1)[0])
        == RECORDS1[42]
    )


# ---------------------------------------------------------------------------
# Donation + relayout accounting (TransferLedger)
# ---------------------------------------------------------------------------


def test_donation_stages_scratch_once_not_per_request():
    """ROADMAP 3a, asserted in the ledger: with the donated scratch
    pool, N same-shape requests after warmup add ZERO
    selection_scratch copies (the donated buffer recycles); with
    DPF_TPU_DONATE=0 every request stages a fresh scratch. Donation
    therefore removes one copy per steady-state request."""
    mesh = require_mesh2d()
    meshed = DenseDpfPirServer.create_plain(build_db(RECORDS0), mesh=mesh)
    client = DenseDpfPirClient.create(NUM_RECORDS, lambda pt, ci: pt)
    keys0, _ = client._generate_key_pairs([7, 8, 9, 10])
    ledger = default_telemetry().transfers

    serve(meshed, keys0)  # warm: stages the one pooled scratch
    assert meshed._mesh_plan is not None
    warm_scratch = ledger.copies("selection_scratch")
    warm_keys = ledger.copies("key_staging")
    n = 4
    for _ in range(n):
        serve(meshed, keys0)
    assert ledger.copies("selection_scratch") == warm_scratch
    # Exactly one batched key-staging copy per request, nothing else.
    assert ledger.copies("key_staging") == warm_keys + n
    assert meshed._mesh_plan.scratch.reuses >= n

    # Control arm: donation off restages the scratch per request.
    undonated = ShardedServingPlan(
        mesh,
        walk_levels=meshed._mesh_plan.walk_levels,
        cut_levels=meshed._mesh_plan.cut_levels,
        chunk_levels=meshed._mesh_plan.chunk_levels,
        ip=meshed._mesh_plan.ip,
        donate=False,
    )
    from distributed_point_functions_tpu.pir.dense_eval import (
        stage_keys_host,
    )

    staged_host = stage_keys_host(list(keys0))
    db = meshed._mesh_db
    undonated.run(undonated.stage_keys(staged_host), db)  # warm
    before = ledger.copies("selection_scratch")
    for _ in range(n):
        undonated.run(undonated.stage_keys(staged_host), db)
    assert ledger.copies("selection_scratch") == before + n


def test_mesh_per_request_copies_not_higher_than_single_device():
    """Zero host relayout at dispatch: a warm mesh request costs no
    more TransferLedger h2d copies than a warm single-device request
    (both are exactly one batched key staging)."""
    mesh = require_mesh2d()
    oracle = DenseDpfPirServer.create_plain(build_db(RECORDS0))
    meshed = DenseDpfPirServer.create_plain(build_db(RECORDS0), mesh=mesh)
    client = DenseDpfPirClient.create(NUM_RECORDS, lambda pt, ci: pt)
    keys0, _ = client._generate_key_pairs([11, 12, 13])
    ledger = default_telemetry().transfers

    serve(oracle, keys0)  # warm both paths (db + scratch staged)
    serve(meshed, keys0)
    assert meshed._mesh_plan is not None

    before = ledger.copies()
    serve(oracle, keys0)
    single_device_copies = ledger.copies() - before

    before = ledger.copies()
    serve(meshed, keys0)
    mesh_copies = ledger.copies() - before

    assert mesh_copies <= single_device_copies
    assert mesh_copies == 1  # the one batched key staging


# ---------------------------------------------------------------------------
# Batcher / capacity wiring
# ---------------------------------------------------------------------------


def test_batcher_pads_buckets_to_key_multiple():
    from distributed_point_functions_tpu.serving.batcher import (
        DynamicBatcher,
    )

    batches = []

    def evaluate(keys):
        batches.append(len(keys))
        return list(keys)

    batcher = DynamicBatcher(evaluate, max_batch_size=16, max_wait_ms=0.5)
    try:
        batcher.set_key_multiple(8)
        assert batcher.submit([b"a", b"b", b"c"]) == [b"a", b"b", b"c"]
        assert batches[-1] == 8  # bucket_size(3)=4, padded to 8
        batcher.set_key_multiple(1)
        batcher.submit([b"a", b"b", b"c"])
        assert batches[-1] == 4
    finally:
        batcher.close()


def test_session_configures_mesh_capacity_and_key_multiple():
    mesh = require_mesh2d()
    with PlainSession(
        build_db(RECORDS0), ServingConfig(max_batch_size=8), mesh=mesh
    ) as session:
        assert session.batcher._key_multiple == 2
        model = default_capacity_model()
        assert model.mesh_shape == (4, 2)
        assert model.mesh_device_count() == 8


def test_capacity_model_mesh_pricing():
    model = CapacityModel(
        device_memory_bytes=1 << 30,
        calibration=ThroughputCalibration(history_path="/nonexistent"),
    )
    single_qps = model.serving_queries_per_sec()
    single_bytes = model.price_pir_keys(64, num_blocks=1024).bytes_peak
    model.configure_mesh(4, 2)
    # Per-mesh throughput prior: device count x single-device until a
    # calibrated multi-device record lands.
    assert model.serving_queries_per_sec() == pytest.approx(
        8 * single_qps
    )
    # Per-shard byte price is strictly below the materialized
    # single-device peak, and per-mesh budget scales by device count.
    mesh_bytes = model.price_pir_keys(64, num_blocks=1024).bytes_peak
    assert 0 < mesh_bytes < single_bytes
    assert (
        model.mesh_selection_budget_bytes()
        == 8 * model.selection_budget_bytes()
    )
    assert model.export()["mesh"]["devices"] == 8
    model.configure_mesh(None)
    assert model.price_pir_keys(64, num_blocks=1024).bytes_peak == (
        single_bytes
    )


def test_statusz_mesh_section_and_debug_bundles(tmp_path):
    import json
    import urllib.request

    from distributed_point_functions_tpu.observability.admin import (
        AdminServer,
    )
    from distributed_point_functions_tpu.observability.bundle import (
        BundleManager,
    )

    mesh = require_mesh2d()
    meshed = DenseDpfPirServer.create_plain(build_db(RECORDS0), mesh=mesh)
    client = DenseDpfPirClient.create(NUM_RECORDS, lambda pt, ci: pt)
    keys0, _ = client._generate_key_pairs([5])
    serve(meshed, keys0)  # builds the plan + mesh staging

    bundles = BundleManager(directory=str(tmp_path))
    with AdminServer(mesh=meshed.mesh_export, bundles=bundles) as admin:
        base = f"http://127.0.0.1:{admin.port}"
        page = urllib.request.urlopen(base + "/statusz").read().decode()
        assert "<h2>Mesh</h2>" in page
        assert "HBM watermark" in page
        state = json.load(
            urllib.request.urlopen(base + "/statusz?format=json")
        )
    mesh_state = state["mesh"]
    assert mesh_state["configured"] and mesh_state["two_dee"]
    assert mesh_state["shape"] == {"shard": 4, "key": 2}
    # One staging row per device: each of the 4 chunk shards lands on
    # both devices of its key-axis row (replicated over "key").
    shards = mesh_state["staging"]["shards"]
    assert len(shards) == 8
    assert len({(s["chunk_start"], s["chunk_stop"]) for s in shards}) == 4
    for shard in shards:
        assert shard["bytes"] > 0 and shard["copies"] == 1
        assert shard["hbm_watermark_bytes"] >= shard["bytes"]
    assert mesh_state["plan"]["donate"] is True
    # The mesh view rides incident debug bundles too.
    bundle = bundles.trigger("test")
    captured = json.load(open(f"{bundle['path']}/mesh.json"))
    assert captured["shape"] == {"shard": 4, "key": 2}


def test_make_mesh2d_validates_shape():
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 devices")
    mesh = make_mesh2d(2, 4)
    assert tuple(mesh.axis_names) == ("shard", "key")
    assert mesh.shape["shard"] == 2 and mesh.shape["key"] == 4
    assert make_mesh2d(key_devices=2).shape["shard"] == (
        len(jax.devices()) // 2
    )
    with pytest.raises(ValueError, match="needs"):
        make_mesh2d(len(jax.devices()), 2)
