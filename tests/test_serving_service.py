"""Serving session tests: batched correctness, retries, degradation.

Small database (128 x 16B) so the per-bucket jit compiles stay cheap;
the protocol mechanics (hybrid encryption, OTP masking, share
combination) are the real ones from pir/ and crypto/.
"""

import threading
import time

import numpy as np
import pytest

from distributed_point_functions_tpu.pir import (
    DenseDpfPirClient,
    DenseDpfPirDatabase,
    DenseDpfPirServer,
)
from distributed_point_functions_tpu.serving import (
    DeadlineExceeded,
    HelperSession,
    HelperUnavailable,
    InProcessTransport,
    LeaderSession,
    PlainSession,
    ServingConfig,
    TransportError,
    TransportTimeout,
)
from distributed_point_functions_tpu.testing import encrypt_decrypt

NUM_RECORDS = 128
RECORD_BYTES = 16
RNG = np.random.default_rng(1234)


def build_database():
    records = [
        bytes(RNG.integers(0, 256, RECORD_BYTES, dtype=np.uint8))
        for _ in range(NUM_RECORDS)
    ]
    builder = DenseDpfPirDatabase.Builder()
    for r in records:
        builder.insert(r)
    return builder.build(), records


DATABASE, RECORDS = build_database()


def make_config(**overrides):
    base = dict(
        max_batch_size=4,
        max_wait_ms=5.0,
        helper_timeout_ms=None,
        helper_retries=2,
        helper_backoff_ms=1.0,
        helper_backoff_max_ms=2.0,
    )
    base.update(overrides)
    return ServingConfig(**base)


# ---------------------------------------------------------------------------
# PlainSession: batched results == unbatched oracle, bounded compiles
# ---------------------------------------------------------------------------


def test_plain_session_bit_identical_to_unbatched_oracle():
    client = DenseDpfPirClient.create(NUM_RECORDS, lambda pt, ci: pt)
    indices = [[3], [77], [12, 99], [0]]
    requests = [client.create_plain_requests(ix)[0] for ix in indices]
    oracle_server = DenseDpfPirServer.create_plain(DATABASE)
    oracle = [
        oracle_server.handle_plain_request(r).dpf_pir_response.masked_response
        for r in requests
    ]

    with PlainSession(DATABASE, make_config()) as session:
        results = [None] * len(requests)

        def worker(i):
            results[i] = session.handle_request(requests[i])

        threads = [
            threading.Thread(target=worker, args=(i,))
            for i in range(len(requests))
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        counters = session.metrics.export()["counters"]

    for got, want in zip(results, oracle):
        assert got.dpf_pir_response.masked_response == want
    # Mixed sizes 1 and 2 over a max batch of 4: at most log2(4)+1 = 3
    # distinct jit shape buckets, counted via the metrics registry.
    assert 1 <= counters["plain.batcher.jit_bucket_compiles"] <= 3
    assert counters["plain.batcher.requests_submitted"] == len(requests)


def test_plain_session_unbatched_mode_matches_too():
    client = DenseDpfPirClient.create(NUM_RECORDS, lambda pt, ci: pt)
    request = client.create_plain_requests([42])[0]
    oracle_server = DenseDpfPirServer.create_plain(DATABASE)
    want = oracle_server.handle_plain_request(
        request
    ).dpf_pir_response.masked_response
    with PlainSession(DATABASE, make_config(batching=False)) as session:
        got = session.handle_request(request)
    assert got.dpf_pir_response.masked_response == want


def test_expired_deadline_rejected_without_evaluating():
    client = DenseDpfPirClient.create(NUM_RECORDS, lambda pt, ci: pt)
    request = client.create_plain_requests([5])[0]
    with PlainSession(DATABASE, make_config()) as session:
        with pytest.raises(DeadlineExceeded):
            session.handle_request(
                request, deadline=time.monotonic() - 0.001
            )
        counters = session.metrics.export()["counters"]
    assert counters["plain.batcher.requests_deadline_exceeded"] == 1


# ---------------------------------------------------------------------------
# Leader/Helper end-to-end with fault injection on the helper leg
# ---------------------------------------------------------------------------


class FlakyTransport(InProcessTransport):
    """Fails the first `failures` round trips, then behaves."""

    def __init__(self, handler, failures, exc=TransportTimeout):
        super().__init__(handler)
        self.failures = failures
        self.exc = exc
        self.attempts = 0

    def roundtrip(self, payload, timeout=None, on_sent=None):
        self.attempts += 1
        if self.attempts <= self.failures:
            raise self.exc("injected helper fault")
        return super().roundtrip(payload, timeout, on_sent)


def leader_helper_pair(transport_factory, leader_config=None):
    helper = HelperSession(
        DATABASE, encrypt_decrypt.decrypt, make_config()
    )
    leader = LeaderSession(
        DATABASE,
        transport_factory(helper.handle_wire),
        leader_config if leader_config is not None else make_config(),
    )
    return leader, helper


def run_query(leader, indices):
    client = DenseDpfPirClient.create(NUM_RECORDS, encrypt_decrypt.encrypt)
    request, state = client.create_request(indices)
    response = leader.handle_request(request)
    return client.handle_response(response, state)


def test_leader_helper_end_to_end_clean_path():
    leader, helper = leader_helper_pair(InProcessTransport)
    with helper, leader:
        got = run_query(leader, [3, 42, 127])
        counters = leader.metrics.export()["counters"]
    assert got == [RECORDS[3], RECORDS[42], RECORDS[127]]
    assert counters["leader.helper_retries"] == 0
    assert counters["leader.helper_failures"] == 0


def test_helper_timeout_then_retry_then_success():
    transports = []

    def factory(handler):
        t = FlakyTransport(handler, failures=2)
        transports.append(t)
        return t

    leader, helper = leader_helper_pair(factory)
    with helper, leader:
        got = run_query(leader, [7, 77])
        counters = leader.metrics.export()["counters"]
    assert got == [RECORDS[7], RECORDS[77]]
    assert transports[0].attempts == 3
    assert counters["leader.helper_retries"] == 2
    assert counters["leader.helper_timeouts"] == 2
    assert counters["leader.helper_failures"] == 0


def test_helper_permanently_down_raises_helper_unavailable():
    def factory(handler):
        return FlakyTransport(handler, failures=10**9, exc=TransportError)

    leader, helper = leader_helper_pair(factory)
    with helper, leader:
        with pytest.raises(HelperUnavailable):
            run_query(leader, [9])
        counters = leader.metrics.export()["counters"]
    # First attempt + helper_retries, then permanent failure.
    assert counters["leader.helper_retries"] == 2
    assert counters["leader.helper_failures"] == 1
    assert counters["leader.degraded_responses"] == 0


def test_helper_permanently_down_degraded_mode_keeps_answering():
    def factory(handler):
        return FlakyTransport(handler, failures=10**9)

    leader, helper = leader_helper_pair(
        factory, leader_config=make_config(allow_degraded=True)
    )
    client = DenseDpfPirClient.create(NUM_RECORDS, encrypt_decrypt.encrypt)
    request, _ = client.create_request([11])
    with helper, leader:
        response = leader.handle_request(request)
        counters = leader.metrics.export()["counters"]
    # The degraded response is the Leader's share only — a liveness
    # signal, NOT the record (the Helper's share is missing by
    # construction, so the payload must differ from the true record).
    masked = response.dpf_pir_response.masked_response
    assert len(masked) == 1
    assert masked[0] != RECORDS[11]
    assert counters["leader.degraded_responses"] == 1
    assert counters["leader.helper_failures"] == 1
