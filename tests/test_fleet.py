"""Fleet registry and price-aware router tests.

The contracts under test: replica health states are *fed* (breaker
transitions drain/restore, probe staleness drains, operator verbs
shed/readmit/kill — every transition journaled and counted); the
router keeps tenants sticky to one replica, places new tenants by
price x queue-depth score, spills over ONLY within the primary's
serving generation (a cross-generation XOR is well-formed garbage),
and aggregates a fleet-wide shed into one typed `Overloaded` carrying
the smallest positive retry hint.
"""

from types import SimpleNamespace

import pytest

from distributed_point_functions_tpu.fleet import (
    REPLICA_STATES,
    FleetRouter,
    Replica,
    ReplicaSet,
)
from distributed_point_functions_tpu.observability.events import EventJournal
from distributed_point_functions_tpu.serving.batcher import Overloaded
from distributed_point_functions_tpu.serving.metrics import MetricsRegistry


class StubBreaker:
    def __init__(self):
        self.listeners = []

    def on_transition(self, cb):
        self.listeners.append(cb)

    def force(self, old, new):
        for cb in self.listeners:
            cb(old, new)


class StubCapacity:
    """Duck-typed price model with a pinned per-probe device-ms."""

    def __init__(self, device_ms):
        self.device_ms = float(device_ms)
        self.replica = None

    def set_replica(self, rid):
        self.replica = rid

    def price_export(self, num_keys=8, num_blocks=None):
        return {
            "replica": self.replica,
            "probe_keys": num_keys,
            "device_ms": self.device_ms,
            "device_ms_per_key": self.device_ms / max(1, num_keys),
            "bytes_peak": 0,
            "queries_per_sec": 100.0,
        }


class StubSession:
    """Duck-typed leader session: answers, sheds, or counts calls."""

    def __init__(self, name, generation=0, shed=None):
        self.name = name
        self.shed = shed  # None, or an Overloaded to raise
        self.calls = []
        self.breaker = StubBreaker()
        self.degraded = False
        self.metrics = MetricsRegistry()
        self.server = SimpleNamespace(
            database=SimpleNamespace(generation=generation), role="plain"
        )

    def handle_request(self, request, deadline=None, tenant="default"):
        if self.shed is not None:
            raise self.shed
        self.calls.append((request, tenant))
        return f"resp:{self.name}"


def make_replica(rid, generation=0, device_ms=1.0, shed=None):
    return Replica(
        rid,
        StubSession(rid, generation=generation, shed=shed),
        capacity=StubCapacity(device_ms),
    )


def make_set(*replicas, journal=None):
    rs = ReplicaSet(journal=journal or EventJournal())
    for r in replicas:
        rs.add(r)
    return rs


# ---------------------------------------------------------------------------
# Registry: states, transitions, breaker feed, freshness
# ---------------------------------------------------------------------------


def test_states_transitions_and_export():
    journal = EventJournal()
    rs = make_set(
        make_replica("r0"), make_replica("r1"), journal=journal
    )
    assert [r.replica_id for r in rs.healthy()] == ["r0", "r1"]
    rs.shed("r0", reason="operator drill")
    assert rs.state("r0") == "draining"
    assert [r.replica_id for r in rs.healthy()] == ["r1"]
    rs.readmit("r0")
    rs.kill("r1", reason="hardware gone")
    assert rs.state("r1") == "dead"
    assert [r.replica_id for r in rs.alive()] == ["r0"]
    export = rs.export()
    assert export["counts"] == {
        "serving": 1, "staging": 0, "draining": 0, "dead": 1
    }
    assert export["sheds"] == 1 and export["readmissions"] == 1
    assert export["deaths"] == 1
    transitions = [(t["from"], t["to"]) for t in export["history"]]
    assert ("serving", "draining") in transitions
    assert ("draining", "serving") in transitions
    assert ("serving", "dead") in transitions
    row = export["replicas"]["r0"]
    assert row["state"] == "serving"
    assert row["price"]["replica"] == "r0"  # capacity stamped at add
    kinds = [e["kind"] for e in journal.export()["events"]]
    assert "fleet.replica_state" in kinds


def test_unknown_state_and_duplicate_id_rejected():
    rs = make_set(make_replica("r0"))
    with pytest.raises(ValueError, match="unknown replica state"):
        rs.mark("r0", "zombie")
    with pytest.raises(KeyError):
        rs.mark("nope", "serving")
    with pytest.raises(ValueError, match="already registered"):
        rs.add(make_replica("r0"))
    assert set(REPLICA_STATES) == {
        "serving", "staging", "draining", "dead"
    }


def test_breaker_open_drains_and_close_restores():
    r0 = make_replica("r0")
    rs = make_set(r0, make_replica("r1"))
    r0.leader.breaker.force("closed", "open")
    assert rs.state("r0") == "draining"
    assert [r.replica_id for r in rs.healthy()] == ["r1"]
    r0.leader.breaker.force("open", "half-open")
    assert rs.state("r0") == "draining"  # half-open is not healthy yet
    r0.leader.breaker.force("half-open", "closed")
    assert rs.state("r0") == "serving"


def test_breaker_close_does_not_override_operator_drain():
    r0 = make_replica("r0")
    rs = make_set(r0)
    rs.shed("r0", reason="operator drill")
    # A breaker closing must not readmit a replica an operator drained.
    r0.leader.breaker.force("half-open", "closed")
    assert rs.state("r0") == "draining"


def test_probe_staleness_refresh_drains_and_restores():
    r0 = make_replica("r0")
    fresh = {"pir_unbatched": {"identity": True, "fresh": True}}
    stale = {"pir_unbatched": {"identity": True, "fresh": False}}
    state = {"freshness": fresh}
    r0.prober = SimpleNamespace(freshness=lambda: state["freshness"])
    rs = make_set(r0)
    assert rs.refresh()["r0"] == "serving"
    state["freshness"] = stale
    assert rs.refresh()["r0"] == "draining"
    assert rs.healthy() == []
    state["freshness"] = fresh
    assert rs.refresh()["r0"] == "serving"


def test_listener_fires_on_transition():
    seen = []
    rs = make_set(make_replica("r0"))
    rs.add_listener(lambda rid, old, new, why: seen.append((rid, old, new)))
    rs.shed("r0")
    assert seen == [("r0", "serving", "draining")]


# ---------------------------------------------------------------------------
# Router: sticky affinity, price scoring, spillover, typed aggregation
# ---------------------------------------------------------------------------


def test_new_tenant_lands_on_cheapest_idle_replica():
    expensive = make_replica("costly", device_ms=9.0)
    cheap = make_replica("cheap", device_ms=1.0)
    router = FleetRouter(make_set(expensive, cheap))
    assert router.pick("t1").replica_id == "cheap"
    # Sticky: the pin survives a price flip.
    expensive.capacity.device_ms = 0.01
    assert router.pick("t1").replica_id == "cheap"
    assert router.affinity("t1") == "cheap"


def test_queue_depth_penalizes_cheap_but_backlogged_replica():
    cheap = make_replica("cheap", device_ms=1.0)
    pricier = make_replica("pricier", device_ms=2.0)
    # Cheap replica has a deep admission queue: 1.0 * (1+9) > 2.0 * 1.
    cheap.leader.metrics.gauge("plain.batcher.queue_depth").set(9)
    router = FleetRouter(make_set(cheap, pricier))
    assert router.pick("t1").replica_id == "pricier"


def test_affinity_moves_when_pinned_replica_drains():
    a = make_replica("a", device_ms=1.0)
    b = make_replica("b", device_ms=2.0)
    rs = make_set(a, b)
    router = FleetRouter(rs)
    assert router.pick("t1").replica_id == "a"
    rs.shed("a")
    assert router.pick("t1").replica_id == "b"
    assert router.affinity("t1") == "b"
    assert router.export()["affinity_moves"] == 1


def test_requests_route_to_affine_replica():
    a = make_replica("a", device_ms=1.0)
    b = make_replica("b", device_ms=2.0)
    router = FleetRouter(make_set(a, b))
    assert router.handle_request("q1", tenant="t1") == "resp:a"
    assert router.handle_request("q2", tenant="t1") == "resp:a"
    assert a.leader.calls == [("q1", "t1"), ("q2", "t1")]
    assert b.leader.calls == []
    assert router.export()["routed"] == {"a": 2}


def test_spillover_on_shed_stays_within_generation():
    shedding = make_replica(
        "shedding", device_ms=1.0,
        shed=Overloaded("queue full", retry_after_s=0.5, reason="queue"),
    )
    same_gen = make_replica("same_gen", device_ms=5.0)
    other_gen = make_replica("other_gen", device_ms=0.1, generation=7)
    router = FleetRouter(make_set(shedding, same_gen, other_gen))
    # Primary (cheapest healthy at gen 0... other_gen is cheaper but
    # pinning happens by score; force affinity onto the shedding one.
    router._affinity["t1"] = "shedding"
    out = router.handle_request("q", tenant="t1")
    # Spilled to the SAME-generation replica, never the cheaper
    # replica serving generation 7.
    assert out == "resp:same_gen"
    assert other_gen.leader.calls == []
    export = router.export()
    assert export["spillovers"] == 1
    assert export["generation_skips"] == 1


def test_fleet_wide_shed_aggregates_typed_overloaded():
    journal = EventJournal()
    a = make_replica(
        "a", shed=Overloaded("busy", retry_after_s=2.0, reason="queue")
    )
    b = make_replica(
        "b", shed=Overloaded("busy", retry_after_s=0.25, reason="cost")
    )
    router = FleetRouter(make_set(a, b, journal=journal), journal=journal)
    with pytest.raises(Overloaded) as excinfo:
        router.handle_request("q", tenant="t1")
    # One typed fleet error: smallest positive retry hint, fleet reason.
    assert excinfo.value.reason == "fleet"
    assert excinfo.value.retry_after_s == 0.25
    assert router.export()["fleet_sheds"] == 1
    kinds = [e["kind"] for e in journal.export()["events"]]
    assert "fleet.shed" in kinds


def test_no_healthy_replicas_is_typed_overloaded():
    rs = make_set(make_replica("a"))
    rs.kill("a")
    router = FleetRouter(rs)
    with pytest.raises(Overloaded) as excinfo:
        router.pick("t1")
    assert excinfo.value.reason == "fleet"
