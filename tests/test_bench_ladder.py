"""End-to-end exercise of bench.py's kernel-demotion ladder on CPU.

The ladder only runs when the auto pipeline's outer-jit compile fails —
a hardware-only event in production — so without this test its code
path ships unexecuted. A simulated walk-mode failure must: bank the
XLA-levels candidate first, demote the walk tier with attribution
evidence, persist the verdict, and still emit a valid headline JSON.
"""

import io
import json
import os
import sys

import pytest


@pytest.fixture()
def bench_env(monkeypatch, tmp_path):
    monkeypatch.setenv("BENCH_PLATFORM", "cpu")
    monkeypatch.setenv("BENCH_RECORDS", "4096")
    monkeypatch.setenv("BENCH_RECORD_BYTES", "64")
    monkeypatch.setenv("BENCH_QUERIES", "8")
    monkeypatch.setenv("BENCH_ITERS", "1")
    # Must leave >420 s of watchdog budget or the ladder's guard
    # (correctly) refuses to spend compile time on demotion retries.
    monkeypatch.setenv("BENCH_TIMEOUT", "1200")
    monkeypatch.setenv("BENCH_NO_PALLAS", "1")
    # bench.main() mutates this env var in place (the xla-first bank and
    # the winner pinning); setting it here lets monkeypatch restore it.
    monkeypatch.setenv("DPF_TPU_LEVEL_KERNEL", "auto")
    # Keep the XLA compilation cache out of the developer's real
    # ~/.cache/jax_bench.
    monkeypatch.setenv("BENCH_CACHE_DIR", str(tmp_path / "jax_cache"))
    monkeypatch.setenv(
        "DPF_TPU_VERDICT_CACHE", str(tmp_path / "verdicts.json")
    )
    # The v2 candidate has its own differential tests; skipping its
    # ~30 s CPU compile keeps these ladder tests inside the fast tier.
    monkeypatch.setenv("BENCH_NO_V2", "1")
    monkeypatch.chdir(tmp_path)
    return tmp_path


def test_ladder_demotes_walk_with_evidence(bench_env, monkeypatch):
    from distributed_point_functions_tpu.pir import dense_eval_planes as dep

    import bench

    real = dep.evaluate_selection_blocks_planes

    def flaky(*args, **kwargs):
        # Fail exactly the auto walk-mode composition: the XLA bank
        # runs under DPF_TPU_LEVEL_KERNEL=xla and must succeed; the
        # ladder's retry runs with the walk flag demoted and must
        # succeed.
        if (
            os.environ.get("DPF_TPU_LEVEL_KERNEL", "auto") == "auto"
            and dep._WALK_KERNEL_VERIFIED
            and not dep._WALK_KERNEL_FAILED
        ):
            raise RuntimeError("simulated Mosaic serving-shape failure")
        return real(*args, **kwargs)

    monkeypatch.setattr(dep, "evaluate_selection_blocks_planes", flaky)
    monkeypatch.setattr(dep, "warm_level_kernels", lambda: "walk")
    monkeypatch.setattr(dep, "_WALK_KERNEL_VERIFIED", True)
    monkeypatch.setattr(dep, "_WALK_KERNEL_FAILED", False)
    monkeypatch.setattr(dep, "_LEVEL_KERNEL_VERIFIED", True)
    monkeypatch.setattr(dep, "_VERDICTS_LOADED", True)
    monkeypatch.setattr(dep, "_LAST_RECORDED", None)

    out = io.StringIO()
    monkeypatch.setattr(sys, "stdout", out)
    try:
        bench.main()
    finally:
        # The daemon watchdog os._exit()s the WHOLE pytest process at
        # BENCH_TIMEOUT unless told the run completed; a failure above
        # must not nuke the rest of the suite. Also detach the jax
        # compilation-cache config main() installed.
        bench._PROGRESS["done"] = True
        import jax

        jax.config.update("jax_compilation_cache_dir", None)

    line = out.getvalue().strip().splitlines()[-1]
    result = json.loads(line)
    assert result["value"] > 0, result
    assert "error" not in result, result

    # The ladder demoted walk with evidence and persisted it.
    assert dep._WALK_KERNEL_FAILED is True
    with open(bench_env / "verdicts.json") as f:
        stored = json.load(f)
    (entry,) = stored.values()
    assert entry.get("_WALK_KERNEL_FAILED") is True


def test_vet_survives_hung_compile(bench_env, monkeypatch):
    """Fault-inject an infinite Mosaic compile (VERDICT r04 item 10):
    the subprocess vet must kill the hung child, skip the auto
    candidate, persist the engaged tier's hang verdict (backend alive),
    and still emit a valid headline from the banked XLA candidate —
    all without the in-process compile ever touching the hang."""
    import time as _time

    from distributed_point_functions_tpu.pir import dense_eval_planes as dep

    import bench

    # The child subprocess inherits these: the injected hang fires on
    # any non-xla-pinned dispatch (the vet child's compile), while the
    # parent's banked XLA candidate stays clean.
    monkeypatch.setenv("DPF_TPU_FAULT_COMPILE_HANG", "1")
    monkeypatch.setenv("BENCH_VET_TIMEOUT", "60")

    # Parent-side state says the walk tier is verified, so the vet runs.
    monkeypatch.setattr(dep, "warm_level_kernels", lambda: "walk")
    monkeypatch.setattr(dep, "_WALK_KERNEL_VERIFIED", True)
    monkeypatch.setattr(dep, "_WALK_KERNEL_FAILED", False)
    monkeypatch.setattr(dep, "_LEVEL_KERNEL_VERIFIED", True)
    monkeypatch.setattr(dep, "_VERDICTS_LOADED", True)
    monkeypatch.setattr(dep, "_LAST_RECORDED", None)

    out = io.StringIO()
    monkeypatch.setattr(sys, "stdout", out)
    t0 = _time.monotonic()
    try:
        bench.main()
    finally:
        bench._PROGRESS["done"] = True
        import jax

        jax.config.update("jax_compilation_cache_dir", None)
    elapsed = _time.monotonic() - t0

    line = out.getvalue().strip().splitlines()[-1]
    result = json.loads(line)
    assert result["value"] > 0, result
    assert "error" not in result, result
    # The run survived the hang in roughly the vet timeout, not the
    # watchdog's: the in-process compile never executed the fault.
    assert elapsed < 600, elapsed

    # The hang was attributed (CPU backend answers the liveness probe)
    # and persisted for the next process.
    assert dep._WALK_KERNEL_FAILED is True
    with open(bench_env / "verdicts.json") as f:
        stored = json.load(f)
    (entry,) = stored.values()
    assert entry.get("_WALK_KERNEL_FAILED") is True


def test_warm_child_hang_skips_kernel_tiers(bench_env, monkeypatch):
    """A hung self-check compile in the bounded warm child must skip the
    in-process warmup (no kernel tiers this run), demote nothing, and
    still emit a valid headline from the banked XLA candidate."""
    import time as _time

    import jax

    from distributed_point_functions_tpu.pir import dense_eval_planes as dep

    import bench

    # Route the TPU-only warm-child path on CPU; the child itself runs
    # on BENCH_PLATFORM=cpu, writes its marker, then hangs on the
    # injected fault.
    monkeypatch.setattr(jax, "default_backend", lambda: "tpu")
    monkeypatch.setenv("DPF_TPU_FAULT_WARM_HANG", "1")
    monkeypatch.setenv("BENCH_WARM_TIMEOUT", "30")
    monkeypatch.setattr(dep, "_VERDICTS_LOADED", True)
    monkeypatch.setattr(dep, "_LAST_RECORDED", None)

    out = io.StringIO()
    monkeypatch.setattr(sys, "stdout", out)
    t0 = _time.monotonic()
    try:
        bench.main()
    finally:
        bench._PROGRESS["done"] = True
        jax.config.update("jax_compilation_cache_dir", None)
    elapsed = _time.monotonic() - t0

    line = out.getvalue().strip().splitlines()[-1]
    result = json.loads(line)
    assert result["value"] > 0, result
    assert "error" not in result, result
    assert elapsed < 420, elapsed
    # An ambiguous warm hang must never demote kernel tiers.
    assert dep._WALK_KERNEL_FAILED is False
    assert dep._TAIL_KERNEL_FAILED is False
    assert dep._LEVEL_KERNEL_FAILED is False
