"""Differential tests for the plane-resident path walk
(`dpf._eval_paths_planes`) against the limb-space kernel, plus
integration through `evaluate_at` with the dispatcher forced to planes.
"""

import jax.numpy as jnp
import numpy as np
import pytest

from distributed_point_functions_tpu.dpf import (
    DistributedPointFunction,
    DpfParameters,
    _eval_paths_limb,
    _eval_paths_planes,
)
from distributed_point_functions_tpu.value_types import IntType

RNG = np.random.default_rng(17)


@pytest.mark.parametrize(
    "n,levels,mode",
    [
        (7, 5, "shared"),      # key-padding path
        (64, 12, "shared"),
        (33, 9, "per_seed"),   # multi-key batch mode, padded
        (256, 32, "per_seed"),
    ],
)
def test_planes_matches_limb(n, levels, mode):
    m = 1 if mode == "shared" else n
    seeds = jnp.asarray(RNG.integers(0, 2**32, (n, 4), dtype=np.uint32))
    control = jnp.asarray(RNG.integers(0, 2, n, dtype=np.uint32))
    paths = jnp.asarray(RNG.integers(0, 2**32, (n, 4), dtype=np.uint32))
    cw_s = jnp.asarray(
        RNG.integers(0, 2**32, (levels, m, 4), dtype=np.uint32)
    )
    cw_l = jnp.asarray(RNG.integers(0, 2, (levels, m), dtype=np.uint32))
    cw_r = jnp.asarray(RNG.integers(0, 2, (levels, m), dtype=np.uint32))
    bi = jnp.asarray(RNG.integers(0, 128, levels, dtype=np.int32))
    a_seeds, a_ctrl = _eval_paths_limb(
        seeds, control, paths, cw_s, cw_l, cw_r, bi
    )
    b_seeds, b_ctrl = _eval_paths_planes(
        seeds, control, paths, cw_s, cw_l, cw_r, bi
    )
    np.testing.assert_array_equal(np.asarray(a_seeds), np.asarray(b_seeds))
    np.testing.assert_array_equal(np.asarray(a_ctrl), np.asarray(b_ctrl))


def test_evaluate_at_share_correctness_via_planes(monkeypatch):
    """evaluate_at with DPF_TPU_EVAL_PATHS=planes: shares still sum to
    beta at alpha and 0 elsewhere."""
    monkeypatch.setenv("DPF_TPU_EVAL_PATHS", "planes")
    lds = 14
    dpf = DistributedPointFunction.create(
        DpfParameters(log_domain_size=lds, value_type=IntType(64))
    )
    alpha, beta = 777, 123456789
    k0, k1 = dpf.generate_keys(alpha, beta)
    points = [0, 1, alpha - 1, alpha, alpha + 1, (1 << lds) - 1] + [
        int(x) for x in RNG.integers(0, 1 << lds, 40)
    ]
    import jax

    vt = IntType(64)
    e0 = jax.tree_util.tree_map(np.asarray, dpf.evaluate_at(k0, 0, points))
    e1 = jax.tree_util.tree_map(np.asarray, dpf.evaluate_at(k1, 0, points))
    for i, p in enumerate(points):
        s = vt.add(vt.to_python(e0, (i,)), vt.to_python(e1, (i,)))
        assert s == (beta if p == alpha else 0), (p, s)


@pytest.mark.parametrize(
    "p,levels",
    [(1, 1), (1, 6), (3, 5), (32, 4), (7, 0)],
)
def test_expand_levels_planes_matches_limb(p, levels):
    from distributed_point_functions_tpu.dpf import (
        _expand_levels_limb_fn,
        _expand_levels_planes_fn,
    )

    seeds = jnp.asarray(RNG.integers(0, 2**32, (p, 4), dtype=np.uint32))
    control = jnp.asarray(RNG.integers(0, 2, p, dtype=np.uint32))
    lmax = max(levels, 1)
    cw_s = jnp.asarray(
        RNG.integers(0, 2**32, (lmax, 4), dtype=np.uint32)
    )
    cw_l = jnp.asarray(RNG.integers(0, 2, lmax, dtype=np.uint32))
    cw_r = jnp.asarray(RNG.integers(0, 2, lmax, dtype=np.uint32))
    a = _expand_levels_limb_fn(levels)(seeds, control, cw_s, cw_l, cw_r)
    b = _expand_levels_planes_fn(levels)(seeds, control, cw_s, cw_l, cw_r)
    np.testing.assert_array_equal(np.asarray(a[0]), np.asarray(b[0]))
    np.testing.assert_array_equal(np.asarray(a[1]), np.asarray(b[1]))


@pytest.mark.parametrize("p,levels,head_req,tail_req,compact", [
    (8, 5, 2, 2, False),  # walk head (clipped to avail) + walk tail
    (8, 7, 0, 3, False),  # walk tail with a per-level middle
    (8, 7, 0, 3, True),   # compact-entry walk tail
    (8, 5, 2, 2, True),   # compact-entry walk head + tail
])
def test_expand_levels_walk_kinds_match_limb(
    monkeypatch, p, levels, head_req, tail_req, compact
):
    """The hierarchical expansion with walk-kind head/tail must be
    bit-identical to the limb program (incl. the fused leaf hash and
    the composed exit order)."""
    import functools as ft

    from distributed_point_functions_tpu import dpf as dpf_mod
    from distributed_point_functions_tpu.ops import (
        expand_planes_pallas as epp,
    )

    monkeypatch.setattr(
        epp, "walk_descend_planes_pallas",
        ft.partial(epp.walk_descend_planes_pallas, interpret=True),
    )
    monkeypatch.setattr(
        epp, "expand_level_planes_pallas",
        ft.partial(epp.expand_level_planes_pallas, interpret=True),
    )
    monkeypatch.setattr(
        epp, "value_hash_planes_pallas",
        ft.partial(epp.value_hash_planes_pallas, interpret=True),
    )
    seeds = jnp.asarray(RNG.integers(0, 2**32, (p, 4), dtype=np.uint32))
    control = jnp.asarray(RNG.integers(0, 2, p, dtype=np.uint32))
    cw_s = jnp.asarray(
        RNG.integers(0, 2**32, (levels, 4), dtype=np.uint32)
    )
    cw_l = jnp.asarray(RNG.integers(0, 2, levels, dtype=np.uint32))
    cw_r = jnp.asarray(RNG.integers(0, 2, levels, dtype=np.uint32))
    want = dpf_mod._expand_levels_limb_fn(levels, hash_leaves=True)(
        seeds, control, cw_s, cw_l, cw_r
    )
    dpf_mod._expand_levels_planes_fn.cache_clear()
    try:
        got = dpf_mod._expand_levels_planes_fn(
            levels, level_kernel=True, hash_leaves=True,
            tail_req=tail_req, tail_tile_target=128,
            head_req=head_req, head_cap=1 << 20,
            tail_kind="walk", head_kind="walk",
            walk_compact=compact,
        )(seeds, control, cw_s, cw_l, cw_r)
    finally:
        dpf_mod._expand_levels_planes_fn.cache_clear()
    np.testing.assert_array_equal(np.asarray(got[0]), np.asarray(want[0]))
    np.testing.assert_array_equal(np.asarray(got[1]), np.asarray(want[1]))


def test_hierarchical_eval_via_planes(monkeypatch):
    """evaluate_until with DPF_TPU_EXPAND_LEVELS=planes: share sums over
    a two-level hierarchy still reconstruct the point function."""
    monkeypatch.setenv("DPF_TPU_EXPAND_LEVELS", "planes")
    params = [
        DpfParameters(log_domain_size=6, value_type=IntType(32)),
        DpfParameters(log_domain_size=10, value_type=IntType(32)),
    ]
    dpf = DistributedPointFunction.create_incremental(params)
    alpha, betas = 777, [5, 9]
    k0, k1 = dpf.generate_keys_incremental(alpha, betas)
    ctx0 = dpf.create_evaluation_context(k0)
    ctx1 = dpf.create_evaluation_context(k1)
    lvl0_0 = np.asarray(dpf.evaluate_next([], ctx0), dtype=np.uint32)
    lvl0_1 = np.asarray(dpf.evaluate_next([], ctx1), dtype=np.uint32)
    total0 = lvl0_0 + lvl0_1
    prefix = alpha >> 4
    for x in range(64):
        assert total0[x] == (betas[0] if x == prefix else 0), x
    # Descend under the live prefix to the full domain.
    lvl1_0 = np.asarray(
        dpf.evaluate_next([prefix], ctx0), dtype=np.uint32
    )
    lvl1_1 = np.asarray(
        dpf.evaluate_next([prefix], ctx1), dtype=np.uint32
    )
    total1 = lvl1_0 + lvl1_1
    base = prefix << 4
    for j in range(16):
        want = betas[1] if base + j == alpha else 0
        assert total1[j] == want, (base + j, int(total1[j]))


def test_dispatcher_rejects_unknown_mode(monkeypatch):
    from distributed_point_functions_tpu.utils.runtime import planes_selected

    monkeypatch.setenv("DPF_TPU_EVAL_PATHS", "plane")  # typo
    with pytest.raises(ValueError, match="auto|limb|planes"):
        planes_selected("DPF_TPU_EVAL_PATHS")
    monkeypatch.setenv("DPF_TPU_EVAL_PATHS", "limb")
    assert planes_selected("DPF_TPU_EVAL_PATHS") is False
    monkeypatch.setenv("DPF_TPU_EVAL_PATHS", "planes")
    assert planes_selected("DPF_TPU_EVAL_PATHS") is True
