"""Differential tests for the plane-resident path walk
(`dpf._eval_paths_planes`) against the limb-space kernel, plus
integration through `evaluate_at` with the dispatcher forced to planes.
"""

import jax.numpy as jnp
import numpy as np
import pytest

from distributed_point_functions_tpu.dpf import (
    DistributedPointFunction,
    DpfParameters,
    _eval_paths_limb,
    _eval_paths_planes,
)
from distributed_point_functions_tpu.value_types import IntType

RNG = np.random.default_rng(17)


@pytest.mark.parametrize(
    "n,levels,mode",
    [
        (7, 5, "shared"),      # key-padding path
        (64, 12, "shared"),
        (33, 9, "per_seed"),   # multi-key batch mode, padded
        (256, 32, "per_seed"),
    ],
)
def test_planes_matches_limb(n, levels, mode):
    m = 1 if mode == "shared" else n
    seeds = jnp.asarray(RNG.integers(0, 2**32, (n, 4), dtype=np.uint32))
    control = jnp.asarray(RNG.integers(0, 2, n, dtype=np.uint32))
    paths = jnp.asarray(RNG.integers(0, 2**32, (n, 4), dtype=np.uint32))
    cw_s = jnp.asarray(
        RNG.integers(0, 2**32, (levels, m, 4), dtype=np.uint32)
    )
    cw_l = jnp.asarray(RNG.integers(0, 2, (levels, m), dtype=np.uint32))
    cw_r = jnp.asarray(RNG.integers(0, 2, (levels, m), dtype=np.uint32))
    bi = jnp.asarray(RNG.integers(0, 128, levels, dtype=np.int32))
    a_seeds, a_ctrl = _eval_paths_limb(
        seeds, control, paths, cw_s, cw_l, cw_r, bi
    )
    b_seeds, b_ctrl = _eval_paths_planes(
        seeds, control, paths, cw_s, cw_l, cw_r, bi
    )
    np.testing.assert_array_equal(np.asarray(a_seeds), np.asarray(b_seeds))
    np.testing.assert_array_equal(np.asarray(a_ctrl), np.asarray(b_ctrl))


def test_evaluate_at_share_correctness_via_planes(monkeypatch):
    """evaluate_at with DPF_TPU_EVAL_PATHS=planes: shares still sum to
    beta at alpha and 0 elsewhere."""
    monkeypatch.setenv("DPF_TPU_EVAL_PATHS", "planes")
    lds = 14
    dpf = DistributedPointFunction.create(
        DpfParameters(log_domain_size=lds, value_type=IntType(64))
    )
    alpha, beta = 777, 123456789
    k0, k1 = dpf.generate_keys(alpha, beta)
    points = [0, 1, alpha - 1, alpha, alpha + 1, (1 << lds) - 1] + [
        int(x) for x in RNG.integers(0, 1 << lds, 40)
    ]
    import jax

    vt = IntType(64)
    e0 = jax.tree_util.tree_map(np.asarray, dpf.evaluate_at(k0, 0, points))
    e1 = jax.tree_util.tree_map(np.asarray, dpf.evaluate_at(k1, 0, points))
    for i, p in enumerate(points):
        s = vt.add(vt.to_python(e0, (i,)), vt.to_python(e1, (i,)))
        assert s == (beta if p == alpha else 0), (p, s)
