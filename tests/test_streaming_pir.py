"""Streaming fused expand->inner-product serving pipeline tests.

Differential coverage of `dense_eval_planes_v2.streaming_pir_inner_products_v2`
against the materialized selection-matrix path (the oracle), the serving
planner's mode/budget model (`pir/planner.py`), the server-level dispatch,
the chunk-sharded mesh variant, the hierarchical-geometry tail-kernel
verdict, and the database staging locks. All CPU-runnable (tier-1).
"""

import functools
import threading

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_point_functions_tpu.ops.inner_product import (
    xor_inner_product,
    xor_inner_product_accumulate,
)
from distributed_point_functions_tpu.pir import messages
from distributed_point_functions_tpu.pir.client import DenseDpfPirClient
from distributed_point_functions_tpu.pir.database import DenseDpfPirDatabase
from distributed_point_functions_tpu.pir.dense_eval import (
    evaluate_selection_blocks,
    stage_keys,
)
from distributed_point_functions_tpu.pir.dense_eval_planes_v2 import (
    bitrev_permutation,
    streaming_block_order,
    streaming_block_permute_records,
    streaming_pir_inner_products_v2,
)
from distributed_point_functions_tpu.pir.planner import (
    CHUNK_GRANULE_LEVELS,
    chunked_selection_bytes,
    materialized_selection_bytes,
    plan_dense_serving,
    streaming_ip,
    streaming_selection_bytes,
)
from distributed_point_functions_tpu.pir.server import DenseDpfPirServer
from distributed_point_functions_tpu.prng import xor_bytes

RNG = np.random.default_rng(77)


def _staged_batch(num_records, indices):
    """Client keys for `indices`, staged, plus the tree split the server
    uses: (staged, walk_levels, expand_levels)."""
    client = DenseDpfPirClient.create(num_records, lambda pt, ci: pt)
    keys0, keys1 = client._generate_key_pairs(list(indices))
    staged = stage_keys(keys0)
    total = staged[2].shape[0]
    num_blocks = -(-num_records // 128)
    expand = max(0, (num_blocks - 1).bit_length())
    return staged, total - expand, expand, keys0, keys1


def _oracle(db, staged, walk_levels, expand_levels):
    """Materialized path over the full padded (covering) domain."""
    sel = evaluate_selection_blocks(
        *staged,
        walk_levels=walk_levels,
        expand_levels=expand_levels,
        num_blocks=1 << expand_levels,
    )
    return np.asarray(
        xor_inner_product(jnp.asarray(db._host_words_padded()), sel)
    )


# ---------------------------------------------------------------------------
# Streaming block-order algebra
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("e,cut", [(4, 0), (4, 2), (4, 4), (5, 3), (1, 1)])
def test_streaming_block_order_is_involution(e, cut):
    """position -> natural-block is its own inverse (both factors are
    bit reversals), so one gather stages and one gather un-stages."""
    order = streaming_block_order(e, cut)
    assert np.array_equal(order[order], np.arange(1 << e))


def test_streaming_block_order_degenerate_cuts_are_plain_bitrev():
    """cut=0 (whole tree is one chunk) and cut=e (one block per chunk)
    both collapse to the full bit-reversal the bitrev staging uses."""
    for e in (3, 5):
        full = np.asarray(bitrev_permutation(e))
        assert np.array_equal(streaming_block_order(e, 0), full)
        assert np.array_equal(streaming_block_order(e, e), full)


def test_streaming_block_permute_rejects_bad_geometry():
    with pytest.raises(ValueError, match="multiple of 128"):
        streaming_block_permute_records(np.zeros((100, 2), np.uint32), 1)
    with pytest.raises(ValueError, match="power of two"):
        streaming_block_permute_records(np.zeros((3 * 128, 2), np.uint32), 1)
    with pytest.raises(ValueError, match="cut_levels"):
        streaming_block_order(2, 3)


def test_xor_inner_product_accumulate_partitions():
    """XOR-accumulating per-span partials equals the whole-db product
    (the identity the streaming scan relies on)."""
    db = RNG.integers(0, 1 << 32, (512, 3), dtype=np.uint32)
    sel = RNG.integers(0, 1 << 32, (4, 4, 4), dtype=np.uint32)
    whole = np.asarray(xor_inner_product(jnp.asarray(db), jnp.asarray(sel)))
    acc = jnp.zeros((4, 3), jnp.uint32)
    for c in range(4):
        acc = xor_inner_product_accumulate(
            acc,
            jnp.asarray(db[c * 128:(c + 1) * 128]),
            jnp.asarray(sel[:, c:c + 1]),
        )
    np.testing.assert_array_equal(np.asarray(acc), whole)


# ---------------------------------------------------------------------------
# Streaming vs materialized differential (the tentpole's correctness bar)
# ---------------------------------------------------------------------------


@pytest.mark.parametrize(
    "num_records,size,nq,cuts",
    [
        # Full split sweep incl. cut=0 (chunk == whole domain) and
        # chunk_levels=0 (chunk == one block).
        (1000, 8, 5, (0, 1, 2, 3)),
        # Multi-word records; batch of one. The chunk-boundary edges are
        # covered above — one mid split each keeps the CPU tier-1 cost
        # bounded (every (cut, shapes) pair is its own scan compile).
        (384, 256, 33, (1,)),
        (1500, 8, 1, (2,)),
    ],
)
def test_streaming_matches_materialized_cut_sweep(num_records, size, nq, cuts):
    """Bit-identical inner products across cut/chunk splits."""
    records = [RNG.bytes(size) for _ in range(num_records)]
    db = DenseDpfPirDatabase(records)
    indices = [int(i) for i in RNG.integers(0, num_records, nq)]
    staged, walk, e, _, _ = _staged_batch(num_records, indices)
    want = _oracle(db, staged, walk, e)

    for cut in cuts:
        chunks = db.streaming_chunks(cut_levels=cut, bitmajor=False)
        got = np.asarray(
            streaming_pir_inner_products_v2(
                *staged,
                chunks,
                walk_levels=walk,
                cut_levels=cut,
                chunk_levels=e - cut,
                ip="jnp",
            )
        )
        np.testing.assert_array_equal(got, want, err_msg=f"cut={cut}")


def test_streaming_large_batch_matches_materialized():
    """q=128 batch (the bench's headline batch size) through one split."""
    num_records, nq = 1000, 128
    records = [RNG.bytes(8) for _ in range(num_records)]
    db = DenseDpfPirDatabase(records)
    indices = [int(i) for i in RNG.integers(0, num_records, nq)]
    staged, walk, e, _, _ = _staged_batch(num_records, indices)
    want = _oracle(db, staged, walk, e)
    chunks = db.streaming_chunks(cut_levels=1, bitmajor=False)
    got = np.asarray(
        streaming_pir_inner_products_v2(
            *staged,
            chunks,
            walk_levels=walk,
            cut_levels=1,
            chunk_levels=e - 1,
            ip="jnp",
        )
    )
    np.testing.assert_array_equal(got, want)


def test_streaming_pallas2_interpret_matches_jnp():
    """The MXU scan tier (bit-major staging + pallas2 accumulate) is
    bit-identical to the jnp scan tier (interpret mode: no Mosaic)."""
    num_records, nq = 512, 3
    records = [RNG.bytes(12) for _ in range(num_records)]
    db = DenseDpfPirDatabase(records)
    indices = [0, 511, 200]
    staged, walk, e, _, _ = _staged_batch(num_records, indices)
    want = _oracle(db, staged, walk, e)
    kwargs = dict(walk_levels=walk, cut_levels=1, chunk_levels=e - 1)
    got = np.asarray(
        streaming_pir_inner_products_v2(
            *staged,
            db.streaming_chunks(cut_levels=1, bitmajor=True),
            ip="pallas2",
            interpret=True,
            **kwargs,
        )
    )
    np.testing.assert_array_equal(got, want)


def test_streaming_validates_plan_geometry():
    records = [RNG.bytes(8) for _ in range(256)]
    db = DenseDpfPirDatabase(records)
    staged, walk, e, _, _ = _staged_batch(256, [1])
    chunks = db.streaming_chunks(cut_levels=1, bitmajor=False)
    with pytest.raises(ValueError, match="correction levels"):
        streaming_pir_inner_products_v2(
            *staged, chunks, walk_levels=walk, cut_levels=1, chunk_levels=e
        )
    with pytest.raises(ValueError, match="database chunks"):
        streaming_pir_inner_products_v2(
            *staged, chunks, walk_levels=walk, cut_levels=0, chunk_levels=e
        )


# ---------------------------------------------------------------------------
# Planner
# ---------------------------------------------------------------------------


def test_plan_materialized_when_under_budget():
    plan = plan_dense_serving(
        num_keys=4, num_blocks=8, expand_levels=3, budget_bytes=1 << 20
    )
    assert plan.mode == "materialized"
    assert plan.selection_bytes_peak == materialized_selection_bytes(4, 8)
    assert plan.selection_bytes_peak <= plan.budget_bytes


def test_plan_streaming_over_budget_fits_model():
    """Over-budget + covering tree -> streaming, and the chosen split's
    modeled peak respects the budget (the acceptance bound) while
    maximizing chunk_levels."""
    nq, e = 20, 4
    budget = 4000  # mat = 20*16*16B = 5120 > budget
    plan = plan_dense_serving(
        num_keys=nq,
        num_blocks=16,
        expand_levels=e,
        serving_bitrev=True,
        budget_bytes=budget,
    )
    assert plan.mode == "streaming"
    assert plan.cut_levels + plan.chunk_levels == e
    assert plan.num_chunks == 1 << plan.cut_levels
    assert plan.selection_bytes_peak == streaming_selection_bytes(
        nq, plan.cut_levels, plan.chunk_levels
    )
    assert plan.selection_bytes_peak <= budget
    # Largest feasible chunk: every bigger split must overflow the budget.
    for r in range(plan.chunk_levels + 1, e + 1):
        assert streaming_selection_bytes(nq, e - r, r) > budget


def test_plan_streaming_infeasible_budget_minimizes_peak():
    """When no split fits, the planner still streams (each scan step is
    strictly smaller than the materialized tensor) at the peak-minimizing
    split."""
    nq, e = 5, 4
    budget = 256
    plan = plan_dense_serving(
        num_keys=nq, num_blocks=12, expand_levels=e, budget_bytes=budget
    )
    assert plan.mode == "streaming"
    best = min(
        streaming_selection_bytes(nq, e - r, r) for r in range(e + 1)
    )
    assert plan.selection_bytes_peak == best
    assert plan.selection_bytes_peak < materialized_selection_bytes(
        nq, 1 << e
    )


def test_plan_env_gates(monkeypatch):
    kwargs = dict(num_keys=5, num_blocks=12, expand_levels=4, budget_bytes=256)
    monkeypatch.setenv("DPF_TPU_STREAMING", "0")
    plan = plan_dense_serving(**kwargs)
    assert plan.mode == "chunked"
    assert chunked_selection_bytes(5, plan.chunk_levels) == (
        plan.selection_bytes_peak
    )
    monkeypatch.setenv("DPF_TPU_STREAMING", "1")
    under = plan_dense_serving(
        num_keys=1, num_blocks=12, expand_levels=4, budget_bytes=1 << 20
    )
    assert under.mode == "streaming"  # forced even under budget


def test_plan_chunked_when_tree_cannot_cover():
    """A domain smaller than the database (blocks > 2^expand_levels) has
    no streaming staging; the legacy chunked loop serves it."""
    plan = plan_dense_serving(
        num_keys=64, num_blocks=40, expand_levels=3, budget_bytes=1024
    )
    assert plan.mode == "chunked"
    assert plan.chunk_levels <= CHUNK_GRANULE_LEVELS


def test_streaming_ip_resolution(monkeypatch):
    monkeypatch.delenv("DPF_TPU_STREAMING_IP", raising=False)
    assert streaming_ip("tpu") == "pallas2"
    assert streaming_ip("cpu") == "jnp"
    monkeypatch.setenv("DPF_TPU_STREAMING_IP", "jnp")
    assert streaming_ip("tpu") == "jnp"


# ---------------------------------------------------------------------------
# Server-level dispatch
# ---------------------------------------------------------------------------


def test_streaming_serving_matches_materialized_server(monkeypatch):
    """With a tiny selection budget the planner streams; responses must
    be byte-identical to the materialized pipeline, and the two parties'
    shares must still reconstruct the records."""
    num_records = 1500  # 12 blocks -> covering tree of 16
    records = [RNG.bytes(20) for _ in range(num_records)]
    plain = DenseDpfPirServer.create_plain(DenseDpfPirDatabase(records))
    streaming = DenseDpfPirServer.create_plain(DenseDpfPirDatabase(records))

    indices = [0, 77, 1499, 640, 1024]
    _, _, _, keys0, keys1 = _staged_batch(num_records, indices)
    req0 = messages.PirRequest(
        plain_request=messages.PlainRequest(dpf_keys=list(keys0))
    )
    req1 = messages.PirRequest(
        plain_request=messages.PlainRequest(dpf_keys=list(keys1))
    )
    want = plain.handle_plain_request(req0).dpf_pir_response.masked_response

    monkeypatch.setenv("DPF_TPU_SELECTION_BYTES_BUDGET", "256")
    plan = streaming._plan_serving(len(indices), False)
    assert plan.mode == "streaming"
    got = streaming.handle_plain_request(req0).dpf_pir_response.masked_response
    assert got == want

    r1 = streaming.handle_plain_request(req1).dpf_pir_response.masked_response
    for q, idx in enumerate(indices):
        assert xor_bytes(got[q], r1[q]) == records[idx]


def test_streaming_disabled_falls_back_to_chunked(monkeypatch):
    """DPF_TPU_STREAMING=0 + over budget keeps the legacy chunked loop,
    byte-identical as before."""
    num_records = 1500
    records = [RNG.bytes(20) for _ in range(num_records)]
    plain = DenseDpfPirServer.create_plain(DenseDpfPirDatabase(records))
    chunked = DenseDpfPirServer.create_plain(DenseDpfPirDatabase(records))
    indices = [3, 800, 1499]
    _, _, _, keys0, _ = _staged_batch(num_records, indices)
    req = messages.PirRequest(
        plain_request=messages.PlainRequest(dpf_keys=list(keys0))
    )
    want = plain.handle_plain_request(req).dpf_pir_response.masked_response
    monkeypatch.setenv("DPF_TPU_SELECTION_BYTES_BUDGET", "256")
    monkeypatch.setenv("DPF_TPU_STREAMING", "0")
    assert chunked._plan_serving(len(indices), False).mode == "chunked"
    got = chunked.handle_plain_request(req).dpf_pir_response.masked_response
    assert got == want


def test_streaming_ip_failure_demotes_to_jnp(monkeypatch):
    """A crash in the pallas2 scan tier demotes to the jnp tier for the
    process (one warning), still answering correctly."""
    num_records = 1000
    records = [RNG.bytes(8) for _ in range(num_records)]
    plain = DenseDpfPirServer.create_plain(DenseDpfPirDatabase(records))
    server = DenseDpfPirServer.create_plain(DenseDpfPirDatabase(records))
    indices = [5, 999]
    _, _, _, keys0, _ = _staged_batch(num_records, indices)
    req = messages.PirRequest(
        plain_request=messages.PlainRequest(dpf_keys=list(keys0))
    )
    want = plain.handle_plain_request(req).dpf_pir_response.masked_response

    monkeypatch.setenv("DPF_TPU_SELECTION_BYTES_BUDGET", "64")
    monkeypatch.setenv("DPF_TPU_STREAMING_IP", "pallas2")
    # pallas2's compiled path raises on CPU long before Mosaic; the
    # demotion contract is the same as a TPU compile crash.
    with pytest.warns(UserWarning, match="falling back"):
        got = server.handle_plain_request(req).dpf_pir_response.masked_response
    assert got == want
    assert server._streaming_ip_failed is True
    # Second batch goes straight to jnp: no second warning.
    got2 = server.handle_plain_request(req).dpf_pir_response.masked_response
    assert got2 == want


# ---------------------------------------------------------------------------
# Chunk-sharded mesh variant
# ---------------------------------------------------------------------------


def test_sharded_streaming_matches_oracle():
    from distributed_point_functions_tpu.parallel.sharded import (
        make_mesh,
        sharded_dense_pir_step_streaming,
        stage_streaming_chunks,
    )

    if len(jax.devices()) < 8:
        pytest.skip("needs 8 devices")
    mesh = make_mesh(8)
    num_records, nq = 1024, 9  # 8 blocks -> cut=3 gives one chunk/device
    records = [RNG.bytes(16) for _ in range(num_records)]
    db = DenseDpfPirDatabase(records)
    indices = [int(i) for i in RNG.integers(0, num_records, nq)]
    staged, walk, e, _, _ = _staged_batch(num_records, indices)
    want = _oracle(db, staged, walk, e)

    step = sharded_dense_pir_step_streaming(
        mesh, walk_levels=walk, cut_levels=3, chunk_levels=e - 3, ip="jnp"
    )
    chunks = stage_streaming_chunks(
        mesh, db.streaming_chunks(cut_levels=3, bitmajor=False)
    )
    got = np.asarray(step(*staged, chunks))
    np.testing.assert_array_equal(got, want)


# ---------------------------------------------------------------------------
# Hierarchical-geometry tail verdict (dpf.py's walk fallback)
# ---------------------------------------------------------------------------


def test_tail_hier_selfcheck_and_gate(monkeypatch):
    from distributed_point_functions_tpu.pir import dense_eval_planes as dep

    monkeypatch.setattr(
        dep, "expand_tail_planes_pallas",
        functools.partial(dep.expand_tail_planes_pallas, interpret=True),
    )
    for flag in ("_TAIL_HIER_VERIFIED", "_TAIL_HIER_FAILED"):
        monkeypatch.setattr(dep, flag, False)
    assert dep._tail_hier_selfcheck() is True
    assert dep._TAIL_HIER_VERIFIED is True
    assert dep._tail_hier_ok() is True
    status = dep.level_kernel_status()
    assert status["tail_hier_verified"] is True
    assert status["tail_hier_failed"] is False

    # Under an active trace only a prior eager verification counts.
    monkeypatch.setattr(dep, "_trace_state_clean", lambda: False)
    assert dep._tail_hier_ok() is True
    monkeypatch.setattr(dep, "_TAIL_HIER_VERIFIED", False)
    assert dep._tail_hier_ok() is False


def test_tail_hier_failure_is_isolated(monkeypatch):
    """A hier-geometry tail miscompile demotes ONLY that geometry: the
    dense-tile tail verdict keeps serving the concat tail."""
    from distributed_point_functions_tpu.pir import dense_eval_planes as dep

    for flag in ("_TAIL_HIER_VERIFIED", "_TAIL_HIER_FAILED"):
        monkeypatch.setattr(dep, flag, False)
    monkeypatch.setattr(dep, "_TAIL_KERNEL_VERIFIED", True)
    monkeypatch.setattr(dep, "_TAIL_KERNEL_FAILED", False)

    def boom(*a, **k):
        raise RuntimeError("mosaic hier tail says no")

    monkeypatch.setattr(dep, "expand_tail_planes_pallas", boom)
    with pytest.warns(UserWarning, match="hierarchical-geometry"):
        assert dep._tail_hier_ok() is False
    assert dep._TAIL_HIER_FAILED is True
    assert dep._TAIL_KERNEL_VERIFIED is True
    assert dep._TAIL_KERNEL_FAILED is False


def test_dpf_walk_fallback_gates_on_tail_hier(monkeypatch):
    """dpf's hierarchical-walk fallback must consult the hier-geometry
    tail verdict, not the dense-tile `_TAIL_KERNEL_VERIFIED` flag."""
    from distributed_point_functions_tpu import dpf as dpf_mod
    from distributed_point_functions_tpu.pir import dense_eval_planes as dep

    monkeypatch.setenv("DPF_TPU_EXPAND_LEVELS", "planes")
    monkeypatch.setenv("DPF_TPU_LEVEL_KERNEL", "walk")
    monkeypatch.setattr(dep, "_walk_hier_ok", lambda: False)

    captured = {}

    def fake_planes_fn(num_levels, **kwargs):
        captured.update(kwargs)
        return lambda *a: None

    monkeypatch.setattr(dpf_mod, "_expand_levels_planes_fn", fake_planes_fn)

    # Old behavior trusted the dense-tile verdict; the hier verdict must
    # now say no -> per-level tiers (no tail program).
    monkeypatch.setattr(dep, "_TAIL_KERNEL_VERIFIED", True)
    monkeypatch.setattr(dep, "_TAIL_KERNEL_FAILED", False)
    monkeypatch.setattr(dep, "_tail_hier_ok", lambda: False)
    dpf_mod._expand_levels_fn(4, hash_leaves=True)
    assert captured["tail_req"] == 0

    # And the hier verdict alone is sufficient.
    captured.clear()
    monkeypatch.setattr(dep, "_TAIL_KERNEL_VERIFIED", False)
    monkeypatch.setattr(dep, "_tail_hier_ok", lambda: True)
    dpf_mod._expand_levels_fn(4, hash_leaves=True)
    assert captured["tail_req"] > 0


# ---------------------------------------------------------------------------
# Database staging
# ---------------------------------------------------------------------------


def test_bitrev_host_copy_dropped_after_device_staging():
    records = [RNG.bytes(8) for _ in range(300)]
    db = DenseDpfPirDatabase(records)
    host = db._host_words_bitrev()
    assert db._host_rev is not None
    dev = db._row_words(bitrev_blocks=True)
    assert db._host_rev is None  # dropped once the device copy exists
    np.testing.assert_array_equal(np.asarray(dev), host)
    # A later staging that needs the host copy rebuilds it.
    np.testing.assert_array_equal(db._host_words_bitrev(), host)


def test_streaming_chunks_cached_per_plan_key():
    records = [RNG.bytes(8) for _ in range(256)]
    db = DenseDpfPirDatabase(records)
    a = db.streaming_chunks(cut_levels=1, bitmajor=False)
    assert db.streaming_chunks(cut_levels=1, bitmajor=False) is a
    b = db.streaming_chunks(cut_levels=0, bitmajor=False)
    assert b is not a
    assert b.shape[0] == 1 and a.shape[0] == 2


def test_concurrent_staging_builds_once(monkeypatch):
    """Concurrent first requests must not stage the database twice (each
    staging is a full HBM copy)."""
    from distributed_point_functions_tpu.pir import dense_eval_planes_v2 as v2

    records = [RNG.bytes(8) for _ in range(512)]
    db = DenseDpfPirDatabase(records)
    calls = []
    orig = v2.streaming_block_permute_records

    def counting(*a, **k):
        calls.append(1)
        return orig(*a, **k)

    monkeypatch.setattr(v2, "streaming_block_permute_records", counting)
    out, errors = [], []

    def worker():
        try:
            out.append(db.streaming_chunks(cut_levels=2, bitmajor=False))
        except Exception as e:  # surfaced below
            errors.append(e)

    threads = [threading.Thread(target=worker) for _ in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errors
    assert len(calls) == 1
    assert all(o is out[0] for o in out)
