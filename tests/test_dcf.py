"""DCF and MIC gate tests.

Mirrors the reference's strategy: evaluate both parties' shares at every
point of small domains and check the comparison property
(`dcf/distributed_comparison_function_test.cc`), and brute-force all masked
inputs of a small group for the MIC gate
(`dcf/fss_gates/multiple_interval_containment_test.cc:43-119`).
"""


import numpy as np
import pytest

from distributed_point_functions_tpu.dcf import (
    DcfKey,
    DistributedComparisonFunction,
)
from distributed_point_functions_tpu.fss_gates import (
    Interval,
    MicParameters,
    MultipleIntervalContainmentGate,
)
from distributed_point_functions_tpu.value_types import (
    IntType,
    IntModNType,
    TupleType,
)


def eval_both(dcf, k0, k1, xs):
    s0 = dcf.batch_evaluate([k0] * len(xs), xs)
    s1 = dcf.batch_evaluate([k1] * len(xs), xs)
    return np.asarray(s0), np.asarray(s1)


@pytest.mark.parametrize("log_domain_size", [1, 2, 3, 5])
@pytest.mark.parametrize("bits", [32, 128])
def test_dcf_property_all_points(log_domain_size, bits):
    vt = IntType(bits)
    dcf = DistributedComparisonFunction.create(log_domain_size, vt)
    domain = 1 << log_domain_size
    beta = 123 % (1 << bits)
    for alpha in range(domain):
        k0, k1 = dcf.generate_keys(alpha, beta)
        xs = list(range(domain))
        s0, s1 = eval_both(dcf, k0, k1, xs)
        for x in xs:
            got = vt.add(
                vt.to_python(s0, (x,)), vt.to_python(s1, (x,))
            )
            want = beta if x < alpha else 0
            assert got == want, (
                f"alpha={alpha} x={x}: {got} != {want}"
            )


def test_dcf_large_domain_random_points():
    vt = IntType(64)
    lds = 32
    dcf = DistributedComparisonFunction.create(lds, vt)
    alpha = 0x12345678
    beta = 999
    k0, k1 = dcf.generate_keys(alpha, beta)
    xs = [0, 1, alpha - 1, alpha, alpha + 1, (1 << lds) - 1, 0x12340000]
    s0, s1 = eval_both(dcf, k0, k1, xs)
    for i, x in enumerate(xs):
        got = vt.add(vt.to_python(s0, (i,)), vt.to_python(s1, (i,)))
        want = beta if x < alpha else 0
        assert got == want


def test_dcf_int_mod_n():
    vt = IntModNType(base_bits=32, modulus=1000003)
    lds = 4
    dcf = DistributedComparisonFunction.create(lds, vt)
    alpha, beta = 9, 777
    k0, k1 = dcf.generate_keys(alpha, beta)
    xs = list(range(1 << lds))
    s0, s1 = eval_both(dcf, k0, k1, xs)
    for x in xs:
        got = vt.add(vt.to_python(s0, (x,)), vt.to_python(s1, (x,)))
        assert got == (beta if x < alpha else 0)


def test_dcf_tuple_type():
    vt = TupleType([IntType(32), IntType(64)])
    lds = 3
    dcf = DistributedComparisonFunction.create(lds, vt)
    alpha, beta = 5, (42, 77)
    k0, k1 = dcf.generate_keys(alpha, beta)
    xs = list(range(1 << lds))
    s0 = dcf.batch_evaluate([k0] * len(xs), xs)
    s1 = dcf.batch_evaluate([k1] * len(xs), xs)
    for x in xs:
        got = vt.add(vt.to_python(s0, (x,)), vt.to_python(s1, (x,)))
        assert got == (beta if x < alpha else (0, 0))


def test_dcf_rejects_invalid():
    with pytest.raises(ValueError):
        DistributedComparisonFunction.create(0, IntType(32))
    dcf = DistributedComparisonFunction.create(3, IntType(32))
    with pytest.raises(ValueError):
        dcf.generate_keys(8, 1)  # alpha out of range
    k0, k1 = dcf.generate_keys(3, 1)
    with pytest.raises(ValueError):
        dcf.batch_evaluate([k0], [0, 1])  # size mismatch


# ---------------------------------------------------------------------------
# MIC gate
# ---------------------------------------------------------------------------


def mic_reference(x, intervals, n):
    return [
        1 if iv.lower_bound <= x <= iv.upper_bound else 0 for iv in intervals
    ]


def test_mic_gate_brute_force_small_group():
    log_group_size = 4
    n = 1 << log_group_size
    intervals = [Interval(2, 5), Interval(0, 0), Interval(7, 15)]
    gate = MultipleIntervalContainmentGate.create(
        MicParameters(log_group_size, intervals)
    )
    r_in = 11
    r_out = [3, 9, 14]
    k0, k1 = gate.gen(r_in, r_out)
    for x in range(n):
        masked_x = (x + r_in) % n
        y0 = gate.eval(k0, masked_x)
        y1 = gate.eval(k1, masked_x)
        want = mic_reference(x, intervals, n)
        for j in range(len(intervals)):
            # The combined output is masked by r_out (added once via z).
            got = (y0[j] + y1[j] - r_out[j]) % n
            assert got == want[j], f"x={x} interval={j}: {got} != {want[j]}"


def test_mic_batch_eval_matches_single():
    log_group_size = 5
    n = 1 << log_group_size
    intervals = [Interval(3, 17), Interval(20, 30)]
    gate = MultipleIntervalContainmentGate.create(
        MicParameters(log_group_size, intervals)
    )
    r_in = 7
    r_out = [1, 2]
    k0, k1 = gate.gen(r_in, r_out)
    xs = [0, 5, 18, 31]
    batch0 = gate.batch_eval([k0] * len(xs), xs)
    for i, x in enumerate(xs):
        assert batch0[i] == gate.eval(k0, x)


def test_mic_rejects_invalid():
    gate = MultipleIntervalContainmentGate.create(
        MicParameters(4, [Interval(0, 3)])
    )
    with pytest.raises(ValueError):
        gate.gen(16, [0])  # r_in out of group
    with pytest.raises(ValueError):
        gate.gen(0, [0, 1])  # mask count mismatch
    with pytest.raises(ValueError):
        MicParameters_bad = MicParameters(4, [Interval(5, 3)])
        MultipleIntervalContainmentGate.create(MicParameters_bad)
    with pytest.raises(ValueError):
        gate.batch_eval([gate.gen(0, [0])[0]], [99])


def test_dcf_staged_batch_reuse_matches_fresh():
    """A staged key batch must be reusable across batch_evaluate calls
    with different points, matching per-call staging bit-for-bit."""
    dcf = DistributedComparisonFunction.create(8, IntType(32))
    k0, k1 = dcf.generate_keys(100, 7)
    keys = [DcfKey(k0.key), DcfKey(k1.key), DcfKey(k0.key)]
    staged = dcf.stage_keys(keys)
    for points in ([5, 99, 200], [0, 255, 100]):
        fresh = np.asarray(dcf.batch_evaluate(keys, points))
        reused = np.asarray(dcf.batch_evaluate(None, points, staged=staged))
        np.testing.assert_array_equal(fresh, reused)
    with pytest.raises(ValueError, match="either keys or staged"):
        dcf.batch_evaluate(None, [1])


def test_evaluate_and_accumulate_contracts():
    """The fused engine validates its inputs and refuses mixed types."""
    import numpy as np
    import pytest

    from distributed_point_functions_tpu.dpf import (
        DistributedPointFunction,
        DpfParameters,
    )
    from distributed_point_functions_tpu.value_types import IntType

    params = [DpfParameters(i, IntType(32)) for i in range(1, 4)]
    d = DistributedPointFunction.create_incremental(params)
    k0, _ = d.generate_keys_incremental(3, [1, 1, 1])
    staged = d.stage_key_batch([k0, k0])

    with pytest.raises(ValueError, match="size mismatch"):
        d.evaluate_and_accumulate(staged, [1], np.zeros((3, 1), bool))
    with pytest.raises(ValueError, match="level_masks"):
        d.evaluate_and_accumulate(staged, [1, 2], np.zeros((2, 2), bool))

    mixed = [
        DpfParameters(1, IntType(32)),
        DpfParameters(2, IntType(64)),
    ]
    dm = DistributedPointFunction.create_incremental(mixed)
    km, _ = dm.generate_keys_incremental(1, [1, 1])
    staged_m = dm.stage_key_batch([km])
    with pytest.raises(ValueError, match="single value type"):
        dm.evaluate_and_accumulate(
            staged_m, [1], np.zeros((2, 1), bool)
        )
