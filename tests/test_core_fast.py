"""Fast-tier slice of the library's core invariants (VERDICT r04 item 8).

The full share-correctness sweeps (`test_dpf.py`), PIR end-to-end
(`test_pir.py`), and DCF suites (`test_dcf.py`) live outside
`make test-fast`, so the green signal tier never checked the library's
defining property. This file is the budgeted (<~2 min) slice of each:
one share-correctness pass across the value-type zoo at small domains,
one dense-PIR plain protocol round trip, and one DCF all-points check —
enough that `make test-fast` fails if share reconstruction breaks
anywhere in keygen/expansion/correction.
"""

import numpy as np
import pytest

import jax

from distributed_point_functions_tpu import dpf as dpf_mod
from distributed_point_functions_tpu.dcf import (
    DistributedComparisonFunction,
)
from distributed_point_functions_tpu.value_types import (
    IntModNType,
    IntType,
    TupleType,
    XorType,
)

DPF = dpf_mod.DistributedPointFunction
Params = dpf_mod.DpfParameters


@pytest.mark.parametrize(
    "vt,beta",
    [
        (IntType(32), 123456),
        (IntType(128), (1 << 100) + 7),
        (XorType(128), (1 << 99) + 5),
        (IntModNType(32, 4294967291), 12345),
        (TupleType((IntType(32), IntType(64))), (7, 1 << 40)),
    ],
    ids=["u32", "u128", "xor128", "intmodn", "tuple"],
)
def test_share_correctness_small_domain(vt, beta):
    """Sum of both parties' full-domain shares == beta at alpha, 0
    elsewhere (the reference's IncrementalDpfTest core property,
    `dpf/distributed_point_function_test.cc:320-485`)."""
    ld = 5
    d = DPF.create_incremental([Params(log_domain_size=ld, value_type=vt)])
    alpha = 19
    k0, k1 = d.generate_keys_incremental(alpha, [beta])
    v0 = d.evaluate_until(0, [], d.create_evaluation_context(k0))
    v1 = d.evaluate_until(0, [], d.create_evaluation_context(k1))
    v0 = jax.tree_util.tree_map(np.asarray, v0)
    v1 = jax.tree_util.tree_map(np.asarray, v1)
    zero = vt.add(vt.neg(beta), beta)
    for x in range(1 << ld):
        got = vt.add(vt.to_python(v0, (x,)), vt.to_python(v1, (x,)))
        want = beta if x == alpha else zero
        assert got == want, f"x={x}: {got} != {want}"


def test_share_correctness_hierarchical():
    """Two-hierarchy incremental evaluation reconstructs both levels'
    betas (the incremental core of the reference's sweeps)."""
    d = DPF.create_incremental(
        [
            Params(log_domain_size=3, value_type=IntType(32)),
            Params(log_domain_size=7, value_type=IntType(32)),
        ]
    )
    alpha, betas = 100, [21, 42]
    k0, k1 = d.generate_keys_incremental(alpha, betas)
    ctx0, ctx1 = d.create_evaluation_context(k0), d.create_evaluation_context(k1)
    v0 = np.asarray(d.evaluate_until(0, [], ctx0)).astype(np.uint64)
    v1 = np.asarray(d.evaluate_until(0, [], ctx1)).astype(np.uint64)
    s = (v0 + v1) % (1 << 32)
    assert s[alpha >> 4] == betas[0] and s.sum() == betas[0]
    prefixes = [alpha >> 4]
    v0 = np.asarray(d.evaluate_until(1, prefixes, ctx0)).astype(np.uint64)
    v1 = np.asarray(d.evaluate_until(1, prefixes, ctx1)).astype(np.uint64)
    s = (v0 + v1) % (1 << 32)
    assert s[alpha & 15] == betas[1] and s.sum() == betas[1]


def test_dense_pir_plain_end_to_end():
    """Client request -> two plain servers -> XOR of masked responses
    reconstructs the records (`pir/dense_dpf_pir_server_test.cc:288`)."""
    from distributed_point_functions_tpu.pir.client import DenseDpfPirClient
    from distributed_point_functions_tpu.pir.database import (
        DenseDpfPirDatabase,
    )
    from distributed_point_functions_tpu.pir.server import DenseDpfPirServer
    from distributed_point_functions_tpu.testing import encrypt_decrypt

    records = [bytes([i, i ^ 255]) * 12 for i in range(100)]
    server = DenseDpfPirServer.create_plain(DenseDpfPirDatabase(records))
    client = DenseDpfPirClient.create(len(records), encrypt_decrypt.encrypt)
    indices = [0, 42, 99]
    req0, req1 = client.create_plain_requests(indices)
    resp0, resp1 = server.handle_request(req0), server.handle_request(req1)
    for i, idx in enumerate(indices):
        combined = bytes(
            a ^ b
            for a, b in zip(
                resp0.dpf_pir_response.masked_response[i],
                resp1.dpf_pir_response.masked_response[i],
            )
        )
        assert combined[: len(records[idx])] == records[idx]


def test_dcf_all_points_slice():
    """Shares of beta iff x < alpha, every point of a small domain
    (`dcf/distributed_comparison_function_test.cc`)."""
    vt = IntType(32)
    dcf = DistributedComparisonFunction.create(3, vt)
    beta = 123
    for alpha in (0, 3, 7):
        k0, k1 = dcf.generate_keys(alpha, beta)
        xs = list(range(8))
        s0 = np.asarray(dcf.batch_evaluate([k0] * len(xs), xs))
        s1 = np.asarray(dcf.batch_evaluate([k1] * len(xs), xs))
        for x in xs:
            got = vt.add(vt.to_python(s0, (x,)), vt.to_python(s1, (x,)))
            assert got == (beta if x < alpha else 0)
