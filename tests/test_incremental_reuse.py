"""Cut-state reuse bit-identity for batched incremental evaluation.

The tentpole invariant of the heavy-hitters aggregator: evaluating
hierarchy level ℓ from the `BatchCutState` cached at level ℓ−1 must be
*bit-identical* to a fresh root-to-ℓ evaluation — resuming only skips
re-walking tree levels whose output is already determined, it never
changes a single seed, control bit, or value share. Checked across two
hierarchy geometries (even 4-bit steps and uneven non-byte-aligned
steps) including a non-power-of-two prefix frontier, against both the
from-root batch and the per-key `evaluate_at` oracle.
"""

import numpy as np
import pytest

from distributed_point_functions_tpu.dpf import (
    DistributedPointFunction,
    DpfParameters,
)
from distributed_point_functions_tpu.value_types import IntType

# Two geometries: even steps, and uneven steps with a non-byte-aligned
# total — distinct tree shortenings exercise distinct start/stop walks.
GEOMETRIES = {
    "even-4bit-steps": [4, 8, 12],
    "uneven-steps": [3, 7, 13],
}


def _make(widths, alphas):
    params = [DpfParameters(w, IntType(32)) for w in widths]
    dpf = DistributedPointFunction.create_incremental(params)
    betas = [1] * len(widths)
    pairs = [dpf.generate_keys_incremental(a, betas) for a in alphas]
    staged0 = dpf.stage_key_batch([p[0] for p in pairs])
    staged1 = dpf.stage_key_batch([p[1] for p in pairs])
    return dpf, pairs, staged0, staged1


def _values_array(values) -> np.ndarray:
    import jax

    return np.asarray(jax.tree_util.tree_leaves(values)[0][..., 0])


@pytest.mark.parametrize("geometry", sorted(GEOMETRIES))
def test_resume_bit_identical_to_root(geometry):
    widths = GEOMETRIES[geometry]
    alphas = [0, 3, (1 << widths[-1]) - 1, 1 << (widths[-1] - 1)]
    dpf, pairs, staged0, staged1 = _make(widths, alphas)

    # Non-power-of-two frontier at level 0 (5 of the 8+ prefixes),
    # including every alpha's true prefix and some misses.
    shift0 = widths[-1] - widths[0]
    level0 = sorted({a >> shift0 for a in alphas} | {1, 2})[:5]
    assert len(level0) not in (1, 2, 4, 8)

    for staged in (staged0, staged1):
        _, cuts0 = dpf.evaluate_prefixes_batch(staged, 0, level0)

        # Level-1 frontier: all children of the level-0 prefixes (also
        # non-power-of-two), evaluated two ways.
        step = widths[1] - widths[0]
        level1 = sorted(
            (p << step) | c for p in level0 for c in range(1 << step)
        )
        v_resume, cuts_resume = dpf.evaluate_prefixes_batch(
            staged, 1, level1, cuts=cuts0
        )
        v_root, cuts_root = dpf.evaluate_prefixes_batch(staged, 1, level1)

        np.testing.assert_array_equal(
            _values_array(v_resume), _values_array(v_root)
        )
        np.testing.assert_array_equal(
            np.asarray(cuts_resume.seeds), np.asarray(cuts_root.seeds)
        )
        np.testing.assert_array_equal(
            np.asarray(cuts_resume.control), np.asarray(cuts_root.control)
        )

        # And a second descent: level 2 resumed from the level-1 cuts
        # (themselves produced by a resume) still matches from-root.
        step2 = widths[2] - widths[1]
        level2 = sorted(
            (p << step2) | c for p in level1[:3] for c in range(1 << step2)
        )
        v2_resume, _ = dpf.evaluate_prefixes_batch(
            staged, 2, level2, cuts=cuts_resume
        )
        v2_root, _ = dpf.evaluate_prefixes_batch(staged, 2, level2)
        np.testing.assert_array_equal(
            _values_array(v2_resume), _values_array(v2_root)
        )


def test_resume_matches_evaluate_at_oracle():
    widths = GEOMETRIES["even-4bit-steps"]
    alphas = [5, 100, 2048]
    dpf, pairs, staged0, _ = _make(widths, alphas)

    shift0 = widths[-1] - widths[0]
    level0 = sorted({a >> shift0 for a in alphas} | {0})
    _, cuts0 = dpf.evaluate_prefixes_batch(staged0, 0, level0)
    step = widths[1] - widths[0]
    level1 = sorted(
        (p << step) | c for p in level0 for c in range(1 << step)
    )
    v_resume, _ = dpf.evaluate_prefixes_batch(
        staged0, 1, level1, cuts=cuts0
    )
    got = _values_array(v_resume)

    for i, (k0, _) in enumerate(pairs):
        want = _values_array(dpf.evaluate_at(k0, 1, level1))
        np.testing.assert_array_equal(got[i], want)


def test_shares_reconstruct_to_point_function():
    """Both parties' batched shares sum to the indicator histogram."""
    widths = GEOMETRIES["uneven-steps"]
    alphas = [9, 9, 4000]
    dpf, pairs, staged0, staged1 = _make(widths, alphas)

    shift0 = widths[-1] - widths[0]
    level0 = sorted({a >> shift0 for a in alphas} | {3, 5})
    v0, c0 = dpf.evaluate_prefixes_batch(staged0, 0, level0)
    v1, c1 = dpf.evaluate_prefixes_batch(staged1, 0, level0)
    total = (
        _values_array(v0).astype(np.uint64).sum(axis=0)
        + _values_array(v1).astype(np.uint64).sum(axis=0)
    ) & np.uint64(0xFFFFFFFF)
    from collections import Counter

    truth = Counter(a >> shift0 for a in alphas)
    np.testing.assert_array_equal(
        total, [truth.get(p, 0) for p in level0]
    )


def test_stale_and_missing_cuts_are_rejected():
    widths = GEOMETRIES["even-4bit-steps"]
    dpf, pairs, staged0, _ = _make(widths, [7])
    _, cuts0 = dpf.evaluate_prefixes_batch(staged0, 0, [0, 1])
    # A level-1 prefix whose parent was never evaluated at level 0.
    step = widths[1] - widths[0]
    orphan = 3 << step
    with pytest.raises(ValueError):
        dpf.evaluate_prefixes_batch(staged0, 1, [orphan], cuts=cuts0)
