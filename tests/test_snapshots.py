"""Snapshot rotation tests: versioned builds, the v3 wire generation
field, the SnapshotManager lifecycle (stage -> flip -> drain-then-free),
the two-party generation handshake, chaos rotation faults, and the
flip-atomicity race against concurrent batcher submissions.

The invariant under test everywhere: a response is either computed
entirely against one database generation, or it is a typed refusal
(`SnapshotMismatch`) — never a cross-generation XOR, which in the CGKS
two-server model is well-formed garbage no latency metric would flag.
"""

import json
import threading
import urllib.request

import numpy as np
import pytest

from distributed_point_functions_tpu.observability import (
    AdminServer,
    propagation,
    tracing,
)
from distributed_point_functions_tpu.observability.bundle import (
    BundleManager,
)
from distributed_point_functions_tpu.observability.device import (
    default_telemetry,
)
from distributed_point_functions_tpu.observability.events import EventJournal
from distributed_point_functions_tpu.pir import (
    DenseDpfPirClient,
    DenseDpfPirDatabase,
)
from distributed_point_functions_tpu.pir.cuckoo_database import (
    CuckooHashedDpfPirDatabase,
)
from distributed_point_functions_tpu.pir.sparse_server import (
    CuckooHashingSparseDpfPirServer,
)
from distributed_point_functions_tpu.prng import xor_bytes
from distributed_point_functions_tpu.robustness import failpoints
from distributed_point_functions_tpu.serving import (
    HelperSession,
    InProcessTransport,
    LeaderSession,
    PlainSession,
    RotationCoordinator,
    ServingConfig,
    SnapshotManager,
    SnapshotMismatch,
)
from distributed_point_functions_tpu.serving.prober import Prober
from distributed_point_functions_tpu.pir import messages
from distributed_point_functions_tpu.testing import encrypt_decrypt

NUM_RECORDS = 128
RECORD_BYTES = 16
RNG = np.random.default_rng(777)

RECORDS0 = [
    bytes(RNG.integers(0, 256, RECORD_BYTES, dtype=np.uint8))
    for _ in range(NUM_RECORDS)
]
# Generation 1 differs from generation 0 at EVERY index, so a
# cross-generation XOR can never accidentally equal either oracle.
RECORDS1 = [bytes(b ^ 0xA5 for b in r) for r in RECORDS0]


def build_db(records):
    builder = DenseDpfPirDatabase.Builder()
    for r in records:
        builder.insert(r)
    return builder.build()


def make_config(**overrides):
    base = dict(
        max_batch_size=8,
        max_wait_ms=2.0,
        helper_timeout_ms=None,
        helper_retries=2,
        helper_backoff_ms=1.0,
        helper_backoff_max_ms=2.0,
    )
    base.update(overrides)
    return ServingConfig(**base)


@pytest.fixture(autouse=True)
def clean_failpoints():
    reg = failpoints.default_failpoints()
    reg.clear()
    yield reg
    reg.clear()


def two_party(leader_config=None, helper_config=None):
    """Leader+Helper sessions over distinct (identical-record) database
    objects, each with its own SnapshotManager, plus a coordinator."""
    helper = HelperSession(
        build_db(RECORDS0),
        encrypt_decrypt.decrypt,
        helper_config if helper_config is not None else make_config(),
    )
    leader = LeaderSession(
        build_db(RECORDS0),
        InProcessTransport(helper.handle_wire),
        leader_config if leader_config is not None else make_config(),
    )
    leader_mgr = SnapshotManager(leader, journal=EventJournal())
    helper_mgr = SnapshotManager(helper, journal=EventJournal())
    coordinator = RotationCoordinator(leader_mgr, helper_mgr)
    return leader, helper, leader_mgr, helper_mgr, coordinator


def run_query(leader, indices):
    client = DenseDpfPirClient.create(NUM_RECORDS, encrypt_decrypt.encrypt)
    request, state = client.create_request(indices)
    response = leader.handle_request(request)
    return client.handle_response(response, state)


# ---------------------------------------------------------------------------
# Builder delta path and generation tags
# ---------------------------------------------------------------------------


def test_build_from_delta_bumps_generation_and_applies_updates():
    db0 = build_db(RECORDS0)
    assert db0.generation == 0
    new3 = bytes(16)
    db1 = DenseDpfPirDatabase.Builder().update(3, new3).build_from(db0)
    assert db1.generation == 1
    assert db1.size == db0.size
    assert db1.max_value_size == db0.max_value_size
    # The delta applied; untouched records shared; prev untouched.
    assert db1.record(3) == new3
    assert db1.record(5) == RECORDS0[5]
    assert db0.record(3) == RECORDS0[3]
    # A second delta chains the tag.
    db2 = DenseDpfPirDatabase.Builder().update(0, new3).build_from(db1)
    assert db2.generation == 2 and db2.record(3) == new3


def test_build_from_rejects_out_of_bounds_update():
    db0 = build_db(RECORDS0)
    with pytest.raises(IndexError, match="out of bounds"):
        DenseDpfPirDatabase.Builder().update(
            NUM_RECORDS, b"x"
        ).build_from(db0)


def test_build_from_shares_no_device_stagings():
    db0 = build_db(RECORDS0)
    _ = db0.db_words  # stage generation 0
    db1 = DenseDpfPirDatabase.Builder().update(1, b"y" * 16).build_from(db0)
    # A delta build copies host bytes but never inherits HBM stagings.
    assert db1._db_words is None and db1._db_perm is None


def test_cuckoo_builder_carries_generation_tag():
    pairs = [(f"key{i}".encode(), f"value{i}".encode()) for i in range(16)]
    params = CuckooHashingSparseDpfPirServer.generate_params(
        len(pairs), seed=b"0123456789abcdef"
    )
    builder = CuckooHashedDpfPirDatabase.Builder().set_params(params)
    for kv in pairs:
        builder.insert(kv)
    db = builder.set_generation(7).build()
    assert db.generation == 7
    # ...and both backing dense stores wear the same tag (a clone
    # keeps it for the two-party twin build).
    assert db.key_database.generation == 7
    assert db.value_database.generation == 7
    assert builder.clone().build().generation == 7


# ---------------------------------------------------------------------------
# Wire v3: the generation field
# ---------------------------------------------------------------------------


def test_wire_v3_request_carries_generation():
    tid = tracing.new_trace_id()
    wrapped = propagation.encode_request(tid, b"inner", generation=4)
    got_tid, inner, version, generation = (
        propagation.try_decode_request_ext(wrapped)
    )
    assert (got_tid, inner, version, generation) == (tid, b"inner", 3, 4)
    # generation 0 and unbound are distinct on the wire (u64 gen+1).
    _, _, _, g0 = propagation.try_decode_request_ext(
        propagation.encode_request(tid, b"i", generation=0)
    )
    assert g0 == 0
    _, _, _, unbound = propagation.try_decode_request_ext(
        propagation.encode_request(tid, b"i", generation=None)
    )
    assert unbound is None


def test_wire_pre_v3_and_bare_have_no_generation():
    tid = tracing.new_trace_id()
    v2 = propagation.encode_request(tid, b"inner", version=2)
    got_tid, inner, version, generation = (
        propagation.try_decode_request_ext(v2)
    )
    assert (got_tid, inner, version, generation) == (tid, b"inner", 2, None)
    assert propagation.try_decode_request_ext(b"\x0abare") == (
        None, b"\x0abare", 0, None,
    )


def test_wire_v3_response_echoes_generation_v2_does_not():
    tid = tracing.new_trace_id()
    spans = [{"name": "s", "duration_ms": 1.0}]
    meta, inner = propagation.try_decode_response(
        propagation.encode_response(
            b"r", tid, server_ms=1.0, spans=spans, generation=7
        )
    )
    assert inner == b"r" and meta["generation"] == 7
    meta2, _ = propagation.try_decode_response(
        propagation.encode_response(
            b"r", tid, server_ms=1.0, spans=spans, version=2, generation=7
        )
    )
    assert "generation" not in meta2


# ---------------------------------------------------------------------------
# SnapshotManager lifecycle (single party)
# ---------------------------------------------------------------------------


def test_stage_flip_and_immediate_free():
    journal = EventJournal()
    with PlainSession(build_db(RECORDS0), make_config()) as session:
        manager = SnapshotManager(session, journal=journal)
        assert session.snapshots is manager
        assert run_query_plain(session, [3]) == [RECORDS0[3]]
        db1 = delta_db(session.server.database, RECORDS1)
        ledger = default_telemetry().transfers
        h2d_before = ledger.bytes_h2d("db_staging")
        staged = manager.stage(db1)
        # Double-buffered: N+1 moved into HBM while N serves.
        assert staged == int(db1._host_words.nbytes)
        assert ledger.bytes_h2d("db_staging") - h2d_before >= staged
        assert manager.staging_generation() == 1
        record = manager.flip()
        assert record["to_generation"] == 1
        assert record["old_freed"] == "immediate"
        assert manager.serving_generation() == 1
        assert manager.staging_generation() is None
        # The flipped-in generation answers; the old stagings are gone.
        assert run_query_plain(session, [3]) == [RECORDS1[3]]
        export = manager.export()
        assert export["flips"] == 1
        assert export["retired_awaiting_drain"] == []
        kinds = [e["kind"] for e in journal.export()["events"]]
        assert "snapshot.flip" in kinds and "snapshot.drained" in kinds


def run_query_plain(session, indices):
    client = DenseDpfPirClient(NUM_RECORDS, lambda pt, info: pt)
    req0, req1 = client.create_plain_requests(indices)
    resp0 = session.handle_request(req0)
    resp1 = session.handle_request(req1)
    return [
        xor_bytes(a, b)
        for a, b in zip(
            resp0.dpf_pir_response.masked_response,
            resp1.dpf_pir_response.masked_response,
        )
    ]


def delta_db(prev, records):
    builder = DenseDpfPirDatabase.Builder()
    for i, r in enumerate(records):
        builder.update(i, r)
    return builder.build_from(prev)


def test_stage_rejects_geometry_mismatch():
    with PlainSession(build_db(RECORDS0), make_config()) as session:
        manager = SnapshotManager(session, journal=EventJournal())
        with pytest.raises(ValueError, match="size"):
            manager.stage(build_db(RECORDS0[: NUM_RECORDS // 2]))


def test_flip_without_staging_raises():
    with PlainSession(build_db(RECORDS0), make_config()) as session:
        manager = SnapshotManager(session, journal=EventJournal())
        with pytest.raises(RuntimeError, match="no staged generation"):
            manager.flip()


def test_pin_holds_flip_off_then_flip_lands():
    with PlainSession(build_db(RECORDS0), make_config()) as session:
        manager = SnapshotManager(session, journal=EventJournal())
        manager.stage(delta_db(session.server.database, RECORDS1))
        with manager.pin() as gen:
            assert gen == 0
            with pytest.raises(TimeoutError):
                manager.flip(timeout=0.05)
            # The staged candidate survives a timed-out flip.
            assert manager.staging_generation() == 1
            assert manager.serving_generation() == 0
        manager.flip()
        assert manager.serving_generation() == 1


def test_deferred_free_waits_for_inflight_drain():
    journal = EventJournal()
    with PlainSession(build_db(RECORDS0), make_config()) as session:
        manager = SnapshotManager(session, journal=journal)
        old_db = session.server.database
        _ = old_db.db_words  # generation 0 staged and serving
        manager.stage(delta_db(old_db, RECORDS1))
        # A batch is in flight against generation 0...
        gen = manager.begin_batch()
        assert gen == 0
        flipped = []
        t = threading.Thread(
            target=lambda: flipped.append(manager.flip(timeout=5.0))
        )
        t.start()
        # ...the flip still applies at the next batch boundary (the
        # old generation is parked, NOT freed — its batch is live)...
        deadline = 50
        while manager.serving_generation() != 1 and deadline:
            manager_gen = manager.begin_batch()
            manager.end_batch(manager_gen)
            deadline -= 1
        assert manager.serving_generation() == 1
        assert 0 in manager.export()["retired_awaiting_drain"]
        assert old_db._db_words is not None  # still pinned by the batch
        # ...and only the last in-flight batch retiring frees it.
        manager.end_batch(0)
        t.join(timeout=5.0)
        assert flipped and flipped[0]["old_freed"] == "deferred"
        assert manager.export()["retired_awaiting_drain"] == []
        assert old_db._db_words is None
        assert manager.export()["flips"] == 1
        kinds = [e["kind"] for e in journal.export()["events"]]
        assert "snapshot.drained" in kinds


def test_abort_drops_staging_and_keeps_serving():
    journal = EventJournal()
    with PlainSession(build_db(RECORDS0), make_config()) as session:
        manager = SnapshotManager(session, journal=journal)
        db1 = delta_db(session.server.database, RECORDS1)
        manager.stage(db1)
        manager.abort("operator change of heart")
        assert manager.staging_generation() is None
        assert manager.serving_generation() == 0
        assert db1._db_words is None  # staged HBM dropped
        assert manager.export()["aborts"] == 1
        kinds = [e["kind"] for e in journal.export()["events"]]
        assert "snapshot.abort" in kinds
        assert run_query_plain(session, [9]) == [RECORDS0[9]]


# ---------------------------------------------------------------------------
# Two-party handshake
# ---------------------------------------------------------------------------


def test_rotation_handshake_end_to_end():
    leader, helper, leader_mgr, helper_mgr, coordinator = two_party()
    with helper, leader:
        assert run_query(leader, [3, 99]) == [RECORDS0[3], RECORDS0[99]]
        report = coordinator.rotate(
            delta_db(leader.server.database, RECORDS1),
            delta_db(helper.server.database, RECORDS1),
        )
        assert report["to_generation"] == 1
        assert report["staleness_ms"] >= 0.0
        assert report["leader_staged_bytes"] > 0
        assert report["helper_staged_bytes"] > 0
        assert leader_mgr.serving_generation() == 1
        assert helper_mgr.serving_generation() == 1
        # Post-rotation answers are the NEW generation's bits.
        assert run_query(leader, [3, 99]) == [RECORDS1[3], RECORDS1[99]]
        # The measured flip window landed on the leader's flip record.
        assert leader_mgr.export()["history"][-1]["staleness_ms"] is not None


def test_cross_generation_answer_is_typed_refusal_never_wrong_xor(tmp_path):
    bundles = BundleManager(directory=str(tmp_path), cooldown_s=0.0)
    helper = HelperSession(
        build_db(RECORDS0), encrypt_decrypt.decrypt, make_config()
    )
    leader = LeaderSession(
        build_db(RECORDS0),
        InProcessTransport(helper.handle_wire),
        make_config(snapshot_retries=1),
    )
    leader_mgr = SnapshotManager(
        leader, journal=EventJournal(), bundles=bundles
    )
    helper_mgr = SnapshotManager(helper, journal=EventJournal())
    with helper, leader:
        # Split-brain: ONLY the helper rotates. The leader must refuse
        # the echo — a combined answer here would be well-formed
        # garbage.
        helper_mgr.stage(delta_db(helper.server.database, RECORDS1))
        helper_mgr.flip()
        with pytest.raises(SnapshotMismatch) as excinfo:
            run_query(leader, [5])
        assert excinfo.value.leader_generation == 0
        assert excinfo.value.helper_generation == 1
        counters = leader.metrics.export()["counters"]
        # initial attempt + snapshot_retries re-runs, each refused.
        assert counters["leader.snapshot_mismatches"] == 2
        assert counters["leader.snapshot_retries"] == 1
        assert leader_mgr.export()["mismatches"] == 2
        # The mismatch froze a debug bundle.
        assert bundles.export()["fired"] >= 1


def test_handshake_window_converges_via_retries():
    leader, helper, leader_mgr, helper_mgr, coordinator = two_party(
        leader_config=make_config(snapshot_retries=20)
    )
    with helper, leader:
        leader_mgr.stage(delta_db(leader.server.database, RECORDS1))
        helper_mgr.stage(delta_db(helper.server.database, RECORDS1))
        helper_mgr.flip()
        # Hold the leader's flip off (a pin) while a query runs: the
        # query sees leader@0/helper@1, refuses typed, and retries
        # until the pin lifts and the leader's armed flip lands at a
        # batch boundary — the bounded mismatch window, in miniature.
        pin = leader_mgr.pin()
        pin.__enter__()
        flip_thread = threading.Thread(
            target=lambda: leader_mgr.flip(timeout=10.0)
        )
        flip_thread.start()
        got = []
        query_thread = threading.Thread(
            target=lambda: got.append(run_query(leader, [7]))
        )
        query_thread.start()
        import time as _time

        _time.sleep(0.05)
        pin.__exit__(None, None, None)
        query_thread.join(timeout=30.0)
        flip_thread.join(timeout=10.0)
        assert got == [[RECORDS1[7]]]
        counters = leader.metrics.export()["counters"]
        assert counters["leader.snapshot_retries"] >= 1
        assert leader_mgr.serving_generation() == 1


# ---------------------------------------------------------------------------
# Envelope downgrade matrix (pre-generation peers)
# ---------------------------------------------------------------------------


def _version_capped(handler, max_version):
    """Wrap a Helper handler as a pre-v3 build: envelopes newer than
    `max_version` are rejected the way an old peer would."""

    def guard(payload):
        if payload.startswith(b"\xffDPT") and payload[4] > max_version:
            raise propagation.EnvelopeError(
                f"unsupported envelope version {payload[4]}"
            )
        return handler(payload)

    return guard


def test_v2_peer_costs_one_downgrade_and_journals_check_disabled():
    helper = HelperSession(
        build_db(RECORDS0), encrypt_decrypt.decrypt, make_config()
    )
    leader = LeaderSession(
        build_db(RECORDS0),
        InProcessTransport(_version_capped(helper.handle_wire, 2)),
        make_config(),
    )
    journal = EventJournal()
    SnapshotManager(leader, journal=journal)
    with helper, leader:
        got = run_query(leader, [5, 64])
        got2 = run_query(leader, [6])
        counters = leader.metrics.export()["counters"]
    assert got == [RECORDS0[5], RECORDS0[64]]
    assert got2 == [RECORDS0[6]]
    # Exactly ONE counted downgrade (v3 -> v2), sticky.
    assert counters["leader.wire_downgrades"] == 1
    assert leader._peer_wire_version == 2
    assert leader._peer_envelope is True
    # No generation echo at v2: checking is disabled-but-journaled,
    # and never raises.
    assert counters.get("leader.snapshot_mismatches", 0) == 0
    kinds = [e["kind"] for e in journal.export()["events"]]
    assert "snapshot.check_disabled" in kinds


def test_pre_generation_leader_interops_with_v3_helper():
    # helper_digest=False pins the Leader at v1 — indistinguishable
    # from an old build. The rotation-aware Helper must answer it in
    # v1, generation-free, with zero downgrades.
    helper = HelperSession(
        build_db(RECORDS0), encrypt_decrypt.decrypt, make_config()
    )
    SnapshotManager(helper, journal=EventJournal())
    replies = []

    def capture(payload):
        out = helper.handle_wire(payload)
        replies.append(out)
        return out

    leader = LeaderSession(
        build_db(RECORDS0),
        InProcessTransport(capture),
        make_config(helper_digest=False),
    )
    with helper, leader:
        got = run_query(leader, [8])
    assert got == [RECORDS0[8]]
    assert leader.metrics.export()["counters"]["leader.wire_downgrades"] == 0
    assert replies and replies[-1][4] == 1  # answered v1
    meta, inner = propagation.try_decode_response(replies[-1])
    assert inner and "generation" not in meta


# ---------------------------------------------------------------------------
# Chaos: rotation faults are crash-safe (N keeps serving, bit-identical)
# ---------------------------------------------------------------------------


def _assert_both_on_generation_zero(leader, leader_mgr, helper_mgr):
    assert leader_mgr.serving_generation() == 0
    assert helper_mgr.serving_generation() == 0
    assert leader_mgr.staging_generation() is None
    assert helper_mgr.staging_generation() is None
    assert run_query(leader, [11]) == [RECORDS0[11]]


def test_stage_fault_aborts_rotation(clean_failpoints):
    leader, helper, leader_mgr, helper_mgr, coordinator = two_party()
    clean_failpoints.arm("snapshot.stage", "error", times=1)
    with helper, leader:
        with pytest.raises(failpoints.FailpointError):
            coordinator.rotate(
                delta_db(leader.server.database, RECORDS1),
                delta_db(helper.server.database, RECORDS1),
            )
        _assert_both_on_generation_zero(leader, leader_mgr, helper_mgr)
        assert leader_mgr.export()["aborts"] == 1


def test_helper_ack_fault_drops_both_stagings(clean_failpoints):
    leader, helper, leader_mgr, helper_mgr, coordinator = two_party()
    clean_failpoints.arm("snapshot.helper_ack", "error", times=1)
    db1_l = delta_db(leader.server.database, RECORDS1)
    db1_h = delta_db(helper.server.database, RECORDS1)
    with helper, leader:
        with pytest.raises(failpoints.FailpointError):
            coordinator.rotate(db1_l, db1_h)
        _assert_both_on_generation_zero(leader, leader_mgr, helper_mgr)
        # Both staged HBM buffers were dropped by the abort.
        assert db1_l._db_words is None and db1_h._db_words is None
        # A second, un-faulted rotation succeeds from the clean state.
        report = coordinator.rotate(
            delta_db(leader.server.database, RECORDS1),
            delta_db(helper.server.database, RECORDS1),
        )
        assert report["to_generation"] == 1
        assert run_query(leader, [11]) == [RECORDS1[11]]


def test_flip_fault_before_any_commit_is_crash_safe(clean_failpoints):
    # The first flip() call in rotate() is the HELPER's (helper-first
    # order): a fault there must leave BOTH parties on N.
    leader, helper, leader_mgr, helper_mgr, coordinator = two_party()
    clean_failpoints.arm("snapshot.flip", "error", times=1)
    with helper, leader:
        with pytest.raises(failpoints.FailpointError):
            coordinator.rotate(
                delta_db(leader.server.database, RECORDS1),
                delta_db(helper.server.database, RECORDS1),
            )
        _assert_both_on_generation_zero(leader, leader_mgr, helper_mgr)


def test_flip_delay_fault_only_stretches_the_window(clean_failpoints):
    leader, helper, leader_mgr, helper_mgr, coordinator = two_party()
    clean_failpoints.arm("snapshot.flip", "delay", times=1, delay_ms=30)
    with helper, leader:
        report = coordinator.rotate(
            delta_db(leader.server.database, RECORDS1),
            delta_db(helper.server.database, RECORDS1),
        )
        # The injected delay landed inside the measured window.
        assert report["staleness_ms"] >= 0.0
        assert run_query(leader, [2]) == [RECORDS1[2]]


# ---------------------------------------------------------------------------
# Flip atomicity: rotation racing concurrent batcher submissions
# ---------------------------------------------------------------------------


def test_flip_never_tears_under_concurrent_submissions():
    indices = [1, 7]
    client = DenseDpfPirClient(NUM_RECORDS, lambda pt, info: pt)
    req0, req1 = client.create_plain_requests(indices)
    combined = messages.PirRequest(
        plain_request=messages.PlainRequest(
            dpf_keys=list(req0.plain_request.dpf_keys)
            + list(req1.plain_request.dpf_keys)
        )
    )
    oracle = {
        0: [RECORDS0[i] for i in indices],
        1: [RECORDS1[i] for i in indices],
    }
    with PlainSession(build_db(RECORDS0), make_config()) as session:
        manager = SnapshotManager(session, journal=EventJournal())
        # Warm the serving path before racing it.
        session.handle_request(combined)
        tears = []
        generations_seen = set()
        stop = threading.Event()

        def worker():
            while not stop.is_set():
                resp = session.handle_request(combined)
                masked = resp.dpf_pir_response.masked_response
                k = len(indices)
                got = [
                    xor_bytes(masked[i], masked[k + i]) for i in range(k)
                ]
                matches = [g for g, want in oracle.items() if got == want]
                if len(matches) != 1:
                    tears.append(got)
                    return
                generations_seen.add(matches[0])

        threads = [threading.Thread(target=worker) for _ in range(8)]
        for t in threads:
            t.start()
        old_db = session.server.database
        db1 = delta_db(old_db, RECORDS1)
        ledger = default_telemetry().transfers
        h2d_before = ledger.bytes_h2d("db_staging")
        staged = manager.stage(db1)
        assert staged > 0
        assert ledger.bytes_h2d("db_staging") - h2d_before >= staged
        manager.flip(timeout=10.0)
        # Let post-flip traffic run, then quiesce.
        import time as _time

        _time.sleep(0.1)
        stop.set()
        for t in threads:
            t.join(timeout=30.0)
        # Every response was bit-identical to exactly ONE generation's
        # oracle — no batch ever evaluated half-and-half.
        assert tears == []
        assert 1 in generations_seen  # post-flip answers observed
        # The last in-flight batch's end_batch runs just after its
        # waiters release; give the drain a moment to land.
        deadline = _time.monotonic() + 5.0
        while _time.monotonic() < deadline:
            export = manager.export()
            if (
                export["inflight"] == {}
                and export["retired_awaiting_drain"] == []
            ):
                break
            _time.sleep(0.01)
        # Drain counters back to zero, the old generation fully freed.
        assert export["inflight"] == {}
        assert export["retired_awaiting_drain"] == []
        assert old_db._db_words is None
        hbm = default_telemetry().hbm.export()
        assert "db_staging" in hbm["watermark_bytes"]


# ---------------------------------------------------------------------------
# Prober golden rotation
# ---------------------------------------------------------------------------


def test_prober_rotates_goldens_with_the_flip():
    journal = EventJournal()
    with PlainSession(build_db(RECORDS0), make_config()) as session:
        manager = SnapshotManager(session, journal=journal)
        prober = Prober(
            session,
            RECORDS0,
            indices=[0, 64, 127],
            journal=journal,
            period_s=60.0,
        )
        prober.bind_snapshots(
            manager, records_provider=lambda gen: RECORDS1
        )
        for result in prober.run_cycle():
            assert result["status"] == "pass", result
        manager.stage(delta_db(session.server.database, RECORDS1))
        manager.flip()
        # The flip listener re-keyed the goldens to generation 1: the
        # next cycle still proves bit-identity (against the NEW bits).
        for result in prober.run_cycle():
            assert result["status"] == "pass", result
        assert prober.export()["generation"] == 1
        kinds = [e["kind"] for e in journal.export()["events"]]
        assert "prober.goldens_rotated" in kinds


def test_prober_rejects_wrong_size_golden_rotation():
    with PlainSession(build_db(RECORDS0), make_config()) as session:
        SnapshotManager(session, journal=EventJournal())
        prober = Prober(session, RECORDS0, period_s=60.0)
        with pytest.raises(ValueError, match="database size"):
            prober.rotate_goldens(RECORDS1[:10])


# ---------------------------------------------------------------------------
# /statusz surface
# ---------------------------------------------------------------------------


def _get(url):
    with urllib.request.urlopen(url, timeout=10) as resp:
        return resp.status, resp.read().decode()


def test_statusz_snapshot_section():
    with PlainSession(build_db(RECORDS0), make_config()) as session:
        manager = SnapshotManager(session, journal=EventJournal())
        manager.stage(delta_db(session.server.database, RECORDS1))
        manager.flip()
        with AdminServer(
            registry=session.metrics, snapshots=manager
        ) as admin:
            base = f"http://127.0.0.1:{admin.port}"
            status, body = _get(f"{base}/statusz?format=json")
            assert status == 200
            state = json.loads(body)
            snap = state["snapshots"]
            assert snap["serving_generation"] == 1
            assert snap["flips"] == 1
            assert snap["history"][-1]["to_generation"] == 1
            status, html = _get(f"{base}/statusz")
            assert status == 200
            assert "<h2>Snapshots</h2>" in html
            assert "serving generation 1" in html
