"""Proto serialization round-trip tests.

Mirrors the reference's proto-surface tests: keys/contexts/requests are
protos (`dpf/distributed_point_function.proto`,
`pir/private_information_retrieval.proto`); everything must survive a
serialize/parse round trip and still evaluate identically.
"""

import numpy as np
import pytest

from distributed_point_functions_tpu import serialization as ser
from distributed_point_functions_tpu.dpf import (
    DistributedPointFunction,
    DpfParameters,
)
from distributed_point_functions_tpu.pir import messages
from distributed_point_functions_tpu.protos import dpf_pb2, pir_pb2
from distributed_point_functions_tpu.value_types import (
    IntModNType,
    IntType,
    TupleType,
    XorType,
)


def test_block_roundtrip():
    x = (123 << 64) | 456
    b = ser.block_to_proto(x)
    assert b.high == 123 and b.low == 456
    assert ser.block_from_proto(b) == x


@pytest.mark.parametrize(
    "vt",
    [
        IntType(8),
        IntType(64),
        IntType(128),
        XorType(128),
        IntModNType(base_bits=32, modulus=1000003),
        TupleType([IntType(32), XorType(8)]),
        TupleType([TupleType([IntType(8)]), IntModNType(base_bits=64, modulus=997)]),
    ],
)
def test_value_type_roundtrip(vt):
    p = ser.value_type_to_proto(vt)
    assert ser.value_type_from_proto(p) == vt
    data = p.SerializeToString()
    q = dpf_pb2.ValueType()
    q.ParseFromString(data)
    assert ser.value_type_from_proto(q) == vt


def test_value_roundtrip():
    vt = TupleType([IntType(128), IntModNType(base_bits=32, modulus=999983)])
    v = ((1 << 100) | 7, 12345)
    p = ser.value_to_proto(vt, v)
    assert ser.value_from_proto(vt, p) == v


def test_key_roundtrip_evaluates_identically():
    dpf = DistributedPointFunction.create(
        DpfParameters(log_domain_size=10, value_type=IntType(64))
    )
    k0, k1 = dpf.generate_keys(700, 42)
    p = ser.key_to_proto(dpf, k0)
    k0b = ser.key_from_proto(dpf, p.__class__.FromString(p.SerializeToString()))
    pts = [0, 699, 700, 701, 1023]
    a = np.asarray(dpf.evaluate_at(k0, 0, pts))
    b = np.asarray(dpf.evaluate_at(k0b, 0, pts))
    np.testing.assert_array_equal(a, b)


def test_incremental_key_proto_has_intermediate_value_corrections():
    dpf = DistributedPointFunction.create_incremental(
        [
            DpfParameters(log_domain_size=3, value_type=IntType(32)),
            DpfParameters(log_domain_size=6, value_type=IntType(32)),
        ]
    )
    k0, _ = dpf.generate_keys_incremental(37, [5, 9])
    p = ser.key_to_proto(dpf, k0)
    with_vc = [len(cw.value_correction) for cw in p.correction_words]
    assert sum(1 for n in with_vc if n > 0) == 1  # one intermediate output level
    # The correction word at hierarchy level 0's tree level carries it.
    vc_index = dpf._hierarchy_to_tree[0]
    assert with_vc[vc_index] > 0
    k0b = ser.key_from_proto(dpf, p)
    assert k0b.correction_words[vc_index].value_correction is not None


def test_evaluation_context_roundtrip():
    dpf = DistributedPointFunction.create_incremental(
        [
            DpfParameters(log_domain_size=4, value_type=IntType(32)),
            DpfParameters(log_domain_size=8, value_type=IntType(32)),
        ]
    )
    k0, _ = dpf.generate_keys_incremental(200, [1, 2])
    ctx = dpf.create_evaluation_context(k0)
    dpf.evaluate_until(0, [], ctx)  # populates previous_hierarchy_level
    proto = ser.evaluation_context_to_proto(dpf, ctx)
    dpf2, ctx2 = ser.evaluation_context_from_proto(
        dpf_pb2.EvaluationContext.FromString(proto.SerializeToString())
    )
    assert ctx2.previous_hierarchy_level == ctx.previous_hierarchy_level
    assert dpf2.parameters == dpf.parameters
    # Continue evaluation from the deserialized context.
    out = dpf2.evaluate_until(1, [12], ctx2)
    assert np.asarray(out).shape[0] == 16


def test_pir_request_roundtrip():
    from distributed_point_functions_tpu.pir import DenseDpfPirClient
    from distributed_point_functions_tpu.testing import encrypt_decrypt

    client = DenseDpfPirClient.create(500, encrypt_decrypt.encrypt)
    request, _ = client.create_request([3, 499])
    proto = ser.pir_request_to_proto(client.dpf, request)
    data = proto.SerializeToString()
    parsed = ser.pir_request_from_proto(
        client.dpf, pir_pb2.PirRequest.FromString(data)
    )
    assert parsed.leader_request is not None
    assert len(parsed.leader_request.plain_request.dpf_keys) == 2
    assert (
        parsed.leader_request.encrypted_helper_request.encrypted_request
        == request.leader_request.encrypted_helper_request.encrypted_request
    )


def test_pir_response_roundtrip():
    resp = messages.PirResponse(
        dpf_pir_response=messages.DpfPirResponse(
            masked_response=[b"abc", b"\x00\xff"]
        )
    )
    proto = ser.pir_response_to_proto(resp)
    back = ser.pir_response_from_proto(
        pir_pb2.PirResponse.FromString(proto.SerializeToString())
    )
    assert back.dpf_pir_response.masked_response == [b"abc", b"\x00\xff"]


def test_helper_request_proto_wire_format():
    """The helper request wire bytes parse as a DpfPirRequest.HelperRequest."""
    from distributed_point_functions_tpu.pir import DenseDpfPirClient
    from distributed_point_functions_tpu.testing import encrypt_decrypt

    client = DenseDpfPirClient.create(300, encrypt_decrypt.encrypt)
    request, _ = client.create_request([7])
    ciphertext = request.leader_request.encrypted_helper_request.encrypted_request
    plaintext = encrypt_decrypt.decrypt(ciphertext, b"DpfPirServer")
    proto = pir_pb2.DpfPirRequest.HelperRequest()
    proto.ParseFromString(plaintext)
    assert len(proto.plain_request.dpf_key) == 1
    assert len(proto.one_time_pad_seed) == 16
