"""Device telemetry tests: the compile tracker and the HBM accountant.

The compile-tracker contract mirrors jax's executable cache for
shape-bucketed callers: exactly one compile per new (site, shape) pair,
a cache hit for every re-dispatch — asserted both on the tracker
directly and through the dynamic batcher's real dispatch path, where
the power-of-two bucket discipline is what bounds the shape count. The
HBM accountant's contract is per-phase watermarks: monotone within one
phase occurrence, reset on re-entry (driven by an injected sampler so
the tests are byte-exact and JAX-free).
"""

import threading

import pytest

from distributed_point_functions_tpu.observability.device import (
    CompileTracker,
    DeviceTelemetry,
    HbmAccountant,
    default_telemetry,
    set_default_telemetry,
    shape_key,
)
from distributed_point_functions_tpu.serving.batcher import DynamicBatcher
from distributed_point_functions_tpu.serving.metrics import MetricsRegistry


@pytest.fixture
def telemetry():
    """Swap in a fresh process-default telemetry, restore on exit."""
    prev = default_telemetry()
    fresh = set_default_telemetry(DeviceTelemetry())
    try:
        yield fresh
    finally:
        set_default_telemetry(prev)


class TestShapeKey:
    def test_prefixed_parts(self):
        assert shape_key(("q", 64), ("b", 8192)) == "q64.b8192"

    def test_reserved_label_chars_sanitized(self):
        key = shape_key("a,b", "c=d", "{e}")
        for c in ",={}":
            assert c not in key

    def test_array_like_renders_shape_and_dtype(self):
        class Arr:
            shape = (4, 128)
            dtype = "uint32"

        assert shape_key(("x", Arr())) == "x4x128.uint32"

    def test_empty_is_default(self):
        assert shape_key() == "default"


class TestCompileTracker:
    def test_one_compile_per_new_shape_zero_on_redispatch(self):
        t = CompileTracker()
        assert t.record_dispatch("site", "q64") is True
        assert t.record_dispatch("site", "q64") is False
        assert t.record_dispatch("site", "q64") is False
        assert t.record_dispatch("site", "q128") is True
        assert t.compiles("site") == 2
        assert t.hits("site") == 2

    def test_sites_are_independent(self):
        t = CompileTracker()
        t.record_dispatch("a", "q64")
        t.record_dispatch("b", "q64")
        assert t.compiles("a") == 1
        assert t.compiles("b") == 1
        assert t.compiles() == 2

    def test_dispatch_times_first_call_as_compile(self):
        t = CompileTracker()
        with t.dispatch("site", "q64"):
            pass
        with t.dispatch("site", "q64"):
            pass
        export = t.export()["sites"]["site"]
        assert export["compiles"] == 1
        assert export["hits"] == 1
        # First call's wall time lands in the compile histogram; the
        # re-dispatch does not.
        assert export["compile_ms"]["count"] == 1

    def test_dispatch_records_even_when_call_raises(self):
        t = CompileTracker()
        with pytest.raises(RuntimeError):
            with t.dispatch("site", "q64"):
                raise RuntimeError("boom")
        assert t.compiles("site") == 1

    def test_registry_mirroring(self):
        reg = MetricsRegistry()
        t = CompileTracker(reg)
        t.record_dispatch("site", "q64", compile_ms=12.5)
        t.record_dispatch("site", "q64")
        export = reg.export()
        assert export["counters"]["device.compiles{site=site}"] == 1
        assert export["counters"]["device.dispatch_hits{site=site}"] == 1
        assert export["gauges"]["device.distinct_shapes{site=site}"] == 1
        hist = export["histograms"]["device.compile_ms{site=site}"]
        assert hist["count"] == 1

    def test_authoritative_state_survives_registry_reset(self):
        reg = MetricsRegistry()
        t = CompileTracker(reg)
        t.record_dispatch("site", "q64")
        reg.reset()
        assert t.compiles("site") == 1

    def test_track_wrapper(self):
        t = CompileTracker()
        calls = []

        def fn(n):
            calls.append(n)
            return n * 2

        wrapped = t.track("site", fn, key_fn=lambda n: shape_key(("n", n)))
        assert wrapped(3) == 6
        assert wrapped(3) == 6
        assert wrapped(4) == 8
        assert t.compiles("site") == 2
        assert t.hits("site") == 1

    def test_export_hit_ratio_and_reset(self):
        t = CompileTracker()
        t.record_dispatch("site", "a")
        t.record_dispatch("site", "a")
        t.record_dispatch("site", "a")
        t.record_dispatch("site", "a")
        entry = t.export()["sites"]["site"]
        assert entry["hit_ratio"] == 0.75
        t.reset()
        assert t.export()["sites"] == {}

    def test_thread_safety_single_compile_under_contention(self):
        t = CompileTracker()
        compiles = []
        barrier = threading.Barrier(8)

        def worker():
            barrier.wait()
            for _ in range(100):
                if t.record_dispatch("site", "q64"):
                    compiles.append(1)

        threads = [threading.Thread(target=worker) for _ in range(8)]
        for th in threads:
            th.start()
        for th in threads:
            th.join()
        assert len(compiles) == 1
        assert t.compiles("site") == 1
        assert t.hits("site") == 799


class TestBatcherIntegration:
    def test_one_compile_per_bucket_zero_on_redispatch(self, telemetry):
        """The real batcher dispatch path: the first batch landing in a
        power-of-two bucket is the compile; every later batch of the
        same bucket is a cache hit; a new bucket compiles once."""
        with DynamicBatcher(
            lambda keys: [k * 2 for k in keys],
            max_batch_size=1,  # one request per batch: bucket == 1
            name="dev_obs",
        ) as b:
            b.submit([10])
            b.submit([11])
            b.submit([12])
        tracker = telemetry.compile_tracker
        assert tracker.compiles("dev_obs.evaluate") == 1
        assert tracker.hits("dev_obs.evaluate") == 2

    def test_distinct_buckets_compile_independently(self, telemetry):
        with DynamicBatcher(
            lambda keys: list(keys), max_batch_size=2, name="dev_obs2"
        ) as b:
            b.submit([1])  # bucket 1 -> compile
            b.submit([2])  # bucket 1 -> hit
        tracker = telemetry.compile_tracker
        export = tracker.export()["sites"]["dev_obs2.evaluate"]
        assert export["compiles"] == len(export["shapes"])
        assert tracker.compiles("dev_obs2.evaluate") + tracker.hits(
            "dev_obs2.evaluate"
        ) == 2


class TestHbmAccountant:
    def _accountant(self, values):
        it = iter(values)

        def sampler():
            return next(it), "test"

        return HbmAccountant(sampler=sampler)

    def test_watermark_monotone_within_phase(self):
        acc = self._accountant([100, 900, 400, 200])
        with acc.phase("db_staging"):  # entry sample: 100
            acc.sample()  # 900 raises the watermark
            acc.sample()  # 400 does not lower it
            # exit sample: 200
        assert acc.watermark("db_staging") == 900

    def test_watermark_resets_between_phases(self):
        acc = self._accountant([1000, 1000, 50, 80])
        with acc.phase("selection"):
            pass
        assert acc.watermark("selection") == 1000
        with acc.phase("selection"):  # re-entry resets to this pass
            pass
        assert acc.watermark("selection") == 80

    def test_phases_do_not_nest_innermost_wins(self):
        acc = self._accountant([10, 500, 20, 30, 40, 25])
        with acc.phase("outer"):  # entry 10
            with acc.phase("inner"):  # entry 500
                acc.sample()  # 20 -> inner
            # inner exit 30; outer resumes
            acc.sample()  # 40 -> outer
        # outer exit sample: 25 (does not lower the 40 watermark)
        assert acc.watermark("inner") == 500
        assert acc.watermark("outer") == 40

    def test_sample_outside_phase_attributes_to_process(self):
        acc = self._accountant([77])
        acc.sample()
        assert acc.watermark("process") == 77

    def test_registry_gauges(self):
        reg = MetricsRegistry()
        it = iter([5, 10, 3])

        def sampler():
            return next(it), "test"

        acc = HbmAccountant(registry=reg, sampler=sampler)
        with acc.phase("db_staging"):
            acc.sample()
        export = reg.export()
        assert export["gauges"]["device.hbm_live_bytes"] == 3
        assert (
            export["gauges"]["device.hbm_watermark_bytes{phase=db_staging}"]
            == 10
        )
        assert export["counters"]["device.hbm_samples"] == 3

    def test_export_and_reset(self):
        acc = self._accountant([123])
        acc.sample()
        export = acc.export()
        assert export["live_bytes"] == 123
        assert export["source"] == "test"
        assert export["samples"] == 1
        acc.reset()
        assert acc.export()["samples"] == 0
        assert acc.export()["watermark_bytes"] == {}

    def test_live_bytes_real_backend_samples(self):
        """The real sampler (CPU: live_arrays fallback) sees a staged
        device buffer grow the db_staging watermark."""
        jnp = pytest.importorskip("jax.numpy")
        acc = HbmAccountant()
        with acc.phase("db_staging"):
            buf = jnp.zeros((1024, 32), jnp.uint32)
            buf.block_until_ready()
            acc.sample()
        assert acc.watermark("db_staging") >= 1024 * 32 * 4
        del buf

    def test_default_telemetry_swap(self, telemetry):
        telemetry.hbm.sample()
        assert default_telemetry() is telemetry
        assert telemetry.hbm.export()["samples"] == 1
