"""Depth-2 batcher pipeline: async dispatch with a completion thread.

At `pipeline_depth >= 2` the batcher worker dispatches bucket N while
a completion thread finishes bucket N-1. These tests pin the contract:
results/errors are identical to the serial depth-1 path, `close()`
drains the in-flight completion stage, the phase-attribution residual
(`dispatch`) clamps at zero with the excess counted in
`attribution_slop_ms`, and — the rotation-safety half — a snapshot
flip can never apply between the dispatch and completion halves of a
pipelined bucket (in-flight counts span begin_batch .. end_batch).
"""

import threading
import time

import numpy as np
import pytest

from distributed_point_functions_tpu.observability import phases as phases_mod
from distributed_point_functions_tpu.pir import (
    DenseDpfPirClient,
    DenseDpfPirDatabase,
)
from distributed_point_functions_tpu.pir import messages
from distributed_point_functions_tpu.prng import xor_bytes
from distributed_point_functions_tpu.serving import (
    PlainSession,
    ServingConfig,
    SnapshotManager,
)
from distributed_point_functions_tpu.serving.batcher import DynamicBatcher
from distributed_point_functions_tpu.observability.events import EventJournal


# ---------------------------------------------------------------------------
# Depth-2 equivalence on a stub evaluator
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("depth", [1, 2, 3])
def test_pipelined_results_match_serial(depth):
    with DynamicBatcher(
        lambda keys: [k * 3 for k in keys],
        max_batch_size=8,
        max_wait_ms=1.0,
        pipeline_depth=depth,
    ) as batcher:
        out = {}

        def work(i):
            out[i] = batcher.submit([i, i + 1000])

        threads = [
            threading.Thread(target=work, args=(i,)) for i in range(24)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert out == {i: [3 * i, 3 * (i + 1000)] for i in range(24)}


def test_pipelined_error_fans_out_and_worker_recovers():
    flaky = {"fail": True}

    def evaluate(keys):
        if flaky["fail"]:
            raise RuntimeError("boom")
        return list(keys)

    with DynamicBatcher(
        evaluate, max_batch_size=4, max_wait_ms=1.0, pipeline_depth=2
    ) as batcher:
        errors = []

        def work(i):
            try:
                batcher.submit([i])
            except RuntimeError as e:
                errors.append(str(e))

        threads = [
            threading.Thread(target=work, args=(i,)) for i in range(5)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert errors == ["boom"] * 5
        flaky["fail"] = False
        assert batcher.submit([42]) == [42]


def test_close_drains_the_inflight_completion_stage():
    def slow(keys):
        time.sleep(0.15)
        return list(keys)

    batcher = DynamicBatcher(
        slow, max_batch_size=1, max_wait_ms=0.0, pipeline_depth=2
    )
    results = {}

    def work(i):
        results[i] = batcher.submit([i])

    threads = [threading.Thread(target=work, args=(i,)) for i in range(3)]
    for t in threads:
        t.start()
    time.sleep(0.05)
    batcher.close()
    for t in threads:
        t.join(timeout=5.0)
    assert results == {0: [0], 1: [1], 2: [2]}


def test_validates_pipeline_depth():
    with pytest.raises(ValueError, match="pipeline_depth"):
        DynamicBatcher(lambda k: k, pipeline_depth=0)


# ---------------------------------------------------------------------------
# dispatch attribution: non-negative residual, slop counted
# ---------------------------------------------------------------------------


def test_dispatch_clamps_at_zero_and_slop_is_counted():
    """An evaluation whose phase brackets over-cover its wall time
    (clock skew, out-of-band attribution) must not produce a negative
    `dispatch` residual — it clamps at zero and the excess lands in
    the `attribution_slop_ms` counter."""

    def evaluate(keys):
        # Out-of-band attribution far exceeding the actual wall time.
        phases_mod.record("device_compute", 60_000.0)
        return list(keys)

    recorder = phases_mod.default_phase_recorder()
    with DynamicBatcher(
        evaluate, max_wait_ms=0.0, pipeline_depth=2
    ) as batcher:
        with recorder.request("test-client", fresh=True) as req:
            assert batcher.submit([7]) == [7]
        snapshot = req.snapshot()
        assert snapshot.get("dispatch", 0.0) == 0.0
        assert snapshot["device_compute"] == 60_000.0
        counters = batcher.metrics.export()["counters"]
        assert counters["batcher.attribution_slop_ms"] > 59_000.0


def test_real_dispatch_time_still_attributes():
    """With no phase brackets at all, the whole evaluation wall time is
    dispatch — the clamp only removes the impossible negative case."""
    with DynamicBatcher(
        lambda keys: (time.sleep(0.02), list(keys))[1],
        max_wait_ms=0.0,
        pipeline_depth=2,
    ) as batcher:
        recorder = phases_mod.default_phase_recorder()
        with recorder.request("test-client", fresh=True) as req:
            assert batcher.submit([1]) == [1]
        assert req.snapshot().get("dispatch", 0.0) >= 20.0
        counters = batcher.metrics.export()["counters"]
        assert counters.get("batcher.attribution_slop_ms", 0.0) == 0.0


# ---------------------------------------------------------------------------
# Flip atomicity across the dispatch/completion split
# ---------------------------------------------------------------------------

NUM_RECORDS = 128
RECORD_BYTES = 16
RNG = np.random.default_rng(20260807)
RECORDS0 = [
    bytes(RNG.integers(0, 256, RECORD_BYTES, dtype=np.uint8))
    for _ in range(NUM_RECORDS)
]
RECORDS1 = [bytes(b ^ 0xA5 for b in r) for r in RECORDS0]


def build_db(records):
    builder = DenseDpfPirDatabase.Builder()
    for r in records:
        builder.insert(r)
    return builder.build()


def delta_db(prev, records):
    builder = DenseDpfPirDatabase.Builder()
    for i, r in enumerate(records):
        builder.update(i, r)
    return builder.build_from(prev)


def test_flip_never_applies_between_dispatch_and_completion():
    """A pipelined bucket binds its generation at dispatch
    (`begin_batch`) and retires it at completion (`end_batch`). While
    it sits between the two halves — evaluated, waiting for fan-out —
    the rotation's idle-apply path must refuse to flip: the in-flight
    count spans the whole pipeline, not just the evaluation."""
    indices = [1, 7]
    client = DenseDpfPirClient(NUM_RECORDS, lambda pt, info: pt)
    req0, req1 = client.create_plain_requests(indices)
    combined = messages.PirRequest(
        plain_request=messages.PlainRequest(
            dpf_keys=list(req0.plain_request.dpf_keys)
            + list(req1.plain_request.dpf_keys)
        )
    )
    config = ServingConfig(
        max_batch_size=8, max_wait_ms=1.0, pipeline_depth=2
    )
    with PlainSession(build_db(RECORDS0), config) as session:
        manager = SnapshotManager(session, journal=EventJournal())
        session.handle_request(combined)  # warm the jit path
        batcher = session._batcher
        entered = threading.Event()
        gate = threading.Event()
        orig_finish = batcher._finish

        def gated_finish(rec):
            entered.set()
            gate.wait(timeout=10.0)
            orig_finish(rec)

        batcher._finish = gated_finish
        try:
            responses = {}

            def query():
                responses["resp"] = session.handle_request(combined)

            thread = threading.Thread(target=query)
            thread.start()
            # The bucket is now evaluated (dispatch half done,
            # generation 0 bound and counted in flight) but stuck
            # before its completion half.
            assert entered.wait(timeout=10.0)
            manager.stage(delta_db(session.server.database, RECORDS1))
            assert sum(manager.export()["inflight"].values()) >= 1
            with pytest.raises(TimeoutError):
                manager.flip(timeout=0.3)
            # Still serving generation 0: the armed flip refused the
            # idle-apply mid-bucket and timed out instead.
            assert manager.serving_generation() == 0
        finally:
            gate.set()
        thread.join(timeout=10.0)
        batcher._finish = orig_finish
        # The gated bucket fanned out against generation 0 exactly.
        masked = responses["resp"].dpf_pir_response.masked_response
        k = len(indices)
        got = [xor_bytes(masked[i], masked[k + i]) for i in range(k)]
        assert got == [RECORDS0[i] for i in indices]
        # Drained: the flip now applies and generation 1 serves.
        record = manager.flip(timeout=10.0)
        assert record["to_generation"] == 1
        resp = session.handle_request(combined)
        masked = resp.dpf_pir_response.masked_response
        got = [xor_bytes(masked[i], masked[k + i]) for i in range(k)]
        assert got == [RECORDS1[i] for i in indices]
        # stage() surfaced the delta-prestage accounting.
        last_stage = manager.export()["last_stage"]
        assert last_stage is not None
        assert last_stage["bytes_staged"] >= 0
