"""Sparse PIR tests: hash families, hash tables, cuckoo database,
sparse server/client, Leader/Helper protocol.

Mirrors `pir/hashing/*_test.cc` and
`pir/cuckoo_hashing_sparse_dpf_pir_server_test.cc`.
"""

import numpy as np
import pytest

from distributed_point_functions_tpu.hashing import (
    CuckooHashTable,
    HashFamilyConfig,
    HASH_FAMILY_SHA256,
    MultipleChoiceHashTable,
    SHA256HashFamily,
    SimpleHashTable,
    create_hash_family_from_config,
    create_hash_functions,
    wrap_with_seed,
)
from distributed_point_functions_tpu.pir import (
    CuckooHashedDpfPirDatabase,
    CuckooHashingSparseDpfPirClient,
    CuckooHashingSparseDpfPirServer,
)
from distributed_point_functions_tpu.prng import xor_bytes
from distributed_point_functions_tpu.testing import encrypt_decrypt

RNG = np.random.default_rng(11)


# ---------------------------------------------------------------------------
# Hashing
# ---------------------------------------------------------------------------


def test_sha256_hash_function_deterministic_and_in_range():
    fn = SHA256HashFamily()(b"seed")
    for ub in [1, 2, 7, 1000, 1 << 30]:
        vals = [fn(f"input{i}".encode(), ub) for i in range(50)]
        assert all(0 <= v < ub for v in vals)
        assert vals == [fn(f"input{i}".encode(), ub) for i in range(50)]
    # Different seeds give different functions.
    fn2 = SHA256HashFamily()(b"seed2")
    assert any(
        fn(f"x{i}".encode(), 1 << 20) != fn2(f"x{i}".encode(), 1 << 20)
        for i in range(10)
    )


def test_sha256_reduction_matches_digest_interpretation():
    import hashlib

    fn = SHA256HashFamily()(b"s")
    digest = hashlib.sha256(b"s" + b"data").digest()
    lo = int.from_bytes(digest[:16], "little")
    hi = int.from_bytes(digest[16:], "little")
    assert fn(b"data", 1000003) == ((hi << 128) | lo) % 1000003


def test_wrap_with_seed_and_create_hash_functions():
    family = wrap_with_seed(SHA256HashFamily(), b"family")
    fns = create_hash_functions(family, 3)
    assert len(fns) == 3
    direct = SHA256HashFamily()(b"family" + b"1")
    assert fns[1](b"abc", 999) == direct(b"abc", 999)


def test_cuckoo_hash_table_inserts_all():
    fns = create_hash_functions(SHA256HashFamily(), 3)
    table = CuckooHashTable(fns, num_buckets=150, max_relocations=100)
    elements = [f"elem{i}".encode() for i in range(100)]
    for e in elements:
        table.insert(e)
    stored = [x for x in table.get_table() if x is not None]
    assert sorted(stored + table.get_stash()) == sorted(elements)
    # Each stored element is in one of its hash buckets.
    for i, slot in enumerate(table.get_table()):
        if slot is not None:
            assert i in [fn(slot, 150) for fn in fns]


def test_cuckoo_hash_table_stash_overflow():
    fns = create_hash_functions(SHA256HashFamily(), 2)
    table = CuckooHashTable(
        fns, num_buckets=2, max_relocations=5, max_stash_size=0
    )
    with pytest.raises(RuntimeError, match="stash"):
        for i in range(10):
            table.insert(f"e{i}".encode())


def test_multiple_choice_hash_table():
    fns = create_hash_functions(SHA256HashFamily(), 2)
    table = MultipleChoiceHashTable(fns, num_buckets=50)
    for i in range(40):
        table.insert(f"x{i}".encode())
    all_stored = [e for bucket in table.get_table() for e in bucket]
    assert sorted(all_stored) == sorted(f"x{i}".encode() for i in range(40))
    # Load is balanced: least-loaded choice keeps buckets small.
    assert max(len(b) for b in table.get_table()) <= 4


def test_simple_hash_table_stores_under_all_functions():
    fns = create_hash_functions(SHA256HashFamily(), 3)
    table = SimpleHashTable(fns, num_buckets=30)
    table.insert(b"hello")
    count = sum(b.count(b"hello") for b in table.get_table())
    # Stored once per (distinct) hash bucket; duplicates collapse only if
    # two hash functions collide.
    assert 1 <= count <= 3
    buckets = {fn(b"hello", 30) for fn in fns}
    assert count == len(buckets)


def test_hash_family_config_validation():
    with pytest.raises(ValueError, match="seed"):
        create_hash_family_from_config(
            HashFamilyConfig(HASH_FAMILY_SHA256, b"")
        )
    with pytest.raises(ValueError, match="unspecified"):
        create_hash_family_from_config(HashFamilyConfig(0, b"s"))


# ---------------------------------------------------------------------------
# Cuckoo database + sparse PIR end-to-end
# ---------------------------------------------------------------------------


def build_sparse_fixture(num_elements=60, value_size=20):
    rng = np.random.default_rng(123)
    pairs = [
        (
            f"key_{i}".encode(),
            bytes(rng.integers(0, 256, value_size, dtype=np.uint8)),
        )
        for i in range(num_elements)
    ]
    params = CuckooHashingSparseDpfPirServer.generate_params(
        num_elements, seed=b"0123456789abcdef"
    )
    builder = CuckooHashedDpfPirDatabase.Builder().set_params(params)
    for kv in pairs:
        builder.insert(kv)
    return params, builder.build(), dict(pairs)


def test_cuckoo_database_layout():
    params, db, pairs = build_sparse_fixture()
    assert db.size == len(pairs)
    assert db.num_buckets == params.num_buckets


def test_sparse_pir_plain_protocol():
    params, db, pairs = build_sparse_fixture()
    _, db2, _ = build_sparse_fixture()
    server0 = CuckooHashingSparseDpfPirServer.create_plain(params, db)
    server1 = CuckooHashingSparseDpfPirServer.create_plain(params, db2)
    client = CuckooHashingSparseDpfPirClient.create(
        params, encrypt_decrypt.encrypt
    )

    queries = [b"key_0", b"key_31", b"missing_key"]
    req0, req1 = client.create_plain_requests(queries)
    resp0 = server0.handle_request(req0)
    resp1 = server1.handle_request(req1)
    combined = [
        xor_bytes(a, b)
        for a, b in zip(
            resp0.dpf_pir_response.masked_response,
            resp1.dpf_pir_response.masked_response,
        )
    ]
    # Decode without masking via the sparse client's matching logic.
    from distributed_point_functions_tpu.pir.sparse_client import (
        _is_prefix_padded_with_zeros,
    )

    num_hashes = params.num_hash_functions
    for i, q in enumerate(queries):
        found = None
        for j in range(num_hashes):
            idx = 2 * (num_hashes * i + j)
            if found is None and _is_prefix_padded_with_zeros(
                combined[idx], q
            ):
                found = combined[idx + 1]
        if q in pairs:
            assert found is not None
            assert found[: len(pairs[q])] == pairs[q]
        else:
            assert found is None or all(b == 0 for b in found)


def test_sparse_pir_leader_helper_end_to_end():
    params, db, pairs = build_sparse_fixture(num_elements=40)
    _, db2, _ = build_sparse_fixture(num_elements=40)
    helper = CuckooHashingSparseDpfPirServer.create_helper(
        params, db2, encrypt_decrypt.decrypt
    )

    def sender(helper_request, while_waiting):
        while_waiting()
        return helper.handle_request(helper_request)

    leader = CuckooHashingSparseDpfPirServer.create_leader(
        params, db, sender
    )
    client = CuckooHashingSparseDpfPirClient.create(
        params, encrypt_decrypt.encrypt
    )
    queries = [b"key_5", b"nope", b"key_39"]
    request, state = client.create_request(queries)
    response = leader.handle_request(request)
    results = client.handle_response(response, state)
    assert results[1] is None
    for qi in (0, 2):
        q = queries[qi]
        assert results[qi] is not None
        assert results[qi][: len(pairs[q])] == pairs[q]


def test_client_from_serialized_public_params_completes_query():
    """A client constructed ONLY from the server's serialized
    `PirServerPublicParams` wire message must complete a real query
    (`pir/pir_server.h:31`, `cuckoo_hashing_sparse_dpf_pir_client_test.cc:170`)."""
    params, db, pairs = build_sparse_fixture(num_elements=30)
    _, db2, _ = build_sparse_fixture(num_elements=30)
    helper = CuckooHashingSparseDpfPirServer.create_helper(
        params, db2, encrypt_decrypt.decrypt
    )

    def sender(helper_request, while_waiting):
        while_waiting()
        return helper.handle_request(helper_request)

    leader = CuckooHashingSparseDpfPirServer.create_leader(params, db, sender)

    # The client sees nothing but the wire bytes from the leader.
    wire = leader.get_public_params().SerializeToString()
    assert isinstance(wire, bytes) and len(wire) > 0
    client = CuckooHashingSparseDpfPirClient.create_from_public_params(
        wire, encrypt_decrypt.encrypt
    )
    queries = [b"key_3", b"key_29", b"nope"]
    request, state = client.create_request(queries)
    results = client.handle_response(leader.handle_request(request), state)
    for qi, q in enumerate(queries):
        if q in pairs:
            assert results[qi] is not None
            assert results[qi][: len(pairs[q])] == pairs[q]
        else:
            assert results[qi] is None


def test_dense_server_public_params_empty_message():
    from distributed_point_functions_tpu.pir.database import (
        DenseDpfPirDatabase,
    )
    from distributed_point_functions_tpu.pir.server import DenseDpfPirServer

    server = DenseDpfPirServer.create_plain(
        DenseDpfPirDatabase([b"a", b"b", b"c"])
    )
    proto = server.get_public_params()
    assert proto.WhichOneof("wrapped_pir_server_public_params") is None
    assert proto.SerializeToString() == b""
