"""Fleet-wide quorum rotation tests over real serving sessions.

The invariants: a quorum of replicas staging generation N+1 commits
the fleet (flip everywhere, Helper-first per pair); a replica killed
mid-stage becomes a laggard that is SHED from the candidate set,
re-converged party by party, and readmitted — with zero wrong bits
served at any point; short of quorum NOTHING flips anywhere and
`QuorumFailed` is typed; an unrecoverable laggard is marked dead, not
retried forever.
"""

import numpy as np
import pytest

from distributed_point_functions_tpu.fleet import (
    FleetRotationCoordinator,
    QuorumFailed,
    Replica,
    ReplicaSet,
)
from distributed_point_functions_tpu.observability.events import EventJournal
from distributed_point_functions_tpu.pir import (
    DenseDpfPirClient,
    DenseDpfPirDatabase,
)
from distributed_point_functions_tpu.prng import xor_bytes
from distributed_point_functions_tpu.robustness import failpoints
from distributed_point_functions_tpu.serving import (
    HelperSession,
    InProcessTransport,
    LeaderSession,
    PlainSession,
    ServingConfig,
    SnapshotManager,
)
from distributed_point_functions_tpu.testing import encrypt_decrypt

NUM_RECORDS = 64
RECORD_BYTES = 16
RNG = np.random.default_rng(4242)

RECORDS0 = [
    bytes(RNG.integers(0, 256, RECORD_BYTES, dtype=np.uint8))
    for _ in range(NUM_RECORDS)
]
# Generation 1 differs at every byte so a cross-generation XOR can
# never accidentally equal either oracle.
RECORDS1 = [bytes(b ^ 0xA5 for b in r) for r in RECORDS0]


def build_db(records):
    builder = DenseDpfPirDatabase.Builder()
    for r in records:
        builder.insert(r)
    return builder.build()


def delta_db(prev, records):
    builder = DenseDpfPirDatabase.Builder()
    for i, r in enumerate(records):
        builder.update(i, r)
    return builder.build_from(prev)


def make_config(**overrides):
    base = dict(
        max_batch_size=8,
        max_wait_ms=2.0,
        helper_timeout_ms=None,
        helper_retries=2,
        helper_backoff_ms=1.0,
        helper_backoff_max_ms=2.0,
    )
    base.update(overrides)
    return ServingConfig(**base)


@pytest.fixture(autouse=True)
def clean_failpoints():
    reg = failpoints.default_failpoints()
    reg.clear()
    yield reg
    reg.clear()


def plain_replica(rid):
    session = PlainSession(build_db(RECORDS0), make_config())
    manager = SnapshotManager(session, journal=EventJournal())
    return Replica(rid, session, leader_snapshots=manager)


def make_fleet(n=3):
    journal = EventJournal()
    rs = ReplicaSet(journal=journal)
    replicas = [rs.add(plain_replica(f"r{i}")) for i in range(n)]
    return rs, replicas, journal


def next_dbs(replica):
    """databases callable: one fresh generation-1 delta per replica."""
    leader_db = delta_db(replica.leader.server.database, RECORDS1)
    helper_db = (
        delta_db(replica.helper.server.database, RECORDS1)
        if replica.helper is not None
        else None
    )
    return leader_db, helper_db


def query_plain(session, indices):
    client = DenseDpfPirClient(NUM_RECORDS, lambda pt, info: pt)
    req0, req1 = client.create_plain_requests(indices)
    resp0 = session.handle_request(req0)
    resp1 = session.handle_request(req1)
    return [
        xor_bytes(a, b)
        for a, b in zip(
            resp0.dpf_pir_response.masked_response,
            resp1.dpf_pir_response.masked_response,
        )
    ]


def close_all(replicas):
    for r in replicas:
        r.leader.close()
        if r.helper is not None:
            r.helper.close()


# ---------------------------------------------------------------------------


def test_quorum_rotation_happy_path():
    rs, replicas, journal = make_fleet(3)
    coordinator = FleetRotationCoordinator(rs, journal=journal)
    try:
        report = coordinator.rotate(next_dbs)
        assert report["to_generation"] == 1
        assert report["quorum"] == 2  # majority of 3
        assert sorted(report["acked"]) == ["r0", "r1", "r2"]
        assert sorted(report["flipped"]) == ["r0", "r1", "r2"]
        assert report["laggards"] == {}
        for r in replicas:
            assert r.serving_generation() == 1
            assert rs.state(r.replica_id) == "serving"
            assert query_plain(r.leader, [0, 33]) == [
                RECORDS1[0], RECORDS1[33],
            ]
        kinds = [e["kind"] for e in journal.export()["events"]]
        assert "fleet.rotation" in kinds
        assert coordinator.export()["rotations"] == 1
    finally:
        close_all(replicas)


def test_replica_killed_mid_stage_is_shed_converged_and_readmitted(
    clean_failpoints,
):
    rs, replicas, journal = make_fleet(3)
    coordinator = FleetRotationCoordinator(rs, journal=journal)
    # Kill r1 exactly once, mid-stage: the per-replica chaos site fires
    # between marking it `staging` and staging its managers.
    clean_failpoints.arm("fleet.stage.r1", "error", times=1)
    try:
        report = coordinator.rotate(next_dbs)
        # Quorum (2/3) held: the fleet committed to generation 1.
        assert report["to_generation"] == 1
        assert sorted(report["acked"]) == ["r0", "r2"]
        # The laggard was shed, converged party by party, readmitted.
        assert report["laggards"] == {"r1": "recovered"}
        for r in replicas:
            assert r.serving_generation() == 1
            assert rs.state(r.replica_id) == "serving"
            # Zero wrong bits: every replica answers generation 1.
            assert query_plain(r.leader, [5, 63]) == [
                RECORDS1[5], RECORDS1[63],
            ]
        export = rs.export()
        assert export["sheds"] == 1 and export["readmissions"] == 1
        transitions = [(t["replica"], t["to"]) for t in export["history"]]
        assert ("r1", "draining") in transitions
        assert ("r1", "serving") in transitions
    finally:
        close_all(replicas)


def test_quorum_failure_aborts_everywhere(clean_failpoints):
    rs, replicas, journal = make_fleet(3)
    # Unanimity required: one mid-stage death must abort the rotation.
    coordinator = FleetRotationCoordinator(rs, quorum=3, journal=journal)
    clean_failpoints.arm("fleet.stage.r1", "error", times=1)
    try:
        with pytest.raises(QuorumFailed) as excinfo:
            coordinator.rotate(next_dbs)
        assert excinfo.value.to_generation == 1
        assert sorted(excinfo.value.acked) == ["r0", "r2"]
        assert sorted(excinfo.value.failed) == ["r1"]
        # NOTHING flipped: every replica serves generation 0, nothing
        # left staged, states restored.
        for r in replicas:
            assert r.serving_generation() == 0
            assert r.staging_generation() is None
            assert rs.state(r.replica_id) == "serving"
            assert query_plain(r.leader, [7]) == [RECORDS0[7]]
        kinds = [e["kind"] for e in journal.export()["events"]]
        assert "fleet.quorum_failed" in kinds
        assert coordinator.export()["quorum_failures"] == 1
        # A clean retry converges from the aborted state.
        report = coordinator.rotate(next_dbs)
        assert report["laggards"] == {}
        assert all(r.serving_generation() == 1 for r in replicas)
    finally:
        close_all(replicas)


def test_unrecoverable_laggard_is_marked_dead(clean_failpoints):
    rs, replicas, journal = make_fleet(3)
    coordinator = FleetRotationCoordinator(rs, journal=journal)
    clean_failpoints.arm("fleet.stage.r1", "error", times=1)
    # Phase 1 stages r0 and r2 (two snapshot.stage firings); the THIRD
    # stage is r1's laggard convergence — fail it too.
    clean_failpoints.arm("snapshot.stage", "error", times=1, after=2)
    try:
        report = coordinator.rotate(next_dbs)
        assert report["laggards"] == {"r1": "dead"}
        assert rs.state("r1") == "dead"
        assert rs.export()["deaths"] == 1
        # The rest of the fleet committed and serves the new bits.
        for rid in ("r0", "r2"):
            r = rs.get(rid)
            assert r.serving_generation() == 1
            assert query_plain(r.leader, [3]) == [RECORDS1[3]]
        # The dead replica is out of the alive/rotatable set.
        assert sorted(r.replica_id for r in rs.alive()) == ["r0", "r2"]
    finally:
        close_all(replicas)


def test_two_party_replicas_rotate_helper_first():
    journal = EventJournal()
    rs = ReplicaSet(journal=journal)
    replicas = []
    for i in range(2):
        helper = HelperSession(
            build_db(RECORDS0), encrypt_decrypt.decrypt, make_config()
        )
        leader = LeaderSession(
            build_db(RECORDS0),
            InProcessTransport(helper.handle_wire),
            make_config(),
        )
        replica = Replica(
            f"pair{i}",
            leader,
            helper,
            leader_snapshots=SnapshotManager(
                leader, journal=EventJournal()
            ),
            helper_snapshots=SnapshotManager(
                helper, journal=EventJournal()
            ),
        )
        replicas.append(rs.add(replica))
    coordinator = FleetRotationCoordinator(rs, journal=journal)
    try:
        report = coordinator.rotate(next_dbs)
        assert report["to_generation"] == 1
        assert report["laggards"] == {}
        # Each pair's measured helper->leader flip window landed.
        for rid in ("pair0", "pair1"):
            assert report["per_replica"][rid]["staleness_ms"] >= 0.0
            assert report["per_replica"][rid]["helper_staged_bytes"] > 0
        client = DenseDpfPirClient.create(
            NUM_RECORDS, encrypt_decrypt.encrypt
        )
        for r in replicas:
            assert r.serving_generation() == 1
            assert r.helper_snapshots.serving_generation() == 1
            request, state = client.create_request([9, 41])
            response = r.leader.handle_request(request)
            assert client.handle_response(response, state) == [
                RECORDS1[9], RECORDS1[41],
            ]
    finally:
        close_all(replicas)


def test_rotation_requires_snapshot_managers():
    rs = ReplicaSet(journal=EventJournal())
    session = PlainSession(build_db(RECORDS0), make_config())
    try:
        rs.add(Replica("bare", session))  # no SnapshotManager
        coordinator = FleetRotationCoordinator(rs)
        with pytest.raises(ValueError, match="no rotatable replicas"):
            coordinator.rotate(next_dbs)
    finally:
        session.close()
