"""Observability tests: tracing, flight recorder, Prometheus exposition,
the admin endpoint, and cross-party trace propagation.

The end-to-end sections reuse the small serving fixture from
`test_serving_service` (128 x 16B records, real crypto) and the tiny
heavy-hitters domain from the demo smoke (8 bits, 2 levels), so the
traces asserted on here come out of the real Leader/Helper wire paths —
including the old-peer downgrade legs of both wire formats.
"""

import json
import threading
import urllib.error
import urllib.request

import numpy as np
import pytest

from distributed_point_functions_tpu import heavy_hitters as hh
from distributed_point_functions_tpu import serialization
from distributed_point_functions_tpu.observability import (
    AdminServer,
    exposition,
    propagation,
    tracing,
)
from distributed_point_functions_tpu.pir import (
    DenseDpfPirClient,
    DenseDpfPirDatabase,
)
from distributed_point_functions_tpu.protos import (
    private_information_retrieval_pb2 as pir_pb2,
)
from distributed_point_functions_tpu.serving import (
    FramedTcpServer,
    HelperSession,
    InProcessTransport,
    LeaderSession,
    ServingConfig,
    TcpTransport,
)
from distributed_point_functions_tpu.serving.metrics import (
    MetricsRegistry,
    labeled_name,
)
from distributed_point_functions_tpu.testing import encrypt_decrypt

NUM_RECORDS = 128
RECORD_BYTES = 16
RNG = np.random.default_rng(4321)


def build_database():
    records = [
        bytes(RNG.integers(0, 256, RECORD_BYTES, dtype=np.uint8))
        for _ in range(NUM_RECORDS)
    ]
    builder = DenseDpfPirDatabase.Builder()
    for r in records:
        builder.insert(r)
    return builder.build(), records


DATABASE, RECORDS = build_database()


def make_config(**overrides):
    base = dict(
        max_batch_size=4,
        max_wait_ms=5.0,
        helper_timeout_ms=None,
        helper_retries=2,
        helper_backoff_ms=1.0,
        helper_backoff_max_ms=2.0,
    )
    base.update(overrides)
    return ServingConfig(**base)


def leader_helper_pair(transport_factory):
    helper = HelperSession(DATABASE, encrypt_decrypt.decrypt, make_config())
    leader = LeaderSession(
        DATABASE, transport_factory(helper.handle_wire), make_config()
    )
    return leader, helper


def run_query(leader, indices):
    client = DenseDpfPirClient.create(NUM_RECORDS, encrypt_decrypt.encrypt)
    request, state = client.create_request(indices)
    response = leader.handle_request(request)
    return client.handle_response(response, state)


@pytest.fixture
def recorder():
    """Swap in a fresh default flight recorder for one test."""
    prev = tracing.default_recorder()
    rec = tracing.set_default_recorder(tracing.FlightRecorder())
    yield rec
    tracing.set_default_recorder(prev)


# ---------------------------------------------------------------------------
# Tracing core: trace_request / span / flight recorder
# ---------------------------------------------------------------------------


def test_trace_request_roots_records_and_spans(recorder):
    with tracing.trace_request("t.request", role="test") as trace:
        assert tracing.current_trace() is trace
        with tracing.span("stage_a", detail=7):
            pass
    assert tracing.current_trace() is None
    dump = recorder.dump()
    assert dump["recorded"] == 1
    (slow,) = dump["slowest"]
    assert slow["name"] == "t.request"
    assert slow["duration_ms"] >= 0
    assert slow["attrs"] == {"role": "test"}
    (span,) = slow["spans"]
    assert span["name"] == "stage_a"
    assert span["detail"] == 7


def test_nested_trace_reuses_outer_unless_fresh(recorder):
    with tracing.trace_request("outer") as outer:
        with tracing.trace_request("inner") as inner:
            assert inner is outer  # nested root reuses the active trace
        with tracing.trace_request(
            "server_side", trace_id=outer.trace_id, fresh=True
        ) as srv:
            assert srv is not outer
            assert srv.trace_id == outer.trace_id
            assert tracing.current_trace() is srv
        assert tracing.current_trace() is outer
    # The fresh server-side trace and the outer trace both recorded.
    assert recorder.dump()["recorded"] == 2


def test_errored_trace_lands_in_error_ring(recorder):
    with pytest.raises(ValueError, match="boom"):
        with tracing.trace_request("t.request"):
            raise ValueError("boom")
    dump = recorder.dump()
    assert not dump["slowest"]
    (err,) = dump["errors"]
    assert err["error"] == "ValueError: boom"


def _finished_trace(name, duration_ms):
    t = tracing.Trace(name)
    t.duration_ms = duration_ms
    return t


def test_flight_recorder_keeps_the_slowest_n():
    rec = tracing.FlightRecorder(max_slow=3, max_recent=2)
    for d in [5.0, 1.0, 3.0, 2.0, 4.0]:
        rec.record(_finished_trace(f"t{d}", d))
    dump = rec.dump()
    assert dump["recorded"] == 5
    assert [t["duration_ms"] for t in dump["slowest"]] == [5.0, 4.0, 3.0]
    assert len(dump["recent"]) == 2  # plain most-recent ring
    rec.clear()
    assert rec.dump() == {
        "recorded": 0, "slowest": [], "errors": [], "recent": [],
    }


def test_flight_recorder_disabled_is_noop():
    rec = tracing.FlightRecorder()
    rec.enabled = False
    rec.record(_finished_trace("t", 1.0))
    assert rec.dump()["recorded"] == 0


def test_add_span_from_another_thread(recorder):
    with tracing.trace_request("t") as trace:
        worker = threading.Thread(
            target=tracing.add_span, args=("cross_thread", 2.5, trace)
        )
        worker.start()
        worker.join()
    (slow,) = recorder.dump()["slowest"]
    assert [s["name"] for s in slow["spans"]] == ["cross_thread"]


def test_stage_summary_aggregates_spans():
    tracing.reset_stages()
    for _ in range(3):
        with tracing.span("agg_stage"):
            pass
    summary = tracing.stage_summary()["agg_stage"]
    assert summary["count"] == 3
    assert summary["total_ms"] >= 0
    assert set(summary) >= {"mean_ms", "p50_ms", "p95_ms", "max_ms"}
    tracing.reset_stages()
    assert "agg_stage" not in tracing.stage_summary()


def test_counter_group():
    group = tracing.CounterGroup()
    group.inc("a")
    group.inc("a", 4)
    group.inc("b")
    assert group.get("a") == 5
    assert group.export() == {"a": 5, "b": 1}
    group.reset()
    assert group.export() == {}


# ---------------------------------------------------------------------------
# Metrics labels and histogram export
# ---------------------------------------------------------------------------


def test_labeled_name_convention():
    assert labeled_name("req") == "req"
    assert labeled_name("req", {"role": "leader", "b": 1}) == (
        "req{b=1,role=leader}"  # keys sorted -> stable instrument name
    )
    with pytest.raises(ValueError, match="reserved"):
        labeled_name("req", {"role": "a,b"})
    with pytest.raises(ValueError, match="reserved"):
        labeled_name("req", {"k=v": "x"})


def test_registry_labels_create_distinct_instruments():
    reg = MetricsRegistry()
    reg.counter("req", labels={"role": "leader"}).inc(2)
    reg.counter("req", labels={"role": "helper"}).inc()
    with reg.timed("lat_ms", labels={"role": "leader"}):
        pass
    export = reg.export()
    assert export["counters"]["req{role=leader}"] == 2
    assert export["counters"]["req{role=helper}"] == 1
    assert export["histograms"]["lat_ms{role=leader}"]["count"] == 1


def test_histogram_export_percentiles_consistent():
    reg = MetricsRegistry()
    hist = reg.histogram("h", buckets=(10.0, 50.0))
    for v in range(1, 101):
        hist.observe(float(v))
    out = hist.export()
    assert out["count"] == 100
    assert out["sum"] == 5050.0
    # Nearest-rank on the sorted reservoir: round(0.5 * 99) = 50 -> 51.0.
    assert out["p50"] == 51.0
    assert out["p95"] == 95.0
    assert out["max"] == 100.0
    assert out["buckets"] == {"10.0": 10, "50.0": 40, "+inf": 50}
    assert hist.percentile(99) == 99.0


# ---------------------------------------------------------------------------
# Prometheus exposition
# ---------------------------------------------------------------------------


def test_parse_labeled_name():
    assert exposition.parse_labeled_name("req") == ("req", {})
    assert exposition.parse_labeled_name("req{role=leader,lvl=2}") == (
        "req", {"role": "leader", "lvl": "2"}
    )
    # Malformed label bodies degrade instead of raising.
    base, labels = exposition.parse_labeled_name("req{oops}")
    assert labels == {} and "{" not in base


def test_render_prometheus_counters_and_gauges():
    text = exposition.render_prometheus({
        "counters": {"a.b": 2, "req{role=leader}": 1},
        "gauges": {"depth": 1.5},
        "histograms": {},
    })
    lines = text.splitlines()
    assert "# TYPE dpf_a_b counter" in lines
    assert "dpf_a_b 2" in lines
    assert "# TYPE dpf_req counter" in lines
    assert 'dpf_req{role="leader"} 1' in lines
    assert "# TYPE dpf_depth gauge" in lines
    assert "dpf_depth 1.5" in lines


def test_render_prometheus_histogram_buckets_cumulative():
    text = exposition.render_prometheus({
        "counters": {},
        "gauges": {},
        "histograms": {
            "lat": {
                "count": 3,
                "sum": 7.0,
                "buckets": {"1.0": 1, "2.0": 1, "+inf": 1},
            }
        },
    })
    lines = text.splitlines()
    assert "# TYPE dpf_lat histogram" in lines
    # Per-bucket increments re-accumulate to cumulative counts.
    assert 'dpf_lat_bucket{le="1"} 1' in lines
    assert 'dpf_lat_bucket{le="2"} 2' in lines
    assert 'dpf_lat_bucket{le="+Inf"} 3' in lines
    assert "dpf_lat_sum 7" in lines
    assert "dpf_lat_count 3" in lines
    # The +Inf bucket is last of the bucket series (cumulativity holds).
    buckets = [ln for ln in lines if "_bucket" in ln]
    assert buckets[-1] == 'dpf_lat_bucket{le="+Inf"} 3'


def test_render_prometheus_escapes_label_values():
    text = exposition.render_prometheus(
        {"counters": {'x{k=he"y}': 1}, "gauges": {}, "histograms": {}}
    )
    assert 'dpf_x{k="he\\"y"} 1' in text


def test_histogram_records_exemplar_inside_active_trace(recorder):
    reg = MetricsRegistry()
    with tracing.trace_request("req", recorder=recorder) as trace:
        reg.histogram("lat_ms").observe(42.0)
    export = reg.export()["histograms"]["lat_ms"]
    (bucket,) = export["exemplars"].keys()
    exemplar = export["exemplars"][bucket]
    assert exemplar["value"] == 42.0
    assert exemplar["trace_id"] == trace.trace_id
    assert float(bucket) >= 42.0  # lands on its own bucket bound


def test_histogram_without_trace_has_no_exemplars():
    reg = MetricsRegistry()
    reg.histogram("lat_ms").observe(42.0)
    assert "exemplars" not in reg.export()["histograms"]["lat_ms"]


def test_render_prometheus_exemplar_on_bucket_line(recorder):
    reg = MetricsRegistry()
    with tracing.trace_request("req", recorder=recorder) as trace:
        reg.histogram("lat_ms", buckets=(50.0, 100.0)).observe(42.0)
    reg.histogram("lat_ms", buckets=(50.0, 100.0)).observe(60.0)
    text = exposition.render_prometheus(reg.export())
    exemplar_lines = [
        ln for ln in text.splitlines() if "# {trace_id=" in ln
    ]
    (line,) = exemplar_lines  # only the traced bucket carries one
    assert line.startswith('dpf_lat_ms_bucket{le="50"}')
    assert f'# {{trace_id="{trace.trace_id}"}} 42' in line


# ---------------------------------------------------------------------------
# Trace-context envelope codec
# ---------------------------------------------------------------------------


def test_envelope_request_roundtrip_and_bare_passthrough():
    tid = tracing.new_trace_id()
    wrapped = propagation.encode_request(tid, b"inner-proto")
    assert propagation.try_decode_request(wrapped) == (tid, b"inner-proto")
    # 0xFF first byte: an old peer's proto parser rejects this payload.
    assert wrapped[0] == 0xFF
    # Bare payloads (old-version peers) pass through untouched.
    assert propagation.try_decode_request(b"\x0abare") == (None, b"\x0abare")
    with pytest.raises(propagation.EnvelopeError, match="body"):
        propagation.try_decode_request(wrapped + b"extra")


def test_envelope_response_roundtrip():
    tid = tracing.new_trace_id()
    spans = [{"name": "device_compute", "duration_ms": 1.25, "extra": "x"}]
    wrapped = propagation.encode_response(
        b"reply", tid, server_ms=3.5, spans=spans
    )
    meta, inner = propagation.try_decode_response(wrapped)
    assert inner == b"reply"
    assert meta["trace_id"] == tid
    assert meta["server_ms"] == 3.5
    assert meta["spans"] == [
        {"name": "device_compute", "duration_ms": 1.25}
    ]
    assert propagation.try_decode_response(b"bare") == (None, b"bare")


def test_envelope_v2_digest_rides_only_v2():
    """The critical-path digest (phases + recv/send timestamps +
    per-span offsets) is a v2-only extension: the identical encode call
    at v1 is byte-equal to the pre-digest encoder, so the downgrade
    ladder drops the digest and nothing else."""
    tid = tracing.new_trace_id()
    spans = [
        {"name": "device_compute", "duration_ms": 1.0, "offset_ms": 0.5}
    ]
    digest = dict(
        phases={"device_compute": 1.0, "respond": 0.25},
        recv_ms=10.0,
        send_ms=12.0,
    )
    meta, inner = propagation.try_decode_response(
        propagation.encode_response(
            b"r", tid, server_ms=2.0, spans=spans, **digest
        )
    )
    assert inner == b"r"
    assert meta["phases"] == {"device_compute": 1.0, "respond": 0.25}
    assert (meta["recv_ms"], meta["send_ms"]) == (10.0, 12.0)
    assert meta["spans"][0]["offset_ms"] == 0.5
    v1 = propagation.encode_response(
        b"r", tid, server_ms=2.0, spans=spans, version=1, **digest
    )
    assert v1 == propagation.encode_response(
        b"r", tid, server_ms=2.0, spans=spans, version=1
    )
    meta1, inner1 = propagation.try_decode_response(v1)
    assert inner1 == b"r"  # the inner share is never the casualty
    assert "phases" not in meta1 and "recv_ms" not in meta1
    assert meta1["server_ms"] == 2.0
    assert meta1["spans"] == [
        {"name": "device_compute", "duration_ms": 1.0}
    ]


def test_envelope_response_span_list_is_bounded():
    tid = tracing.new_trace_id()
    cap = propagation.MAX_RESPONSE_SPANS
    spans = [
        {"name": f"s{i}", "duration_ms": 1.0} for i in range(cap + 9)
    ]
    before = tracing.runtime_counters.export().get(
        "propagation.spans_dropped", 0
    )
    meta, _ = propagation.try_decode_response(
        propagation.encode_response(b"x", tid, server_ms=0.0, spans=spans)
    )
    assert len(meta["spans"]) == cap
    assert meta["spans"][0]["name"] == "s0"  # chronological head kept
    after = tracing.runtime_counters.export()["propagation.spans_dropped"]
    assert after - before == 9


def test_add_span_clamps_negative_offset(recorder):
    with tracing.trace_request("t.clamp") as trace:
        trace.add_span("rewound", 1.0, offset_ms=-5.0)
        trace.add_span("normal", 1.0, offset_ms=2.0)
        trace.add_remote_spans(
            [{"name": "early", "duration_ms": 0.5, "offset_ms": 1.0}],
            prefix="helper.",
            base_offset_ms=-3.0,
        )
        spans = trace.span_list()
    rewound = next(s for s in spans if s["name"] == "rewound")
    assert rewound["offset_ms"] == 0.0 and rewound["clamped"] is True
    normal = next(s for s in spans if s["name"] == "normal")
    assert normal["offset_ms"] == 2.0 and "clamped" not in normal
    early = next(s for s in spans if s["name"] == "helper.early")
    assert early["offset_ms"] == 0.0 and early["clamped"] is True


# ---------------------------------------------------------------------------
# Admin endpoint
# ---------------------------------------------------------------------------


def test_admin_endpoints_serve_metrics_varz_tracez(recorder):
    reg = MetricsRegistry()
    reg.counter("admin.hits", labels={"role": "leader"}).inc(3)
    with tracing.trace_request("admin.request"):
        with reg.timed("admin.request_ms"):
            with tracing.span("device_compute"):
                pass
    tracing.runtime_counters.inc("pir.plan.materialized")
    try:
        with AdminServer(registry=reg, recorder=recorder) as admin:
            base = f"http://127.0.0.1:{admin.port}"

            assert urllib.request.urlopen(base + "/healthz").read() == b"ok\n"

            resp = urllib.request.urlopen(base + "/metrics")
            assert resp.headers["Content-Type"].startswith(
                "text/plain; version=0.0.4"
            )
            text = resp.read().decode()
            assert 'dpf_admin_hits{role="leader"} 3' in text
            assert "# TYPE dpf_admin_request_ms histogram" in text
            assert "dpf_admin_request_ms_count 1" in text
            # Runtime counters (layers below serving) merge in too.
            assert "dpf_pir_plan_materialized" in text

            varz = json.load(urllib.request.urlopen(base + "/varz"))
            assert varz["metrics"]["counters"]["admin.hits{role=leader}"] == 3
            assert "device_compute" in varz["stages"]
            assert varz["uptime_s"] >= 0

            tracez = json.load(urllib.request.urlopen(base + "/tracez"))
            assert tracez["recorded"] == 1
            assert tracez["slowest"][0]["name"] == "admin.request"
            names = [s["name"] for s in tracez["slowest"][0]["spans"]]
            assert "device_compute" in names

            with pytest.raises(urllib.error.HTTPError) as e:
                urllib.request.urlopen(base + "/nope")
            assert e.value.code == 404
    finally:
        tracing.runtime_counters.reset()


# ---------------------------------------------------------------------------
# Serving Leader/Helper: trace propagation and envelope interop
# ---------------------------------------------------------------------------


def _assert_leader_trace_decomposed(dump):
    """The acceptance-criterion shape: one Leader trace whose spans
    split latency into queue wait / device compute / helper leg, with
    the Helper's server-side spans grafted on under `helper.`."""
    traces = dump["slowest"] + dump["recent"]
    leader = next(t for t in traces if t["name"] == "leader.request")
    names = [s["name"] for s in leader["spans"]]
    assert "queue_wait" in names
    assert "device_compute" in names
    assert "leader_own_share" in names
    assert "helper_leg" in names
    helper_leg = next(
        s for s in leader["spans"] if s["name"] == "helper_leg"
    )
    # Helper-reported compute vs. the rest of the RTT (the network).
    assert "remote_ms" in helper_leg and "network_ms" in helper_leg
    remote = [n for n in names if n.startswith("helper.")]
    assert "helper.device_compute" in remote
    # The Helper's own server-side trace shares the Leader's trace id.
    helper = next(t for t in traces if t["name"] == "helper.request")
    assert helper["trace_id"] == leader["trace_id"]
    return leader


def test_trace_propagates_in_process(recorder):
    leader, helper = leader_helper_pair(InProcessTransport)
    with helper, leader:
        got = run_query(leader, [3, 99])
    assert got == [RECORDS[3], RECORDS[99]]
    assert leader._peer_envelope is True
    _assert_leader_trace_decomposed(recorder.dump())
    assert leader.metrics.export()["counters"]["leader.wire_downgrades"] == 0
    assert (
        leader.metrics.export()["histograms"]["leader.helper_remote_ms"][
            "count"
        ]
        == 1
    )


def test_trace_propagates_over_tcp(recorder):
    helper = HelperSession(DATABASE, encrypt_decrypt.decrypt, make_config())
    server = FramedTcpServer(
        helper.handle_wire, port=0, name="obs-helper"
    ).start()
    transport = TcpTransport("localhost", server.port)
    leader = LeaderSession(DATABASE, transport, make_config())
    try:
        with helper, leader:
            got = run_query(leader, [7, 42])
    finally:
        transport.close()
        server.stop()
    assert got == [RECORDS[7], RECORDS[42]]
    assert leader._peer_envelope is True
    leader_trace = _assert_leader_trace_decomposed(recorder.dump())
    assert leader_trace["duration_ms"] > 0


def test_old_helper_downgrades_leader_to_bare_proto(recorder):
    helper = HelperSession(DATABASE, encrypt_decrypt.decrypt, make_config())

    def old_helper(data):
        # An old-version Helper proto-parses the payload directly; the
        # envelope's 0xFF lead byte makes that fail before any handling.
        pir_pb2.PirRequest.FromString(data)
        return helper.handle_wire(data)

    leader = LeaderSession(
        DATABASE, InProcessTransport(old_helper), make_config()
    )
    with helper, leader:
        got = run_query(leader, [5, 64])
        # A second query must go out bare immediately (downgrade sticks).
        got2 = run_query(leader, [6])
        counters = leader.metrics.export()["counters"]
    assert got == [RECORDS[5], RECORDS[64]]
    assert got2 == [RECORDS[6]]
    assert leader._peer_envelope is False
    # Stepwise ladder: v3 -> v2 -> v1 -> bare, one downgrade per fault.
    assert counters["leader.wire_downgrades"] == 3
    # The probe faults did not consume a retry attempt.
    assert counters["leader.helper_retries"] == 0
    assert counters["leader.helper_failures"] == 0


def _v1_envelope_only(handler):
    """Wrap a Helper handler as a v1-envelope-era peer: v2 requests are
    rejected the way an old build would (envelope magic known, version
    byte not), bare and v1 traffic passes through."""

    def guard(payload):
        if payload.startswith(b"\xffDPT") and payload[4] != 1:
            raise propagation.EnvelopeError(
                f"unsupported envelope version {payload[4]}"
            )
        return handler(payload)

    return guard


def test_new_leader_steps_down_to_v1_helper_keeping_spans(recorder):
    """Decode matrix, new Leader x old (v1-envelope) Helper: two ladder
    steps (v3 -> v2 -> v1), and the downgrade drops only the generation
    echo and the digest — the inner share, server_ms split, and remote
    spans all survive at v1."""
    helper = HelperSession(DATABASE, encrypt_decrypt.decrypt, make_config())
    leader = LeaderSession(
        DATABASE,
        InProcessTransport(_v1_envelope_only(helper.handle_wire)),
        make_config(),
    )
    with helper, leader:
        got = run_query(leader, [5, 64])
        got2 = run_query(leader, [6])
        counters = leader.metrics.export()["counters"]
    assert got == [RECORDS[5], RECORDS[64]]
    assert got2 == [RECORDS[6]]
    assert leader._peer_envelope is True  # still an enveloped peer
    assert leader._peer_wire_version == 1  # ...pinned at v1, sticky
    assert counters["leader.wire_downgrades"] == 2
    assert counters["leader.helper_retries"] == 0
    # v1 keeps server_ms + spans, so the remote/network split and the
    # grafted helper.* spans are intact.
    leader_trace = _assert_leader_trace_decomposed(recorder.dump())
    helper_leg = next(
        s for s in leader_trace["spans"] if s["name"] == "helper_leg"
    )
    # ...but the digest is gone: no skew estimate on the leg.
    assert "offset_ms_est" not in helper_leg


def test_new_helper_answers_v1_requests_in_v1(recorder):
    """Decode matrix, old (v1) Leader x new Helper: the Helper answers
    in the request's version, so a v1 peer never sees v2 fields."""
    helper = HelperSession(DATABASE, encrypt_decrypt.decrypt, make_config())
    replies = []

    def capture(payload):
        out = helper.handle_wire(payload)
        replies.append(out)
        return out

    # helper_digest=False pins this Leader's envelope at v1 — from the
    # Helper's side it is indistinguishable from an old build.
    leader = LeaderSession(
        DATABASE,
        InProcessTransport(capture),
        make_config(helper_digest=False),
    )
    with helper, leader:
        got = run_query(leader, [8])
    assert got == [RECORDS[8]]
    assert leader.metrics.export()["counters"]["leader.wire_downgrades"] == 0
    assert replies and replies[-1][4] == 1  # version byte: answered v1
    meta, inner = propagation.try_decode_response(replies[-1])
    assert inner  # the share rode along
    assert meta["server_ms"] >= 0.0 and meta["spans"]
    assert "phases" not in meta
    assert "recv_ms" not in meta and "send_ms" not in meta


def test_new_leader_serves_old_bare_proto_clients(recorder):
    """An old client speaks bare proto to `handle_wire`; the reply must
    come back bare (no envelope magic) and parse as a plain proto."""
    leader, helper = leader_helper_pair(InProcessTransport)
    client = DenseDpfPirClient.create(NUM_RECORDS, encrypt_decrypt.encrypt)
    request, state = client.create_request([11])
    wire = serialization.pir_request_to_proto(
        client.dpf, request
    ).SerializeToString()
    with helper, leader:
        reply = leader.handle_wire(wire)
    assert not reply.startswith(b"\xffDPT")
    response = serialization.pir_response_from_proto(
        pir_pb2.PirResponse.FromString(reply)
    )
    assert client.handle_response(response, state) == [RECORDS[11]]


# ---------------------------------------------------------------------------
# Heavy-hitters wire v2: codec, propagation, and v1 interop
# ---------------------------------------------------------------------------

HH_CONFIG = hh.HeavyHittersConfig(domain_bits=8, level_bits=4, threshold=2)
HH_VALUES = [3, 3, 3, 77, 77, 200, 9, 9, 14]


@pytest.fixture(scope="module")
def hh_keys():
    client = hh.HeavyHittersClient(HH_CONFIG)
    pairs = [client.generate_report(v) for v in HH_VALUES]
    return [p[0] for p in pairs], [p[1] for p in pairs]


def test_hh_wire_v2_codec_roundtrip():
    frontier = np.array([0, 5, 1 << 40], dtype=np.uint64)
    tid = tracing.new_trace_id()
    req = hh.encode_eval_request(3, frontier, trace_id=tid, version=2)
    r, decoded, version, got_tid = hh.decode_eval_request_full(req)
    assert (r, version, got_tid) == (3, 2, tid)
    np.testing.assert_array_equal(decoded, frontier)
    # No trace id -> zeros on the wire -> None on decode.
    _, _, _, none_tid = hh.decode_eval_request_full(
        hh.encode_eval_request(3, frontier, version=2)
    )
    assert none_tid is None

    shares = np.array([7, 0, 0xFFFFFFFF], dtype=np.uint32)
    resp = hh.encode_eval_response(3, shares, helper_ms=12.5, version=2)
    (
        r,
        decoded,
        version,
        helper_ms,
        epoch,
        timing,
    ) = hh.decode_eval_response_full(resp)
    assert (r, version, helper_ms, epoch, timing) == (3, 2, 12.5, None, None)
    np.testing.assert_array_equal(decoded, shares)

    # The 2-tuple decoders keep working for every version.
    assert hh.decode_eval_response(resp)[0] == 3
    v1_req = hh.encode_eval_request(1, frontier, version=1)
    r, decoded = hh.decode_eval_request(v1_req)
    assert r == 1
    np.testing.assert_array_equal(decoded, frontier)
    # v1 requests carry no extension: 8 bytes shorter than v2.
    assert len(v1_req) + 8 == len(
        hh.encode_eval_request(1, frontier, version=2)
    )

    with pytest.raises(hh.ProtocolError, match="v2 extension"):
        hh.decode_eval_request_full(req[:20])
    with pytest.raises(ValueError, match="wire version"):
        hh.encode_eval_request(0, frontier, version=5)


def _hh_oracle():
    return hh.plaintext_heavy_hitters(HH_VALUES, HH_CONFIG)


def test_hh_v2_sweep_propagates_trace_and_helper_timing(recorder, hh_keys):
    keys0, keys1 = hh_keys
    helper = hh.HeavyHittersHelper(hh.HeavyHittersServer(HH_CONFIG, keys1))
    leader = hh.HeavyHittersLeader(
        hh.HeavyHittersServer(HH_CONFIG, keys0),
        InProcessTransport(helper.handle_wire),
    )
    result = leader.run()
    assert result.as_dict() == _hh_oracle()
    assert leader.wire_version == 4
    snap = leader.metrics.export()
    assert snap["counters"]["hh.wire_downgrades"] == 0
    rounds = snap["counters"]["hh.rounds"]
    assert snap["histograms"]["hh.helper_remote_ms"]["count"] == rounds
    assert snap["histograms"]["hh.helper_network_ms"]["count"] == rounds

    dump = recorder.dump()
    traces = dump["slowest"] + dump["recent"]
    sweep = next(t for t in traces if t["name"] == "hh.sweep")
    legs = [s for s in sweep["spans"] if s["name"] == "helper_leg"]
    assert len(legs) == rounds
    assert all("remote_ms" in s and "network_ms" in s for s in legs)
    assert any(s["name"] == "leader_own_share" for s in sweep["spans"])
    # Each Helper round rooted a server-side trace under the sweep's id
    # (count in the recent ring only — slow traces appear in both lists).
    helper_rounds = [
        t for t in dump["recent"] if t["name"] == "hh.helper.round"
    ]
    assert len(helper_rounds) == rounds
    assert all(t["trace_id"] == sweep["trace_id"] for t in helper_rounds)


def _v1_only(handler):
    """Wrap a Helper handler as a v1-only peer: any v2 message is
    rejected the way an old build would (before reaching the server)."""

    def guard(payload):
        if len(payload) >= 5 and payload[4] != 1:
            raise hh.ProtocolError(
                f"unsupported wire version {payload[4]}"
            )
        return handler(payload)

    return guard


def test_hh_leader_downgrades_for_v1_helper_in_process(hh_keys):
    keys0, keys1 = hh_keys
    helper = hh.HeavyHittersHelper(hh.HeavyHittersServer(HH_CONFIG, keys1))
    leader = hh.HeavyHittersLeader(
        hh.HeavyHittersServer(HH_CONFIG, keys0),
        InProcessTransport(_v1_only(helper.handle_wire)),
    )
    result = leader.run()
    assert result.as_dict() == _hh_oracle()
    assert leader.wire_version == 1
    # Stepwise: v4 -> v3 -> v2 -> v1, one downgrade per rejected probe.
    assert leader.metrics.export()["counters"]["hh.wire_downgrades"] == 3
    # v1 responses carry no helper timing, so no remote/network split.
    assert "hh.helper_remote_ms" not in leader.metrics.export()["histograms"]


def test_hh_leader_downgrades_for_v1_helper_over_tcp(hh_keys):
    keys0, keys1 = hh_keys
    helper = hh.HeavyHittersHelper(hh.HeavyHittersServer(HH_CONFIG, keys1))
    server = FramedTcpServer(
        _v1_only(helper.handle_wire), port=0, name="hh-v1-helper"
    ).start()
    transport = TcpTransport("localhost", server.port)
    leader = hh.HeavyHittersLeader(
        hh.HeavyHittersServer(HH_CONFIG, keys0), transport
    )
    try:
        # Over TCP the v1 peer's rejection surfaces as a dropped
        # connection (TransportError), the other downgrade trigger.
        result = leader.run()
    finally:
        transport.close()
        server.stop()
    assert result.as_dict() == _hh_oracle()
    assert leader.wire_version == 1
    assert leader.metrics.export()["counters"]["hh.wire_downgrades"] == 3


def test_hh_helper_answers_v1_leaders_in_v1(hh_keys):
    _, keys1 = hh_keys
    helper = hh.HeavyHittersHelper(hh.HeavyHittersServer(HH_CONFIG, keys1))
    frontier = np.arange(16, dtype=np.uint64)
    reply = helper.handle_wire(
        hh.encode_eval_request(0, frontier, version=1)
    )
    assert reply[4] == 1  # version byte: the Helper answered in v1
    (
        r,
        shares,
        version,
        helper_ms,
        epoch,
        timing,
    ) = hh.decode_eval_response_full(reply)
    assert (r, version, helper_ms, epoch, timing) == (0, 1, None, None, None)
    assert shares.shape == (16,)


def test_statusz_renders_circuit_breaker_rows():
    from distributed_point_functions_tpu.observability.admin import (
        AdminServer,
    )
    from distributed_point_functions_tpu.robustness import CircuitBreaker

    breaker = CircuitBreaker(
        failure_threshold=1, reset_timeout_ms=60_000.0, name="leader.helper"
    )
    breaker.record_failure()  # drive it open for the breach styling
    assert breaker.state == "open"

    class SessionShim:
        # Mirrors LeaderSession.breaker_export(): breaker export plus
        # the degraded-mode flag the /statusz row shows alongside it.
        def export(self):
            out = breaker.export()
            out["degraded_mode"] = True
            return out

    with AdminServer(
        registry=MetricsRegistry(), breakers={"leader.helper": SessionShim()}
    ) as admin:
        base = f"http://127.0.0.1:{admin.port}"
        html = urllib.request.urlopen(base + "/statusz").read().decode()
        assert "Circuit breakers" in html
        assert "leader.helper" in html
        assert "open" in html

        state = json.load(
            urllib.request.urlopen(base + "/statusz?format=json")
        )
        row = state["breakers"]["leader.helper"]
        assert row["state"] == "open"
        assert row["state_code"] == 2
        assert row["degraded_mode"] is True


# ---------------------------------------------------------------------------
# Route table: the 404 index is generated, never hand-maintained
# ---------------------------------------------------------------------------


def test_404_endpoint_index_matches_dispatched_routes():
    with AdminServer(registry=MetricsRegistry()) as admin:
        base = f"http://127.0.0.1:{admin.port}"
        with pytest.raises(urllib.error.HTTPError) as e:
            urllib.request.urlopen(base + "/definitely_not_a_route")
        assert e.value.code == 404
        body = e.value.read().decode()
        # The advertised index is generated from the same route table
        # `_route` dispatches on — exactly, in order.
        advertised = body.split("try ", 1)[1].split()
        assert advertised == list(admin.routes)
        assert "/utilz" in advertised and "/timeseriesz" in advertised
        # And every advertised path really dispatches: none of them
        # falls through to the unknown-endpoint reply (optional
        # surfaces may 404 with their own "not attached" message).
        for path in admin.routes:
            url = base + path
            if path == "/profilez":
                url += "?duration_ms=1"
            try:
                urllib.request.urlopen(url).read()
            except urllib.error.HTTPError as err:
                assert "unknown endpoint" not in err.read().decode(), path


def test_utilz_reports_live_closed_loop_duty_cycle():
    from distributed_point_functions_tpu.observability.utilization import (
        UtilizationTracker,
        default_utilization_tracker,
        set_default_utilization_tracker,
    )

    prev = default_utilization_tracker()
    tracker = set_default_utilization_tracker(
        UtilizationTracker(window_s=60.0)
    )
    try:
        leader, helper = leader_helper_pair(InProcessTransport)
        try:
            for i in range(6):
                values = run_query(leader, [i])
                assert values[0] == RECORDS[i]
        finally:
            leader.close()
            helper.close()
        with AdminServer(
            registry=leader.metrics, utilization=tracker
        ) as admin:
            base = f"http://127.0.0.1:{admin.port}"
            state = json.load(
                urllib.request.urlopen(base + "/utilz?format=json")
            )
        # The real batcher worker reported: evaluations became busy
        # time and the waits became typed bubbles whose causes sum to
        # the measured idle total.
        tracked = state["current"]
        totals = state["totals"]
        busy = totals["busy_s"] + tracked["busy_s"]
        idle = totals["idle_total_s"] + tracked["idle_total_s"]
        assert busy > 0.0
        assert idle > 0.0
        causes = dict(totals["idle_s"])
        for cause, s in tracked["idle_s"].items():
            causes[cause] = causes.get(cause, 0.0) + s
        # Causes sum to the measured idle total (within the export's
        # per-cause 6-decimal rounding).
        assert sum(causes.values()) == pytest.approx(idle, abs=1e-4)
        assert set(causes) <= {
            "empty_queue", "admission_shed", "batch_wait",
            "pipeline_full", "staging_sync", "helper_rtt",
            "snapshot_flip", "other",
        }
        # The helper leg reported its exposed RTT barrier.
        assert "leader" in state["threads"] or "helper_rtt" in causes
    finally:
        set_default_utilization_tracker(prev)
