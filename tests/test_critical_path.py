"""Cross-party critical-path tests: NTP-style skew estimation, the
helper_rtt decomposition, the two-party timeline DAG, the analyzer /
`/criticalz` surface, and the in-process acceptance criterion (on
`InProcessTransport` the decomposition must attribute helper_net ~ 0
and helper_queue + helper_compute ~ the exchange rtt, within the
estimator's own stated uncertainty)."""

import json
import urllib.error
import urllib.request

import numpy as np
import pytest

from distributed_point_functions_tpu.observability import (
    AdminServer,
    critical_path as cp,
    phases as phases_mod,
    tracing,
)
from distributed_point_functions_tpu.pir import (
    DenseDpfPirClient,
    DenseDpfPirDatabase,
)
from distributed_point_functions_tpu.serving import (
    HelperSession,
    InProcessTransport,
    LeaderSession,
    ServingConfig,
)
from distributed_point_functions_tpu.serving.metrics import MetricsRegistry
from distributed_point_functions_tpu.testing import encrypt_decrypt

# ---------------------------------------------------------------------------
# Skew estimation
# ---------------------------------------------------------------------------

# One synthetic exchange: the Helper clock runs 100 ms ahead, each wire
# leg takes 2 ms, the Helper holds the request for 6 ms.
#   t0=0 (send), t1=102 (helper recv), t2=108 (helper send), t3=10.
_T0, _T1, _T2, _T3 = 0.0, 102.0, 108.0, 10.0


def test_estimate_skew_recovers_offset_and_uncertainty():
    skew = cp.estimate_skew(_T0, _T3, _T1, _T2)
    assert skew.valid
    assert skew.offset_ms == pytest.approx(100.0)
    assert skew.rtt_ms == pytest.approx(10.0)
    assert skew.exchange_ms == pytest.approx(10.0)
    assert skew.helper_service_ms == pytest.approx(6.0)
    # Exact bound: the unseen quantity is the outbound/return split of
    # the 4 ms of non-service time, so the offset error is within 2 ms.
    assert skew.uncertainty_ms == pytest.approx(2.0)


def test_estimate_skew_negative_offset():
    # Helper clock 50 ms BEHIND the Leader's: t1=-48, t2=-42.
    skew = cp.estimate_skew(0.0, 10.0, -48.0, -42.0)
    assert skew.valid
    assert skew.offset_ms == pytest.approx(-50.0)
    assert skew.uncertainty_ms == pytest.approx(2.0)
    decomp = cp.decompose_helper_leg(skew, {"device_compute": 6.0})
    assert decomp is not None
    assert decomp["helper_net_ms"] == pytest.approx(4.0)


def test_estimate_skew_subtracts_own_share_overlap():
    # 4 ms of the bracket was the Leader's own-share compute running
    # inline (InProcessTransport): the exchange rtt excludes it, so the
    # wire estimate tightens to exactly the service time.
    skew = cp.estimate_skew(_T0, _T3, _T1, _T2, overlap_ms=4.0)
    assert skew.valid
    assert skew.exchange_ms == pytest.approx(6.0)
    assert skew.uncertainty_ms == pytest.approx(0.0)
    decomp = cp.decompose_helper_leg(skew, {"device_compute": 6.0})
    assert decomp["helper_net_ms"] == pytest.approx(0.0)


def test_concurrent_overlap_is_capped_not_refused():
    # Threaded transport (real TCP): the own share runs CONCURRENTLY
    # with the Helper's 6 ms service, so the claimed 8 ms overlap
    # cannot all have been serial — raw subtraction would push the
    # exchange below the service floor and refuse every split. The
    # serial part is capped at rtt - service (wire time cannot be
    # negative); the 4 ms concurrent remainder widens the uncertainty
    # instead of vanishing.
    skew = cp.estimate_skew(_T0, _T3, _T1, _T2, overlap_ms=8.0)
    assert skew.valid
    assert skew.exchange_ms == pytest.approx(6.0)  # clamped to service
    # (exchange - service)/2 = 0 plus min(hidden=4, rtt-exchange=4)/2.
    assert skew.uncertainty_ms == pytest.approx(2.0)
    decomp = cp.decompose_helper_leg(skew, {"device_compute": 6.0})
    assert decomp is not None
    assert decomp["helper_net_ms"] == pytest.approx(0.0)
    assert decomp["helper_queue_ms"] + decomp["helper_compute_ms"] == (
        pytest.approx(skew.exchange_ms)
    )


def test_decompose_identity_and_queue_split():
    skew = cp.estimate_skew(_T0, _T3, _T1, _T2)
    decomp = cp.decompose_helper_leg(
        skew, {"device_compute": 3.0, "dispatch": 1.0, "respond": 9.0}
    )
    # respond is not a compute phase; compute = 3 + 1, queue the rest.
    assert decomp["helper_compute_ms"] == pytest.approx(4.0)
    assert decomp["helper_queue_ms"] == pytest.approx(2.0)
    assert decomp["helper_net_ms"] == pytest.approx(4.0)
    total = (
        decomp["helper_net_ms"]
        + decomp["helper_queue_ms"]
        + decomp["helper_compute_ms"]
    )
    assert total == pytest.approx(skew.exchange_ms)
    assert decomp["uncertain"] is False
    # An over-reported digest is capped at the service time.
    capped = cp.decompose_helper_leg(skew, {"device_compute": 50.0})
    assert capped["helper_compute_ms"] == pytest.approx(6.0)
    assert capped["helper_queue_ms"] == pytest.approx(0.0)


def test_jitter_dominating_service_is_flagged_not_bogus():
    # rtt 10 ms around a 0.1 ms service: the estimate is still valid
    # (the split exists) but the uncertainty (4.95 ms) dwarfs the
    # service time being split — `uncertain` must say so.
    skew = cp.estimate_skew(0.0, 10.0, 100.0, 100.1)
    assert skew.valid
    decomp = cp.decompose_helper_leg(skew, {"device_compute": 0.1})
    assert decomp is not None
    assert decomp["uncertain"] is True
    assert decomp["uncertainty_ms"] == pytest.approx(4.95)


def test_service_exceeding_exchange_refuses_to_split():
    # Clock-granularity jitter: the Helper claims more service time
    # than the whole exchange. No clamped-but-confident split.
    skew = cp.estimate_skew(0.0, 5.0, 100.0, 110.0)
    assert not skew.valid
    assert cp.decompose_helper_leg(skew, {"device_compute": 9.0}) is None
    # Negative rtt (caller bug / non-monotonic inputs): same refusal.
    assert not cp.estimate_skew(10.0, 0.0, 100.0, 101.0).valid
    assert cp.decompose_helper_leg(None, {}) is None


# ---------------------------------------------------------------------------
# Timeline DAG
# ---------------------------------------------------------------------------


def _leg(rtt=8.0, own=3.0, net=2.0, queue=2.0, compute=4.0):
    return {
        "rtt_ms": rtt,
        "own_ms": own,
        "decomp": {
            "helper_net_ms": net,
            "helper_queue_ms": queue,
            "helper_compute_ms": compute,
            "uncertainty_ms": 0.1,
            "uncertain": False,
        },
        "skew": {"exchange_ms": net + queue + compute, "valid": True},
    }


def test_build_timeline_marks_the_longer_leg_critical():
    phases = {
        "queue": 1.0,
        "batch": 1.0,
        "device_compute": 3.0,
        "respond": 1.0,
    }
    segments, leg = cp.build_timeline(phases, _leg())
    assert leg == "helper"
    by_phase = {(s["party"], s["phase"]): s for s in segments}
    # Serial head and tail are always critical.
    assert by_phase[("leader", "queue")]["critical"]
    assert by_phase[("leader", "batch")]["critical"]
    assert by_phase[("leader", "respond")]["critical"]
    # Parallel section: helper leg (8 ms) beats own-share (3 ms).
    assert not by_phase[("leader", "device_compute")]["critical"]
    assert by_phase[("helper", "helper_queue")]["critical"]
    assert by_phase[("helper", "helper_compute")]["critical"]
    # helper_net splits into symmetric half-legs around the service.
    nets = [s for s in segments if s["phase"] == "helper_net"]
    assert [n["duration_ms"] for n in nets] == [1.0, 1.0]
    assert nets[0]["start_ms"] == pytest.approx(2.0)
    assert nets[1]["start_ms"] == pytest.approx(9.0)
    # The tail starts after the slower leg joins.
    assert by_phase[("leader", "respond")]["start_ms"] == pytest.approx(
        2.0 + 8.0
    )
    # Per-party starts are monotone and every segment is in-range.
    for party in {s["party"] for s in segments}:
        starts = [s["start_ms"] for s in segments if s["party"] == party]
        assert starts == sorted(starts)
    assert all(s["start_ms"] >= 0.0 for s in segments)


def test_build_timeline_local_critical_and_fallback():
    phases = {"queue": 1.0, "device_compute": 20.0, "respond": 1.0}
    segments, leg = cp.build_timeline(phases, _leg(own=20.0))
    assert leg == "local"
    own = next(s for s in segments if s["phase"] == "device_compute")
    assert own["critical"]
    assert not any(
        s["critical"] for s in segments if s["phase"] == "helper_queue"
    )
    # No decomposition (invalid skew / v1 peer): one opaque rtt block.
    segments, leg = cp.build_timeline(
        {}, {"rtt_ms": 8.0, "own_ms": 1.0, "decomp": None, "skew": {}}
    )
    assert leg == "helper"
    assert [s["phase"] for s in segments] == ["helper_rtt"]


# ---------------------------------------------------------------------------
# Analyzer aggregation + admin surface
# ---------------------------------------------------------------------------


@pytest.fixture
def analyzer():
    prev = cp.default_analyzer()
    fresh = cp.set_default_analyzer(cp.CriticalPathAnalyzer())
    yield fresh
    cp.set_default_analyzer(prev)


@pytest.fixture
def recorder():
    prev = tracing.default_recorder()
    rec = tracing.set_default_recorder(tracing.FlightRecorder())
    yield rec
    tracing.set_default_recorder(prev)


def test_analyzer_observe_round_profile_and_metrics(analyzer):
    reg = MetricsRegistry()
    analyzer.bind_registry(reg)
    skew = cp.estimate_skew(_T0, _T3, _T1, _T2)
    decomp = cp.decompose_helper_leg(skew, {"device_compute": 6.0})
    for _ in range(3):
        analyzer.observe_round(
            "hh-leader", own_ms=1.0, rtt_ms=10.0, decomp=decomp, skew=skew
        )
    state = analyzer.export()
    assert state["requests"] == 3
    assert state["legs"]["helper"] == 3
    assert state["skew_invalid"] == 0
    profile = state["profile"]
    assert profile["helper"]["helper_compute"]["count"] == 3
    assert profile["helper"]["helper_compute"]["p50_ms"] == pytest.approx(
        6.0
    )
    # Shares sum to 1 over all critical cells.
    total_share = sum(
        entry["share"] for phases in profile.values()
        for entry in phases.values()
    )
    assert total_share == pytest.approx(1.0, abs=0.01)
    last = analyzer.last("hh-leader")
    assert last["critical_leg"] == "helper"
    assert last["helper_net_ms"] == pytest.approx(4.0)
    snap = reg.export()
    assert snap["counters"]["critical.legs{leg=helper}"] == 3
    assert snap["gauges"]["critical.helper_compute_ms"] == pytest.approx(
        6.0
    )
    hist = snap["histograms"][
        "critical.path_ms{party=helper,phase=helper_compute}"
    ]
    assert hist["count"] == 3
    # An invalid estimate counts, never splits.
    analyzer.observe_round(
        "hh-leader", own_ms=1.0, rtt_ms=5.0, decomp=None,
        skew=cp.estimate_skew(0.0, 5.0, 100.0, 110.0),
    )
    assert analyzer.export()["skew_invalid"] == 1
    assert reg.export()["counters"]["critical.skew_invalid"] == 1


def test_criticalz_endpoint_text_json_and_statusz(analyzer):
    skew = cp.estimate_skew(_T0, _T3, _T1, _T2)
    decomp = cp.decompose_helper_leg(skew, {"device_compute": 6.0})
    analyzer.observe_round(
        "leader", own_ms=1.0, rtt_ms=10.0, decomp=decomp, skew=skew
    )
    with AdminServer() as admin:  # defaults to the default analyzer
        base = f"http://127.0.0.1:{admin.port}"
        text = urllib.request.urlopen(base + "/criticalz").read().decode()
        assert "critical path" in text
        assert "helper_compute" in text
        assert "last merged request [leader]" in text
        state = json.loads(
            urllib.request.urlopen(
                base + "/criticalz?format=json"
            ).read()
        )
        assert state["requests"] == 1
        assert state["last"]["leader"]["helper_net_ms"] == pytest.approx(
            4.0
        )
        assert (
            state["profile"]["helper"]["helper_compute"]["count"] == 1
        )
        statusz = json.loads(
            urllib.request.urlopen(
                base + "/statusz?format=json"
            ).read()
        )
        assert statusz["critical"]["requests"] == 1
        html = urllib.request.urlopen(base + "/statusz").read().decode()
        assert "Critical path (cross-party)" in html
        # The 404 help lists the endpoint.
        try:
            urllib.request.urlopen(base + "/nope")
        except urllib.error.HTTPError as e:
            assert "/criticalz" in e.read().decode()


# ---------------------------------------------------------------------------
# In-process acceptance: the decomposition is honest end to end
# ---------------------------------------------------------------------------

NUM_RECORDS = 64
RECORD_BYTES = 16
RNG = np.random.default_rng(77)


def _build_database():
    records = [
        bytes(RNG.integers(0, 256, RECORD_BYTES, dtype=np.uint8))
        for _ in range(NUM_RECORDS)
    ]
    builder = DenseDpfPirDatabase.Builder()
    for r in records:
        builder.insert(r)
    return builder.build(), records


def test_in_process_decomposition_attributes_rtt(analyzer, recorder):
    """ISSUE acceptance: on InProcessTransport the helper leg is all
    service (the Helper runs inline), so helper_net ~ 0 and
    helper_queue + helper_compute ~ exchange rtt, within the
    estimator's own stated uncertainty — checked from the same numbers
    an operator would read off /criticalz."""
    database, records = _build_database()
    config = ServingConfig(max_batch_size=4, max_wait_ms=2.0)
    helper = HelperSession(database, encrypt_decrypt.decrypt, config)
    leader = LeaderSession(
        database, InProcessTransport(helper.handle_wire), config
    )
    client = DenseDpfPirClient.create(NUM_RECORDS, encrypt_decrypt.encrypt)
    with helper, leader:
        for idx in (3, 17, 41):
            request, state = client.create_request([idx])
            response = leader.handle_request(request)
            assert client.handle_response(response, state) == [
                records[idx]
            ]
    last = analyzer.last("leader")
    assert last is not None, "no merged timeline reached the analyzer"
    assert last["skew_valid"] is True
    net = last["helper_net_ms"]
    queue = last["helper_queue_ms"]
    compute = last["helper_compute_ms"]
    exchange = last["exchange_ms"]
    uncertainty = last["uncertainty_ms"]
    # helper_net ~ 0: within the estimator's stated uncertainty plus
    # envelope-codec slop, and small in absolute terms.
    assert net <= 2.0 * uncertainty + 0.5
    assert net < 5.0
    # The split accounts for the exchange rtt to the same tolerance.
    assert queue + compute == pytest.approx(
        exchange, abs=2.0 * uncertainty + 0.5
    )
    state = analyzer.export()
    assert state["requests"] == 3
    assert state["profile"], "no critical time attributed"
    # The merged timeline rode the flight-recorder trace (/tracez).
    dump = recorder.dump()
    traces = dump["slowest"] + dump["recent"]
    leader_trace = next(
        t for t in traces if t["name"] == "leader.request"
    )
    merged = leader_trace["attrs"]["critical_path"]
    assert merged["critical_leg"] in ("helper", "local")
    timeline = merged["timeline"]
    assert timeline
    for party in {s["party"] for s in timeline}:
        starts = [
            s["start_ms"] for s in timeline if s["party"] == party
        ]
        assert starts == sorted(starts)
    assert all(
        s["start_ms"] >= 0.0 and s["duration_ms"] >= 0.0
        for s in timeline
    )
    # The waterfall gained the overlay phases for this role.
    waterfall = phases_mod.default_phase_recorder().waterfall()
    leader_phases = waterfall["leader"]["phases"]
    assert leader_phases.get("helper_net", {}).get("count", 0) >= 1
    assert leader_phases.get("helper_compute", {}).get("count", 0) >= 1
