"""Differential tests for the Pallas plane-expansion kernels (interpret
mode on CPU) against their XLA twins in `pir/dense_eval_planes.py` —
the same per-target discipline as the inner-product kernels
(`pir/internal/inner_product_hwy_test.cc:427-434`)."""

import numpy as np
import pytest

import jax.numpy as jnp

from distributed_point_functions_tpu import keys as fixed_keys
from distributed_point_functions_tpu.ops.aes_bitslice import (
    mmo_hash_planes,
)
from distributed_point_functions_tpu.ops.expand_planes_pallas import (
    expand_level_planes_pallas,
    value_hash_planes_pallas,
)
from distributed_point_functions_tpu.pir.dense_eval_planes import (
    _tile_keys,
    expand_level_planes,
    pack_key_bits,
    pack_key_planes,
)

RNG = np.random.default_rng(23)


def _random_inputs(g, nk):
    kg = nk // 32
    assert g % kg == 0
    state = RNG.integers(0, 1 << 32, (16, 8, g), dtype=np.uint32)
    ctrl = RNG.integers(0, 1 << 32, (g,), dtype=np.uint32)
    cw = RNG.integers(0, 1 << 32, (nk, 4), dtype=np.uint32)
    cwl = RNG.integers(0, 2, (nk,), dtype=np.uint32)
    cwr = RNG.integers(0, 2, (nk,), dtype=np.uint32)
    return state, ctrl, cw, cwl, cwr


@pytest.mark.parametrize("g,nk", [(2, 64), (8, 32), (64, 64), (24, 96)])
def test_level_kernel_matches_xla(g, nk):
    state, ctrl, cw, cwl, cwr = _random_inputs(g, nk)
    cwp_kg = pack_key_planes(jnp.asarray(cw))
    cwl_kg = pack_key_bits(jnp.asarray(cwl))
    cwr_kg = pack_key_bits(jnp.asarray(cwr))

    want_state, want_ctrl = expand_level_planes(
        jnp.asarray(state),
        jnp.asarray(ctrl),
        _tile_keys(cwp_kg, 2 * g),
        _tile_keys(cwl_kg, g),
        _tile_keys(cwr_kg, g),
    )
    got_state, got_ctrl = expand_level_planes_pallas(
        jnp.asarray(state), jnp.asarray(ctrl), cwp_kg, cwl_kg, cwr_kg,
        interpret=True,
    )
    np.testing.assert_array_equal(np.asarray(got_state),
                                  np.asarray(want_state))
    np.testing.assert_array_equal(np.asarray(got_ctrl),
                                  np.asarray(want_ctrl))


@pytest.mark.parametrize("g,nk", [(2, 64), (64, 64), (24, 96)])
def test_value_kernel_matches_xla(g, nk):
    state, ctrl, cw, _, _ = _random_inputs(g, nk)
    vc_kg = pack_key_planes(jnp.asarray(cw))

    want = mmo_hash_planes(fixed_keys.RK_VALUE, jnp.asarray(state))
    want = want ^ (
        _tile_keys(vc_kg, g) & jnp.asarray(ctrl)[None, None, :]
    )
    got = value_hash_planes_pallas(
        jnp.asarray(state), jnp.asarray(ctrl), vc_kg, interpret=True
    )
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_serving_expansion_with_level_kernel(monkeypatch):
    """The full covering-subtree expansion served through the Pallas
    level kernels (interpret mode) is bit-identical to the limb kernel."""
    import functools

    from distributed_point_functions_tpu.pir import dense_eval_planes as dep
    from distributed_point_functions_tpu.pir.client import DenseDpfPirClient
    from distributed_point_functions_tpu.pir.dense_eval import (
        evaluate_selection_blocks,
        stage_keys,
    )

    monkeypatch.setattr(
        dep, "expand_level_planes_pallas",
        functools.partial(dep.expand_level_planes_pallas, interpret=True),
    )
    monkeypatch.setattr(
        dep, "value_hash_planes_pallas",
        functools.partial(dep.value_hash_planes_pallas, interpret=True),
    )
    monkeypatch.setenv("DPF_TPU_LEVEL_KERNEL", "pallas")

    num_records = 33 * 128  # odd block count: exercises truncation
    nq = 64
    num_blocks = (num_records + 127) // 128
    total = max(0, (num_records - 1).bit_length())
    expand = min((num_blocks - 1).bit_length(), total)
    walk = total - expand

    client = DenseDpfPirClient.create(num_records, lambda pt, ci: pt)
    idx = [int(i) for i in RNG.integers(0, num_records, nq)]
    keys0, _ = client._generate_key_pairs(idx)
    staged = stage_keys(keys0)

    want = np.asarray(evaluate_selection_blocks(
        *staged, walk_levels=walk, expand_levels=expand,
        num_blocks=num_blocks,
    ))
    got = np.asarray(dep.evaluate_selection_blocks_planes(
        *staged, walk_levels=walk, expand_levels=expand,
        num_blocks=num_blocks, force_planes=True,
    ))
    np.testing.assert_array_equal(got, want)
