"""Differential tests for the Pallas plane-expansion kernels (interpret
mode on CPU) against their XLA twins in `pir/dense_eval_planes.py` —
the same per-target discipline as the inner-product kernels
(`pir/internal/inner_product_hwy_test.cc:427-434`)."""

import warnings

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from distributed_point_functions_tpu import keys as fixed_keys
from distributed_point_functions_tpu.ops.aes_bitslice import (
    mmo_hash_planes,
)
from distributed_point_functions_tpu.ops.expand_planes_pallas import (
    expand_level_planes_pallas,
    value_hash_planes_pallas,
)
from distributed_point_functions_tpu.pir.dense_eval_planes import (
    _tile_keys,
    expand_level_planes,
    pack_key_bits,
    pack_key_planes,
)

RNG = np.random.default_rng(23)


def _random_inputs(g, nk):
    kg = nk // 32
    assert g % kg == 0
    state = RNG.integers(0, 1 << 32, (16, 8, g), dtype=np.uint32)
    ctrl = RNG.integers(0, 1 << 32, (g,), dtype=np.uint32)
    cw = RNG.integers(0, 1 << 32, (nk, 4), dtype=np.uint32)
    cwl = RNG.integers(0, 2, (nk,), dtype=np.uint32)
    cwr = RNG.integers(0, 2, (nk,), dtype=np.uint32)
    return state, ctrl, cw, cwl, cwr


@pytest.mark.parametrize("g,nk", [(8, 32), (64, 64), (24, 96)])
def test_level_kernel_matches_xla(g, nk):
    state, ctrl, cw, cwl, cwr = _random_inputs(g, nk)
    cwp_kg = pack_key_planes(jnp.asarray(cw))
    cwl_kg = pack_key_bits(jnp.asarray(cwl))
    cwr_kg = pack_key_bits(jnp.asarray(cwr))

    want_state, want_ctrl = jax.jit(expand_level_planes)(
        jnp.asarray(state),
        jnp.asarray(ctrl),
        _tile_keys(cwp_kg, 2 * g),
        _tile_keys(cwl_kg, g),
        _tile_keys(cwr_kg, g),
    )
    got_state, got_ctrl = expand_level_planes_pallas(
        jnp.asarray(state), jnp.asarray(ctrl), cwp_kg, cwl_kg, cwr_kg,
        interpret=True,
    )
    np.testing.assert_array_equal(np.asarray(got_state),
                                  np.asarray(want_state))
    np.testing.assert_array_equal(np.asarray(got_ctrl),
                                  np.asarray(want_ctrl))


@pytest.mark.parametrize("tile", [16, 32, 128])
def test_level_kernel_chunked_matches_xla(tile):
    """Forced sub-width lane tiles exercise the chunked path (one
    grid-(1,) pallas_call per lane slice — multi-step lane grids crash
    tpu_compile_helper on v5e) and must keep the global
    [all-left; all-right] child order. tile=128 > g covers a chunk
    narrower than the nominal tile (the in-kernel repeat factor must
    follow the chunk width, not the tile)."""
    g, nk = 64, 64
    state, ctrl, cw, cwl, cwr = _random_inputs(g, nk)
    cwp_kg = pack_key_planes(jnp.asarray(cw))
    cwl_kg = pack_key_bits(jnp.asarray(cwl))
    cwr_kg = pack_key_bits(jnp.asarray(cwr))

    want_state, want_ctrl = expand_level_planes_pallas(
        jnp.asarray(state), jnp.asarray(ctrl), cwp_kg, cwl_kg, cwr_kg,
        interpret=True,
    )
    got_state, got_ctrl = expand_level_planes_pallas(
        jnp.asarray(state), jnp.asarray(ctrl), cwp_kg, cwl_kg, cwr_kg,
        interpret=True, tile_lanes=tile,
    )
    np.testing.assert_array_equal(np.asarray(got_state),
                                  np.asarray(want_state))
    np.testing.assert_array_equal(np.asarray(got_ctrl),
                                  np.asarray(want_ctrl))


def test_value_kernel_chunked_matches_xla():
    g, nk = 64, 64
    state, ctrl, cw, _, _ = _random_inputs(g, nk)
    vc_kg = pack_key_planes(jnp.asarray(cw))

    want = value_hash_planes_pallas(
        jnp.asarray(state), jnp.asarray(ctrl), vc_kg, interpret=True
    )
    got = value_hash_planes_pallas(
        jnp.asarray(state), jnp.asarray(ctrl), vc_kg, interpret=True,
        tile_lanes=16,
    )
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


@pytest.mark.parametrize("per_seed", [False, True])
def test_path_kernel_chunked_matches_unchunked(per_seed):
    from distributed_point_functions_tpu.ops.expand_planes_pallas import (
        path_level_planes_pallas,
    )

    g, nk = 64, 64
    state, ctrl, cw, cwl, cwr = _random_inputs(g, nk)
    sel = RNG.integers(0, 1 << 32, (g,), dtype=np.uint32)
    if per_seed:
        cwp = jnp.asarray(
            RNG.integers(0, 1 << 32, (16, 8, g), dtype=np.uint32)
        )
        cwlb = jnp.asarray(RNG.integers(0, 1 << 32, (g,), dtype=np.uint32))
        cwrb = jnp.asarray(RNG.integers(0, 1 << 32, (g,), dtype=np.uint32))
    else:
        cwp = pack_key_planes(jnp.asarray(cw))
        cwlb = pack_key_bits(jnp.asarray(cwl))
        cwrb = pack_key_bits(jnp.asarray(cwr))

    want = path_level_planes_pallas(
        jnp.asarray(state), jnp.asarray(ctrl), jnp.asarray(sel),
        cwp, cwlb, cwrb, per_seed, interpret=True,
    )
    got = path_level_planes_pallas(
        jnp.asarray(state), jnp.asarray(ctrl), jnp.asarray(sel),
        cwp, cwlb, cwrb, per_seed, interpret=True, tile_lanes=16,
    )
    np.testing.assert_array_equal(np.asarray(got[0]), np.asarray(want[0]))
    np.testing.assert_array_equal(np.asarray(got[1]), np.asarray(want[1]))


@pytest.mark.parametrize("g,nk", [(2, 64), (64, 64), (24, 96)])
def test_value_kernel_matches_xla(g, nk):
    state, ctrl, cw, _, _ = _random_inputs(g, nk)
    vc_kg = pack_key_planes(jnp.asarray(cw))

    want = mmo_hash_planes(fixed_keys.RK_VALUE, jnp.asarray(state))
    want = want ^ (
        _tile_keys(vc_kg, g) & jnp.asarray(ctrl)[None, None, :]
    )
    got = value_hash_planes_pallas(
        jnp.asarray(state), jnp.asarray(ctrl), vc_kg, interpret=True
    )
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_serving_expansion_with_level_kernel(monkeypatch):
    """The full covering-subtree expansion served through the Pallas
    level kernels (interpret mode) is bit-identical to the limb kernel."""
    import functools

    from distributed_point_functions_tpu.pir import dense_eval_planes as dep
    from distributed_point_functions_tpu.pir.client import DenseDpfPirClient
    from distributed_point_functions_tpu.pir.dense_eval import (
        evaluate_selection_blocks,
        stage_keys,
    )

    monkeypatch.setattr(
        dep, "expand_level_planes_pallas",
        functools.partial(dep.expand_level_planes_pallas, interpret=True),
    )
    monkeypatch.setattr(
        dep, "value_hash_planes_pallas",
        functools.partial(dep.value_hash_planes_pallas, interpret=True),
    )
    monkeypatch.setenv("DPF_TPU_LEVEL_KERNEL", "pallas")

    num_records = 9 * 128  # odd block count: exercises truncation
    nq = 32
    num_blocks = (num_records + 127) // 128
    total = max(0, (num_records - 1).bit_length())
    expand = min((num_blocks - 1).bit_length(), total)
    walk = total - expand

    client = DenseDpfPirClient.create(num_records, lambda pt, ci: pt)
    idx = [int(i) for i in RNG.integers(0, num_records, nq)]
    keys0, _ = client._generate_key_pairs(idx)
    staged = stage_keys(keys0)

    want = np.asarray(evaluate_selection_blocks(
        *staged, walk_levels=walk, expand_levels=expand,
        num_blocks=num_blocks,
    ))
    got = np.asarray(dep.evaluate_selection_blocks_planes(
        *staged, walk_levels=walk, expand_levels=expand,
        num_blocks=num_blocks, force_planes=True,
    ))
    np.testing.assert_array_equal(got, want)


def test_hierarchical_expansion_with_level_kernel(monkeypatch):
    """Full-domain evaluate_next through the plane path with the Pallas
    level kernel (interpret mode) matches the limb program."""
    import functools

    from distributed_point_functions_tpu import dpf as dpf_mod
    from distributed_point_functions_tpu.dpf import (
        DistributedPointFunction,
        DpfParameters,
    )
    from distributed_point_functions_tpu.ops import (
        expand_planes_pallas as epp,
    )
    from distributed_point_functions_tpu.value_types import IntType

    monkeypatch.setenv("DPF_TPU_EXPAND_LEVELS", "limb")
    params = DpfParameters(log_domain_size=9, value_type=IntType(64))
    d = DistributedPointFunction.create(params)
    k0, k1 = d.generate_keys(300, 99)

    def run_both():
        outs = []
        for k in (k0, k1):
            ctx = d.create_evaluation_context(k)
            outs.append(np.asarray(d.evaluate_next([], ctx)))
        return outs

    want = run_both()

    # Planes path + forced Pallas level kernel, interpret mode: patch the
    # kernel symbol where the planes program imports it from.
    monkeypatch.setenv("DPF_TPU_EXPAND_LEVELS", "planes")
    monkeypatch.setenv("DPF_TPU_LEVEL_KERNEL", "pallas")
    monkeypatch.setattr(
        epp, "expand_level_planes_pallas",
        functools.partial(epp.expand_level_planes_pallas, interpret=True),
    )
    monkeypatch.setattr(
        epp, "value_hash_planes_pallas",
        functools.partial(epp.value_hash_planes_pallas, interpret=True),
    )
    dpf_mod._expand_levels_planes_fn.cache_clear()
    with warnings.catch_warnings():
        # The kernel path must actually serve (no silent XLA fallback).
        warnings.simplefilter("error")
        got = run_both()
    dpf_mod._expand_levels_planes_fn.cache_clear()
    for w, g in zip(want, got):
        np.testing.assert_array_equal(g, w)
    # uint64 values are (lo, hi) uint32 limb pairs on CPU (no x64).
    def u64(x):
        return (int(x[1]) << 32) | int(x[0])

    total = (u64(want[0][300]) + u64(want[1][300])) % (1 << 64)
    assert total == 99


@pytest.mark.parametrize("per_seed", [False, True])
def test_path_walk_with_level_kernel(monkeypatch, per_seed):
    """The path walk served through the Pallas select-key kernel
    (interpret mode) matches the limb walk in both correction modes."""
    import functools

    from distributed_point_functions_tpu import dpf as dpf_mod
    from distributed_point_functions_tpu.ops import (
        expand_planes_pallas as epp,
    )

    monkeypatch.setattr(
        epp, "path_level_planes_pallas",
        functools.partial(epp.path_level_planes_pallas, interpret=True),
    )

    n, levels = 64, 6
    seeds = RNG.integers(0, 1 << 32, (n, 4), dtype=np.uint32)
    control = RNG.integers(0, 2, (n,), dtype=np.uint32)
    paths = RNG.integers(0, 1 << 32, (n, 4), dtype=np.uint32)
    m = n if per_seed else 1
    cw_seeds = RNG.integers(0, 1 << 32, (levels, m, 4), dtype=np.uint32)
    cw_left = RNG.integers(0, 2, (levels, m), dtype=np.uint32)
    cw_right = RNG.integers(0, 2, (levels, m), dtype=np.uint32)
    bit_indices = np.arange(levels, dtype=np.uint32)[::-1].copy()

    args = tuple(
        jnp.asarray(a)
        for a in (seeds, control, paths, cw_seeds, cw_left, cw_right,
                  bit_indices)
    )
    want_s, want_c = dpf_mod._eval_paths_limb(*args)
    got_s, got_c = dpf_mod._eval_paths_planes(*args, level_kernel=True)
    np.testing.assert_array_equal(np.asarray(got_s), np.asarray(want_s))
    np.testing.assert_array_equal(np.asarray(got_c), np.asarray(want_c))


def test_hierarchical_fused_leaf_hash_planes_xla(monkeypatch):
    """The fused leaf value hash (hash_leaves) in the XLA planes program
    matches the limb program (which fuses the same hash)."""
    from distributed_point_functions_tpu import dpf as dpf_mod
    from distributed_point_functions_tpu.dpf import (
        DistributedPointFunction,
        DpfParameters,
    )
    from distributed_point_functions_tpu.value_types import IntType

    params = DpfParameters(log_domain_size=8, value_type=IntType(32))
    d = DistributedPointFunction.create(params)
    k0, k1 = d.generate_keys(213, 7)

    def run_both():
        outs = []
        for k in (k0, k1):
            ctx = d.create_evaluation_context(k)
            outs.append(np.asarray(d.evaluate_next([], ctx)))
        return outs

    monkeypatch.setenv("DPF_TPU_EXPAND_LEVELS", "limb")
    want = run_both()
    monkeypatch.setenv("DPF_TPU_EXPAND_LEVELS", "planes")
    monkeypatch.setenv("DPF_TPU_LEVEL_KERNEL", "xla")
    got = run_both()
    for w, g in zip(want, got):
        np.testing.assert_array_equal(g, w)
    total = (want[0].astype(np.uint64) + want[1].astype(np.uint64))
    assert int(total[213].item()) % (1 << 32) == 7


def test_level_kernel_selfcheck(monkeypatch):
    """Auto mode runs a one-time on-device bit-identity self-check; a
    mismatching kernel is remembered as failed and serving falls back."""
    import functools

    from distributed_point_functions_tpu.ops import (
        expand_planes_pallas as epp,
    )
    from distributed_point_functions_tpu.pir import dense_eval_planes as dep

    monkeypatch.setenv("DPF_TPU_LEVEL_KERNEL", "auto")
    monkeypatch.setattr(dep.jax, "default_backend", lambda: "tpu")
    monkeypatch.setattr(dep, "_LEVEL_KERNEL_FAILED", False)
    monkeypatch.setattr(dep, "_LEVEL_KERNEL_VERIFIED", False)
    monkeypatch.setattr(dep, "_TAIL_KERNEL_FAILED", False)
    monkeypatch.setattr(dep, "_TAIL_KERNEL_VERIFIED", False)
    monkeypatch.setattr(dep, "_HEAD_KERNEL_FAILED", False)
    monkeypatch.setattr(dep, "_HEAD_KERNEL_VERIFIED", False)
    monkeypatch.setattr(dep, "_WALK_KERNEL_FAILED", False)
    monkeypatch.setattr(dep, "_WALK_KERNEL_VERIFIED", False)

    # Interpret-mode kernels: the self-checks pass and auto mode prefers
    # the walk-descent kernels (tail/head verified alongside would be
    # skipped — walk wins first).
    for name in ("expand_level_planes_pallas", "value_hash_planes_pallas",
                 "path_level_planes_pallas"):
        monkeypatch.setattr(
            epp, name, functools.partial(getattr(epp, name), interpret=True)
        )
    monkeypatch.setattr(
        dep, "expand_tail_planes_pallas",
        functools.partial(dep.expand_tail_planes_pallas, interpret=True),
    )
    monkeypatch.setattr(
        dep, "expand_head_planes_pallas",
        functools.partial(dep.expand_head_planes_pallas, interpret=True),
    )
    monkeypatch.setattr(
        dep, "walk_descend_planes_pallas",
        functools.partial(dep.walk_descend_planes_pallas, interpret=True),
    )
    assert dep._level_kernel_enabled() == "walk"
    assert dep._LEVEL_KERNEL_VERIFIED is True
    assert dep._WALK_KERNEL_VERIFIED is True

    # A broken walk kernel demotes to the fused tail, not to XLA.
    monkeypatch.setattr(dep, "_WALK_KERNEL_FAILED", False)
    monkeypatch.setattr(dep, "_WALK_KERNEL_VERIFIED", False)

    def walk_boom(*a, **k):
        raise RuntimeError("mosaic says no")

    monkeypatch.setattr(dep, "walk_descend_planes_pallas", walk_boom)
    with pytest.warns(UserWarning, match="walk-descent"):
        assert dep._level_kernel_enabled() == "tail"
    assert dep._WALK_KERNEL_FAILED is True
    assert dep._TAIL_KERNEL_VERIFIED is True
    assert dep._HEAD_KERNEL_VERIFIED is True

    # A failing tail degrades auto mode to the per-level kernels only.
    monkeypatch.setattr(dep, "_TAIL_KERNEL_VERIFIED", False)

    def bad_tail(*a, **kw):
        raise RuntimeError("tail exploded")

    monkeypatch.setattr(dep, "expand_tail_planes_pallas", bad_tail)
    with pytest.warns(UserWarning, match="tail kernel"):
        assert dep._level_kernel_enabled() == "pallas"
    assert dep._TAIL_KERNEL_FAILED is True
    monkeypatch.setattr(dep, "_TAIL_KERNEL_FAILED", False)
    monkeypatch.setattr(dep, "_TAIL_KERNEL_VERIFIED", False)

    # A kernel that returns garbage: self-check trips, failure remembered.
    monkeypatch.setattr(dep, "_LEVEL_KERNEL_VERIFIED", False)
    monkeypatch.setattr(dep, "_LEVEL_KERNEL_FAILED", False)

    def bad(state, ctrl, cwp, cwl, cwr, **kw):
        s, c = epp.expand_level_planes_pallas(state, ctrl, cwp, cwl, cwr)
        return s ^ jnp.uint32(1), c

    monkeypatch.setattr(epp, "expand_level_planes_pallas", bad)
    with pytest.warns(UserWarning, match="self-check"):
        assert dep._level_kernel_enabled() is False
    assert dep._LEVEL_KERNEL_FAILED is True


@pytest.mark.parametrize(
    "g0,nk,r,tile",
    [(12, 96, 2, 6), (2, 64, 3, 2)],
)
def test_tail_kernel_matches_xla(g0, nk, r, tile):
    """The fused multi-level tail kernel (interpret mode) is
    bit-identical to per-tile XLA levels + value hash, in tiled order.
    (The minimal r=1 multi-tile case lives in the fast tier,
    `test_pallas_fast.py`.)"""
    from distributed_point_functions_tpu.ops.expand_planes_pallas import (
        expand_tail_planes_pallas,
    )

    kg = nk // 32
    state = jnp.asarray(
        RNG.integers(0, 1 << 32, (16, 8, g0), dtype=np.uint32)
    )
    ctrl = jnp.asarray(RNG.integers(0, 1 << 32, (g0,), dtype=np.uint32))
    cwp_kg = [
        pack_key_planes(
            jnp.asarray(RNG.integers(0, 1 << 32, (nk, 4), dtype=np.uint32))
        )
        for _ in range(r)
    ]
    cwl_kg = [
        pack_key_bits(
            jnp.asarray(RNG.integers(0, 2, (nk,), dtype=np.uint32))
        )
        for _ in range(r)
    ]
    cwr_kg = [
        pack_key_bits(
            jnp.asarray(RNG.integers(0, 2, (nk,), dtype=np.uint32))
        )
        for _ in range(r)
    ]
    vc_kg = pack_key_planes(
        jnp.asarray(RNG.integers(0, 1 << 32, (nk, 4), dtype=np.uint32))
    )

    # XLA twin: per tile, r global-order levels then the value hash —
    # jitted per tile shape (one compile, reused across tiles; the eager
    # bitsliced-AES dispatch is what made this module cost minutes).
    @jax.jit
    def twin_tile(s, c, cwp_all, cwl_all, cwr_all, vc):
        for i in range(r):
            g2 = 2 * s.shape[-1]
            s, c = expand_level_planes(
                s,
                c,
                _tile_keys(cwp_all[i], g2),
                _tile_keys(cwl_all[i], g2 // 2),
                _tile_keys(cwr_all[i], g2 // 2),
            )
        v = mmo_hash_planes(fixed_keys.RK_VALUE, s) ^ (
            _tile_keys(vc, s.shape[-1]) & c[None, None, :]
        )
        return v, c

    cwp_st = jnp.stack(cwp_kg)
    cwl_st = jnp.stack(cwl_kg)
    cwr_st = jnp.stack(cwr_kg)
    outs = []
    out_ctrls = []
    for lo in range(0, g0, tile):
        v, c = twin_tile(
            state[:, :, lo : lo + tile], ctrl[lo : lo + tile],
            cwp_st, cwl_st, cwr_st, vc_kg,
        )
        outs.append(v)
        out_ctrls.append(c)
    want = np.asarray(jnp.concatenate(outs, axis=-1))
    want_ctrl = np.asarray(jnp.concatenate(out_ctrls))

    got_v, got_c = expand_tail_planes_pallas(
        state,
        ctrl,
        jnp.stack(cwp_kg),
        jnp.stack(cwl_kg),
        jnp.stack(cwr_kg),
        vc_kg,
        tile_lanes=tile,
        interpret=True,
    )
    np.testing.assert_array_equal(np.asarray(got_v), want)
    np.testing.assert_array_equal(np.asarray(got_c), want_ctrl)


def test_serving_expansion_with_tail_kernel(monkeypatch):
    """The covering-subtree expansion served in tail mode (fused last
    levels + value hash, interpret mode) is bit-identical to the limb
    kernel — exercising the tiled-order exit permutation."""
    import functools

    from distributed_point_functions_tpu.pir import dense_eval_planes as dep
    from distributed_point_functions_tpu.pir.client import DenseDpfPirClient
    from distributed_point_functions_tpu.pir.dense_eval import (
        evaluate_selection_blocks,
        stage_keys,
    )

    monkeypatch.setattr(
        dep, "expand_level_planes_pallas",
        functools.partial(dep.expand_level_planes_pallas, interpret=True),
    )
    monkeypatch.setattr(
        dep, "expand_tail_planes_pallas",
        functools.partial(dep.expand_tail_planes_pallas, interpret=True),
    )
    monkeypatch.setattr(
        dep, "expand_head_planes_pallas",
        functools.partial(dep.expand_head_planes_pallas, interpret=True),
    )
    monkeypatch.setenv("DPF_TPU_LEVEL_KERNEL", "tail")
    monkeypatch.setenv("DPF_TPU_TAIL_LEVELS", "2")
    # Tiny tiles so several tail calls + the cross-tile order run.
    monkeypatch.setenv("DPF_TPU_TAIL_TILE_LANES", "8")
    # Fused head over the first two levels: head -> per-level -> tail in
    # one serving program.
    monkeypatch.setenv("DPF_TPU_HEAD_LEVELS", "2")

    num_records = 19 * 128  # odd block count: exercises truncation
    nq = 64  # exact key-group multiple (kg 3 coverage lives in
    #          test_tail_kernel_matches_xla[12-96-2-6])
    num_blocks = (num_records + 127) // 128
    total = max(0, (num_records - 1).bit_length())
    expand = min((num_blocks - 1).bit_length(), total)
    walk = total - expand

    client = DenseDpfPirClient.create(num_records, lambda pt, ci: pt)
    idx = [int(i) for i in RNG.integers(0, num_records, nq)]
    keys0, _ = client._generate_key_pairs(idx)
    staged = stage_keys(keys0)

    want = np.asarray(evaluate_selection_blocks(
        *staged, walk_levels=walk, expand_levels=expand,
        num_blocks=num_blocks,
    ))
    got = np.asarray(dep.evaluate_selection_blocks_planes(
        *staged, walk_levels=walk, expand_levels=expand,
        num_blocks=num_blocks, force_planes=True,
    ))
    np.testing.assert_array_equal(got, want)


def test_hierarchical_expansion_with_tail_kernel(monkeypatch):
    """Full-domain evaluate_next in tail mode (fused last levels + leaf
    hash per subtree tile, interpret mode) matches the limb program —
    exercising the tiled exit permutation with shared correction words
    (kg=1 planes) and the kernel's control-bit output."""
    import functools

    from distributed_point_functions_tpu import dpf as dpf_mod
    from distributed_point_functions_tpu.dpf import (
        DistributedPointFunction,
        DpfParameters,
    )
    from distributed_point_functions_tpu.ops import (
        expand_planes_pallas as epp,
    )
    from distributed_point_functions_tpu.value_types import IntType

    monkeypatch.setenv("DPF_TPU_EXPAND_LEVELS", "limb")
    params = DpfParameters(log_domain_size=9, value_type=IntType(64))
    d = DistributedPointFunction.create(params)
    k0, k1 = d.generate_keys(400, 55)

    def run_both():
        outs = []
        for k in (k0, k1):
            ctx = d.create_evaluation_context(k)
            outs.append(np.asarray(d.evaluate_next([], ctx)))
        return outs

    want = run_both()

    monkeypatch.setenv("DPF_TPU_EXPAND_LEVELS", "planes")
    monkeypatch.setenv("DPF_TPU_LEVEL_KERNEL", "tail")
    monkeypatch.setenv("DPF_TPU_TAIL_LEVELS", "2")
    monkeypatch.setenv("DPF_TPU_TAIL_TILE_LANES", "16")
    # Fused head over the first two plane levels: head -> per-level ->
    # tail in the one hierarchical program.
    monkeypatch.setenv("DPF_TPU_HEAD_LEVELS", "2")
    for name in ("expand_level_planes_pallas", "value_hash_planes_pallas",
                 "expand_tail_planes_pallas", "expand_head_planes_pallas"):
        monkeypatch.setattr(
            epp, name, functools.partial(getattr(epp, name), interpret=True)
        )
    dpf_mod._expand_levels_planes_fn.cache_clear()
    with warnings.catch_warnings():
        # The tail path must actually serve (no silent XLA fallback).
        warnings.simplefilter("error")
        got = run_both()
    dpf_mod._expand_levels_planes_fn.cache_clear()
    for w, g in zip(want, got):
        np.testing.assert_array_equal(g, w)

    def u64(x):
        return (int(x[1]) << 32) | int(x[0])

    total = (u64(want[0][400]) + u64(want[1][400])) % (1 << 64)
    assert total == 55


def test_walk_descend_multi_tile():
    """Tile boundaries inside and across the 2^r leaf blocks must not
    change the result (per-lane descent is tile-local)."""
    from distributed_point_functions_tpu.ops.expand_planes_pallas import (
        walk_descend_planes_pallas,
    )

    nk, r, kg, n_entry = 64, 2, 2, 2
    g0 = n_entry * kg
    state, ctrl, _, _, _ = _random_inputs(g0, nk)
    cwp_all = jnp.stack(
        [pack_key_planes(jnp.asarray(
            RNG.integers(0, 1 << 32, (nk, 4), dtype=np.uint32)
        )) for _ in range(r)]
    )
    cwl_all = jnp.stack(
        [pack_key_bits(jnp.asarray(
            RNG.integers(0, 2, (nk,), dtype=np.uint32)
        )) for _ in range(r)]
    )
    cwr_all = jnp.stack(
        [pack_key_bits(jnp.asarray(
            RNG.integers(0, 2, (nk,), dtype=np.uint32)
        )) for _ in range(r)]
    )
    full, full_c = walk_descend_planes_pallas(
        jnp.asarray(state), jnp.asarray(ctrl), cwp_all, cwl_all,
        cwr_all, r=r, tile_lanes=g0 << r, interpret=True,
    )
    tiled, tiled_c = walk_descend_planes_pallas(
        jnp.asarray(state), jnp.asarray(ctrl), cwp_all, cwl_all,
        cwr_all, r=r, tile_lanes=kg * 4, interpret=True,
    )
    np.testing.assert_array_equal(np.asarray(full), np.asarray(tiled))
    np.testing.assert_array_equal(np.asarray(full_c), np.asarray(tiled_c))


@pytest.mark.parametrize(
    "expand_levels,head,tail,compact",
    [
        (4, 2, 2, False),  # walk head + walk tail, no middle
        (5, 2, 2, False),  # walk head + PER-LEVEL middle + walk tail:
        #                    the production composition at serving
        #                    shapes, where the leaf-order bookkeeping
        #                    appends doubling between two natural-order
        #                    walk phases
        (5, 2, 2, True),   # same, compact-entry mode (offset-major
        #                    tiles composed into the exit gather)
    ],
)
def test_walk_dispatch_integration(
    monkeypatch, expand_levels, head, tail, compact
):
    """The planes pipeline with walk-kind head+tail must be
    bit-identical to the XLA pipeline — exercises the leaf-order
    bookkeeping end to end."""
    import functools as ft

    from distributed_point_functions_tpu.ops import (
        expand_planes_pallas as epp,
    )
    from distributed_point_functions_tpu.pir import dense_eval_planes as dep

    monkeypatch.setattr(
        dep, "walk_descend_planes_pallas",
        ft.partial(epp.walk_descend_planes_pallas, interpret=True),
    )
    monkeypatch.setattr(
        dep, "expand_level_planes_pallas",
        ft.partial(epp.expand_level_planes_pallas, interpret=True),
    )
    nk = 32
    num_blocks = 1 << expand_levels
    rng = np.random.default_rng(55)
    seeds0 = rng.integers(0, 1 << 32, (nk, 4), dtype=np.uint32)
    control0 = rng.integers(0, 2, (nk,), dtype=np.uint32)
    cw_seeds = rng.integers(
        0, 1 << 32, (expand_levels, nk, 4), dtype=np.uint32
    )
    cw_left = rng.integers(0, 2, (expand_levels, nk), dtype=np.uint32)
    cw_right = rng.integers(0, 2, (expand_levels, nk), dtype=np.uint32)
    last_vc = rng.integers(0, 1 << 32, (nk, 4), dtype=np.uint32)
    args = tuple(
        jnp.asarray(a)
        for a in (seeds0, control0, cw_seeds, cw_left, cw_right, last_vc)
    )
    kwargs = dict(
        walk_levels=0, expand_levels=expand_levels, num_blocks=num_blocks
    )
    want = np.asarray(
        dep._evaluate_selection_blocks_planes_jit(*args, **kwargs)
    )
    got = np.asarray(
        dep._evaluate_selection_blocks_planes_jit(
            *args, **kwargs,
            level_kernel=True,
            head_levels=head,
            tail_levels=tail,
            tail_kind="walk",
            head_kind="walk",
            walk_compact=compact,
        )
    )
    np.testing.assert_array_equal(got, want)


@pytest.mark.parametrize("tiles", [1, 2])
def test_walk_compact_entry_matches_replicated(tiles):
    """compact_entry reads the unreplicated entry per tile and exits
    offset-major; through walk_compact_leaf_order it must be
    bit-identical to the replicated natural-order mode."""
    from distributed_point_functions_tpu.ops.expand_planes_pallas import (
        walk_compact_leaf_order,
        walk_descend_planes_pallas,
    )

    nk, r, kg = 64, 2, 2
    n_entry = 4
    g0 = n_entry * kg
    w = g0 << r
    tile = w // tiles
    state, ctrl, _, _, _ = _random_inputs(g0, nk)
    cwp_all = jnp.stack([
        pack_key_planes(jnp.asarray(
            RNG.integers(0, 1 << 32, (nk, 4), dtype=np.uint32)
        )) for _ in range(r)
    ])
    cwl_all = jnp.stack([
        pack_key_bits(jnp.asarray(
            RNG.integers(0, 2, (nk,), dtype=np.uint32)
        )) for _ in range(r)
    ])
    cwr_all = jnp.stack([
        pack_key_bits(jnp.asarray(
            RNG.integers(0, 2, (nk,), dtype=np.uint32)
        )) for _ in range(r)
    ])
    vc = pack_key_planes(jnp.asarray(
        RNG.integers(0, 1 << 32, (nk, 4), dtype=np.uint32)
    ))
    nat_v, nat_c = walk_descend_planes_pallas(
        jnp.asarray(state), jnp.asarray(ctrl), cwp_all, cwl_all,
        cwr_all, vc, r=r, tile_lanes=w, value_hash=True, interpret=True,
    )
    got_v, got_c = walk_descend_planes_pallas(
        jnp.asarray(state), jnp.asarray(ctrl), cwp_all, cwl_all,
        cwr_all, vc, r=r, tile_lanes=tile, value_hash=True,
        compact_entry=True, interpret=True,
    )
    # Natural-mode output: leaf g at node-position g. Compact output:
    # position per walk_compact_leaf_order; gather compact -> natural.
    order = walk_compact_leaf_order(
        np.arange(n_entry), r, (tile >> r) // kg
    )
    pos_of_leaf = np.argsort(order)
    lanes = (
        pos_of_leaf[:, None] * kg + np.arange(kg)[None, :]
    ).reshape(-1)
    np.testing.assert_array_equal(
        np.asarray(got_v)[:, :, lanes], np.asarray(nat_v)
    )
    np.testing.assert_array_equal(
        np.asarray(got_c)[lanes], np.asarray(nat_c)
    )


def test_walk_compact_and_hier_selfchecks(monkeypatch):
    """The compact-entry and hierarchical walk geometries carry their
    own verdicts (ADVICE r04): each is bit-verified in exactly the
    mode/tile `walk_plan` picks, and the dispatch gates honor
    requested/verified/failed state."""
    import functools

    from distributed_point_functions_tpu.pir import dense_eval_planes as dep

    monkeypatch.setattr(
        dep, "walk_descend_planes_pallas",
        functools.partial(dep.walk_descend_planes_pallas, interpret=True),
    )
    for flag in ("_WALK_COMPACT_VERIFIED", "_WALK_COMPACT_FAILED",
                 "_WALK_HIER_VERIFIED", "_WALK_HIER_FAILED"):
        monkeypatch.setattr(dep, flag, False)
    monkeypatch.setenv("DPF_TPU_WALK_COMPACT", "1")

    assert dep._walk_compact_selfcheck() is True
    assert dep._WALK_COMPACT_VERIFIED is True
    # Hier check covers replicated AND compact modes when the knob is on.
    assert dep._walk_hier_selfcheck() is True
    assert dep._WALK_HIER_VERIFIED is True

    # Gate logic: requested + verified + not failed.
    assert dep._walk_compact_ok() is True
    monkeypatch.setenv("DPF_TPU_WALK_COMPACT", "")
    assert dep._walk_compact_ok() is False  # not requested
    monkeypatch.setenv("DPF_TPU_WALK_COMPACT", "1")
    monkeypatch.setattr(dep, "_WALK_COMPACT_FAILED", True)
    assert dep._walk_compact_ok() is False  # FAILED wins over VERIFIED

    # Under an active trace only a prior eager verification counts.
    monkeypatch.setattr(dep, "_trace_state_clean", lambda: False)
    assert dep._walk_hier_ok() is True
    monkeypatch.setattr(dep, "_WALK_HIER_VERIFIED", False)
    assert dep._walk_hier_ok() is False


def test_walk_compact_selfcheck_failure_is_isolated(monkeypatch):
    """A compact-mode miscompile demotes ONLY compact mode: the base
    walk family keeps serving replicated entries."""
    from distributed_point_functions_tpu.pir import dense_eval_planes as dep

    for flag in ("_WALK_COMPACT_VERIFIED", "_WALK_COMPACT_FAILED"):
        monkeypatch.setattr(dep, flag, False)
    monkeypatch.setattr(dep, "_WALK_KERNEL_VERIFIED", True)
    monkeypatch.setattr(dep, "_WALK_KERNEL_FAILED", False)
    monkeypatch.setenv("DPF_TPU_WALK_COMPACT", "1")

    def boom(*a, **k):
        raise RuntimeError("mosaic compact says no")

    monkeypatch.setattr(dep, "walk_descend_planes_pallas", boom)
    with pytest.warns(UserWarning, match="compact-entry"):
        assert dep._walk_compact_ok() is False
    assert dep._WALK_COMPACT_FAILED is True
    assert dep._WALK_KERNEL_VERIFIED is True
    assert dep._WALK_KERNEL_FAILED is False


def test_tail_dispatch_odd_kg_matches_xla(monkeypatch):
    """Serving-side concat-tail dispatch at kg=3 with a non-power-of-two
    tile (tile % 8 != 0): the cross-tile exit-order composition and
    truncation at odd-kg geometry — the coverage the shrunken
    test_serving_expansion_with_tail_kernel (nq 64) no longer carries."""
    import functools as ft

    from distributed_point_functions_tpu.ops import (
        expand_planes_pallas as epp,
    )
    from distributed_point_functions_tpu.pir import dense_eval_planes as dep

    for name in ("expand_level_planes_pallas", "expand_tail_planes_pallas"):
        monkeypatch.setattr(
            dep, name, ft.partial(getattr(dep, name), interpret=True)
        )
    nk, expand_levels = 96, 4  # kg=3
    num_blocks = 13  # odd, < 2^4: exercises truncation
    rng = np.random.default_rng(77)
    args = tuple(
        jnp.asarray(a)
        for a in (
            rng.integers(0, 1 << 32, (nk, 4), dtype=np.uint32),
            rng.integers(0, 2, (nk,), dtype=np.uint32),
            rng.integers(0, 1 << 32, (expand_levels, nk, 4),
                         dtype=np.uint32),
            rng.integers(0, 2, (expand_levels, nk), dtype=np.uint32),
            rng.integers(0, 2, (expand_levels, nk), dtype=np.uint32),
            rng.integers(0, 1 << 32, (nk, 4), dtype=np.uint32),
        )
    )
    kwargs = dict(
        walk_levels=0, expand_levels=expand_levels, num_blocks=num_blocks
    )
    want = np.asarray(
        dep._evaluate_selection_blocks_planes_jit(*args, **kwargs)
    )
    got = np.asarray(
        dep._evaluate_selection_blocks_planes_jit(
            *args, **kwargs,
            level_kernel=True, tail_levels=2, tail_tile_nodes=2,
        )
    )
    np.testing.assert_array_equal(got, want)
