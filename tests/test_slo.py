"""SLO tracker tests: grading kinds, burn state, and the /healthz
degrade-to-503 / recover-to-200 contract through a live AdminServer."""

import json
import urllib.error
import urllib.request

import pytest

from distributed_point_functions_tpu.observability import AdminServer
from distributed_point_functions_tpu.observability.device import (
    DeviceTelemetry,
)
from distributed_point_functions_tpu.observability.slo import (
    KINDS,
    SloObjective,
    SloTracker,
)
from distributed_point_functions_tpu.serving.metrics import MetricsRegistry


def _get(url):
    """(status, body) tolerating HTTP error statuses."""
    try:
        with urllib.request.urlopen(url) as resp:
            return resp.status, resp.read().decode()
    except urllib.error.HTTPError as e:
        return e.code, e.read().decode()


class TestObjectiveValidation:
    def test_unknown_kind_rejected(self):
        with pytest.raises(ValueError, match="unknown SLO kind"):
            SloObjective(name="x", kind="p42", metric="m", threshold=1)

    def test_bad_severity_rejected(self):
        with pytest.raises(ValueError, match="severity"):
            SloObjective(
                name="x", kind="p99_ms_max", metric="m", threshold=1,
                severity="panic",
            )

    def test_all_kinds_construct(self):
        for kind in KINDS:
            SloObjective(name=kind, kind=kind, metric="m", threshold=1)


class TestGrading:
    def test_p99_ceiling_ok_breach_and_no_data(self):
        reg = MetricsRegistry()
        tracker = SloTracker(
            [SloObjective(name="lat", kind="p99_ms_max",
                          metric="req_ms", threshold=50.0)],
            registry=reg,
        )
        (r,) = tracker.evaluate()
        assert r["state"] == "no_data" and r["observed"] is None
        reg.histogram("req_ms").observe(10.0)
        (r,) = tracker.evaluate()
        assert r["state"] == "ok"
        reg.histogram("req_ms").observe(500.0)
        (r,) = tracker.evaluate()
        assert r["state"] == "breach"

    def test_counter_max_compile_budget(self):
        reg = MetricsRegistry()
        tracker = SloTracker(
            [SloObjective(name="compile_budget", kind="counter_max",
                          metric="device.compiles{site=s}", threshold=2)],
            registry=reg,
        )
        c = reg.counter("device.compiles", labels={"site": "s"})
        c.inc(2)
        (r,) = tracker.evaluate()
        assert r["state"] == "ok"
        c.inc()
        (r,) = tracker.evaluate()
        assert r["state"] == "breach" and r["observed"] == 3

    def test_gauge_max(self):
        reg = MetricsRegistry()
        tracker = SloTracker(
            [SloObjective(name="hbm", kind="gauge_max",
                          metric="device.hbm_live_bytes",
                          threshold=1000.0)],
            registry=reg,
        )
        reg.gauge("device.hbm_live_bytes").set(2000)
        (r,) = tracker.evaluate()
        assert r["state"] == "breach"

    def test_rate_min_needs_two_marks_then_grades(self):
        reg = MetricsRegistry()
        clock = [0.0]
        tracker = SloTracker(
            [SloObjective(name="qps", kind="rate_min",
                          metric="served", threshold=10.0)],
            registry=reg, clock=lambda: clock[0],
        )
        reg.counter("served").inc(100)
        (r,) = tracker.evaluate()  # first mark
        assert r["state"] == "no_data"
        clock[0] = 10.0
        reg.counter("served").inc(500)  # 50/s since the mark
        (r,) = tracker.evaluate()
        assert r["state"] == "ok" and r["observed"] == 50.0
        clock[0] = 20.0
        reg.counter("served").inc(1)  # 0.1/s: below the floor
        (r,) = tracker.evaluate()
        assert r["state"] == "breach"

    def test_burn_accrues_while_breaching_and_clears(self):
        reg = MetricsRegistry()
        clock = [0.0]
        tracker = SloTracker(
            [SloObjective(name="lat", kind="p99_ms_max",
                          metric="req_ms", threshold=1.0)],
            registry=reg, clock=lambda: clock[0],
        )
        reg.histogram("req_ms").observe(100.0)
        (r,) = tracker.evaluate()
        assert r["burn_s"] == 0.0
        clock[0] = 30.0
        (r,) = tracker.evaluate()
        assert r["burn_s"] == 30.0
        reg.reset()  # metric gone -> no_data -> burn clears
        (r,) = tracker.evaluate()
        assert r["state"] == "no_data" and r["burn_s"] == 0.0

    def test_soft_breach_never_unhealthy(self):
        reg = MetricsRegistry()
        tracker = SloTracker(
            [SloObjective(name="lat", kind="p99_ms_max",
                          metric="req_ms", threshold=1.0,
                          severity="soft")],
            registry=reg,
        )
        reg.histogram("req_ms").observe(100.0)
        assert tracker.healthy()
        assert tracker.breaches(evaluate=True) == []
        assert tracker.export()["objectives"][0]["state"] == "breach"

    def test_from_config_dict_and_json_path(self, tmp_path):
        config = {
            "objectives": [
                {"name": "lat", "kind": "p99_ms_max",
                 "metric": "req_ms", "threshold": 50.0},
                {"name": "qps", "kind": "rate_min",
                 "metric": "served", "threshold": 10,
                 "severity": "soft"},
            ]
        }
        reg = MetricsRegistry()
        t1 = SloTracker.from_config(config, reg)
        assert [o.name for o in t1.objectives] == ["lat", "qps"]
        path = tmp_path / "slo.json"
        path.write_text(json.dumps(config))
        t2 = SloTracker.from_config(str(path), reg)
        assert t2.objectives == t1.objectives


class TestHealthzIntegration:
    def test_breach_flips_healthz_503_and_recovery_flips_back(self):
        reg = MetricsRegistry()
        tracker = SloTracker(
            [SloObjective(name="lat", kind="p99_ms_max",
                          metric="plain.request_ms", threshold=5.0)],
            registry=reg,
        )
        with AdminServer(
            registry=reg, slo=tracker, device=DeviceTelemetry()
        ) as admin:
            base = f"http://127.0.0.1:{admin.port}"
            # No data yet: healthy.
            assert _get(base + "/healthz") == (200, "ok\n")
            reg.histogram("plain.request_ms").observe(100.0)
            status, body = _get(base + "/healthz")
            assert status == 503
            assert "slo breach: lat" in body
            # Recovery: the slow sample ages out (registry reset is the
            # test's stand-in); the very next probe is healthy again.
            reg.reset()
            assert _get(base + "/healthz") == (200, "ok\n")

    def test_statusz_shows_burn_table(self):
        reg = MetricsRegistry()
        tracker = SloTracker(
            [SloObjective(name="lat", kind="p99_ms_max",
                          metric="req_ms", threshold=5.0)],
            registry=reg,
        )
        reg.histogram("req_ms").observe(50.0)
        with AdminServer(
            registry=reg, slo=tracker, device=DeviceTelemetry()
        ) as admin:
            base = f"http://127.0.0.1:{admin.port}"
            status, body = _get(base + "/statusz")
            assert status == 200
            assert "SLO burn" in body and "UNHEALTHY" in body
            status, body = _get(base + "/statusz?format=json")
            state = json.loads(body)
            assert state["slo"]["healthy"] is False
            (obj,) = state["slo"]["objectives"]
            assert obj["name"] == "lat" and obj["state"] == "breach"

    def test_healthz_without_slo_is_bare_liveness(self):
        with AdminServer(
            registry=MetricsRegistry(), device=DeviceTelemetry()
        ) as admin:
            base = f"http://127.0.0.1:{admin.port}"
            assert _get(base + "/healthz") == (200, "ok\n")


class TestBreakerBurnSignal:
    def test_open_breaker_gauge_breaches_and_recovery_clears(self):
        # The Leader mirrors its helper-leg breaker into the
        # `leader.breaker_state` gauge (0 closed / 1 half-open /
        # 2 open), so a plain gauge_max objective at threshold 0 turns
        # an open breaker into SLO burn — and a closed one clears it.
        from distributed_point_functions_tpu.robustness.breaker import (
            STATE_CODES,
        )

        reg = MetricsRegistry()
        gauge = reg.gauge("leader.breaker_state")
        tracker = SloTracker(
            [SloObjective(name="helper_breaker", kind="gauge_max",
                          metric="leader.breaker_state", threshold=0.0)],
            registry=reg,
        )
        gauge.set(float(STATE_CODES["closed"]))
        (r,) = tracker.evaluate()
        assert r["state"] == "ok"
        gauge.set(float(STATE_CODES["open"]))
        (r,) = tracker.evaluate()
        assert r["state"] == "breach" and r["observed"] == 2.0
        assert tracker.breaches()
        gauge.set(float(STATE_CODES["closed"]))
        (r,) = tracker.evaluate()
        assert r["state"] == "ok"
        assert not tracker.breaches()
