"""Differential tests for the key-major (v2) plane expansion
(`pir/dense_eval_planes_v2.py`) against the limb kernel and the v1
planes path: bit-identical natural-order output, bitrev-leaves mode
consistency, and the staged-database involution that makes the
gather-free serving exit correct end to end.
"""

import numpy as np
import pytest

from distributed_point_functions_tpu.pir.client import DenseDpfPirClient
from distributed_point_functions_tpu.pir.dense_eval import (
    evaluate_selection_blocks,
    stage_keys,
)
from distributed_point_functions_tpu.pir.dense_eval_planes import (
    bitrev_permutation,
    evaluate_selection_blocks_planes,
)
from distributed_point_functions_tpu.pir.dense_eval_planes_v2 import (
    bitrev_block_permute_records,
    evaluate_selection_blocks_planes_v2,
)

RNG = np.random.default_rng(1234)


def _split(client, num_blocks):
    total = client._dpf._tree_levels_needed - 1
    el = min(max(0, (num_blocks - 1).bit_length()), total)
    return total - el, el


@pytest.mark.parametrize(
    "num_records,nq",
    [
        (1024, 7),    # walk > 0, keys need padding to 32
        (512, 64),    # exact key-group multiple, kg=2
        (300, 3),     # tiny: 3 blocks, expand < 2 levels
        (128, 1),     # single block, expand_levels == 0
    ],
)
def test_v2_matches_limb(num_records, nq):
    num_blocks = (num_records + 127) // 128
    client = DenseDpfPirClient.create(num_records, lambda pt, ci: pt)
    indices = [int(i) for i in RNG.integers(0, num_records, nq)]
    wl, el = _split(client, num_blocks)
    for keys in client._generate_key_pairs(indices):
        staged = stage_keys(keys)
        a = np.asarray(
            evaluate_selection_blocks(
                *staged,
                walk_levels=wl, expand_levels=el, num_blocks=num_blocks,
            )
        )
        b = np.asarray(
            evaluate_selection_blocks_planes_v2(
                *staged,
                walk_levels=wl, expand_levels=el, num_blocks=num_blocks,
            )
        )
        np.testing.assert_array_equal(a, b)


def test_v2_bitrev_matches_v1_bitrev():
    """Both planes paths must emit the same doubling-order leaves in
    bitrev_leaves mode (the gather-free serving contract)."""
    num_records, nq = 512, 33  # padded key axis, kg=2
    num_blocks = num_records // 128
    client = DenseDpfPirClient.create(num_records, lambda pt, ci: pt)
    indices = [int(i) for i in RNG.integers(0, num_records, nq)]
    wl, el = _split(client, num_blocks)
    keys0, _ = client._generate_key_pairs(indices)
    staged = stage_keys(keys0)
    a = np.asarray(
        evaluate_selection_blocks_planes(
            *staged,
            walk_levels=wl, expand_levels=el, num_blocks=num_blocks,
            bitrev_leaves=True, force_planes=True,
        )
    )
    b = np.asarray(
        evaluate_selection_blocks_planes_v2(
            *staged,
            walk_levels=wl, expand_levels=el, num_blocks=num_blocks,
            bitrev_leaves=True,
        )
    )
    np.testing.assert_array_equal(a, b)


def test_v2_pads_beyond_tree_capacity():
    num_records = 300  # tree capacity 4 blocks
    client = DenseDpfPirClient.create(num_records, lambda pt, ci: pt)
    indices = [0, 1, 150, 299]
    wl, el = _split(client, 4)
    keys0, _ = client._generate_key_pairs(indices)
    staged = stage_keys(keys0)
    a = np.asarray(
        evaluate_selection_blocks(
            *staged, walk_levels=wl, expand_levels=el, num_blocks=8
        )
    )
    b = np.asarray(
        evaluate_selection_blocks_planes_v2(
            *staged, walk_levels=wl, expand_levels=el, num_blocks=8
        )
    )
    np.testing.assert_array_equal(a, b)


def test_bitrev_staging_involution_end_to_end():
    """The gather-free serving identity: XOR inner product of
    bitrev-order selections against a block-bitrev-permuted database
    equals the natural-order product against the natural database, and
    the two parties' responses reconstruct the queried records."""
    from distributed_point_functions_tpu.ops.inner_product import (
        xor_inner_product,
    )

    num_records, nq = 1024, 5
    num_blocks = num_records // 128
    words = 8
    db = RNG.integers(0, 1 << 32, (num_records, words), dtype=np.uint32)
    db_rev = bitrev_block_permute_records(db)
    # Involution: applying twice restores the natural order.
    np.testing.assert_array_equal(
        bitrev_block_permute_records(db_rev), db
    )

    client = DenseDpfPirClient.create(num_records, lambda pt, ci: pt)
    indices = [int(i) for i in RNG.integers(0, num_records, nq)]
    wl, el = _split(client, num_blocks)
    keys0, keys1 = client._generate_key_pairs(indices)
    responses = []
    for keys in (keys0, keys1):
        staged = stage_keys(keys)
        sel_nat = evaluate_selection_blocks_planes_v2(
            *staged,
            walk_levels=wl, expand_levels=el, num_blocks=num_blocks,
        )
        sel_rev = evaluate_selection_blocks_planes_v2(
            *staged,
            walk_levels=wl, expand_levels=el, num_blocks=num_blocks,
            bitrev_leaves=True,
        )
        r_nat = np.asarray(xor_inner_product(db, sel_nat))
        r_rev = np.asarray(xor_inner_product(db_rev, sel_rev))
        np.testing.assert_array_equal(r_nat, r_rev)
        responses.append(r_rev)
    np.testing.assert_array_equal(
        responses[0] ^ responses[1], db[np.asarray(indices)]
    )


def test_bitrev_block_permute_rejects_bad_shapes():
    with pytest.raises(ValueError):
        bitrev_block_permute_records(np.zeros((100, 4), np.uint32))
    with pytest.raises(ValueError):
        bitrev_block_permute_records(np.zeros((3 * 128, 4), np.uint32))


def test_dense_server_serves_via_v2(monkeypatch):
    """DPF_TPU_EXPANSION=v2 serves the gather-free exit (doubling-order
    selections against the bitrev-block staging) with byte-identical
    responses, including a non-power-of-two block count, and the full
    plain protocol still reconstructs records."""
    from distributed_point_functions_tpu.pir import messages
    from distributed_point_functions_tpu.pir.database import (
        DenseDpfPirDatabase,
    )
    from distributed_point_functions_tpu.pir.server import DenseDpfPirServer
    from distributed_point_functions_tpu.testing import encrypt_decrypt

    num_records = 700  # 6 blocks -> bitrev staging pads to 8
    records = [RNG.bytes(20) for _ in range(num_records)]
    client = DenseDpfPirClient.create(num_records, encrypt_decrypt.encrypt)
    indices = [5, 42, 699]
    keys0, _ = client._generate_key_pairs(indices)
    req = messages.PirRequest(
        plain_request=messages.PlainRequest(dpf_keys=list(keys0))
    )
    server = DenseDpfPirServer.create_plain(DenseDpfPirDatabase(records))

    monkeypatch.setenv("DPF_TPU_EXPANSION", "limb")
    a = server.handle_request(req).dpf_pir_response.masked_response
    monkeypatch.setenv("DPF_TPU_EXPANSION", "v2")
    b = server.handle_request(req).dpf_pir_response.masked_response
    assert a == b

    # End-to-end under v2: both parties' responses reconstruct records.
    req0, req1 = client.create_plain_requests(indices)
    r0 = server.handle_request(req0)
    r1 = server.handle_request(req1)
    for i, idx in enumerate(indices):
        combined = bytes(
            x ^ y
            for x, y in zip(
                r0.dpf_pir_response.masked_response[i],
                r1.dpf_pir_response.masked_response[i],
            )
        )
        assert combined[: len(records[idx])] == records[idx]


def test_database_bitrev_inner_product_matches_natural():
    """inner_product_with(bitrev_blocks=True) against bitrev-order
    selections equals the natural product, and shape mismatches are
    rejected."""
    import jax.numpy as jnp

    from distributed_point_functions_tpu.pir.database import (
        DenseDpfPirDatabase,
    )
    from distributed_point_functions_tpu.pir.dense_eval_planes import (
        bitrev_permutation,
    )

    num_records = 700  # 6 blocks, bitrev staging 8 blocks
    records = [RNG.bytes(24) for _ in range(num_records)]
    db = DenseDpfPirDatabase(records)
    nb, nb_rev = db.num_selection_blocks, db.bitrev_block_count()
    assert (nb, nb_rev) == (6, 8)
    sel_nat = RNG.integers(0, 1 << 32, (3, nb, 4), dtype=np.uint32)
    # Natural block g sits at position bitrev(g) in the bitrev layout.
    perm = bitrev_permutation(3)
    sel_full = np.zeros((3, nb_rev, 4), np.uint32)
    sel_full[:, :nb] = sel_nat
    sel_rev = sel_full[:, perm]
    want = db.inner_product_with(jnp.asarray(sel_nat))
    got = db.inner_product_with(
        jnp.asarray(sel_rev), bitrev_blocks=True
    )
    assert want == got
    with pytest.raises(ValueError, match="exactly"):
        db.inner_product_with(
            jnp.asarray(sel_nat), bitrev_blocks=True
        )
