"""PhaseRecorder: per-request latency attribution (observability/phases).

Covers the ISSUE 6 contract: attributed phases + `other` sum to the
request's end-to-end time (within scheduler tolerance), nested brackets
attribute exclusive time, the batcher re-attributes worker-side phases
onto submitter records, Leader and Helper sessions produce their
distinct phase sets, and a disabled recorder is a no-op.
"""

import threading
import time

import pytest

from distributed_point_functions_tpu.observability import phases as pm
from distributed_point_functions_tpu.observability.phases import (
    PHASES,
    PhaseRecorder,
    RequestPhases,
)


@pytest.fixture
def recorder():
    rec = PhaseRecorder()
    old = pm.default_phase_recorder()
    pm.set_default_phase_recorder(rec)
    yield rec
    pm.set_default_phase_recorder(old)


def test_phases_sum_close_to_end_to_end(recorder):
    with recorder.request("unit") as req:
        with pm.phase("h2d_transfer"):
            time.sleep(0.01)
        with pm.phase("device_compute"):
            time.sleep(0.02)
        time.sleep(0.005)  # unattributed -> "other"
        total = req.elapsed_ms()
    wf = recorder.waterfall()["unit"]
    phase_sum = sum(p["total_ms"] for p in wf["phases"].values())
    e2e = wf["end_to_end_ms"]["total_ms"]
    # close() happens at context exit, microseconds after elapsed_ms()
    assert e2e == pytest.approx(total, rel=0.25)
    # attributed + other == e2e by construction (other is the remainder)
    assert phase_sum == pytest.approx(e2e, rel=0.01)
    assert wf["phases"]["h2d_transfer"]["total_ms"] >= 8.0
    assert wf["phases"]["device_compute"]["total_ms"] >= 15.0
    assert wf["phases"]["other"]["total_ms"] >= 3.0


def test_nested_brackets_attribute_exclusive_time(recorder):
    with recorder.request("unit"):
        with pm.phase("device_compute"):
            with pm.phase("h2d_transfer"):
                time.sleep(0.02)
            time.sleep(0.01)
    wf = recorder.waterfall()["unit"]["phases"]
    # The inner bracket's elapsed is deducted from the outer phase:
    # no double counting.
    assert wf["h2d_transfer"]["total_ms"] >= 15.0
    assert wf["device_compute"]["total_ms"] < 18.0
    assert wf["device_compute"]["total_ms"] >= 8.0


def test_out_of_band_record_and_share(recorder):
    with recorder.request("unit"):
        time.sleep(0.002)
        pm.record("helper_rtt", 40.0)
    wf = recorder.waterfall()["unit"]
    assert wf["phases"]["helper_rtt"]["total_ms"] == pytest.approx(40.0)
    # helper_rtt overlaps other phases by design: share may exceed 1.
    assert wf["phases"]["helper_rtt"]["share"] > 1.0


def test_nested_request_reuses_outer_record(recorder):
    with recorder.request("outer") as outer:
        with recorder.request("inner") as inner:
            assert inner is outer
            pm.record("respond", 5.0)
    wf = recorder.waterfall()
    assert "inner" not in wf
    assert wf["outer"]["phases"]["respond"]["total_ms"] == pytest.approx(5.0)


def test_fresh_request_isolates_rpc_halves(recorder):
    """fresh=True (the in-process RPC boundary) must NOT merge the
    server half's phases into the client half's record."""
    with recorder.request("client"):
        pm.record("queue", 1.0)
        with recorder.request("server", fresh=True):
            pm.record("device_compute", 2.0)
        # back on the client record
        pm.record("respond", 3.0)
    wf = recorder.waterfall()
    assert {"queue", "respond"} <= set(wf["client"]["phases"])
    assert "device_compute" not in wf["client"]["phases"]
    assert "device_compute" in wf["server"]["phases"]
    assert "queue" not in wf["server"]["phases"]


def test_cross_thread_attribution_by_reference(recorder):
    """The batcher pattern: a worker thread adds phases onto the
    submitting request's record."""
    with recorder.request("submitter") as req:
        worker = threading.Thread(
            target=lambda: req.add("device_compute", 12.5)
        )
        worker.start()
        worker.join()
    wf = recorder.waterfall()["submitter"]
    assert wf["phases"]["device_compute"]["total_ms"] == pytest.approx(12.5)


def test_closed_record_drops_late_adds():
    req = RequestPhases("x")
    req.add("queue", 1.0)
    final = req.close()
    req.add("queue", 99.0)  # a worker finishing after abandonment
    assert final == {"queue": 1.0}
    assert req.snapshot() == {"queue": 1.0}


def test_collect_does_not_feed_aggregates(recorder):
    with recorder.collect() as batch:
        pm.record("h2d_transfer", 7.0)
    assert batch.snapshot() == {"h2d_transfer": 7.0}
    assert recorder.waterfall() == {}


def test_disabled_recorder_is_noop():
    rec = PhaseRecorder(enabled=False)
    old = pm.default_phase_recorder()
    pm.set_default_phase_recorder(rec)
    try:
        with rec.request("unit") as req:
            assert req is None
            assert pm.current_request() is None
            with pm.phase("device_compute"):
                pass
            pm.record("queue", 5.0)
        with rec.collect() as batch:
            assert batch is None
        assert rec.waterfall() == {}
    finally:
        pm.set_default_phase_recorder(old)


def test_waterfall_orders_phases_canonically(recorder):
    with recorder.request("unit"):
        pm.record("respond", 1.0)
        pm.record("queue", 1.0)
        pm.record("device_compute", 1.0)
    names = list(recorder.waterfall()["unit"]["phases"])
    order = {n: i for i, n in enumerate(PHASES)}
    assert names == sorted(names, key=lambda n: order[n])


def test_registry_mirror(recorder):
    from distributed_point_functions_tpu.serving.metrics import (
        MetricsRegistry,
    )

    reg = MetricsRegistry()
    recorder.bind_registry(reg)
    with recorder.request("unit"):
        pm.record("queue", 2.0)
    export = reg.export()
    hist_names = set(export["histograms"])
    assert any("phase_ms" in n and "queue" in n for n in hist_names)
    assert any("phase_total_ms" in n for n in hist_names)


def test_trace_attachment(recorder):
    from distributed_point_functions_tpu.observability import tracing

    with tracing.trace_request("t.request", record=False) as trace:
        with recorder.request("unit"):
            pm.record("device_compute", 4.0)
    assert trace.attrs["phases"]["device_compute"] == pytest.approx(4.0)
    assert trace.attrs["phase_total_ms"] >= 0.0


# ---------------------------------------------------------------------------
# Serving integration: Leader vs Helper phase sets
# ---------------------------------------------------------------------------


def test_leader_vs_helper_phase_sets(recorder):
    """A two-party request produces a leader waterfall WITH helper_rtt
    and a helper waterfall WITHOUT it (the Helper has no helper leg);
    both see device phases via the batcher re-attribution."""
    import numpy as np

    from distributed_point_functions_tpu.pir import (
        DenseDpfPirClient,
        DenseDpfPirDatabase,
    )
    from distributed_point_functions_tpu.serving import (
        HelperSession,
        InProcessTransport,
        LeaderSession,
        ServingConfig,
    )
    from distributed_point_functions_tpu.testing import encrypt_decrypt

    rng = np.random.default_rng(99)
    builder = DenseDpfPirDatabase.Builder()
    records = [
        bytes(rng.integers(0, 256, 16, dtype=np.uint8)) for _ in range(64)
    ]
    for r in records:
        builder.insert(r)
    database = builder.build()
    config = ServingConfig(max_batch_size=4, max_wait_ms=5.0)

    helper = HelperSession(database, encrypt_decrypt.decrypt, config)
    leader = LeaderSession(
        database, InProcessTransport(helper.handle_wire), config
    )
    with helper, leader:
        client = DenseDpfPirClient.create(
            len(records), encrypt_decrypt.encrypt
        )
        request, state = client.create_request([3, 42])
        response = leader.handle_request(request)
        got = client.handle_response(response, state)
    assert got == [records[3], records[42]]

    wf = recorder.waterfall()
    assert "leader" in wf and "helper" in wf
    leader_phases = set(wf["leader"]["phases"])
    helper_phases = set(wf["helper"]["phases"])
    assert "helper_rtt" in leader_phases
    assert "helper_rtt" not in helper_phases
    # Both roles ran a batched device step: queue + a device phase.
    for phases in (leader_phases, helper_phases):
        assert "queue" in phases
        assert phases & {"compile", "device_compute"}
