"""Device-utilization timeline and flight-data TSDB tests.

Attribution tests inject a deterministic clock into the
`UtilizationTracker` so busy/idle splits and window boundaries are
exact; the batcher integration runs the real worker/completion threads
against a stub evaluator and asserts the live bubble breakdown. TSDB
tests flood the store past its series budget and assert the bound
holds; sampler tests drive `sample_once` with a fake clock and check
the anomaly watch journals `util.anomaly`.
"""

import json
import threading
import time
import urllib.request

import pytest

from distributed_point_functions_tpu.observability import AdminServer
from distributed_point_functions_tpu.observability.events import EventJournal
from distributed_point_functions_tpu.observability.timeseries import (
    AnomalyWatch,
    MetricsSampler,
    TimeSeriesStore,
    render_sparklines,
    sparkline,
)
from distributed_point_functions_tpu.observability.utilization import (
    BUBBLE_CAUSES,
    UtilizationTracker,
)
from distributed_point_functions_tpu.serving.batcher import DynamicBatcher
from distributed_point_functions_tpu.serving.metrics import (
    MetricsRegistry,
    labeled_name,
)


class FakeClock:
    def __init__(self, t=0.0):
        self.t = t

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


class CapturingJournal:
    def __init__(self):
        self.events = []

    def emit(self, kind, message, **fields):
        self.events.append((kind, message, fields))


# ---------------------------------------------------------------------------
# UtilizationTracker: exact attribution under an injected clock
# ---------------------------------------------------------------------------


def test_synthetic_schedule_reproduces_exact_attribution():
    clock = FakeClock()
    tracker = UtilizationTracker(window_s=10.0, clock=clock)
    # Window 1: 6 s busy, 3 s empty queue, 1 s batch wait.
    tracker.record_busy(6.0)
    tracker.record_idle("empty_queue", 3.0)
    tracker.record_idle("batch_wait", 1.0)
    clock.advance(10.0)
    # Window 2: 2 s busy, 1 s staging sync, 1 s pipeline full.
    tracker.record_busy(2.0)
    tracker.record_idle("staging_sync", 1.0)
    tracker.record_idle("pipeline_full", 1.0)
    clock.advance(10.0)
    snap = tracker.export()
    assert len(snap["windows"]) == 2
    w1, w2 = snap["windows"]
    assert w1["duty_cycle_pct"] == 60.0
    assert w1["idle_s"] == {"empty_queue": 3.0, "batch_wait": 1.0}
    assert w1["device_feed_efficiency"] == 0.6
    assert w2["duty_cycle_pct"] == 50.0
    assert w2["idle_s"] == {"staging_sync": 1.0, "pipeline_full": 1.0}
    totals = snap["totals"]
    assert totals["busy_s"] == 8.0
    assert totals["idle_total_s"] == 6.0
    # The causes sum exactly to measured idle.
    assert sum(totals["idle_s"].values()) == totals["idle_total_s"]
    assert totals["duty_cycle_pct"] == pytest.approx(100 * 8 / 14, abs=0.01)
    assert totals["bubbles"] == 4


def test_unknown_cause_degrades_to_other_and_brackets_measure():
    clock = FakeClock()
    tracker = UtilizationTracker(window_s=100.0, clock=clock)
    tracker.record_idle("not_a_cause", 1.0)
    with tracker.busy():
        clock.advance(2.0)
    with tracker.idle("batch_wait"):
        clock.advance(0.5)
    snap = tracker.export()
    assert snap["current"]["idle_s"] == {"other": 1.0, "batch_wait": 0.5}
    assert snap["totals"]["busy_s"] == 2.0
    assert "not_a_cause" not in BUBBLE_CAUSES


def test_empty_windows_are_skipped_and_timeline_is_bounded():
    clock = FakeClock()
    tracker = UtilizationTracker(window_s=1.0, max_windows=5, clock=clock)
    clock.advance(50.0)  # dead air: no windows
    assert tracker.export()["windows"] == []
    for _ in range(10):
        tracker.record_busy(0.5)
        clock.advance(1.0)
    windows = tracker.export()["windows"]
    assert len(windows) == 5  # deque bound holds


def test_registry_mirror_and_reset():
    clock = FakeClock()
    registry = MetricsRegistry()
    tracker = UtilizationTracker(window_s=1.0, clock=clock)
    tracker.bind_registry(registry)
    tracker.record_busy(0.75)
    tracker.record_idle("helper_rtt", 0.25)
    clock.advance(1.0)
    assert tracker.last_duty_cycle_pct() == 75.0
    export = registry.export()
    assert export["gauges"]["util.duty_cycle_pct"] == 75.0
    assert export["gauges"]["util.device_feed_efficiency"] == 0.75
    name = labeled_name("util.bubble_ms", {"cause": "helper_rtt"})
    assert export["histograms"][name]["count"] == 1
    tracker.reset()
    snap = tracker.export()
    assert snap["windows"] == [] and snap["totals"]["busy_s"] == 0.0


def test_straggler_skew_journals_event():
    clock = FakeClock()
    journal = CapturingJournal()
    tracker = UtilizationTracker(
        window_s=1.0, straggler_band=0.25, clock=clock, journal=journal
    )
    # Shard 0 busy the whole window, shard 3 nearly idle: skew 0.9.
    tracker.record_shard_busy(0, 1.0)
    tracker.record_shard_busy(3, 0.1)
    clock.advance(1.0)
    snap = tracker.export()
    assert snap["stragglers"] == 1
    assert snap["shards"][0]["busy_s"] == 1.0
    (kind, message, fields) = journal.events[0]
    assert kind == "util.straggler"
    assert fields["max_shard"] == 0 and fields["min_shard"] == 3
    assert fields["skew"] == pytest.approx(0.9)
    # Balanced shards stay quiet.
    tracker.record_shard_busy(0, 0.5)
    tracker.record_shard_busy(3, 0.5)
    clock.advance(1.0)
    assert tracker.export()["stragglers"] == 1


# ---------------------------------------------------------------------------
# Batcher integration: live threads, real causes
# ---------------------------------------------------------------------------


def test_batcher_reports_busy_and_bubbles():
    tracker = UtilizationTracker(window_s=60.0)
    done = threading.Event()

    def evaluate(keys):
        time.sleep(0.01)
        return [k * 2 for k in keys]

    with DynamicBatcher(
        evaluate, max_batch_size=4, max_wait_ms=5.0, pipeline_depth=2
    ) as batcher:
        batcher.set_utilization(tracker)
        results = []

        def client():
            for _ in range(3):
                results.extend(batcher.submit([1, 2]))
                time.sleep(0.02)  # gaps -> empty_queue bubbles
            done.set()

        t = threading.Thread(target=client)
        t.start()
        t.join(timeout=10)
    assert done.is_set() and results == [2, 4] * 3
    snap = tracker.export()
    current = snap["current"]
    assert current["busy_s"] > 0.0  # evaluations credited
    # The worker saw typed bubbles: waiting for the first request
    # and/or holding the batch window open.
    assert current["idle_s"], snap
    assert set(current["idle_s"]) <= set(BUBBLE_CAUSES)
    assert {"empty_queue", "batch_wait"} & set(current["idle_s"])
    # Both halves of the pipeline reported.
    assert snap["threads"]["worker"]["busy_s"] > 0.0
    assert "completer" in snap["threads"]
    # Worker-tracked time (busy + attributed idle) stays within the
    # worker's wall clock — attribution never invents time.
    worker = snap["threads"]["worker"]
    assert worker["busy_s"] + worker["idle_s"] <= 10.0


# ---------------------------------------------------------------------------
# TimeSeriesStore: budgets and ring behavior
# ---------------------------------------------------------------------------


def test_store_budget_holds_under_labeled_metric_flood():
    clock = FakeClock()
    store = TimeSeriesStore(
        tiers=((1.0, 16), (8.0, 8)), max_series=24, clock=clock
    )
    for i in range(400):
        name = labeled_name("flood.metric", {"tenant": f"t{i}"})
        store.record(name, float(i), t=float(i % 64))
    export = store.export(now=64.0)
    assert export["series_count"] <= 24
    assert export["dropped_series"] == 400 - 24
    assert store.occupancy() <= store.slot_budget()
    assert store.slot_budget() == 24 * (16 + 8)
    assert store.approx_bytes() > 0


def test_ring_laps_expire_old_points_and_last_sample_wins():
    clock = FakeClock()
    store = TimeSeriesStore(tiers=((1.0, 4),), max_series=4, clock=clock)
    for i in range(10):
        store.record("s", float(i), t=float(i))
    points = store.series("s", tier=0, now=10.0)
    assert [v for _, v in points] == [6.0, 7.0, 8.0, 9.0]
    # Two samples in the same slot: the later write wins.
    store.record("s", 100.0, t=9.2)
    points = store.series("s", tier=0, now=10.0)
    assert points[-1][1] == 100.0
    assert store.occupancy() <= store.slot_budget()


def test_query_range_aligned_grid_marks_gaps_and_lap_expiry():
    clock = FakeClock()
    store = TimeSeriesStore(tiers=((1.0, 4),), max_series=4, clock=clock)
    for i in range(10):
        store.record("s", float(i), t=float(i))
    # Slots 0..5 lapped out of the 4-slot ring: the grid still covers
    # the requested window (clamped to one ring length) with None.
    step_s, samples = store.query_range("s", 5.0, 9.0, tier=0, now=10.0)
    assert step_s == 1.0
    assert samples == [
        (6.0, 6.0), (7.0, 7.0), (8.0, 8.0), (9.0, 9.0),
    ]
    # A gap inside the live window is None at its grid slot, not
    # silently skipped (the forecaster needs the grid).
    store.record("gappy", 1.0, t=20.0)
    store.record("gappy", 3.0, t=22.0)
    _, samples = store.query_range("gappy", 20.0, 22.0, tier=0, now=22.5)
    assert samples == [(20.0, 1.0), (21.0, None), (22.0, 3.0)]


def test_query_range_tier_fallthrough_and_validation():
    clock = FakeClock()
    store = TimeSeriesStore(
        tiers=((1.0, 4), (10.0, 12)), max_series=4, clock=clock
    )
    for i in range(40):
        store.record("s", float(i), t=float(i))
    # tier=None: a window the 4s fine tier cannot cover falls through
    # to the 10s tier; a short recent window stays on the fine tier.
    step_s, _ = store.query_range("s", 0.0, 39.0, now=40.0)
    assert step_s == 10.0
    step_s, _ = store.query_range("s", 37.0, 39.0, now=40.0)
    assert step_s == 1.0
    # Unknown series: the aligned grid of Nones, never an error (the
    # read side must not race series creation).
    step_s, samples = store.query_range("missing", 37.0, 39.0, now=40.0)
    assert step_s == 1.0
    assert samples == [(37.0, None), (38.0, None), (39.0, None)]
    with pytest.raises(ValueError):
        store.query_range("s", 5.0, 1.0, now=40.0)
    with pytest.raises(ValueError):
        store.query_range("s", 0.0, 1.0, tier=7, now=40.0)


def test_sparkline_rendering():
    assert sparkline([]) == ""
    assert len(sparkline([1, 2, 3, 4])) == 4
    assert sparkline([5.0, 5.0]) == "▄▄"
    clock = FakeClock()
    store = TimeSeriesStore(tiers=((1.0, 8),), clock=clock)
    for i in range(8):
        store.record("ramp", float(i), t=float(i))
    text = render_sparklines(store, tier=0)
    assert "ramp" in text


# ---------------------------------------------------------------------------
# MetricsSampler: deterministic sampling, anomaly watch, shutdown
# ---------------------------------------------------------------------------


class FakeRegistry:
    def __init__(self):
        self.p99 = 5.0

    def export(self):
        return {
            "counters": {"leader.requests": 10, "unselected.x": 1},
            "gauges": {"device.hbm_peak": 2.0},
            "histograms": {"helper.rtt_ms": {"p50": 1.0, "p99": self.p99}},
        }


def test_sampler_selects_series_and_samples_utilization():
    clock = FakeClock()
    tracker = UtilizationTracker(window_s=1.0, clock=clock)
    tracker.record_busy(0.9)
    tracker.record_idle("batch_wait", 0.1)
    clock.advance(1.0)
    sampler = MetricsSampler(
        registry=FakeRegistry(), utilization=tracker, clock=clock
    )
    written = sampler.sample_once()
    assert written > 0
    names = sampler.store.names()
    assert "leader.requests.count" in names
    assert "helper.rtt_ms.p99" in names
    assert "util.duty_cycle_pct" in names
    assert "util.idle_s.batch_wait" in names
    assert "unselected.x.count" not in names
    points = sampler.store.series("util.duty_cycle_pct")
    assert points[-1][1] == 90.0


def test_anomaly_watch_journals_spike_into_event_journal():
    clock = FakeClock()
    journal = EventJournal(clock=clock)
    reg = FakeRegistry()
    sampler = MetricsSampler(
        registry=reg,
        clock=clock,
        watch=AnomalyWatch(min_samples=3, journal=journal),
    )
    for _ in range(6):
        sampler.sample_once()
        clock.advance(1.0)
    reg.p99 = 500.0  # injected stall: p99 spikes 100x
    sampler.sample_once()
    kinds = [e["kind"] for e in journal.tail(n=20)]
    assert "util.anomaly" in kinds
    event = [e for e in journal.tail(n=20) if e["kind"] == "util.anomaly"][-1]
    assert event["series"] == "helper.rtt_ms.p99"
    assert event["direction"] == "spike"
    assert sampler.export()["watch"]["anomalies"] >= 1


def test_anomaly_watch_ignores_near_zero_series():
    """Regression: a quiet counter ticking 0 -> 1 is noise, not a 3x
    spike — the absolute noise floor (`min_mean`) must keep an idle
    series (e.g. `fleet.spillover`) out of the journal."""
    journal = EventJournal(clock=FakeClock())
    watch = AnomalyWatch(min_samples=3, journal=journal)
    for t in range(6):
        assert watch.observe("fleet.spillover", 0.0, float(t)) is None
    # The 0 -> 1 tick: infinitely above the trailing mean of 0, but
    # below ratio * min_mean + floor.
    assert watch.observe("fleet.spillover", 1.0, 6.0) is None
    assert journal.tail(n=10, kind="util.anomaly") == []
    assert watch.export()["anomalies"] == 0
    # The floor only mutes near-zero series: a real spike on the same
    # watch still fires.
    for t in range(6):
        watch.observe("busy", 100.0, float(t))
    record = watch.observe("busy", 1000.0, 6.0)
    assert record is not None and record["direction"] == "spike"
    # And a collapse on a quiet series stays quiet (mean below the
    # judged floor).
    for t in range(6):
        watch.observe("quiet", 0.4, float(t))
    assert watch.observe("quiet", 0.0, 6.0) is None


def test_sampler_thread_shuts_down_cleanly_with_admin_server():
    sampler = MetricsSampler(
        registry=FakeRegistry(), period_s=0.05, jitter_frac=0.1
    )
    sampler.start()
    with AdminServer(timeseries=sampler) as admin:
        assert sampler.running
        deadline = time.monotonic() + 5.0
        while not sampler.store.names() and time.monotonic() < deadline:
            time.sleep(0.01)
        base = f"http://127.0.0.1:{admin.port}"
        body = json.load(
            urllib.request.urlopen(base + "/timeseriesz?format=json")
        )
        assert body["store"]["series_count"] > 0
        assert body["sampler"]["running"] is True
    # AdminServer.stop() stopped the sampler with the listener.
    assert not sampler.running
    assert sampler.export()["samples_taken"] > 0
