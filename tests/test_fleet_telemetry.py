"""Fleet telemetry plane: scopes, aggregation, SLOs, bundles, hops.

The contracts under test: `ReplicaTelemetry` threads one replica's
identity into its journal/registry/TSDB scope and scrapes to a plain
dict; `FleetTelemetry.sample()` derives the fleet gauges (routable
floor, QPS from request-count deltas, generation lag, spillover rate,
rotation staleness, probe age) and grades the fleet SLOs; a hard
breach degrades the fleet healthz verdict and fires ONE fleet-wide
debug bundle holding every replica's section plus the merged timeline;
the router stamps `(replica, attempt, reason)` hop records so a
primary-shed -> spillover-served request reads as one trace; and the
admin endpoints `/fleet-statusz` / `/fleet-timelinez` render it all in
text and JSON.
"""

import json
import os
import time
import urllib.error
import urllib.request
from types import SimpleNamespace

import pytest

from distributed_point_functions_tpu.fleet import (
    FleetRouter,
    FleetTelemetry,
    Replica,
    ReplicaSet,
    ReplicaTelemetry,
)
from distributed_point_functions_tpu.observability import tracing
from distributed_point_functions_tpu.observability.admin import AdminServer
from distributed_point_functions_tpu.observability.bundle import BundleManager
from distributed_point_functions_tpu.observability.events import EventJournal
from distributed_point_functions_tpu.serving.batcher import Overloaded
from distributed_point_functions_tpu.serving.metrics import MetricsRegistry


class FakeClock:
    def __init__(self, t=100.0):
        self.t = float(t)

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt
        return self.t


class StubCapacity:
    def __init__(self, device_ms=1.0):
        self.device_ms = float(device_ms)
        self.replica = None

    def set_replica(self, rid):
        self.replica = rid

    def price_export(self, num_keys=8, num_blocks=None):
        return {
            "replica": self.replica,
            "probe_keys": num_keys,
            "device_ms": self.device_ms,
            "device_ms_per_key": self.device_ms / max(1, num_keys),
            "bytes_peak": 0,
            "queries_per_sec": 100.0,
        }


class StubSession:
    """Duck-typed leader session that records request latency like the
    real ones (`<role>.request_ms`) so QPS derivation has a source."""

    def __init__(self, name, generation=0, shed=None):
        self.name = name
        self.shed = shed  # None, or an Overloaded to raise
        self.breaker = None
        self.degraded = False
        self.metrics = MetricsRegistry()
        self.server = SimpleNamespace(
            database=SimpleNamespace(generation=generation), role="plain"
        )

    def handle_request(self, request, deadline=None, tenant="default"):
        if self.shed is not None:
            raise self.shed
        self.metrics.histogram("plain.request_ms").observe(1.0)
        return f"resp:{self.name}"


def make_replica(rid, generation=0, device_ms=1.0, shed=None):
    return Replica(
        rid,
        StubSession(rid, generation, shed),
        capacity=StubCapacity(device_ms),
    )


def make_fleet(clock, n=3, journal=None, **telemetry_kwargs):
    journal = journal if journal is not None else EventJournal(
        capacity=64, clock=clock
    )
    replica_set = ReplicaSet(journal=journal)
    replicas = [replica_set.add(make_replica(f"r{i}")) for i in range(n)]
    telemetry = FleetTelemetry(
        replica_set, journal=journal, clock=clock, **telemetry_kwargs
    )
    for replica in replicas:
        telemetry.scope(replica)
    return replica_set, replicas, telemetry


@pytest.fixture
def recorder():
    prev = tracing.default_recorder()
    rec = tracing.set_default_recorder(tracing.FlightRecorder())
    yield rec
    tracing.set_default_recorder(prev)


# ---------------------------------------------------------------------------
# ReplicaTelemetry
# ---------------------------------------------------------------------------


class TestReplicaTelemetry:
    def test_scope_is_replica_tagged(self):
        clock = FakeClock()
        telemetry = ReplicaTelemetry("r7", clock=clock)
        event = telemetry.journal.emit("breaker.transition", "open")
        assert event["replica"] == "r7"
        assert telemetry.journal.scope == "r7"

    def test_adopt_collects_session_registries(self):
        clock = FakeClock()
        replica = make_replica("r0")
        telemetry = ReplicaTelemetry("r0", clock=clock).adopt(replica)
        replica.leader.handle_request("q")
        export = telemetry.metrics_export()
        assert export["histograms"]["plain.request_ms"]["count"] == 1
        assert telemetry.request_count() == 1

    def test_scrape_shape(self):
        clock = FakeClock()
        replica = make_replica("r0")
        telemetry = ReplicaTelemetry("r0", clock=clock).adopt(replica)
        replica.leader.handle_request("q")
        telemetry.sample_once(clock())
        scrape = telemetry.scrape()
        assert scrape["replica_id"] == "r0"
        assert set(scrape) == {
            "replica_id", "metrics", "journal", "utilization", "timeseries",
        }
        assert scrape["metrics"]["histograms"]["plain.request_ms"]["count"] == 1
        assert scrape["timeseries"]["series_count"] >= 1


# ---------------------------------------------------------------------------
# FleetTelemetry aggregation
# ---------------------------------------------------------------------------


class TestFleetSample:
    def test_routable_and_qps_derivation(self):
        clock = FakeClock()
        _, replicas, telemetry = make_fleet(clock)
        telemetry.sample()  # establish QPS marks
        for _ in range(10):
            replicas[0].leader.handle_request("q")
        for _ in range(5):
            replicas[1].leader.handle_request("q")
        clock.advance(10.0)
        result = telemetry.sample()
        assert result["routable"] == 3
        assert result["qps"] == pytest.approx(1.5)
        gauges = telemetry.registry.export()["gauges"]
        assert gauges["fleet.replica_qps{replica=r0}"] == pytest.approx(1.0)
        assert gauges["fleet.replica_qps{replica=r1}"] == pytest.approx(0.5)
        assert gauges["fleet.qps"] == pytest.approx(1.5)
        # The derived gauges also land as flat fleet TSDB series.
        assert "fleet.qps" in result["series"]
        assert "fleet.replica_qps.r0" in result["series"]
        assert telemetry.store.series("fleet.qps", now=clock())[-1][1] == (
            pytest.approx(1.5)
        )

    def test_generation_lag_per_replica(self):
        clock = FakeClock()
        journal = EventJournal(capacity=64, clock=clock)
        replica_set = ReplicaSet(journal=journal)
        replica_set.add(make_replica("r0", generation=5))
        replica_set.add(make_replica("r1", generation=3))
        telemetry = FleetTelemetry(
            replica_set, journal=journal, clock=clock
        )
        for replica in replica_set.replicas():
            telemetry.scope(replica)
        result = telemetry.sample()
        assert result["generation_lag"] == {"r0": 0, "r1": 2}

    def test_rotation_staleness_and_probe_age_feed_gauges(self):
        clock = FakeClock()
        _, _, telemetry = make_fleet(clock)
        telemetry.set_rotation(
            SimpleNamespace(
                export=lambda: {"last_report": {"staleness_ms": 1250.0}}
            )
        )
        telemetry.set_probe(SimpleNamespace(last_pass_age_s=lambda: 42.0))
        telemetry.sample()
        gauges = telemetry.registry.export()["gauges"]
        assert gauges["fleet.rotation_staleness_ms"] == 1250.0
        assert gauges["fleet.divergence_probe_age_s"] == 42.0

    def test_merged_metrics_view_carries_replica_rows(self):
        clock = FakeClock()
        _, replicas, telemetry = make_fleet(clock)
        for replica in replicas:
            replica.leader.handle_request("q")
        merged = telemetry.metrics()
        hist = merged["histograms"]["plain.request_ms"]
        assert hist["count"] == 3
        assert hist["replicas"] == ["r0", "r1", "r2"]
        assert "fleet" in merged

    def test_export_is_statusz_shaped(self):
        clock = FakeClock()
        _, _, telemetry = make_fleet(clock)
        telemetry.sample()
        state = telemetry.export()
        assert sorted(state["replicas"]) == ["r0", "r1", "r2"]
        for scrape in state["replicas"].values():
            assert scrape["state"] == "serving"
        assert state["merged"]["replicas"] == ["r0", "r1", "r2"]
        assert state["samples"] == 1
        assert {o["name"] for o in state["slo"]["objectives"]} == {
            "fleet_routable_floor",
            "fleet_rotation_staleness",
            "fleet_probe_freshness",
            "fleet_spillover_rate",
        }

    def test_export_federates_per_replica_workloads(self):
        from distributed_point_functions_tpu.observability.workload import (
            WorkloadObservatory,
        )

        clock = FakeClock()
        _, _, telemetry = make_fleet(clock, n=2)
        # Workloads are opt-in: no scrape carries one yet, so the
        # merged view is absent rather than empty.
        assert "workload" not in telemetry.export()
        for rid, keys in (("r0", [5] * 20 + [1] * 5), ("r1", [5] * 10)):
            observatory = WorkloadObservatory(top_k=8)
            for key in keys:
                observatory.observe(key_indices=(key,), tenant=rid)
            telemetry.scopes()[rid].set_workload(observatory)
        merged = telemetry.export()["workload"]
        assert merged["replicas"] == ["r0", "r1"]
        assert merged["observations"] == 35
        # Key 5's count sums across both replicas' top-K digests.
        assert merged["top_keys"][0]["key"] == 5
        assert merged["top_keys"][0]["count"] == 30
        assert merged["tenants"]["r0"]["observations"] == 25


# ---------------------------------------------------------------------------
# Fleet SLOs -> healthz
# ---------------------------------------------------------------------------


class TestFleetHealth:
    def test_routable_floor_degrades_and_recovers(self):
        clock = FakeClock()
        replica_set, _, telemetry = make_fleet(clock)
        assert telemetry.healthz()["status"] == "ok"
        replica_set.shed("r1", reason="test")
        replica_set.shed("r2", reason="test")
        clock.advance(1.0)
        verdict = telemetry.healthz()
        assert verdict["status"] == "degraded"
        assert verdict["healthy"] is False
        assert verdict["routable"] == 1
        assert [b["name"] for b in verdict["breaches"]] == [
            "fleet_routable_floor"
        ]
        assert verdict["replicas"]["r1"] == "draining"
        replica_set.readmit("r1", reason="test")
        replica_set.readmit("r2", reason="test")
        clock.advance(1.0)
        assert telemetry.healthz()["status"] == "ok"

    def test_soft_breach_does_not_degrade(self):
        clock = FakeClock()
        _, _, telemetry = make_fleet(clock)
        # Spillover rate over the ceiling is soft: pages, doesn't drain.
        telemetry.set_router(
            SimpleNamespace(
                spillover_rate_pct=lambda: 99.0, export=lambda: {}
            )
        )
        verdict = telemetry.healthz()
        assert verdict["status"] == "ok"
        records = {r["name"]: r for r in telemetry.slo.evaluate()}
        assert records["fleet_spillover_rate"]["state"] == "breach"

    def test_one_fleet_bundle_with_every_replica_section(self, tmp_path):
        clock = FakeClock()
        replica_set, _, telemetry = make_fleet(clock)
        bundles = BundleManager(
            directory=str(tmp_path), cooldown_s=60.0, clock=clock,
            journal=telemetry.journal,
        )
        telemetry.wire_bundles(bundles)
        telemetry.sample()  # healthy baseline, no burn
        assert bundles.export()["fired"] == 0
        replica_set.kill("r1", reason="test")
        replica_set.kill("r2", reason="test")
        clock.advance(1.0)
        telemetry.sample()  # burn transition -> ONE capture
        clock.advance(1.0)
        telemetry.sample()  # continuing breach: no new transition
        export = bundles.export()
        assert export["fired"] == 1
        (entry,) = export["bundles"]
        assert entry["reason"] == "slo_hard_breach"
        for source in (
            "replica_r0", "replica_r1", "replica_r2",
            "fleet_timeline", "fleet_status",
        ):
            assert entry["sources"][source] == "ok"
            assert os.path.exists(
                os.path.join(entry["path"], f"{source}.json")
            )
        with open(os.path.join(entry["path"], "fleet_timeline.json")) as f:
            timeline = json.load(f)
        kinds = [e["kind"] for e in timeline["events"]]
        assert "fleet.replica_state" in kinds

    def test_probe_failure_triggers_fleet_bundle(self, tmp_path):
        clock = FakeClock()
        _, _, telemetry = make_fleet(clock)

        class StubProbe:
            def __init__(self):
                self.listeners = []

            def add_failure_listener(self, cb):
                self.listeners.append(cb)

            def last_pass_age_s(self):
                return 1.0

            def export(self):
                return {"history": [1, 2, 3], "cycles": 3}

        probe = StubProbe()
        telemetry.set_probe(probe)
        bundles = BundleManager(
            directory=str(tmp_path), cooldown_s=60.0, clock=clock,
            journal=telemetry.journal,
        )
        telemetry.wire_bundles(bundles)
        (listener,) = probe.listeners
        listener({"kind": "divergence", "status": "fail", "seq": 9})
        export = bundles.export()
        assert export["fired"] == 1
        assert export["bundles"][0]["reason"] == "probe_failure"


# ---------------------------------------------------------------------------
# Fleet timeline
# ---------------------------------------------------------------------------


class TestFleetTimeline:
    def test_replica_and_fleet_events_interleave_with_attribution(self):
        # Real clocks here: the rebase anchors journals to one another
        # on the wall clock, so deterministic cross-journal order needs
        # t_wall and t_mono to advance together.
        replica_set, _, telemetry = make_fleet(time.monotonic)
        scopes = telemetry.scopes()
        scopes["r0"].journal.emit("breaker.transition", "closed->open")
        time.sleep(0.005)
        replica_set.shed("r0", reason="breaker open")
        time.sleep(0.005)
        scopes["r1"].journal.emit("snapshot.flip", "gen 2")
        timeline = telemetry.timeline()
        rows = [
            (e["replica"], e["kind"])
            for e in timeline["events"]
            if e["kind"] in (
                "breaker.transition", "fleet.replica_state", "snapshot.flip",
            )
        ]
        assert rows == [
            ("r0", "breaker.transition"),
            ("r0", "fleet.replica_state"),
            ("r1", "snapshot.flip"),
        ]
        assert set(timeline["replicas"]) == {"r0", "r1", "r2", "fleet"}

    def test_kind_filter_and_n(self):
        clock = FakeClock()
        _, _, telemetry = make_fleet(clock)
        scopes = telemetry.scopes()
        for i in range(4):
            scopes["r0"].journal.emit("snapshot.flip", f"gen {i}")
            scopes["r0"].journal.emit("other", "noise")
            clock.advance(0.1)
        timeline = telemetry.timeline(n=2, kind="snapshot")
        assert [e["message"] for e in timeline["events"]] == [
            "gen 2", "gen 3",
        ]


# ---------------------------------------------------------------------------
# Router hop stitching + spillover counters (satellite 3)
# ---------------------------------------------------------------------------


class TestRouterHops:
    def test_spillover_trace_shows_both_hops(self, recorder):
        journal = EventJournal(capacity=64)
        replica_set = ReplicaSet(journal=journal)
        replica_set.add(
            make_replica(
                "r0",
                device_ms=1.0,
                shed=Overloaded("full", retry_after_s=0.1, reason="queue_full"),
            )
        )
        replica_set.add(make_replica("r1", device_ms=2.0))
        metrics = MetricsRegistry()
        router = FleetRouter(
            replica_set, journal=journal, metrics=metrics
        )
        assert router.handle_request("q", tenant="t") == "resp:r1"
        (trace,) = recorder.dump()["recent"]
        assert trace["name"] == "fleet.request"
        assert trace["attrs"]["hops"] == [
            {
                "replica": "r0", "attempt": 0,
                "reason": "primary", "outcome": "shed",
            },
            {
                "replica": "r1", "attempt": 1,
                "reason": "spillover:queue_full", "outcome": "served",
            },
        ]
        counters = metrics.export()["counters"]
        assert counters[
            "fleet.spillover{from=r0,reason=queue_full,to=r1}"
        ] == 1

    def test_primary_served_is_one_hop(self, recorder):
        replica_set = ReplicaSet(journal=EventJournal(capacity=64))
        replica_set.add(make_replica("r0"))
        router = FleetRouter(replica_set, metrics=MetricsRegistry())
        router.handle_request("q", tenant="t")
        (trace,) = recorder.dump()["recent"]
        assert trace["attrs"]["hops"] == [
            {
                "replica": "r0", "attempt": 0,
                "reason": "primary", "outcome": "served",
            }
        ]
        assert router.spillover_rate_pct() == 0.0

    def test_spillover_storm_event_coalesces(self, recorder):
        clock = FakeClock()
        journal = EventJournal(capacity=64, clock=clock)
        replica_set = ReplicaSet(journal=journal)
        replica_set.add(
            make_replica(
                "r0",
                device_ms=1.0,
                shed=Overloaded("full", retry_after_s=0.1, reason="queue_full"),
            )
        )
        replica_set.add(make_replica("r1", device_ms=2.0))
        router = FleetRouter(
            replica_set,
            journal=journal,
            metrics=MetricsRegistry(),
            storm_band=0.2,
            storm_window=8,
            storm_coalesce_s=300.0,
        )
        for i in range(8):
            router.handle_request(f"q{i}", tenant=f"t{i}")
        assert router.spillover_rate_pct() == 100.0
        storms = [
            e for e in journal.export()["events"]
            if e["kind"] == "fleet.spillover_storm"
        ]
        assert len(storms) == 1  # coalesced, not one line per request
        assert storms[0]["severity"] == "warning"
        assert storms[0]["rate_pct"] == 100.0
        assert storms[0].get("repeats", 0) >= 1
        assert router.export()["spillover_storms"] >= 1


# ---------------------------------------------------------------------------
# Admin endpoints
# ---------------------------------------------------------------------------


class TestFleetAdminEndpoints:
    def test_fleet_statusz_and_timelinez(self):
        clock = FakeClock()
        _, replicas, telemetry = make_fleet(clock)
        replicas[0].leader.handle_request("q")
        telemetry.sample()
        telemetry.scopes()["r0"].journal.emit(
            "breaker.transition", "closed->open", severity="warning"
        )
        with AdminServer(
            registry=MetricsRegistry(), fleet_telemetry=telemetry
        ) as admin:
            base = f"http://127.0.0.1:{admin.port}"
            state = json.load(
                urllib.request.urlopen(base + "/fleet-statusz?format=json")
            )
            assert state["verdict"]["status"] == "ok"
            assert sorted(state["replicas"]) == ["r0", "r1", "r2"]
            assert (
                state["merged"]["histograms"]["plain.request_ms"]["count"]
                == 1
            )
            text = (
                urllib.request.urlopen(base + "/fleet-statusz")
                .read()
                .decode()
            )
            assert "fleet_routable_floor" in text
            for rid in ("r0", "r1", "r2"):
                assert rid in text

            timeline = json.load(
                urllib.request.urlopen(base + "/fleet-timelinez?format=json")
            )
            assert timeline["count"] >= 1
            kinds = [e["kind"] for e in timeline["events"]]
            assert "breaker.transition" in kinds
            text = (
                urllib.request.urlopen(base + "/fleet-timelinez")
                .read()
                .decode()
            )
            assert "breaker.transition" in text
            assert "r0" in text

            filtered = json.load(
                urllib.request.urlopen(
                    base + "/fleet-timelinez?format=json&kind=nothing.matches"
                )
            )
            assert filtered["count"] == 0
            with pytest.raises(urllib.error.HTTPError) as e:
                urllib.request.urlopen(base + "/fleet-timelinez?n=bogus")
            assert e.value.code == 400

    def test_endpoints_404_without_fleet_telemetry(self):
        with AdminServer(registry=MetricsRegistry()) as admin:
            base = f"http://127.0.0.1:{admin.port}"
            for path in ("/fleet-statusz", "/fleet-timelinez"):
                with pytest.raises(urllib.error.HTTPError) as e:
                    urllib.request.urlopen(base + path)
                assert e.value.code == 404

    def test_fleet_breach_degrades_process_healthz(self):
        clock = FakeClock()
        replica_set, _, telemetry = make_fleet(clock)
        replica_set.kill("r1", reason="test")
        replica_set.kill("r2", reason="test")
        with AdminServer(
            registry=MetricsRegistry(), fleet_telemetry=telemetry
        ) as admin:
            base = f"http://127.0.0.1:{admin.port}"
            with pytest.raises(urllib.error.HTTPError) as e:
                urllib.request.urlopen(base + "/healthz")
            assert e.value.code == 503
            body = e.value.read().decode()
            assert "fleet breach: fleet_routable_floor" in body

    def test_fleet_breach_in_json_healthz_with_prober(self):
        clock = FakeClock()
        replica_set, _, telemetry = make_fleet(clock)
        replica_set.kill("r1", reason="test")
        replica_set.kill("r2", reason="test")
        prober = SimpleNamespace(
            freshness=lambda: {}, export=lambda: {"probes": {}}
        )
        with AdminServer(
            registry=MetricsRegistry(),
            fleet_telemetry=telemetry,
            prober=prober,
        ) as admin:
            base = f"http://127.0.0.1:{admin.port}"
            with pytest.raises(urllib.error.HTTPError) as e:
                urllib.request.urlopen(base + "/healthz")
            assert e.value.code == 503
            detail = json.load(e.value)
            assert detail["status"] == "unhealthy"
            assert detail["fleet"]["status"] == "degraded"
            assert [b["name"] for b in detail["fleet"]["breaches"]] == [
                "fleet_routable_floor"
            ]
