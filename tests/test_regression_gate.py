"""Regression-gate tests: verdicts over synthetic history fixtures.

The verdict table under test (ISSUE acceptance): identical history ->
``ok`` exit 0; an injected 30% throughput drop -> ``regression`` exit
nonzero; a hung-init record (``status: infra_error``, the BENCH_r05
shape) -> ``infra_error`` exit 0; not enough clean history ->
``first_run``.
"""

import json

from benchmarks.regression_gate import (
    append_record,
    direction_of,
    gate,
    judge_metric,
    load_history,
    main,
)

METRIC = "dense_pir_queries_per_sec_chip_1048576x256B"


def _rec(value, metric=METRIC, status="ok", unit="queries/s", **extra):
    return {
        "metric": metric, "value": value, "unit": unit,
        "status": status, **extra,
    }


def _clean_history(values=(7080.0, 7240.0, 7150.0, 7200.0, 7188.0)):
    return [_rec(v) for v in values]


class TestDirection:
    def test_throughput_units_higher(self):
        assert direction_of({"unit": "queries/s"}) == "higher"
        assert direction_of({"unit": "lanes/s"}) == "higher"
        assert direction_of({"unit": "GB/s"}) == "higher"

    def test_time_units_lower(self):
        assert direction_of({"unit": "ns/leaf"}) == "lower"
        assert direction_of({"unit": "ms"}) == "lower"

    def test_explicit_direction_wins(self):
        assert direction_of({"unit": "ms", "direction": "higher"}) == (
            "higher"
        )

    def test_unknown_unit_defaults_higher(self):
        assert direction_of({"unit": "furlongs"}) == "higher"


class TestVerdicts:
    def test_stable_history_is_ok(self):
        v = judge_metric(_clean_history())
        assert v["verdict"] == "ok"
        assert abs(v["delta_pct"]) < 5

    def test_thirty_percent_drop_is_regression(self):
        history = _clean_history() + [_rec(7188.0 * 0.70)]
        v = judge_metric(history)
        assert v["verdict"] == "regression"
        assert v["delta_pct"] < -15
        assert "noise band" in v["reason"]

    def test_drop_inside_band_is_ok(self):
        history = _clean_history() + [_rec(7188.0 * 0.90)]
        assert judge_metric(history)["verdict"] == "ok"

    def test_jump_above_band_is_improved_not_failure(self):
        history = _clean_history() + [_rec(7188.0 * 1.40)]
        assert judge_metric(history)["verdict"] == "improved"

    def test_lower_is_better_metric_regresses_upward(self):
        history = [
            _rec(23.0, metric="expand_ns_leaf", unit="ns/leaf")
            for _ in range(4)
        ] + [_rec(40.0, metric="expand_ns_leaf", unit="ns/leaf")]
        v = judge_metric(history)
        assert v["direction"] == "lower"
        assert v["verdict"] == "regression"

    def test_infra_error_never_fails_and_carries_last_good(self):
        history = _clean_history() + [
            _rec(0.0, status="infra_error",
                 error="TPU backend init hung past 900s budget",
                 last_good=7188.0),
        ]
        v = judge_metric(history)
        assert v["verdict"] == "infra_error"
        assert v["last_good"] == 7188.0
        assert "hung" in v["reason"]

    def test_infra_errors_do_not_pollute_the_median(self):
        # Interleave zero-valued infra errors with clean runs: the
        # median must form over clean values only, so the newest clean
        # run stays ok.
        history = []
        for v in (7080.0, 7240.0, 7150.0):
            history.append(_rec(v))
            history.append(_rec(0.0, status="infra_error"))
        history.append(_rec(7200.0))
        v = judge_metric(history)
        assert v["verdict"] == "ok"
        assert v["median"] == 7150.0

    def test_first_run_with_insufficient_clean_history(self):
        assert judge_metric([_rec(7000.0)])["verdict"] == "first_run"
        assert judge_metric(
            [_rec(7000.0), _rec(7010.0)]
        )["verdict"] == "first_run"

    def test_window_limits_the_median(self):
        # Ancient bad values outside the window must not drag the
        # median; only the `window` most recent clean priors count.
        history = [_rec(100.0)] * 10 + [_rec(7000.0)] * 5 + [_rec(7010.0)]
        v = judge_metric(history, window=5)
        assert v["verdict"] == "ok"
        assert v["median"] == 7000.0

    def test_gate_groups_by_metric(self):
        records = (
            _clean_history()
            + [_rec(1.9e6, metric="hh_lanes") for _ in range(3)]
            + [_rec(1.0e6, metric="hh_lanes")]
        )
        verdicts = {v["metric"]: v["verdict"] for v in gate(records)}
        assert verdicts[METRIC] == "ok"
        assert verdicts["hh_lanes"] == "regression"


class TestHistoryStore:
    def test_append_and_load_roundtrip(self, tmp_path):
        path = str(tmp_path / "history.jsonl")
        append_record(_rec(7000.0), path)
        append_record(_rec(7010.0), path)
        records, skipped = load_history(path)
        assert skipped == 0
        assert [r["value"] for r in records] == [7000.0, 7010.0]
        assert all("ts_unix" in r for r in records)

    def test_malformed_lines_skipped_not_fatal(self, tmp_path):
        path = tmp_path / "history.jsonl"
        path.write_text(
            json.dumps(_rec(7000.0)) + "\n"
            + "{not json\n"
            + json.dumps({"no_metric": True}) + "\n"
            + json.dumps(_rec(7010.0)) + "\n"
        )
        records, skipped = load_history(str(path))
        assert len(records) == 2 and skipped == 2

    def test_missing_file_is_empty(self, tmp_path):
        assert load_history(str(tmp_path / "nope.jsonl")) == ([], 0)


class TestCli:
    def _write(self, tmp_path, records):
        path = str(tmp_path / "history.jsonl")
        for r in records:
            append_record(r, path)
        return path

    def test_identical_history_twice_exits_zero(self, tmp_path, capsys):
        path = self._write(tmp_path, _clean_history())
        assert main(["--history", path]) == 0
        assert main(["--history", path]) == 0  # deterministic re-run
        out = capsys.readouterr().out
        assert "ok" in out and "0 regression(s)" in out

    def test_injected_drop_exits_nonzero(self, tmp_path, capsys):
        path = self._write(
            tmp_path, _clean_history() + [_rec(7188.0 * 0.70)]
        )
        assert main(["--history", path]) == 1
        assert "regression" in capsys.readouterr().out

    def test_hung_init_record_exits_zero_infra_error(
        self, tmp_path, capsys
    ):
        path = self._write(
            tmp_path,
            _clean_history()
            + [_rec(0.0, status="infra_error",
                    error="TPU backend init hung past 900s budget",
                    last_good=7188.0)],
        )
        assert main(["--history", path]) == 0
        assert "infra_error" in capsys.readouterr().out

    def test_missing_history_errors_unless_check_only(self, tmp_path):
        missing = str(tmp_path / "none.jsonl")
        assert main(["--history", missing]) == 2
        assert main(["--history", missing, "--check-only"]) == 0

    def test_committed_fixture_passes_check_only(self):
        import os

        fixture = os.path.join(
            os.path.dirname(__file__), "..", "benchmarks", "fixtures",
            "history_fixture.jsonl",
        )
        assert main(["--history", fixture, "--check-only"]) == 0

    def test_metric_filter_and_json_output(self, tmp_path, capsys):
        path = self._write(
            tmp_path,
            _clean_history()
            + [_rec(1.0, metric="other") for _ in range(4)],
        )
        assert main(
            ["--history", path, "--metric", METRIC, "--json"]
        ) == 0
        out = capsys.readouterr().out
        doc = json.loads(out[: out.rindex("}") + 1])
        assert [v["metric"] for v in doc["verdicts"]] == [METRIC]

    def test_band_is_configurable(self, tmp_path):
        path = self._write(
            tmp_path, _clean_history() + [_rec(7188.0 * 0.90)]
        )
        assert main(["--history", path]) == 0  # inside the 15% band
        assert main(["--history", path, "--band", "0.05"]) == 1


class TestLatencyRecords:
    """The bench latency records: explicit `direction: lower`, `ms`
    unit, and p99 riding alongside the judged p50."""

    def _lat(self, value, **extra):
        return _rec(
            value, metric="serving_leader_e2e_ms", unit="ms",
            direction="lower", **extra,
        )

    def test_injected_latency_regression_flagged(self):
        history = [self._lat(12.0 + i * 0.1) for i in range(4)]
        history.append(self._lat(30.0))  # p50 latency blew up
        v = judge_metric(history)
        assert v["direction"] == "lower"
        assert v["verdict"] == "regression"
        assert v["delta_pct"] > 15

    def test_latency_drop_is_improved(self):
        history = [self._lat(12.0) for _ in range(4)] + [self._lat(6.0)]
        assert judge_metric(history)["verdict"] == "improved"

    def test_ms_unit_implies_lower_without_explicit_field(self):
        history = [
            _rec(12.0, metric="dense_leader_phase_queue_ms", unit="ms")
            for _ in range(4)
        ] + [_rec(30.0, metric="dense_leader_phase_queue_ms", unit="ms")]
        v = judge_metric(history)
        assert v["direction"] == "lower"
        assert v["verdict"] == "regression"

    def test_vs_baseline_passthrough_with_direction(self):
        history = _clean_history() + [_rec(7190.0, vs_baseline=1.02)]
        v = judge_metric(history)
        assert v["vs_baseline"] == 1.02
        assert v["vs_baseline_direction"] == "higher"
        lat = [self._lat(12.0) for _ in range(4)]
        lat.append(self._lat(12.1, vs_baseline=0.98))
        v = judge_metric(lat)
        assert v["vs_baseline_direction"] == "lower"


class TestStackGrouping:
    """jax_version/backend stamps partition the rolling median; records
    without stamps (pre-stamp history) stay judgeable everywhere."""

    def test_other_stack_excluded_from_median(self):
        # Three fast priors on TPU, three slow priors on CPU; the new
        # TPU run must be judged against the TPU median only.
        history = (
            [_rec(7200.0, backend="tpu", jax_version="0.4.30")
             for _ in range(3)]
            + [_rec(80.0, backend="cpu", jax_version="0.4.30")
               for _ in range(3)]
            + [_rec(7150.0, backend="tpu", jax_version="0.4.30")]
        )
        v = judge_metric(history)
        assert v["verdict"] == "ok"
        assert v["median"] == 7200.0
        assert v["backend"] == "tpu"
        assert v["jax_version"] == "0.4.30"

    def test_unstamped_history_still_counts(self):
        # Pre-stamp records have no backend/jax_version: they wildcard
        # into every stack, so the first stamped run is not first_run.
        history = _clean_history() + [_rec(7188.0, backend="tpu")]
        v = judge_metric(history)
        assert v["verdict"] == "ok"
        assert v["window"] == 5

    def test_unstamped_newest_sees_all_history(self):
        history = (
            [_rec(7200.0, backend="tpu") for _ in range(3)]
            + [_rec(7180.0)]
        )
        assert judge_metric(history)["verdict"] == "ok"

    def test_stack_switch_is_first_run_not_false_regression(self):
        # Moving to a new jax requires re-baselining, not comparing
        # against the old stack's median.
        history = (
            [_rec(7200.0, jax_version="0.4.30") for _ in range(5)]
            + [_rec(5000.0, jax_version="0.5.0")]
        )
        v = judge_metric(history)
        assert v["verdict"] == "first_run"
        assert "on this stack" in v["reason"]
