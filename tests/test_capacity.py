"""Capacity package units: byte/time model, calibration, admission.

Everything here is deterministic — models get pinned budgets and
calibration files, controllers get fake clocks — so the arithmetic the
planners and the serving admission path delegate to is checked exactly,
with no JAX and no wall clock.
"""

import json
import time

import pytest

from distributed_point_functions_tpu.capacity import (
    AdmissionController,
    BROWNOUT_STEPS,
    BrownoutController,
    CapacityModel,
    ShedReason,
    TenantPolicy,
    ThroughputCalibration,
    TokenBucket,
    WeightedFairQueue,
)
from distributed_point_functions_tpu.serving.metrics import MetricsRegistry

GIB = 1 << 30


class FakeClock:
    def __init__(self, t=0.0):
        self.t = t

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


def pinned_model(tmp_path, qps=1000.0, lanes=1_000_000.0, **kwargs):
    """A CapacityModel calibrated from a throwaway history file, so
    device-ms pricing is exact (1 key == 1 ms at qps=1000)."""
    path = tmp_path / "history.jsonl"
    records = [
        {"metric": "serving_closed_loop_queries_per_sec", "value": qps},
        {"metric": "heavy_hitters_sweep_lanes_per_sec", "value": lanes},
    ]
    path.write_text("".join(json.dumps(r) + "\n" for r in records))
    kwargs.setdefault("device_memory_bytes", 16 * GIB)
    return CapacityModel(
        calibration=ThroughputCalibration(str(path)), **kwargs
    )


# ---------------------------------------------------------------------------
# Byte model: the planner formulas, verbatim
# ---------------------------------------------------------------------------


def test_selection_byte_formulas():
    m = CapacityModel(device_memory_bytes=16 * GIB)
    assert m.materialized_selection_bytes(8, 256) == 8 * 256 * 16
    # cut-state + double-buffered chunk
    assert m.streaming_selection_bytes(8, 10, 6) == 8 * 16 * (
        (1 << 10) + 2 * (1 << 6)
    )
    assert m.chunked_selection_bytes(8, 10) == 8 * (1 << 10) * 16


def test_pick_streaming_split_prefers_largest_feasible():
    m = CapacityModel(device_memory_bytes=16 * GIB)
    expand = 20
    budget = m.selection_budget_bytes()
    split = m.pick_streaming_split(64, expand)
    assert (
        m.streaming_selection_bytes(64, expand - split, split) <= budget
    )
    if split < expand:
        assert (
            m.streaming_selection_bytes(
                64, expand - (split + 1), split + 1
            )
            > budget
        )


def test_pick_streaming_split_minimizes_peak_when_infeasible():
    m = CapacityModel(device_memory_bytes=16 * GIB, selection_budget=1)
    expand = 10
    split = m.pick_streaming_split(1 << 20, expand)
    best = min(
        m.streaming_selection_bytes(1 << 20, expand - r, r)
        for r in range(expand + 1)
    )
    assert (
        m.streaming_selection_bytes(1 << 20, expand - split, split) == best
    )


def test_pick_chunked_expand_levels_caps_at_granule_and_budget():
    m = CapacityModel(device_memory_bytes=16 * GIB)
    # Plenty of budget: the MXU granule is the cap.
    assert m.pick_chunked_expand_levels(1, 20, 10) == 10
    # Tight budget: shrink until one chunk fits (floor 0).
    tight = CapacityModel(
        device_memory_bytes=16 * GIB, selection_budget=1024
    )
    cel = tight.pick_chunked_expand_levels(4, 20, 10)
    assert tight.chunked_selection_bytes(4, cel) <= 1024 or cel == 0


def test_hh_level_plan_is_pow2_and_fits():
    m = CapacityModel(device_memory_bytes=16 * GIB, frontier_budget=1 << 20)
    plan = m.plan_hh_level(
        num_keys=100, num_prefixes=700, walk_levels=4, value_blocks=1
    )
    assert plan.lane_bytes == 16 * (4 + 1 + 3)
    assert plan.chunk_prefixes & (plan.chunk_prefixes - 1) == 0
    assert plan.bytes_peak == 100 * plan.chunk_prefixes * plan.lane_bytes
    assert plan.bytes_peak <= plan.budget_bytes or plan.chunk_prefixes == 1
    assert plan.num_chunks * plan.chunk_prefixes >= 700


# ---------------------------------------------------------------------------
# Budget resolution order: env > ctor > device fraction > default
# ---------------------------------------------------------------------------


def test_budget_resolution_order(monkeypatch):
    monkeypatch.delenv("DPF_TPU_SELECTION_BYTES_BUDGET", raising=False)
    monkeypatch.delenv("DPF_TPU_HH_BYTES_BUDGET", raising=False)
    # Known device memory: budgets derive as fractions; on a 16 GiB v5e
    # the derivation lands exactly on the historical fixed defaults.
    m = CapacityModel(device_memory_bytes=16 * GIB)
    assert m.selection_budget_bytes() == 1 * GIB
    assert m.frontier_budget_bytes() == 256 * (1 << 20)
    # Explicit construction beats the derivation.
    m2 = CapacityModel(
        device_memory_bytes=16 * GIB,
        selection_budget=123456,
        frontier_budget=7890,
    )
    assert m2.selection_budget_bytes() == 123456
    assert m2.frontier_budget_bytes() == 7890
    # Env beats everything.
    monkeypatch.setenv("DPF_TPU_SELECTION_BYTES_BUDGET", "999")
    monkeypatch.setenv("DPF_TPU_HH_BYTES_BUDGET", "888")
    assert m2.selection_budget_bytes() == 999
    assert m2.frontier_budget_bytes() == 888


def test_unknown_device_memory_keeps_historical_defaults(monkeypatch):
    monkeypatch.delenv("DPF_TPU_SELECTION_BYTES_BUDGET", raising=False)
    monkeypatch.delenv("DPF_TPU_HH_BYTES_BUDGET", raising=False)
    monkeypatch.setenv("DPF_TPU_DEVICE_MEMORY_BYTES", "")
    m = CapacityModel(calibration=ThroughputCalibration("/nonexistent"))
    if m.device_memory_bytes is None:  # CPU test process
        assert m.selection_budget_bytes() == 1 * GIB
        assert m.frontier_budget_bytes() == 256 * (1 << 20)


# ---------------------------------------------------------------------------
# Calibration: newest clean record wins, junk degrades to fallbacks
# ---------------------------------------------------------------------------


def test_calibration_newest_clean_record_wins(tmp_path):
    path = tmp_path / "h.jsonl"
    lines = [
        json.dumps({"metric": "m", "value": 100.0}),
        "not json at all",
        json.dumps({"metric": "m", "value": 0.0}),  # non-positive: dirty
        json.dumps({"metric": "m", "value": 250.0, "status": "ok"}),
        json.dumps({"metric": "m", "value": 999.0, "status": "regression"}),
    ]
    path.write_text("\n".join(lines) + "\n")
    cal = ThroughputCalibration(str(path))
    assert cal.lookup("m") == 250.0
    assert cal.lookup("absent") is None
    assert cal.throughput("absent", 7.0) == 7.0


def test_calibration_missing_file_degrades_to_fallback(tmp_path):
    cal = ThroughputCalibration(str(tmp_path / "never_written.jsonl"))
    m = CapacityModel(device_memory_bytes=16 * GIB, calibration=cal)
    # The built-in fallbacks are the derated v5e captures.
    assert m.serving_queries_per_sec() == 1300.0
    assert m.hh_lanes_per_sec() == 950_000.0


def _bench_record(metric, value, status="ok", ts=None, **extra):
    """One history.jsonl record in the real writer's shape (stack
    stamps and all) — the BENCH_r02–r05 tunnel-outage episodes mix ok,
    infra_error, and last_good rows exactly like this."""
    rec = {
        "device": extra.pop("device", "v5e-1"),
        "git_rev": extra.pop("git_rev", "abc1234"),
        "metric": metric,
        "status": status,
        "topology": extra.pop("topology", "1x1"),
        "ts_unix": time.time() if ts is None else ts,
        "unit": extra.pop("unit", "per_sec"),
        "value": value,
        "vs_baseline": extra.pop("vs_baseline", None),
    }
    rec.update(extra)
    return rec


def test_calibration_skips_infra_error_and_last_good(tmp_path):
    """The tunnel-outage shape: an infra_error record echoing the last
    good value, then explicit last_good echoes, must never calibrate —
    only genuinely clean rows do, and the skips are counted."""
    path = tmp_path / "h.jsonl"
    rows = [
        _bench_record("serving_closed_loop_queries_per_sec", 2600.0),
        _bench_record(
            "serving_closed_loop_queries_per_sec", 2590.0,
            status="infra_error", error="ssh tunnel reset",
            last_good=2600.0,
        ),
        _bench_record(
            "serving_closed_loop_queries_per_sec", 2600.0,
            status="last_good",
        ),
        _bench_record(
            "heavy_hitters_sweep_lanes_per_sec", 1.9e6,
            status="infra_error", error="tpu preempted",
        ),
    ]
    path.write_text("".join(json.dumps(r) + "\n" for r in rows))
    cal = ThroughputCalibration(str(path))
    assert cal.lookup("serving_closed_loop_queries_per_sec") == 2600.0
    # The hh metric never had a clean row: fallback, not the echo.
    assert cal.lookup("heavy_hitters_sweep_lanes_per_sec") is None
    assert cal.throughput(
        "heavy_hitters_sweep_lanes_per_sec", 950_000.0
    ) == 950_000.0
    export = cal.export()
    assert export["skipped_records"] == {"infra_error": 2, "last_good": 1}


def test_calibration_mixed_stack_stamps_last_clean_wins(tmp_path):
    """Append order is time order whatever the (device, topology,
    git_rev) stamp: a newer clean record from a different stack stamp
    replaces the older one, per metric independently."""
    path = tmp_path / "h.jsonl"
    rows = [
        _bench_record(
            "serving_closed_loop_queries_per_sec", 1000.0,
            device="v5e-1", topology="1x1", git_rev="old1111",
        ),
        _bench_record(
            "heavy_hitters_sweep_lanes_per_sec", 2.0e6,
            device="v5e-1", topology="1x1",
        ),
        _bench_record(
            "serving_closed_loop_queries_per_sec", 2600.0,
            device="v5p-8", topology="2x4", git_rev="new2222",
        ),
        _bench_record(
            "serving_closed_loop_queries_per_sec", 0.0,  # dirty value
            device="v5p-8", topology="2x4",
        ),
    ]
    path.write_text("".join(json.dumps(r) + "\n" for r in rows))
    cal = ThroughputCalibration(str(path))
    assert cal.lookup("serving_closed_loop_queries_per_sec") == 2600.0
    assert cal.lookup("heavy_hitters_sweep_lanes_per_sec") == 2.0e6


def test_calibration_staleness_and_record_age(tmp_path):
    path = tmp_path / "h.jsonl"
    now = time.time()
    rows = [
        _bench_record("fresh_metric", 100.0, ts=now - 10.0),
        _bench_record("old_metric", 200.0, ts=now - 500.0),
        {"metric": "untimed_metric", "value": 300.0, "status": "ok"},
    ]
    path.write_text("".join(json.dumps(r) + "\n" for r in rows))
    cal = ThroughputCalibration(str(path), stale_after_s=60.0)
    assert cal.record_age_s("fresh_metric") == pytest.approx(10.0, abs=5.0)
    assert not cal.stale("fresh_metric")
    assert cal.stale("old_metric")
    # A clean record without a timestamp can't be aged: fresh, not
    # permanently stale.
    assert cal.record_age_s("untimed_metric") is None
    assert not cal.stale("untimed_metric")
    # No record at all IS stale (pricing runs on fallbacks).
    assert cal.stale("absent_metric")
    export = cal.export()
    assert export["stale"] is True  # old_metric drags the summary flag
    assert export["metrics"]["fresh_metric"]["stale"] is False
    assert export["metrics"]["old_metric"]["stale"] is True
    assert export["metrics"]["old_metric"]["age_s"] == pytest.approx(
        500.0, abs=5.0
    )
    assert export["stale_after_s"] == 60.0


def test_calibration_fallback_journals_once_per_metric(tmp_path):
    from distributed_point_functions_tpu.observability.events import (
        default_journal,
    )

    journal = default_journal()
    seq0 = max((e["seq"] for e in journal.tail(n=1)), default=0)
    cal = ThroughputCalibration(str(tmp_path / "missing.jsonl"))
    for _ in range(3):
        assert cal.throughput("test_only_fallback_metric", 7.0) == 7.0
    events = [
        e
        for e in journal.tail(n=32, kind="capacity.calibration_fallback")
        if e["seq"] > seq0
        and e.get("metric") == "test_only_fallback_metric"
    ]
    assert len(events) == 1
    assert events[0]["fallback"] == 7.0


def test_price_pir_keys_device_ms(tmp_path):
    m = pinned_model(tmp_path, qps=1000.0)
    cost = m.price_pir_keys(5)
    assert cost.device_ms == pytest.approx(5.0)  # 1 key == 1 ms
    assert cost.quantity == 5 and cost.unit == "pir_keys"
    assert m.price_pir_keys(5, num_blocks=64).bytes_peak == 5 * 64 * 16


def test_price_hh_level(tmp_path):
    m = pinned_model(tmp_path, lanes=1_000_000.0)
    cost = m.price_hh_level(
        num_keys=100, num_prefixes=1000, walk_levels=4, value_blocks=1
    )
    assert cost.quantity == 100 * 1000
    assert cost.device_ms == pytest.approx(100 * 1000 * 1e3 / 1e6)
    assert cost.unit == "hh_lanes"


# ---------------------------------------------------------------------------
# TokenBucket
# ---------------------------------------------------------------------------


def test_token_bucket_burst_refill_and_hint():
    clock = FakeClock()
    bucket = TokenBucket(rate=10.0, burst=5.0, clock=clock)
    assert bucket.try_take(5)
    assert not bucket.try_take(1)
    assert bucket.time_until(1) == pytest.approx(0.1)
    clock.advance(0.25)  # refills 2.5 tokens
    assert bucket.try_take(2)
    assert bucket.tokens == pytest.approx(0.5)
    clock.advance(100.0)  # refill clamps at burst
    assert bucket.tokens == pytest.approx(5.0)


# ---------------------------------------------------------------------------
# WeightedFairQueue
# ---------------------------------------------------------------------------


def test_wfq_single_tenant_is_exact_fifo():
    q = WeightedFairQueue()
    for i in range(50):
        q.push(i, tenant="only", cost=float(1 + i % 3))
    assert q.drain() == list(range(50))


def test_wfq_backlogged_shares_follow_weights():
    q = WeightedFairQueue()
    weights = {"a": 3.0, "b": 2.0, "c": 1.0}
    for i in range(120):
        for tenant, w in weights.items():
            q.push((tenant, i), tenant=tenant, weight=w)
    first = [q.pop()[0] for _ in range(60)]
    total_w = sum(weights.values())
    for tenant, w in weights.items():
        share = first.count(tenant) / len(first)
        assert share == pytest.approx(w / total_w, rel=0.15)


def test_wfq_idle_tenant_cannot_burst_ahead_of_backlog():
    q = WeightedFairQueue()
    for i in range(10):
        q.push(("busy", i), tenant="busy")
    for _ in range(5):
        q.pop()
    # A newly-arriving tenant starts at the advanced virtual time: it
    # interleaves with the remaining backlog instead of jumping all of
    # it (start tags equal => arrival order breaks the tie).
    q.push(("late", 0), tenant="late")
    drained = q.drain()
    assert drained[0] == ("busy", 5)
    assert ("late", 0) in drained[:3]


# ---------------------------------------------------------------------------
# AdmissionController: every shed reason, exactly once each
# ---------------------------------------------------------------------------


def test_admission_quota_shed_with_refill_hint(tmp_path):
    clock = FakeClock()
    adm = AdmissionController(
        pinned_model(tmp_path), queue_budget_ms=10_000.0, clock=clock
    )
    adm.set_tenant("t", TenantPolicy(rate_qps=10.0, burst=4.0))
    assert adm.admit(4, tenant="t").admitted
    decision = adm.admit(2, tenant="t")
    assert not decision.admitted
    assert decision.reason is ShedReason.QUOTA
    assert decision.retry_after_s == pytest.approx(0.2)
    clock.advance(0.2)
    assert adm.admit(2, tenant="t").admitted


def test_admission_sheds_doomed_request_before_queue_budget(tmp_path):
    clock = FakeClock(100.0)
    adm = AdmissionController(
        pinned_model(tmp_path), queue_budget_ms=1000.0, clock=clock
    )
    assert adm.admit(500).admitted  # 500 ms outstanding
    # 100 more keys => 600 ms drain, but only 200 ms until deadline:
    # doomed, shed with a drain-the-gap hint — even though the queue
    # budget (1000 ms) has room.
    decision = adm.admit(100, deadline=clock.t + 0.2)
    assert not decision.admitted
    assert decision.reason is ShedReason.DRAIN_DEADLINE
    assert decision.retry_after_s == pytest.approx(0.4)


def test_admission_queue_cost_budget_and_release(tmp_path):
    adm = AdmissionController(
        pinned_model(tmp_path), queue_budget_ms=100.0,
        clock=FakeClock(),
        metrics=MetricsRegistry(),
    )
    first = adm.admit(80)
    assert first.admitted and adm.outstanding_ms == pytest.approx(80.0)
    decision = adm.admit(40)
    assert not decision.admitted
    assert decision.reason is ShedReason.QUEUE_COST
    assert decision.retry_after_s > 0
    adm.release(first.cost)
    assert adm.outstanding_ms == 0.0
    assert adm.admit(40).admitted
    counters = adm.metrics.export()["counters"]
    assert counters["admission.shed{reason=queue_cost}"] == 1
    assert counters["admission.admitted"] == 2


def test_admission_priority_floor_sheds_best_effort(tmp_path):
    adm = AdmissionController(
        pinned_model(tmp_path), queue_budget_ms=1000.0, clock=FakeClock()
    )
    adm.set_tenant("batch", TenantPolicy(priority=0))
    adm.set_tenant("vip", TenantPolicy(priority=2))
    adm.set_min_priority(1)
    shed = adm.admit(1, tenant="batch")
    assert not shed.admitted and shed.reason is ShedReason.PRIORITY
    assert adm.admit(1, tenant="vip").admitted
    assert adm.admit(1, tenant="unregistered").admitted  # default prio 1
    adm.set_min_priority(2)
    assert not adm.admit(1, tenant="unregistered").admitted
    adm.set_min_priority(0)
    assert adm.admit(1, tenant="batch").admitted
    export = adm.export()
    assert export["tenants"]["batch"]["shed"] == 1
    assert export["tenants"]["vip"]["admitted"] == 1


# ---------------------------------------------------------------------------
# BrownoutController: hysteretic engage/escalate/revert
# ---------------------------------------------------------------------------


def test_brownout_full_ladder_and_full_revert():
    clock = FakeClock()
    breaching = [True]
    engaged, reverted = [], []
    bc = BrownoutController(
        signal=lambda: breaching[0],
        engage_after_s=0.0,
        escalate_after_s=5.0,
        revert_after_s=10.0,
        metrics=MetricsRegistry(),
        clock=clock,
    )
    for step in BROWNOUT_STEPS:
        bc.add_step_action(
            step,
            lambda s=step: engaged.append(s),
            lambda s=step: reverted.append(s),
        )
    assert bc.evaluate() == 1  # engages on first breach observation
    assert bc.evaluate() == 1  # escalation hysteresis holds
    for want in (2, 3, 4):
        clock.advance(5.0)
        assert bc.evaluate() == want
    clock.advance(5.0)
    assert bc.evaluate() == 4  # ladder is exhausted, stays put
    assert engaged == list(BROWNOUT_STEPS)
    assert bc.active_steps() == BROWNOUT_STEPS

    breaching[0] = False
    assert bc.evaluate() == 4  # healthy, but not for long enough yet
    for want in (3, 2, 1, 0):
        clock.advance(10.0)
        assert bc.evaluate() == want
    clock.advance(10.0)
    assert bc.evaluate() == 0
    assert reverted == list(reversed(BROWNOUT_STEPS))
    counters = bc.metrics.export()["counters"]
    assert counters["brownout.engaged{step=critical_only}"] == 1
    assert counters["brownout.reverted{step=shed_low_priority}"] == 1
    export = bc.export()
    assert export["level"] == 0
    assert len(export["transitions"]) == 8
    assert [t["action"] for t in export["transitions"][:4]] == ["engage"] * 4


def test_brownout_breach_resets_revert_clock():
    clock = FakeClock()
    breaching = [True]
    bc = BrownoutController(
        signal=lambda: breaching[0],
        escalate_after_s=60.0,
        revert_after_s=10.0,
        clock=clock,
    )
    assert bc.evaluate() == 1
    breaching[0] = False
    clock.advance(9.0)
    assert bc.evaluate() == 1  # almost healthy long enough...
    breaching[0] = True
    assert bc.evaluate() == 1  # ...but the breach resets the clock
    breaching[0] = False
    clock.advance(9.0)
    assert bc.evaluate() == 1  # only 0 s healthy again at this point
    clock.advance(9.0)
    assert bc.evaluate() == 1  # 9 s — without the reset this reverts
    clock.advance(1.5)
    assert bc.evaluate() == 0


def test_brownout_force_level_runs_crossed_actions():
    log = []
    bc = BrownoutController(signal=lambda: False, clock=FakeClock())
    for step in BROWNOUT_STEPS:
        bc.add_step_action(
            step,
            lambda s=step: log.append(("engage", s)),
            lambda s=step: log.append(("revert", s)),
        )
    bc.force_level(3)
    assert log == [("engage", s) for s in BROWNOUT_STEPS[:3]]
    log.clear()
    bc.force_level(0)
    assert log == [("revert", s) for s in reversed(BROWNOUT_STEPS[:3])]


def test_brownout_action_error_does_not_stall_ladder():
    def boom():
        raise RuntimeError("step exploded")

    bc = BrownoutController(
        signal=lambda: True,
        clock=FakeClock(),
        metrics=MetricsRegistry(),
    )
    bc.add_step_action("shed_low_priority", boom, boom)
    assert bc.evaluate() == 1
    assert bc.metrics.export()["counters"]["brownout.action_errors"] == 1
    assert bc.export()["transitions"][0]["action_error"].startswith(
        "RuntimeError"
    )


def test_brownout_rejects_unknown_step():
    bc = BrownoutController(signal=lambda: False)
    with pytest.raises(ValueError):
        bc.add_step_action("power_cycle", lambda: None, lambda: None)


def test_brownout_slo_tracker_duck_typing():
    class FakeSlo:
        def __init__(self):
            self.breaching = True

        def breaches(self, evaluate=False):
            return [{"name": "x"}] if self.breaching else []

    slo = FakeSlo()
    bc = BrownoutController(slo=slo, clock=FakeClock())
    assert bc.evaluate() == 1
    slo.breaching = False
    clock_steps = bc  # revert_after defaults to 10 s of the fake clock
    # (no advance: the fake clock never moves, so no revert yet)
    assert clock_steps.evaluate() == 1
