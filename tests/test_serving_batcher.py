"""DynamicBatcher unit tests: pure-Python stubs, no JAX involved.

The batcher is generic over `evaluate(keys) -> results`, so these tests
drive it with counting stubs to pin down the coalescing, bucketing,
shedding, deadline, and error-fanout contracts in isolation; the
integration against real servers lives in test_serving_service.py.
"""

import threading
import time

import pytest

from distributed_point_functions_tpu.serving import (
    DeadlineExceeded,
    DynamicBatcher,
    MetricsRegistry,
    Overloaded,
    bucket_size,
)


class RecordingEvaluator:
    """Identity evaluation that records every batch it is handed."""

    def __init__(self, delay_s=0.0):
        self.calls = []
        self.delay_s = delay_s
        self.lock = threading.Lock()

    def __call__(self, keys):
        with self.lock:
            self.calls.append(list(keys))
        if self.delay_s:
            time.sleep(self.delay_s)
        return list(keys)


def test_bucket_size_powers_of_two():
    assert [bucket_size(n) for n in (1, 2, 3, 4, 5, 8, 9, 63, 64)] == [
        1, 2, 4, 4, 8, 8, 16, 64, 64,
    ]
    with pytest.raises(ValueError):
        bucket_size(0)


def test_single_submit_identity():
    ev = RecordingEvaluator()
    with DynamicBatcher(ev, max_batch_size=8, max_wait_ms=1.0) as b:
        assert b.submit(["k0", "k1"]) == ["k0", "k1"]
    # One batch, padded from 2 keys to the 2-bucket (no padding needed).
    assert len(ev.calls) == 1
    assert ev.calls[0] == ["k0", "k1"]


def test_concurrent_submits_coalesce_and_slice_in_order():
    ev = RecordingEvaluator(delay_s=0.02)
    metrics = MetricsRegistry()
    with DynamicBatcher(
        ev, max_batch_size=16, max_wait_ms=20.0, metrics=metrics, name="b"
    ) as b:
        results = {}

        def client(i):
            results[i] = b.submit([f"r{i}a", f"r{i}b"])

        # Park one submission so the worker is busy, then pile up
        # concurrent clients that must coalesce into ONE batch.
        first = threading.Thread(target=client, args=(99,))
        first.start()
        time.sleep(0.005)  # let the worker pick up the first batch
        threads = [
            threading.Thread(target=client, args=(i,)) for i in range(5)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        first.join()
    # Every request got exactly its own keys back, in its own order.
    for i in list(range(5)) + [99]:
        assert results[i] == [f"r{i}a", f"r{i}b"]
    # The five concurrent clients shared batches (fewer batches than
    # clients); with the worker parked they typically form one batch.
    assert len(ev.calls) <= 3
    counters = metrics.export()["counters"]
    assert counters["b.requests_submitted"] == 6
    assert counters["b.batches"] == len(ev.calls)


def test_batches_padded_to_power_of_two_buckets():
    ev = RecordingEvaluator(delay_s=0.02)
    metrics = MetricsRegistry()
    with DynamicBatcher(
        ev, max_batch_size=16, max_wait_ms=20.0, metrics=metrics, name="b"
    ) as b:
        hold = threading.Thread(target=b.submit, args=(["x"],))
        hold.start()
        time.sleep(0.005)
        threads = [
            threading.Thread(target=b.submit, args=([f"k{i}"],))
            for i in range(3)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        hold.join()
    # Every evaluated batch is a power-of-two size; padding duplicates
    # the first key.
    for call in ev.calls:
        assert len(call) == bucket_size(len(call))
    padded = metrics.export()["counters"]["b.padded_keys"]
    total_keys = sum(len(c) for c in ev.calls)
    assert total_keys - 4 == padded


def test_overload_shedding():
    release = threading.Event()

    def slow(keys):
        release.wait(5.0)
        return list(keys)

    metrics = MetricsRegistry()
    b = DynamicBatcher(
        slow, max_batch_size=1, max_queue=2, metrics=metrics, name="b"
    )
    try:
        threads = [
            threading.Thread(target=lambda: b.submit(["k"]))
            for _ in range(3)
        ]
        for t in threads:
            t.start()
        time.sleep(0.05)  # worker holds one batch; 2 more fill the queue
        with pytest.raises(Overloaded):
            b.submit(["shed-me"])
        assert metrics.export()["counters"]["b.requests_shed"] == 1
    finally:
        release.set()
        for t in threads:
            t.join()
        b.close()


def test_deadline_expired_in_queue_never_evaluated():
    started = threading.Event()
    release = threading.Event()
    ev = RecordingEvaluator()

    def gated(keys):
        started.set()
        release.wait(5.0)
        return ev(keys)

    metrics = MetricsRegistry()
    b = DynamicBatcher(gated, max_batch_size=1, metrics=metrics, name="b")
    try:
        hold = threading.Thread(target=lambda: b.submit(["hold"]))
        hold.start()
        assert started.wait(2.0)
        # This request's deadline passes while the worker is busy; it
        # must fail without its keys ever reaching the evaluator.
        with pytest.raises(DeadlineExceeded):
            b.submit(["late"], deadline=time.monotonic() + 0.01)
        release.set()
        hold.join()
        time.sleep(0.05)
        assert all("late" not in call for call in ev.calls)
        counters = metrics.export()["counters"]
        assert counters["b.requests_deadline_exceeded"] == 1
    finally:
        release.set()
        b.close()


def test_evaluation_error_fans_out_to_all_batch_members():
    def boom(keys):
        raise RuntimeError("device on fire")

    b = DynamicBatcher(boom, max_batch_size=8, max_wait_ms=5.0)
    try:
        errors = []

        def client():
            try:
                b.submit(["k"])
            except RuntimeError as e:
                errors.append(str(e))

        threads = [threading.Thread(target=client) for _ in range(3)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert errors == ["device on fire"] * 3
    finally:
        b.close()


def test_mixed_sizes_bounded_compile_count():
    """1..N mixed-size request streams touch at most log2(max_batch)+1
    distinct jit buckets — counted via the metrics registry."""
    ev = RecordingEvaluator()
    metrics = MetricsRegistry()
    max_batch = 16
    with DynamicBatcher(
        ev, max_batch_size=max_batch, max_wait_ms=2.0,
        metrics=metrics, name="b",
    ) as b:
        for round_sizes in [(1,), (3,), (2, 2), (5,), (7, 1), (16,), (11,)]:
            threads = [
                threading.Thread(
                    target=b.submit, args=([f"s{s}k{j}" for j in range(s)],)
                )
                for s in round_sizes
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
    bound = max_batch.bit_length()  # log2(16)+1 = 5
    counters = metrics.export()["counters"]
    assert counters["b.jit_bucket_compiles"] <= bound
    distinct = {len(c) for c in ev.calls}
    assert len(distinct) == counters["b.jit_bucket_compiles"]
    assert counters["b.jit_bucket_hits"] == counters["b.batches"] - len(
        distinct
    )


def test_submit_after_close_raises():
    b = DynamicBatcher(lambda keys: list(keys))
    b.close()
    with pytest.raises(RuntimeError):
        b.submit(["k"])


def test_close_drains_pending_work():
    ev = RecordingEvaluator(delay_s=0.01)
    b = DynamicBatcher(ev, max_batch_size=4, max_wait_ms=1.0)
    results = []
    threads = [
        threading.Thread(target=lambda: results.append(b.submit(["k"])))
        for _ in range(4)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    b.close()
    assert len(results) == 4
