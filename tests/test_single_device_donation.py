"""ROADMAP 3c: buffer donation on the remaining single-device jit
entry points, proved three ways.

Correctness: serving with `DPF_TPU_DONATE` on must be bit-identical to
serving with it off — donation is a pure HBM aliasing hint, never a
semantic change. Same for `evaluate_prefixes_batch(donate_cuts=True)`
versus a plain resume. Accounting: the TransferLedger proves the
donated steady state re-stages nothing — N warm same-shape plain
requests cost exactly N `key_staging` copy batches and ZERO additional
`db_staging` copies (the resident database buffer is never donated,
never re-uploaded).
"""

import numpy as np
import pytest

from distributed_point_functions_tpu.dpf import (
    DistributedPointFunction,
    DpfParameters,
)
from distributed_point_functions_tpu.observability.device import (
    DeviceTelemetry,
    default_telemetry,
    set_default_telemetry,
)
from distributed_point_functions_tpu.pir import (
    DenseDpfPirClient,
    DenseDpfPirDatabase,
    DenseDpfPirServer,
)
from distributed_point_functions_tpu.pir.dense_eval import donation_enabled
from distributed_point_functions_tpu.value_types import IntType

NUM_RECORDS = 96
RECORD_BYTES = 24
RNG = np.random.default_rng(3434)
RECORDS = [
    bytes(RNG.integers(0, 256, RECORD_BYTES, dtype=np.uint8))
    for _ in range(NUM_RECORDS)
]


@pytest.fixture
def telemetry():
    prev = default_telemetry()
    fresh = set_default_telemetry(DeviceTelemetry())
    try:
        yield fresh
    finally:
        set_default_telemetry(prev)


def build_db():
    builder = DenseDpfPirDatabase.Builder()
    for r in RECORDS:
        builder.insert(r)
    return builder.build()


def masked(server, indices):
    client = DenseDpfPirClient(NUM_RECORDS, lambda pt, info: pt)
    req0, req1 = client.create_plain_requests(indices)
    resp0 = server.handle_request(req0)
    resp1 = server.handle_request(req1)
    return (
        list(resp0.dpf_pir_response.masked_response),
        list(resp1.dpf_pir_response.masked_response),
    )


def test_donation_defaults_on_and_env_gates_it(monkeypatch):
    monkeypatch.delenv("DPF_TPU_DONATE", raising=False)
    assert donation_enabled() is True
    monkeypatch.setenv("DPF_TPU_DONATE", "0")
    assert donation_enabled() is False
    monkeypatch.setenv("DPF_TPU_DONATE", "1")
    assert donation_enabled() is True


def test_donated_serving_bit_identical_to_undonated(monkeypatch):
    indices = [0, 17, NUM_RECORDS - 1]
    # ONE request pair served under both arms: key generation is
    # randomized, so bit-identity only holds for identical requests.
    client = DenseDpfPirClient(NUM_RECORDS, lambda pt, info: pt)
    req0, req1 = client.create_plain_requests(indices)
    server = DenseDpfPirServer.create_plain(build_db())

    def serve():
        return [
            list(server.handle_request(r).dpf_pir_response.masked_response)
            for r in (req0, req1)
        ]

    monkeypatch.setenv("DPF_TPU_DONATE", "0")
    plain0, plain1 = serve()
    monkeypatch.setenv("DPF_TPU_DONATE", "1")
    donated0, donated1 = serve()
    assert donated0 == plain0 and donated1 == plain1
    for i, idx in enumerate(indices):
        combined = bytes(a ^ b for a, b in zip(donated0[i], donated1[i]))
        assert combined[:RECORD_BYTES] == RECORDS[idx]


def test_warm_requests_restage_keys_only_never_database(telemetry):
    """The zero-re-staging assertion: once the database is resident and
    the shape is compiled, each plain request uploads exactly one key
    batch and touches `db_staging` zero times."""
    server = DenseDpfPirServer.create_plain(build_db())
    ledger = telemetry.transfers
    # Warm-up: first dispatch stages the database and compiles.
    masked(server, [3, 9])
    db_before = ledger.copies("db_staging")
    key_before = ledger.copies("key_staging")
    assert db_before > 0  # the warm-up actually staged the database

    rounds = 4
    for i in range(rounds):
        masked(server, [i, i + 11])  # same shape: two keys per request
    # Two handle_request calls per `masked` round, one staged key batch
    # (one h2d copy) each; the resident database is never re-uploaded.
    assert ledger.copies("key_staging") - key_before == 2 * rounds
    assert ledger.copies("db_staging") == db_before


def test_donate_cuts_resume_bit_identical():
    widths = [4, 8, 12]
    params = [DpfParameters(w, IntType(32)) for w in widths]
    dpf = DistributedPointFunction.create_incremental(params)
    alphas = [0, 77, (1 << widths[-1]) - 1]
    betas = [1] * len(widths)
    pairs = [dpf.generate_keys_incremental(a, betas) for a in alphas]
    shift0 = widths[-1] - widths[0]
    level0 = sorted({a >> shift0 for a in alphas} | {1, 2})
    step = widths[1] - widths[0]
    level1 = sorted(
        (p << step) | c for p in level0 for c in range(1 << step)
    )

    import jax

    def leaves(values):
        return [np.asarray(x) for x in jax.tree_util.tree_leaves(values)]

    for party in (0, 1):
        staged = dpf.stage_key_batch([p[party] for p in pairs])
        # Two independent cut states: donate_cuts=True consumes one.
        _, cuts_a = dpf.evaluate_prefixes_batch(staged, 0, level0)
        _, cuts_b = dpf.evaluate_prefixes_batch(staged, 0, level0)
        v_plain, next_plain = dpf.evaluate_prefixes_batch(
            staged, 1, level1, cuts=cuts_a
        )
        v_donated, next_donated = dpf.evaluate_prefixes_batch(
            staged, 1, level1, cuts=cuts_b, donate_cuts=True
        )
        for a, b in zip(leaves(v_plain), leaves(v_donated)):
            np.testing.assert_array_equal(a, b)
        np.testing.assert_array_equal(
            np.asarray(next_plain.seeds), np.asarray(next_donated.seeds)
        )
        np.testing.assert_array_equal(
            np.asarray(next_plain.control),
            np.asarray(next_donated.control),
        )
