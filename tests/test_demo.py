"""Cross-process protocol test: the Leader/Helper deployment running over
real TCP sockets in three OS processes (examples/leader_helper_demo.py).

The reference tests the two-party protocol in-process with lambdas as the
network (`pir/dpf_pir_server_test.cc:145-196`); this goes one step further
and exercises the serialized wire path end-to-end across processes.
"""

import importlib.util
import os
import sys


def _load_demo():
    path = os.path.join(
        os.path.dirname(__file__), "..", "examples", "leader_helper_demo.py"
    )
    spec = importlib.util.spec_from_file_location("leader_helper_demo", path)
    mod = importlib.util.module_from_spec(spec)
    sys.modules[spec.name] = mod
    spec.loader.exec_module(mod)
    return mod


def test_leader_helper_demo_over_tcp():
    demo = _load_demo()
    # run_demo raises (SystemExit / RuntimeError) on any mismatch, early
    # subprocess death, or port timeout.
    demo.run_demo(19750, "cpu")
