"""Cost-model accuracy ledger, drift detection, and recalibration.

Two layers of coverage: exact unit arithmetic on `CostLedger` /
`Recalibrator` (synthetic observations, deterministic windows), and
the acceptance path — a real `PlainSession` serving real batches and a
real heavy-hitters sweep populating the ledger, read back through the
live `/capacityz` endpoint, including the deliberate-mispricing drill
(drift event + gauge burn + clamped correction + kill-switch revert,
responses bit-identical throughout).
"""

import json
import threading
import urllib.error
import urllib.request

import numpy as np
import pytest

from distributed_point_functions_tpu import heavy_hitters as hh
from distributed_point_functions_tpu.capacity import (
    recalibrate as recalibrate_mod,
)
from distributed_point_functions_tpu.capacity.model import (
    CapacityModel,
    ThroughputCalibration,
    misprice_factor,
    set_default_capacity_model,
)
from distributed_point_functions_tpu.capacity.recalibrate import (
    KILL_SWITCH_ENV,
    CapacityAccuracy,
    Recalibrator,
    set_default_recalibrator,
)
from distributed_point_functions_tpu.observability import AdminServer
from distributed_point_functions_tpu.observability import (
    costmodel as costmodel_mod,
)
from distributed_point_functions_tpu.observability.costmodel import (
    DRIFT_GAUGE,
    CostLedger,
    drift_objective,
    set_default_cost_ledger,
    shape_bucket,
)
from distributed_point_functions_tpu.observability.events import (
    default_journal,
)
from distributed_point_functions_tpu.observability.slo import SloTracker
from distributed_point_functions_tpu.pir import (
    DenseDpfPirClient,
    DenseDpfPirDatabase,
    DenseDpfPirServer,
)
from distributed_point_functions_tpu.serving import (
    PlainSession,
    ServingConfig,
)
from distributed_point_functions_tpu.serving.metrics import MetricsRegistry

GIB = 1 << 30
NUM_RECORDS = 64
RECORD_BYTES = 16
RNG = np.random.default_rng(77)


def _get(url):
    """(status, body) tolerating HTTP error statuses."""
    try:
        with urllib.request.urlopen(url) as resp:
            return resp.status, resp.read().decode()
    except urllib.error.HTTPError as e:
        return e.code, e.read().decode()


def build_database():
    records = [
        bytes(RNG.integers(0, 256, RECORD_BYTES, dtype=np.uint8))
        for _ in range(NUM_RECORDS)
    ]
    builder = DenseDpfPirDatabase.Builder()
    for r in records:
        builder.insert(r)
    return builder.build()


DATABASE = build_database()


def pinned_model(tmp_path, qps=1000.0, lanes=1_000_000.0):
    path = tmp_path / "history.jsonl"
    records = [
        {"metric": "serving_closed_loop_queries_per_sec", "value": qps},
        {"metric": "heavy_hitters_sweep_lanes_per_sec", "value": lanes},
    ]
    path.write_text("".join(json.dumps(r) + "\n" for r in records))
    return CapacityModel(
        device_memory_bytes=16 * GIB,
        calibration=ThroughputCalibration(str(path)),
    )


@pytest.fixture
def fresh_defaults(tmp_path):
    """Swap in a small-window ledger, a pinned model, and no
    recalibrator as the process defaults; restore lazily afterwards so
    no learned state leaks between tests."""
    ledger = CostLedger(window_size=4, drift_band=0.35, drift_windows=1)
    prev_ledger = set_default_cost_ledger(ledger)
    prev_model = set_default_capacity_model(pinned_model(tmp_path))
    prev_rec = set_default_recalibrator(None)
    try:
        yield ledger
    finally:
        set_default_cost_ledger(prev_ledger)
        set_default_capacity_model(prev_model)
        set_default_recalibrator(None)
        if prev_rec is not None:
            prev_rec.uninstall()


# ---------------------------------------------------------------------------
# CostLedger units: residual math, windows, drift, registry mirror
# ---------------------------------------------------------------------------


def test_shape_bucket_rounds_to_next_power_of_two():
    assert shape_bucket(0) == "0"
    assert shape_bucket(-3) == "0"
    assert shape_bucket(1) == "1"
    assert shape_bucket(2) == "2"
    assert shape_bucket(3) == "4"
    assert shape_bucket(1000) == "1024"
    assert shape_bucket(1024) == "1024"


def test_residual_is_signed_ratio_error():
    ledger = CostLedger(window_size=100)
    assert ledger.observe("pir", "t", "4", 2.0, 2.0) == pytest.approx(0.0)
    assert ledger.observe("pir", "t", "4", 1.0, 2.0) == pytest.approx(1.0)
    assert ledger.observe("pir", "t", "4", 2.0, 1.0) == pytest.approx(-0.5)
    cell = ledger.export()["cells"]["pir/t/4"]
    assert cell["samples"] == 3
    assert cell["residual_p50"] == pytest.approx(0.0)
    assert cell["mean_predicted_ms"] == pytest.approx(5.0 / 3, abs=1e-3)
    assert cell["mean_actual_ms"] == pytest.approx(5.0 / 3, abs=1e-3)


def test_unpriced_samples_counted_not_graded():
    ledger = CostLedger(window_size=100)
    assert ledger.observe("pir", "t", "4", 0.0, 1.0) is None
    assert ledger.observe("pir", "t", "4", -1.0, 1.0) is None
    cell = ledger.export()["cells"]["pir/t/4"]
    assert cell["unpriced"] == 2 and cell["samples"] == 0
    assert ledger.export()["total_unpriced"] == 2


def test_observe_never_raises_on_junk():
    ledger = CostLedger(window_size=100)
    assert ledger.observe("pir", "t", "4", "junk", object()) is None


def test_worst_residual_keeps_trace_id():
    ledger = CostLedger(window_size=100)
    ledger.observe("pir", "t", "4", 1.0, 1.1, trace_id="aaaa")
    ledger.observe("pir", "t", "4", 1.0, 5.0, trace_id="bbbb")
    ledger.observe("pir", "t", "4", 1.0, 1.2, trace_id="cccc")
    worst = ledger.export()["cells"]["pir/t/4"]["worst"]
    assert worst["trace_id"] == "bbbb"
    assert worst["residual"] == pytest.approx(4.0)


def test_bytes_residuals_tracked_when_both_sides_present():
    ledger = CostLedger(window_size=100)
    ledger.observe(
        "hh", "root", "16", 1.0, 1.0,
        predicted_bytes=100, actual_bytes=150,
    )
    cell = ledger.export()["cells"]["hh/root/16"]
    assert cell["bytes_residual_p50"] == pytest.approx(0.5)
    assert cell["bytes_samples"] == 1


def test_drift_trips_after_consecutive_windows_and_clears():
    ledger = CostLedger(window_size=2, drift_band=0.3, drift_windows=2)
    reg = MetricsRegistry()
    ledger.bind_registry(reg)
    # Created at zero so the SLO grades ok, not no_data, pre-traffic.
    assert reg.export()["gauges"][DRIFT_GAUGE] == 0.0
    tracker = SloTracker([drift_objective()], registry=reg)
    (r,) = tracker.evaluate()
    assert r["state"] == "ok"

    journal = default_journal()
    seq0 = max((e["seq"] for e in journal.tail(n=1)), default=0)
    # One out-of-band window: not drifting yet (hysteresis).
    for _ in range(2):
        ledger.observe("pir", "t", "4", 1.0, 2.0)
    assert ledger.drifting_cells() == []
    # Second consecutive out-of-band window trips the cell.
    for _ in range(2):
        ledger.observe("pir", "t", "4", 1.0, 2.0)
    assert ledger.drifting_cells() == ["pir/t/4"]
    assert reg.export()["gauges"][DRIFT_GAUGE] == 1.0
    (r,) = tracker.evaluate()
    assert r["state"] == "breach"
    assert not tracker.healthy()
    drifted = [
        e for e in journal.tail(n=16, kind="capacity.drift")
        if e["seq"] > seq0
    ]
    assert drifted and drifted[-1]["state"] == "drifting"

    # One in-band window clears it and the gauge falls back.
    for _ in range(2):
        ledger.observe("pir", "t", "4", 1.0, 1.0)
    assert ledger.drifting_cells() == []
    assert reg.export()["gauges"][DRIFT_GAUGE] == 0.0
    (r,) = tracker.evaluate()
    assert r["state"] == "ok"
    cleared = [
        e for e in journal.tail(n=16, kind="capacity.drift")
        if e["seq"] > seq0
    ]
    assert cleared[-1]["state"] == "cleared"


def test_in_band_window_resets_consecutive_count():
    ledger = CostLedger(window_size=2, drift_band=0.3, drift_windows=2)
    for _ in range(2):
        ledger.observe("pir", "t", "4", 1.0, 2.0)  # out of band
    for _ in range(2):
        ledger.observe("pir", "t", "4", 1.0, 1.0)  # in band: reset
    for _ in range(2):
        ledger.observe("pir", "t", "4", 1.0, 2.0)  # out again, count 1
    assert ledger.drifting_cells() == []


def test_window_listener_payload_and_isolation():
    seen = []
    ledger = CostLedger(window_size=3)
    ledger.add_window_listener(
        lambda w, t, b, win: seen.append((w, t, b, win))
    )
    ledger.add_window_listener(lambda *a: 1 / 0)  # must be swallowed
    for _ in range(3):
        ledger.observe("hh", "root", "16", 1.0, 1.5)
    assert len(seen) == 1
    w, t, b, win = seen[0]
    assert (w, t, b) == ("hh", "root", "16")
    assert win["p50"] == pytest.approx(0.5)
    assert win["samples"] == 3 and win["cell_samples"] == 3
    assert win["drifting"] is False


def test_residual_histogram_mirrored_with_labels_and_exemplar():
    ledger = CostLedger(window_size=100)
    reg = MetricsRegistry()
    ledger.bind_registry(reg)

    class FakeTrace:
        trace_id = "feedbeef"

    ledger.observe("pir", "fused", "8", 1.0, 1.5, trace=FakeTrace())
    hists = reg.export()["histograms"]
    name = "capacity_residual_ratio{bucket=8,tier=fused,workload=pir}"
    assert name in hists
    assert hists[name]["count"] == 1
    exemplars = hists[name].get("exemplars") or {}
    assert any(
        ex.get("trace_id") == "feedbeef" for ex in exemplars.values()
    )


def test_ledger_reset_clears_cells_and_gauge():
    ledger = CostLedger(window_size=1, drift_band=0.1, drift_windows=1)
    reg = MetricsRegistry()
    ledger.bind_registry(reg)
    ledger.observe("pir", "t", "4", 1.0, 2.0)
    assert reg.export()["gauges"][DRIFT_GAUGE] == 1.0
    ledger.reset()
    assert ledger.export()["cells"] == {}
    assert reg.export()["gauges"][DRIFT_GAUGE] == 0.0


# ---------------------------------------------------------------------------
# Recalibrator: guarded EWMA loop on a pinned model
# ---------------------------------------------------------------------------


def test_recalibrator_moves_clamps_and_prices(tmp_path):
    model = pinned_model(tmp_path, qps=1000.0)  # 1 key == 1 ms raw
    ledger = CostLedger(window_size=2)
    rec = Recalibrator(
        model=model, ledger=ledger, alpha=0.5, clamp=(0.5, 2.0),
        min_samples=2,
    ).install()
    assert model.price_pir_keys(1).device_ms == pytest.approx(1.0)
    # Device consistently 2x the price: p50 = +1.0, one window moves
    # the factor by 1 + 0.5*1.0 = 1.5x.
    for _ in range(2):
        ledger.observe("pir", "fused", "4", 1.0, 2.0)
    assert rec.factor("pir") == pytest.approx(1.5)
    assert model.price_pir_keys(1).device_ms == pytest.approx(1.5)
    # Another 2x window: 1.5 * 1.5 = 2.25 clamps at 2.0.
    for _ in range(2):
        ledger.observe("pir", "fused", "4", 1.0, 2.0)
    assert rec.factor("pir") == pytest.approx(2.0)
    assert model.price_pir_keys(1).device_ms == pytest.approx(2.0)
    # hh prices are untouched by a pir factor.
    assert rec.factor("hh") == pytest.approx(1.0)


def test_recalibrator_min_samples_gate():
    ledger = CostLedger(window_size=2)
    rec = Recalibrator(ledger=ledger, min_samples=10)
    ledger.add_window_listener(rec._on_window)
    for _ in range(4):  # 2 windows close, but cell has < 10 samples
        ledger.observe("pir", "fused", "4", 1.0, 2.0)
    assert rec.factor("pir") == pytest.approx(1.0)
    for _ in range(6):  # lifetime hits 10: the window at 10 applies
        ledger.observe("pir", "fused", "4", 1.0, 2.0)
    assert rec.factor("pir") > 1.0


def test_recalibrator_converges_on_corrected_prices(tmp_path):
    """The closed loop: the ledger sees *corrected* predictions, so
    once the correction matches truth the factor stops moving."""
    model = pinned_model(tmp_path, qps=1000.0)
    ledger = CostLedger(window_size=2)
    rec = Recalibrator(
        model=model, ledger=ledger, alpha=1.0, min_samples=1
    ).install()
    truth_ms = 1.5  # device truth for a 1-key batch priced 1.0 raw
    for _ in range(20):
        predicted = model.price_pir_keys(1).device_ms
        ledger.observe("pir", "fused", "1", predicted, truth_ms)
    assert rec.factor("pir") == pytest.approx(1.5, rel=1e-3)
    assert model.price_pir_keys(1).device_ms == pytest.approx(
        truth_ms, rel=1e-3
    )


def test_kill_switch_reverts_and_reenables(tmp_path, monkeypatch):
    monkeypatch.delenv(KILL_SWITCH_ENV, raising=False)
    model = pinned_model(tmp_path, qps=1000.0)
    ledger = CostLedger(window_size=2)
    rec = Recalibrator(
        model=model, ledger=ledger, alpha=0.5, min_samples=1
    ).install()
    for _ in range(2):
        ledger.observe("pir", "fused", "4", 1.0, 2.0)
    assert model.price_pir_keys(1).device_ms == pytest.approx(1.5)

    journal = default_journal()
    seq0 = max((e["seq"] for e in journal.tail(n=1)), default=0)
    monkeypatch.setenv(KILL_SWITCH_ENV, "0")
    # Raw price, instantly, no restart; journaled once.
    assert model.price_pir_keys(1).device_ms == pytest.approx(1.0)
    assert model.price_pir_keys(1).device_ms == pytest.approx(1.0)
    assert rec.export()["enabled"] is False
    assert rec.export()["reverted"] is True
    reverts = [
        e for e in journal.tail(
            n=16, kind="capacity.correction_reverted"
        )
        if e["seq"] > seq0
    ]
    assert len(reverts) == 1
    # Re-enabling resumes from the learned factor.
    monkeypatch.setenv(KILL_SWITCH_ENV, "1")
    assert model.price_pir_keys(1).device_ms == pytest.approx(1.5)
    assert rec.export()["reverted"] is False


def test_correction_applied_journaled_on_material_moves(tmp_path):
    model = pinned_model(tmp_path, qps=1000.0)
    ledger = CostLedger(window_size=2)
    journal = default_journal()
    rec = Recalibrator(
        model=model, ledger=ledger, alpha=0.5, min_samples=1
    ).install()
    for _ in range(2):
        ledger.observe("pir", "fused", "4", 1.0, 2.0)
    # The journal write is coalesced per workload (other tests in this
    # process may share the window), so assert through the counter plus
    # the journal's merged view.
    assert rec.export()["applied_events"] == 1
    applied = [
        e
        for e in journal.tail(n=64, kind="capacity.correction_applied")
        if e.get("workload") == "pir"
    ]
    assert applied


def test_misprice_env_parsed_live(monkeypatch):
    monkeypatch.delenv("DPF_TPU_COSTMODEL_MISPRICE", raising=False)
    assert misprice_factor("pir") == 1.0
    monkeypatch.setenv("DPF_TPU_COSTMODEL_MISPRICE", "pir=3.0,hh=0.5")
    assert misprice_factor("pir") == 3.0
    assert misprice_factor("hh") == 0.5
    assert misprice_factor("other") == 1.0
    monkeypatch.setenv("DPF_TPU_COSTMODEL_MISPRICE", "garbage")
    assert misprice_factor("pir") == 1.0


def test_misprice_scales_prices_only(tmp_path, monkeypatch):
    monkeypatch.delenv("DPF_TPU_COSTMODEL_MISPRICE", raising=False)
    model = pinned_model(tmp_path, qps=1000.0)
    base = model.price_pir_keys(4)
    hh_base = model.price_hh_level(4, 4, 2, 1)
    monkeypatch.setenv("DPF_TPU_COSTMODEL_MISPRICE", "pir=3.0")
    priced = model.price_pir_keys(4)
    assert priced.device_ms == pytest.approx(3 * base.device_ms)
    assert priced.bytes_peak == base.bytes_peak  # bytes are untouched
    # A pir-only misprice leaves the hh workload's prices alone.
    assert model.price_hh_level(4, 4, 2, 1).device_ms == pytest.approx(
        hh_base.device_ms
    )


# ---------------------------------------------------------------------------
# Acceptance: real served batches populate the ledger end to end
# ---------------------------------------------------------------------------


def _serve_queries(session, indices):
    client = DenseDpfPirClient.create(NUM_RECORDS, lambda pt, ci: pt)
    requests = [client.create_plain_requests([i])[0] for i in indices]
    results = [None] * len(requests)

    def worker(i):
        results[i] = session.handle_request(requests[i])

    threads = [
        threading.Thread(target=worker, args=(i,))
        for i in range(len(requests))
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    oracle_server = DenseDpfPirServer.create_plain(DATABASE)
    oracle = [
        oracle_server.handle_plain_request(
            r
        ).dpf_pir_response.masked_response
        for r in requests
    ]
    return results, oracle


def test_served_pir_batches_populate_capacityz(fresh_defaults):
    config = ServingConfig(max_batch_size=4, max_wait_ms=5.0)
    with PlainSession(DATABASE, config) as session:
        results, oracle = _serve_queries(session, [3, 17, 42, 9, 60, 5])
        with AdminServer(
            registry=session.metrics,
            capacity=session.capacity_accuracy,
        ) as admin:
            base = f"http://127.0.0.1:{admin.port}"
            status, body = _get(f"{base}/capacityz?format=json")
            assert status == 200
            state = json.loads(body)
            cells = state["ledger"]["cells"]
            pir_cells = {
                k: v for k, v in cells.items() if k.startswith("pir/")
            }
            assert pir_cells, f"no pir cells in {sorted(cells)}"
            for cell in pir_cells.values():
                assert cell["samples"] >= 1
                assert isinstance(cell["residual_p50"], float)
                assert np.isfinite(cell["residual_p50"])
            assert "recalibration" in state
            assert "calibration" in state["model"]

            status, text = _get(f"{base}/capacityz")
            assert status == 200 and "pir/" in text
            assert "throughput calibration" in text

            status, html_body = _get(f"{base}/statusz")
            assert status == 200
            assert "Cost-model accuracy" in html_body

            status, body = _get(f"{base}/nope")
            assert status == 404 and "/capacityz" in body
    for got, want in zip(results, oracle):
        assert got.dpf_pir_response.masked_response == want


def test_capacityz_404_without_capacity_export():
    with AdminServer(registry=MetricsRegistry()) as admin:
        status, _ = _get(f"http://127.0.0.1:{admin.port}/capacityz")
        assert status == 404


HH_CONFIG = hh.HeavyHittersConfig(domain_bits=8, level_bits=4, threshold=2)


def test_hh_sweep_levels_populate_ledger(fresh_defaults):
    client = hh.HeavyHittersClient(HH_CONFIG)
    keys0 = [client.generate_report(v)[0] for v in (3, 3, 9, 200)]
    dpf = HH_CONFIG.make_dpf()
    agg = hh.LevelAggregator(dpf, keys0)
    agg.evaluate_level(0, list(range(16)))
    agg.evaluate_level(1, [(0 << 4) | c for c in range(16)])
    cells = fresh_defaults.export()["cells"]
    roots = [k for k in cells if k.startswith("hh/root/")]
    resumes = [k for k in cells if k.startswith("hh/resume/")]
    assert roots and resumes, sorted(cells)
    for k in roots + resumes:
        assert cells[k]["samples"] >= 1
        assert isinstance(cells[k]["residual_p50"], float)


def test_mispriced_cell_end_to_end_drill(
    fresh_defaults, tmp_path, monkeypatch
):
    """The acceptance drill: deliberate mispricing on live traffic =>
    drift journal event + SLO gauge burn + clamped correction applied
    to subsequent admission prices + bit-identical responses, with the
    kill switch fully reverting."""
    monkeypatch.delenv(KILL_SWITCH_ENV, raising=False)
    monkeypatch.setenv("DPF_TPU_COSTMODEL_MISPRICE", "pir=3.0")
    monkeypatch.setenv("DPF_TPU_COSTMODEL_MIN_SAMPLES", "4")
    # An absurdly fast calibration makes every residual hugely positive
    # regardless of host speed: drift trips deterministically.
    model = pinned_model(tmp_path, qps=1e9)
    set_default_capacity_model(model)
    raw_1key_ms = 3.0 * 1e3 / 1e9  # misprice only, no correction

    journal = default_journal()
    seq0 = max((e["seq"] for e in journal.tail(n=1)), default=0)
    config = ServingConfig(max_batch_size=1, max_wait_ms=1.0)
    with PlainSession(DATABASE, config) as session:
        results, oracle = _serve_queries(
            session, [1, 2, 3, 4, 5, 6, 7, 8]
        )
        # Responses stayed bit-identical under mispricing.
        for got, want in zip(results, oracle):
            assert got.dpf_pir_response.masked_response == want
        # Drift journaled + gauge burned: /healthz-style SLO breach.
        drifts = [
            e for e in journal.tail(n=32, kind="capacity.drift")
            if e["seq"] > seq0 and e["workload"] == "pir"
        ]
        assert drifts and drifts[0]["state"] == "drifting"
        gauges = session.metrics.export()["gauges"]
        assert gauges[DRIFT_GAUGE] >= 1.0
        tracker = SloTracker(
            [drift_objective()], registry=session.metrics
        )
        assert not tracker.healthy()
        # The correction clamped at 2.0x (the residual is enormous) and
        # applies to subsequent admission prices.
        rec = session.capacity_accuracy.recalibrator
        assert rec.factor("pir") == pytest.approx(2.0)
        assert model.price_pir_keys(1).device_ms == pytest.approx(
            2.0 * raw_1key_ms
        )
        # Kill switch: raw (still mispriced) prices, journaled revert.
        monkeypatch.setenv(KILL_SWITCH_ENV, "0")
        assert model.price_pir_keys(1).device_ms == pytest.approx(
            raw_1key_ms
        )
        reverts = [
            e for e in journal.tail(
                n=32, kind="capacity.correction_reverted"
            )
            if e["seq"] > seq0
        ]
        assert len(reverts) == 1
        # Re-enable: the learned factor resumes without relearning.
        monkeypatch.delenv(KILL_SWITCH_ENV)
        assert model.price_pir_keys(1).device_ms == pytest.approx(
            2.0 * raw_1key_ms
        )


def test_capacity_accuracy_export_shape(tmp_path):
    ledger = CostLedger(window_size=8)
    ledger.observe("pir", "t", "1", 1.0, 1.2)
    acc = CapacityAccuracy(
        ledger=ledger,
        recalibrator=Recalibrator(
            model=pinned_model(tmp_path), ledger=ledger
        ),
        model=pinned_model(tmp_path),
    )
    out = acc.export()
    assert out["ledger"]["total_samples"] == 1
    assert out["recalibration"]["kill_switch_env"] == KILL_SWITCH_ENV
    assert "calibration" in out["model"]


def test_default_instances_swap_and_restore():
    mine = CostLedger(window_size=2)
    prev = set_default_cost_ledger(mine)
    try:
        assert costmodel_mod.default_cost_ledger() is mine
    finally:
        set_default_cost_ledger(prev)
    r = Recalibrator()
    prev_r = set_default_recalibrator(r)
    try:
        assert recalibrate_mod.default_recalibrator() is r
    finally:
        set_default_recalibrator(prev_r)
