"""Known-answer and differential tests for the AES core.

Mirrors the reference's test strategy (SURVEY.md §4.2-4.3): FIPS-197
known-answer vectors for the numpy oracle, then SIMD-vs-scalar style
differential tests of the bitsliced JAX kernel against the oracle.
"""

import numpy as np
import pytest

from distributed_point_functions_tpu import keys
from distributed_point_functions_tpu.ops import aes

# FIPS-197 Appendix C.1: AES-128 known-answer vector.
FIPS_KEY = bytes(range(16))
FIPS_PT = bytes(int(f"{h}{h}", 16) for h in "0123456789abcdef")
FIPS_CT = bytes.fromhex("69c4e0d86a7b0430d8cdb78070b4c55a")

# FIPS-197 Appendix B worked example.
B_KEY = bytes.fromhex("2b7e151628aed2a6abf7158809cf4f3c")
B_PT = bytes.fromhex("3243f6a8885a308d313198a2e0370734")
B_CT = bytes.fromhex("3925841d02dc09fbdc118597196a0b32")


@pytest.mark.parametrize(
    "key,pt,ct", [(FIPS_KEY, FIPS_PT, FIPS_CT), (B_KEY, B_PT, B_CT)]
)
def test_numpy_oracle_fips_vectors(key, pt, ct):
    rk = aes.key_expansion(key)
    out = aes.aes_encrypt_np(rk, np.frombuffer(pt, dtype=np.uint8).reshape(1, 16))
    assert out.tobytes() == ct


def test_sbox_known_entries():
    # Spot values from the published S-box table.
    assert aes.SBOX[0x00] == 0x63
    assert aes.SBOX[0x01] == 0x7C
    assert aes.SBOX[0x53] == 0xED
    assert aes.SBOX[0xFF] == 0x16


def test_limb_byte_roundtrip():
    rng = np.random.default_rng(0)
    limbs = rng.integers(0, 2**32, size=(17, 4), dtype=np.uint32)
    assert np.array_equal(
        aes.bytes_to_limbs_np(aes.limbs_to_bytes_np(limbs)), limbs
    )
    x = 0x0123456789ABCDEF_FEDCBA9876543210
    assert aes.limbs_to_u128(aes.u128_to_limbs(x)) == x


def test_jax_matches_oracle_fips():
    rk = aes.key_expansion(FIPS_KEY)
    limbs = aes.bytes_to_limbs_np(np.frombuffer(FIPS_PT, dtype=np.uint8).reshape(1, 16))
    out = np.asarray(aes.aes_encrypt(rk, limbs))
    assert aes.limbs_to_bytes_np(out).tobytes() == FIPS_CT


def test_jax_matches_oracle_random_batch():
    rng = np.random.default_rng(42)
    blocks = rng.integers(0, 2**32, size=(133, 4), dtype=np.uint32)
    for rk in (keys.RK_LEFT, keys.RK_RIGHT, keys.RK_VALUE):
        expect = aes.bytes_to_limbs_np(
            aes.aes_encrypt_np(rk, aes.limbs_to_bytes_np(blocks))
        )
        got = np.asarray(aes.aes_encrypt(rk, blocks))
        np.testing.assert_array_equal(got, expect)


def test_key_select_matches_individual_keys():
    rng = np.random.default_rng(7)
    blocks = rng.integers(0, 2**32, size=(64, 4), dtype=np.uint32)
    select = rng.integers(0, 2, size=(64,), dtype=np.uint32)
    got = np.asarray(
        aes.aes_encrypt_select(keys.RK_LEFT, keys.RK_RIGHT, select, blocks)
    )
    left = np.asarray(aes.aes_encrypt(keys.RK_LEFT, blocks))
    right = np.asarray(aes.aes_encrypt(keys.RK_RIGHT, blocks))
    expect = np.where(select[:, None] != 0, right, left)
    np.testing.assert_array_equal(got, expect)


def test_mmo_hash_jax_vs_numpy():
    rng = np.random.default_rng(3)
    blocks = rng.integers(0, 2**32, size=(50, 4), dtype=np.uint32)
    expect = aes.mmo_hash_np(keys.RK_LEFT, blocks)
    got = np.asarray(aes.mmo_hash(keys.RK_LEFT, blocks))
    np.testing.assert_array_equal(got, expect)


def test_mmo_hash_select_matches():
    rng = np.random.default_rng(4)
    blocks = rng.integers(0, 2**32, size=(32, 4), dtype=np.uint32)
    select = rng.integers(0, 2, size=(32,), dtype=np.uint32)
    got = np.asarray(
        aes.mmo_hash_select(keys.RK_LEFT, keys.RK_RIGHT, select, blocks)
    )
    left = aes.mmo_hash_np(keys.RK_LEFT, blocks)
    right = aes.mmo_hash_np(keys.RK_RIGHT, blocks)
    expect = np.where(select[:, None] != 0, right, left)
    np.testing.assert_array_equal(got, expect)


def test_sigma_semantics():
    # sigma(x) = (hi ^ lo, hi): low 64 bits of output = hi, high 64 = hi ^ lo.
    x = 0x00112233445566778899AABBCCDDEEFF
    limbs = aes.u128_to_limbs(x)[None, :]
    s = aes.limbs_to_u128(np.asarray(aes.sigma(limbs))[0])
    hi, lo = x >> 64, x & ((1 << 64) - 1)
    assert s == (((hi ^ lo) << 64) | hi)
