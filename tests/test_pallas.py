"""Pallas kernel differential tests (interpret mode on CPU).

Mirrors the reference's per-target kernel testing discipline
(`pir/internal/inner_product_hwy_test.cc:427-434`): the Pallas kernel must
be bit-identical to the jnp implementation and the numpy oracle.
"""

import numpy as np
import pytest

from distributed_point_functions_tpu.ops.inner_product import (
    pack_selection_bits_np,
    xor_inner_product,
    xor_inner_product_np,
)
from distributed_point_functions_tpu.ops.inner_product_pallas import (
    xor_inner_product_pallas,
)

RNG = np.random.default_rng(17)


@pytest.mark.parametrize(
    "num_records,num_words,nq,tile",
    [(256, 8, 1, 128), (1024, 64, 4, 256), (384, 5, 2, 128)],
)
def test_pallas_inner_product_matches_oracles(num_records, num_words, nq, tile):
    db = RNG.integers(0, 1 << 32, (num_records, num_words), dtype=np.uint32)
    bits = RNG.integers(0, 2, (nq, num_records), dtype=np.uint32)
    sel = pack_selection_bits_np(bits)
    got = np.asarray(
        xor_inner_product_pallas(db, sel, tile_records=tile, interpret=True)
    )
    np.testing.assert_array_equal(got, xor_inner_product_np(db, sel))
    np.testing.assert_array_equal(
        got, np.asarray(xor_inner_product(db, sel))
    )


def test_pallas_inner_product_non_pow2_tile_fallback():
    # R=128*3: tile 1024 -> halved until it divides (128 works).
    db = RNG.integers(0, 1 << 32, (384, 4), dtype=np.uint32)
    bits = RNG.integers(0, 2, (2, 384), dtype=np.uint32)
    sel = pack_selection_bits_np(bits)
    got = np.asarray(xor_inner_product_pallas(db, sel, interpret=True))
    np.testing.assert_array_equal(got, xor_inner_product_np(db, sel))
