"""Pallas kernel differential tests (interpret mode on CPU).

Mirrors the reference's per-target kernel testing discipline
(`pir/internal/inner_product_hwy_test.cc:427-434`): the Pallas kernel must
be bit-identical to the jnp implementation and the numpy oracle.
"""

import numpy as np
import pytest

from distributed_point_functions_tpu.ops.inner_product import (
    pack_selection_bits_np,
    xor_inner_product,
    xor_inner_product_np,
)
from distributed_point_functions_tpu.ops.inner_product_pallas import (
    permute_db_bitmajor,
    xor_inner_product_pallas,
    xor_inner_product_pallas_staged,
)

RNG = np.random.default_rng(17)


@pytest.mark.parametrize(
    "num_records,num_words,nq",
    [(256, 8, 1), (1024, 64, 4), (384, 5, 2), (8192, 16, 16)],
)
def test_pallas_inner_product_matches_oracles(num_records, num_words, nq):
    db = RNG.integers(0, 1 << 32, (num_records, num_words), dtype=np.uint32)
    bits = RNG.integers(0, 2, (nq, num_records), dtype=np.uint32)
    sel = pack_selection_bits_np(bits)
    got = np.asarray(xor_inner_product_pallas(db, sel, interpret=True))
    np.testing.assert_array_equal(got, xor_inner_product_np(db, sel))
    np.testing.assert_array_equal(
        got, np.asarray(xor_inner_product(db, sel))
    )


def test_pallas_inner_product_staged_bitmajor():
    # The serving path stages the bit-major permutation once; staged and
    # per-call entries must agree with the oracle.
    db = RNG.integers(0, 1 << 32, (1152, 4), dtype=np.uint32)
    bits = RNG.integers(0, 2, (3, 1152), dtype=np.uint32)
    sel = pack_selection_bits_np(bits)
    db_perm = np.asarray(permute_db_bitmajor(db))
    # 1152 records pad to 4096 = 128 groups of 32 (full-lane tiles).
    assert db_perm.shape == (32, 128, 4)
    # Spot-check the permutation: record 32g+b lands at [b, g].
    np.testing.assert_array_equal(db_perm[5, 7], db[32 * 7 + 5])
    assert not db_perm[:, 36:].any()  # zero padding
    got = np.asarray(
        xor_inner_product_pallas_staged(db_perm, sel, interpret=True)
    )
    np.testing.assert_array_equal(got, xor_inner_product_np(db, sel))


@pytest.mark.parametrize("nq", [1, 3, 65, 100])
def test_pallas_inner_product_odd_query_counts(nq):
    # Regression: query counts with no multiple-of-8 divisor used to drive
    # the tile search to zero (ZeroDivisionError). Queries are now padded.
    db = RNG.integers(0, 1 << 32, (256, 4), dtype=np.uint32)
    bits = RNG.integers(0, 2, (nq, 256), dtype=np.uint32)
    sel = pack_selection_bits_np(bits)
    got = np.asarray(xor_inner_product_pallas(db, sel, interpret=True))
    np.testing.assert_array_equal(got, xor_inner_product_np(db, sel))


def test_bitplane_jnp_matches_xor_paths():
    """The pure-jnp MXU bit-plane inner product (the serving path's
    middle fallback) must match the mask-and-XOR path bit for bit."""
    import numpy as np
    import jax.numpy as jnp

    from distributed_point_functions_tpu.ops.inner_product import (
        xor_inner_product,
        xor_inner_product_bitplane,
    )
    from distributed_point_functions_tpu.ops.inner_product_pallas import (
        permute_db_bitmajor,
    )

    rng = np.random.default_rng(31)
    for R, W, nq in [(512, 8, 5), (4096, 20, 3)]:
        db = jnp.asarray(rng.integers(0, 1 << 32, (R, W), dtype=np.uint32))
        sel = jnp.asarray(
            rng.integers(0, 1 << 32, (nq, R // 128, 4), dtype=np.uint32)
        )
        a = np.asarray(
            xor_inner_product_bitplane(permute_db_bitmajor(db), sel)
        )
        b = np.asarray(xor_inner_product(db, sel))
        np.testing.assert_array_equal(a, b)


def test_database_serves_via_bitplane(monkeypatch):
    """DPF_TPU_INNER_PRODUCT=bitplane routes the database through the
    bit-plane path with identical record bytes."""
    import numpy as np

    from distributed_point_functions_tpu.ops.inner_product import (
        pack_selection_bits_np,
    )
    from distributed_point_functions_tpu.pir.database import (
        DenseDpfPirDatabase,
    )

    rng = np.random.default_rng(32)
    records = [rng.bytes(24) for _ in range(300)]
    db = DenseDpfPirDatabase(records)
    bits = rng.integers(0, 2, (4, db.num_selection_bits), dtype=np.uint32)
    sel = pack_selection_bits_np(bits)

    monkeypatch.setenv("DPF_TPU_INNER_PRODUCT", "jnp")
    a = db.inner_product_with(sel)
    monkeypatch.setenv("DPF_TPU_INNER_PRODUCT", "bitplane")
    b = db.inner_product_with(sel)
    assert a == b


@pytest.mark.parametrize("int8", [False, True])
@pytest.mark.parametrize(
    "num_records,num_words,nq",
    [(256, 8, 1), (1024, 64, 4), (384, 5, 2), (8192, 16, 16)],
)
def test_pallas_v2_matches_oracles(num_records, num_words, nq, int8):
    from distributed_point_functions_tpu.ops.inner_product_pallas import (
        xor_inner_product_pallas2_staged,
    )

    db = RNG.integers(0, 1 << 32, (num_records, num_words), dtype=np.uint32)
    bits = RNG.integers(0, 2, (nq, num_records), dtype=np.uint32)
    sel = pack_selection_bits_np(bits)
    got = np.asarray(
        xor_inner_product_pallas2_staged(
            permute_db_bitmajor(db), sel, int8=int8, interpret=True
        )
    )
    np.testing.assert_array_equal(got, xor_inner_product_np(db, sel))


@pytest.mark.parametrize("tile_groups,j_chunk", [(8, 4), (16, 16), (64, 32)])
def test_pallas_v2_tile_variants(tile_groups, j_chunk):
    from distributed_point_functions_tpu.ops.inner_product_pallas import (
        xor_inner_product_pallas2_staged,
    )

    # W=16: wide enough that the narrow-record cap leaves j_chunk alone,
    # so each declared chunk size actually runs in the kernel.
    db = RNG.integers(0, 1 << 32, (4096, 16), dtype=np.uint32)
    bits = RNG.integers(0, 2, (5, 4096), dtype=np.uint32)
    sel = pack_selection_bits_np(bits)
    got = np.asarray(
        xor_inner_product_pallas2_staged(
            permute_db_bitmajor(db),
            sel,
            tile_groups=tile_groups,
            j_chunk=j_chunk,
            interpret=True,
        )
    )
    np.testing.assert_array_equal(got, xor_inner_product_np(db, sel))


def test_pallas_v2_narrow_records_cap_j_chunk(monkeypatch):
    """W<16 must drop j_chunk to 1 (no in-kernel db repeat at all):
    Mosaic's pltpu.repeat miscompiles for sub-half-lane-tile sources —
    factors >8 mapped 2026-07-31, and the W=8 x factor-8 kernel smoke
    crash showed the true boundary is the source width. The public entry
    degrades instead of crashing; the drop must actually reach the
    jitted core, and results stay exact."""
    from distributed_point_functions_tpu.ops import inner_product_pallas as ipp

    forwarded = {}
    real_core = ipp._ip_pallas_staged_v2

    def spy(db_perm, packed, **kw):
        forwarded["j_chunk"] = kw["j_chunk"]
        return real_core(db_perm, packed, **kw)

    monkeypatch.setattr(ipp, "_ip_pallas_staged_v2", spy)
    for num_words, want_chunk in ((4, 1), (8, 1), (16, 32)):
        db = RNG.integers(0, 1 << 32, (4096, num_words), dtype=np.uint32)
        bits = RNG.integers(0, 2, (5, 4096), dtype=np.uint32)
        sel = pack_selection_bits_np(bits)
        got = np.asarray(
            ipp.xor_inner_product_pallas2_staged(
                permute_db_bitmajor(db), sel, j_chunk=32, interpret=True
            )
        )
        assert forwarded["j_chunk"] == want_chunk
        np.testing.assert_array_equal(got, xor_inner_product_np(db, sel))


def test_pallas_v2_rejects_tiny_group_count():
    """Compiled mode refuses hand-built layouts under 16 groups (the
    selections repeat would hit the same Mosaic miscompile, factor 32)."""
    import jax.numpy as jnp

    from distributed_point_functions_tpu.ops.inner_product_pallas import (
        xor_inner_product_pallas2_staged,
    )

    db_perm = jnp.zeros((32, 8, 4), dtype=jnp.uint32)
    sel = pack_selection_bits_np(
        RNG.integers(0, 2, (2, 256), dtype=np.uint32)
    )
    with pytest.raises(ValueError, match="16 selection groups"):
        xor_inner_product_pallas2_staged(db_perm, sel)
    # interpret mode has no Mosaic and still serves tiny layouts
    out = xor_inner_product_pallas2_staged(db_perm, sel, interpret=True)
    assert out.shape == (2, 4)


def test_database_tier_chain_fallthrough(monkeypatch):
    """Auto mode falls through failing tiers and serves; forced tiers
    propagate errors; remembered failures skip retries."""
    import jax

    from distributed_point_functions_tpu.pir import database as db_mod
    from distributed_point_functions_tpu.pir.database import (
        DenseDpfPirDatabase,
    )

    rng = np.random.default_rng(9)
    records = [rng.bytes(16) for _ in range(200)]
    db = DenseDpfPirDatabase(records)
    bits = rng.integers(0, 2, (2, db.num_selection_bits), dtype=np.uint32)
    sel = pack_selection_bits_np(bits)

    monkeypatch.setenv("DPF_TPU_INNER_PRODUCT", "jnp")
    want = db.inner_product_with(sel)

    # Forced unknown tier raises.
    monkeypatch.setenv("DPF_TPU_INNER_PRODUCT", "nope")
    with pytest.raises(ValueError, match="unknown"):
        db.inner_product_with(sel)

    # Auto on a fake-TPU backend: break pallas2 + pallas, bitplane serves.
    db2 = DenseDpfPirDatabase(records)
    monkeypatch.setenv("DPF_TPU_INNER_PRODUCT", "auto")
    monkeypatch.setattr(jax, "default_backend", lambda: "tpu")

    def boom(*a, **k):
        raise RuntimeError("mosaic says no")

    monkeypatch.setattr(db_mod, "xor_inner_product_pallas2_staged", boom)
    monkeypatch.setattr(db_mod, "xor_inner_product_pallas_staged", boom)
    with pytest.warns(UserWarning):
        got = db2.inner_product_with(sel)
    assert got == want
    assert db2._failed_tiers == {"pallas2", "pallas"}


def test_pallas_v2_wide_records_cap_query_tile():
    """W=64-word (256 B) records at a 256-query batch: the VMEM cap
    drops the query tile below the 256 default and the kernel still
    matches the oracle (grid covers all query tiles)."""
    from distributed_point_functions_tpu.ops.inner_product_pallas import (
        xor_inner_product_pallas2_staged,
    )

    db = RNG.integers(0, 1 << 32, (4096, 64), dtype=np.uint32)
    bits = RNG.integers(0, 2, (256, 4096), dtype=np.uint32)
    sel = pack_selection_bits_np(bits)
    got = np.asarray(
        xor_inner_product_pallas2_staged(
            permute_db_bitmajor(db), sel, interpret=True
        )
    )
    np.testing.assert_array_equal(got, xor_inner_product_np(db, sel))


@pytest.mark.parametrize(
    "num_groups,max_tile",
    [
        (128, 128),    # bench.py's small verify instance (4096 records)
        (32768, 128),  # headline 2^20 records
        (131072, 128), # dense_big 2^22 records
        (128, 32),     # the round-2 hardware failure: requested tile 32
        (64, 128),     # small database, tile spans the axis
    ],
)
def test_group_tile_mosaic_legal(num_groups, max_tile):
    """Non-interpret lowering must pick selection-block lane dims Mosaic
    accepts: divisible by 128 or equal to the whole group axis. The
    round-2 TPU window showed tile_groups=32 on a [8, 128] selections
    array is rejected by Mosaic ('block shape ... divisible by 8 and 128
    respectively'), silently dropping the v2 MXU kernel from the tier
    chain."""
    from distributed_point_functions_tpu.ops.inner_product_pallas import (
        _pick_group_tile,
    )

    tg = _pick_group_tile(num_groups, max_tile=max_tile, lane_step=128)
    assert num_groups % tg == 0
    assert tg % 128 == 0 or tg == num_groups
