"""Chaos harness: scripted fault schedules through the real stack.

The invariant under test everywhere: **no injected fault may change
bytes**. Every response a client actually receives — after retries,
reconnects, wire downgrades, breaker probes, checkpoint resume, tier
demotion — must be bit-identical to the fault-free oracle. Faults may
cost latency or surface as typed errors; they may never silently
corrupt a share.

Schedules are armed on the process-default failpoint registry (that is
what the instrumented sites consult), so the autouse fixture clears it
around every test.
"""

import time

import numpy as np
import pytest

from distributed_point_functions_tpu import heavy_hitters as hh
from distributed_point_functions_tpu.observability import tracing
from distributed_point_functions_tpu.pir import (
    DenseDpfPirClient,
    DenseDpfPirDatabase,
    DenseDpfPirServer,
)
from distributed_point_functions_tpu.robustness import failpoints
from distributed_point_functions_tpu.serving import (
    HelperSession,
    HelperUnavailable,
    InProcessTransport,
    LeaderSession,
    PlainSession,
    ServingConfig,
)
from distributed_point_functions_tpu.serving.metrics import MetricsRegistry
from distributed_point_functions_tpu.serving.transport import (
    FramedTcpServer,
    TcpTransport,
)
from distributed_point_functions_tpu.testing import encrypt_decrypt

NUM_RECORDS = 128
RECORD_BYTES = 16
RNG = np.random.default_rng(99)


def build_database():
    records = [
        bytes(RNG.integers(0, 256, RECORD_BYTES, dtype=np.uint8))
        for _ in range(NUM_RECORDS)
    ]
    builder = DenseDpfPirDatabase.Builder()
    for r in records:
        builder.insert(r)
    return builder.build(), records


DATABASE, RECORDS = build_database()

HH_VALUES = [3, 3, 3, 77, 77, 9, 9, 200]
HH_CONFIG = hh.HeavyHittersConfig(domain_bits=8, level_bits=4, threshold=2)
HH_ORACLE = hh.plaintext_heavy_hitters(HH_VALUES, HH_CONFIG)


@pytest.fixture(autouse=True)
def clean_failpoints():
    reg = failpoints.default_failpoints()
    reg.clear()
    yield reg
    reg.clear()


@pytest.fixture(scope="module")
def hh_key_pairs():
    client = hh.HeavyHittersClient(HH_CONFIG)
    pairs = [client.generate_report(v) for v in HH_VALUES]
    return [p[0] for p in pairs], [p[1] for p in pairs]


def hh_servers(hh_key_pairs, **kwargs):
    keys0, keys1 = hh_key_pairs
    return (
        hh.HeavyHittersServer(HH_CONFIG, keys0, **kwargs),
        hh.HeavyHittersServer(HH_CONFIG, keys1, **kwargs),
    )


def make_config(**overrides):
    base = dict(
        max_batch_size=4,
        max_wait_ms=5.0,
        helper_timeout_ms=None,
        helper_retries=2,
        helper_backoff_ms=1.0,
        helper_backoff_max_ms=2.0,
    )
    base.update(overrides)
    return ServingConfig(**base)


def leader_helper_pair(leader_config=None):
    helper = HelperSession(DATABASE, encrypt_decrypt.decrypt, make_config())
    leader = LeaderSession(
        DATABASE,
        InProcessTransport(helper.handle_wire),
        leader_config if leader_config is not None else make_config(),
    )
    return leader, helper


def run_query(leader, indices):
    client = DenseDpfPirClient.create(NUM_RECORDS, encrypt_decrypt.encrypt)
    request, state = client.create_request(indices)
    response = leader.handle_request(request)
    return client.handle_response(response, state)


# ---------------------------------------------------------------------------
# PIR serving under fault schedules
# ---------------------------------------------------------------------------


def test_helper_leg_fault_schedule_retries_to_bit_identical_answer(
    clean_failpoints,
):
    # Two injected faults on the helper leg: the retry ladder absorbs
    # them (helper_retries=2) and the answer must equal the records.
    clean_failpoints.arm("service.helper_leg", "error", times=2)
    leader, helper = leader_helper_pair()
    with helper, leader:
        got = run_query(leader, [3, 42, 127])
        counters = leader.metrics.export()["counters"]
    assert got == [RECORDS[3], RECORDS[42], RECORDS[127]]
    assert counters["leader.helper_retries"] == 2
    assert counters["leader.helper_failures"] == 0


def test_latency_spike_schedule_changes_timing_not_bytes(clean_failpoints):
    clean_failpoints.arm(
        "service.helper_leg", "delay", times=None, delay_ms=20.0
    )
    leader, helper = leader_helper_pair()
    with helper, leader:
        got = run_query(leader, [7])
    assert got == [RECORDS[7]]


def test_breaker_opens_and_fast_fails_under_a_millisecond(clean_failpoints):
    # Permanent helper-leg failure: after threshold consecutive leg
    # failures the breaker opens, and every later request fast-fails
    # to HelperUnavailable without serialization/backoff.
    clean_failpoints.arm("service.helper_leg", "error", times=None)
    config = make_config(
        helper_retries=0,
        breaker_failure_threshold=3,
        breaker_reset_ms=60_000.0,  # stays open for the whole test
    )
    leader, helper = leader_helper_pair(leader_config=config)
    client = DenseDpfPirClient.create(NUM_RECORDS, encrypt_decrypt.encrypt)
    with helper, leader:
        for _ in range(3):
            with pytest.raises(HelperUnavailable):
                run_query(leader, [1])
        assert leader.breaker.state == "open"

        # End-to-end: an open breaker surfaces as HelperUnavailable.
        request, _ = client.create_request([1])
        with pytest.raises(HelperUnavailable, match="fast-fail"):
            leader.handle_request(request)

        # Acceptance bar: the open-breaker helper leg costs well under
        # 1 ms per request — no serialization, no connect, no backoff.
        # (Timed at the leg, where the breaker guards; handle_request
        # wraps it in batching waits that are not breaker cost.)
        durations = []
        for _ in range(30):
            t0 = time.perf_counter()
            with pytest.raises(HelperUnavailable, match="fast-fail"):
                leader._send_to_helper(None, lambda: None)
            durations.append(time.perf_counter() - t0)
        counters = leader.metrics.export()["counters"]
        export = leader.breaker_export()
    durations.sort()
    median = durations[len(durations) // 2]
    assert median < 1e-3, f"fast-fail median {median * 1e3:.3f} ms"
    assert counters["leader.breaker_opens"] == 1
    assert counters["leader.breaker_fast_fails"] >= 30
    assert export["state"] == "open"
    assert export["state_code"] == 2


def test_degraded_mode_recovers_when_probe_closes_breaker(clean_failpoints):
    # Helper leg fails exactly 3 times -> breaker (threshold 3) opens
    # and the Leader serves degraded. Once the fault schedule is
    # exhausted, the half-open probe succeeds, the breaker closes, and
    # responses return to full two-share answers.
    clean_failpoints.arm("service.helper_leg", "error", times=3)
    config = make_config(
        helper_retries=0,
        allow_degraded=True,
        breaker_failure_threshold=3,
        breaker_reset_ms=30.0,
    )
    leader, helper = leader_helper_pair(leader_config=config)
    client = DenseDpfPirClient.create(NUM_RECORDS, encrypt_decrypt.encrypt)
    with helper, leader:
        for _ in range(3):
            request, _ = client.create_request([11])
            degraded = leader.handle_request(request)
        assert leader.breaker.state == "open"
        assert leader.degraded
        assert leader.breaker_export()["degraded_mode"] is True
        # Degraded answers are Leader-share-only: NOT the record.
        masked = degraded.dpf_pir_response.masked_response
        assert len(masked) == 1 and masked[0] != RECORDS[11]

        time.sleep(0.05)  # past the reset window: next request probes
        recovered = run_query(leader, [11])
        counters = leader.metrics.export()["counters"]
    assert recovered == [RECORDS[11]]  # full two-share answer again
    assert leader.breaker.state == "closed"
    assert not leader.degraded
    assert counters["leader.degraded_exits"] == 1
    assert counters["leader.degraded_responses"] == 3


def test_own_share_computes_once_even_when_on_sent_fires_twice():
    # Regression: a transparent reconnect (or fault resend) re-invokes
    # on_sent; the Leader's own share must be computed exactly once or
    # the XOR combination would double-fold it.
    calls = {"n": 0}

    class DoubleOnSentTransport(InProcessTransport):
        def roundtrip(self, payload, timeout=None, on_sent=None):
            if on_sent is not None:
                on_sent()
                calls["n"] += 1
            return super().roundtrip(payload, timeout, on_sent)

    helper = HelperSession(DATABASE, encrypt_decrypt.decrypt, make_config())
    leader = LeaderSession(
        DATABASE,
        DoubleOnSentTransport(helper.handle_wire),
        make_config(),
    )
    with helper, leader:
        got = run_query(leader, [64])
    assert calls["n"] >= 1  # the hook really did fire an extra time
    assert got == [RECORDS[64]]


def test_batcher_worker_fault_fans_out_and_worker_survives(clean_failpoints):
    clean_failpoints.arm("batcher.evaluate", "error", times=1)
    client = DenseDpfPirClient.create(NUM_RECORDS, lambda pt, ci: pt)
    with PlainSession(DATABASE, make_config()) as session:
        with pytest.raises(Exception, match="injected fault"):
            session.handle_request(client.create_plain_requests([5])[0])
        # The worker thread survived the fault and keeps serving.
        request = client.create_plain_requests([5])[0]
        got = session.handle_request(request)
        oracle = DenseDpfPirServer.create_plain(DATABASE)
        want = oracle.handle_plain_request(request)
    assert (
        got.dpf_pir_response.masked_response
        == want.dpf_pir_response.masked_response
    )


def test_device_oom_demotes_tier_and_stays_bit_identical(clean_failpoints):
    # A multi-block database (8 selection blocks, expand_levels 3) so
    # there IS a lower tier to demote to; DATABASE above is one block.
    rng = np.random.default_rng(5)
    builder = DenseDpfPirDatabase.Builder()
    for _ in range(1024):
        builder.insert(bytes(rng.integers(0, 256, 8, dtype=np.uint8)))
    database = builder.build()
    client = DenseDpfPirClient.create(1024, lambda pt, ci: pt)
    request = client.create_plain_requests([23, 999])[0]
    want = (
        DenseDpfPirServer.create_plain(database)
        .handle_plain_request(request)
        .dpf_pir_response
    )

    before = tracing.runtime_counters.get("pir.tier_demotions")
    clean_failpoints.arm("device.dispatch.pir.plain", "oom", times=1)
    server = DenseDpfPirServer.create_plain(database)
    with pytest.warns(UserWarning, match="demoting this shape"):
        got = server.handle_plain_request(request).dpf_pir_response
    assert got.masked_response == want.masked_response
    assert tracing.runtime_counters.get("pir.tier_demotions") == before + 1
    # The demotion floor is sticky for this shape: later batches plan
    # straight at the lower tier, no OOM required.
    again = server.handle_plain_request(request).dpf_pir_response
    assert again.masked_response == want.masked_response
    assert server._tier_floor == {2: 1}  # num_keys=2 -> streaming floor


# ---------------------------------------------------------------------------
# Heavy-hitters sweep under fault schedules
# ---------------------------------------------------------------------------


def test_hh_inproc_fault_schedule_resends_to_oracle(
    clean_failpoints, hh_key_pairs
):
    # Drop one round trip outright: the round retry resends, the
    # Helper's replay cache keeps the resend idempotent, and the final
    # heavy-hitter set equals the plaintext oracle.
    clean_failpoints.arm(
        "transport.inproc.roundtrip", "error", times=1, after=1
    )
    s0, s1 = hh_servers(hh_key_pairs, allow_resume=True)
    metrics = MetricsRegistry()
    leader = hh.HeavyHittersLeader(
        s0,
        InProcessTransport(hh.HeavyHittersHelper(s1).handle_wire),
        metrics=metrics,
        round_retries=2,
    )
    result = leader.run()
    assert result.as_dict() == HH_ORACLE
    # The dropped trip is absorbed either by the version-downgrade
    # probe (a TransportError is indistinguishable from an old peer on
    # the first fault) or by the round retry — both are free resends.
    counters = metrics.export()["counters"]
    assert (
        counters["hh.round_retries"] + counters["hh.wire_downgrades"]
    ) >= 1


def test_hh_corrupt_frame_never_decodes_to_wrong_share(
    clean_failpoints, hh_key_pairs
):
    # A flipped byte anywhere in the response frame must surface as a
    # typed error (IntegrityError checksum, or a header/body
    # ProtocolError) and be resent — never decode into a wrong share.
    clean_failpoints.arm("transport.response", "corrupt", times=2)
    s0, s1 = hh_servers(hh_key_pairs, allow_resume=True)
    metrics = MetricsRegistry()
    leader = hh.HeavyHittersLeader(
        s0,
        InProcessTransport(hh.HeavyHittersHelper(s1).handle_wire),
        metrics=metrics,
        round_retries=4,
    )
    result = leader.run()
    counters = metrics.export()["counters"]
    assert result.as_dict() == HH_ORACLE
    recovered = (
        counters["hh.round_retries"]
        + counters["hh.corrupt_frames"]
        + counters["hh.wire_downgrades"]
    )
    assert recovered >= 1


def test_hh_corrupt_frame_over_tcp_matches_oracle_too(
    clean_failpoints, hh_key_pairs
):
    clean_failpoints.arm("transport.response", "corrupt", times=1)
    s0, s1 = hh_servers(hh_key_pairs, allow_resume=True)
    metrics = MetricsRegistry()
    helper = hh.HeavyHittersHelper(s1)
    with FramedTcpServer(helper.handle_wire, name="hh-chaos") as srv:
        with TcpTransport("localhost", srv.port) as transport:
            leader = hh.HeavyHittersLeader(
                s0,
                transport,
                metrics=metrics,
                round_timeout_ms=120_000.0,
                round_retries=4,
            )
            result = leader.run()
    counters = metrics.export()["counters"]
    assert result.as_dict() == HH_ORACLE
    assert (
        counters["hh.round_retries"]
        + counters["hh.corrupt_frames"]
        + counters["hh.wire_downgrades"]
    ) >= 1


def test_hh_request_corruption_rejected_by_helper(
    clean_failpoints, hh_key_pairs
):
    # Corrupt the REQUEST leg: the Helper must reject the frame with a
    # typed error and count it — and the sweep still converges to the
    # oracle via the round retry.
    clean_failpoints.arm("transport.request", "corrupt", times=1)
    s0, s1 = hh_servers(hh_key_pairs, allow_resume=True)
    helper_metrics = MetricsRegistry()
    leader_metrics = MetricsRegistry()
    leader = hh.HeavyHittersLeader(
        s0,
        InProcessTransport(
            hh.HeavyHittersHelper(s1, metrics=helper_metrics).handle_wire
        ),
        metrics=leader_metrics,
        round_retries=4,
    )
    result = leader.run()
    assert result.as_dict() == HH_ORACLE
    helper_counters = helper_metrics.export()["counters"]
    leader_counters = leader_metrics.export()["counters"]
    # Either the CRC caught it on the Helper (IntegrityError) or the
    # flip landed in the header and the Leader re-sent one version
    # down; both are typed recoveries, neither is a wrong count.
    assert (
        helper_counters.get("hh.corrupt_frames", 0)
        + leader_counters["hh.wire_downgrades"]
        + leader_counters["hh.round_retries"]
    ) >= 1


def test_hh_helper_restart_mid_sweep_detected_and_survived(hh_key_pairs):
    # The Helper "restarts" between rounds: a fresh Helper instance
    # (new session epoch, empty sweep state) takes over the handler.
    # The epoch change is counted, the new Helper rebuilds the round
    # from the root (allow_resume), and the result stays oracle-exact.
    s0, s1 = hh_servers(hh_key_pairs, allow_resume=True)
    _, keys1 = hh_key_pairs
    helper_a = hh.HeavyHittersHelper(s1, epoch=1)
    restarted_server = hh.HeavyHittersServer(
        HH_CONFIG, keys1, allow_resume=True
    )
    helper_b = hh.HeavyHittersHelper(restarted_server, epoch=2)
    seen = {"n": 0}

    def handler(payload):
        seen["n"] += 1
        helper = helper_a if seen["n"] <= 1 else helper_b
        return helper.handle_wire(payload)

    metrics = MetricsRegistry()
    leader = hh.HeavyHittersLeader(
        s0, InProcessTransport(handler), metrics=metrics
    )
    result = leader.run()
    assert result.as_dict() == HH_ORACLE
    assert leader.helper_epoch == 2
    assert metrics.export()["counters"]["hh.helper_restarts"] == 1


def test_hh_sweep_checkpoint_resume_after_leader_crash(
    clean_failpoints, hh_key_pairs, tmp_path
):
    # Kill the sweep after round 0 completes (fault on the round-1
    # trip, no retries). A fresh Leader — new process, new server
    # instance — resumes from the checkpoint and must land on the
    # oracle WITHOUT replaying round 0.
    ckpt = str(tmp_path / "sweep.json")
    keys0, keys1 = hh_key_pairs
    helper_server = hh.HeavyHittersServer(HH_CONFIG, keys1, allow_resume=True)
    transport = InProcessTransport(
        hh.HeavyHittersHelper(helper_server).handle_wire
    )

    clean_failpoints.arm(
        "transport.inproc.roundtrip", "error", times=None, after=1
    )
    crashed = hh.HeavyHittersLeader(
        hh.HeavyHittersServer(HH_CONFIG, keys0),
        transport,
        checkpoint=ckpt,
    )
    with pytest.raises(Exception, match="injected fault"):
        crashed.run()
    clean_failpoints.clear()

    # "Restarted" Leader: fresh server (its sweep state starts empty;
    # evaluate_round rebuilds the resumed round from the root — the
    # PR 3 invariant), same checkpoint path.
    metrics = MetricsRegistry()
    resumed = hh.HeavyHittersLeader(
        hh.HeavyHittersServer(HH_CONFIG, keys0, allow_resume=True),
        transport,
        metrics=metrics,
        checkpoint=ckpt,
    )
    result = resumed.run()
    counters = metrics.export()["counters"]
    assert result.as_dict() == HH_ORACLE
    assert counters["hh.sweep_resumes"] == 1
    # Only the crashed round re-ran: the full sweep is 2 rounds and
    # the resumed run sent exactly the remaining one.
    assert counters["hh.rounds"] == 1
    # Both rounds' stats survive in the result via the checkpoint.
    assert len(result.rounds) == 2
    import os

    assert not os.path.exists(ckpt)  # deleted on completion


def test_hh_checkpoint_config_mismatch_refuses_resume(
    hh_key_pairs, tmp_path
):
    ckpt = str(tmp_path / "sweep.json")
    keys0, keys1 = hh_key_pairs
    from distributed_point_functions_tpu.robustness import CheckpointStore

    sweep = hh.FrontierSweep(HH_CONFIG)
    CheckpointStore(ckpt).save(sweep.snapshot())
    other = hh.HeavyHittersConfig(domain_bits=8, level_bits=2, threshold=2)
    client = hh.HeavyHittersClient(other)
    keys = [client.generate_report(1)[0]]
    leader = hh.HeavyHittersLeader(
        hh.HeavyHittersServer(other, keys),
        InProcessTransport(lambda p: p),
        checkpoint=ckpt,
    )
    with pytest.raises(hh.ProtocolError, match="checkpoint"):
        leader.run()
