"""Framed transport tests: framing, pooling, reconnect, timeouts."""

import socket
import struct
import threading
import time

import pytest

from distributed_point_functions_tpu.serving import (
    FramedTcpServer,
    InProcessTransport,
    TcpTransport,
    TransportError,
    TransportTimeout,
    parse_hostport,
    recv_msg,
    send_msg,
)


def test_parse_hostport():
    assert parse_hostport("localhost:9001") == ("localhost", 9001)
    assert parse_hostport("10.0.0.2:80") == ("10.0.0.2", 80)
    with pytest.raises(ValueError):
        parse_hostport("no-port")


def test_send_recv_roundtrip_over_socketpair():
    a, b = socket.socketpair()
    try:
        send_msg(a, b"hello \x00 world")
        assert recv_msg(b) == b"hello \x00 world"
        send_msg(b, b"")
        assert recv_msg(a) == b""
    finally:
        a.close()
        b.close()


def test_recv_rejects_oversized_length_prefix():
    a, b = socket.socketpair()
    try:
        a.sendall(struct.pack(">I", (1 << 30) + 1))
        with pytest.raises(TransportError):
            recv_msg(b)
    finally:
        a.close()
        b.close()


def test_in_process_transport_on_sent_ordering():
    events = []

    def handler(payload):
        events.append(("handled", payload))
        return payload.upper()

    t = InProcessTransport(handler)
    out = t.roundtrip(b"abc", on_sent=lambda: events.append(("sent", None)))
    assert out == b"ABC"
    # on_sent fires after the send, before the reply is consumed.
    assert events[0][0] == "sent"


def test_framed_tcp_server_echo_and_connection_reuse():
    with FramedTcpServer(lambda data: b"echo:" + data) as server:
        t = TcpTransport("localhost", server.port)
        try:
            assert t.roundtrip(b"one") == b"echo:one"
            assert t.roundtrip(b"two") == b"echo:two"
            # Both round-trips reused one pooled connection.
            assert t.reconnects == 0
        finally:
            t.close()


def test_tcp_transport_reconnects_after_peer_restart():
    handler = lambda data: b"ok:" + data  # noqa: E731
    server = FramedTcpServer(handler)
    server.start()
    port = server.port
    t = TcpTransport("localhost", port)
    try:
        assert t.roundtrip(b"a") == b"ok:a"
        server.stop()
        # Same port, fresh server: the pooled connection is stale and the
        # transport must transparently reconnect and resend once.
        server = FramedTcpServer(handler, port=port)
        server.start()
        assert t.roundtrip(b"b") == b"ok:b"
        assert t.reconnects >= 1
    finally:
        t.close()
        server.stop()


def test_tcp_transport_timeout_on_slow_handler():
    def slow(data):
        time.sleep(2.0)
        return data

    with FramedTcpServer(slow) as server:
        t = TcpTransport("localhost", server.port)
        try:
            with pytest.raises(TransportTimeout):
                t.roundtrip(b"x", timeout=0.1)
        finally:
            t.close()


def test_tcp_transport_connection_refused_raises_immediately():
    # Grab a port that is definitely closed.
    probe = socket.socket()
    probe.bind(("localhost", 0))
    port = probe.getsockname()[1]
    probe.close()
    t = TcpTransport("localhost", port, connect_timeout=0.5)
    try:
        with pytest.raises(TransportError):
            t.roundtrip(b"x")
    finally:
        t.close()


def test_framed_server_survives_handler_exception():
    calls = []

    def flaky(data):
        calls.append(data)
        if data == b"bad":
            raise ValueError("handler bug")
        return b"ok"

    with FramedTcpServer(flaky) as server:
        t1 = TcpTransport("localhost", server.port)
        try:
            # The failing request drops its connection...
            with pytest.raises(TransportError):
                t1.roundtrip(b"bad", timeout=2.0)
        finally:
            t1.close()
        # ...but the server keeps accepting new ones.
        t2 = TcpTransport("localhost", server.port)
        try:
            assert t2.roundtrip(b"good") == b"ok"
        finally:
            t2.close()


def test_concurrent_clients_one_server():
    with FramedTcpServer(lambda d: d[::-1]) as server:
        results = {}

        def client(i):
            t = TcpTransport("localhost", server.port)
            try:
                payload = b"payload-%d" % i
                for _ in range(3):
                    results[i] = t.roundtrip(payload)
            finally:
                t.close()

        threads = [
            threading.Thread(target=client, args=(i,)) for i in range(8)
        ]
        for th in threads:
            th.start()
        for th in threads:
            th.join()
        for i in range(8):
            assert results[i] == (b"payload-%d" % i)[::-1]
