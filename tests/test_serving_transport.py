"""Framed transport tests: framing, pooling, reconnect, timeouts."""

import socket
import struct
import threading
import time

import pytest

from distributed_point_functions_tpu.serving import (
    FramedTcpServer,
    InProcessTransport,
    TcpTransport,
    TransportError,
    TransportTimeout,
    parse_hostport,
    recv_msg,
    send_msg,
)


def test_parse_hostport():
    assert parse_hostport("localhost:9001") == ("localhost", 9001)
    assert parse_hostport("10.0.0.2:80") == ("10.0.0.2", 80)
    with pytest.raises(ValueError):
        parse_hostport("no-port")


def test_send_recv_roundtrip_over_socketpair():
    a, b = socket.socketpair()
    try:
        send_msg(a, b"hello \x00 world")
        assert recv_msg(b) == b"hello \x00 world"
        send_msg(b, b"")
        assert recv_msg(a) == b""
    finally:
        a.close()
        b.close()


def test_recv_rejects_oversized_length_prefix():
    a, b = socket.socketpair()
    try:
        a.sendall(struct.pack(">I", (1 << 30) + 1))
        with pytest.raises(TransportError):
            recv_msg(b)
    finally:
        a.close()
        b.close()


def test_in_process_transport_on_sent_ordering():
    events = []

    def handler(payload):
        events.append(("handled", payload))
        return payload.upper()

    t = InProcessTransport(handler)
    out = t.roundtrip(b"abc", on_sent=lambda: events.append(("sent", None)))
    assert out == b"ABC"
    # on_sent fires after the send, before the reply is consumed.
    assert events[0][0] == "sent"


def test_framed_tcp_server_echo_and_connection_reuse():
    with FramedTcpServer(lambda data: b"echo:" + data) as server:
        t = TcpTransport("localhost", server.port)
        try:
            assert t.roundtrip(b"one") == b"echo:one"
            assert t.roundtrip(b"two") == b"echo:two"
            # Both round-trips reused one pooled connection.
            assert t.reconnects == 0
        finally:
            t.close()


def test_tcp_transport_reconnects_after_peer_restart():
    handler = lambda data: b"ok:" + data  # noqa: E731
    server = FramedTcpServer(handler)
    server.start()
    port = server.port
    t = TcpTransport("localhost", port)
    try:
        assert t.roundtrip(b"a") == b"ok:a"
        server.stop()
        # Same port, fresh server: the pooled connection is stale and the
        # transport must transparently reconnect and resend once.
        server = FramedTcpServer(handler, port=port)
        server.start()
        assert t.roundtrip(b"b") == b"ok:b"
        assert t.reconnects >= 1
    finally:
        t.close()
        server.stop()


def test_tcp_transport_timeout_on_slow_handler():
    def slow(data):
        time.sleep(2.0)
        return data

    with FramedTcpServer(slow) as server:
        t = TcpTransport("localhost", server.port)
        try:
            with pytest.raises(TransportTimeout):
                t.roundtrip(b"x", timeout=0.1)
        finally:
            t.close()


def test_tcp_transport_connection_refused_raises_immediately():
    # Grab a port that is definitely closed.
    probe = socket.socket()
    probe.bind(("localhost", 0))
    port = probe.getsockname()[1]
    probe.close()
    t = TcpTransport("localhost", port, connect_timeout=0.5)
    try:
        with pytest.raises(TransportError):
            t.roundtrip(b"x")
    finally:
        t.close()


def test_framed_server_survives_handler_exception():
    calls = []

    def flaky(data):
        calls.append(data)
        if data == b"bad":
            raise ValueError("handler bug")
        return b"ok"

    with FramedTcpServer(flaky) as server:
        t1 = TcpTransport("localhost", server.port)
        try:
            # The failing request drops its connection...
            with pytest.raises(TransportError):
                t1.roundtrip(b"bad", timeout=2.0)
        finally:
            t1.close()
        # ...but the server keeps accepting new ones.
        t2 = TcpTransport("localhost", server.port)
        try:
            assert t2.roundtrip(b"good") == b"ok"
        finally:
            t2.close()


def test_concurrent_clients_one_server():
    with FramedTcpServer(lambda d: d[::-1]) as server:
        results = {}

        def client(i):
            t = TcpTransport("localhost", server.port)
            try:
                payload = b"payload-%d" % i
                for _ in range(3):
                    results[i] = t.roundtrip(payload)
            finally:
                t.close()

        threads = [
            threading.Thread(target=client, args=(i,)) for i in range(8)
        ]
        for th in threads:
            th.start()
        for th in threads:
            th.join()
        for i in range(8):
            assert results[i] == (b"payload-%d" % i)[::-1]


# ---------------------------------------------------------------------------
# Reconnect accounting and per-call deadline (robustness satellites)
# ---------------------------------------------------------------------------


def test_tcp_reconnect_counted_in_metrics_registry():
    from distributed_point_functions_tpu.serving.metrics import (
        MetricsRegistry,
    )

    handler = lambda data: b"ok:" + data  # noqa: E731
    metrics = MetricsRegistry()
    server = FramedTcpServer(handler)
    server.start()
    port = server.port
    t = TcpTransport("localhost", port, metrics=metrics)
    try:
        assert t.roundtrip(b"a") == b"ok:a"
        server.stop()
        server = FramedTcpServer(handler, port=port)
        server.start()
        assert t.roundtrip(b"b") == b"ok:b"
        assert t.reconnects >= 1
        counters = metrics.export()["counters"]
        assert counters["transport.reconnects"] == t.reconnects
    finally:
        t.close()
        server.stop()


def test_tcp_stale_reconnect_honors_remaining_deadline():
    # The transparent reconnect+resend must run inside the SAME
    # per-call deadline as the original attempt: when a stale pooled
    # connection surfaces after the budget is gone, the call times out
    # instead of borrowing a fresh connect_timeout.
    from distributed_point_functions_tpu.robustness import failpoints

    reg = failpoints.default_failpoints()
    reg.clear()
    handler = lambda data: b"ok:" + data  # noqa: E731
    with FramedTcpServer(handler) as server:
        t = TcpTransport("localhost", server.port, connect_timeout=5.0)
        try:
            assert t.roundtrip(b"a", timeout=1.0) == b"ok:a"
            # The pooled connection "goes stale" only after the whole
            # 200 ms budget is burned: a send fault delayed past the
            # deadline.
            reg.arm(
                "transport.tcp.send",
                "error",
                times=1,
                delay_ms=300.0,
                message="stale pooled connection",
            )
            t0 = time.time()
            with pytest.raises(TransportTimeout, match="no budget remains"):
                t.roundtrip(b"b", timeout=0.2)
            elapsed = time.time() - t0
            # No reconnect happened (nothing left to spend on it) and
            # the call never borrowed the 5 s connect_timeout.
            assert t.reconnects == 0
            assert elapsed < 2.0
        finally:
            reg.clear()
            t.close()


def test_tcp_zero_remaining_budget_raises_timeout_not_hang():
    from distributed_point_functions_tpu.robustness import failpoints

    reg = failpoints.default_failpoints()
    reg.clear()

    def slow(data):
        time.sleep(0.15)
        return b"ok:" + data

    with FramedTcpServer(slow) as server:
        t = TcpTransport("localhost", server.port)
        try:
            with pytest.raises(TransportTimeout):
                t.roundtrip(b"x", timeout=0.05)
        finally:
            t.close()
