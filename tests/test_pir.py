"""Dense PIR tests: inner product, database, servers, client, protocol.

Mirrors the reference's test strategy (SURVEY.md §4): share-correctness of
the selection vectors, SIMD-vs-scalar differential tests of the inner
product, end-to-end Plain and Leader/Helper protocol runs with an
in-process lambda as "the network" (`pir/dpf_pir_server_test.cc:145-196`).
"""

import secrets

import numpy as np
import pytest

from distributed_point_functions_tpu.ops.inner_product import (
    pack_selection_bits_np,
    xor_inner_product,
    xor_inner_product_np,
)
from distributed_point_functions_tpu.pir import (
    DenseDpfPirClient,
    DenseDpfPirDatabase,
    DenseDpfPirServer,
    messages,
)
from distributed_point_functions_tpu.prng import Aes128CtrSeededPrng, xor_bytes
from distributed_point_functions_tpu.testing import encrypt_decrypt

RNG = np.random.default_rng(42)


def random_records(n, size=32, variable=False):
    return [
        bytes(RNG.integers(0, 256, RNG.integers(1, size + 1) if variable else size, dtype=np.uint8))
        for _ in range(n)
    ]


# ---------------------------------------------------------------------------
# Inner product kernel
# ---------------------------------------------------------------------------


@pytest.mark.parametrize(
    "num_records,num_words,nq",
    [(128, 8, 1), (256, 20, 3), (384, 1, 2), (1024, 64, 4)],
)
def test_xor_inner_product_matches_oracle(num_records, num_words, nq):
    db = RNG.integers(0, 1 << 32, (num_records, num_words), dtype=np.uint32)
    bits = RNG.integers(0, 2, (nq, num_records), dtype=np.uint32)
    selections = pack_selection_bits_np(bits)
    got = np.asarray(xor_inner_product(db, selections))
    want = xor_inner_product_np(db, selections)
    np.testing.assert_array_equal(got, want)


def test_xor_inner_product_chunking_invariance():
    db = RNG.integers(0, 1 << 32, (896, 4), dtype=np.uint32)
    bits = RNG.integers(0, 2, (2, 896), dtype=np.uint32)
    selections = pack_selection_bits_np(bits)
    a = np.asarray(xor_inner_product(db, selections, chunk=128))
    b = np.asarray(xor_inner_product(db, selections, chunk=896))
    c = np.asarray(xor_inner_product(db, selections, chunk=300))
    np.testing.assert_array_equal(a, b)
    np.testing.assert_array_equal(a, c)


# ---------------------------------------------------------------------------
# PRNG
# ---------------------------------------------------------------------------


def test_prng_deterministic_and_split_invariant():
    seed = secrets.token_bytes(16)
    p1 = Aes128CtrSeededPrng(seed)
    p2 = Aes128CtrSeededPrng(seed)
    a = p1.get_random_bytes(7) + p1.get_random_bytes(25) + p1.get_random_bytes(0) + p1.get_random_bytes(100)
    b = p2.get_random_bytes(132)
    assert a == b
    assert len(set([bytes(a), p2.get_random_bytes(132)])) == 2


def test_prng_nonce_gives_independent_streams():
    seed = secrets.token_bytes(16)
    a = Aes128CtrSeededPrng(seed, b"\x00" * 16).get_random_bytes(32)
    b = Aes128CtrSeededPrng(seed, b"\x01" + b"\x00" * 15).get_random_bytes(32)
    assert a != b


def test_prng_matches_ctr_mode_semantics():
    # Keystream block i must be AES_seed(nonce + i) with a big-endian counter.
    from distributed_point_functions_tpu.ops import aes

    seed = bytes(range(16))
    nonce = (123).to_bytes(16, "big")
    rk = aes.key_expansion(seed)
    blocks = np.stack(
        [
            np.frombuffer((123 + i).to_bytes(16, "big"), dtype=np.uint8)
            for i in range(3)
        ]
    )
    want = aes.aes_encrypt_np(rk, blocks).tobytes()
    got = Aes128CtrSeededPrng(seed, nonce).get_random_bytes(48)
    assert got == want


# ---------------------------------------------------------------------------
# Database
# ---------------------------------------------------------------------------


def test_database_basic_properties():
    records = random_records(10, size=40, variable=True)
    db = DenseDpfPirDatabase.Builder()
    for r in records:
        db.insert(r)
    db = db.build()
    assert db.size == 10
    assert db.max_value_size == max(len(r) for r in records)
    assert db.num_selection_bits == 128
    for i, r in enumerate(records):
        assert db.record(i) == r


def test_database_inner_product_single_bits():
    records = random_records(5, size=16)
    db = DenseDpfPirDatabase(records)
    bits = np.zeros((5, db.num_selection_bits), dtype=np.uint32)
    for q in range(5):
        bits[q, q] = 1
    out = db.inner_product_with(
        np.asarray(pack_selection_bits_np(bits))
    )
    for q in range(5):
        assert out[q][: len(records[q])] == records[q]


def test_database_inner_product_xor_of_pair():
    records = random_records(4, size=8)
    db = DenseDpfPirDatabase(records)
    bits = np.zeros((1, db.num_selection_bits), dtype=np.uint32)
    bits[0, 1] = 1
    bits[0, 3] = 1
    out = db.inner_product_with(np.asarray(pack_selection_bits_np(bits)))
    assert out[0][:8] == xor_bytes(records[1], records[3])


# ---------------------------------------------------------------------------
# Dense server + client, plain protocol
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("num_records", [3, 100, 130, 1000])
def test_plain_protocol_end_to_end(num_records):
    records = random_records(num_records, size=24, variable=True)
    database = DenseDpfPirDatabase(records)
    server = DenseDpfPirServer.create_plain(database)
    client = DenseDpfPirClient.create(num_records, encrypt_decrypt.encrypt)

    indices = [0, num_records - 1, num_records // 2]
    req0, req1 = client.create_plain_requests(indices)
    resp0 = server.handle_request(req0)
    resp1 = server.handle_request(req1)
    for i, idx in enumerate(indices):
        combined = xor_bytes(
            resp0.dpf_pir_response.masked_response[i],
            resp1.dpf_pir_response.masked_response[i],
        )
        assert combined[: len(records[idx])] == records[idx]
        # Bytes beyond the record are zero padding.
        assert all(b == 0 for b in combined[len(records[idx]) :])


def test_plain_request_rejects_malformed_keys():
    records = random_records(100)
    server = DenseDpfPirServer.create_plain(DenseDpfPirDatabase(records))
    client = DenseDpfPirClient.create(100, encrypt_decrypt.encrypt)
    req0, _ = client.create_plain_requests([5])
    req0.plain_request.dpf_keys[0].correction_words.pop()
    with pytest.raises(ValueError, match="correction words"):
        server.handle_request(req0)
    with pytest.raises(ValueError, match="not be empty"):
        server.handle_request(
            messages.PirRequest(
                plain_request=messages.PlainRequest(dpf_keys=[])
            )
        )


# ---------------------------------------------------------------------------
# Leader/Helper protocol with an in-process "network"
# ---------------------------------------------------------------------------


def make_leader_helper_pair(records):
    database = DenseDpfPirDatabase(records)
    helper = DenseDpfPirServer.create_helper(
        DenseDpfPirDatabase(records), encrypt_decrypt.decrypt
    )

    def sender(helper_request, while_waiting):
        # Plays the network: forwards to the helper, runs the callback
        # "while waiting" like the reference test does
        # (`pir/dpf_pir_server_test.cc:145-196`).
        while_waiting()
        return helper.handle_request(helper_request)

    leader = DenseDpfPirServer.create_leader(database, sender)
    return leader, helper


def test_leader_helper_protocol_end_to_end():
    num_records = 300
    records = random_records(num_records, size=16, variable=True)
    leader, _ = make_leader_helper_pair(records)
    client = DenseDpfPirClient.create(num_records, encrypt_decrypt.encrypt)

    indices = [7, 0, 299, 131]
    request, state = client.create_request(indices)
    response = leader.handle_request(request)
    results = client.handle_response(response, state)
    assert len(results) == len(indices)
    for got, idx in zip(results, indices):
        assert got[: len(records[idx])] == records[idx]


def test_leader_detects_sender_not_calling_while_waiting():
    records = random_records(100)
    helper = DenseDpfPirServer.create_helper(
        DenseDpfPirDatabase(records), encrypt_decrypt.decrypt
    )

    def bad_sender(helper_request, while_waiting):
        return helper.handle_request(helper_request)  # never calls back

    leader = DenseDpfPirServer.create_leader(
        DenseDpfPirDatabase(records), bad_sender
    )
    client = DenseDpfPirClient.create(100, encrypt_decrypt.encrypt)
    request, _ = client.create_request([3])
    with pytest.raises(RuntimeError, match="while_waiting"):
        leader.handle_request(request)


def test_client_validates_indices():
    client = DenseDpfPirClient.create(10, encrypt_decrypt.encrypt)
    with pytest.raises(ValueError):
        client.create_request([-1])
    with pytest.raises(ValueError):
        client.create_request([10])


def test_helper_request_roundtrip_serialization():
    client = DenseDpfPirClient.create(1000, encrypt_decrypt.encrypt)
    _, helper_keys = client._generate_key_pairs([3, 997])
    hr = messages.HelperRequest(
        plain_request=messages.PlainRequest(dpf_keys=helper_keys),
        one_time_pad_seed=secrets.token_bytes(16),
    )
    data = messages.serialize_helper_request(client.dpf, hr)
    parsed = messages.parse_helper_request(client.dpf, data)
    assert parsed.one_time_pad_seed == hr.one_time_pad_seed
    assert len(parsed.plain_request.dpf_keys) == 2
    for a, b in zip(parsed.plain_request.dpf_keys, helper_keys):
        assert a.seed == b.seed
        assert a.party == b.party
        assert a.last_level_value_correction == b.last_level_value_correction
        assert len(a.correction_words) == len(b.correction_words)
        for ca, cb in zip(a.correction_words, b.correction_words):
            assert (ca.seed, ca.control_left, ca.control_right) == (
                cb.seed,
                cb.control_left,
                cb.control_right,
            )


def test_concurrent_plain_requests():
    """Regression test mirroring the reference's concurrency hammer
    (`pir/dense_dpf_pir_server_test.cc:307-326`): the server is stateless,
    so parallel `handle_plain_request` calls must all answer correctly."""
    import threading

    records = random_records(96, size=16)
    database = DenseDpfPirDatabase(records)
    server = DenseDpfPirServer.create_plain(database)
    client = DenseDpfPirClient.create(len(records), lambda pt, ci: pt)

    results = {}
    errors = []

    def worker(tid, indices):
        try:
            req0, req1 = client.create_plain_requests(indices)
            r0 = server.handle_plain_request(req0)
            r1 = server.handle_plain_request(req1)
            out = [
                xor_bytes(a, b)[:16]
                for a, b in zip(
                    r0.dpf_pir_response.masked_response,
                    r1.dpf_pir_response.masked_response,
                )
            ]
            results[tid] = (indices, out)
        except Exception as e:  # surfaced below
            errors.append((tid, e))

    threads = [
        threading.Thread(target=worker, args=(t, [(7 * t + k) % 96 for k in range(3)]))
        for t in range(8)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errors, errors
    assert len(results) == 8
    for indices, out in results.values():
        assert out == [records[i] for i in indices]


def test_chunked_serving_matches_unchunked(monkeypatch):
    """With a tiny selection budget the server switches to chunked
    expansion (`chunked_pir_inner_products`); responses must be
    byte-identical to the unchunked pipeline."""
    import numpy as np

    rng = np.random.default_rng(9)
    records = [rng.bytes(20) for _ in range(1500)]  # 12 blocks, pads oddly
    plain = DenseDpfPirServer.create_plain(DenseDpfPirDatabase(records))
    chunked = DenseDpfPirServer.create_plain(DenseDpfPirDatabase(records))
    # 5 queries x 12 blocks x 16B = 960B > 256B budget -> chunking kicks in.
    monkeypatch.setenv("DPF_TPU_SELECTION_BYTES_BUDGET", "256")
    assert chunked._needs_chunking(5)

    client = DenseDpfPirClient.create(1500, encrypt_decrypt.encrypt)
    indices = [0, 77, 1499, 640, 1024]
    keys0, keys1 = client._generate_key_pairs(indices)
    req = messages.PirRequest(
        plain_request=messages.PlainRequest(dpf_keys=list(keys0))
    )
    got = chunked.handle_request(req).dpf_pir_response.masked_response
    monkeypatch.delenv("DPF_TPU_SELECTION_BYTES_BUDGET")
    want = plain.handle_request(req).dpf_pir_response.masked_response
    assert got == want

    # Share correctness through the chunked path for both parties.
    monkeypatch.setenv("DPF_TPU_SELECTION_BYTES_BUDGET", "256")
    r0 = chunked.handle_request(req).dpf_pir_response.masked_response
    r1 = chunked.handle_request(
        messages.PirRequest(
            plain_request=messages.PlainRequest(dpf_keys=list(keys1))
        )
    ).dpf_pir_response.masked_response
    for q, idx in enumerate(indices):
        assert xor_bytes(r0[q], r1[q]) == records[idx]


# ---------------------------------------------------------------------------
# Host-side zeros-walk staging


def test_stage_keys_host_walk_matches_device_walk():
    """`stage_keys(host_walk_levels=K)` must put the staged batch at
    exactly the state the device walk reaches: same seeds/control, and
    the correction-word arrays drop the walked levels."""
    import jax
    import numpy as np

    from distributed_point_functions_tpu.pir.client import DenseDpfPirClient
    from distributed_point_functions_tpu.pir.dense_eval import (
        _walk_zeros,
        stage_keys,
    )

    num_records = 1 << 14  # 128 blocks; tree has walkable prefix levels
    client = DenseDpfPirClient.create(num_records, lambda pt, ci: pt)
    rng = np.random.default_rng(21)
    indices = [int(i) for i in rng.integers(0, num_records, 7)]
    keys0, _ = client._generate_key_pairs(indices)

    plain = stage_keys(keys0)
    total = plain[2].shape[0]
    walk = total - max(0, (128 - 1).bit_length())
    assert walk > 0

    want_seeds, want_ctrl = jax.jit(_walk_zeros)(
        plain[0], plain[1], plain[2][:walk], plain[3][:walk]
    )
    got = stage_keys(keys0, host_walk_levels=walk)
    np.testing.assert_array_equal(np.asarray(got[0]), np.asarray(want_seeds))
    np.testing.assert_array_equal(np.asarray(got[1]), np.asarray(want_ctrl))
    np.testing.assert_array_equal(
        np.asarray(got[2]), np.asarray(plain[2][walk:])
    )
    np.testing.assert_array_equal(
        np.asarray(got[3]), np.asarray(plain[3][walk:])
    )
    np.testing.assert_array_equal(
        np.asarray(got[4]), np.asarray(plain[4][walk:])
    )
    with pytest.raises(ValueError, match="host_walk_levels"):
        stage_keys(keys0, host_walk_levels=total + 1)


def test_stage_keys_host_walk_numpy_fallback(monkeypatch):
    """The numpy MMO fallback walks identically to the native oracle."""
    import numpy as np

    from distributed_point_functions_tpu.pir import dense_eval

    num_records = 1 << 14
    from distributed_point_functions_tpu.pir.client import DenseDpfPirClient

    client = DenseDpfPirClient.create(num_records, lambda pt, ci: pt)
    rng = np.random.default_rng(22)
    indices = [int(i) for i in rng.integers(0, num_records, 5)]
    keys0, _ = client._generate_key_pairs(indices)

    from distributed_point_functions_tpu import native

    # `want` must really come from the native oracle: on a machine where
    # the native lib cannot build, stage_keys would silently fall back to
    # the same numpy walk and the comparison below would be vacuous.
    try:
        native.get_lib()
    except Exception as e:  # noqa: BLE001
        pytest.skip(f"native host-walk oracle unavailable: {e}")

    want = dense_eval.stage_keys(keys0, host_walk_levels=7)

    def no_lib():
        raise OSError("native disabled for test")

    monkeypatch.setattr(native, "get_lib", no_lib)
    monkeypatch.setattr(dense_eval, "_HOST_WALK_NATIVE_UNAVAILABLE", False)
    with pytest.warns(UserWarning, match="numpy path"):
        got = dense_eval.stage_keys(keys0, host_walk_levels=7)
    # The unavailability is remembered: no further warning, still correct.
    assert dense_eval._HOST_WALK_NATIVE_UNAVAILABLE is True
    got2 = dense_eval.stage_keys(keys0, host_walk_levels=7)
    monkeypatch.setattr(dense_eval, "_HOST_WALK_NATIVE_UNAVAILABLE", False)
    for w, g in zip(want, got):
        np.testing.assert_array_equal(np.asarray(w), np.asarray(g))
    for w, g in zip(want, got2):
        np.testing.assert_array_equal(np.asarray(w), np.asarray(g))


def test_plain_serving_with_host_walk_matches_device_walk(monkeypatch):
    """End-to-end: responses are identical with the host walk on and off."""
    records = random_records(3000, size=24)
    database = DenseDpfPirDatabase(records)
    server = DenseDpfPirServer.create_plain(database)
    client = DenseDpfPirClient.create(len(records), encrypt_decrypt.encrypt)
    req0, _ = client.create_plain_requests([5, 1234, 2999])

    monkeypatch.setenv("DPF_TPU_HOST_WALK", "1")
    on = server.handle_request(req0)
    monkeypatch.setenv("DPF_TPU_HOST_WALK", "0")
    off = server.handle_request(req0)
    assert (
        on.dpf_pir_response.masked_response
        == off.dpf_pir_response.masked_response
    )
