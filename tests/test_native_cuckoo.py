"""Native cuckoo builder differentials (`native/cuckoo_build.cc`).

The native table layout may legally differ from the Python builder's
(random eviction order); what must hold is (1) hash semantics identical
to `hashing/sha256_hash_family.py`, (2) every key placed in one of its
own hash buckets with no key lost, (3) the sparse PIR protocol serves
correctly from a natively-built database."""


import numpy as np
import pytest

from distributed_point_functions_tpu import native
from distributed_point_functions_tpu.hashing import (
    create_hash_family_from_config,
)
from distributed_point_functions_tpu.hashing.hash_family import (
    create_hash_functions,
)
from distributed_point_functions_tpu.hashing.hash_family_config import (
    HASH_FAMILY_SHA256,
    HashFamilyConfig,
)

RNG = np.random.default_rng(41)


def _keys(n):
    return [bytes(f"key-{i:08d}", "ascii") for i in range(n)]


def test_native_hash_matches_python_family():
    lib = native.get_lib()
    import ctypes

    keys = [b"alpha", b"beta-longer-key", b"\x00\x01\x02", b"d" * 300]
    family_seed = b"fam-seed-0123"
    seeds = [family_seed + str(i).encode() for i in range(3)]
    nb = 1013
    concat = b"".join(keys)
    offs = np.cumsum([0] + [len(k) for k in keys]).astype(np.uint64)
    sconcat = b"".join(seeds)
    soffs = np.cumsum([0] + [len(s) for s in seeds]).astype(np.uint64)
    out = np.zeros(len(keys) * len(seeds), dtype=np.int64)
    rc = lib.dpf_cuckoo_hash_buckets(
        ctypes.c_char_p(concat),
        offs.ctypes.data_as(ctypes.c_void_p),
        ctypes.c_int64(len(keys)),
        ctypes.c_char_p(sconcat),
        soffs.ctypes.data_as(ctypes.c_void_p),
        ctypes.c_int(len(seeds)),
        ctypes.c_int64(nb),
        out.ctypes.data_as(ctypes.c_void_p),
    )
    assert rc == 0

    config = HashFamilyConfig(HASH_FAMILY_SHA256, family_seed)
    fns = create_hash_functions(create_hash_family_from_config(config), 3)
    want = [fn(k, nb) for k in keys for fn in fns]
    assert out.tolist() == want


def test_native_build_is_legal_assignment():
    keys = _keys(2000)
    family_seed = b"seedling"
    num_hashes = 3
    nb = 3000
    seeds = [family_seed + str(i).encode() for i in range(num_hashes)]
    slots = native.cuckoo_build(keys, seeds, nb, max_relocations=2000)
    assert slots.shape == (nb,)
    placed = slots[slots >= 0]
    # No key lost, none duplicated.
    assert sorted(placed.tolist()) == list(range(len(keys)))
    # Every key sits in one of ITS OWN hash buckets.
    config = HashFamilyConfig(HASH_FAMILY_SHA256, family_seed)
    fns = create_hash_functions(
        create_hash_family_from_config(config), num_hashes
    )
    for b in np.nonzero(slots >= 0)[0][:200]:
        k = keys[slots[b]]
        assert b in {fn(k, nb) for fn in fns}


def test_native_build_failure_raises():
    # 5 keys, 2 buckets, 2 hash functions: pigeonhole failure.
    keys = _keys(5)
    seeds = [b"s0", b"s1"]
    with pytest.raises(RuntimeError, match="relocation"):
        native.cuckoo_build(keys, seeds, 2, max_relocations=64)


def test_sparse_protocol_serves_from_native_build(monkeypatch):
    from distributed_point_functions_tpu.pir.cuckoo_database import (
        CuckooHashedDpfPirDatabase,
    )
    from distributed_point_functions_tpu.pir.sparse_client import (
        CuckooHashingSparseDpfPirClient,
        _is_prefix_padded_with_zeros,
    )
    from distributed_point_functions_tpu.pir.sparse_server import (
        CuckooHashingSparseDpfPirServer,
    )

    monkeypatch.setenv("DPF_NATIVE_CUCKOO", "1")
    pairs = [
        (f"user{i}".encode(), f"value-{i}".encode()) for i in range(300)
    ]
    params = CuckooHashingSparseDpfPirServer.generate_params(
        len(pairs), seed=b"0123456789abcdef"
    )
    builder = CuckooHashedDpfPirDatabase.Builder().set_params(params)
    for kv in pairs:
        builder.insert(kv)
    db = builder.build()
    db2 = builder.clone().build()
    server0 = CuckooHashingSparseDpfPirServer.create_plain(params, db)
    server1 = CuckooHashingSparseDpfPirServer.create_plain(params, db2)
    client = CuckooHashingSparseDpfPirClient.create(
        params, lambda pt, ci: pt
    )
    queries = [b"user3", b"user244", b"missing-key"]
    req0, req1 = client.create_plain_requests(queries)
    r0 = server0.handle_request(req0)
    r1 = server1.handle_request(req1)
    combined = [
        bytes(x ^ y for x, y in zip(a, b))
        for a, b in zip(
            r0.dpf_pir_response.masked_response,
            r1.dpf_pir_response.masked_response,
        )
    ]
    expected = {b"user3": b"value-3", b"user244": b"value-244"}
    nh = params.num_hash_functions
    for i, q in enumerate(queries):
        found = None
        for j in range(nh):
            idx = 2 * (nh * i + j)
            if found is None and _is_prefix_padded_with_zeros(
                combined[idx], q
            ):
                found = combined[idx + 1]
        if q in expected:
            assert found is not None
            assert found[: len(expected[q])] == expected[q]
        else:
            assert found is None or all(b == 0 for b in found)
