"""Cross-replica consistency: the same golden must reconstruct
bit-identically on every replica at the same generation.

`CrossReplicaProbe` issues ONE golden plain pair to every replica,
groups reconstructions by the generation each replica served from,
and asserts bit-identity within each group (plus the oracle when
known). A replica serving different bytes at the same generation is a
divergence: journaled, counted, listener-fired (debug bundle). Also
covers the `/fleetz` admin endpoint and the stable replica identity
on `/varz` + `/statusz` (satellite 2).
"""

import json
import os
import urllib.error
import urllib.request

import numpy as np
import pytest

from distributed_point_functions_tpu.fleet import Replica, ReplicaSet
from distributed_point_functions_tpu.observability import AdminServer
from distributed_point_functions_tpu.observability.bundle import (
    BundleManager,
)
from distributed_point_functions_tpu.observability.events import EventJournal
from distributed_point_functions_tpu.pir import DenseDpfPirDatabase
from distributed_point_functions_tpu.serving import (
    PlainSession,
    ServingConfig,
    SnapshotManager,
)
from distributed_point_functions_tpu.serving.prober import CrossReplicaProbe

NUM_RECORDS = 64
RECORD_BYTES = 16
RNG = np.random.default_rng(1717)

RECORDS0 = [
    bytes(RNG.integers(0, 256, RECORD_BYTES, dtype=np.uint8))
    for _ in range(NUM_RECORDS)
]
RECORDS1 = [bytes(b ^ 0xA5 for b in r) for r in RECORDS0]
# Same size and generation as RECORDS0 but different bytes at every
# index: what a replica restored from the wrong snapshot serves.
RECORDS_CORRUPT = [bytes(b ^ 0x3C for b in r) for r in RECORDS0]


def build_db(records):
    builder = DenseDpfPirDatabase.Builder()
    for r in records:
        builder.insert(r)
    return builder.build()


def delta_db(prev, records):
    builder = DenseDpfPirDatabase.Builder()
    for i, r in enumerate(records):
        builder.update(i, r)
    return builder.build_from(prev)


def make_config():
    return ServingConfig(max_batch_size=8, max_wait_ms=2.0)


def plain_replica(rid, records=RECORDS0):
    session = PlainSession(build_db(records), make_config())
    manager = SnapshotManager(session, journal=EventJournal())
    return Replica(rid, session, leader_snapshots=manager)


def close_all(replicas):
    for r in replicas:
        r.leader.close()


def _get(url):
    with urllib.request.urlopen(url, timeout=10) as resp:
        return resp.status, resp.read().decode()


# ---------------------------------------------------------------------------


def test_identical_replicas_probe_bit_identical():
    replicas = [plain_replica(f"r{i}") for i in range(3)]
    probe = CrossReplicaProbe(replicas, RECORDS0, journal=EventJournal())
    try:
        result = probe.run_cycle()
        assert result["status"] == "pass", result
        assert result["divergences"] == []
        assert result["errors"] == {}
        assert result["replicas"] == ["r0", "r1", "r2"]
        # All three answered from one generation group.
        assert result["generations"] == {"0": ["r0", "r1", "r2"]}
        assert probe.export()["divergences"] == 0
    finally:
        close_all(replicas)


def test_divergent_replica_is_caught_and_bundled(tmp_path):
    journal = EventJournal()
    bundles = BundleManager(directory=str(tmp_path), cooldown_s=0.0)
    replicas = [
        plain_replica("r0"),
        # Wrong snapshot, same size, same generation tag: the silent
        # fleet failure no per-replica prober can see.
        plain_replica("r1", records=RECORDS_CORRUPT),
        plain_replica("r2"),
    ]
    probe = CrossReplicaProbe(replicas, RECORDS0, journal=journal)
    probe.add_failure_listener(bundles.on_probe_failure)
    try:
        result = probe.run_cycle()
        assert result["status"] == "mismatch"
        offenders = {d["replica"] for d in result["divergences"]}
        assert offenders == {"r1"}
        # The divergence names the generation and golden index.
        first = result["divergences"][0]
        assert first["generation"] == 0 and first["against"] == "oracle"
        kinds = [e["kind"] for e in journal.export()["events"]]
        assert "fleet.divergence" in kinds
        # The failure listener froze a debug bundle.
        export = bundles.export()
        assert export["fired"] == 1
        path = export["bundles"][-1]["path"]
        assert path and os.path.exists(path)
        assert probe.export()["divergences"] == 1
    finally:
        close_all(replicas)


def test_rotation_split_groups_by_generation_without_failing():
    replicas = [plain_replica(f"r{i}") for i in range(3)]
    # r2 already flipped to generation 1 (mid-rotation snapshot of the
    # fleet); the others still serve generation 0. Legitimate split:
    # grouped and reported, NOT a divergence.
    r2 = replicas[2]
    r2.snapshots.stage(delta_db(r2.leader.server.database, RECORDS1))
    r2.snapshots.flip()
    oracles = {0: RECORDS0, 1: RECORDS1}
    probe = CrossReplicaProbe(
        replicas,
        RECORDS0,
        records_provider=lambda gen: oracles.get(gen),
        journal=EventJournal(),
    )
    try:
        result = probe.run_cycle()
        assert result["status"] == "pass", result
        assert result["generations"] == {"0": ["r0", "r1"], "1": ["r2"]}
    finally:
        close_all(replicas)


def test_divergence_against_peer_when_no_oracle_known():
    replicas = [plain_replica("r0"), plain_replica("r1", RECORDS_CORRUPT)]
    # Both flip to databases the probe has NO oracle for — divergence
    # is still caught peer-against-peer within the generation group.
    for r, records in ((replicas[0], RECORDS1),
                       (replicas[1], RECORDS_CORRUPT)):
        r.snapshots.stage(delta_db(r.leader.server.database, records))
        r.snapshots.flip()
    probe = CrossReplicaProbe(replicas, RECORDS0, journal=EventJournal())
    try:
        result = probe.run_cycle()
        assert result["status"] == "mismatch"
        assert result["divergences"][0]["against"] == "r0"
    finally:
        close_all(replicas)


def test_probe_accepts_callable_replica_source():
    rs = ReplicaSet(journal=EventJournal())
    replicas = [rs.add(plain_replica(f"r{i}")) for i in range(2)]
    probe = CrossReplicaProbe(
        rs.healthy, RECORDS0, journal=EventJournal()
    )
    try:
        assert probe.run_cycle()["status"] == "pass"
        rs.kill("r1")
        result = probe.run_cycle()
        assert result["replicas"] == ["r0"]
    finally:
        close_all(replicas)


# ---------------------------------------------------------------------------
# /fleetz + replica identity on the admin surface (satellite 2)
# ---------------------------------------------------------------------------


def test_fleetz_endpoint_serves_registry_view():
    rs = ReplicaSet(journal=EventJournal())
    replicas = [rs.add(plain_replica(f"r{i}")) for i in range(2)]
    rs.shed("r1", reason="drill")
    try:
        with AdminServer(fleet=rs) as admin:
            base = f"http://127.0.0.1:{admin.port}"
            status, body = _get(f"{base}/fleetz")
            assert status == 200
            state = json.loads(body)
            assert state["counts"] == {
                "serving": 1, "staging": 0, "draining": 1, "dead": 0
            }
            assert state["replicas"]["r1"]["state"] == "draining"
            assert state["replicas"]["r0"]["serving_generation"] == 0
            assert state["sheds"] == 1
            # The 404 index knows the new route.
            status, body = _get(f"{base}/varz")
            assert status == 200
    finally:
        close_all(replicas)


def test_fleetz_404_without_fleet():
    with AdminServer() as admin:
        base = f"http://127.0.0.1:{admin.port}"
        with pytest.raises(urllib.error.HTTPError) as excinfo:
            _get(f"{base}/fleetz")
        assert excinfo.value.code == 404
        assert "no fleet attached" in excinfo.value.read().decode()


def test_varz_and_statusz_expose_replica_identity():
    replica = plain_replica("fleet-r7")
    try:
        with AdminServer(
            registry=replica.leader.metrics,
            snapshots=replica.snapshots,
            identity={"replica_id": "fleet-r7", "role": "leader"},
        ) as admin:
            base = f"http://127.0.0.1:{admin.port}"
            status, body = _get(f"{base}/varz")
            identity = json.loads(body)["identity"]
            assert identity == {
                "replica_id": "fleet-r7",
                "role": "leader",
                "serving_generation": 0,
            }
            # The generation is LIVE: a flip shows up on the next scrape.
            replica.snapshots.stage(
                delta_db(replica.leader.server.database, RECORDS1)
            )
            replica.snapshots.flip()
            _, body = _get(f"{base}/varz")
            assert json.loads(body)["identity"]["serving_generation"] == 1
            status, body = _get(f"{base}/statusz?format=json")
            assert json.loads(body)["identity"]["replica_id"] == "fleet-r7"
            status, html = _get(f"{base}/statusz")
            assert "fleet-r7" in html and "serving_generation" in html
    finally:
        replica.leader.close()


def test_fleet_bundle_source_registered():
    rs = ReplicaSet(journal=EventJournal())
    replica = rs.add(plain_replica("r0"))
    try:
        import tempfile

        with tempfile.TemporaryDirectory() as tmp:
            bundles = BundleManager(directory=tmp, cooldown_s=0.0)
            with AdminServer(fleet=rs, bundles=bundles):
                entry = bundles.trigger("test", {"why": "fleet source"})
            with open(
                os.path.join(entry["path"], "fleet.json"), "rb"
            ) as f:
                captured = json.load(f)
            assert "replicas" in captured and "r0" in captured["replicas"]
    finally:
        replica.leader.close()
