"""Differential tests for the fully-bitsliced AES kernel
(`ops/aes_bitslice.py`) against the numpy oracle and the byte-lane kernel
— the TPU analog of the reference's per-target SIMD-vs-scalar tests
(`dpf/internal/evaluate_prg_hwy_test.cc:49-136`)."""

import numpy as np
import jax.numpy as jnp
import pytest

from distributed_point_functions_tpu.ops import aes, aes_bitslice as bs


RK0 = aes.key_expansion(bytes(range(16)))
RK1 = aes.key_expansion(bytes(range(16, 32)))


def random_blocks(rng, n):
    return jnp.asarray(
        rng.integers(0, 1 << 32, (n, 4), dtype=np.uint64).astype(np.uint32)
    )


class TestTranspose:
    def test_bit_transpose_property(self):
        rng = np.random.default_rng(0)
        x = jnp.asarray(
            rng.integers(0, 1 << 32, (3, 32), dtype=np.uint64).astype(
                np.uint32
            )
        )
        t = np.asarray(bs._transpose32(x))
        xn = np.asarray(x)
        for b in range(32):
            for i in range(32):
                assert (
                    (t[..., b] >> i) & 1 == (xn[..., i] >> b) & 1
                ).all()

    def test_plane_roundtrip(self):
        rng = np.random.default_rng(1)
        blocks = random_blocks(rng, 96)
        rt = bs.planes_to_limbs(bs.limbs_to_planes(blocks))
        assert np.array_equal(np.asarray(rt), np.asarray(blocks))


class TestBitslicedAes:
    @pytest.mark.parametrize("n", [1, 31, 32, 33, 64, 257])
    def test_vs_numpy_oracle(self, n):
        rng = np.random.default_rng(n)
        blocks = random_blocks(rng, n)
        got = np.asarray(bs.aes_encrypt_bs(RK0, blocks))
        want = aes.bytes_to_limbs_np(
            aes.aes_encrypt_np(RK0, aes.limbs_to_bytes_np(np.asarray(blocks)))
        )
        assert np.array_equal(got, want)

    @pytest.mark.parametrize("n", [1, 32, 57])
    def test_select_vs_numpy_oracle(self, n):
        rng = np.random.default_rng(100 + n)
        blocks = random_blocks(rng, n)
        sel = jnp.asarray(
            rng.integers(0, 2, n, dtype=np.uint64).astype(np.uint32)
        )
        got = np.asarray(bs.aes_encrypt_select_bs(RK0, RK1, sel, blocks))
        w0 = aes.bytes_to_limbs_np(
            aes.aes_encrypt_np(RK0, aes.limbs_to_bytes_np(np.asarray(blocks)))
        )
        w1 = aes.bytes_to_limbs_np(
            aes.aes_encrypt_np(RK1, aes.limbs_to_bytes_np(np.asarray(blocks)))
        )
        want = np.where(np.asarray(sel)[:, None] != 0, w1, w0)
        assert np.array_equal(got, want)

    def test_vs_bytelane_kernel(self):
        rng = np.random.default_rng(7)
        blocks = random_blocks(rng, 128)
        got = np.asarray(bs.aes_encrypt_bs(RK0, blocks))
        want = np.asarray(aes.aes_encrypt(RK0, blocks))
        assert np.array_equal(got, want)

    def test_fips_197_c1(self):
        rk = aes.key_expansion(
            bytes.fromhex("000102030405060708090a0b0c0d0e0f")
        )
        pt = np.frombuffer(
            bytes.fromhex("00112233445566778899aabbccddeeff"), dtype=np.uint8
        )
        ct = np.asarray(
            bs.aes_encrypt_bs(rk, jnp.asarray(aes.bytes_to_limbs_np(pt[None])))
        )
        assert (
            aes.limbs_to_bytes_np(ct).tobytes().hex()
            == "69c4e0d86a7b0430d8cdb78070b4c55a"
        )

    def test_batch_shapes_preserved(self):
        rng = np.random.default_rng(9)
        blocks = random_blocks(rng, 60).reshape(3, 20, 4)
        out = bs.aes_encrypt_bs(RK0, blocks)
        assert out.shape == (3, 20, 4)
        flat = np.asarray(bs.aes_encrypt_bs(RK0, blocks.reshape(-1, 4)))
        assert np.array_equal(np.asarray(out).reshape(-1, 4), flat)


class TestMmoDispatch:
    def test_mmo_hash_matches_oracle(self):
        rng = np.random.default_rng(11)
        blocks = random_blocks(rng, 40)
        got = np.asarray(aes.mmo_hash(RK0, blocks))
        want = aes.mmo_hash_np(RK0, np.asarray(blocks))
        assert np.array_equal(got, want)

    def test_mmo_hash_select_matches_both_keys(self):
        rng = np.random.default_rng(12)
        blocks = random_blocks(rng, 40)
        sel = jnp.asarray(
            rng.integers(0, 2, 40, dtype=np.uint64).astype(np.uint32)
        )
        got = np.asarray(aes.mmo_hash_select(RK0, RK1, sel, blocks))
        w0 = aes.mmo_hash_np(RK0, np.asarray(blocks))
        w1 = aes.mmo_hash_np(RK1, np.asarray(blocks))
        want = np.where(np.asarray(sel)[:, None] != 0, w1, w0)
        assert np.array_equal(got, want)
