"""Share-correctness property tests for the DPF core.

Mirrors the reference's `IncrementalDpfTest` / `DpfEvaluationTest` strategy
(`dpf/distributed_point_function_test.cc:320-1196`): generate keys, evaluate
*both* shares, and check the group sum is beta at/under alpha and zero
elsewhere — over sweeps of domain sizes, value types, and evaluation modes.
"""

import numpy as np
import pytest

import jax

from distributed_point_functions_tpu import dpf as dpf_mod
from distributed_point_functions_tpu.value_types import (
    IntModNType,
    IntType,
    TupleType,
    XorType,
)

DPF = dpf_mod.DistributedPointFunction
Params = dpf_mod.DpfParameters


def both_full_expansions(d, k0, k1, level=None):
    ctx0 = d.create_evaluation_context(k0)
    ctx1 = d.create_evaluation_context(k1)
    if level is None:
        level = len(d.parameters) - 1
    v0 = d.evaluate_until(level, [], ctx0)
    v1 = d.evaluate_until(level, [], ctx1)
    return v0, v1


def check_share_sums(vt, v0, v1, alpha, beta, domain_size):
    v0 = jax.tree_util.tree_map(np.asarray, v0)
    v1 = jax.tree_util.tree_map(np.asarray, v1)
    for x in range(domain_size):
        s = vt.add(vt.to_python(v0, (x,)), vt.to_python(v1, (x,)))
        expected = beta if x == alpha else vt.zero()
        assert s == expected, f"x={x}: got {s}, want {expected}"


INT_TYPES = [IntType(8), IntType(16), IntType(32), IntType(64), IntType(128)]


@pytest.mark.parametrize("vt", INT_TYPES, ids=lambda t: f"u{t.bits}")
@pytest.mark.parametrize("lds", [0, 1, 2, 5, 7])
def test_single_level_full_expansion_integers(vt, lds):
    d = DPF.create(Params(lds, vt))
    domain = 1 << lds
    alpha = domain // 2 if domain > 1 else 0
    beta = (123456789123456789 % (1 << vt.bits)) | 1
    k0, k1 = d.generate_keys(alpha, beta)
    v0, v1 = both_full_expansions(d, k0, k1)
    check_share_sums(vt, v0, v1, alpha, beta, domain)


@pytest.mark.parametrize(
    "vt",
    [
        XorType(32),
        XorType(128),
        IntModNType(32, 4294967291),  # largest 32-bit prime
        IntModNType(8, 251),
        TupleType([IntType(32), IntType(32)]),
        TupleType([IntType(8), IntType(16)]),
        TupleType([IntType(64), IntType(64), IntType(64)]),
        TupleType([IntType(32), IntModNType(32, 4294967291)]),
        TupleType([IntModNType(16, 65521), IntModNType(16, 65521)]),
    ],
    ids=str,
)
def test_single_level_full_expansion_type_zoo(vt):
    lds = 4
    d = DPF.create(Params(lds, vt))
    alpha = 9

    def make_beta(t):
        if isinstance(t, TupleType):
            return tuple(make_beta(e) for e in t.elements)
        if isinstance(t, IntModNType):
            return 987654321 % t.modulus
        return 987654321 % (1 << t.bits)

    beta = make_beta(vt)
    k0, k1 = d.generate_keys(alpha, beta)
    v0, v1 = both_full_expansions(d, k0, k1)
    check_share_sums(vt, v0, v1, alpha, beta, 1 << lds)


@pytest.mark.parametrize("vt", [IntType(32), IntType(128), XorType(64)],
                         ids=lambda t: str(t))
def test_evaluate_at_matches_expansion(vt):
    lds = 6
    d = DPF.create(Params(lds, vt))
    alpha, beta = 37, 999
    k0, k1 = d.generate_keys(alpha, beta)
    points = [0, 1, 36, 37, 38, 63, 17]
    e0 = d.evaluate_at(k0, 0, points)
    e1 = d.evaluate_at(k1, 0, points)
    e0 = jax.tree_util.tree_map(np.asarray, e0)
    e1 = jax.tree_util.tree_map(np.asarray, e1)
    for i, x in enumerate(points):
        s = vt.add(vt.to_python(e0, (i,)), vt.to_python(e1, (i,)))
        expected = beta if x == alpha else 0
        assert s == expected, f"x={x}"


@pytest.mark.parametrize("level_step", [1, 2, 3])
def test_incremental_hierarchical_evaluation(level_step):
    """Multi-level keys; evaluate with prefixes descending the hierarchy."""
    vt = IntType(32)
    lds_list = [2, 4, 6, 8]
    params = [Params(l, vt) for l in lds_list]
    d = DPF.create_incremental(params)
    alpha = 0b10110101  # in the final domain
    betas = [10, 20, 30, 40]
    k0, k1 = d.generate_keys_incremental(alpha, betas)

    ctx0 = d.create_evaluation_context(k0)
    ctx1 = d.create_evaluation_context(k1)

    level = -1
    prefixes = []
    prev_lds = 0
    while level < len(params) - 1:
        level = min(level + level_step, len(params) - 1)
        v0 = d.evaluate_until(level, prefixes, ctx0)
        v1 = d.evaluate_until(level, prefixes, ctx1)
        v0 = jax.tree_util.tree_map(np.asarray, v0)
        v1 = jax.tree_util.tree_map(np.asarray, v1)
        lds = lds_list[level]
        # Determine which domain indices the outputs correspond to.
        if not prefixes:
            indices = list(range(1 << lds))
        else:
            opp = 1 << (lds - prev_lds)
            indices = []
            for p in prefixes:
                indices.extend(p * opp + j for j in range(opp))
        alpha_here = alpha >> (lds_list[-1] - lds)
        for i, x in enumerate(indices):
            s = vt.add(vt.to_python(v0, (i,)), vt.to_python(v1, (i,)))
            expected = betas[level] if x == alpha_here else 0
            assert s == expected, f"level={level} x={x}"
        # Next round: descend under alpha's prefix plus some cold prefixes.
        prev_lds = lds
        prefixes = sorted({alpha_here, 0, (1 << lds) - 1})


def test_keygen_validation_errors():
    d = DPF.create(Params(5, IntType(32)))
    with pytest.raises(ValueError):
        d.generate_keys(32, 1)  # alpha out of range
    with pytest.raises(ValueError):
        d.generate_keys(3, 1 << 32)  # beta out of range
    with pytest.raises(ValueError):
        DPF.create_incremental(
            [Params(5, IntType(32)), Params(5, IntType(32))]
        )  # non-ascending domains
    with pytest.raises(ValueError):
        DPF.create_incremental([])


def test_context_prefix_errors():
    d = DPF.create_incremental(
        [Params(2, IntType(32)), Params(4, IntType(32))]
    )
    k0, _ = d.generate_keys_incremental(5, [1, 2])
    ctx0 = d.create_evaluation_context(k0)
    with pytest.raises(ValueError):
        d.evaluate_until(0, [1], ctx0)  # prefixes must be empty on 1st call
    d.evaluate_until(0, [], ctx0)
    with pytest.raises(ValueError):
        d.evaluate_until(0, [1], ctx0)  # level must increase
    with pytest.raises(ValueError):
        d.evaluate_until(1, [], ctx0)  # prefixes required now


def test_packed_type_tree_shortening():
    # u8 packs 16 elements/block: domain 2^5 needs just one tree level.
    d = DPF.create(Params(5, IntType(8)))
    assert d._tree_levels_needed == 2  # 5 - 7 + 3 = 1 -> levels {0,1}
    alpha, beta = 21, 200
    k0, k1 = d.generate_keys(alpha, beta)
    v0, v1 = both_full_expansions(d, k0, k1)
    check_share_sums(IntType(8), v0, v1, alpha, beta, 32)


def test_evaluate_and_apply_multi_key():
    """Many independent keys, each at its own point, per-key correction words."""
    vt = IntType(32)
    d = DPF.create(Params(8, vt))
    cases = [(13, 100), (200, 5), (13, 7), (255, 9)]  # (alpha, beta)
    keys, points, expected = [], [], []
    for i, (alpha, beta) in enumerate(cases):
        k0, k1 = d.generate_keys(alpha, beta)
        pt = alpha if i % 2 == 0 else (alpha ^ 0x55)  # half hit, half miss
        keys += [k0, k1]
        points += [pt, pt]
        expected.append(beta if pt == alpha else 0)

    got = {}

    def op(values, hl):
        got[hl] = jax.tree_util.tree_map(np.asarray, values)

    d.evaluate_and_apply(keys, points, op)
    assert list(got) == [0]
    v = got[0]
    for i, want in enumerate(expected):
        s = vt.add(vt.to_python(v, (2 * i,)), vt.to_python(v, (2 * i + 1,)))
        assert s == want, f"pair {i}"


def test_evaluate_and_apply_rightshift():
    """rightshift=1: keys on alpha, evaluated at (x >> 1) — the DCF pattern.

    Uses the per-bit hierarchy a DCF builds (one level per domain bit), so
    the out-of-range path-bit guard (`evaluate_prg_hwy.cc:591-597` semantics)
    and the per-level block arithmetic are both exercised.
    """
    vt = IntType(32)
    lds = 5
    d = DPF.create_incremental([Params(i + 1, vt) for i in range(lds)])
    alpha = 0b1011  # 4 bits within the 5-bit final domain -> key on alpha
    betas = [(i + 1) * 11 for i in range(lds)]
    k0, k1 = d.generate_keys_incremental(alpha, betas)

    x = 0b10111  # x >> 1 == alpha
    got = {}

    def op(values, hl):
        got[hl] = jax.tree_util.tree_map(np.asarray, values)

    d.evaluate_and_apply(
        [k0, k1], [x, x], op, evaluation_points_rightshift=1
    )
    assert list(got) == list(range(lds))
    for hl in range(lds):
        v = got[hl]
        s = vt.add(vt.to_python(v, (0,)), vt.to_python(v, (1,)))
        # At hierarchy level hl (domain size 2^(hl+1)) the evaluated prefix
        # is (x >> 1) >> (lds - 1 - hl); it hits iff it equals alpha's prefix
        # alpha >> (lds - 1 - hl).
        hits = ((x >> 1) >> (lds - 1 - hl)) == (alpha >> (lds - 1 - hl))
        want = betas[hl] if hits else 0
        assert s == want, f"hl={hl}: got {s}, want {want}"


def test_evaluate_and_apply_early_stop():
    vt = IntType(32)
    d = DPF.create_incremental([Params(2, vt), Params(4, vt)])
    k0, k1 = d.generate_keys_incremental(5, [1, 2])
    seen = []

    def op(values, hl):
        seen.append(hl)
        return False  # stop after the first level

    d.evaluate_and_apply([k0, k1], [5, 5], op)
    assert seen == [0]


def test_128bit_domain_point_eval():
    d = DPF.create(Params(128, IntType(64)))
    alpha = (1 << 127) + 12345
    beta = 77
    k0, k1 = d.generate_keys(alpha, beta)
    points = [alpha, alpha - 1, alpha + 1, 0, (1 << 128) - 1]
    e0 = d.evaluate_at(k0, 0, points)
    e1 = d.evaluate_at(k1, 0, points)
    vt = IntType(64)
    e0 = jax.tree_util.tree_map(np.asarray, e0)
    e1 = jax.tree_util.tree_map(np.asarray, e1)
    for i, x in enumerate(points):
        s = vt.add(vt.to_python(e0, (i,)), vt.to_python(e1, (i,)))
        assert s == (beta if x == alpha else 0), f"x={x}"


def test_generate_keys_batch_share_correctness():
    """Batched keygen must produce valid shares: full-domain XOR of the two
    parties equals beta at alpha and 0 elsewhere (dense-PIR key shape)."""
    dpf = DPF.create(Params(6, XorType(128)))
    rng = np.random.default_rng(5)
    alphas = [int(a) for a in rng.integers(0, 64, 17)]  # odd batch size
    betas = [1 << int(b) for b in rng.integers(0, 128, 17)]
    keys0, keys1 = dpf.generate_keys_batch(alphas, betas)
    assert len(keys0) == len(keys1) == 17
    for a, b, k0, k1 in zip(alphas, betas, keys0, keys1):
        ctx0 = dpf.create_evaluation_context(k0)
        ctx1 = dpf.create_evaluation_context(k1)
        v0 = np.asarray(dpf.evaluate_next([], ctx0))
        v1 = np.asarray(dpf.evaluate_next([], ctx1))
        combined = v0 ^ v1
        for x in range(64):
            got = sum(int(combined[x, i]) << (32 * i) for i in range(4))
            want = b if x == a else 0
            assert got == want, f"alpha={a} x={x}"


def test_generate_keys_batch_falls_back_for_other_types():
    dpf = DPF.create(Params(4, IntType(32)))
    keys0, keys1 = dpf.generate_keys_batch([3, 5], [7, 9])
    out0 = np.asarray(dpf.evaluate_next([], dpf.create_evaluation_context(keys0[0])))
    out1 = np.asarray(dpf.evaluate_next([], dpf.create_evaluation_context(keys1[0])))
    combined = (out0.astype(np.uint64) + out1.astype(np.uint64)) % (1 << 32)
    assert combined[3].item() == 7 and combined.sum().item() == 7


def test_generate_keys_batch_validates_alphas():
    dpf = DPF.create(Params(6, XorType(128)))
    with pytest.raises(ValueError, match="out of domain"):
        dpf.generate_keys_batch([-1], [1])
    with pytest.raises(ValueError, match="out of domain"):
        dpf.generate_keys_batch([64], [1])
    with pytest.raises(TypeError, match="integer"):
        dpf.generate_keys_batch([1.5], [1])
