"""Tests for the derived composite-field S-box circuit
(`ops/aes_sbox_tower.py`)."""

import numpy as np

from distributed_point_functions_tpu.ops import aes, aes_sbox_tower as tw


def test_tower_params_irreducible():
    # z^2 + z + nu has no root in GF(4); w^2 + w + lam none in GF(16).
    assert all(tw._gf4_mul(z, z) ^ z ^ tw._NU for z in range(4))
    assert all(tw._gf16_mul(w, w, tw._NU) ^ w ^ tw._LAM for w in range(16))


def test_basis_change_is_field_isomorphism():
    rng = np.random.default_rng(0)
    M = tw._M_IN

    def phi(x):
        bits = np.array([(x >> i) & 1 for i in range(8)], dtype=np.uint8)
        out = (M @ bits) % 2
        return int(sum(int(b) << i for i, b in enumerate(out)))

    for _ in range(200):
        a, b = int(rng.integers(256)), int(rng.integers(256))
        # phi(a*b) == phi(a)*phi(b) in the tower
        lhs = phi(aes._gf_mul(a, b))
        rhs = tw._gf256_mul_tower(phi(a), phi(b), tw._NU, tw._LAM)
        assert lhs == rhs
        assert phi(a ^ b) == phi(a) ^ phi(b)


def test_plane_circuit_full_truth_table():
    xs = np.arange(256, dtype=np.uint32)
    planes = [(xs >> i) & 1 for i in range(8)]
    out = tw.sbox_planes_tower(planes, np.uint32(1))
    got = np.zeros(256, dtype=np.uint32)
    for i in range(8):
        got |= (out[i] & 1) << i
    assert np.array_equal(got, aes.SBOX[xs].astype(np.uint32))


def test_plane_circuit_packed_words():
    # Packed convention: 32 independent bytes per word position, `one` =
    # all-ones. Evaluate byte value k in bit lane k%32 of word k//32.
    rng = np.random.default_rng(1)
    vals = rng.integers(0, 256, 64).astype(np.uint32)
    planes = []
    for i in range(8):
        bits = (vals >> i) & 1
        planes.append(
            np.array(
                [
                    int((bits[w * 32 : (w + 1) * 32] << np.arange(32)).sum())
                    for w in range(2)
                ],
                dtype=np.uint32,
            )
        )
    out = tw.sbox_planes_tower(planes, np.uint32(0xFFFFFFFF))
    got = np.zeros(64, dtype=np.uint64)
    for i in range(8):
        for w in range(2):
            bits = (int(out[i][w]) >> np.arange(32)) & 1
            got[w * 32 : (w + 1) * 32] |= (bits << i).astype(np.uint64)
    assert np.array_equal(got, aes.SBOX[vals].astype(np.uint32))
