"""Federation merge rules, scoped telemetry identity, and fleet SLOs.

The contracts under test: merge_metrics sums counters, means
proportion gauges, bucket-merges same-layout histograms (and surfaces
layout conflicts in `skipped`, never silently averaging); the merged
timeline rebases each replica's monotonic clock by the median
wall-mono offset so causal order survives skewed clocks; scoped
EventJournals stamp replica identity and prefix coalesce keys so two
replicas' storms cannot merge; ScopedRegistry is a label-scoped view
whose reset/snapshot touch only its own slice; N samplers into scoped
TSDBs never bleed series across replicas and honor `max_series`; and
the `gauge_min` SLO kind breaches below the floor.
"""

import pytest

from distributed_point_functions_tpu.observability import federation
from distributed_point_functions_tpu.observability.events import EventJournal
from distributed_point_functions_tpu.observability.slo import (
    SloObjective,
    SloTracker,
)
from distributed_point_functions_tpu.observability.timeseries import (
    MetricsSampler,
    TimeSeriesStore,
)
from distributed_point_functions_tpu.serving.metrics import (
    MetricsRegistry,
    split_labeled_name,
)


class FakeClock:
    def __init__(self, t=0.0):
        self.t = float(t)

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt
        return self.t


# ---------------------------------------------------------------------------
# Merge rules
# ---------------------------------------------------------------------------


class TestMergeRules:
    def test_counters_sum_with_per_replica_attribution(self):
        merged = federation.merge_metrics(
            {
                "r0": {"counters": {"x.requests": 3}},
                "r1": {"counters": {"x.requests": 5}},
            }
        )
        row = merged["counters"]["x.requests"]
        assert row["value"] == 8
        assert row["rule"] == "sum"
        assert row["per_replica"] == {"r0": 3, "r1": 5}
        assert merged["rows"]["counters"] == {
            "x.requests{replica=r0}": 3,
            "x.requests{replica=r1}": 5,
        }

    def test_gauges_sum_by_default_mean_for_proportions(self):
        merged = federation.merge_metrics(
            {
                "r0": {
                    "gauges": {"q.depth": 4.0, "util.duty_cycle_pct": 90.0}
                },
                "r1": {
                    "gauges": {"q.depth": 6.0, "util.duty_cycle_pct": 70.0}
                },
            }
        )
        assert merged["gauges"]["q.depth"]["value"] == 10.0
        assert merged["gauges"]["q.depth"]["rule"] == "sum"
        duty = merged["gauges"]["util.duty_cycle_pct"]
        assert duty["value"] == pytest.approx(80.0)
        assert duty["rule"] == "mean"

    def test_mean_suffixes_cover_the_proportion_family(self):
        for name in ("a_pct", "b_ratio", "c_efficiency", "d_factor"):
            assert federation.gauge_rule(name) == "mean"
        assert federation.gauge_rule("queue_depth") == "sum"
        # Labels don't confuse the rule.
        assert federation.gauge_rule("a_pct{replica=r0}") == "mean"

    def test_missing_replica_rows_merge_what_exists(self):
        merged = federation.merge_metrics(
            {
                "r0": {"counters": {"only_r0": 2}},
                "r1": {},
                "r2": None,
            }
        )
        assert merged["replicas"] == ["r0", "r1", "r2"]
        assert merged["counters"]["only_r0"]["per_replica"] == {"r0": 2}

    def test_label_replica_preserves_sorted_pairs(self):
        assert (
            federation.label_replica("x.requests", "r1")
            == "x.requests{replica=r1}"
        )
        assert (
            federation.label_replica("x.requests{tenant=a}", "r1")
            == "x.requests{replica=r1,tenant=a}"
        )
        # Round-trips through the registry's own parser.
        base, labels = split_labeled_name(
            federation.label_replica("x{zz=1}", "r0")
        )
        assert base == "x" and labels == {"zz": "1", "replica": "r0"}


class TestHistogramMerge:
    @staticmethod
    def _hist(registry, name, values):
        h = registry.histogram(name, buckets=(1.0, 10.0, 100.0))
        for v in values:
            h.observe(v)
        return registry.export()["histograms"][name]

    def test_bucket_merge_sums_counts_and_estimates_percentiles(self):
        r0, r1 = MetricsRegistry(), MetricsRegistry()
        h0 = self._hist(r0, "lat_ms", [0.5] * 50)
        h1 = self._hist(r1, "lat_ms", [50.0] * 50)
        merged = federation.merge_histograms({"r0": h0, "r1": h1})
        assert merged is not None
        assert merged["count"] == 100
        assert merged["sum"] == pytest.approx(0.5 * 50 + 50.0 * 50)
        assert merged["max"] == pytest.approx(max(h0["max"], h1["max"]))
        assert merged["replicas"] == ["r0", "r1"]
        # p50 lands in the first bucket (<=1ms), p99 in the 10..100 one:
        # the merged view knows half the fleet was fast and the tail
        # slow, which neither replica's own percentiles could say.
        assert merged["p50"] <= 1.0
        assert 10.0 < merged["p99"] <= 100.0

    def test_layout_conflict_is_skipped_not_averaged(self):
        r0, r1 = MetricsRegistry(), MetricsRegistry()
        h0 = self._hist(r0, "lat_ms", [1.0])
        h1 = r1.histogram("lat_ms", buckets=(2.0, 20.0))
        h1.observe(1.0)
        merged = federation.merge_metrics(
            {
                "r0": {"histograms": {"lat_ms": h0}},
                "r1": {
                    "histograms": {
                        "lat_ms": r1.export()["histograms"]["lat_ms"]
                    }
                },
            }
        )
        assert merged["histograms"] == {}
        assert merged["skipped"] == ["lat_ms"]

    def test_percentile_interpolates_within_winning_bucket(self):
        # 100 observations all in the (0, 10] bucket: rank 50 sits
        # half-way up the bucket by linear interpolation.
        buckets = {"10.0": 100, "+inf": 0}
        p50 = federation.percentile_from_buckets(buckets, 100, 50)
        assert p50 == pytest.approx(5.0)

    def test_percentile_inf_clamps_to_largest_finite_bound(self):
        buckets = {"10.0": 1, "+inf": 99}
        p99 = federation.percentile_from_buckets(buckets, 100, 99)
        assert p99 == pytest.approx(10.0)

    def test_percentile_empty_is_none(self):
        assert federation.percentile_from_buckets({}, 0, 50) is None

    def test_merged_flat_is_registry_shaped(self):
        flat = federation.merged_flat(
            {
                "r0": {"counters": {"c": 1}, "gauges": {"g_pct": 10.0}},
                "r1": {"counters": {"c": 2}, "gauges": {"g_pct": 30.0}},
            }
        )
        assert flat["counters"] == {"c": 3}
        assert flat["gauges"] == {"g_pct": pytest.approx(20.0)}
        assert flat["histograms"] == {}


# ---------------------------------------------------------------------------
# Timeline federation: skewed clocks
# ---------------------------------------------------------------------------


class TestTimelineMerge:
    def test_rebase_offset_is_median_robust_to_stepped_wall(self):
        events = [
            {"t_wall": 1000.0, "t_mono": 10.0},
            {"t_wall": 1001.0, "t_mono": 11.0},
            # One stepped wall stamp (NTP jump) must not drag the offset.
            {"t_wall": 5000.0, "t_mono": 12.0},
        ]
        assert federation.rebase_offset(events) == pytest.approx(990.0)
        assert federation.rebase_offset([]) is None

    def test_skewed_monotonic_clocks_merge_causally(self):
        # Two replicas whose monotonic clocks share no epoch: replica a
        # booted long ago (t_mono ~ 5000), replica b just booted
        # (t_mono ~ 3). Wall clocks roughly agree. The causal story is
        # a1 -> b1 -> a2 -> b2; raw monotonic order would give a1, a2
        # first.
        journal_a = [
            {"kind": "step.a1", "t_wall": 100.0, "t_mono": 5000.0, "seq": 1},
            {"kind": "step.a2", "t_wall": 102.0, "t_mono": 5002.0, "seq": 2},
        ]
        journal_b = [
            {"kind": "step.b1", "t_wall": 101.0, "t_mono": 3.0, "seq": 1},
            {"kind": "step.b2", "t_wall": 103.0, "t_mono": 5.0, "seq": 2},
        ]
        merged = federation.merge_timelines({"a": journal_a, "b": journal_b})
        kinds = [e["kind"] for e in merged["events"]]
        assert kinds == ["step.a1", "step.b1", "step.a2", "step.b2"]
        # Every event carries the replica attribution and the rebased
        # stamp; the offsets are surfaced as the audit trail.
        assert [e["replica"] for e in merged["events"]] == [
            "a", "b", "a", "b",
        ]
        assert all(e["t_fleet"] is not None for e in merged["events"])
        assert merged["offsets"]["a"] == pytest.approx(-4900.0)
        assert merged["offsets"]["b"] == pytest.approx(98.0)

    def test_intra_replica_order_survives_rebase(self):
        # A replica's own monotonic order is preserved exactly even
        # when its wall clock stepped backwards mid-story.
        journal = [
            {"kind": "first", "t_wall": 200.0, "t_mono": 10.0, "seq": 1},
            {"kind": "second", "t_wall": 150.0, "t_mono": 11.0, "seq": 2},
            {"kind": "third", "t_wall": 201.0, "t_mono": 12.0, "seq": 3},
        ]
        merged = federation.merge_timelines({"a": journal})
        assert [e["kind"] for e in merged["events"]] == [
            "first", "second", "third",
        ]

    def test_kind_and_severity_filters_and_n(self):
        journal = [
            {
                "kind": "fleet.rotation", "t_wall": 1.0, "t_mono": 1.0,
                "seq": 1, "severity": "info",
            },
            {
                "kind": "fleet.rotation.abort", "t_wall": 2.0, "t_mono": 2.0,
                "seq": 2, "severity": "error",
            },
            {
                "kind": "other", "t_wall": 3.0, "t_mono": 3.0,
                "seq": 3, "severity": "warning",
            },
        ]
        by_kind = federation.merge_timelines({"a": journal}, kind="fleet.rotation")
        assert [e["kind"] for e in by_kind["events"]] == [
            "fleet.rotation", "fleet.rotation.abort",
        ]
        by_sev = federation.merge_timelines({"a": journal}, min_severity="warning")
        assert [e["kind"] for e in by_sev["events"]] == [
            "fleet.rotation.abort", "other",
        ]
        newest = federation.merge_timelines({"a": journal}, n=1)
        assert [e["kind"] for e in newest["events"]] == ["other"]

    def test_journal_export_shape_is_accepted(self):
        clock = FakeClock(5.0)
        journal = EventJournal(capacity=8, clock=clock, scope="r0")
        journal.emit("boot", "up")
        merged = federation.merge_timelines({"r0": journal.export()})
        assert merged["count"] == 1
        assert merged["events"][0]["replica"] == "r0"


# ---------------------------------------------------------------------------
# Scoped event identity (satellite 1)
# ---------------------------------------------------------------------------


class TestScopedJournal:
    def test_scope_stamps_replica_field(self):
        journal = EventJournal(capacity=8, scope="r1")
        event = journal.emit("breaker.transition", "open")
        assert event["replica"] == "r1"
        assert journal.scope == "r1"
        assert journal.export()["scope"] == "r1"

    def test_explicit_replica_field_wins_over_scope(self):
        journal = EventJournal(capacity=8, scope="r1")
        event = journal.emit("x", "y", replica="override")
        assert event["replica"] == "override"

    def test_unscoped_journal_unchanged(self):
        journal = EventJournal(capacity=8)
        event = journal.emit("x", "y")
        assert "replica" not in event
        assert journal.scope is None
        assert journal.export()["scope"] is None

    def test_coalesce_keys_do_not_collide_across_scopes(self):
        # Two replicas' scoped views emitting the same coalesce key into
        # the SAME underlying capacity regime must not merge each
        # other's storms; an unscoped emitter with the same key is a
        # third identity.
        clock = FakeClock(1.0)
        a = EventJournal(capacity=16, clock=clock, scope="ra")
        b = EventJournal(capacity=16, clock=clock, scope="rb")
        plain = EventJournal(capacity=16, clock=clock)
        for journal in (a, b, plain):
            journal.emit("shed", "x", coalesce_key="storm", coalesce_s=60.0)
            journal.emit("shed", "x", coalesce_key="storm", coalesce_s=60.0)
        # Each journal coalesced its own repeat...
        assert len(a.export()["events"]) == 1
        assert a.export()["events"][0]["repeats"] == 1
        # ...under a scope-prefixed key, so identities stay distinct.
        assert a._coalesce.keys() == {"ra:storm"}
        assert b._coalesce.keys() == {"rb:storm"}
        assert plain._coalesce.keys() == {"storm"}


# ---------------------------------------------------------------------------
# ScopedRegistry (satellite 2)
# ---------------------------------------------------------------------------


class TestScopedRegistry:
    def test_labels_merge_into_call_sites(self):
        parent = MetricsRegistry()
        scoped = parent.scoped({"replica": "r0"})
        scoped.counter("x.requests").inc(2)
        scoped.gauge("x.depth", labels={"tenant": "a"}).set(3.0)
        export = parent.export()
        assert export["counters"] == {"x.requests{replica=r0}": 2}
        assert export["gauges"] == {"x.depth{replica=r0,tenant=a}": 3.0}

    def test_export_sees_only_own_slice(self):
        parent = MetricsRegistry()
        r0 = parent.scoped({"replica": "r0"})
        r1 = parent.scoped({"replica": "r1"})
        r0.counter("c").inc(1)
        r1.counter("c").inc(5)
        parent.counter("unscoped").inc(9)
        assert r0.export()["counters"] == {"c{replica=r0}": 1}
        assert r1.snapshot()["counters"] == {"c{replica=r1}": 5}
        assert parent.export()["counters"]["unscoped"] == 9

    def test_scoped_reset_leaves_siblings_and_parent_alone(self):
        parent = MetricsRegistry()
        r0 = parent.scoped({"replica": "r0"})
        r1 = parent.scoped({"replica": "r1"})
        c0 = r0.counter("c")
        c0.inc(3)
        r1.counter("c").inc(5)
        parent.counter("unscoped").inc(7)
        r0.histogram("h").observe(1.0)
        r0.reset()
        export = parent.export()
        assert export["counters"]["c{replica=r0}"] == 0
        assert export["counters"]["c{replica=r1}"] == 5
        assert export["counters"]["unscoped"] == 7
        assert export["histograms"]["h{replica=r0}"]["count"] == 0
        # In-place: the live object the holder kept keeps working.
        c0.inc(1)
        assert parent.export()["counters"]["c{replica=r0}"] == 1

    def test_parent_reset_zeroes_everything_in_place(self):
        parent = MetricsRegistry()
        scoped = parent.scoped({"replica": "r0"})
        counter = scoped.counter("c")
        counter.inc(3)
        parent.gauge("g").set(2.0)
        parent.reset()
        export = parent.export()
        assert export["counters"] == {"c{replica=r0}": 0}
        assert export["gauges"] == {"g": 0.0}
        counter.inc(1)
        assert parent.export()["counters"]["c{replica=r0}"] == 1

    def test_nested_scopes_compose(self):
        parent = MetricsRegistry()
        inner = parent.scoped({"replica": "r0"}).scoped({"tenant": "t"})
        inner.counter("c").inc()
        assert parent.export()["counters"] == {
            "c{replica=r0,tenant=t}": 1
        }

    def test_empty_labels_rejected(self):
        with pytest.raises(ValueError):
            MetricsRegistry().scoped({})


# ---------------------------------------------------------------------------
# N trackers in one process: no series bleed, budget honored (satellite 4)
# ---------------------------------------------------------------------------


class TestPerReplicaSampling:
    def test_n_samplers_separate_stores_no_bleed(self):
        clock = FakeClock(10.0)
        registries = {}
        stores = {}
        samplers = {}
        for rid in ("r0", "r1", "r2"):
            registry = MetricsRegistry()
            registry.gauge("leader.q_depth").set(float(len(stores)))
            store = TimeSeriesStore(max_series=8, clock=clock)
            registries[rid] = registry
            stores[rid] = store
            samplers[rid] = MetricsSampler(
                store=store, registry=registry, clock=clock
            )
        for sampler in samplers.values():
            sampler.sample_once(clock())
        for i, rid in enumerate(("r0", "r1", "r2")):
            assert stores[rid].names() == ["leader.q_depth"]
            points = stores[rid].series("leader.q_depth", now=clock())
            assert points[-1][1] == float(i)

    def test_shared_store_with_replica_labels_no_bleed(self):
        clock = FakeClock(10.0)
        parent = MetricsRegistry()
        store = TimeSeriesStore(max_series=8, clock=clock)
        for i, rid in enumerate(("r0", "r1")):
            parent.scoped({"replica": rid}).gauge("leader.q_depth").set(
                float(i)
            )
        MetricsSampler(store=store, registry=parent, clock=clock).sample_once(
            clock()
        )
        assert store.names() == [
            "leader.q_depth{replica=r0}",
            "leader.q_depth{replica=r1}",
        ]
        assert store.series("leader.q_depth{replica=r0}", now=clock())[-1][
            1
        ] == 0.0
        assert store.series("leader.q_depth{replica=r1}", now=clock())[-1][
            1
        ] == 1.0

    def test_max_series_budget_under_per_replica_labels(self):
        clock = FakeClock(10.0)
        parent = MetricsRegistry()
        store = TimeSeriesStore(max_series=4, clock=clock)
        sampler = MetricsSampler(store=store, registry=parent, clock=clock)
        for i in range(10):
            parent.scoped({"replica": f"r{i}"}).gauge("leader.q").set(1.0)
        sampler.sample_once(clock())
        export = store.export(clock())
        assert export["series_count"] == 4
        assert export["dropped_series"] == 6
        assert store.occupancy() <= store.slot_budget()

    def test_extra_sources_bypass_prefix_filter(self):
        clock = FakeClock(10.0)
        store = TimeSeriesStore(max_series=8, clock=clock)
        sampler = MetricsSampler(
            store=store,
            clock=clock,
            extra_sources=[lambda: {"fleet.qps": 12.0}],
        )
        sampler.add_extra_source(lambda: {"fleet.routable_replicas": 3.0})
        written = sampler.sample_once(clock())
        assert written == 2
        assert store.names() == ["fleet.qps", "fleet.routable_replicas"]

    def test_extra_source_errors_counted_not_raised(self):
        clock = FakeClock(10.0)
        store = TimeSeriesStore(max_series=8, clock=clock)

        def broken():
            raise RuntimeError("scrape failed")

        sampler = MetricsSampler(
            store=store, clock=clock, extra_sources=[broken]
        )
        assert sampler.sample_once(clock()) == 0
        assert sampler.export()["errors"] == 1


# ---------------------------------------------------------------------------
# gauge_min SLO kind
# ---------------------------------------------------------------------------


class TestGaugeMinSlo:
    @staticmethod
    def _tracker(registry, clock):
        return SloTracker(
            [
                SloObjective(
                    name="routable_floor",
                    kind="gauge_min",
                    metric="fleet.routable_replicas",
                    threshold=2.0,
                    severity="hard",
                )
            ],
            registry,
            clock=clock,
        )

    def test_breach_below_floor_ok_at_floor(self):
        clock = FakeClock(1.0)
        registry = MetricsRegistry()
        tracker = self._tracker(registry, clock)
        registry.gauge("fleet.routable_replicas").set(3.0)
        (record,) = tracker.evaluate()
        assert record["state"] == "ok"
        registry.gauge("fleet.routable_replicas").set(1.0)
        (record,) = tracker.evaluate()
        assert record["state"] == "breach"
        assert tracker.breaches(evaluate=True)
        registry.gauge("fleet.routable_replicas").set(2.0)
        (record,) = tracker.evaluate()
        assert record["state"] == "ok"

    def test_absent_gauge_is_no_data_not_breach(self):
        clock = FakeClock(1.0)
        tracker = self._tracker(MetricsRegistry(), clock)
        (record,) = tracker.evaluate()
        assert record["state"] == "no_data"
        assert tracker.breaches(evaluate=True) == []
