"""Tests for the `testing/` sublibrary (mirrors the reference's
`pir/testing/` — mock database generators, request generator, selection
bits; `pir/testing/mock_pir_database.h`, `request_generator.h`,
`pir_selection_bits.h`)."""

import numpy as np
import pytest

from distributed_point_functions_tpu import testing as pt
from distributed_point_functions_tpu.pir import messages
from distributed_point_functions_tpu.pir.database import DenseDpfPirDatabase
from distributed_point_functions_tpu.pir.server import DenseDpfPirServer
from distributed_point_functions_tpu.prng import Aes128CtrSeededPrng, xor_bytes
from distributed_point_functions_tpu.testing import encrypt_decrypt


class TestGenerators:
    def test_counting_strings(self):
        elems = pt.generate_counting_strings(3, "Element ")
        assert elems == [b"Element 0", b"Element 1", b"Element 2"]

    def test_counting_strings_negative(self):
        with pytest.raises(ValueError):
            pt.generate_counting_strings(-1, "x")

    def test_random_strings_sizes(self):
        elems = pt.generate_random_strings([0, 1, 5, 16])
        assert [len(e) for e in elems] == [0, 1, 5, 16]

    def test_random_strings_equal_size(self):
        elems = pt.generate_random_strings_equal_size(10, 8)
        assert len(elems) == 10
        assert all(len(e) == 8 for e in elems)
        # Overwhelmingly likely all distinct.
        assert len(set(elems)) > 1

    def test_random_strings_variable_size(self):
        elems = pt.generate_random_strings_variable_size(100, 10, 3)
        assert len(elems) == 100
        assert all(7 <= len(e) <= 13 for e in elems)

    def test_variable_size_rejects_bad_diff(self):
        with pytest.raises(ValueError):
            pt.generate_random_strings_variable_size(1, 4, 5)

    def test_create_fake_database(self):
        elems = pt.generate_counting_strings(5, "r")
        db = pt.create_fake_database(DenseDpfPirDatabase, elems)
        assert db.size == 5
        assert db.record(2) == b"r2"

    def test_mock_client(self):
        mock = pt.MockPirClient()
        mock.on_create_request = lambda idx: ("req", "state")
        assert mock.create_request([1, 2]) == ("req", "state")
        assert mock.create_request_calls == [[1, 2]]
        mock.on_handle_response = lambda r, s: [b"rec"]
        assert mock.handle_response("resp", "state") == [b"rec"]
        with pytest.raises(NotImplementedError):
            pt.MockPirClient().create_request([0])

    def test_mock_database(self):
        mock = pt.MockPirDatabase()
        mock.records = [b"a", b"b"]
        mock.on_inner_product = lambda sel: [b"fake"]
        assert mock.size == 2
        assert mock.inner_product_with("sel") == [b"fake"]
        assert mock.inner_product_calls == ["sel"]


class TestSelectionBits:
    def test_pack_matches_manual(self):
        bits = [False] * 200
        bits[0] = bits[31] = bits[32] = bits[127] = bits[128] = bits[199] = True
        packed = pt.pack_selection_bits(bits)
        assert packed.shape == (2, 4)
        assert packed[0, 0] == (1 | (1 << 31))
        assert packed[0, 1] == 1
        assert packed[0, 3] == (1 << 31)
        assert packed[1, 0] == 1
        assert packed[1, 2] == (1 << (199 - 128 - 64))

    def test_pack_unpack_roundtrip(self):
        rng = np.random.default_rng(3)
        bits = rng.integers(0, 2, 500).astype(bool)
        packed = pt.pack_selection_bits(bits)
        assert np.array_equal(
            pt.unpack_selection_bits_np(packed, 500), bits.astype(np.uint8)
        )

    def test_random_packed_shape(self):
        packed = pt.generate_random_packed_selection_bits(
            300, np.random.default_rng(0)
        )
        assert packed.shape == (3, 4)

    def test_unpacked_oracle_vs_database(self):
        rng = np.random.default_rng(7)
        records = pt.generate_random_strings_equal_size(150, 12)
        bits = rng.integers(0, 2, 150).astype(bool)
        db = DenseDpfPirDatabase(records)
        packed = pt.pack_selection_bits(
            np.concatenate([bits, np.zeros(db.num_selection_bits - 150, bool)])
        )
        got = db.inner_product_with(packed[None])[0]
        want = pt.inner_product_with_unpacked(bits, records)
        assert got == want


class TestRequestGenerator:
    def test_plain_requests_answer_queries(self):
        records = pt.generate_counting_strings(70, "Record ")
        db = pt.create_fake_database(DenseDpfPirDatabase, records)
        server = DenseDpfPirServer.create_plain(db)
        gen = pt.RequestGenerator.create(len(records), "test ctx")
        indices = [0, 13, 69]
        plain0, plain1 = gen.create_plain_requests(indices)
        resp0 = server.handle_plain_request(
            messages.PirRequest(plain_request=plain0)
        )
        resp1 = server.handle_plain_request(
            messages.PirRequest(plain_request=plain1)
        )
        for i, idx in enumerate(indices):
            got = xor_bytes(
                resp0.dpf_pir_response.masked_response[i],
                resp1.dpf_pir_response.masked_response[i],
            )
            assert got.rstrip(b"\x00") == records[idx]

    def test_rejects_out_of_range(self):
        gen = pt.RequestGenerator.create(10, "ctx")
        with pytest.raises(ValueError):
            gen.create_plain_requests([10])
        with pytest.raises(ValueError):
            gen.create_plain_requests([-1])

    def test_leader_request_decrypts_to_helper_leg(self):
        records = pt.generate_counting_strings(40, "v")
        gen = pt.RequestGenerator.create(len(records), "ctx info")
        leader = gen.create_leader_request([5, 17])
        plaintext = encrypt_decrypt.decrypt(
            leader.encrypted_helper_request.encrypted_request, b"ctx info"
        )
        helper = messages.parse_helper_request(gen._dpf, plaintext)
        assert helper.one_time_pad_seed == gen.otp_seed
        assert len(helper.plain_request.dpf_keys) == 2
        # OTP seed regenerates the helper's mask stream.
        prng = Aes128CtrSeededPrng(gen.otp_seed)
        assert len(prng.get_random_bytes(16)) == 16


def test_profiling_hooks_are_safe_no_ops():
    """trace/annotate must not require an active profiler backend."""
    import tempfile

    from distributed_point_functions_tpu.utils import profiling

    with tempfile.TemporaryDirectory() as d:
        with profiling.trace(d):
            with profiling.annotate("region"):
                x = sum(range(10))
    assert x == 45


def test_backend_mode_string():
    from distributed_point_functions_tpu.utils.runtime import (
        get_backend_mode_string,
    )

    s = get_backend_mode_string()
    assert "backend=" in s and "devices=" in s
