"""Sparse KV serving under degraded own-share-only mode.

The contract: when the Leader's helper-leg breaker is open and
`allow_degraded=True`, a sparse lookup NEVER resolves to a wrong
value. A one-share response reconstructs to garbage buckets, the
cuckoo key-slot check rejects every one of them, and `resolve` returns
the typed-falsy `KeyNotFound` for present and absent keys alike —
absence of the second share degrades to absence of an answer, not to
a fabricated value. Recovery restores real values for the same keys.
"""

import time

import pytest

from distributed_point_functions_tpu.pir.cuckoo_database import (
    CuckooHashedDpfPirDatabase,
)
from distributed_point_functions_tpu.pir.sparse_client import KeyNotFound
from distributed_point_functions_tpu.pir.sparse_server import (
    CuckooHashingSparseDpfPirServer,
)
from distributed_point_functions_tpu.robustness import failpoints
from distributed_point_functions_tpu.serving import (
    InProcessTransport,
    ServingConfig,
    SparseHelperSession,
    SparseLeaderSession,
    make_sparse_client,
    sparse_lookup,
)
from distributed_point_functions_tpu.testing import encrypt_decrypt

SEED = b"0123456789abcdef"
NUM_KEYS = 40
RECORDS = {b"key_%02d" % i: b"val_%02d" % i for i in range(NUM_KEYS)}
VALUES = set(RECORDS.values())


def build_sparse(params=None):
    if params is None:
        params = CuckooHashingSparseDpfPirServer.generate_params(
            len(RECORDS), seed=SEED
        )
    builder = CuckooHashedDpfPirDatabase.Builder().set_params(params)
    for kv in RECORDS.items():
        builder.insert(kv)
    return params, builder.build()


def make_config(**overrides):
    base = dict(
        max_batch_size=8,
        max_wait_ms=2.0,
        helper_timeout_ms=None,
        helper_retries=0,
        helper_backoff_ms=1.0,
        helper_backoff_max_ms=2.0,
        allow_degraded=True,
        breaker_failure_threshold=1,
        breaker_reset_ms=30.0,
    )
    base.update(overrides)
    return ServingConfig(**base)


@pytest.fixture(autouse=True)
def clean_failpoints():
    reg = failpoints.default_failpoints()
    reg.clear()
    yield reg
    reg.clear()


def make_pair(**config_overrides):
    params, db_h = build_sparse()
    _, db_l = build_sparse(params)
    helper = SparseHelperSession(
        params, db_h, encrypt_decrypt.decrypt, make_config()
    )
    leader = SparseLeaderSession(
        params,
        db_l,
        InProcessTransport(helper.handle_wire),
        make_config(**config_overrides),
    )
    return leader, helper


QUERIES = [b"key_00", b"key_17", b"key_39", b"absent"]


def test_degraded_lookups_stay_typed_absent_never_wrong(clean_failpoints):
    # Helper leg dead for good: the first failure (threshold 1) opens
    # the breaker and every subsequent lookup serves own-share-only.
    clean_failpoints.arm("service.helper_leg", "error", times=None)
    leader, helper = make_pair()
    client = make_sparse_client(leader, encrypter=encrypt_decrypt.encrypt)
    with helper, leader:
        for _ in range(3):
            out = sparse_lookup(leader, client, QUERIES)
            for key, got in zip(QUERIES, out):
                # The load-bearing half: a one-share reconstruction
                # must never pass the key-slot check and surface as a
                # value — neither the right one nor anybody else's.
                assert isinstance(got, KeyNotFound), (key, got)
                assert got.key == key
                assert not got  # typed-falsy: callers branch safely
                assert got not in VALUES
        assert leader.degraded
        assert leader.breaker.state == "open"
        counters = leader.metrics.export()["counters"]
        assert counters["leader.degraded_responses"] >= 3


def test_degraded_recovery_restores_real_values(clean_failpoints):
    # Exactly one helper-leg failure: breaker opens, one degraded
    # answer, then the half-open probe succeeds and values come back.
    clean_failpoints.arm("service.helper_leg", "error", times=1)
    leader, helper = make_pair()
    client = make_sparse_client(leader, encrypter=encrypt_decrypt.encrypt)
    with helper, leader:
        out = sparse_lookup(leader, client, QUERIES)
        assert all(isinstance(v, KeyNotFound) for v in out)
        assert leader.degraded

        time.sleep(0.05)  # past breaker_reset_ms: next request probes
        out = sparse_lookup(leader, client, QUERIES)
        assert out[:3] == [b"val_00", b"val_17", b"val_39"]
        assert isinstance(out[3], KeyNotFound) and out[3].key == b"absent"
        assert not leader.degraded
        assert leader.breaker.state == "closed"
        assert leader.metrics.export()["counters"]["leader.degraded_exits"] == 1


def test_degraded_disallowed_raises_instead_of_guessing(clean_failpoints):
    # Without the opt-in, a dead helper is an error, not a degraded
    # answer — the session must never silently serve one share.
    clean_failpoints.arm("service.helper_leg", "error", times=None)
    leader, helper = make_pair(allow_degraded=False)
    client = make_sparse_client(leader, encrypter=encrypt_decrypt.encrypt)
    with helper, leader:
        with pytest.raises(Exception):
            sparse_lookup(leader, client, [b"key_00"])
