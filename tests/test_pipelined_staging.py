"""Pipelined double-buffered staging and incremental delta prestage.

The hot-path pipelining work (double-buffered H2D chunk staging, delta
prestage) must never change a single staged bit: the pipelined path
(async per-piece uploads, device-side assembly, ONE final counted
sync) and the delta path (scatter of only the touched rows/chunks into
the base generation's resident staging) are pure layout/transport
optimizations. These tests pin that down:

* bit-identity of pipelined vs upfront staging on every planner tier —
  materialized (row-major `db_words`), streaming row-major chunks, and
  chunked bit-major (the pallas2 scan layout) — plus the forced
  8-device mesh staging against the single-device oracle;
* the ledger signature of pipelining: strictly fewer syncs than h2d
  copies, nonzero `overlapped_ms` (vs the upfront path's one copy /
  one sync);
* delta prestage equivalence: a `Builder.build_from` generation whose
  `prestage()` scatters only updated rows/chunks produces buffers
  byte-identical to a from-scratch full staging of the same records,
  at a fraction of the staged bytes.
"""

import numpy as np
import pytest

from distributed_point_functions_tpu.observability.device import (
    DeviceTelemetry,
    default_telemetry,
    set_default_telemetry,
)
from distributed_point_functions_tpu.parallel import make_mesh
from distributed_point_functions_tpu.pir import DenseDpfPirDatabase
from distributed_point_functions_tpu.pir.database import (
    pipelined_staging_enabled,
)

NUM_RECORDS = 1024  # 8 selection blocks: enough for chunked plans
RECORD_BYTES = 8
RNG = np.random.default_rng(20260806)
RECORDS = [
    bytes(RNG.integers(0, 256, RECORD_BYTES, dtype=np.uint8))
    for _ in range(NUM_RECORDS)
]


def build_db(records=RECORDS):
    builder = DenseDpfPirDatabase.Builder()
    for r in records:
        builder.insert(r)
    return builder.build()


@pytest.fixture
def telemetry():
    prev = default_telemetry()
    fresh = set_default_telemetry(DeviceTelemetry())
    try:
        yield fresh
    finally:
        set_default_telemetry(prev)


@pytest.fixture
def pipelined(monkeypatch):
    monkeypatch.setenv("DPF_TPU_PIPELINED_STAGING", "1")
    assert pipelined_staging_enabled()


@pytest.fixture
def upfront(monkeypatch):
    monkeypatch.setenv("DPF_TPU_PIPELINED_STAGING", "0")
    assert not pipelined_staging_enabled()


def _staged_with_env(monkeypatch, value, stage_fn):
    """Stage a fresh database with the pipelining env set to `value`
    and return the staged buffer as a host array."""
    monkeypatch.setenv("DPF_TPU_PIPELINED_STAGING", value)
    return np.asarray(stage_fn(build_db()))


# ---------------------------------------------------------------------------
# Bit-identity: pipelined == upfront on every tier
# ---------------------------------------------------------------------------


def test_rowmajor_pipelined_matches_upfront(monkeypatch, telemetry):
    ref = _staged_with_env(monkeypatch, "0", lambda db: db.db_words)
    pipe = _staged_with_env(monkeypatch, "1", lambda db: db.db_words)
    np.testing.assert_array_equal(ref, pipe)


@pytest.mark.parametrize("bitmajor", [False, True])
@pytest.mark.parametrize("cut_levels", [1, 2])
def test_streaming_pipelined_matches_upfront(
    monkeypatch, telemetry, bitmajor, cut_levels
):
    """Streaming row-major (streaming tier) and per-chunk bit-major
    (chunked/pallas2 tier) stagings are byte-identical either way."""

    def stage(db):
        return db.streaming_chunks(cut_levels=cut_levels, bitmajor=bitmajor)

    ref = _staged_with_env(monkeypatch, "0", stage)
    pipe = _staged_with_env(monkeypatch, "1", stage)
    np.testing.assert_array_equal(ref, pipe)


@pytest.mark.parametrize("bitmajor", [False, True])
def test_mesh_staging_matches_single_device(
    monkeypatch, telemetry, pipelined, bitmajor
):
    """The forced-8-device mesh staging assembles the same global bytes
    as the single-device staging of the same plan."""
    mesh = make_mesh(8, axis_name="shard")
    single = np.asarray(
        build_db().streaming_chunks(cut_levels=3, bitmajor=bitmajor)
    )
    meshed = np.asarray(
        build_db().streaming_chunks(
            cut_levels=3, bitmajor=bitmajor, mesh=mesh
        )
    )
    np.testing.assert_array_equal(single, meshed)


# ---------------------------------------------------------------------------
# Ledger signature: many async copies, ONE sync, nonzero overlap
# ---------------------------------------------------------------------------


def test_pipelined_staging_syncs_fewer_than_copies(telemetry, pipelined):
    ledger = telemetry.transfers
    db = build_db()
    ledger.reset()
    _ = db.db_words
    assert ledger.copies("db_staging") >= 2  # per-slab async uploads
    assert ledger.syncs("db_staging") == 1  # ... drained by ONE sync
    assert ledger.syncs("db_staging") < ledger.copies("db_staging")
    assert ledger.overlapped_ms("db_staging") > 0.0

    db2 = build_db()
    ledger.reset()
    _ = db2.streaming_chunks(cut_levels=2, bitmajor=True)
    assert ledger.syncs("db_staging") < ledger.copies("db_staging")
    assert ledger.overlapped_ms("db_staging") > 0.0


def test_upfront_staging_is_one_copy_one_sync(telemetry, upfront):
    ledger = telemetry.transfers
    db = build_db()
    ledger.reset()
    _ = db.db_words
    assert ledger.copies("db_staging") == 1
    assert ledger.syncs("db_staging") == 1
    assert ledger.overlapped_ms("db_staging") == 0.0


# ---------------------------------------------------------------------------
# Delta prestage: scatter only the touched rows/chunks, bit-identical
# ---------------------------------------------------------------------------


def delta_records(updates):
    records = list(RECORDS)
    for i in updates:
        records[i] = bytes(b ^ 0x5A for b in records[i])
    return records


def delta_build(base, updates):
    builder = DenseDpfPirDatabase.Builder()
    records = delta_records(updates)
    for i in updates:
        builder.update(i, records[i])
    return builder.build_from(base)


UPDATES = [3, 129, 700]


def test_delta_prestage_rowmajor_equivalence(telemetry, pipelined):
    base = build_db()
    _ = base.db_words  # base generation resident, as when serving
    db1 = delta_build(base, UPDATES)
    ledger = telemetry.transfers
    before = ledger.bytes_h2d("db_staging")
    staged = db1.prestage()
    assert staged == ledger.bytes_h2d("db_staging") - before
    # Only the touched rows (plus the index vector) crossed the bus.
    assert 0 < staged < int(db1._host_words.nbytes)
    stats = db1.last_prestage_stats
    assert stats["mode"] == "delta"
    assert stats["bytes_saved"] > 0
    assert stats["bytes_staged"] + stats["bytes_saved"] == (
        stats["bytes_full_image"]
    )
    np.testing.assert_array_equal(
        np.asarray(db1.db_words),
        np.asarray(build_db(delta_records(UPDATES)).db_words),
    )


@pytest.mark.parametrize("bitmajor", [False, True])
def test_delta_prestage_streaming_equivalence(
    telemetry, pipelined, bitmajor
):
    """When the base generation serves a streaming/chunked staging, a
    delta build's prestage() re-derives that layout by scattering only
    the touched chunks — byte-identical to staging the new records
    from scratch."""
    base = build_db()
    _ = base.streaming_chunks(cut_levels=2, bitmajor=bitmajor)
    db1 = delta_build(base, UPDATES)
    ledger = telemetry.transfers
    before = ledger.bytes_h2d("db_staging")
    staged = db1.prestage()
    assert 0 < staged
    stats = db1.last_prestage_stats
    assert stats["mode"] == "delta"
    assert stats["bytes_saved"] > 0
    # The staged streaming layout is already resident (no new bytes)
    # and matches the full-image oracle bit for bit.
    mid = ledger.bytes_h2d("db_staging")
    got = db1.streaming_chunks(cut_levels=2, bitmajor=bitmajor)
    assert ledger.bytes_h2d("db_staging") == mid  # cache hit
    np.testing.assert_array_equal(
        np.asarray(got),
        np.asarray(
            build_db(delta_records(UPDATES)).streaming_chunks(
                cut_levels=2, bitmajor=bitmajor
            )
        ),
    )


def test_delta_touching_everything_stages_in_full(telemetry, pipelined):
    """A delta that rewrites (nearly) every row would cost full-image
    bytes plus scatter overhead — the delta path steps aside and the
    staging goes up in full, still bit-identical."""
    base = build_db()
    _ = base.db_words
    all_rows = list(range(NUM_RECORDS))
    db1 = delta_build(base, all_rows)
    staged = db1.prestage()
    assert staged == int(db1._host_words.nbytes)
    assert db1.last_prestage_stats["mode"] == "full"
    np.testing.assert_array_equal(
        np.asarray(db1.db_words),
        np.asarray(build_db(delta_records(all_rows)).db_words),
    )


def test_empty_delta_shares_the_base_buffer(telemetry, pipelined):
    """`build_from` with zero updates shares the base's immutable
    device buffer outright: nothing crosses the bus."""
    base = build_db()
    base_words = base.db_words
    db1 = delta_build(base, [])
    ledger = telemetry.transfers
    before = ledger.bytes_h2d("db_staging")
    staged = db1.prestage()
    assert staged == 0
    assert ledger.bytes_h2d("db_staging") == before
    assert db1.db_words is base_words


def test_released_base_falls_back_to_full(telemetry, pipelined):
    """The delta base is held by weakref: once the previous generation
    is garbage (rotation chains must not pin every ancestor's host
    image), prestage degrades to a plain full staging."""
    base = build_db()
    _ = base.db_words
    db1 = delta_build(base, UPDATES)
    del base
    import gc

    gc.collect()
    staged = db1.prestage()
    assert staged == int(db1._host_words.nbytes)
    assert db1.last_prestage_stats["mode"] == "full"
    np.testing.assert_array_equal(
        np.asarray(db1.db_words),
        np.asarray(build_db(delta_records(UPDATES)).db_words),
    )
