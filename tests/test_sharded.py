"""Multi-device sharding tests on the virtual 8-device CPU mesh.

Covers `parallel/sharded.py` at non-toy shapes — the shapes the driver's
`dryrun_multichip` does not reach: >= 64 queries, >= 2^13 records,
multi-word records, walk_levels > 0 — plus the divisibility contracts.
The sharding checker (`check_vma`) runs at its default (on): the XOR
combine happens outside the manual region, where XLA places the
collective.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_point_functions_tpu.ops.inner_product import (
    pack_selection_bits_np,
    xor_inner_product_np,
)
from distributed_point_functions_tpu.parallel.sharded import (
    make_mesh,
    shard_database,
    sharded_dense_pir_step,
    sharded_inner_product,
)
from distributed_point_functions_tpu.pir.client import DenseDpfPirClient
from distributed_point_functions_tpu.pir.dense_eval import stage_keys

RNG = np.random.default_rng(23)


def require_mesh(n=8):
    if len(jax.devices()) < n:
        pytest.skip(f"needs {n} devices")
    return make_mesh(n)


def test_sharded_inner_product_matches_oracle():
    mesh = require_mesh()
    num_records, num_words, nq = 1 << 13, 16, 64  # 8192 records, 64B each
    db = RNG.integers(0, 1 << 32, (num_records, num_words), dtype=np.uint32)
    bits = RNG.integers(0, 2, (nq, num_records), dtype=np.uint32)
    sel = jnp.asarray(pack_selection_bits_np(bits))
    fn = sharded_inner_product(mesh)
    db_sharded = shard_database(mesh, jnp.asarray(db))
    got = np.asarray(fn(db_sharded, sel))
    np.testing.assert_array_equal(got, xor_inner_product_np(db, np.asarray(sel)))


def test_sharded_dense_pir_step_end_to_end():
    """Full sharded step: 64 queries x 2^14 records x 64B, walk_levels > 0.

    The database is 2^14 records but the client domain is 2^17, so the
    covering subtree leaves walk_levels = 17 - ceil(log2(2^14/128)) > 0.
    """
    mesh = require_mesh()
    num_records = 1 << 14
    domain = 1 << 17  # forces a non-trivial walk phase
    num_words = 16
    nq = 64
    num_blocks = num_records // 128

    client = DenseDpfPirClient.create(domain, lambda pt, ci: pt)
    indices = [int(i) for i in RNG.integers(0, num_records, nq)]
    keys0, keys1 = client._generate_key_pairs(indices)

    total_levels = client._dpf._tree_levels_needed - 1
    expand_levels = min((num_blocks - 1).bit_length(), total_levels)
    walk_levels = total_levels - expand_levels
    assert walk_levels > 0

    db = RNG.integers(0, 1 << 32, (num_records, num_words), dtype=np.uint32)
    step = sharded_dense_pir_step(
        mesh,
        walk_levels=walk_levels,
        expand_levels=expand_levels,
        num_blocks=num_blocks,
    )
    db_sharded = shard_database(mesh, jnp.asarray(db))

    out0 = np.asarray(step(*stage_keys(keys0), db_sharded))
    out1 = np.asarray(step(*stage_keys(keys1), db_sharded))
    assert out0.shape == (nq, num_words)

    # Share correctness: XOR of the two parties' outputs must equal the
    # queried record (alpha = idx//128, beta = 1 << idx%128 selection).
    combined = out0 ^ out1
    for q, idx in enumerate(indices):
        np.testing.assert_array_equal(
            combined[q], db[idx], err_msg=f"query {q} (index {idx})"
        )


def test_sharded_step_matches_single_device_path():
    """The sharded pipeline must be bit-identical to the single-device
    fused pipeline for one party's keys (not just after combining)."""
    from distributed_point_functions_tpu.pir.dense_eval import (
        evaluate_selection_blocks,
    )
    from distributed_point_functions_tpu.ops.inner_product import (
        xor_inner_product,
    )

    mesh = require_mesh()
    num_records, num_words, nq = 1 << 13, 8, 16
    num_blocks = num_records // 128
    client = DenseDpfPirClient.create(num_records, lambda pt, ci: pt)
    indices = [int(i) for i in RNG.integers(0, num_records, nq)]
    keys0, _ = client._generate_key_pairs(indices)
    staged = stage_keys(keys0)
    total_levels = client._dpf._tree_levels_needed - 1
    expand_levels = min((num_blocks - 1).bit_length(), total_levels)
    walk_levels = total_levels - expand_levels

    db = RNG.integers(0, 1 << 32, (num_records, num_words), dtype=np.uint32)
    step = sharded_dense_pir_step(
        mesh,
        walk_levels=walk_levels,
        expand_levels=expand_levels,
        num_blocks=num_blocks,
    )
    got = np.asarray(step(*staged, shard_database(mesh, jnp.asarray(db))))

    sel = evaluate_selection_blocks(
        *staged,
        walk_levels=walk_levels,
        expand_levels=expand_levels,
        num_blocks=num_blocks,
    )
    want = np.asarray(xor_inner_product(jnp.asarray(db), sel))
    np.testing.assert_array_equal(got, want)


def test_sharded_inner_product_rejects_bad_record_count():
    mesh = require_mesh()
    fn = sharded_inner_product(mesh)
    # 8 devices * 128 = 1024 required; 512 records is not divisible.
    db = jnp.zeros((512, 4), jnp.uint32)
    sel = jnp.zeros((4, 4, 4), jnp.uint32)
    with pytest.raises(ValueError, match="divisible by 1024"):
        fn(shard_database(mesh, db), sel)


def test_sharded_step_rejects_bad_query_count():
    mesh = require_mesh()
    step = sharded_dense_pir_step(
        mesh, walk_levels=0, expand_levels=3, num_blocks=8
    )
    nq = 12  # not divisible by 8 devices
    with pytest.raises(ValueError, match="num_queries"):
        step(
            jnp.zeros((nq, 4), jnp.uint32),
            jnp.zeros((nq,), jnp.uint32),
            jnp.zeros((3, nq, 4), jnp.uint32),
            jnp.zeros((3, nq), jnp.uint32),
            jnp.zeros((3, nq), jnp.uint32),
            jnp.zeros((nq, 4), jnp.uint32),
            jnp.zeros((1024, 4), jnp.uint32),
        )


def test_mesh_server_matches_single_device_server():
    """DenseDpfPirServer with a mesh must answer byte-identically to the
    single-device server, including non-divisible query counts."""
    from distributed_point_functions_tpu.pir.database import (
        DenseDpfPirDatabase,
    )
    from distributed_point_functions_tpu.pir.server import DenseDpfPirServer
    from distributed_point_functions_tpu.pir import messages

    mesh = require_mesh()
    num_records = 2000  # pads to 2048 = 128*8*2
    records = [RNG.bytes(24) for _ in range(num_records)]
    plain = DenseDpfPirServer.create_plain(DenseDpfPirDatabase(records))
    sharded = DenseDpfPirServer.create_plain(
        DenseDpfPirDatabase(records), mesh=mesh
    )

    client = DenseDpfPirClient.create(num_records, lambda pt, ci: pt)
    indices = [3, 1999, 777]  # 3 queries: not divisible by 8 devices
    keys0, keys1 = client._generate_key_pairs(indices)
    for keys in (keys0, keys1):
        req = messages.PirRequest(
            plain_request=messages.PlainRequest(dpf_keys=list(keys))
        )
        a = plain.handle_request(req).dpf_pir_response.masked_response
        b = sharded.handle_request(req).dpf_pir_response.masked_response
        assert a == b

    # And the two parties' sharded responses combine to the records.
    from distributed_point_functions_tpu.prng import xor_bytes

    r0 = sharded.handle_request(
        messages.PirRequest(
            plain_request=messages.PlainRequest(dpf_keys=list(keys0))
        )
    ).dpf_pir_response.masked_response
    r1 = sharded.handle_request(
        messages.PirRequest(
            plain_request=messages.PlainRequest(dpf_keys=list(keys1))
        )
    ).dpf_pir_response.masked_response
    for q, idx in enumerate(indices):
        assert xor_bytes(r0[q], r1[q]) == records[idx]


def test_mesh_sparse_server_matches_single_device_server():
    """CuckooHashingSparseDpfPirServer with a mesh: one expansion feeds
    both bucket databases (`sharded_dense_pir_step_multi`), and responses
    are byte-identical to the single-device server."""
    from distributed_point_functions_tpu.pir.cuckoo_database import (
        CuckooHashedDpfPirDatabase,
    )
    from distributed_point_functions_tpu.pir.sparse_client import (
        CuckooHashingSparseDpfPirClient,
    )
    from distributed_point_functions_tpu.pir.sparse_server import (
        CuckooHashingSparseDpfPirServer,
    )
    from distributed_point_functions_tpu.pir import messages

    mesh = require_mesh()
    num_keys = 700
    pairs = [
        (b"key-%04d" % i, b"value-%04d" % i) for i in range(num_keys)
    ]
    params = CuckooHashingSparseDpfPirServer.generate_params(
        num_keys, seed=b"0123456789abcdef"
    )
    builder = CuckooHashedDpfPirDatabase.Builder().set_params(params)
    for kv in pairs:
        builder.insert(kv)
    db = builder.build()

    plain = CuckooHashingSparseDpfPirServer.create_plain(params, db)
    sharded = CuckooHashingSparseDpfPirServer.create_plain(
        params, db, mesh=mesh
    )

    client = CuckooHashingSparseDpfPirClient.create_from_public_params(
        plain.get_public_params().SerializeToString(), lambda pt, ci: pt
    )
    queries = [b"key-0003", b"key-0699", b"no-such-key"]
    req0, req1 = client.create_plain_requests(queries)

    a = plain.handle_request(req0).dpf_pir_response.masked_response
    b = sharded.handle_request(req0).dpf_pir_response.masked_response
    assert a == b

    # Combining both parties' sharded responses answers the queries.
    from distributed_point_functions_tpu.pir.sparse_client import (
        _is_prefix_padded_with_zeros,
    )
    from distributed_point_functions_tpu.prng import xor_bytes

    r0 = sharded.handle_request(req0).dpf_pir_response.masked_response
    r1 = sharded.handle_request(req1).dpf_pir_response.masked_response
    combined = [xor_bytes(x, y) for x, y in zip(r0, r1)]
    expected = {queries[0]: b"value-0003", queries[1]: b"value-0699"}
    num_hashes = params.num_hash_functions
    for i, q in enumerate(queries):
        found = None
        for j in range(num_hashes):
            idx = 2 * (num_hashes * i + j)
            if found is None and _is_prefix_padded_with_zeros(
                combined[idx], q
            ):
                found = combined[idx + 1]
        if q in expected:
            assert found is not None
            assert found[: len(expected[q])] == expected[q]
        else:
            assert found is None or all(b == 0 for b in found)


def test_mesh_server_small_database_beyond_tree_capacity():
    """A small database mesh-padded past the DPF tree's leaf capacity
    (300 records -> 4-block tree, padded to 8 blocks on 8 devices) must be
    served correctly: selection blocks beyond 2^expand_levels are
    zero-padded and can only meet guaranteed-zero padding rows."""
    from distributed_point_functions_tpu.pir.database import (
        DenseDpfPirDatabase,
    )
    from distributed_point_functions_tpu.pir.server import DenseDpfPirServer
    from distributed_point_functions_tpu.pir import messages
    from distributed_point_functions_tpu.prng import xor_bytes

    mesh = require_mesh()
    num_records = 300  # tree capacity 4 blocks < 8 padded blocks
    records = [RNG.bytes(16) for _ in range(num_records)]
    plain = DenseDpfPirServer.create_plain(DenseDpfPirDatabase(records))
    sharded = DenseDpfPirServer.create_plain(
        DenseDpfPirDatabase(records), mesh=mesh
    )
    client = DenseDpfPirClient.create(num_records, lambda pt, ci: pt)
    indices = [0, 150, 299]
    keys0, keys1 = client._generate_key_pairs(indices)
    reqs = [
        messages.PirRequest(
            plain_request=messages.PlainRequest(dpf_keys=list(k))
        )
        for k in (keys0, keys1)
    ]
    for req in reqs:
        a = plain.handle_request(req).dpf_pir_response.masked_response
        b = sharded.handle_request(req).dpf_pir_response.masked_response
        assert a == b
    r0 = sharded.handle_request(reqs[0]).dpf_pir_response.masked_response
    r1 = sharded.handle_request(reqs[1]).dpf_pir_response.masked_response
    for q, idx in enumerate(indices):
        assert xor_bytes(r0[q], r1[q]) == records[idx]


def test_sharded_step_planes_matches_limb(monkeypatch):
    """The sharded step with the plane-resident expansion forced must be
    bit-identical to the limb expansion (both through shard_map).

    nq = 256 so each of the 8 shards sees 32 keys — enough that the
    planes path's small-batch padding guard does not reroute to limb
    (which would make this comparison vacuous)."""
    num_records, num_words, nq = 1 << 13, 8, 256
    num_blocks = num_records // 128
    client = DenseDpfPirClient.create(num_records, lambda pt, ci: pt)
    indices = [int(i) for i in RNG.integers(0, num_records, nq)]
    keys0, _ = client._generate_key_pairs(indices)
    staged = stage_keys(keys0)
    total_levels = client._dpf._tree_levels_needed - 1
    expand_levels = min((num_blocks - 1).bit_length(), total_levels)
    walk_levels = total_levels - expand_levels
    db = RNG.integers(0, 1 << 32, (num_records, num_words), dtype=np.uint32)

    outs = {}
    for mode in ("limb", "planes"):
        monkeypatch.setenv("DPF_TPU_EXPANSION", mode)
        mesh = require_mesh()
        step = sharded_dense_pir_step(
            mesh,
            walk_levels=walk_levels,
            expand_levels=expand_levels,
            num_blocks=num_blocks,
        )
        outs[mode] = np.asarray(
            step(*staged, shard_database(mesh, jnp.asarray(db)))
        )
    np.testing.assert_array_equal(outs["limb"], outs["planes"])


def test_sharded_mxu_step_matches_xor_step():
    """The MXU sharded step (bit-major shards + v2 Pallas kernel in
    interpret mode) is bit-identical to the mask-and-XOR sharded step."""
    from distributed_point_functions_tpu.parallel.sharded import (
        sharded_dense_pir_step_mxu,
        stage_sharded_bitmajor,
    )

    mesh8 = require_mesh()
    rng = np.random.default_rng(77)
    ndev = mesh8.devices.size
    num_records = 4096 * ndev  # stage_sharded_bitmajor's granularity
    num_words = 8
    nq = 8 * ndev
    num_blocks = num_records // 128
    total = (num_records - 1).bit_length()
    expand = min((num_blocks - 1).bit_length(), total)
    walk = total - expand

    db = jnp.asarray(rng.integers(
        0, 1 << 32, (num_records, num_words), dtype=np.uint32
    ))
    client = DenseDpfPirClient.create(num_records, lambda pt, ci: pt)
    keys0, _ = client._generate_key_pairs(
        [int(i) for i in rng.integers(0, num_records, nq)]
    )
    staged = stage_keys(keys0)

    want = np.asarray(sharded_dense_pir_step(
        mesh8, walk_levels=walk, expand_levels=expand,
        num_blocks=num_blocks,
    )(*staged, db))

    db_perm = stage_sharded_bitmajor(mesh8, db)
    assert db_perm.shape == (32, num_records // 32, num_words)
    got = np.asarray(sharded_dense_pir_step_mxu(
        mesh8, walk_levels=walk, expand_levels=expand,
        num_blocks=num_blocks, interpret=True,
    )(*staged, db_perm)[0])
    np.testing.assert_array_equal(got, want)
