"""Predictive capacity plane tests: Holt fit math, the Forecaster's
time-to-breach + act-before-burn journaling, the soft SLO wiring, the
`PredictiveGovernor` loop closing into admission token buckets, the
`RotationCoordinator` trough-window hook, fleet workload federation,
and the `/forecastz` + `/capacityz` admin surfaces.

Everything runs on injected clocks: the store, the forecaster, and the
buckets share one `FakeClock`, so ramps and reverts are deterministic.
"""

import json
import urllib.error
import urllib.request

import pytest

from distributed_point_functions_tpu.capacity.admission import (
    AdmissionController,
    PredictiveGovernor,
    TenantPolicy,
    TokenBucket,
)
from distributed_point_functions_tpu.fleet.telemetry import ReplicaTelemetry
from distributed_point_functions_tpu.observability import (
    AdminServer,
    EventJournal,
    Forecaster,
    SloTracker,
    TimeSeriesStore,
    WorkloadObservatory,
    holt_fit,
)
from distributed_point_functions_tpu.observability import events as events_mod
from distributed_point_functions_tpu.observability import federation
from distributed_point_functions_tpu.serving.metrics import MetricsRegistry
from distributed_point_functions_tpu.serving.snapshots import (
    RotationCoordinator,
)


class FakeClock:
    def __init__(self, t=0.0):
        self.t = t

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


class CapturingJournal:
    def __init__(self):
        self.events = []

    def emit(self, kind, message, **fields):
        self.events.append((kind, message, fields))


def _ramping_forecaster(
    clock,
    *,
    slope=10.0,
    ceiling=1000.0,
    n=60,
    journal=None,
    registry=None,
    **kwargs,
):
    """A store+forecaster pair over a linear ramp: value = slope * t,
    1s tier, watched against `ceiling`."""
    store = TimeSeriesStore(tiers=((1.0, 120),), clock=clock)
    for i in range(n):
        clock.advance(1.0)
        store.record("queue_ms", slope * clock.t)
    forecaster = Forecaster(
        store,
        window_s=kwargs.pop("window_s", 30.0),
        horizon_s=kwargs.pop("horizon_s", 120.0),
        page_horizon_s=kwargs.pop("page_horizon_s", 120.0),
        min_points=5,
        journal=journal,
        registry=registry,
        clock=clock,
        **kwargs,
    )
    forecaster.watch("queue_ms", ceiling=ceiling, label="queue depth")
    return store, forecaster


# ---------------------------------------------------------------------------
# Holt fit
# ---------------------------------------------------------------------------


class TestHoltFit:
    def test_exact_on_linear_series(self):
        """A perfectly linear series leaves zero residuals and the
        smoothed level/trend equal to the last sample and the step."""
        fit = holt_fit([2.0 * i for i in range(1, 11)])
        assert fit["level"] == pytest.approx(20.0)
        assert fit["trend"] == pytest.approx(2.0)
        assert fit["residual_std"] == pytest.approx(0.0, abs=1e-12)
        assert fit["n"] == 10

    def test_too_few_samples(self):
        assert holt_fit([]) is None
        assert holt_fit([1.0, 2.0]) is None

    def test_flat_series_has_no_trend(self):
        fit = holt_fit([7.0] * 20)
        assert fit["level"] == pytest.approx(7.0)
        assert fit["trend"] == pytest.approx(0.0, abs=1e-12)


# ---------------------------------------------------------------------------
# Forecaster
# ---------------------------------------------------------------------------


class TestForecaster:
    def test_ramp_predicts_finite_breach_and_journals(self):
        clock = FakeClock()
        journal = CapturingJournal()
        registry = MetricsRegistry()
        _, forecaster = _ramping_forecaster(
            clock, journal=journal, registry=registry
        )
        state = forecaster.run()
        (record,) = state["series"]
        assert record["state"] == "ok"
        assert record["trend_per_s"] == pytest.approx(10.0, rel=0.05)
        # value ~600 climbing 10/s toward 1000: breach ~40s out.
        assert record["time_to_breach_s"] == pytest.approx(40.0, abs=5.0)
        earliest = record["time_to_breach_earliest_s"]
        assert earliest is not None
        assert earliest <= record["time_to_breach_s"]
        assert state["min_time_to_breach_s"] == earliest
        assert state["paging"] == ["queue_ms"]
        # Act-before-burn: the coalesced warning event fired.
        kinds = [kind for kind, _, _ in journal.events]
        assert kinds == ["forecast.breach_predicted"]
        _, message, fields = journal.events[0]
        assert "queue depth" in message
        assert fields["coalesce_key"] == "forecast.breach:queue_ms"
        assert fields["time_to_breach_s"] == earliest
        # The gauge the soft SLO grades.
        gauge = registry.export()["gauges"][
            "forecast.min_time_to_breach_s"
        ]
        assert gauge == pytest.approx(earliest, abs=0.01)

    def test_repeat_predictions_coalesce_in_real_journal(self):
        clock = FakeClock()
        journal = EventJournal(capacity=32, clock=clock)
        _, forecaster = _ramping_forecaster(
            clock, journal=journal, coalesce_s=30.0
        )
        forecaster.run()
        clock.advance(1.0)
        forecaster.run()  # within coalesce window: same event, bumped
        events = journal.tail(10, kind="forecast.breach_predicted")
        assert len(events) == 1
        assert events[0]["repeats"] >= 1

    def test_calm_series_is_finite_gauge_no_page(self):
        clock = FakeClock()
        registry = MetricsRegistry()
        _, forecaster = _ramping_forecaster(
            clock, slope=0.0, registry=registry
        )
        state = forecaster.run()
        assert state["min_time_to_breach_s"] is None
        assert state["paging"] == []
        assert forecaster.min_time_to_breach_s() is None
        # Calm still writes a finite gauge (= horizon) so the soft
        # gauge_min objective has data to grade.
        assert registry.export()["gauges"][
            "forecast.min_time_to_breach_s"
        ] == pytest.approx(forecaster.horizon_s)

    def test_insufficient_data_state(self):
        clock = FakeClock()
        store = TimeSeriesStore(tiers=((1.0, 60),), clock=clock)
        forecaster = Forecaster(store, min_points=5, clock=clock)
        forecaster.watch("nope", ceiling=10.0)
        state = forecaster.run()
        assert state["series"][0]["state"] == "insufficient_data"
        assert state["min_time_to_breach_s"] is None

    def test_ceiling_source_callable_and_broken_source(self):
        clock = FakeClock()
        store = TimeSeriesStore(tiers=((1.0, 120),), clock=clock)
        for _ in range(30):
            clock.advance(1.0)
            store.record("load", 5.0 * clock.t)
        forecaster = Forecaster(
            store, window_s=20.0, horizon_s=60.0, min_points=5,
            clock=clock, journal=CapturingJournal(),
        )

        def boom():
            raise RuntimeError("capacity model gone")

        forecaster.watch("load", ceiling_source=lambda: 200.0)
        forecaster.watch("load", ceiling_source=boom, label="broken")
        state = forecaster.run()
        live, broken = state["series"]
        assert live["ceiling"] == 200.0
        assert live["time_to_breach_s"] is not None
        # A broken ceiling source degrades to no_ceiling — forecast
        # still published, just ungraded.
        assert broken["ceiling"] is None
        assert broken["state"] == "no_ceiling"

    def test_direction_below_breaches_on_falling_series(self):
        clock = FakeClock()
        store = TimeSeriesStore(tiers=((1.0, 120),), clock=clock)
        for _ in range(40):
            clock.advance(1.0)
            store.record("headroom", max(0.0, 500.0 - 5.0 * clock.t))
        forecaster = Forecaster(
            store, window_s=30.0, horizon_s=120.0, min_points=5,
            clock=clock, journal=CapturingJournal(),
        )
        forecaster.watch("headroom", ceiling=100.0, direction="below")
        record = forecaster.run()["series"][0]
        # 300 falling 5/s toward 100: crossing ~40s out.
        assert record["time_to_breach_s"] == pytest.approx(40.0, abs=6.0)

    def test_objective_grades_soft_via_slo_tracker(self):
        clock = FakeClock()
        registry = MetricsRegistry()
        _, forecaster = _ramping_forecaster(
            clock, slope=0.0, registry=registry, horizon_s=300.0
        )
        objective = forecaster.objective(threshold_s=60.0)
        assert objective.severity == "soft"  # pages, never drains
        tracker = SloTracker([objective], registry, clock=clock)
        forecaster.run()
        (calm,) = tracker.evaluate()
        assert calm["state"] == "ok"
        assert calm["observed"] == pytest.approx(300.0)
        # Now a ramp: predicted breach well inside 60s -> soft breach.
        store = forecaster._store
        for _ in range(60):
            clock.advance(1.0)
            store.record("queue_ms", 50.0 * clock.t)
        forecaster.watch("queue_ms2", ceiling=1.0)  # ignored: no data
        forecaster.run()
        (burning,) = tracker.evaluate()
        assert burning["state"] == "breach"
        assert burning["severity"] == "soft"

    def test_trough_window_prefers_forecast_minimum(self):
        clock = FakeClock()
        store = TimeSeriesStore(tiers=((1.0, 120),), clock=clock)
        for i in range(60):
            clock.advance(1.0)
            store.record("rate", 1000.0 - 10.0 * clock.t)  # declining
        forecaster = Forecaster(
            store, window_s=30.0, horizon_s=60.0, min_points=5,
            clock=clock,
        )
        falling = forecaster.trough_window("rate", window_s=10.0)
        assert falling["state"] == "ok"
        # Load is falling: the cheapest prestage window is at the far
        # end of the horizon.
        assert falling["start_offset_s"] == pytest.approx(
            60.0 - 10.0, abs=2.0
        )
        assert falling["expected_value"] >= 0.0
        # Unknown series: graceful insufficient_data, prestage "now".
        unknown = forecaster.trough_window("missing", window_s=10.0)
        assert unknown["state"] == "insufficient_data"
        assert unknown["start_offset_s"] == 0.0


# ---------------------------------------------------------------------------
# TokenBucket scaling + admission governor hook
# ---------------------------------------------------------------------------


class TestTokenBucketScaling:
    def test_set_scale_refills_at_old_rate_first(self):
        clock = FakeClock()
        bucket = TokenBucket(10.0, burst=10.0, clock=clock)
        assert bucket.try_take(10.0)  # drain the burst
        clock.advance(0.5)
        # The 0.5s before the tightening was earned at 10/s: the
        # rescale must not retroactively reprice it.
        bucket.set_scale(0.5)
        assert bucket.rate == pytest.approx(5.0)
        assert bucket.base_rate == pytest.approx(10.0)
        assert bucket.try_take(5.0)  # the 0.5s * 10/s already earned
        assert not bucket.try_take(5.0)
        clock.advance(1.0)  # now earning at 5/s
        assert bucket.try_take(5.0)
        assert not bucket.try_take(1.0)

    def test_set_scale_restores_exactly(self):
        clock = FakeClock()
        bucket = TokenBucket(8.0, burst=4.0, clock=clock)
        bucket.set_scale(0.25)
        bucket.set_scale(1.0)
        assert bucket.rate == pytest.approx(8.0)

    def test_set_scale_validates(self):
        bucket = TokenBucket(8.0)
        with pytest.raises(ValueError):
            bucket.set_scale(0.0)
        with pytest.raises(ValueError):
            bucket.set_scale(-1.0)

    def test_admission_rate_scale_covers_existing_and_new_tenants(self):
        clock = FakeClock()
        registry = MetricsRegistry()
        admission = AdmissionController(
            metrics=registry, clock=clock, name="adm"
        )
        admission.set_tenant("a", TenantPolicy(rate_qps=100.0))
        admission.set_rate_scale(0.5)
        assert admission.rate_scale == 0.5
        # Tenants declared after the tightening inherit it.
        admission.set_tenant("b", TenantPolicy(rate_qps=40.0))
        export = admission.export()
        assert export["rate_scale"] == 0.5
        assert export["tenants"]["a"]["rate_qps"] == 100.0
        assert export["tenants"]["a"]["effective_rate_qps"] == (
            pytest.approx(50.0)
        )
        assert export["tenants"]["b"]["effective_rate_qps"] == (
            pytest.approx(20.0)
        )
        assert registry.export()["gauges"]["adm.rate_scale"] == 0.5
        admission.set_rate_scale(1.0)
        assert admission.export()["tenants"]["a"][
            "effective_rate_qps"
        ] == pytest.approx(100.0)
        with pytest.raises(ValueError):
            admission.set_rate_scale(0.0)


class TestPredictiveGovernor:
    def _governor(self, source, **kwargs):
        clock = FakeClock()
        admission = AdmissionController(clock=clock)
        admission.set_tenant("t", TenantPolicy(rate_qps=100.0))
        return PredictiveGovernor(
            admission, source, clock=clock,
            **{"horizon_s": 100.0, "floor": 0.25, **kwargs},
        )

    def test_scale_map_is_monotone_with_floor(self):
        governor = self._governor(lambda: None)
        assert governor.scale_for(None) == 1.0
        assert governor.scale_for(100.0) == 1.0
        assert governor.scale_for(500.0) == 1.0
        assert governor.scale_for(50.0) == pytest.approx(0.5)
        assert governor.scale_for(10.0) == pytest.approx(0.25)  # floored
        assert governor.scale_for(0.0) == pytest.approx(0.25)
        ttbs = [None, 100.0, 75.0, 50.0, 25.0, 10.0, 0.0]
        scales = [governor.scale_for(t) for t in ttbs]
        assert scales == sorted(scales, reverse=True)

    def test_update_tightens_and_reverts_exactly(self):
        ttb = {"value": None}
        journal = CapturingJournal()
        previous = events_mod.default_journal()
        events_mod.set_default_journal(journal)
        try:
            governor = self._governor(lambda: ttb["value"])
            assert governor.update() == 1.0
            ttb["value"] = 40.0  # forecast closes in
            assert governor.update() == pytest.approx(0.4)
            assert governor.admission.rate_scale == pytest.approx(0.4)
            assert governor.admission.export()["tenants"]["t"][
                "effective_rate_qps"
            ] == pytest.approx(40.0)
            ttb["value"] = None  # forecast recedes: exact revert
            assert governor.update() == 1.0
            assert governor.admission.rate_scale == 1.0
            state = governor.export()
            assert state["updates"] == 3
            assert state["tightenings"] == 1
            kinds = [kind for kind, _, _ in journal.events]
            assert kinds.count("governor.scale") == 2  # tighten + revert
        finally:
            events_mod.set_default_journal(previous)

    def test_broken_forecast_source_fails_open(self):
        def boom():
            raise RuntimeError("forecaster crashed")

        governor = self._governor(boom)
        governor.admission.set_rate_scale(0.5)  # pre-tightened
        assert governor.update() == 1.0  # fail open, not stuck at 0.5
        assert governor.admission.rate_scale == 1.0

    def test_constructor_validation(self):
        clock = FakeClock()
        admission = AdmissionController(clock=clock)
        with pytest.raises(ValueError):
            PredictiveGovernor(admission, lambda: None, horizon_s=0.0)
        with pytest.raises(ValueError):
            PredictiveGovernor(admission, lambda: None, floor=0.0)
        with pytest.raises(ValueError):
            PredictiveGovernor(admission, lambda: None, floor=1.5)


# ---------------------------------------------------------------------------
# Rotation prestage scheduling
# ---------------------------------------------------------------------------


class TestSuggestWindow:
    def test_no_source_means_now(self):
        coordinator = RotationCoordinator(object())
        suggestion = coordinator.suggest_window(30.0)
        assert suggestion == {
            "window_s": 30.0,
            "start_offset_s": 0.0,
            "source": "none",
        }

    def test_forecast_source_schedules_into_trough(self):
        clock = FakeClock()
        store = TimeSeriesStore(tiers=((1.0, 120),), clock=clock)
        for _ in range(60):
            clock.advance(1.0)
            store.record("rate", 1000.0 - 10.0 * clock.t)
        forecaster = Forecaster(
            store, window_s=30.0, horizon_s=60.0, min_points=5,
            clock=clock,
        )
        coordinator = RotationCoordinator(object(), clock=clock)
        coordinator.set_window_source(forecaster.window_source("rate"))
        suggestion = coordinator.suggest_window(10.0)
        assert suggestion["source"] == "forecast"
        assert suggestion["state"] == "ok"
        assert suggestion["start_offset_s"] > 0.0  # falling load: wait

    def test_source_error_is_advisory_only(self):
        coordinator = RotationCoordinator(object())
        coordinator.set_window_source(
            lambda window_s: (_ for _ in ()).throw(RuntimeError("x"))
        )
        suggestion = coordinator.suggest_window(30.0)
        assert suggestion["source"] == "error"
        assert suggestion["start_offset_s"] == 0.0


# ---------------------------------------------------------------------------
# Fleet federation of workload scrapes
# ---------------------------------------------------------------------------


class TestWorkloadFederation:
    def _observed(self, keys, tenant):
        observatory = WorkloadObservatory(top_k=8)
        for key in keys:
            observatory.observe(
                key_indices=(key,), tenant=tenant, deadline_s=0.1
            )
        return observatory

    def test_replica_scrape_carries_workload(self):
        clock = FakeClock()
        telemetry = ReplicaTelemetry("r0", clock=clock)
        assert "workload" not in telemetry.scrape()
        telemetry.set_workload(self._observed([1, 1, 2], "a"))
        scrape = telemetry.scrape()
        assert scrape["workload"]["observations"] == 3
        assert scrape["workload"]["tenants"]["a"]["observations"] == 3

    def test_merge_sums_counts_and_reranks_top_keys(self):
        export_a = self._observed([7] * 30 + [1] * 10, "a").export()
        export_b = self._observed([7] * 5 + [2] * 40, "b").export()
        merged = federation.merge_workloads(
            {"r0": export_a, "r1": export_b}
        )
        assert merged["replicas"] == ["r0", "r1"]
        assert merged["observations"] == 85
        # Per-key counts sum across replicas, then re-rank: key 2 (40)
        # leads key 7 (35).
        top = {row["key"]: row["count"] for row in merged["top_keys"]}
        assert top[7] == 35
        assert top[2] == 40
        assert merged["top_keys"][0]["key"] == 2
        assert set(merged["tenants"]) == {"a", "b"}
        assert merged["tenants"]["a"]["observations"] == 40
        # Histograms bucket-sum (same fixed layout both sides).
        assert merged["deadline_ms"]["count"] == 85
        assert sum(
            merged["batch_keys"]["buckets"].values()
        ) >= 85  # +inf bucket double-listed by export layout

    def test_merge_empty_and_partial(self):
        assert federation.merge_workloads({})["observations"] == 0
        merged = federation.merge_workloads(
            {"r0": self._observed([1], "a").export(), "r1": {}}
        )
        assert merged["observations"] == 1


# ---------------------------------------------------------------------------
# Admin surfaces
# ---------------------------------------------------------------------------


class TestForecastzEndpoint:
    def test_text_json_governor_fold_and_statusz(self):
        clock = FakeClock()
        journal = CapturingJournal()
        registry = MetricsRegistry()
        _, forecaster = _ramping_forecaster(
            clock, journal=journal, registry=registry
        )
        admission = AdmissionController(clock=clock)
        admission.set_tenant("t", TenantPolicy(rate_qps=100.0))
        governor = PredictiveGovernor(
            admission,
            forecaster.min_time_to_breach_s,
            horizon_s=100.0,
            floor=0.25,
            clock=clock,
        )
        governor.update()
        with AdminServer(
            registry=registry, forecast=forecaster, governor=governor
        ) as admin:
            base = f"http://127.0.0.1:{admin.port}"
            text = urllib.request.urlopen(base + "/forecastz").read()
            assert b"capacity forecast" in text
            assert b"earliest predicted breach" in text
            assert b"queue depth" in text
            assert b"governor: scale" in text
            state = json.loads(
                urllib.request.urlopen(
                    base + "/forecastz?format=json"
                ).read()
            )
            ttb = state["min_time_to_breach_s"]
            assert ttb is not None and 0 < ttb < forecaster.horizon_s
            assert state["series"][0]["series"] == "queue_ms"
            assert state["governor"]["scale"] < 1.0
            # /capacityz shows the tightened effective rate even with
            # no cost ledger attached.
            capacity = urllib.request.urlopen(base + "/capacityz").read()
            assert b"predictive governor: scale" in capacity
            assert b"t: rate 100.0 ->" in capacity
            # /statusz folds the forecast summary in.
            status = urllib.request.urlopen(base + "/statusz").read()
            assert b"Forecast" in status

    def test_404_without_forecaster(self):
        with AdminServer(registry=MetricsRegistry()) as admin:
            with pytest.raises(urllib.error.HTTPError) as err:
                urllib.request.urlopen(
                    f"http://127.0.0.1:{admin.port}/forecastz"
                )
            assert err.value.code == 404
            assert b"no forecaster" in err.value.read()
