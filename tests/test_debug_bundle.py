"""BundleManager: atomic capture, cooldown, retention ring, trigger
adapters. All disk I/O goes to pytest tmp_path; the cooldown clock is
a fake so suppression windows are deterministic.
"""

import json
import os
import threading

from distributed_point_functions_tpu.observability.bundle import BundleManager
from distributed_point_functions_tpu.observability.events import EventJournal


class FakeClock:
    def __init__(self):
        self.now = 1000.0

    def __call__(self):
        return self.now

    def advance(self, s):
        self.now += s


def make_manager(tmp_path, **kwargs):
    clock = FakeClock()
    journal = EventJournal()
    kwargs.setdefault("cooldown_s", 60.0)
    mgr = BundleManager(
        str(tmp_path), clock=clock, journal=journal, **kwargs
    )
    return mgr, clock, journal


def test_capture_writes_sources_and_manifest(tmp_path):
    mgr, _, journal = make_manager(tmp_path)
    mgr.add_source("statusz", lambda: {"healthy": True})
    mgr.add_source("metrics", lambda: {"counters": {"x": 1}})
    entry = mgr.trigger("probe_failure", {"kind": "pir_unbatched"})
    assert entry is not None and "error" not in entry
    assert os.path.isdir(entry["path"])
    assert os.path.basename(entry["path"]).startswith("bundle-0001-")
    with open(os.path.join(entry["path"], "manifest.json")) as f:
        manifest = json.load(f)
    assert manifest["reason"] == "probe_failure"
    assert manifest["context"] == {"kind": "pir_unbatched"}
    assert manifest["sources"] == {"statusz": "ok", "metrics": "ok"}
    with open(os.path.join(entry["path"], "statusz.json")) as f:
        assert json.load(f) == {"healthy": True}
    # The capture announced itself on the journal.
    assert [e["kind"] for e in journal.tail()] == ["bundle.captured"]


def test_no_partial_bundle_visible_and_source_errors_recorded(tmp_path):
    mgr, _, _ = make_manager(tmp_path)
    seen_during_capture = []

    def nosy_source():
        # Runs mid-capture: only committed (renamed) bundles may be
        # visible without their dot prefix.
        seen_during_capture.extend(
            n for n in os.listdir(tmp_path) if not n.startswith(".")
        )
        return {"ok": True}

    def broken_source():
        raise RuntimeError("snapshot exploded")

    mgr.add_source("nosy", nosy_source)
    mgr.add_source("broken", broken_source)
    entry = mgr.trigger("breaker_open")
    assert seen_during_capture == []
    assert entry["sources"]["nosy"] == "ok"
    assert "RuntimeError" in entry["sources"]["broken"]
    # A failing source never aborts the bundle; the rest landed.
    assert os.path.exists(os.path.join(entry["path"], "nosy.json"))
    assert not os.path.exists(os.path.join(entry["path"], "broken.json"))
    # Nothing un-committed remains.
    assert all(
        n.startswith("bundle-") for n in os.listdir(tmp_path)
    )


def test_cooldown_suppresses_then_allows(tmp_path):
    mgr, clock, _ = make_manager(tmp_path, cooldown_s=60.0)
    assert mgr.trigger("first") is not None
    assert mgr.trigger("second") is None
    clock.advance(61.0)
    third = mgr.trigger("third")
    assert third is not None and third["seq"] == 2
    export = mgr.export()
    assert export["suppressed_cooldown"] == 1
    assert export["fired"] == 2


def test_retention_ring_deletes_evicted_directories(tmp_path):
    mgr, clock, _ = make_manager(tmp_path, cooldown_s=0.0, max_bundles=2)
    paths = []
    for i in range(4):
        clock.advance(1.0)
        paths.append(mgr.trigger(f"r{i}")["path"])
    kept = mgr.bundles()
    assert [os.path.basename(b["path"]) for b in kept] == [
        os.path.basename(paths[2]),
        os.path.basename(paths[3]),
    ]
    assert not os.path.exists(paths[0]) and not os.path.exists(paths[1])
    assert os.path.isdir(paths[2]) and os.path.isdir(paths[3])


def test_concurrent_triggers_yield_exactly_one_bundle(tmp_path):
    mgr, _, _ = make_manager(tmp_path, cooldown_s=3600.0)
    barrier = threading.Barrier(8)
    results = []

    def fire(i):
        barrier.wait()
        results.append(mgr.trigger(f"concurrent-{i}"))

    threads = [threading.Thread(target=fire, args=(i,)) for i in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wins = [r for r in results if r is not None]
    assert len(wins) == 1
    export = mgr.export()
    assert export["fired"] == 1
    assert (
        export["suppressed_cooldown"] + export["suppressed_inflight"] == 7
    )
    assert len([n for n in os.listdir(tmp_path) if n.startswith("bundle-")]) == 1


def test_trigger_adapters_filter_correctly(tmp_path):
    mgr, clock, _ = make_manager(tmp_path, cooldown_s=0.0)
    # Soft burns and non-open transitions must not capture.
    mgr.on_burn({"severity": "soft", "name": "advisory"})
    mgr.on_breaker_transition("open", "half_open")
    assert mgr.export()["fired"] == 0
    clock.advance(1.0)
    mgr.on_burn(
        {"severity": "hard", "name": "lat", "metric": "m",
         "observed": 99, "threshold": 10}
    )
    clock.advance(1.0)
    mgr.on_breaker_transition("closed", "open")
    clock.advance(1.0)
    mgr.on_probe_failure(
        {"kind": "pir_chunked", "status": "mismatch",
         "detail": "index 3", "seq": 7}
    )
    reasons = [b["reason"] for b in mgr.bundles()]
    assert reasons == ["slo_hard_breach", "breaker_open", "probe_failure"]


def test_reason_is_sanitized_into_path(tmp_path):
    mgr, _, _ = make_manager(tmp_path)
    entry = mgr.trigger("weird reason/../with spaces")
    base = os.path.basename(entry["path"])
    assert "/" not in base.replace("bundle-", "") and " " not in base
    assert os.path.isdir(entry["path"])
