"""Blackbox prober: tier coverage, oracle mismatch detection,
degraded-mode flagging, freshness, and the /healthz and /probez
integration on the admin server.

The serving fixture is intentionally tiny (16 x 8B records) and shared
module-wide so jit compiles are paid once; the mismatch tests corrupt
the *oracle*, not the session, so sharing stays sound.
"""

import json
import urllib.error
import urllib.request

import numpy as np
import pytest

from distributed_point_functions_tpu.heavy_hitters.protocol import (
    HeavyHittersConfig,
)
from distributed_point_functions_tpu.observability import AdminServer
from distributed_point_functions_tpu.observability.events import EventJournal
from distributed_point_functions_tpu.pir import DenseDpfPirDatabase
from distributed_point_functions_tpu.pir.server import tier_floor
from distributed_point_functions_tpu.serving import (
    InProcessTransport,
    LeaderSession,
    PlainSession,
    ServingConfig,
)
from distributed_point_functions_tpu.serving.metrics import MetricsRegistry
from distributed_point_functions_tpu.serving.prober import Prober
from distributed_point_functions_tpu.serving.transport import TransportError
from distributed_point_functions_tpu.testing import encrypt_decrypt

NUM_RECORDS = 16
RECORD_BYTES = 8
RNG = np.random.default_rng(99)


def build_database():
    records = [
        bytes(RNG.integers(0, 256, RECORD_BYTES, dtype=np.uint8))
        for _ in range(NUM_RECORDS)
    ]
    builder = DenseDpfPirDatabase.Builder()
    for r in records:
        builder.insert(r)
    return builder.build(), records


DATABASE, RECORDS = build_database()
CONFIG = ServingConfig(
    max_batch_size=4, max_wait_ms=2.0, request_timeout_ms=None
)


class FakeClock:
    def __init__(self):
        self.now = 1000.0

    def __call__(self):
        return self.now

    def advance(self, s):
        self.now += s


@pytest.fixture(scope="module")
def plain_session():
    session = PlainSession(DATABASE, CONFIG)
    yield session
    session.close()


def make_prober(session, records=RECORDS, **kwargs):
    kwargs.setdefault("journal", EventJournal())
    kwargs.setdefault("metrics", MetricsRegistry())
    return Prober(session, records, **kwargs)


# -- tier coverage and pass path ---------------------------------------------


def test_cycle_passes_every_dense_tier(plain_session):
    prober = make_prober(plain_session)
    results = prober.run_cycle()
    by_kind = {r["kind"]: r for r in results}
    assert set(by_kind) == {
        "pir_materialized",
        "pir_streaming",
        "pir_chunked",
        "pir_unbatched",
    }
    assert all(r["status"] == "pass" for r in results), by_kind
    # The tier floor was restored after the forced-tier probes.
    assert tier_floor() == "materialized"
    metrics = prober._metrics.export()["counters"]
    assert metrics["prober.probes"] == 4
    assert metrics["prober.passes{kind=pir_chunked}"] == 1
    export = prober.export()
    assert export["cycles"] == 1 and export["mismatches"] == 0


def test_hh_sweep_probe_matches_plaintext_oracle(plain_session):
    prober = make_prober(
        plain_session,
        hh_values=[3, 3, 3, 9, 9, 14],
        hh_config=HeavyHittersConfig(
            domain_bits=4, level_bits=2, threshold=2
        ),
    )
    assert "hh_sweep" in prober.kinds()
    results = {r["kind"]: r["status"] for r in prober.run_cycle()}
    assert results["hh_sweep"] == "pass"
    # The sweep servers reset cleanly: a second cycle passes too.
    second = {r["kind"]: r["status"] for r in prober.run_cycle()}
    assert second["hh_sweep"] == "pass"


def test_golden_index_validation(plain_session):
    with pytest.raises(ValueError):
        make_prober(plain_session, indices=[NUM_RECORDS])
    with pytest.raises(ValueError):
        make_prober(plain_session, records=[])


# -- mismatch detection -------------------------------------------------------


def test_oracle_mismatch_fires_event_metric_and_listener(plain_session):
    # A wrong oracle is indistinguishable from wrong served bits: flip
    # one byte of one golden record.
    wrong = list(RECORDS)
    wrong[0] = bytes([wrong[0][0] ^ 0xFF]) + wrong[0][1:]
    journal = EventJournal()
    prober = make_prober(plain_session, records=wrong, journal=journal)
    failures = []
    prober.add_failure_listener(failures.append)
    results = prober.run_cycle()
    assert all(r["status"] == "mismatch" for r in results)
    assert "index 0" in results[0]["detail"]
    assert len(failures) == len(results)
    counters = prober._metrics.export()["counters"]
    assert counters["prober.mismatches{kind=pir_unbatched}"] == 1
    mismatch_events = journal.tail(kind="prober.mismatch")
    assert len(mismatch_events) == len(results)
    assert mismatch_events[0]["severity"] == "error"
    # Recovery: probing with the true oracle again emits recovered...
    # (same journal, fresh prober — state transition is per prober).
    good = make_prober(plain_session, journal=journal)
    good._last_status.update(
        {k: "mismatch" for k in good.kinds()}
    )
    good.run_cycle()
    recovered = journal.tail(kind="prober.recovered")
    assert len(recovered) == len(good.kinds())


def test_probe_error_is_contained_and_journaled(plain_session):
    journal = EventJournal()
    prober = make_prober(plain_session, journal=journal)

    def explode(*a, **k):
        raise RuntimeError("synthetic probe wreck")

    prober._probe_unbatched = explode
    failures = []
    prober.add_failure_listener(failures.append)
    results = {r["kind"]: r for r in prober.run_cycle()}
    assert results["pir_unbatched"]["status"] == "error"
    assert "synthetic probe wreck" in results["pir_unbatched"]["detail"]
    # The other probes still ran and passed.
    assert results["pir_chunked"]["status"] == "pass"
    assert [e["kind"] for e in journal.tail(kind="prober.error")] == [
        "prober.error"
    ]
    assert len(failures) == 1


# -- degraded-mode flagging ---------------------------------------------------


def test_leader_degraded_mode_flags_not_fails():
    def dead_helper(payload: bytes) -> bytes:
        raise TransportError("helper is gone")

    leader = LeaderSession(
        DATABASE,
        InProcessTransport(dead_helper),
        ServingConfig(
            max_batch_size=4,
            max_wait_ms=2.0,
            request_timeout_ms=None,
            helper_timeout_ms=None,
            helper_retries=0,
            helper_backoff_ms=1.0,
            allow_degraded=True,
        ),
    )
    try:
        journal = EventJournal()
        prober = make_prober(
            leader, encrypter=encrypt_decrypt.encrypt, journal=journal
        )
        failures = []
        prober.add_failure_listener(failures.append)
        results = {r["kind"]: r for r in prober.run_cycle()}
        # Plain probes never touch the helper leg: still bit-identical.
        assert results["pir_unbatched"]["status"] == "pass"
        # The e2e probe cannot reconstruct — flagged degraded, not failed.
        assert results["leader_e2e"]["status"] == "degraded"
        assert failures == []
        counters = prober._metrics.export()["counters"]
        assert counters["prober.degraded{kind=leader_e2e}"] == 1
        assert journal.tail(kind="prober.mismatch") == []
    finally:
        leader.close()


# -- freshness and admin integration -----------------------------------------


def _get(url):
    try:
        with urllib.request.urlopen(url) as resp:
            return resp.status, resp.read()
    except urllib.error.HTTPError as e:
        return e.code, e.read()


def test_freshness_window_and_healthz_degrade(plain_session):
    clock = FakeClock()
    prober = make_prober(
        plain_session, period_s=5.0, freshness_window_s=30.0, clock=clock
    )
    with AdminServer(
        registry=prober._metrics, port=0, prober=prober
    ) as admin:
        base = f"http://127.0.0.1:{admin.port}"
        # Never probed and the window has not elapsed: still healthy.
        status, body = _get(base + "/healthz")
        assert status == 200
        detail = json.loads(body)
        assert detail["status"] == "ok"
        assert detail["probes"]["pir_unbatched"]["last_status"] is None

        prober.run_cycle()
        clock.advance(10.0)
        status, body = _get(base + "/healthz")
        assert status == 200
        detail = json.loads(body)
        assert detail["probes"]["pir_unbatched"]["last_pass_age_s"] == 10.0

        # Past the window with no fresh pass: drain this process.
        clock.advance(31.0)
        status, body = _get(base + "/healthz")
        assert status == 503
        detail = json.loads(body)
        assert detail["status"] == "unhealthy"
        assert "pir_unbatched" in detail["stale_probes"]

        # A passing cycle recovers it.
        prober.run_cycle()
        status, _ = _get(base + "/healthz")
        assert status == 200

        # /probez serves the history; /statusz carries the summary.
        status, body = _get(base + "/probez")
        assert status == 200
        probez = json.loads(body)
        assert probez["cycles"] == 2
        assert len(probez["history"]["pir_chunked"]) == 2
        status, body = _get(base + "/statusz?format=json")
        assert json.loads(body)["prober"]["cycles"] == 2


def test_healthz_stays_plaintext_without_prober():
    with AdminServer(port=0) as admin:
        status, body = _get(f"http://127.0.0.1:{admin.port}/healthz")
        assert (status, body) == (200, b"ok\n")


def test_rate_floor_objective_shape(plain_session):
    prober = make_prober(plain_session, period_s=4.0)
    objective = prober.rate_floor_objective()
    assert objective.kind == "rate_min"
    assert objective.metric == "prober.probes"
    assert objective.threshold == pytest.approx(0.25 * 4 / 4.0)


def test_background_loop_runs_and_stops(plain_session):
    prober = make_prober(
        plain_session, period_s=0.05, max_duty_cycle=1.0
    )
    import time as _time

    with prober:
        deadline = _time.time() + 20.0
        while prober.export()["cycles"] < 2 and _time.time() < deadline:
            _time.sleep(0.05)
    assert prober.export()["cycles"] >= 2
    assert prober._thread is None
