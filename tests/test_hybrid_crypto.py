"""Tests for the X25519+HKDF+AES-GCM hybrid encryption
(`distributed_point_functions_tpu/crypto/hybrid.py`), the framework's
equivalent of the reference's Tink hybrid primitives
(`pir/testing/encrypt_decrypt.h:29-36`)."""

import pytest

from distributed_point_functions_tpu.crypto import (
    HybridDecrypt,
    HybridEncrypt,
    generate_keypair,
    keypair_from_private_bytes,
)
from distributed_point_functions_tpu.testing import encrypt_decrypt


def test_roundtrip():
    sk, pk = generate_keypair()
    enc, dec = HybridEncrypt(pk), HybridDecrypt(sk)
    for msg in (b"", b"x", b"hello helper", bytes(range(256)) * 10):
        ct = enc(msg, b"ctx")
        assert dec(ct, b"ctx") == msg


def test_ciphertexts_are_randomized():
    sk, pk = generate_keypair()
    enc = HybridEncrypt(pk)
    assert enc(b"same message", b"ctx") != enc(b"same message", b"ctx")


def test_wrong_context_info_rejected():
    sk, pk = generate_keypair()
    ct = HybridEncrypt(pk)(b"secret", b"DpfPirServer")
    with pytest.raises(Exception):
        HybridDecrypt(sk)(ct, b"OtherContext")


def test_wrong_key_rejected():
    _, pk = generate_keypair()
    sk2, _ = generate_keypair()
    ct = HybridEncrypt(pk)(b"secret", b"ctx")
    with pytest.raises(Exception):
        HybridDecrypt(sk2)(ct, b"ctx")


def test_tampered_ciphertext_rejected():
    sk, pk = generate_keypair()
    ct = bytearray(HybridEncrypt(pk)(b"secret", b"ctx"))
    ct[-1] ^= 1  # flip a tag bit
    with pytest.raises(Exception):
        HybridDecrypt(sk)(bytes(ct), b"ctx")
    with pytest.raises(ValueError):
        HybridDecrypt(sk)(b"short", b"ctx")


def test_keypair_from_private_bytes():
    sk, pk = generate_keypair()
    sk2, pk2 = keypair_from_private_bytes(sk)
    assert (sk2, pk2) == (sk, pk)


def test_checked_in_keyset_consistent():
    """testing/data/hybrid_test_keyset.json must be a matching pair."""
    _, pk = keypair_from_private_bytes(encrypt_decrypt.TEST_PRIVATE_KEY)
    assert pk == encrypt_decrypt.TEST_PUBLIC_KEY
    ct = encrypt_decrypt.encrypt(b"payload", b"DpfPirServer")
    assert encrypt_decrypt.decrypt(ct, b"DpfPirServer") == b"payload"
    assert encrypt_decrypt.decrypt.public_bytes == pk
