"""Negative/validation tests for the proto validator.

Mirrors the reference's exhaustive malformed-proto rejection
(`dpf/internal/proto_validator_test.cc`).
"""


import pytest

from distributed_point_functions_tpu import serialization as ser
from distributed_point_functions_tpu.dpf import (
    DistributedPointFunction,
    DpfParameters,
)
from distributed_point_functions_tpu.proto_validator import ProtoValidator
from distributed_point_functions_tpu.protos import dpf_pb2
from distributed_point_functions_tpu.value_types import IntType


def make_params(*lds, bits=32):
    out = []
    for d in lds:
        p = dpf_pb2.DpfParameters()
        p.log_domain_size = d
        p.value_type.integer.bitsize = bits
        out.append(p)
    return out


def test_valid_parameters_accepted():
    ProtoValidator.create(make_params(5))
    ProtoValidator.create(make_params(3, 6, 10))


def test_rejects_empty_parameters():
    with pytest.raises(ValueError, match="must not be empty"):
        ProtoValidator.validate_parameters([])


def test_rejects_negative_and_oversized_domain():
    p = make_params(5)[0]
    p.log_domain_size = -1
    with pytest.raises(ValueError, match="non-negative"):
        ProtoValidator.validate_parameters([p])
    p.log_domain_size = 129
    with pytest.raises(ValueError, match="<= 128"):
        ProtoValidator.validate_parameters([p])


def test_rejects_non_ascending_domains():
    with pytest.raises(ValueError, match="ascending"):
        ProtoValidator.validate_parameters(make_params(6, 6))
    with pytest.raises(ValueError, match="ascending"):
        ProtoValidator.validate_parameters(make_params(6, 3))


def test_rejects_missing_value_type():
    p = dpf_pb2.DpfParameters()
    p.log_domain_size = 4
    with pytest.raises(ValueError, match="value_type is required"):
        ProtoValidator.validate_parameters([p])


def test_rejects_bad_bitsize():
    p = make_params(4)[0]
    p.value_type.integer.bitsize = 12
    with pytest.raises(ValueError, match="bitsize"):
        ProtoValidator.validate_parameters([p])


def test_rejects_bad_security_parameter():
    p = make_params(4)[0]
    p.security_parameter = float("nan")
    with pytest.raises(ValueError, match="NaN"):
        ProtoValidator.validate_parameters([p])
    p.security_parameter = 129.0
    with pytest.raises(ValueError, match=r"\[0, 128\]"):
        ProtoValidator.validate_parameters([p])


def make_key_proto(lds=6, alpha=3, beta=42):
    dpf = DistributedPointFunction.create(
        DpfParameters(log_domain_size=lds, value_type=IntType(32))
    )
    k0, _ = dpf.generate_keys(alpha, beta)
    return dpf, ser.key_to_proto(dpf, k0)


def test_validate_key_accepts_valid():
    dpf, key = make_key_proto()
    v = ProtoValidator.create(
        [ser.parameters_to_proto(p) for p in dpf.parameters]
    )
    v.validate_dpf_key(key)


def test_validate_key_rejects_malformed():
    dpf, key = make_key_proto()
    v = ProtoValidator.create(
        [ser.parameters_to_proto(p) for p in dpf.parameters]
    )
    bad = dpf_pb2.DpfKey.FromString(key.SerializeToString())
    bad.ClearField("seed")
    with pytest.raises(ValueError, match="seed"):
        v.validate_dpf_key(bad)

    bad = dpf_pb2.DpfKey.FromString(key.SerializeToString())
    bad.ClearField("last_level_value_correction")
    with pytest.raises(ValueError, match="last_level_value_correction"):
        v.validate_dpf_key(bad)

    bad = dpf_pb2.DpfKey.FromString(key.SerializeToString())
    del bad.correction_words[-1]
    with pytest.raises(ValueError, match="correction words"):
        v.validate_dpf_key(bad)


def test_validate_key_requires_intermediate_value_correction():
    dpf = DistributedPointFunction.create_incremental(
        [
            DpfParameters(log_domain_size=3, value_type=IntType(32)),
            DpfParameters(log_domain_size=9, value_type=IntType(32)),
        ]
    )
    k0, _ = dpf.generate_keys_incremental(100, [1, 2])
    proto = ser.key_to_proto(dpf, k0)
    v = ProtoValidator.create(
        [ser.parameters_to_proto(p) for p in dpf.parameters]
    )
    v.validate_dpf_key(proto)
    vc_index = dpf._hierarchy_to_tree[0]
    proto.correction_words[vc_index].ClearField("value_correction")
    with pytest.raises(ValueError, match="value correction"):
        v.validate_dpf_key(proto)


def test_validate_evaluation_context():
    dpf = DistributedPointFunction.create_incremental(
        [
            DpfParameters(log_domain_size=3, value_type=IntType(32)),
            DpfParameters(log_domain_size=9, value_type=IntType(32)),
        ]
    )
    k0, _ = dpf.generate_keys_incremental(100, [1, 2])
    ctx = dpf.create_evaluation_context(k0)
    proto = ser.evaluation_context_to_proto(dpf, ctx)
    v = ProtoValidator.create(
        [ser.parameters_to_proto(p) for p in dpf.parameters]
    )
    v.validate_evaluation_context(proto)

    exhausted = dpf_pb2.EvaluationContext.FromString(proto.SerializeToString())
    exhausted.previous_hierarchy_level = 1
    with pytest.raises(ValueError, match="fully evaluated"):
        v.validate_evaluation_context(exhausted)

    mismatched = dpf_pb2.EvaluationContext.FromString(proto.SerializeToString())
    mismatched.parameters[0].log_domain_size = 4
    with pytest.raises(ValueError, match="doesn't match"):
        v.validate_evaluation_context(mismatched)


# ---------------------------------------------------------------------------
# Malformed-message corpus (ports the reference's exhaustive sweep,
# `dpf/internal/proto_validator_test.cc` + proto_validator_test.textproto).
# Each entry mutates a valid message and must be rejected.
# ---------------------------------------------------------------------------


def _integer_value(v):
    out = dpf_pb2.Value()
    if v < 1 << 64:
        out.integer.value_uint64 = v
    else:
        out.integer.value_uint128.high = v >> 64
        out.integer.value_uint128.low = v & ((1 << 64) - 1)
    return out


BAD_PARAMETER_MUTATIONS = [
    ("domain_negative", lambda p: setattr(p, "log_domain_size", -1),
     "non-negative"),
    ("domain_too_large", lambda p: setattr(p, "log_domain_size", 129),
     "<= 128"),
    ("bitsize_zero", lambda p: setattr(p.value_type.integer, "bitsize", 0),
     "positive"),
    ("bitsize_negative",
     lambda p: setattr(p.value_type.integer, "bitsize", -2), "positive"),
    ("bitsize_too_large",
     lambda p: setattr(p.value_type.integer, "bitsize", 256),
     "less than or equal to 128"),
    ("bitsize_not_pow2",
     lambda p: setattr(p.value_type.integer, "bitsize", 23), "power of 2"),
    ("bitsize_unsupported_small_pow2",
     lambda p: setattr(p.value_type.integer, "bitsize", 4), "one of"),
    ("xor_bitsize_not_pow2",
     lambda p: setattr(p.value_type.xor_wrapper, "bitsize", 33),
     "power of 2"),
    ("xor_bitsize_zero",
     lambda p: setattr(p.value_type.xor_wrapper, "bitsize", 0), "positive"),
    ("tuple_member_bad_bitsize",
     lambda p: p.value_type.tuple.elements.add().integer.__setattr__(
         "bitsize", 7), "power of 2"),
    ("security_nan",
     lambda p: setattr(p, "security_parameter", float("nan")), "NaN"),
    ("security_negative",
     lambda p: setattr(p, "security_parameter", -1.0), r"\[0, 128\]"),
    ("security_too_large",
     lambda p: setattr(p, "security_parameter", 160.0), r"\[0, 128\]"),
]


@pytest.mark.parametrize(
    "name,mutate,msg", BAD_PARAMETER_MUTATIONS,
    ids=[m[0] for m in BAD_PARAMETER_MUTATIONS],
)
def test_rejects_malformed_parameters(name, mutate, msg):
    p = make_params(5)[0]
    mutate(p)
    with pytest.raises(ValueError, match=msg):
        ProtoValidator.validate_parameters([p])


def test_rejects_int_mod_n_bad_base_and_modulus():
    p = make_params(5)[0]
    p.value_type.Clear()
    p.value_type.int_mod_n.base_integer.bitsize = 9
    p.value_type.int_mod_n.modulus.value_uint64 = 3
    with pytest.raises(ValueError, match="power of 2"):
        ProtoValidator.validate_parameters([p])

    p.value_type.Clear()
    p.value_type.int_mod_n.base_integer.bitsize = 32
    # Modulus doesn't fit the base integer.
    p.value_type.int_mod_n.modulus.value_uint64 = 1 << 33
    with pytest.raises(ValueError, match="too large"):
        ProtoValidator.validate_parameters([p])


def test_rejects_value_type_not_set():
    vt = dpf_pb2.ValueType()
    with pytest.raises(ValueError, match="type set"):
        ProtoValidator.validate_value_type(vt)


# -- ValidateValue corpus (`proto_validator_test.cc:287-380`) ---------------


def _vt_integer(bits=32):
    vt = dpf_pb2.ValueType()
    vt.integer.bitsize = bits
    return vt


def test_validate_value_accepts_valid():
    ProtoValidator.validate_value(_integer_value(23), _vt_integer(32))
    big = dpf_pb2.ValueType()
    big.integer.bitsize = 128
    ProtoValidator.validate_value(_integer_value(1 << 100), big)


def test_validate_value_fails_if_type_not_integer():
    value = dpf_pb2.Value()
    value.tuple.elements.add().integer.value_uint64 = 23
    with pytest.raises(ValueError, match="Expected integer value"):
        ProtoValidator.validate_value(value, _vt_integer(32))


def test_validate_value_fails_if_integer_too_large():
    with pytest.raises(ValueError, match="too large for ValueType"):
        ProtoValidator.validate_value(_integer_value(1 << 32), _vt_integer(32))
    # 128-bit encoding of a value too large for a 64-bit type.
    with pytest.raises(ValueError, match="too large for ValueType"):
        ProtoValidator.validate_value(_integer_value(1 << 70), _vt_integer(64))


def test_validate_value_fails_if_integer_value_case_unset():
    value = dpf_pb2.Value()
    value.integer.SetInParent()
    with pytest.raises(ValueError, match="Unknown value case"):
        ProtoValidator.validate_value(value, _vt_integer(32))


def test_validate_value_fails_if_type_not_tuple():
    vt = dpf_pb2.ValueType()
    vt.tuple.elements.add().integer.bitsize = 32
    with pytest.raises(ValueError, match="Expected tuple value"):
        ProtoValidator.validate_value(_integer_value(23), vt)


def test_validate_value_fails_if_tuple_size_doesnt_match():
    vt = dpf_pb2.ValueType()
    vt.tuple.elements.add().integer.bitsize = 32
    value = dpf_pb2.Value()
    value.tuple.elements.add().integer.value_uint64 = 23
    value.tuple.elements.add().integer.value_uint64 = 42
    with pytest.raises(ValueError, match="size 1 but got size 2"):
        ProtoValidator.validate_value(value, vt)


def test_validate_value_fails_inside_nested_tuple():
    vt = dpf_pb2.ValueType()
    vt.tuple.elements.add().integer.bitsize = 8
    value = dpf_pb2.Value()
    value.tuple.elements.add().integer.value_uint64 = 300  # > 2^8
    with pytest.raises(ValueError, match="too large for ValueType"):
        ProtoValidator.validate_value(value, vt)


def test_validate_value_fails_if_value_larger_than_modulus():
    vt = dpf_pb2.ValueType()
    vt.int_mod_n.base_integer.bitsize = 64
    vt.int_mod_n.modulus.value_uint64 = 3
    value = dpf_pb2.Value()
    value.int_mod_n.value_uint64 = 3
    with pytest.raises(ValueError, match=r"too large for modulus \(= 3\)"):
        ProtoValidator.validate_value(value, vt)


def test_validate_value_fails_if_int_mod_n_case_mismatch():
    vt = dpf_pb2.ValueType()
    vt.int_mod_n.base_integer.bitsize = 64
    vt.int_mod_n.modulus.value_uint64 = 1000
    with pytest.raises(ValueError, match="Expected IntModN value"):
        ProtoValidator.validate_value(_integer_value(23), vt)


def test_validate_value_fails_if_type_not_xor_wrapper():
    vt = dpf_pb2.ValueType()
    vt.xor_wrapper.bitsize = 32
    with pytest.raises(ValueError, match="Expected XorWrapper value"):
        ProtoValidator.validate_value(_integer_value(23), vt)


def test_validate_value_fails_if_xor_wrapper_too_large():
    vt = dpf_pb2.ValueType()
    vt.xor_wrapper.bitsize = 8
    value = dpf_pb2.Value()
    value.xor_wrapper.value_uint64 = 256
    with pytest.raises(ValueError, match="too large for ValueType"):
        ProtoValidator.validate_value(value, vt)


def test_validate_value_fails_if_type_unknown():
    with pytest.raises(ValueError, match="Unsupported ValueType"):
        ProtoValidator.validate_value(dpf_pb2.Value(), dpf_pb2.ValueType())


# -- DpfKey corpus ----------------------------------------------------------


def _key_fixture():
    dpf, key = make_key_proto()
    v = ProtoValidator.create(
        [ser.parameters_to_proto(p) for p in dpf.parameters]
    )
    return v, key


BAD_KEY_MUTATIONS = [
    ("seed_missing", lambda k: k.ClearField("seed"), "seed"),
    ("last_level_vc_missing",
     lambda k: k.ClearField("last_level_value_correction"),
     "last_level_value_correction"),
    ("correction_word_removed",
     lambda k: k.correction_words.pop(), "correction words"),
    ("correction_word_added",
     lambda k: k.correction_words.add(), "correction words"),
    ("all_correction_words_cleared",
     lambda k: k.ClearField("correction_words"), "correction words"),
]


@pytest.mark.parametrize(
    "name,mutate,msg", BAD_KEY_MUTATIONS,
    ids=[m[0] for m in BAD_KEY_MUTATIONS],
)
def test_rejects_malformed_keys(name, mutate, msg):
    v, key = _key_fixture()
    bad = dpf_pb2.DpfKey.FromString(key.SerializeToString())
    mutate(bad)
    with pytest.raises(ValueError, match=msg):
        v.validate_dpf_key(bad)


# -- EvaluationContext corpus -----------------------------------------------


def _ctx_fixture():
    dpf = DistributedPointFunction.create_incremental(
        [
            DpfParameters(log_domain_size=3, value_type=IntType(32)),
            DpfParameters(log_domain_size=9, value_type=IntType(32)),
        ]
    )
    k0, _ = dpf.generate_keys_incremental(100, [1, 2])
    ctx = dpf.create_evaluation_context(k0)
    proto = ser.evaluation_context_to_proto(dpf, ctx)
    v = ProtoValidator.create(
        [ser.parameters_to_proto(p) for p in dpf.parameters]
    )
    return v, proto


def _clone_ctx(proto):
    return dpf_pb2.EvaluationContext.FromString(proto.SerializeToString())


BAD_CTX_MUTATIONS = [
    ("key_missing", lambda c: c.ClearField("key"), "key must be present"),
    ("key_seed_missing", lambda c: c.key.ClearField("seed"), "seed"),
    ("parameters_removed", lambda c: c.parameters.pop(), "doesn't match"),
    ("parameters_added",
     lambda c: c.parameters.add(), "doesn't match"),
    ("log_domain_size_mismatch",
     lambda c: setattr(c.parameters[0], "log_domain_size", 4),
     "doesn't match"),
    ("value_type_mismatch",
     lambda c: setattr(c.parameters[0].value_type.integer, "bitsize", 64),
     "doesn't match"),
    ("security_parameter_mismatch",
     lambda c: setattr(c.parameters[0], "security_parameter", 100.0),
     "doesn't match"),
    ("fully_evaluated",
     lambda c: setattr(c, "previous_hierarchy_level", 1),
     "fully evaluated"),
]


@pytest.mark.parametrize(
    "name,mutate,msg", BAD_CTX_MUTATIONS,
    ids=[m[0] for m in BAD_CTX_MUTATIONS],
)
def test_rejects_malformed_contexts(name, mutate, msg):
    v, proto = _ctx_fixture()
    bad = _clone_ctx(proto)
    mutate(bad)
    with pytest.raises(ValueError, match=msg):
        v.validate_evaluation_context(bad)


def test_ctx_accepts_default_security_parameter_as_equal():
    """Explicit default and 0 security parameters compare equal
    (`proto_validator_test.cc:244-254`)."""
    v, proto = _ctx_fixture()
    ok = _clone_ctx(proto)
    ok.parameters[0].security_parameter = 0.0
    v.validate_evaluation_context(ok)
    ok.parameters[0].security_parameter = 43.0  # 40 + log_domain_size(3)
    v.validate_evaluation_context(ok)


def test_ctx_rejects_partial_evaluations_level_too_large():
    v, proto = _ctx_fixture()
    bad = _clone_ctx(proto)
    bad.previous_hierarchy_level = 0
    bad.partial_evaluations_level = 1
    bad.partial_evaluations.add()
    with pytest.raises(ValueError, match="partial_evaluations_level"):
        v.validate_evaluation_context(bad)


# -- Reference-corpus anchors -----------------------------------------------


def test_create_works_when_element_bitsizes_decrease():
    """Hierarchy bitsizes may decrease (`proto_validator_test.cc:161`):
    only log_domain_size must ascend."""
    ps = make_params(5, 7)
    ps[0].value_type.integer.bitsize = 64
    ps[1].value_type.integer.bitsize = 32
    ProtoValidator.create(ps)


def test_create_works_when_hierarchies_are_far_apart():
    """ld 10 -> 128 in one hierarchy step is valid
    (`proto_validator_test.cc:169`)."""
    ProtoValidator.create(make_params(10, 128))


def test_reference_corpus_anchor_three_hierarchies():
    """The reference's embedded corpus fixture shape (3 hierarchies,
    ld 4/6/8, security 44/46/48, uint32 values —
    `proto_validator_test.textproto`): real keys and contexts built at
    exactly those parameters must validate for both parties, and the
    same corpus mutations reject (the sweeps above run them on the
    2-hierarchy fixture; this anchors the exact reference shape)."""
    protos = []
    for ld in (4, 6, 8):
        p = dpf_pb2.DpfParameters()
        p.log_domain_size = ld
        p.value_type.integer.bitsize = 32
        p.security_parameter = 40 + ld
        protos.append(p)
    params = [ser.parameters_from_proto(p) for p in protos]
    dpf = DistributedPointFunction.create_incremental(params)
    k0, k1 = dpf.generate_keys_incremental(11, [1, 2, 3])
    v = ProtoValidator.create(protos)
    for k in (k0, k1):
        kp = ser.key_to_proto(dpf, k)
        v.validate_dpf_key(kp)
        ctx_proto = ser.evaluation_context_to_proto(
            dpf, dpf.create_evaluation_context(k)
        )
        v.validate_evaluation_context(ctx_proto)
        # The corpus key mutations reject on this fixture too.
        bad = dpf_pb2.DpfKey.FromString(kp.SerializeToString())
        bad.correction_words.add()
        with pytest.raises(ValueError, match="correction words"):
            v.validate_dpf_key(bad)
