"""Negative/validation tests for the proto validator.

Mirrors the reference's exhaustive malformed-proto rejection
(`dpf/internal/proto_validator_test.cc`).
"""

import math

import pytest

from distributed_point_functions_tpu import serialization as ser
from distributed_point_functions_tpu.dpf import (
    DistributedPointFunction,
    DpfParameters,
)
from distributed_point_functions_tpu.proto_validator import ProtoValidator
from distributed_point_functions_tpu.protos import dpf_pb2
from distributed_point_functions_tpu.value_types import IntType


def make_params(*lds, bits=32):
    out = []
    for d in lds:
        p = dpf_pb2.DpfParameters()
        p.log_domain_size = d
        p.value_type.integer.bitsize = bits
        out.append(p)
    return out


def test_valid_parameters_accepted():
    ProtoValidator.create(make_params(5))
    ProtoValidator.create(make_params(3, 6, 10))


def test_rejects_empty_parameters():
    with pytest.raises(ValueError, match="must not be empty"):
        ProtoValidator.validate_parameters([])


def test_rejects_negative_and_oversized_domain():
    p = make_params(5)[0]
    p.log_domain_size = -1
    with pytest.raises(ValueError, match="non-negative"):
        ProtoValidator.validate_parameters([p])
    p.log_domain_size = 129
    with pytest.raises(ValueError, match="<= 128"):
        ProtoValidator.validate_parameters([p])


def test_rejects_non_ascending_domains():
    with pytest.raises(ValueError, match="ascending"):
        ProtoValidator.validate_parameters(make_params(6, 6))
    with pytest.raises(ValueError, match="ascending"):
        ProtoValidator.validate_parameters(make_params(6, 3))


def test_rejects_missing_value_type():
    p = dpf_pb2.DpfParameters()
    p.log_domain_size = 4
    with pytest.raises(ValueError, match="value_type is required"):
        ProtoValidator.validate_parameters([p])


def test_rejects_bad_bitsize():
    p = make_params(4)[0]
    p.value_type.integer.bitsize = 12
    with pytest.raises(ValueError, match="bitsize"):
        ProtoValidator.validate_parameters([p])


def test_rejects_bad_security_parameter():
    p = make_params(4)[0]
    p.security_parameter = float("nan")
    with pytest.raises(ValueError, match="NaN"):
        ProtoValidator.validate_parameters([p])
    p.security_parameter = 129.0
    with pytest.raises(ValueError, match=r"\[0, 128\]"):
        ProtoValidator.validate_parameters([p])


def make_key_proto(lds=6, alpha=3, beta=42):
    dpf = DistributedPointFunction.create(
        DpfParameters(log_domain_size=lds, value_type=IntType(32))
    )
    k0, _ = dpf.generate_keys(alpha, beta)
    return dpf, ser.key_to_proto(dpf, k0)


def test_validate_key_accepts_valid():
    dpf, key = make_key_proto()
    v = ProtoValidator.create(
        [ser.parameters_to_proto(p) for p in dpf.parameters]
    )
    v.validate_dpf_key(key)


def test_validate_key_rejects_malformed():
    dpf, key = make_key_proto()
    v = ProtoValidator.create(
        [ser.parameters_to_proto(p) for p in dpf.parameters]
    )
    bad = dpf_pb2.DpfKey.FromString(key.SerializeToString())
    bad.ClearField("seed")
    with pytest.raises(ValueError, match="seed"):
        v.validate_dpf_key(bad)

    bad = dpf_pb2.DpfKey.FromString(key.SerializeToString())
    bad.ClearField("last_level_value_correction")
    with pytest.raises(ValueError, match="last_level_value_correction"):
        v.validate_dpf_key(bad)

    bad = dpf_pb2.DpfKey.FromString(key.SerializeToString())
    del bad.correction_words[-1]
    with pytest.raises(ValueError, match="correction words"):
        v.validate_dpf_key(bad)


def test_validate_key_requires_intermediate_value_correction():
    dpf = DistributedPointFunction.create_incremental(
        [
            DpfParameters(log_domain_size=3, value_type=IntType(32)),
            DpfParameters(log_domain_size=9, value_type=IntType(32)),
        ]
    )
    k0, _ = dpf.generate_keys_incremental(100, [1, 2])
    proto = ser.key_to_proto(dpf, k0)
    v = ProtoValidator.create(
        [ser.parameters_to_proto(p) for p in dpf.parameters]
    )
    v.validate_dpf_key(proto)
    vc_index = dpf._hierarchy_to_tree[0]
    proto.correction_words[vc_index].ClearField("value_correction")
    with pytest.raises(ValueError, match="value correction"):
        v.validate_dpf_key(proto)


def test_validate_evaluation_context():
    dpf = DistributedPointFunction.create_incremental(
        [
            DpfParameters(log_domain_size=3, value_type=IntType(32)),
            DpfParameters(log_domain_size=9, value_type=IntType(32)),
        ]
    )
    k0, _ = dpf.generate_keys_incremental(100, [1, 2])
    ctx = dpf.create_evaluation_context(k0)
    proto = ser.evaluation_context_to_proto(dpf, ctx)
    v = ProtoValidator.create(
        [ser.parameters_to_proto(p) for p in dpf.parameters]
    )
    v.validate_evaluation_context(proto)

    exhausted = dpf_pb2.EvaluationContext.FromString(proto.SerializeToString())
    exhausted.previous_hierarchy_level = 1
    with pytest.raises(ValueError, match="fully evaluated"):
        v.validate_evaluation_context(exhausted)

    mismatched = dpf_pb2.EvaluationContext.FromString(proto.SerializeToString())
    mismatched.parameters[0].log_domain_size = 4
    with pytest.raises(ValueError, match="doesn't match"):
        v.validate_evaluation_context(mismatched)
