"""AutoProfiler: SLO-burn-triggered capture policy.

Driven entirely through a real `SloTracker` over a real registry — the
tests push metric values over/under the threshold and call
`evaluate()`, exactly the way a /healthz scrape drives production. The
capture itself is a stub (`capture_fn`) and the cooldown clock is a
fake, so the tests are deterministic and JAX-free.
"""

from distributed_point_functions_tpu.observability.autoprofile import (
    LATENCY_KINDS,
    AutoProfiler,
)
from distributed_point_functions_tpu.observability.slo import (
    SloObjective,
    SloTracker,
)
from distributed_point_functions_tpu.serving.metrics import MetricsRegistry


class FakeClock:
    def __init__(self):
        self.now = 1000.0

    def __call__(self):
        return self.now

    def advance(self, s):
        self.now += s


def make_latency_rig(threshold=50.0, **profiler_kwargs):
    reg = MetricsRegistry()
    tracker = SloTracker(
        [SloObjective(name="lat", kind="p99_ms_max",
                      metric="req_ms", threshold=threshold)],
        registry=reg,
    )
    clock = FakeClock()
    captured = []

    def capture_fn(record):
        captured.append(record)
        return {"log_dir": f"/tmp/fake-{len(captured)}"}

    profiler_kwargs.setdefault("cooldown_s", 60.0)
    prof = AutoProfiler(
        tracker,
        capture_fn=capture_fn,
        clock=clock,
        async_capture=False,
        **profiler_kwargs,
    )
    return reg, tracker, clock, prof, captured


def breach(reg, ms=500.0):
    # The histogram is cumulative; reset so the new p99 IS this sample.
    reg.reset()
    reg.histogram("req_ms").observe(ms)


def recover(reg):
    reg.reset()
    reg.histogram("req_ms").observe(1.0)


def test_burn_transition_fires_exactly_one_capture():
    reg, tracker, clock, prof, captured = make_latency_rig()
    breach(reg)
    tracker.evaluate()
    assert len(captured) == 1
    assert prof.export()["fired"] == 1
    entry = prof.captures()[0]
    assert entry["objective"] == "lat"
    assert entry["metric"] == "req_ms"
    assert entry["observed"] >= entry["threshold"] == 50.0
    assert entry["log_dir"] == "/tmp/fake-1"


def test_continuing_breach_never_refires():
    reg, tracker, clock, prof, captured = make_latency_rig()
    breach(reg)
    for _ in range(5):
        tracker.evaluate()  # still in breach every scrape
        clock.advance(120.0)  # well past cooldown — state, not window
    assert len(captured) == 1
    export = prof.export()
    assert export["fired"] == 1
    assert export["suppressed_cooldown"] == 0


def test_flapping_objective_respects_cooldown():
    reg, tracker, clock, prof, captured = make_latency_rig(cooldown_s=60.0)
    breach(reg)
    tracker.evaluate()  # burn #1 -> capture
    recover(reg)
    tracker.evaluate()  # back to ok
    clock.advance(10.0)
    breach(reg, ms=10_000.0)
    tracker.evaluate()  # burn #2 inside cooldown -> suppressed
    assert len(captured) == 1
    assert prof.export()["suppressed_cooldown"] == 1

    recover(reg)
    tracker.evaluate()
    clock.advance(120.0)
    breach(reg, ms=10_000_000.0)
    tracker.evaluate()  # burn #3 past cooldown -> fires again
    assert len(captured) == 2
    assert prof.export()["fired"] == 2


def test_non_latency_kinds_are_filtered():
    reg = MetricsRegistry()
    tracker = SloTracker(
        [SloObjective(name="compiles", kind="counter_max",
                      metric="device.compiles", threshold=1)],
        registry=reg,
    )
    captured = []
    prof = AutoProfiler(
        tracker, capture_fn=lambda r: captured.append(r),
        clock=FakeClock(), async_capture=False,
    )
    reg.counter("device.compiles").inc(5)
    tracker.evaluate()
    assert captured == []
    export = prof.export()
    assert export["fired"] == 0
    assert export["suppressed_kind"] == 1
    assert list(export["kinds"]) == list(LATENCY_KINDS)


def test_ring_buffer_evicts_oldest():
    reg, tracker, clock, prof, captured = make_latency_rig(
        cooldown_s=1.0, max_captures=2
    )
    for _ in range(3):
        breach(reg, ms=10_000.0)
        tracker.evaluate()
        recover(reg)
        tracker.evaluate()
        clock.advance(5.0)
    assert len(captured) == 3
    entries = prof.captures()
    assert len(entries) == 2  # ring kept only the last two
    assert [e["log_dir"] for e in entries] == ["/tmp/fake-2", "/tmp/fake-3"]
    assert prof.export()["fired"] == 3


def test_failed_capture_is_an_error_entry_not_a_crash():
    reg = MetricsRegistry()
    tracker = SloTracker(
        [SloObjective(name="lat", kind="p99_ms_max",
                      metric="req_ms", threshold=50.0)],
        registry=reg,
    )

    def boom(record):
        raise RuntimeError("profiler backend exploded")

    prof = AutoProfiler(
        tracker, capture_fn=boom, clock=FakeClock(), async_capture=False
    )
    breach(reg)
    tracker.evaluate()  # must not raise through the scrape
    (entry,) = prof.captures()
    assert "profiler backend exploded" in entry["error"]
    export = prof.export()
    assert export["fired"] == 1 and export["in_flight"] is False


def test_capture_xprof_writes_a_directory(tmp_path):
    from distributed_point_functions_tpu.observability.autoprofile import (
        capture_xprof,
    )

    result = capture_xprof(str(tmp_path), "unit", duration_ms=1.0)
    assert result["log_dir"].startswith(str(tmp_path))
    assert result["duration_ms"] >= 1.0
