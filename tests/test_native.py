"""Differential tests: native C++ kernels vs numpy oracle vs JAX kernels.

The three-way check mirrors the reference's per-target SIMD-vs-scalar
differential testing (`dpf/internal/evaluate_prg_hwy_test.cc:49-136`,
`pir/internal/inner_product_hwy_test.cc:427-434`): identical inputs through
every implementation, outputs must be bit-identical.
"""

import numpy as np
import pytest

from distributed_point_functions_tpu import keys as fixed_keys
from distributed_point_functions_tpu import native
from distributed_point_functions_tpu.ops import aes
from distributed_point_functions_tpu.ops.inner_product import (
    pack_selection_bits_np,
    xor_inner_product,
    xor_inner_product_np,
)

RNG = np.random.default_rng(5)


def random_blocks(n):
    return RNG.integers(0, 256, (n, 16), dtype=np.uint8)


@pytest.mark.parametrize("which,rk", [
    (0, "RK_LEFT"), (1, "RK_RIGHT"), (2, "RK_VALUE"),
])
def test_native_mmo_hash_matches_oracle(which, rk):
    blocks = random_blocks(65)
    got = native.mmo_hash(which, blocks)
    limbs = aes.bytes_to_limbs_np(blocks)
    want = aes.limbs_to_bytes_np(
        aes.mmo_hash_np(getattr(fixed_keys, rk), limbs)
    )
    np.testing.assert_array_equal(got, want)


def test_native_expand_level_matches_jax():
    import jax.numpy as jnp

    from distributed_point_functions_tpu.dpf import _expand_level

    n = 17
    seeds = random_blocks(n)
    control = RNG.integers(0, 2, (n,), dtype=np.uint8)
    cw_seed = random_blocks(1)[0]
    cw_left, cw_right = 1, 0

    got_seeds, got_control = native.expand_level(
        seeds, control, cw_seed, cw_left, cw_right
    )

    limbs = aes.bytes_to_limbs_np(seeds)
    cw_limbs = aes.bytes_to_limbs_np(cw_seed[None])[0]
    jax_seeds, jax_control = _expand_level(
        jnp.asarray(limbs),
        jnp.asarray(control.astype(np.uint32)),
        jnp.asarray(cw_limbs),
        jnp.uint32(cw_left),
        jnp.uint32(cw_right),
    )
    np.testing.assert_array_equal(
        got_seeds, aes.limbs_to_bytes_np(np.asarray(jax_seeds))
    )
    np.testing.assert_array_equal(
        got_control, np.asarray(jax_control).astype(np.uint8)
    )


@pytest.mark.parametrize("per_seed_cw", [False, True])
def test_native_evaluate_seeds_matches_jax(per_seed_cw):
    import jax.numpy as jnp

    from distributed_point_functions_tpu.dpf import _eval_paths

    n, levels = 9, 6
    seeds = random_blocks(n)
    control = RNG.integers(0, 2, (n,), dtype=np.uint8)
    paths = np.zeros((n, 16), dtype=np.uint8)
    paths[:, 0] = RNG.integers(0, 64, n)  # 6-bit paths
    m = n if per_seed_cw else 1
    cw_seeds = RNG.integers(0, 256, (levels, m, 16), dtype=np.uint8)
    cw_left = RNG.integers(0, 2, (levels, m), dtype=np.uint8)
    cw_right = RNG.integers(0, 2, (levels, m), dtype=np.uint8)

    got_seeds, got_control = native.evaluate_seeds(
        seeds, control, paths, cw_seeds, cw_left, cw_right, per_seed_cw
    )

    bit_indices = np.array(
        [levels - 1 - j for j in range(levels)], dtype=np.int32
    )
    jax_seeds, jax_control = _eval_paths(
        jnp.asarray(aes.bytes_to_limbs_np(seeds)),
        jnp.asarray(control.astype(np.uint32)),
        jnp.asarray(aes.bytes_to_limbs_np(paths)),
        jnp.asarray(aes.bytes_to_limbs_np(cw_seeds)),
        jnp.asarray(cw_left.astype(np.uint32)),
        jnp.asarray(cw_right.astype(np.uint32)),
        jnp.asarray(bit_indices),
    )
    np.testing.assert_array_equal(
        got_seeds, aes.limbs_to_bytes_np(np.asarray(jax_seeds))
    )
    np.testing.assert_array_equal(
        got_control, np.asarray(jax_control).astype(np.uint8)
    )


def test_native_value_hash_matches_jax():
    from distributed_point_functions_tpu.dpf import _value_hash

    seeds = random_blocks(5)
    got = native.value_hash(seeds, 3)
    jax_out = np.asarray(
        _value_hash(aes.bytes_to_limbs_np(seeds), 3)
    )  # [n, B, 4]
    want = aes.limbs_to_bytes_np(jax_out)
    np.testing.assert_array_equal(got, want)


def test_native_inner_product_matches_oracles():
    num_records, num_words, nq = 384, 10, 3
    db = RNG.integers(0, 1 << 32, (num_records, num_words), dtype=np.uint32)
    bits = RNG.integers(0, 2, (nq, num_records), dtype=np.uint32)
    packed = pack_selection_bits_np(bits)  # [nq, B, 4] uint32
    sel_bytes = np.ascontiguousarray(packed.astype("<u4")).view(np.uint8)
    sel_bytes = sel_bytes.reshape(nq, -1, 16)

    got = native.inner_product(db, sel_bytes)
    want_np = xor_inner_product_np(db, packed)
    want_jax = np.asarray(xor_inner_product(db, packed))
    np.testing.assert_array_equal(got, want_np)
    np.testing.assert_array_equal(got, want_jax)


def test_native_keygen_batch_matches_numpy(monkeypatch):
    """The C++ AES-NI batch keygen (`native/keygen.cc`) must be
    bit-identical to the numpy engine on the same injected root seeds."""
    from distributed_point_functions_tpu.dpf import (
        DistributedPointFunction,
        DpfParameters,
    )
    from distributed_point_functions_tpu.value_types import XorType

    dpf = DistributedPointFunction.create(
        DpfParameters(log_domain_size=9, value_type=XorType(128))
    )
    rng = np.random.default_rng(31)
    n = 13
    alphas = [int(a) for a in rng.integers(0, 512, n)]
    betas = [1 << int(b) for b in rng.integers(0, 128, n)]
    seeds = rng.integers(0, 1 << 32, (2, n, 4), dtype=np.uint32)

    monkeypatch.setenv("DPF_NATIVE_KEYGEN", "1")
    nat0, nat1 = dpf.generate_keys_batch(alphas, betas, _root_seeds=seeds)
    monkeypatch.setenv("DPF_NATIVE_KEYGEN", "0")
    py0, py1 = dpf.generate_keys_batch(alphas, betas, _root_seeds=seeds)

    for a, b in zip(nat0 + nat1, py0 + py1):
        assert a.seed == b.seed and a.party == b.party
        assert a.last_level_value_correction == b.last_level_value_correction
        for ca, cb in zip(a.correction_words, b.correction_words):
            assert ca.seed == cb.seed
            assert ca.control_left == cb.control_left
            assert ca.control_right == cb.control_right
