"""Sparse key-value PIR through the production serving stack.

Pins the PR's parity contract: string-keyed lookups routed through the
DynamicBatcher and the Leader/Helper sessions must be bit-identical to
the seed's unbatched `CuckooHashingSparseDpfPirServer` oracle, absent
keys must resolve to the typed `KeyNotFound` (never a wrong value),
key-value write batches must land as SnapshotManager delta rotations
(`bytes_saved > 0`), a mis-rotated cuckoo geometry must raise
`SnapshotMismatch`, and the forced-8-device mesh path must match the
single-device one byte for byte.
"""

import threading
import time

import pytest

from distributed_point_functions_tpu.parallel.sharded import make_mesh
from distributed_point_functions_tpu.pir.cuckoo_database import (
    CuckooHashedDpfPirDatabase,
)
from distributed_point_functions_tpu.pir.sparse_client import (
    KeyNotFound,
)
from distributed_point_functions_tpu.pir.sparse_server import (
    CuckooHashingSparseDpfPirServer,
)
from distributed_point_functions_tpu.serving import (
    InProcessTransport,
    ServingConfig,
    SnapshotManager,
    SnapshotMismatch,
    SparseHelperSession,
    SparseLeaderSession,
    SparsePlainSession,
    make_sparse_client,
    sparse_lookup,
    sparse_lookup_plain,
)
from distributed_point_functions_tpu.serving.prober import Prober
from distributed_point_functions_tpu.testing import encrypt_decrypt

import jax

SEED = b"0123456789abcdef"
NUM_KEYS = 40

# Fixed-width keys and values: delta rotations preserve each dense
# store's packed row width, so goldens and upserts stay in-width.
RECORDS = {b"key_%02d" % i: b"val_%02d" % i for i in range(NUM_KEYS)}


def build_sparse(records=None, params=None, generation=0):
    records = RECORDS if records is None else records
    if params is None:
        params = CuckooHashingSparseDpfPirServer.generate_params(
            len(records), seed=SEED
        )
    builder = CuckooHashedDpfPirDatabase.Builder().set_params(params)
    builder.set_generation(generation)
    for kv in records.items():
        builder.insert(kv)
    return params, builder.build()


def make_config(**overrides):
    base = dict(
        max_batch_size=8,
        max_wait_ms=2.0,
        helper_timeout_ms=None,
        helper_retries=1,
        helper_backoff_ms=1.0,
        helper_backoff_max_ms=2.0,
    )
    base.update(overrides)
    return ServingConfig(**base)


QUERIES = [b"key_00", b"key_17", b"key_39", b"absent"]


def test_batched_plain_bit_identical_to_unbatched_oracle():
    """Every masked response through session+batcher must equal the
    seed's unbatched sparse server, byte for byte."""
    params, db = build_sparse()
    session = SparsePlainSession(params, db, make_config())
    oracle = CuckooHashingSparseDpfPirServer.create_plain(params, db)
    client = make_sparse_client(session)

    req0, req1 = client.create_plain_requests(QUERIES)
    for request in (req0, req1):
        batched = session.handle_request(request)
        unbatched = oracle.handle_plain_request(request)
        assert (
            batched.dpf_pir_response.masked_response
            == unbatched.dpf_pir_response.masked_response
        )


def test_plain_lookup_values_and_typed_absent():
    params, db = build_sparse()
    session = SparsePlainSession(params, db, make_config())
    client = make_sparse_client(session)
    out = sparse_lookup_plain(session, client, QUERIES)
    assert out[0] == b"val_00"
    assert out[1] == b"val_17"
    assert out[2] == b"val_39"
    assert isinstance(out[3], KeyNotFound)
    assert out[3].key == b"absent"
    assert not out[3]  # falsy: callers can branch on truthiness


def test_leader_helper_end_to_end():
    """Full two-party path: encrypted helper leg, one-time-pad unmask,
    XOR combine — values and typed absence both survive the trip."""
    params, db_h = build_sparse()
    _, db_l = build_sparse()
    helper = SparseHelperSession(
        params, db_h, encrypt_decrypt.decrypt, make_config()
    )
    leader = SparseLeaderSession(
        params,
        db_l,
        InProcessTransport(helper.handle_wire),
        make_config(),
    )
    client = make_sparse_client(leader, encrypter=encrypt_decrypt.encrypt)
    out = sparse_lookup(leader, client, QUERIES)
    assert out[:3] == [b"val_00", b"val_17", b"val_39"]
    assert isinstance(out[3], KeyNotFound)

    # The leader's combined responses must match an unbatched oracle
    # pair over the same two databases.
    oracle_h = CuckooHashingSparseDpfPirServer.create_helper(
        params, db_h, encrypt_decrypt.decrypt
    )

    def sender(helper_request, while_waiting):
        while_waiting()
        return oracle_h.handle_request(helper_request)

    oracle_l = CuckooHashingSparseDpfPirServer.create_leader(
        params, db_l, sender
    )
    request, state = client.create_request(QUERIES)
    got = leader.handle_request(request)
    want = oracle_l.handle_request(request)
    assert (
        got.dpf_pir_response.masked_response
        == want.dpf_pir_response.masked_response
    )
    assert client.resolve(want, state)[:3] == out[:3]


def test_write_batch_lands_as_delta_rotation_under_traffic():
    """Upsert build_from + SnapshotManager stage/flip while lookups
    hammer the session: prestage must be a delta (`bytes_saved > 0`),
    no query may ever see a torn generation, and post-flip lookups
    serve the new values."""
    params, db = build_sparse()
    session = SparsePlainSession(params, db, make_config())
    client = make_sparse_client(session)
    manager = SnapshotManager(session)

    # Warm the serving path first: the base generation's device
    # stagings must be resident for the rotation to prestage as a
    # delta (and the cold jit compile stays out of the timed window).
    warm = sparse_lookup_plain(session, client, [b"key_05"])
    assert warm[0] == b"val_05"

    stop = threading.Event()
    failures = []

    def traffic():
        while not stop.is_set():
            # A two-share plain lookup is two requests; pin the manager
            # so the flip cannot land between them (cross-generation
            # XOR is garbage by construction — same contract the prober
            # enforces for its golden pairs).
            with manager.pin():
                out = sparse_lookup_plain(
                    session, client, [b"key_05", b"absent"]
                )
            # key_05 is untouched by the write batch: either generation
            # serves val_05; absent stays typed-absent throughout.
            if out[0] != b"val_05" or not isinstance(
                out[1], KeyNotFound
            ):
                failures.append(out)
                return
            # Leave unpinned windows so the armed flip can land at a
            # batch boundary (a zero-gap pin loop would starve it).
            time.sleep(0.05)

    threads = [threading.Thread(target=traffic) for _ in range(2)]
    for t in threads:
        t.start()
    try:
        builder = CuckooHashedDpfPirDatabase.Builder()
        builder.insert((b"key_03", b"VAL_03"))  # rewrite
        builder.insert((b"new_01", b"val_99"))  # insert
        db1 = builder.build_from(db)
        assert db1.generation == 1
        assert db1.size == NUM_KEYS + 1
        staged = manager.stage(db1)
        assert staged > 0
        stats = db1.last_prestage_stats
        assert stats is not None and stats["mode"] == "delta"
        assert stats["bytes_saved"] > 0
        assert (
            stats["bytes_staged"] + stats["bytes_saved"]
            == stats["bytes_full_image"]
        )
        manager.flip(timeout=60.0)
    finally:
        stop.set()
        for t in threads:
            t.join(timeout=30)
    assert not failures, failures[:1]
    assert manager.serving_generation() == 1

    out = sparse_lookup_plain(
        session, client, [b"key_03", b"new_01", b"key_07", b"absent"]
    )
    assert out[0] == b"VAL_03"
    assert out[1] == b"val_99"
    assert out[2] == b"val_07"
    assert isinstance(out[3], KeyNotFound)


def test_mis_rotated_geometry_raises_snapshot_mismatch():
    """A snapshot built under different cuckoo params (other seed =
    other hash functions = other bucket mapping) must be rejected as
    `SnapshotMismatch`, not served — clients hash against the serving
    geometry, so silently swapping it in would answer garbage."""
    params, db = build_sparse()
    session = SparsePlainSession(params, db, make_config())
    manager = SnapshotManager(session)

    wrong_params = CuckooHashingSparseDpfPirServer.generate_params(
        NUM_KEYS, seed=b"fedcba9876543210"
    )
    _, wrong_db = build_sparse(params=wrong_params, generation=1)
    with pytest.raises(SnapshotMismatch):
        manager.stage(wrong_db)

    # A dense snapshot is just as unservable on a sparse session.
    from distributed_point_functions_tpu.pir.database import (
        DenseDpfPirDatabase,
    )

    dense_builder = DenseDpfPirDatabase.Builder()
    for i in range(NUM_KEYS):
        dense_builder.insert(b"rec_%02d" % i)
    with pytest.raises(SnapshotMismatch):
        manager.stage(dense_builder.build())

    assert session.server.database is db  # still serving generation 0


def test_mesh_sparse_session_matches_single_device():
    """SparsePlainSession over a forced 8-device mesh answers byte-
    identically to the single-device session (and to the unbatched
    oracle) — the batcher seam must not disturb the sharded path."""
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 devices")
    mesh = make_mesh(8)
    params, db = build_sparse()
    single = SparsePlainSession(params, db, make_config())
    sharded = SparsePlainSession(params, db, make_config(), mesh=mesh)
    client = make_sparse_client(single)

    req0, req1 = client.create_plain_requests(QUERIES)
    oracle = CuckooHashingSparseDpfPirServer.create_plain(params, db)
    for request in (req0, req1):
        a = single.handle_request(request)
        b = sharded.handle_request(request)
        want = oracle.handle_plain_request(request)
        assert (
            a.dpf_pir_response.masked_response
            == b.dpf_pir_response.masked_response
        )
        assert (
            b.dpf_pir_response.masked_response
            == want.dpf_pir_response.masked_response
        )

    out = sparse_lookup_plain(sharded, client, QUERIES)
    assert out[:3] == [b"val_00", b"val_17", b"val_39"]
    assert isinstance(out[3], KeyNotFound)


def test_sparse_prober_kinds_pass_and_follow_rotation():
    """sparse_kv + sparse_absent probe kinds run clean against a live
    session, goldens follow a delta rotation via bind_snapshots, and a
    served wrong-generation would be caught (forced here by rotating
    the goldens without rotating the database)."""
    params, db = build_sparse()
    session = SparsePlainSession(params, db, make_config())
    prober = Prober(session, sparse_records=RECORDS, period_s=0.1)
    assert prober.kinds() == ["sparse_kv", "sparse_absent"]

    results = prober.run_cycle()
    assert [r["status"] for r in results] == ["pass", "pass"]
    fresh = prober.freshness()
    assert all(v["identity"] for v in fresh.values())

    # Rotate one golden's value (key_00 is the first sorted golden).
    manager = SnapshotManager(session)
    new_records = dict(RECORDS)
    new_records[b"key_00"] = b"VAL_XX"
    prober.bind_snapshots(manager, lambda gen: new_records)
    builder = CuckooHashedDpfPirDatabase.Builder()
    builder.insert((b"key_00", b"VAL_XX"))
    manager.stage(builder.build_from(db))
    manager.flip(timeout=60.0)

    results = prober.run_cycle()
    assert [r["status"] for r in results] == ["pass", "pass"]
    export = prober.export()
    assert export["mismatches"] == 0 and export["errors"] == 0
    assert export["generation"] == 1

    # Desync the oracle on purpose: the kv probe must catch it.
    prober.rotate_sparse_goldens({b"key_00": b"val_ZZ"})
    bad = [r for r in prober.run_cycle() if r["kind"] == "sparse_kv"]
    assert bad[0]["status"] == "mismatch"


def test_sparse_session_admission_prices_sparse_workload():
    """The session installs the sparse pricer: admission sees two
    dense inner products per key and the cost ledger joins batches
    under the "sparse" workload."""
    params, db = build_sparse()
    session = SparsePlainSession(
        params, db, make_config(admission_enabled=True)
    )
    assert session.admission is not None
    pricer = session.admission._pricer
    assert pricer is not None
    cost = pricer(4)
    assert cost.unit == "sparse_keys"
    # Uncorrected ratio on a fresh model (the process-wide default
    # model may already carry observed-cost corrections from earlier
    # traffic in this test run — that feedback is the point of the
    # per-workload ledger, so don't assert through it).
    from distributed_point_functions_tpu.capacity.model import (
        CapacityModel,
    )

    model = CapacityModel()
    sparse = model.price_sparse_pir_keys(
        4, num_blocks=db.num_selection_blocks
    )
    dense = model.price_pir_keys(4, num_blocks=db.num_selection_blocks)
    assert sparse.device_ms == pytest.approx(2.0 * dense.device_ms)
    assert sparse.bytes_peak == dense.bytes_peak

    client = make_sparse_client(session)
    out = sparse_lookup_plain(session, client, [b"key_01", b"absent"])
    assert out[0] == b"val_01"
    assert isinstance(out[1], KeyNotFound)
