"""Overload chaos suite: shed-early admission, QoS fairness, brownout.

The contract under test, end to end:

* a scripted 2x over-capacity load keeps goodput (completed-in-deadline
  work per second) within 70% of the saturation throughput measured in
  the *same run* — overload costs the excess, never the service;
* the excess is shed AT ADMISSION with a `retry_after_s` hint, before
  any device work runs on its behalf;
* backlogged tenants drain in proportion to their weights (within 15%);
* the brownout ladder engages under a breach signal, actually moves the
  serving knobs (admission floor, batch cap, PIR tier floor), and fully
  reverts when the breach clears;
* and — the chaos invariant — every response a client actually receives
  under overload is bit-identical to the fault-free oracle. Sheds may
  cost retries; they may never corrupt bytes.

The throughput-shaped tests run the real `DynamicBatcher` +
`AdmissionController` over a stub evaluator with a deterministic
per-key service time, so capacity is exact and no JAX timing noise
enters the measurement. The bit-identity and wire tests run the real
serving sessions.
"""

import json
import threading
import time

import numpy as np
import pytest

from distributed_point_functions_tpu.capacity import (
    AdmissionController,
    BrownoutController,
    CapacityModel,
    TenantPolicy,
    ThroughputCalibration,
)
from distributed_point_functions_tpu.pir import (
    DenseDpfPirClient,
    DenseDpfPirDatabase,
    DenseDpfPirServer,
)
from distributed_point_functions_tpu.pir import server as pir_server
from distributed_point_functions_tpu.serving import (
    HelperSession,
    HelperUnavailable,
    InProcessTransport,
    LeaderSession,
    Overloaded,
    PlainSession,
    ServingConfig,
)
from distributed_point_functions_tpu.serving.batcher import DynamicBatcher
from distributed_point_functions_tpu.serving.metrics import MetricsRegistry
from distributed_point_functions_tpu.serving.transport import (
    TransportTimeout,
)
from distributed_point_functions_tpu.testing import encrypt_decrypt

NUM_RECORDS = 128
RECORD_BYTES = 16
RNG = np.random.default_rng(99)


def build_database():
    records = [
        bytes(RNG.integers(0, 256, RECORD_BYTES, dtype=np.uint8))
        for _ in range(NUM_RECORDS)
    ]
    builder = DenseDpfPirDatabase.Builder()
    for r in records:
        builder.insert(r)
    return builder.build(), records


DATABASE, RECORDS = build_database()


def exact_model(tmp_path, qps):
    """A capacity model whose serving throughput is pinned to the stub
    evaluator's real service rate, via a throwaway calibration file."""
    path = tmp_path / "history.jsonl"
    path.write_text(
        json.dumps(
            {"metric": "serving_closed_loop_queries_per_sec", "value": qps}
        )
        + "\n"
    )
    return CapacityModel(
        device_memory_bytes=16 << 30,
        calibration=ThroughputCalibration(str(path)),
    )


@pytest.fixture(autouse=True)
def _clear_global_tier_floor():
    yield
    pir_server.clear_tier_floor()


# ---------------------------------------------------------------------------
# Goodput under 2x overload >= 70% of same-run saturation, shed early
# ---------------------------------------------------------------------------


def test_goodput_survives_2x_overload_with_early_shed(tmp_path):
    # Stub service: 1 ms per key => capacity is exactly 1000 keys/s.
    def evaluate(keys):
        time.sleep(len(keys) * 0.001)
        return list(keys)

    adm = AdmissionController(
        exact_model(tmp_path, qps=1000.0),
        queue_budget_ms=60.0,
        metrics=MetricsRegistry(),
    )
    batcher = DynamicBatcher(
        evaluate,
        max_batch_size=16,
        max_wait_ms=0.5,
        max_queue=100_000,
        metrics=adm.metrics,
        admission=adm,
    )
    keys_per_request = 8
    lock = threading.Lock()
    stats = {"ok_keys": 0, "shed": 0, "bad_hints": 0, "deadline": 0}

    def run_phase(num_threads, duration_s):
        with lock:
            stats.update(ok_keys=0, shed=0, bad_hints=0, deadline=0)
        stop = time.monotonic() + duration_s
        def worker(i):
            while time.monotonic() < stop:
                payload = [f"t{i}"] * keys_per_request
                try:
                    out = batcher.submit(
                        payload, deadline=time.monotonic() + 0.5
                    )
                    assert out == payload
                    with lock:
                        stats["ok_keys"] += keys_per_request
                except Overloaded as e:
                    with lock:
                        stats["shed"] += 1
                        if e.retry_after_s <= 0 or e.reason is None:
                            stats["bad_hints"] += 1
                    # The client contract: honor the hint.
                    time.sleep(min(e.retry_after_s, 0.05))
                except Exception:
                    with lock:
                        stats["deadline"] += 1
        threads = [
            threading.Thread(target=worker, args=(i,))
            for i in range(num_threads)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        return dict(stats)

    try:
        # Phase 1 — saturation: 6 closed-loop threads x 8 keys sit just
        # under the 60 ms cost budget, so (almost) nothing sheds.
        sat = run_phase(num_threads=6, duration_s=1.0)
        saturation_kps = sat["ok_keys"] / 1.0
        # Phase 2 — 2x the threads: the queued-cost estimate now
        # overflows the budget and the excess must shed at admission.
        over = run_phase(num_threads=12, duration_s=1.0)
        goodput_kps = over["ok_keys"] / 1.0
    finally:
        batcher.close()

    assert saturation_kps > 0
    assert goodput_kps >= 0.70 * saturation_kps, (
        f"goodput collapsed under overload: {goodput_kps:.0f} keys/s vs "
        f"saturation {saturation_kps:.0f} keys/s"
    )
    assert over["shed"] > 0, "2x overload shed nothing"
    assert over["bad_hints"] == 0, "a shed lacked retry_after_s/reason"
    counters = adm.metrics.export()["counters"]
    # Shed-early: every refusal happened at admission (batcher shed
    # counter), none after queuing (no expired-in-batch deadline work).
    assert counters["batcher.requests_shed"] >= over["shed"]
    assert counters.get("batcher.expired_in_batch", 0) == 0
    shed_reasons = {
        k: v for k, v in counters.items()
        if k.startswith("admission.shed{")
    }
    assert sum(shed_reasons.values()) >= over["shed"]


# ---------------------------------------------------------------------------
# Weighted-fair shares across backlogged tenants, within 15%
# ---------------------------------------------------------------------------


def test_weighted_fair_shares_hold_within_15_percent(tmp_path):
    def evaluate(keys):
        time.sleep(len(keys) * 0.001)
        return list(keys)

    adm = AdmissionController(
        exact_model(tmp_path, qps=1000.0), queue_budget_ms=10_000.0
    )
    weights = {"gold": 3.0, "silver": 2.0, "bronze": 1.0}
    for tenant, w in weights.items():
        adm.set_tenant(tenant, TenantPolicy(weight=w))
    # max_batch_size=1: each dequeue is one service, so completion
    # counts measure the WFQ's dequeue order directly.
    batcher = DynamicBatcher(
        evaluate,
        max_batch_size=1,
        max_wait_ms=0.0,
        max_queue=100_000,
        admission=adm,
    )
    served = {t: 0 for t in weights}
    lock = threading.Lock()
    stop = time.monotonic() + 1.5

    def worker(tenant):
        while time.monotonic() < stop:
            batcher.submit([tenant], tenant=tenant)
            with lock:
                served[tenant] += 1

    try:
        threads = [
            threading.Thread(target=worker, args=(t,))
            for t in weights for _ in range(3)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
    finally:
        batcher.close()

    total = sum(served.values())
    total_w = sum(weights.values())
    assert total > 200, f"too few services to judge fairness: {served}"
    for tenant, w in weights.items():
        share = served[tenant] / total
        expected = w / total_w
        assert share == pytest.approx(expected, rel=0.15), (
            f"{tenant}: share {share:.3f}, expected {expected:.3f} "
            f"(served={served})"
        )


# ---------------------------------------------------------------------------
# Pre-dispatch deadline gate: expired work never reaches the evaluator
# ---------------------------------------------------------------------------


def test_expired_requests_dropped_before_dispatch():
    calls = []
    gate = threading.Event()

    def evaluate(keys):
        calls.append(list(keys))
        if keys[0] == "blocker":
            gate.wait(2.0)
        return list(keys)

    batcher = DynamicBatcher(
        evaluate, max_batch_size=4, max_wait_ms=1.0, metrics=MetricsRegistry()
    )
    try:
        blocker = threading.Thread(
            target=lambda: batcher.submit(["blocker"])
        )
        blocker.start()
        time.sleep(0.05)  # the worker is now inside evaluate()

        # Queued behind the blocker: one request that will expire before
        # the worker gets to it, one with no deadline.
        doomed_err = []
        def doomed():
            try:
                batcher.submit(["doomed"], deadline=time.monotonic() + 0.05)
            except Exception as e:  # noqa: BLE001
                doomed_err.append(e)
        survivor_out = []
        t1 = threading.Thread(target=doomed)
        t2 = threading.Thread(
            target=lambda: survivor_out.append(batcher.submit(["survivor"]))
        )
        t1.start()
        t2.start()
        t1.join(1.0)  # expires while the blocker batch is still running
        time.sleep(0.05)  # clear margin past the doomed deadline
        gate.set()
        t2.join(5.0)
        blocker.join(5.0)
    finally:
        gate.set()
        batcher.close()

    assert type(doomed_err[0]).__name__ == "DeadlineExceeded"
    assert survivor_out == [["survivor"]]
    # The evaluator saw the blocker and the survivor — never the
    # expired request's key.
    assert ["doomed"] not in calls
    assert all("doomed" not in batch for batch in calls)
    counters = batcher.metrics.export()["counters"]
    assert counters["batcher.expired_in_batch"] == 1


def test_all_dead_batch_skips_dispatch_entirely():
    calls = []
    gate = threading.Event()

    def evaluate(keys):
        calls.append(list(keys))
        if keys[0] == "blocker":
            gate.wait(2.0)
        return list(keys)

    batcher = DynamicBatcher(
        evaluate, max_batch_size=4, max_wait_ms=1.0, metrics=MetricsRegistry()
    )
    try:
        blocker = threading.Thread(
            target=lambda: batcher.submit(["blocker"])
        )
        blocker.start()
        time.sleep(0.05)
        t1 = threading.Thread(
            target=lambda: pytest.raises(
                Exception,
                batcher.submit,
                ["doomed"],
                deadline=time.monotonic() + 0.05,
            )
        )
        t1.start()
        t1.join(1.0)
        time.sleep(0.1)  # the doomed request is now expired in queue
        gate.set()
        blocker.join(5.0)
        deadline = time.monotonic() + 5.0
        while (
            batcher.metrics.export()["counters"].get(
                "batcher.batches_skipped_dead", 0
            ) < 1
            and time.monotonic() < deadline
        ):
            time.sleep(0.01)
    finally:
        gate.set()
        batcher.close()

    counters = batcher.metrics.export()["counters"]
    assert counters["batcher.batches_skipped_dead"] == 1
    assert calls == [["blocker"]]


# ---------------------------------------------------------------------------
# Brownout ladder drives the real serving knobs, and fully reverts
# ---------------------------------------------------------------------------


def test_brownout_ladder_moves_serving_knobs_and_reverts():
    breaching = [True]
    config = ServingConfig(
        max_batch_size=4, max_wait_ms=1.0, admission_enabled=True
    )
    with PlainSession(DATABASE, config) as session:
        session.set_tenant("batch", TenantPolicy(priority=0))
        brownout = session.attach_brownout(
            BrownoutController(
                signal=lambda: breaching[0],
                engage_after_s=0.0,
                escalate_after_s=0.0,
                revert_after_s=0.0,
                metrics=session.metrics,
            ),
            batch_cap=2,
            cheap_tier="streaming",
        )
        adm = session.admission

        assert brownout.evaluate() == 1  # shed_low_priority
        assert adm.min_priority == 1
        client = DenseDpfPirClient.create(NUM_RECORDS, lambda pt, ci: pt)
        request = client.create_plain_requests([7])[0]
        with pytest.raises(Overloaded) as exc_info:
            session.handle_request(request, tenant="batch")
        assert exc_info.value.reason == "priority"
        assert exc_info.value.retry_after_s > 0

        assert brownout.evaluate() == 2  # cap_batches
        assert session.batcher._batch_cap == 2
        assert brownout.evaluate() == 3  # force_cheap_tier
        assert pir_server.tier_floor() == "streaming"
        assert brownout.evaluate() == 4  # critical_only
        assert adm.min_priority == 2
        with pytest.raises(Overloaded):
            session.handle_request(request)  # default tenant: priority 1

        # Load drops: the ladder walks all the way back down and every
        # knob returns to its pre-brownout value.
        breaching[0] = False
        for want in (3, 2, 1, 0):
            assert brownout.evaluate() == want
        assert adm.min_priority == 0
        assert session.batcher._batch_cap is None
        assert pir_server.tier_floor() == "materialized"

        # ...and the previously-shed tenant serves again, correctly.
        response = session.handle_request(request, tenant="batch")
        oracle = DenseDpfPirServer.create_plain(DATABASE)
        want = oracle.handle_plain_request(request)
        assert (
            response.dpf_pir_response.masked_response
            == want.dpf_pir_response.masked_response
        )
        counters = session.metrics.export()["counters"]
        assert counters["brownout.engaged{step=critical_only}"] == 1
        assert counters["brownout.reverted{step=shed_low_priority}"] == 1


# ---------------------------------------------------------------------------
# Bit-identity: every response served under overload matches the oracle
# ---------------------------------------------------------------------------


def test_overloaded_session_responses_bit_identical_to_oracle():
    client = DenseDpfPirClient.create(NUM_RECORDS, lambda pt, ci: pt)
    indices = [3, 17, 42, 77, 99, 101, 5, 64]
    requests = {i: client.create_plain_requests([i])[0] for i in indices}
    oracle_server = DenseDpfPirServer.create_plain(DATABASE)
    oracle = {
        i: oracle_server.handle_plain_request(
            requests[i]
        ).dpf_pir_response.masked_response
        for i in indices
    }

    config = ServingConfig(
        max_batch_size=4, max_wait_ms=2.0, admission_enabled=True
    )
    with PlainSession(DATABASE, config) as session:
        # A tight quota forces real sheds mid-run; clients retry with
        # the server's hint until served.
        session.set_tenant(
            "bursty", TenantPolicy(rate_qps=30.0, burst=2.0)
        )
        results = {}
        errors = []
        lock = threading.Lock()

        def worker(slot, index):
            tenant = "bursty" if slot % 2 == 0 else "default"
            for _ in range(400):
                try:
                    response = session.handle_request(
                        requests[index], tenant=tenant
                    )
                    with lock:
                        results[(slot, index)] = (
                            response.dpf_pir_response.masked_response
                        )
                    return
                except Overloaded as e:
                    time.sleep(max(e.retry_after_s, 1e-3))
                except Exception as e:  # noqa: BLE001
                    with lock:
                        errors.append(e)
                    return
            with lock:
                errors.append(RuntimeError(f"slot {slot} never served"))

        threads = [
            threading.Thread(target=worker, args=(slot, index))
            for slot, index in enumerate(indices * 2)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(120.0)
        counters = session.metrics.export()["counters"]

    assert not errors, errors
    assert len(results) == len(indices) * 2
    for (_slot, index), masked in results.items():
        assert masked == oracle[index], f"index {index} corrupted"
    # The run actually overloaded: the quota shed at least once.
    assert counters.get("plain.admission.shed{reason=quota}", 0) > 0


# ---------------------------------------------------------------------------
# Typed Overloaded/RetryAfter over the wire (Leader <- Helper)
# ---------------------------------------------------------------------------


def test_helper_shed_travels_to_leader_as_typed_overloaded():
    helper_config = ServingConfig(
        max_batch_size=4, max_wait_ms=1.0, admission_enabled=True
    )
    helper = HelperSession(
        DATABASE, encrypt_decrypt.decrypt, helper_config
    )
    # One token, near-zero refill: the first leader request drains the
    # helper's quota, the second is shed over the wire.
    helper.admission.set_tenant(
        "default", TenantPolicy(rate_qps=0.01, burst=1.0)
    )
    leader_config = ServingConfig(
        max_batch_size=4, max_wait_ms=1.0, helper_retries=2,
        helper_backoff_ms=1.0, helper_backoff_max_ms=2.0,
    )
    client = DenseDpfPirClient.create(NUM_RECORDS, encrypt_decrypt.encrypt)
    with LeaderSession(
        DATABASE, InProcessTransport(helper.handle_wire), leader_config
    ) as leader:
        request, state = client.create_request([11])
        response = leader.handle_request(request)
        plaintexts = client.handle_response(response, state)
        assert plaintexts == [RECORDS[11]]

        second, _ = client.create_request([23])
        with pytest.raises(Overloaded) as exc_info:
            leader.handle_request(second)
        assert exc_info.value.reason == "helper_overloaded"
        assert exc_info.value.retry_after_s > 0
        counters = leader.metrics.export()["counters"]
        # A typed refusal is not a helper fault: no retries burned, no
        # breaker failures, and the helper answered in-protocol.
        assert counters["leader.helper_overloaded"] == 1
        assert counters.get("leader.helper_retries", 0) == 0
        assert leader.breaker.state == "closed"
    helper_counters = helper.metrics.export()["counters"]
    assert helper_counters["helper.wire_overloads"] == 1
    assert helper_counters["helper.admission.shed{reason=quota}"] == 1
    helper.close()


# ---------------------------------------------------------------------------
# Helper-leg retry budget caps retry amplification
# ---------------------------------------------------------------------------


class DeadTransport:
    """Every round trip times out; the retry ladder alone decides how
    many attempts the Leader burns."""

    def __init__(self):
        self.attempts = 0

    def roundtrip(self, data, timeout=None, on_sent=None):
        self.attempts += 1
        if on_sent is not None:
            on_sent()
        raise TransportTimeout("dead transport")

    def close(self):
        pass


def test_retry_budget_exhaustion_stops_the_ladder():
    transport = DeadTransport()
    config = ServingConfig(
        max_batch_size=4, max_wait_ms=1.0,
        helper_retries=50,  # the ladder would allow 50 retries...
        helper_backoff_ms=0.1, helper_backoff_max_ms=0.2,
        helper_retry_budget_min=3.0,  # ...but the budget allows 3
        breaker_enabled=False,
    )
    client = DenseDpfPirClient.create(
        NUM_RECORDS, encrypt_decrypt.encrypt
    )
    with LeaderSession(DATABASE, transport, config) as leader:
        request, _ = client.create_request([9])
        with pytest.raises(HelperUnavailable) as exc_info:
            leader.handle_request(request)
        assert "retry budget exhausted" in str(exc_info.value)
        counters = leader.metrics.export()["counters"]
        gauges = leader.metrics.export()["gauges"]
    # 1 initial attempt + 3 budgeted retries, not 51 attempts.
    assert transport.attempts == 4
    assert counters["leader.retries_budget_exhausted"] == 1
    assert counters["leader.helper_retries"] == 3
    assert gauges["leader.retry_budget_tokens"] == 0.0
