"""Differential tests for the plane-resident dense-PIR expansion
(`pir/dense_eval_planes.py`) against the per-level limb kernel — the two
implementations must be bit-identical for both parties across shapes,
including non-multiple-of-32 key counts and databases mesh-padded past
the tree's leaf capacity.
"""

import numpy as np
import pytest

from distributed_point_functions_tpu.pir.client import DenseDpfPirClient
from distributed_point_functions_tpu.pir.dense_eval import (
    evaluate_selection_blocks,
    stage_keys,
)
from distributed_point_functions_tpu.pir.dense_eval_planes import (
    bitrev_permutation,
    evaluate_selection_blocks_planes,
)

RNG = np.random.default_rng(99)


def _split(client, num_blocks):
    total = client._dpf._tree_levels_needed - 1
    el = min(max(0, (num_blocks - 1).bit_length()), total)
    return total - el, el


@pytest.mark.parametrize(
    "num_records,nq",
    [
        (1024, 7),    # walk > 0, keys need padding to 32
        (512, 64),    # exact key-group multiple
        (300, 3),     # tiny: 3 blocks, expand < 2 levels
        (128, 1),     # single block, expand_levels == 0
    ],
)
def test_planes_matches_limb(num_records, nq):
    num_blocks = (num_records + 127) // 128
    client = DenseDpfPirClient.create(num_records, lambda pt, ci: pt)
    indices = [int(i) for i in RNG.integers(0, num_records, nq)]
    wl, el = _split(client, num_blocks)
    for keys in client._generate_key_pairs(indices):
        staged = stage_keys(keys)
        a = np.asarray(
            evaluate_selection_blocks(
                *staged,
                walk_levels=wl, expand_levels=el, num_blocks=num_blocks,
            )
        )
        b = np.asarray(
            evaluate_selection_blocks_planes(
                *staged,
                walk_levels=wl, expand_levels=el, num_blocks=num_blocks,
                force_planes=True,
            )
        )
        np.testing.assert_array_equal(a, b)


def test_planes_pads_beyond_tree_capacity():
    """num_blocks beyond 2^expand_levels (mesh-padded database) must
    yield zero selection blocks, like the limb path."""
    num_records, nq = 300, 4  # tree capacity 4 blocks
    client = DenseDpfPirClient.create(num_records, lambda pt, ci: pt)
    indices = [0, 1, 150, 299]
    wl, el = _split(client, 4)
    keys0, _ = client._generate_key_pairs(indices)
    staged = stage_keys(keys0)
    a = np.asarray(
        evaluate_selection_blocks(
            *staged, walk_levels=wl, expand_levels=el, num_blocks=8
        )
    )
    b = np.asarray(
        evaluate_selection_blocks_planes(
            *staged, walk_levels=wl, expand_levels=el, num_blocks=8,
            force_planes=True,
        )
    )
    np.testing.assert_array_equal(a, b)
    assert not a[:, 4:, :].any()


def test_bitrev_leaves_mode():
    """bitrev_leaves=True returns the plane-order leaves: natural block g
    at position bitrev(g), full 2^expand_levels width."""
    num_records, nq = 512, 8
    num_blocks = num_records // 128
    client = DenseDpfPirClient.create(num_records, lambda pt, ci: pt)
    indices = [int(i) for i in RNG.integers(0, num_records, nq)]
    wl, el = _split(client, num_blocks)
    keys0, _ = client._generate_key_pairs(indices)
    staged = stage_keys(keys0)
    natural = np.asarray(
        evaluate_selection_blocks_planes(
            *staged, walk_levels=wl, expand_levels=el,
            num_blocks=num_blocks,
        )
    )
    raw = np.asarray(
        evaluate_selection_blocks_planes(
            *staged, walk_levels=wl, expand_levels=el,
            num_blocks=num_blocks, bitrev_leaves=True,
        )
    )
    perm = bitrev_permutation(el)
    np.testing.assert_array_equal(raw[:, perm, :][:, :num_blocks], natural)


def test_bitrev_permutation_is_involution():
    for levels in range(0, 8):
        perm = bitrev_permutation(levels)
        np.testing.assert_array_equal(perm[perm], np.arange(1 << levels))


def test_dense_server_serves_via_planes(monkeypatch):
    """DPF_TPU_EXPANSION=planes routes the dense server through the
    plane-resident expansion with byte-identical responses."""
    from distributed_point_functions_tpu.pir import messages
    from distributed_point_functions_tpu.pir.database import (
        DenseDpfPirDatabase,
    )
    from distributed_point_functions_tpu.pir.server import DenseDpfPirServer

    num_records = 1000
    records = [RNG.bytes(20) for _ in range(num_records)]
    client = DenseDpfPirClient.create(num_records, lambda pt, ci: pt)
    keys0, _ = client._generate_key_pairs([5, 999, 123])
    req = messages.PirRequest(
        plain_request=messages.PlainRequest(dpf_keys=list(keys0))
    )
    server = DenseDpfPirServer.create_plain(DenseDpfPirDatabase(records))

    monkeypatch.setenv("DPF_TPU_EXPANSION", "limb")
    a = server.handle_request(req).dpf_pir_response.masked_response
    monkeypatch.setenv("DPF_TPU_EXPANSION", "planes")
    b = server.handle_request(req).dpf_pir_response.masked_response
    assert a == b
