"""TransferLedger: host<->device copy/byte accounting per phase.

Unit half: recording lands in the right phase bucket, readers and
export roll up, the registry mirror counts, and a disabled ledger is a
bare passthrough with no counters. Integration half: the staging paths
actually wired through the ledger — `dense_eval.stage_keys` and
`dpf.stage_key_batch` each cost exactly ONE h2d copy per batch (the
`value_types.host_const` batching contract), and database staging
lands in `db_staging`.
"""

import numpy as np
import pytest

from distributed_point_functions_tpu.observability.device import (
    DeviceTelemetry,
    TransferLedger,
    default_telemetry,
    set_default_telemetry,
)


@pytest.fixture
def telemetry():
    prev = default_telemetry()
    fresh = set_default_telemetry(DeviceTelemetry())
    try:
        yield fresh
    finally:
        set_default_telemetry(prev)


# ---------------------------------------------------------------------------
# Unit: recording, attribution, export
# ---------------------------------------------------------------------------


def test_records_land_in_the_right_phase():
    led = TransferLedger()
    led.record_h2d(1024, "key_staging")
    led.record_h2d(4096, "db_staging", copies=2)
    led.record_d2h(256, "result_readback")
    led.record_sync("db_staging")
    led.record_sync("key_staging", wait_ms=1.25)
    led.record_overlap(2.5, "db_staging")

    assert led.copies("key_staging") == 1
    assert led.copies("db_staging") == 2
    assert led.copies("result_readback") == 0
    assert led.copies() == 3
    assert led.bytes_h2d("key_staging") == 1024
    assert led.bytes_h2d() == 5120
    assert led.syncs("db_staging") == 1
    assert led.syncs() == 2
    assert led.overlapped_ms("db_staging") == 2.5
    assert led.overlapped_ms("key_staging") == 0.0
    assert led.sync_wait_ms("key_staging") == 1.25
    assert led.sync_wait_ms("db_staging") == 0.0
    assert led.sync_wait_ms() == 1.25

    export = led.export()
    assert export["enabled"] is True
    assert export["totals"] == {
        "h2d_copies": 3, "h2d_bytes": 5120,
        "d2h_copies": 1, "d2h_bytes": 256, "syncs": 2,
        "sync_wait_ms": 1.25, "overlapped_ms": 2.5,
    }
    assert export["phases"]["result_readback"]["d2h_bytes"] == 256
    assert export["phases"]["db_staging"]["syncs"] == 1
    assert export["phases"]["db_staging"]["overlapped_ms"] == 2.5
    assert export["phases"]["key_staging"]["syncs"] == 1
    assert export["phases"]["key_staging"]["sync_wait_ms"] == 1.25


def test_wrappers_count_and_preserve_values():
    led = TransferLedger()
    x = np.arange(8, dtype=np.uint32)
    dev = led.device_put(x, phase="key_staging")
    np.testing.assert_array_equal(np.asarray(dev), x)
    host = led.to_host(dev, phase="result_readback")
    np.testing.assert_array_equal(host, x)
    led.block_until_ready(dev, phase="key_staging")

    export = led.export()
    assert export["phases"]["key_staging"]["h2d_copies"] == 1
    assert export["phases"]["key_staging"]["h2d_bytes"] == x.nbytes
    assert export["phases"]["key_staging"]["syncs"] == 1
    assert export["phases"]["result_readback"]["d2h_copies"] == 1
    assert export["phases"]["result_readback"]["d2h_bytes"] == x.nbytes


def test_device_put_counts_a_pytree_once():
    led = TransferLedger()
    tree = {"a": np.zeros(4, np.uint32), "b": [np.zeros(2, np.uint32)]}
    led.device_put(tree, phase="key_staging")
    assert led.copies("key_staging") == 1
    assert led.bytes_h2d("key_staging") == 16 + 8


def test_disabled_ledger_is_bare_passthrough():
    led = TransferLedger(enabled=False)
    led.record_h2d(1024, "key_staging")
    led.record_d2h(256, "result_readback")
    led.record_sync("db_staging", wait_ms=3.0)
    led.record_overlap(5.0, "db_staging")
    x = np.ones(4, np.uint32)
    dev = led.device_put(x, phase="key_staging")
    led.block_until_ready(dev, phase="key_staging")
    np.testing.assert_array_equal(led.to_host(dev, phase="r"), x)

    export = led.export()
    assert export["enabled"] is False
    assert export["phases"] == {}
    assert led.copies() == 0
    assert led.bytes_h2d() == 0


def test_registry_mirror_counts():
    from distributed_point_functions_tpu.serving.metrics import (
        MetricsRegistry,
    )

    reg = MetricsRegistry()
    led = TransferLedger(registry=reg)
    led.record_h2d(100, "key_staging", copies=3)
    led.record_sync("db_staging")
    counters = reg.export()["counters"]
    h2d = {k: v for k, v in counters.items() if "h2d_copies" in k}
    assert sum(h2d.values()) == 3
    assert any("key_staging" in k for k in h2d)
    assert any("sync_waits" in k for k in counters)


def test_reset_clears_phases():
    led = TransferLedger()
    led.record_h2d(10, "key_staging")
    led.reset()
    assert led.copies() == 0
    assert led.export()["phases"] == {}


def test_default_telemetry_carries_a_ledger(telemetry):
    assert isinstance(telemetry.transfers, TransferLedger)
    telemetry.transfers.record_h2d(1, "db_staging")
    assert default_telemetry().transfers.copies("db_staging") == 1
    assert "transfers" in telemetry.export()


# ---------------------------------------------------------------------------
# Integration: the staging paths cost ONE copy per batch
# ---------------------------------------------------------------------------


def test_stage_keys_is_a_single_h2d_copy(telemetry):
    """`dense_eval.stage_keys` packs every key block into one flat
    uint32 array and one `device_put` (the `value_types.host_const`
    batching contract)."""
    from distributed_point_functions_tpu.pir import DenseDpfPirClient
    from distributed_point_functions_tpu.pir.dense_eval import stage_keys

    client = DenseDpfPirClient.create(256, lambda pt, ci: pt)
    keys = next(iter(client._generate_key_pairs([3, 99])))
    ledger = telemetry.transfers
    ledger.reset()
    staged = stage_keys(keys)
    assert ledger.copies("key_staging") == 1
    assert ledger.copies() == 1
    assert ledger.bytes_h2d("key_staging") == sum(
        np.asarray(a).nbytes for a in staged
    )


def test_stage_key_batch_is_a_single_h2d_copy(telemetry):
    """`dpf.stage_key_batch` takes the same single-transfer fast path
    for uniform uint32 key material."""
    from distributed_point_functions_tpu.dpf import (
        DistributedPointFunction,
        DpfParameters,
    )
    from distributed_point_functions_tpu.value_types import IntType

    params = [DpfParameters(i, IntType(32)) for i in range(1, 5)]
    d = DistributedPointFunction.create_incremental(params)
    k0, k1 = d.generate_keys_incremental(3, [1, 1, 1, 1])
    ledger = telemetry.transfers
    ledger.reset()
    d.stage_key_batch([k0, k1])
    assert ledger.copies("key_staging") == 1
    assert ledger.copies() == 1


def test_database_staging_attributes_to_db_staging(telemetry):
    from distributed_point_functions_tpu.pir import DenseDpfPirDatabase

    builder = DenseDpfPirDatabase.Builder()
    for i in range(32):
        builder.insert(bytes([i]) * 8)
    database = builder.build()
    ledger = telemetry.transfers
    ledger.reset()
    _ = database.db_words  # first touch stages the database
    assert ledger.copies("db_staging") >= 1
    assert ledger.copies("key_staging") == 0
    assert ledger.bytes_h2d("db_staging") > 0
