"""Workload observatory tests: sketch/top-K correctness against exact
oracles, Zipf-fit recovery of the generator's ground-truth exponent,
bounded memory under key floods, the serving hot-path hook, and the
`/workloadz` admin surface.

Sketch tests use deterministic seeds so the probabilistic error bounds
are asserted exactly (same stream every run); generator tests pin the
`uniform` profile to the retired `overload_bench` pool byte-for-byte —
the history-continuity invariant the profile handoff depends on.
"""

import collections
import json
import urllib.error
import urllib.request

import pytest

from benchmarks import workload_gen
from distributed_point_functions_tpu.observability import AdminServer
from distributed_point_functions_tpu.observability.timeseries import (
    TimeSeriesStore,
)
from distributed_point_functions_tpu.observability.workload import (
    CountMinSketch,
    TopKTracker,
    WorkloadObservatory,
    detect_periodicity,
    fit_zipf_exponent,
)
from distributed_point_functions_tpu.serving.metrics import MetricsRegistry


class FakeClock:
    def __init__(self, t=0.0):
        self.t = t

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


def _zipf_stream(s=1.1, domain=4096, n=100_000, seed=7):
    profile = workload_gen.WorkloadProfile(name="z", zipf_s=s)
    return workload_gen.zipf_stream(profile, domain, n, seed=seed)


# ---------------------------------------------------------------------------
# Count-min sketch
# ---------------------------------------------------------------------------


class TestCountMinSketch:
    def test_never_undershoots_and_bounds_overshoot_under_flood(self):
        """Adversarial flood: a huge churn of one-off keys tries to
        smear every counter. Estimates must stay >= truth and within
        the Cormode-Muthukrishnan overshoot ceiling."""
        sketch = CountMinSketch(width=256, depth=4, seed=3)
        truth = collections.Counter()
        tracked = [11, 222, 3333, 44444]
        for i, key in enumerate(tracked):
            for _ in range(100 * (i + 1)):
                sketch.add(key)
                truth[key] += 1
        # The flood: 50k distinct keys, one observation each.
        for key in range(10**6, 10**6 + 50_000):
            sketch.add(key)
            truth[key] += 1
        bound = sketch.error_bound()
        assert bound == pytest.approx(
            2.718281828 * sketch.total / 256, rel=1e-6
        )
        for key in tracked + list(range(10**6, 10**6 + 100)):
            estimate = sketch.estimate(key)
            assert estimate >= truth[key]
            assert estimate - truth[key] <= bound

    def test_unseen_key_estimate_is_pure_collision_noise(self):
        sketch = CountMinSketch(width=1024, depth=4, seed=0)
        for key in range(1000):
            sketch.add(key)
        assert sketch.estimate(999_999_999) <= sketch.error_bound()

    def test_export_shape_and_validation(self):
        sketch = CountMinSketch(width=64, depth=2, seed=1)
        sketch.add(5, count=3)
        state = sketch.export()
        assert state["width"] == 64 and state["depth"] == 2
        assert state["total"] == 3
        assert 0 < state["fill_pct"] <= 100
        with pytest.raises(ValueError):
            CountMinSketch(width=4)
        with pytest.raises(ValueError):
            CountMinSketch(depth=0)


# ---------------------------------------------------------------------------
# Top-K + Zipf fit against exact oracles
# ---------------------------------------------------------------------------


class TestTopKTracker:
    def test_agrees_with_exact_oracle_on_zipf_stream(self):
        """10^5 synthetic Zipf draws: the space-saving table's head
        must match an exact Counter's head."""
        stream = _zipf_stream(s=1.1, n=100_000)
        tracker = TopKTracker(64)
        truth = collections.Counter()
        for key in stream:
            tracker.add(key)
            truth[key] += 1
        exact_top10 = [k for k, _ in truth.most_common(10)]
        tracked = {k: (c, e) for k, c, e in tracker.items()}
        # Every true top-10 key is tracked, with count within the
        # Metwally bound: true <= tracked <= true + error.
        for key in exact_top10:
            assert key in tracked
            count, error = tracked[key]
            assert truth[key] <= count <= truth[key] + error
        # The table's own top-5 is exactly the true top-5 (order-free).
        table_top5 = {k for k, _, _ in tracker.items()[:5]}
        assert table_top5 == set(exact_top10[:5])

    def test_capacity_never_exceeded(self):
        tracker = TopKTracker(8)
        for key in range(1000):
            tracker.add(key)
        assert len(tracker) == 8

    def test_zipf_fit_recovers_generator_exponent(self):
        """Satellite (d): fitted exponent within +-0.1 of the
        generator's ground truth, via the full observatory path."""
        for s in (0.9, 1.1, 1.3):
            stream = _zipf_stream(s=s, n=100_000, seed=11)
            observatory = WorkloadObservatory(top_k=64)
            for key in stream:
                observatory.observe(key_indices=(key,))
            fitted = observatory.zipf_exponent()
            assert fitted == pytest.approx(s, abs=0.1), (s, fitted)

    def test_zipf_fit_degenerate_inputs(self):
        assert fit_zipf_exponent([]) is None
        assert fit_zipf_exponent([5.0, 4.0]) is None  # < min_points
        # Uniform counts: no spread, exponent ~ 0.
        assert fit_zipf_exponent([7.0] * 20) == pytest.approx(0.0)


# ---------------------------------------------------------------------------
# Periodicity
# ---------------------------------------------------------------------------


class TestPeriodicity:
    def test_detects_sinusoid_period(self):
        import math

        step_s, lag = 10.0, 8
        values = [
            100 + 50 * math.sin(2 * math.pi * i / lag) for i in range(64)
        ]
        found = detect_periodicity(values, step_s)
        assert found is not None
        assert found["period_s"] == pytest.approx(lag * step_s, abs=step_s)
        assert found["strength"] >= 0.4

    def test_flat_and_short_series_yield_none(self):
        assert detect_periodicity([5.0] * 64, 10.0) is None
        assert detect_periodicity([1.0, 2.0, 3.0], 10.0) is None


# ---------------------------------------------------------------------------
# Observatory: hot path, bounded memory, export
# ---------------------------------------------------------------------------


class TestWorkloadObservatory:
    def test_memory_bounded_under_key_and_tenant_flood(self):
        """The fixed-byte-budget acceptance check: 10^5 distinct keys
        and hundreds of tenant names must not grow the footprint."""
        budget = 256 * 1024
        observatory = WorkloadObservatory(byte_budget=budget)

        def flood(start, n):
            for i in range(start, start + n):
                observatory.observe(
                    num_keys=1 + i % 7,
                    tenant=f"tenant-{i % 400}",
                    key_indices=(i,),
                    deadline_s=0.001 * (i % 500),
                )

        flood(0, 1_000)  # tenant table + sketch at steady state
        plateau = observatory.approx_bytes()
        flood(1_000, 99_000)
        assert observatory.approx_bytes() == plateau  # flat, not just bounded
        assert observatory.approx_bytes() <= budget
        state = observatory.export()
        assert state["within_budget"] is True
        assert state["observations"] == 100_000
        # Tenant table clamped: max_tenants plus the overflow bucket.
        assert len(state["tenants"]) <= 16 + 1
        assert "__other__" in state["tenants"]

    def test_rate_and_burstiness_with_fake_clock(self):
        clock = FakeClock()
        observatory = WorkloadObservatory(ewma_alpha=0.3, clock=clock)
        for _ in range(200):
            clock.advance(0.01)  # steady 100 q/s
            observatory.observe()
        state = observatory.export()
        assert state["rate_qps"] == pytest.approx(100.0, rel=0.05)
        assert state["burstiness_cv2"] == pytest.approx(0.0, abs=0.05)

    def test_deadline_and_batch_histograms(self):
        observatory = WorkloadObservatory()
        observatory.observe(num_keys=3, deadline_s=0.040)
        observatory.observe(num_keys=1000, deadline_s=9.0)
        state = observatory.export()
        assert state["batch_keys"]["buckets"]["4"] == 1
        assert state["batch_keys"]["buckets"]["+inf"] == 1
        assert state["deadline_ms"]["buckets"]["50"] == 1
        assert state["deadline_ms"]["buckets"]["+inf"] == 1
        assert state["deadline_ms"]["count"] == 2

    def test_hot_share_on_skewed_stream(self):
        observatory = WorkloadObservatory(top_k=32)
        for key in _zipf_stream(s=1.3, domain=1024, n=20_000):
            observatory.observe(key_indices=(key,))
        hot = observatory.hot_share_pct()
        assert hot is not None and hot > 50.0

    def test_gauge_source_binds_registry(self):
        registry = MetricsRegistry()
        observatory = WorkloadObservatory(registry=registry)
        for i in range(50):
            observatory.observe(key_indices=(i % 5,))
        series = observatory.gauge_source()
        assert "workload.observations" in series
        assert registry.export()["gauges"]["workload.observations"] == 50.0

    def test_periodicity_reads_coarse_tier(self):
        import math

        clock = FakeClock()
        store = TimeSeriesStore(
            tiers=((1.0, 60), (10.0, 360)), clock=clock
        )
        period_s = 80.0
        for i in range(240):
            clock.advance(10.0)
            store.record(
                "workload.rate_qps",
                100 + 50 * math.sin(2 * math.pi * clock.t / period_s),
            )
        observatory = WorkloadObservatory(store=store, clock=clock)
        found = observatory.periodicity()
        assert found is not None
        assert found["period_s"] == pytest.approx(period_s, abs=10.0)


# ---------------------------------------------------------------------------
# Workload generator (benchmarks/workload_gen.py)
# ---------------------------------------------------------------------------


class TestWorkloadGenerator:
    def test_uniform_pool_reproduces_legacy_overload_bench_pool(self):
        """History-continuity invariant: `--profile uniform` must build
        the exact pool the retired inline generator built."""
        import numpy as np

        for num_records in (256, 1024, 4096):
            legacy = [
                int(i)
                for i in np.random.default_rng(8).integers(
                    0, num_records, 32
                )
            ]
            assert workload_gen.key_pool(
                workload_gen.PROFILES["uniform"], num_records
            ) == legacy

    def test_zipf_pool_skewed_and_deterministic(self):
        profile = workload_gen.PROFILES["zipf"]
        pool_a = workload_gen.key_pool(profile, 4096)
        pool_b = workload_gen.key_pool(profile, 4096)
        assert pool_a == pool_b  # seeded
        assert len(pool_a) == profile.pool_size
        # Skew: duplicates appear in a 64-draw pool under Zipf 1.1.
        assert len(set(pool_a)) < len(pool_a)

    def test_arrival_times_diurnal_and_bursty(self):
        diurnal = workload_gen.PROFILES["diurnal"]
        times = workload_gen.arrival_times(
            diurnal, duration_s=60.0, base_rate_qps=50.0, seed=5
        )
        assert times == sorted(times)
        assert all(0 <= t < 60.0 for t in times)
        # Sinusoidal envelope: the peak half hosts more arrivals.
        peak = sum(1 for t in times if t < 30.0)
        trough = len(times) - peak
        assert peak > trough
        bursty = workload_gen.PROFILES["bursty"]
        burst_times = workload_gen.arrival_times(
            bursty, duration_s=30.0, base_rate_qps=50.0, seed=5
        )
        # Poisson bursts inject back-to-back duplicates.
        repeats = sum(
            1
            for a, b in zip(burst_times, burst_times[1:])
            if a == b
        )
        assert repeats >= bursty.burst_size

    def test_tenant_mix_sampling_follows_weights(self):
        import random

        profile = workload_gen.PROFILES["mixed"]
        rng = random.Random(0)
        draws = collections.Counter(
            workload_gen.pick_tenant(profile, rng).name
            for _ in range(6000)
        )
        assert draws["interactive"] > draws["standard"] > draws["batch"]


# ---------------------------------------------------------------------------
# /workloadz admin surface
# ---------------------------------------------------------------------------


class TestWorkloadzEndpoint:
    def test_text_json_and_404(self):
        observatory = WorkloadObservatory()
        for key in _zipf_stream(s=1.1, domain=512, n=5_000):
            observatory.observe(
                key_indices=(key,), tenant="probe", deadline_s=0.1
            )
        with AdminServer(
            registry=MetricsRegistry(), workload=observatory
        ) as admin:
            base = f"http://127.0.0.1:{admin.port}"
            text = urllib.request.urlopen(base + "/workloadz").read()
            assert b"workload observatory" in text
            assert b"sketch:" in text
            assert b"per-tenant:" in text
            state = json.loads(
                urllib.request.urlopen(
                    base + "/workloadz?format=json"
                ).read()
            )
            assert state["observations"] == 5_000
            assert state["top_keys"]
            assert state["tenants"]["probe"]["observations"] == 5_000
            # Folded into /statusz as well.
            status = urllib.request.urlopen(base + "/statusz").read()
            assert b"Workload" in status
        with AdminServer(registry=MetricsRegistry()) as admin:
            with pytest.raises(urllib.error.HTTPError) as err:
                urllib.request.urlopen(
                    f"http://127.0.0.1:{admin.port}/workloadz"
                )
            assert err.value.code == 404
