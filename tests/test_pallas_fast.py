"""Fast-tier Pallas kernel signal: tiny-shape interpret-mode differential
cases of every serving kernel family (level / path / tail / v2 inner
product), sized for the presubmit's <3 min budget.

The full differential sweeps live in `tests/test_expand_pallas.py` and
`tests/test_pallas.py`; this module exists because a presubmit whose fast
tier skips every Pallas kernel is blind to the code the serving path
actually runs (VERDICT r03). Twins are jitted — an eager bitsliced-AES
twin pays thousands of per-op CPU dispatches and would blow the budget.
"""

import functools

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from distributed_point_functions_tpu import keys as fixed_keys
from distributed_point_functions_tpu.ops.aes_bitslice import (
    mmo_hash_planes,
)
from distributed_point_functions_tpu.ops.expand_planes_pallas import (
    expand_level_planes_pallas,
    expand_tail_planes_pallas,
    path_level_planes_pallas,
    value_hash_planes_pallas,
)
from distributed_point_functions_tpu.pir.dense_eval_planes import (
    _tile_keys,
    expand_level_planes,
    pack_key_bits,
    pack_key_planes,
)

RNG = np.random.default_rng(71)


def _inputs(g, nk):
    state = RNG.integers(0, 1 << 32, (16, 8, g), dtype=np.uint32)
    ctrl = RNG.integers(0, 1 << 32, (g,), dtype=np.uint32)
    cw = RNG.integers(0, 1 << 32, (nk, 4), dtype=np.uint32)
    cwl = RNG.integers(0, 2, (nk,), dtype=np.uint32)
    cwr = RNG.integers(0, 2, (nk,), dtype=np.uint32)
    return state, ctrl, cw, cwl, cwr


def test_level_kernel_tiny():
    g, nk = 2, 64
    state, ctrl, cw, cwl, cwr = _inputs(g, nk)
    cwp_kg = pack_key_planes(jnp.asarray(cw))
    cwl_kg = pack_key_bits(jnp.asarray(cwl))
    cwr_kg = pack_key_bits(jnp.asarray(cwr))
    want_s, want_c = jax.jit(expand_level_planes)(
        jnp.asarray(state), jnp.asarray(ctrl),
        _tile_keys(cwp_kg, 2 * g), _tile_keys(cwl_kg, g),
        _tile_keys(cwr_kg, g),
    )
    got_s, got_c = expand_level_planes_pallas(
        jnp.asarray(state), jnp.asarray(ctrl), cwp_kg, cwl_kg, cwr_kg,
        interpret=True,
    )
    np.testing.assert_array_equal(np.asarray(got_s), np.asarray(want_s))
    np.testing.assert_array_equal(np.asarray(got_c), np.asarray(want_c))


def test_value_kernel_tiny():
    g, nk = 2, 64
    state, ctrl, cw, _, _ = _inputs(g, nk)
    vc_kg = pack_key_planes(jnp.asarray(cw))

    @jax.jit
    def twin(state, ctrl, vc):
        out = mmo_hash_planes(fixed_keys.RK_VALUE, state)
        return out ^ (_tile_keys(vc, g) & ctrl[None, None, :])

    want = twin(jnp.asarray(state), jnp.asarray(ctrl), vc_kg)
    got = value_hash_planes_pallas(
        jnp.asarray(state), jnp.asarray(ctrl), vc_kg, interpret=True
    )
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_path_kernel_tiny():
    from distributed_point_functions_tpu.ops.aes_bitslice import (
        aes_rounds_select_planes,
        sigma_planes,
    )

    g, nk = 2, 64
    state, ctrl, cw, cwl, cwr = _inputs(g, nk)
    sel = RNG.integers(0, 1 << 32, (g,), dtype=np.uint32)
    cwp = pack_key_planes(jnp.asarray(cw))
    cwlb = pack_key_bits(jnp.asarray(cwl))
    cwrb = pack_key_bits(jnp.asarray(cwr))

    @jax.jit
    def twin(state, ctrl, sel, cwp, cwlb, cwrb):
        sig = sigma_planes(state)
        h = aes_rounds_select_planes(
            fixed_keys.RK_LEFT, fixed_keys.RK_RIGHT, sel, sig
        ) ^ sig
        h = h ^ (_tile_keys(cwp, g) & ctrl[None, None, :])
        t_new = h[0, 0]
        out_s = h.at[0, 0].set(jnp.zeros_like(t_new))
        cw_dir = (sel & _tile_keys(cwrb, g)) | (~sel & _tile_keys(cwlb, g))
        return out_s, t_new ^ (ctrl & cw_dir)

    want_s, want_c = twin(
        jnp.asarray(state), jnp.asarray(ctrl), jnp.asarray(sel),
        cwp, cwlb, cwrb,
    )
    got_s, got_c = path_level_planes_pallas(
        jnp.asarray(state), jnp.asarray(ctrl), jnp.asarray(sel),
        cwp, cwlb, cwrb, per_seed=False, interpret=True,
    )
    np.testing.assert_array_equal(np.asarray(got_s), np.asarray(want_s))
    np.testing.assert_array_equal(np.asarray(got_c), np.asarray(want_c))


def test_tail_kernel_tiny():
    """One fused tail level + value hash over two tiles — the multi-tile
    assembly and the in-kernel doubling at minimum interpret cost."""
    g0, nk, r, tile = 4, 32, 1, 2
    state, ctrl, cw, cwl, cwr = _inputs(g0, nk)
    cwp_kg = pack_key_planes(jnp.asarray(cw))[None]
    cwl_kg = pack_key_bits(jnp.asarray(cwl))[None]
    cwr_kg = pack_key_bits(jnp.asarray(cwr))[None]
    vc = RNG.integers(0, 1 << 32, (nk, 4), dtype=np.uint32)
    vc_kg = pack_key_planes(jnp.asarray(vc))

    @functools.partial(jax.jit, static_argnames=("lo",))
    def twin_tile(state, ctrl, cwp, cwlb, cwrb, vc, lo):
        s = jax.lax.slice_in_dim(state, lo, lo + tile, axis=2)
        c = jax.lax.slice_in_dim(ctrl, lo, lo + tile)
        s, c = expand_level_planes(
            s, c, _tile_keys(cwp[0], 2 * tile), _tile_keys(cwlb[0], tile),
            _tile_keys(cwrb[0], tile),
        )
        v = mmo_hash_planes(fixed_keys.RK_VALUE, s) ^ (
            _tile_keys(vc, s.shape[-1]) & c[None, None, :]
        )
        return v, c

    want_v, want_c = [], []
    for lo in range(0, g0, tile):
        v, c = twin_tile(
            jnp.asarray(state), jnp.asarray(ctrl), cwp_kg, cwl_kg,
            cwr_kg, vc_kg, lo,
        )
        want_v.append(np.asarray(v))
        want_c.append(np.asarray(c))
    got_v, got_c = expand_tail_planes_pallas(
        jnp.asarray(state), jnp.asarray(ctrl), cwp_kg, cwl_kg, cwr_kg,
        vc_kg, tile_lanes=tile, interpret=True,
    )
    np.testing.assert_array_equal(
        np.asarray(got_v), np.concatenate(want_v, axis=-1)
    )
    np.testing.assert_array_equal(
        np.asarray(got_c), np.concatenate(want_c)
    )


def test_head_kernel_tiny():
    """The fused head (first r levels, one launch) is bit-identical to
    sequential XLA levels — no exit permutation, single tile."""
    from distributed_point_functions_tpu.ops.expand_planes_pallas import (
        expand_head_planes_pallas,
    )

    g0, nk, r = 2, 64, 2
    state, ctrl, _, _, _ = _inputs(g0, nk)
    cwp = [
        pack_key_planes(jnp.asarray(
            RNG.integers(0, 1 << 32, (nk, 4), dtype=np.uint32)
        ))
        for _ in range(r)
    ]
    cwl = [
        pack_key_bits(jnp.asarray(
            RNG.integers(0, 2, (nk,), dtype=np.uint32)
        ))
        for _ in range(r)
    ]
    cwr = [
        pack_key_bits(jnp.asarray(
            RNG.integers(0, 2, (nk,), dtype=np.uint32)
        ))
        for _ in range(r)
    ]

    @jax.jit
    def twin(s, c, cwp_st, cwl_st, cwr_st):
        for i in range(r):
            g2 = 2 * s.shape[-1]
            s, c = expand_level_planes(
                s, c, _tile_keys(cwp_st[i], g2),
                _tile_keys(cwl_st[i], g2 // 2),
                _tile_keys(cwr_st[i], g2 // 2),
            )
        return s, c

    want_s, want_c = twin(
        jnp.asarray(state), jnp.asarray(ctrl), jnp.stack(cwp),
        jnp.stack(cwl), jnp.stack(cwr),
    )
    got_s, got_c = expand_head_planes_pallas(
        jnp.asarray(state), jnp.asarray(ctrl), jnp.stack(cwp),
        jnp.stack(cwl), jnp.stack(cwr), interpret=True,
    )
    np.testing.assert_array_equal(np.asarray(got_s), np.asarray(want_s))
    np.testing.assert_array_equal(np.asarray(got_c), np.asarray(want_c))


def test_head_split_policy():
    """_head_split: honors the env override even unverified (forced A/B
    legs), requires verification in auto, caps by VMEM lanes, and never
    returns a 1-level head."""
    from distributed_point_functions_tpu.pir import dense_eval_planes as dep

    # Unverified auto -> no head.
    assert dep._head_split(4, 13) == 0
    # Explicit env override works unverified (clamped to a_levels).
    import os

    os.environ["DPF_TPU_HEAD_LEVELS"] = "6"
    try:
        assert dep._head_split(4, 13) == 6
        assert dep._head_split(4, 3) == 3
    finally:
        del os.environ["DPF_TPU_HEAD_LEVELS"]
    # Verified auto: fill until the 2048-lane cap (kg=4 -> 9 levels).
    old_v, old_f = dep._HEAD_KERNEL_VERIFIED, dep._HEAD_KERNEL_FAILED
    dep._HEAD_KERNEL_VERIFIED, dep._HEAD_KERNEL_FAILED = True, False
    try:
        assert dep._head_split(4, 13) == 9
        assert dep._head_split(4, 2) == 2
        assert dep._head_split(2048, 5) == 0  # cap below 2 levels
        # A remembered failure disables the auto head.
        dep._HEAD_KERNEL_FAILED = True
        assert dep._head_split(4, 13) == 0
    finally:
        dep._HEAD_KERNEL_VERIFIED, dep._HEAD_KERNEL_FAILED = old_v, old_f


@pytest.mark.parametrize("num_words", [2, 16])
def test_ip_v2_tiny(num_words):
    """v2 MXU inner product at a narrow (j_chunk=1 regression shape) and
    a regular width."""
    from distributed_point_functions_tpu.ops.inner_product import (
        pack_selection_bits_np,
        xor_inner_product_np,
    )
    from distributed_point_functions_tpu.ops.inner_product_pallas import (
        permute_db_bitmajor,
        xor_inner_product_pallas2_staged,
    )

    num_records, nq = 512, 4
    db = RNG.integers(0, 1 << 32, (num_records, num_words), dtype=np.uint32)
    bits = RNG.integers(0, 2, (nq, num_records), dtype=np.uint32)
    sel = pack_selection_bits_np(bits)
    db_perm = np.asarray(permute_db_bitmajor(db))
    got = np.asarray(
        xor_inner_product_pallas2_staged(db_perm, sel, interpret=True)
    )
    np.testing.assert_array_equal(got, xor_inner_product_np(db, sel))


def test_tail_failure_demotes_tail_mode(monkeypatch):
    """A remembered tail failure must demote tail mode everywhere —
    FAILED wins over a stale VERIFIED flag, in both the eager self-check
    and the traced-context branch (ADVICE r03 medium)."""
    from distributed_point_functions_tpu.pir import dense_eval_planes as dep

    monkeypatch.setattr(dep, "_TAIL_KERNEL_VERIFIED", True)
    monkeypatch.setattr(dep, "_TAIL_KERNEL_FAILED", True)
    assert dep._tail_kernel_selfcheck() is False

    monkeypatch.setenv("DPF_TPU_LEVEL_KERNEL", "auto")
    monkeypatch.setattr(dep.jax, "default_backend", lambda: "tpu")
    monkeypatch.setattr(dep, "_LEVEL_KERNEL_FAILED", False)
    monkeypatch.setattr(dep, "_LEVEL_KERNEL_VERIFIED", True)
    monkeypatch.setattr(dep, "_trace_state_clean", lambda: False)
    assert dep._level_kernel_enabled() == "pallas"
    monkeypatch.setattr(dep, "_TAIL_KERNEL_FAILED", False)
    assert dep._level_kernel_enabled() == "tail"


@pytest.mark.parametrize(
    "value_hash,unroll", [(True, True), (False, False)]
)
def test_walk_descend_kernel_tiny(value_hash, unroll):
    """Fixed-width walk-descent vs the doubling expansion: 2 levels from
    2 entry nodes, natural leaf order (the doubling twin's [all-left;
    all-right] order is mapped through tail_node_permutation)."""
    from distributed_point_functions_tpu.ops.expand_planes_pallas import (
        tail_node_permutation,
        walk_descend_planes_pallas,
    )

    nk, r = 32, 2
    kg = 1
    n_entry = 2
    g0 = n_entry * kg
    state, ctrl, cw, cwl, cwr = _inputs(g0, nk)
    cwp_all = jnp.stack(
        [pack_key_planes(jnp.asarray(
            RNG.integers(0, 1 << 32, (nk, 4), dtype=np.uint32)
        )) for _ in range(r)]
    )
    cwl_all = jnp.stack(
        [pack_key_bits(jnp.asarray(
            RNG.integers(0, 2, (nk,), dtype=np.uint32)
        )) for _ in range(r)]
    )
    cwr_all = jnp.stack(
        [pack_key_bits(jnp.asarray(
            RNG.integers(0, 2, (nk,), dtype=np.uint32)
        )) for _ in range(r)]
    )
    vc = pack_key_planes(jnp.asarray(
        RNG.integers(0, 1 << 32, (nk, 4), dtype=np.uint32)
    ))

    @jax.jit
    def twin_doubling(state, ctrl):
        s, c = jnp.asarray(state), jnp.asarray(ctrl)
        for i in range(r):
            g2 = 2 * s.shape[-1]
            s, c = expand_level_planes(
                s, c, _tile_keys(cwp_all[i], g2),
                _tile_keys(cwl_all[i], g2 // 2),
                _tile_keys(cwr_all[i], g2 // 2),
            )
        if value_hash:
            s = mmo_hash_planes(fixed_keys.RK_VALUE, s) ^ (
                _tile_keys(vc, s.shape[-1]) & c[None, None, :]
            )
        return s, c

    want_s, want_c = twin_doubling(state, ctrl)
    # Map the doubling twin's global [all-left; all-right] node order to
    # the walk kernel's natural leaf order.
    order, _ = tail_node_permutation(np.arange(n_entry), r, n_entry)
    pos_of_leaf = np.argsort(order)
    lane_gather = (
        pos_of_leaf[:, None] * kg + np.arange(kg)[None, :]
    ).reshape(-1)
    want_s = np.asarray(want_s)[:, :, lane_gather]
    want_c = np.asarray(want_c)[lane_gather]

    got_s, got_c = walk_descend_planes_pallas(
        jnp.asarray(state), jnp.asarray(ctrl), cwp_all, cwl_all,
        cwr_all, vc if value_hash else None, r=r,
        tile_lanes=g0 << r, value_hash=value_hash, unroll=unroll,
        interpret=True,
    )
    np.testing.assert_array_equal(np.asarray(got_s), want_s)
    np.testing.assert_array_equal(np.asarray(got_c), want_c)


def test_kernel_verdict_cache_roundtrip(tmp_path, monkeypatch):
    """A recorded Mosaic failure verdict must be re-applied in a fresh
    process (simulated by resetting the flags + the loaded marker):
    re-attempting a known-failing kernel compile costs minutes of
    remote-compile on hardware, which the persistent cache exists to
    skip."""
    from distributed_point_functions_tpu.pir import dense_eval_planes as dep

    cache = tmp_path / "verdicts.json"
    monkeypatch.setenv("DPF_TPU_VERDICT_CACHE", str(cache))
    monkeypatch.setattr(dep, "_LAST_RECORDED", None)
    monkeypatch.setattr(dep, "_TAIL_KERNEL_FAILED", True)
    monkeypatch.setattr(dep, "_HEAD_KERNEL_VERIFIED", True)
    monkeypatch.setattr(dep, "_LEVEL_KERNEL_VERIFIED", True)
    dep.record_kernel_verdicts()
    assert cache.exists()

    # "Fresh process": all flags cleared, loader not yet run.
    for flag in dep._VERDICT_FLAGS:
        monkeypatch.setattr(dep, flag, False)
    monkeypatch.setattr(dep, "_VERDICTS_LOADED", False)
    dep._load_kernel_verdicts()
    assert dep._TAIL_KERNEL_FAILED is True
    assert dep._HEAD_KERNEL_VERIFIED is True
    assert dep._LEVEL_KERNEL_VERIFIED is True
    # Never-set flags stay clear.
    assert dep._LEVEL_KERNEL_FAILED is False
    assert dep._HEAD_KERNEL_FAILED is False

    # Suspended recording must not leak speculative flags to disk.
    monkeypatch.setattr(dep, "_WALK_KERNEL_FAILED", True)
    with dep.suspend_verdict_recording():
        dep.record_kernel_verdicts()
    monkeypatch.setattr(dep, "_WALK_KERNEL_FAILED", False)
    monkeypatch.setattr(dep, "_VERDICTS_LOADED", False)
    dep._load_kernel_verdicts()
    assert dep._WALK_KERNEL_FAILED is False

    # A second record merges (does not clear) earlier verdicts.
    monkeypatch.setattr(dep, "_HEAD_KERNEL_FAILED", True)
    dep.record_kernel_verdicts()
    monkeypatch.setattr(dep, "_VERDICTS_LOADED", False)
    monkeypatch.setattr(dep, "_TAIL_KERNEL_FAILED", False)
    dep._load_kernel_verdicts()
    assert dep._TAIL_KERNEL_FAILED is True
    assert dep._HEAD_KERNEL_FAILED is True
