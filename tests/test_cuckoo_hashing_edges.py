"""Edge cases of the cuckoo hashing layer underpinning sparse PIR.

The serving path trusts this layer twice over: `Builder.build()` for
the initial assignment and `Builder.build_from()` for delta builds that
preseed a prior layout and insert only new keys. These tests pin the
corner behaviors those paths depend on — eviction/relocation, the
lazily-rehashed preseeded slot, the bounded-stash failure mode,
duplicate-key upsert semantics, empty builds, and determinism of
`generate_params` under a fixed seed.
"""

import pytest

from distributed_point_functions_tpu.hashing import (
    CuckooHashTable,
    create_hash_family_from_config,
)
from distributed_point_functions_tpu.hashing.hash_family import (
    create_hash_functions,
)
from distributed_point_functions_tpu.pir.cuckoo_database import (
    CuckooHashedDpfPirDatabase,
)
from distributed_point_functions_tpu.pir.sparse_server import (
    CuckooHashingSparseDpfPirServer,
)

SEED = b"0123456789abcdef"


def make_hash_functions(num=3, num_elements=8):
    params = CuckooHashingSparseDpfPirServer.generate_params(
        num_elements, seed=SEED
    )
    family = create_hash_family_from_config(params.hash_family_config)
    return create_hash_functions(family, num), params


def test_insert_relocates_on_collision():
    """Force every key into one bucket: the eviction loop must still
    place all of them (each key has several candidate buckets)."""
    hash_functions, _ = make_hash_functions()
    table = CuckooHashTable(
        hash_functions, num_buckets=64, max_relocations=128
    )
    keys = [b"k%02d" % i for i in range(32)]
    for key in keys:
        table.insert(key)
    placed = [e for e in table.get_table() if e is not None]
    assert sorted(placed) + sorted(table.get_stash()) == sorted(
        placed + table.get_stash()
    )
    assert sorted(placed + table.get_stash()) == sorted(keys)
    # With generous buckets and relocations nothing should stash.
    assert table.get_stash() == []


def test_stash_overflow_raises():
    """max_stash_size=0 turns placement failure into a hard error —
    the database builder relies on this instead of silently dropping
    keys (a dropped key would serve not-found for a present record)."""
    hash_functions, _ = make_hash_functions(num=2)
    table = CuckooHashTable(
        hash_functions, num_buckets=2, max_relocations=4, max_stash_size=0
    )
    with pytest.raises(RuntimeError, match="stash is full"):
        # 2 hash functions over 2 buckets hold at most 2 elements;
        # the third must fail.
        for i in range(8):
            table.insert(b"key%d" % i)


def test_unbounded_stash_absorbs_overflow():
    hash_functions, _ = make_hash_functions(num=2)
    table = CuckooHashTable(
        hash_functions, num_buckets=2, max_relocations=4
    )
    keys = [b"key%d" % i for i in range(6)]
    for key in keys:
        table.insert(key)
    placed = [e for e in table.get_table() if e is not None]
    assert sorted(placed + table.get_stash()) == sorted(keys)
    assert len(table.get_stash()) >= 4


def test_preseeded_slot_rehashes_lazily_on_eviction():
    """A preseeded element stores no bucket tuple; evicting it must
    rehash it to a legal candidate bucket, not crash or misplace it.
    This is the exact path `Builder.build_from` takes when a new key
    lands on an old key's bucket."""
    hash_functions, _ = make_hash_functions()
    probe = CuckooHashTable(hash_functions, num_buckets=16,
                            max_relocations=64)
    old_key = b"old_key"
    candidates = {fn(old_key, 16) for fn in hash_functions}

    for target in sorted(candidates):
        table = CuckooHashTable(
            hash_functions, num_buckets=16, max_relocations=64
        )
        table.preseed(target, old_key)
        # Fill every OTHER candidate bucket of old_key with preseeded
        # squatters so that, once evicted, it must hop until it finds a
        # free candidate (exercising multiple relocation hops).
        for i in range(64):
            filler = b"filler%02d" % i
            table.insert(filler)
        layout = table.get_table()
        placed = [e for e in layout if e is not None]
        assert old_key in placed + table.get_stash()
        if old_key in placed:
            bucket = layout.index(old_key)
            assert bucket in candidates, (
                f"evicted preseeded key rehashed to non-candidate "
                f"bucket {bucket} (candidates {sorted(candidates)})"
            )
    del probe


def test_preseed_validates_bucket():
    hash_functions, _ = make_hash_functions()
    table = CuckooHashTable(hash_functions, num_buckets=4,
                            max_relocations=8)
    with pytest.raises(ValueError, match="out of range"):
        table.preseed(4, b"x")
    with pytest.raises(ValueError, match="out of range"):
        table.preseed(-1, b"x")
    table.preseed(1, b"x")
    with pytest.raises(ValueError, match="already occupied"):
        table.preseed(1, b"y")


def test_constructor_validation():
    hash_functions, _ = make_hash_functions()
    with pytest.raises(ValueError, match="num_buckets"):
        CuckooHashTable(hash_functions, 0, 8)
    with pytest.raises(ValueError, match="at least 2"):
        CuckooHashTable(hash_functions[:1], 4, 8)
    with pytest.raises(ValueError, match="max_relocations"):
        CuckooHashTable(hash_functions, 4, -1)
    with pytest.raises(ValueError, match="max_stash_size"):
        CuckooHashTable(hash_functions, 4, 8, max_stash_size=-1)


def test_table_layout_deterministic_for_fixed_inputs():
    """Two tables built from identical inputs must produce identical
    layouts (fixed rng_seed) — delta builds and probers depend on
    reproducible assignment."""
    hash_functions, _ = make_hash_functions()
    keys = [b"key_%02d" % i for i in range(24)]
    layouts = []
    for _ in range(2):
        table = CuckooHashTable(
            hash_functions, num_buckets=36, max_relocations=64
        )
        for key in keys:
            table.insert(key)
        layouts.append(table.get_table())
    assert layouts[0] == layouts[1]


def test_generate_params_deterministic_under_fixed_seed():
    a = CuckooHashingSparseDpfPirServer.generate_params(100, seed=SEED)
    b = CuckooHashingSparseDpfPirServer.generate_params(100, seed=SEED)
    assert a == b  # frozen dataclasses: field-wise equality
    assert a.num_buckets == b.num_buckets
    assert a.hash_family_config.seed == b.hash_family_config.seed
    # Without a pinned seed each call draws a fresh family seed.
    c = CuckooHashingSparseDpfPirServer.generate_params(100)
    d = CuckooHashingSparseDpfPirServer.generate_params(100)
    assert c.hash_family_config.seed != d.hash_family_config.seed


def test_duplicate_key_insert_upserts():
    """Builder.insert of the same key twice keeps ONE slot with the
    last value (dict upsert) — the table never holds a key twice."""
    params = CuckooHashingSparseDpfPirServer.generate_params(
        4, seed=SEED
    )
    builder = CuckooHashedDpfPirDatabase.Builder().set_params(params)
    builder.insert((b"dup", b"first"))
    builder.insert((b"other", b"o"))
    builder.insert((b"dup", b"second"))
    db = builder.build()
    assert db.size == 2
    occupied = [s for s in db.slots if s is not None]
    assert sorted(occupied) == [b"dup", b"other"]
    bucket = db.slots.index(b"dup")
    value = db.value_database.record(bucket)
    assert value[: len(b"second")] == b"second"
    assert all(byte == 0 for byte in value[len(b"second"):])


def test_empty_build_rejected():
    """An empty table build: generate_params(0) is invalid, and a
    builder with params for n>0 but zero records still produces a
    well-formed (all-vacant) database."""
    with pytest.raises(ValueError, match="num_elements"):
        CuckooHashingSparseDpfPirServer.generate_params(0, seed=SEED)
    params = CuckooHashingSparseDpfPirServer.generate_params(
        4, seed=SEED
    )
    db = CuckooHashedDpfPirDatabase.Builder().set_params(params).build()
    assert db.size == 0
    assert db.num_buckets == params.num_buckets
    assert all(s is None for s in db.slots)


def test_empty_key_rejected():
    params = CuckooHashingSparseDpfPirServer.generate_params(
        4, seed=SEED
    )
    builder = CuckooHashedDpfPirDatabase.Builder().set_params(params)
    builder.insert((b"", b"v"))
    with pytest.raises(ValueError, match="key cannot be empty"):
        builder.build()
