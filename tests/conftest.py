"""Test configuration.

Forces JAX onto a virtual 8-device CPU mesh so sharding tests run hermetically
without TPU hardware (the driver's dryrun does the same).

Two subtleties:
* ``XLA_FLAGS`` must be set before the CPU client is created (env is read at
  backend-init time, which pytest's conftest-first import order guarantees).
* The environment's ``sitecustomize`` registers the remote-TPU PJRT plugin at
  interpreter startup and forces ``jax_platforms="axon,cpu"`` — plain
  ``JAX_PLATFORMS=cpu`` in the env is overridden, and initializing the remote
  backend dials a tunnel (slow/hanging under test). Overriding the *config*
  after import wins, because the backend itself is only created lazily at
  first ``jax.devices()``.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
# Kernel self-check verdicts must never leak between the developer's
# machine state and the suite (tests monkeypatch the verdict flags).
os.environ.setdefault("DPF_TPU_VERDICT_CACHE", "off")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


@pytest.fixture(autouse=True)
def _isolate_kernel_verdict_flags():
    """Snapshot/restore the kernel self-check flags around EVERY test.

    The flags are plain module globals mutated by dispatch/self-check
    code paths (not only by tests), so a test that triggers a demotion
    without monkeypatching the flag leaks state into every later test —
    observed: test_level_kernel_selfcheck's un-stubbed walk self-check
    failing on CPU left _WALK_KERNEL_FAILED=True suite-wide."""
    from distributed_point_functions_tpu.pir import dense_eval_planes as dep

    saved = {f: getattr(dep, f) for f in dep._VERDICT_FLAGS}
    saved["_VERDICTS_LOADED"] = dep._VERDICTS_LOADED
    saved["_LAST_RECORDED"] = dep._LAST_RECORDED
    yield
    for name, value in saved.items():
        setattr(dep, name, value)


@pytest.fixture(autouse=True, scope="module")
def _clear_jax_caches_between_modules():
    """Drop compiled executables between test modules.

    The suite compiles hundreds of XLA programs in one process; letting
    them accumulate has produced a segfault inside XLA:CPU's compiler
    late in the run (observed 2026-07-30 at the ~240th test, always the
    same shard_map compile, never reproducible in any file subset).
    Per-module cache clearing keeps within-module reuse (where nearly all
    the hits are) while bounding process-lifetime compiler state.
    """
    yield
    jax.clear_caches()


@pytest.fixture(autouse=True)
def _small_selfcheck_shapes(monkeypatch):
    """Shrink the kernel self-check instance shapes suite-wide.

    The production shapes exist for Mosaic legality coverage at the
    SERVING tile geometry — a hardware property CPU tests cannot check
    anyway — and each interpret-mode kernel call costs ~15-30 s on this
    box regardless of width. The shrunken shapes keep every structural
    property the checks verify (multi-tile assembly, compact planning,
    hier node blocks)."""
    from distributed_point_functions_tpu.pir import dense_eval_planes as dep

    monkeypatch.setattr(
        dep, "_WALK_SELFCHECK_SHAPE", dict(g0=64, nk=64, r=2, tile=128)
    )
    monkeypatch.setattr(
        dep, "_WALK_COMPACT_SELFCHECK_SHAPE", dict(g0=64, nk=64, r=2)
    )
    monkeypatch.setattr(
        dep, "_WALK_HIER_SELFCHECK_SHAPE", dict(nl=2, n_entry=8, r=2)
    )
    monkeypatch.setattr(
        dep, "_TAIL_SELFCHECK_SHAPE", dict(g0=32, nk=64, r=2, tile=16)
    )
    monkeypatch.setattr(
        dep, "_TAIL_HIER_SELFCHECK_SHAPE", dict(g0=32, r=2, tile=16)
    )
    yield
