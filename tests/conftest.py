"""Test configuration.

Forces JAX onto a virtual 8-device CPU mesh so sharding tests run hermetically
without TPU hardware (the driver's dryrun does the same). Must run before jax
initializes its backends, which pytest guarantees by importing conftest first.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()
