"""Test configuration.

Forces JAX onto a virtual 8-device CPU mesh so sharding tests run hermetically
without TPU hardware (the driver's dryrun does the same).

Two subtleties:
* ``XLA_FLAGS`` must be set before the CPU client is created (env is read at
  backend-init time, which pytest's conftest-first import order guarantees).
* The environment's ``sitecustomize`` registers the remote-TPU PJRT plugin at
  interpreter startup and forces ``jax_platforms="axon,cpu"`` — plain
  ``JAX_PLATFORMS=cpu`` in the env is overridden, and initializing the remote
  backend dials a tunnel (slow/hanging under test). Overriding the *config*
  after import wins, because the backend itself is only created lazily at
  first ``jax.devices()``.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
