#!/usr/bin/env bash
# Presubmit pipeline — the TPU build's equivalent of the reference's
# .bazelci/presubmit.yml:15-34 (two-compiler matrix, benchmark-tagged
# targets excluded). Stages:
#   1. lint        — stdlib AST lint (tools/lint.py)
#   2. layers      — serving -> pir -> ops layer DAG + import-cycle
#                    check (tools/check_layers.py)
#   3. protos      — generated *_pb2.py match protos/*.proto
#   4. native      — C++ oracle kernels build (g++)
#   5. test-fast   — <5 min hermetic signal tier (incl. tiny-shape
#                    interpret cases of every serving Pallas kernel)
#   6. hh-smoke    — heavy-hitters sweep end to end (tiny domain,
#                    2 levels, in-process transport, plaintext check)
#   7. admin-smoke — operator telemetry endpoint: serve one traced
#                    request, then scrape /healthz, /metrics (Prometheus
#                    text), and /tracez off a live AdminServer
#   8. dryrun      — 8-virtual-device multichip compile+step
# Benchmarks are excluded exactly as the reference excludes
# `--test_tag_filters=-benchmark`. `FULL=1` appends the whole suite.
set -u -o pipefail
cd "$(dirname "$0")/.."
fail=0

stage() {
    echo "=== presubmit: $1 ==="
    shift
    "$@" || { echo "FAILED: $*"; fail=1; }
}

stage lint python tools/lint.py

stage layers python tools/check_layers.py

stage protoc-check bash -c '
    tmp=$(mktemp -d) &&
    protoc --python_out="$tmp" -Iprotos \
        protos/distributed_point_function.proto \
        protos/distributed_comparison_function.proto \
        protos/multiple_interval_containment.proto \
        protos/private_information_retrieval.proto \
        protos/hash_family_config.proto &&
    ok=0 &&
    for f in "$tmp"/*_pb2.py; do
        name=$(basename "$f")
        cmp -s "$f" "distributed_point_functions_tpu/protos/$name" \
            || { echo "stale generated proto: $name"; ok=1; }
    done; rm -rf "$tmp"; exit $ok'

stage native bash -c 'cd native && bash build.sh'

stage test-fast make -s test-fast

stage hh-smoke env JAX_PLATFORMS=cpu \
    python examples/heavy_hitters_demo.py --smoke

stage admin-smoke env JAX_PLATFORMS=cpu python -c '
import json, urllib.request
from distributed_point_functions_tpu import observability as obs
from distributed_point_functions_tpu.serving.metrics import MetricsRegistry

reg = MetricsRegistry()
rec = obs.tracing.FlightRecorder()
with obs.tracing.trace_request("smoke.request", recorder=rec):
    with reg.timed("smoke.request_ms"):
        with obs.tracing.span("device_compute"):
            pass
with obs.AdminServer(registry=reg, recorder=rec) as admin:
    base = f"http://127.0.0.1:{admin.port}"
    assert urllib.request.urlopen(base + "/healthz").read() == b"ok\n"
    text = urllib.request.urlopen(base + "/metrics").read().decode()
    assert "# TYPE dpf_smoke_request_ms histogram" in text, text
    assert "dpf_smoke_request_ms_bucket" in text, text
    tracez = json.load(urllib.request.urlopen(base + "/tracez"))
    assert tracez["recorded"] == 1 and tracez["slowest"], tracez
    spans = [s["name"] for s in tracez["slowest"][0]["spans"]]
    assert "device_compute" in spans, spans
print("admin-smoke: OK (/healthz, /metrics, /tracez)")
'

stage dryrun make -s dryrun

if [ "${FULL:-0}" = "1" ]; then
    stage test-full python -m pytest tests/ -q
fi

echo "presubmit: $([ $fail -eq 0 ] && echo PASS || echo FAIL)"
exit $fail
