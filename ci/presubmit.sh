#!/usr/bin/env bash
# Presubmit pipeline — the TPU build's equivalent of the reference's
# .bazelci/presubmit.yml:15-34 (two-compiler matrix, benchmark-tagged
# targets excluded). Stages:
#   1. lint        — stdlib AST lint (tools/lint.py)
#   2. layers      — serving -> pir -> ops layer DAG + import-cycle
#                    check (tools/check_layers.py)
#   3. protos      — generated *_pb2.py match protos/*.proto
#   4. native      — C++ oracle kernels build (g++)
#   5. test-fast   — <5 min hermetic signal tier (incl. tiny-shape
#                    interpret cases of every serving Pallas kernel)
#   6. hh-smoke    — heavy-hitters sweep end to end (tiny domain,
#                    2 levels, in-process transport, plaintext check)
#   7. admin-smoke — operator telemetry endpoint: serve one traced
#                    request, then scrape /healthz, /metrics (Prometheus
#                    text with exemplars), /statusz (compile counts, HBM
#                    watermarks, SLO burn, phase waterfall, transfer
#                    ledger, auto-captured profiles) and /tracez off a
#                    live AdminServer, check a hard SLO breach degrades
#                    /healthz to 503, and check a synthetic latency-SLO
#                    burn produces exactly one auto-capture entry
#   8. critical-smoke — cross-party critical path over a real TCP
#                    Leader/Helper pair: the skew-corrected
#                    decomposition on /criticalz must account for the
#                    measured helper rtt (helper_net + helper_queue +
#                    helper_compute == exchange rtt exactly, within
#                    own-share overlap + stated uncertainty of the raw
#                    rtt), and the merged two-party timeline on
#                    /tracez must be monotone per party
#   9. chaos-smoke — one scripted fault schedule through the real
#                    stack: a permanently-failing helper leg must open
#                    the Leader's circuit breaker (fast-fail, /statusz
#                    row), and a heavy-hitters sweep killed mid-run
#                    must resume from its checkpoint to the plaintext
#                    answer
#  10. overload-smoke — synthetic burst against cost-aware admission:
#                    a tiny tenant quota must shed at admission with a
#                    typed RetryAfter hint (never reaching the batcher),
#                    and a breaching SLO signal must walk the brownout
#                    ladder to critical_only (visible on /statusz) and
#                    fully auto-revert when the signal clears
#  11. prober-smoke — blackbox-verification chaos drill: a `corrupt`
#                    failpoint armed on the helper-leg response wire
#                    (via DPF_TPU_FAILPOINTS, so the event journal
#                    shows the arming) must be flagged by the prober
#                    within 3 cycles, capture exactly one debug bundle
#                    (cooldown respected) whose journal tail correlates
#                    the timeline, degrade /healthz once the e2e probe
#                    goes stale, and fully recover (probez passing,
#                    /healthz 200) after the failpoint clears
#  12. capacity-accuracy-smoke — the cost-model accuracy loop closed
#                    on live traffic: a deliberately mispriced pir
#                    workload (DPF_TPU_COSTMODEL_MISPRICE) served
#                    through a real PlainSession must populate
#                    /capacityz with finite residuals, journal a
#                    capacity.drift event, burn the drift SLO gauge,
#                    apply a clamped (<= 2x) correction to subsequent
#                    admission prices with bit-identical responses,
#                    and fully revert under the recalibration kill
#                    switch
#  13. shard-smoke — pod-scale mesh serving end to end on 8 forced
#                    host devices: closed-loop traffic against one
#                    logical server spread over a 2-D (shard x key)
#                    mesh, one snapshot rotation mid-traffic, zero
#                    prober failures, no cross-generation reads, the
#                    per-shard staging visible in mesh_export, and the
#                    per-shard busy rows on /utilz
#  14. pipeline-smoke — the hot-path pipelining contract end to end:
#                    depth-2 batcher under closed-loop load, one delta
#                    rotation (prestage saves bytes), prober green
#                    through the flip, /statusz shows overlapped
#                    (hidden) transfer time
#  15. util-smoke  — the device-seconds ledger end to end: closed-loop
#                    traffic must put a nonzero duty cycle with a
#                    populated bubble breakdown (causes summing to the
#                    measured idle) on /utilz, an injected helper-leg
#                    delay failpoint must journal a util.anomaly via
#                    the rate-of-change watch, and a debug bundle
#                    captured after the stall must carry >= 60 s of
#                    flight-data history with the anomaly in its
#                    journal tail
#  16. sparse-smoke — key-value (cuckoo) PIR at serving parity:
#                    closed-loop sparse traffic through the batched
#                    session, one key-value write batch landing as a
#                    SnapshotManager delta rotation under load
#                    (prestage saves bytes), zero sparse-prober
#                    failures through the flip, and the golden absent
#                    key resolving to typed not-found throughout
#  17. forecast-smoke — the predictive capacity plane end to end:
#                    synthetic load ramped toward a deliberately
#                    lowered calibrated capacity must journal a
#                    forecast.breach_predicted BEFORE any hard SLO
#                    burn, /forecastz?format=json must carry a finite
#                    time-to-breach, the predictive governor must
#                    visibly tighten tenant quotas on /capacityz, and
#                    everything must revert exactly once the ramp
#                    recedes — with every served response bit-identical
#                    to the oracle throughout
#  18. perf-gate   — benchmarks/regression_gate.py --check-only against
#                    the committed history fixture (CPU-safe: judges
#                    records, runs no bench)
#  19. dryrun      — 8-virtual-device multichip compile+step
# Benchmarks are excluded exactly as the reference excludes
# `--test_tag_filters=-benchmark`. `FULL=1` appends the whole suite.
set -u -o pipefail
cd "$(dirname "$0")/.."
fail=0

stage() {
    echo "=== presubmit: $1 ==="
    shift
    "$@" || { echo "FAILED: $*"; fail=1; }
}

stage lint python tools/lint.py

stage layers python tools/check_layers.py

stage protoc-check bash -c '
    tmp=$(mktemp -d) &&
    protoc --python_out="$tmp" -Iprotos \
        protos/distributed_point_function.proto \
        protos/distributed_comparison_function.proto \
        protos/multiple_interval_containment.proto \
        protos/private_information_retrieval.proto \
        protos/hash_family_config.proto &&
    ok=0 &&
    for f in "$tmp"/*_pb2.py; do
        name=$(basename "$f")
        cmp -s "$f" "distributed_point_functions_tpu/protos/$name" \
            || { echo "stale generated proto: $name"; ok=1; }
    done; rm -rf "$tmp"; exit $ok'

stage native bash -c 'cd native && bash build.sh'

stage test-fast make -s test-fast

stage hh-smoke env JAX_PLATFORMS=cpu \
    python examples/heavy_hitters_demo.py --smoke

stage admin-smoke env JAX_PLATFORMS=cpu python -c '
import json, urllib.error, urllib.request
from distributed_point_functions_tpu import observability as obs
from distributed_point_functions_tpu.observability import phases as pm
from distributed_point_functions_tpu.observability.slo import (
    SloObjective, SloTracker,
)
from distributed_point_functions_tpu.serving.metrics import MetricsRegistry

reg = MetricsRegistry()
rec = obs.tracing.FlightRecorder()
dev = obs.DeviceTelemetry(registry=reg)
phases = obs.PhaseRecorder()
with obs.tracing.trace_request("smoke.request", recorder=rec):
    with reg.timed("smoke.request_ms"):
        with phases.request("smoke"):
            with pm.phase("h2d_transfer"):
                dev.transfers.record_h2d(4096, "key_staging")
            pm.record("device_compute", 2.5)
            with obs.tracing.span("device_compute"):
                with dev.hbm.phase("db_staging"):
                    dev.hbm.sample()
with dev.compile_tracker.dispatch("smoke.evaluate", "q64.b8192"):
    pass
with dev.compile_tracker.dispatch("smoke.evaluate", "q64.b8192"):
    pass
slo = SloTracker(
    [SloObjective(name="smoke_p99", kind="p99_ms_max",
                  metric="smoke.request_ms", threshold=1e-9)],
    registry=reg,
)
prof = obs.AutoProfiler(
    slo, capture_fn=lambda r: {"log_dir": "/tmp/smoke-capture"},
    async_capture=False,
)
slo.evaluate()  # synthetic latency-SLO burn -> one inline capture
slo.evaluate()  # continuing breach must NOT re-fire
assert len(prof.captures()) == 1, prof.export()
with obs.AdminServer(registry=reg, recorder=rec, device=dev,
                     slo=slo, phases=phases,
                     autoprofiler=prof) as admin:
    base = f"http://127.0.0.1:{admin.port}"
    text = urllib.request.urlopen(base + "/metrics").read().decode()
    assert "# TYPE dpf_smoke_request_ms histogram" in text, text
    assert "dpf_smoke_request_ms_bucket" in text, text
    assert "# {trace_id=" in text, text  # exemplar on a bucket line
    assert "dpf_device_compiles" in text, text
    statusz = urllib.request.urlopen(base + "/statusz").read().decode()
    for needle in ("smoke.evaluate", "q64.b8192", "db_staging",
                   "SLO burn", "smoke_p99",
                   "Phase waterfall", "h2d_transfer", "device_compute",
                   "transfers", "key_staging",
                   "Auto-captured profiles", "/tmp/smoke-capture"):
        assert needle in statusz, (needle, statusz)
    sz_json = json.load(
        urllib.request.urlopen(base + "/statusz?format=json")
    )
    assert sz_json["phases"]["smoke"]["requests"] == 1, sz_json["phases"]
    led = sz_json["device"]["transfers"]["phases"]["key_staging"]
    assert led["h2d_copies"] == 1 and led["h2d_bytes"] == 4096, led
    assert len(sz_json["profiles"]["captures"]) == 1, sz_json["profiles"]
    sz = json.load(urllib.request.urlopen(base + "/statusz?format=json"))
    site = sz["device"]["compile"]["sites"]["smoke.evaluate"]
    assert site["compiles"] == 1 and site["hits"] == 1, site
    tracez = json.load(urllib.request.urlopen(base + "/tracez"))
    assert tracez["recorded"] == 1 and tracez["slowest"], tracez
    spans = [s["name"] for s in tracez["slowest"][0]["spans"]]
    assert "device_compute" in spans, spans
    try:
        urllib.request.urlopen(base + "/healthz")
        raise AssertionError("breached SLO did not degrade /healthz")
    except urllib.error.HTTPError as e:
        assert e.code == 503, e.code
        body = e.read().decode()
        assert "slo breach: smoke_p99" in body, body
    reg.reset()  # breach clears -> next probe recovers
    assert urllib.request.urlopen(base + "/healthz").read() == b"ok\n"
assert len(prof.captures()) == 1, prof.export()  # still exactly one
print("admin-smoke: OK (/metrics incl. exemplars, /statusz incl. phase "
      "waterfall + transfer ledger + auto-captures, /tracez, /healthz "
      "incl. SLO degrade+recover, one capture per burn)")
'

stage critical-smoke env JAX_PLATFORMS=cpu python -c '
import json, urllib.request
import numpy as np
from distributed_point_functions_tpu.observability import AdminServer
from distributed_point_functions_tpu.pir import (
    DenseDpfPirClient, DenseDpfPirDatabase,
)
from distributed_point_functions_tpu.serving import (
    FramedTcpServer, HelperSession, LeaderSession, ServingConfig,
    TcpTransport,
)
from distributed_point_functions_tpu.testing import encrypt_decrypt

rng = np.random.default_rng(7)
records = [bytes(rng.integers(0, 256, 16, dtype=np.uint8))
           for _ in range(64)]
builder = DenseDpfPirDatabase.Builder()
for r in records:
    builder.insert(r)
database = builder.build()
config = ServingConfig(max_batch_size=4, max_wait_ms=2.0)
helper = HelperSession(database, encrypt_decrypt.decrypt, config)
server = FramedTcpServer(
    helper.handle_wire, port=0, name="critical-helper"
).start()
transport = TcpTransport("localhost", server.port)
leader = LeaderSession(database, transport, config)
client = DenseDpfPirClient.create(64, encrypt_decrypt.encrypt)
try:
    with helper, leader:
        for idx in (3, 17, 41):
            request, state = client.create_request([idx])
            response = leader.handle_request(request)
            assert client.handle_response(response, state) == [
                records[idx]
            ], idx
finally:
    transport.close()
    server.stop()
with AdminServer(registry=leader.metrics) as admin:
    base = f"http://127.0.0.1:{admin.port}"
    crit = json.load(
        urllib.request.urlopen(base + "/criticalz?format=json")
    )
    assert crit["requests"] == 3, crit
    assert crit["skew_invalid"] == 0, crit
    last = crit["last"]["leader"]
    assert last["skew_valid"] is True, last
    total = (last["helper_net_ms"] + last["helper_queue_ms"]
             + last["helper_compute_ms"])
    # Identity: the split accounts for the exchange rtt exactly.
    assert abs(total - last["exchange_ms"]) < 1e-2, last
    # ... and for the raw measured rtt within the honest tolerance:
    # the own-share overlap that provably ran serially inside the
    # bracket (bounded by own_ms and, when the concurrency cap
    # engages, equal to 2x the stated uncertainty) plus codec slop.
    assert abs(total - last["rtt_ms"]) <= (
        last["own_ms"] + 2.0 * last["uncertainty_ms"] + 1.0
    ), last
    prof = crit["profile"]
    assert prof and all(
        "p99_ms" in cell
        for party in prof.values() for cell in party.values()
    ), prof
    sz = json.load(urllib.request.urlopen(base + "/statusz?format=json"))
    assert sz["critical"]["requests"] == 3, sz["critical"]
    tracez = json.load(urllib.request.urlopen(base + "/tracez"))
    traces = tracez["slowest"] + tracez["recent"]
    merged = next(
        t for t in traces if t["name"] == "leader.request"
    )["attrs"]["critical_path"]
    assert merged["critical_leg"] in ("helper", "local"), merged
    timeline = merged["timeline"]
    assert timeline and any(s["critical"] for s in timeline), merged
    for party in {s["party"] for s in timeline}:
        starts = [s["start_ms"] for s in timeline if s["party"] == party]
        assert starts == sorted(starts), (party, starts)
    assert all(s["start_ms"] >= 0.0 and s["duration_ms"] >= 0.0
               for s in timeline), timeline
print("critical-smoke: OK (/criticalz net+queue+compute == exchange "
      "rtt over real TCP, ~ raw rtt within overlap+uncertainty; "
      "/tracez merged timeline monotone per party)")
'

stage chaos-smoke env JAX_PLATFORMS=cpu python -c '
import os, tempfile, time, urllib.request
import numpy as np
from distributed_point_functions_tpu import heavy_hitters as hh
from distributed_point_functions_tpu.observability import AdminServer
from distributed_point_functions_tpu.pir import (
    DenseDpfPirClient, DenseDpfPirDatabase,
)
from distributed_point_functions_tpu.robustness import failpoints
from distributed_point_functions_tpu.serving import (
    HelperSession, HelperUnavailable, InProcessTransport, LeaderSession,
    ServingConfig,
)
from distributed_point_functions_tpu.testing import encrypt_decrypt

reg = failpoints.default_failpoints()

# --- breaker-open: a dead helper leg must trip the breaker and then
# cost <1 ms per request (fast-fail), visible on /statusz. -------------
builder = DenseDpfPirDatabase.Builder()
rng = np.random.default_rng(0)
for _ in range(16):
    builder.insert(bytes(rng.integers(0, 256, 8, dtype=np.uint8)))
db = builder.build()
config = ServingConfig(
    max_batch_size=2, max_wait_ms=1.0, helper_retries=0,
    helper_backoff_ms=1.0, helper_backoff_max_ms=1.0,
    breaker_failure_threshold=2, breaker_reset_ms=60_000.0,
)
reg.arm("service.helper_leg", "error", times=None)
helper = HelperSession(db, encrypt_decrypt.decrypt, config)
leader = LeaderSession(db, InProcessTransport(helper.handle_wire), config)
client = DenseDpfPirClient.create(16, encrypt_decrypt.encrypt)
with helper, leader:
    for _ in range(2):
        request, _ = client.create_request([3])
        try:
            leader.handle_request(request)
            raise AssertionError("dead helper leg did not raise")
        except HelperUnavailable:
            pass
    assert leader.breaker.state == "open", leader.breaker_export()
    t0 = time.perf_counter()
    for _ in range(10):
        try:
            leader._send_to_helper(None, lambda: None)
            raise AssertionError("open breaker admitted a request")
        except HelperUnavailable:
            pass
    per_call = (time.perf_counter() - t0) / 10
    assert per_call < 1e-3, f"fast-fail cost {per_call * 1e3:.3f} ms"

    class Shim:
        export = staticmethod(leader.breaker_export)

    with AdminServer(registry=leader.metrics,
                     breakers={"leader.helper": Shim()}) as admin:
        statusz = urllib.request.urlopen(
            f"http://127.0.0.1:{admin.port}/statusz"
        ).read().decode()
        for needle in ("Circuit breakers", "leader.helper", "open"):
            assert needle in statusz, needle
reg.clear()

# --- sweep-resume: kill the sweep after round 0, resume a fresh Leader
# from the checkpoint, land on the plaintext answer. -------------------
values = [1, 1, 1, 9, 9, 14]
cfg = hh.HeavyHittersConfig(domain_bits=4, level_bits=2, threshold=2)
hh_client = hh.HeavyHittersClient(cfg)
pairs = [hh_client.generate_report(v) for v in values]
keys0, keys1 = [p[0] for p in pairs], [p[1] for p in pairs]
transport = InProcessTransport(
    hh.HeavyHittersHelper(
        hh.HeavyHittersServer(cfg, keys1, allow_resume=True)
    ).handle_wire
)
ckpt = os.path.join(tempfile.mkdtemp(), "sweep.json")
reg.arm("transport.inproc.roundtrip", "error", times=None, after=1)
try:
    hh.HeavyHittersLeader(
        hh.HeavyHittersServer(cfg, keys0), transport, checkpoint=ckpt
    ).run()
    raise AssertionError("injected fault did not kill the sweep")
except Exception as e:
    assert "injected fault" in str(e), e
reg.clear()
assert os.path.exists(ckpt), "no checkpoint persisted before the crash"
resumed = hh.HeavyHittersLeader(
    hh.HeavyHittersServer(cfg, keys0, allow_resume=True),
    transport, checkpoint=ckpt,
)
result = resumed.run()
counters = resumed.metrics.export()["counters"]
assert result.as_dict() == hh.plaintext_heavy_hitters(values, cfg), (
    result.as_dict()
)
assert counters["hh.sweep_resumes"] == 1, counters
assert counters["hh.rounds"] == 1, counters  # only the killed round re-ran
assert not os.path.exists(ckpt)  # deleted on completion
print("chaos-smoke: OK (breaker-open fast-fail <1 ms + /statusz row, "
      "sweep resumed from checkpoint and matched plaintext)")
'

stage overload-smoke env JAX_PLATFORMS=cpu python -c '
import urllib.request
import numpy as np
from distributed_point_functions_tpu.capacity import (
    BrownoutController, TenantPolicy,
)
from distributed_point_functions_tpu.observability import AdminServer
from distributed_point_functions_tpu.pir import (
    DenseDpfPirClient, DenseDpfPirDatabase,
)
from distributed_point_functions_tpu.serving import (
    Overloaded, PlainSession, ServingConfig,
)

builder = DenseDpfPirDatabase.Builder()
rng = np.random.default_rng(1)
for _ in range(16):
    builder.insert(bytes(rng.integers(0, 256, 8, dtype=np.uint8)))
db = builder.build()
config = ServingConfig(
    max_batch_size=4, max_wait_ms=1.0, admission_enabled=True
)
client = DenseDpfPirClient.create(16, lambda pt, ci: pt)
request = client.create_plain_requests([3])[0]
with PlainSession(db, config) as session:
    want = session.handle_request(request).dpf_pir_response.masked_response

    # --- synthetic burst: a tiny tenant quota must shed at admission
    # with a typed RetryAfter hint, before any batching/evaluation. ----
    session.set_tenant("burst", TenantPolicy(rate_qps=1.0, burst=1.0))
    got = session.handle_request(request, tenant="burst")
    assert got.dpf_pir_response.masked_response == want
    hint = None
    sheds = 0
    for _ in range(5):
        try:
            session.handle_request(request, tenant="burst")
            raise AssertionError("burst past the quota was admitted")
        except Overloaded as e:
            sheds += 1
            hint = e
    assert hint.retry_after_s > 0 and hint.reason == "quota", vars(hint)
    counters = session.metrics.export()["counters"]
    assert counters["plain.admission.shed{reason=quota}"] == sheds, counters

    # --- brownout: a breaching signal walks the ladder to the top,
    # shows on /statusz, and fully auto-reverts once healthy. ----------
    breaching = {"v": True}
    brown = BrownoutController(
        signal=lambda: breaching["v"],
        engage_after_s=0.0, escalate_after_s=0.0, revert_after_s=0.0,
        metrics=session.metrics,
    )
    session.attach_brownout(brown, batch_cap=2, cheap_tier="streaming")
    for _ in range(4):
        brown.evaluate()
    assert brown.export()["level"] == 4, brown.export()
    with AdminServer(registry=session.metrics, brownout=brown,
                     admission=session.admission) as admin:
        statusz = urllib.request.urlopen(
            f"http://127.0.0.1:{admin.port}/statusz"
        ).read().decode()
        for needle in ("Brownout ladder", "critical_only",
                       "Admission", "burst"):
            assert needle in statusz, needle
    breaching["v"] = False
    for _ in range(4):
        brown.evaluate()
    assert brown.export()["level"] == 0, brown.export()
    # Knobs restored: the default tenant (shed at critical_only) serves
    # again, bit-identical.
    got = session.handle_request(request).dpf_pir_response.masked_response
    assert got == want
print("overload-smoke: OK (quota burst shed at admission with "
      f"RetryAfter={hint.retry_after_s:.2f}s, brownout ladder walked "
      "to critical_only on /statusz and fully reverted)")
'

stage prober-smoke env JAX_PLATFORMS=cpu \
    DPF_TPU_FAILPOINTS="transport.response=corrupt:times=none" \
    python -c '
import json, os, time, urllib.error, urllib.request
import numpy as np
from distributed_point_functions_tpu.observability import (
    AdminServer, BundleManager,
)
from distributed_point_functions_tpu.observability.events import (
    default_journal,
)
from distributed_point_functions_tpu.pir import DenseDpfPirDatabase
from distributed_point_functions_tpu.robustness import failpoints
from distributed_point_functions_tpu.serving import (
    HelperSession, InProcessTransport, LeaderSession, ServingConfig,
)
from distributed_point_functions_tpu.serving.prober import Prober
from distributed_point_functions_tpu.testing import encrypt_decrypt

# The env-armed corrupt failpoint must already be on the timeline
# (events.py emits retroactively for sites armed before import).
journal = default_journal()
armed = journal.tail(kind="failpoint.armed")
assert any(e["site"] == "transport.response" for e in armed), armed

rng = np.random.default_rng(7)
records = [bytes(rng.integers(0, 256, 8, dtype=np.uint8))
           for _ in range(16)]
builder = DenseDpfPirDatabase.Builder()
for r in records:
    builder.insert(r)
db = builder.build()
config = ServingConfig(
    max_batch_size=2, max_wait_ms=1.0, request_timeout_ms=None,
    helper_retries=0, helper_backoff_ms=1.0, breaker_reset_ms=50.0,
)
helper = HelperSession(db, encrypt_decrypt.decrypt, config)
leader = LeaderSession(db, InProcessTransport(helper.handle_wire), config)
bundles = BundleManager(cooldown_s=3600.0, max_bundles=4)
prober = Prober(
    leader, records, encrypter=encrypt_decrypt.encrypt,
    period_s=0.1, freshness_window_s=2.0,
)
prober.add_failure_listener(bundles.on_probe_failure)
# AdminServer registers the bundle sources (statusz/metrics/traces/
# events/probes), so it must exist before the first failing cycle.
with helper, leader, AdminServer(
    registry=leader.metrics, port=0, prober=prober, bundles=bundles
) as admin:
    base = f"http://127.0.0.1:{admin.port}"

    # 1. The prober must flag the corrupted helper leg within 3 cycles.
    flagged_cycle = None
    for cycle in range(3):
        results = prober.run_cycle()
        bad = [r for r in results
               if r["status"] in ("mismatch", "error")]
        if bad:
            flagged_cycle = cycle
            assert all(r["kind"] == "leader_e2e" for r in bad), bad
            break
    assert flagged_cycle is not None, "corruption not flagged in 3 cycles"
    # Plain-share probes bypass the transport: still bit-identical.
    by_kind = {r["kind"]: r["status"] for r in results}
    assert by_kind["pir_unbatched"] == "pass", by_kind

    # 2. Repeated failing cycles: exactly one bundle (cooldown).
    prober.run_cycle()
    prober.run_cycle()
    debugz = json.load(urllib.request.urlopen(base + "/debugz"))
    assert debugz["fired"] == 1, debugz
    assert len(debugz["bundles"]) == 1, debugz
    bundle = debugz["bundles"][0]
    assert bundle["reason"] == "probe_failure", bundle

    # 3. The bundle carries the correlated journal timeline.
    with open(os.path.join(bundle["path"], "events.json")) as f:
        kinds = {e["kind"] for e in json.load(f)["events"]}
    assert "failpoint.armed" in kinds, kinds
    assert kinds & {"prober.mismatch", "prober.error"}, kinds
    with open(os.path.join(bundle["path"], "probes.json")) as f:
        snap = json.load(f)
    assert snap["mismatches"] + snap["errors"] >= 1, snap
    # /eventz shows the same correlated timeline live.
    eventz = urllib.request.urlopen(base + "/eventz").read().decode()
    assert "failpoint.armed" in eventz, eventz
    assert "prober." in eventz, eventz

    # 4. The e2e probe never passed: once the freshness window elapses
    # /healthz must degrade to 503 (identity probes refresh in the
    # cycle, so only the e2e kind is stale).
    time.sleep(2.1)
    prober.run_cycle()
    try:
        urllib.request.urlopen(base + "/healthz")
        raise AssertionError("stale e2e probe did not degrade healthz")
    except urllib.error.HTTPError as e:
        assert e.code == 503, e.code
        detail = json.loads(e.read())
        assert "leader_e2e" in detail["stale_probes"], detail

    # 5. Clear the failpoint: full recovery. The breaker may still be
    # open for up to breaker_reset_ms after the last corrupted call,
    # so allow a few cycles for the half-open probe to close it.
    failpoints.default_failpoints().clear()
    deadline = time.time() + 30.0
    while True:
        results = prober.run_cycle()
        if all(r["status"] == "pass" for r in results):
            break
        assert time.time() < deadline, results
        time.sleep(0.1)
    probez = json.load(urllib.request.urlopen(base + "/probez"))
    statuses = {k: v["last_status"]
                for k, v in probez["freshness"].items()}
    assert set(statuses.values()) == {"pass"}, statuses
    health = json.load(urllib.request.urlopen(base + "/healthz"))
    assert health["status"] == "ok", health
    assert journal.tail(kind="prober.recovered"), "no recovery event"
    assert journal.tail(kind="failpoint.disarmed"), "no disarm event"
print("prober-smoke: OK (corruption flagged in cycle "
      f"{flagged_cycle}, one bundle with correlated timeline, "
      "healthz degraded on stale e2e probe and recovered after clear)")
'

stage capacity-accuracy-smoke env JAX_PLATFORMS=cpu \
    DPF_TPU_COSTMODEL_WINDOW=4 \
    DPF_TPU_COSTMODEL_DRIFT_WINDOWS=1 \
    DPF_TPU_COSTMODEL_MIN_SAMPLES=4 \
    DPF_TPU_COSTMODEL_MISPRICE=pir=3.0 \
    python -c '
import json, os, tempfile, threading, urllib.request
import numpy as np
from distributed_point_functions_tpu.capacity import (
    KILL_SWITCH_ENV, CapacityModel, ThroughputCalibration,
    set_default_capacity_model,
)
from distributed_point_functions_tpu.observability import (
    AdminServer, CostLedger, set_default_cost_ledger,
)
from distributed_point_functions_tpu.observability.costmodel import (
    DRIFT_GAUGE,
)
from distributed_point_functions_tpu.observability.events import (
    default_journal,
)
from distributed_point_functions_tpu.pir import DenseDpfPirDatabase
from distributed_point_functions_tpu.pir.client import DenseDpfPirClient
from distributed_point_functions_tpu.pir.server import DenseDpfPirServer
from distributed_point_functions_tpu.serving import (
    PlainSession, ServingConfig,
)

records = [(b"cap-%02d:" % i).ljust(16, b".")[:16] for i in range(32)]
builder = DenseDpfPirDatabase.Builder()
for r in records:
    builder.insert(r)
database = builder.build()

# Pinned absurdly-fast calibration: every measured batch then looks
# enormously more expensive than priced on any host, so the mispriced
# workload drifts deterministically.
cal = tempfile.NamedTemporaryFile("w", suffix=".jsonl", delete=False)
cal.write(json.dumps({"metric": "serving_closed_loop_queries_per_sec",
                      "value": 1e9}) + "\n")
cal.write(json.dumps({"metric": "heavy_hitters_sweep_lanes_per_sec",
                      "value": 1e9}) + "\n")
cal.close()
model = CapacityModel(device_memory_bytes=16 << 30,
                      calibration=ThroughputCalibration(cal.name))
set_default_capacity_model(model)
set_default_cost_ledger(CostLedger())
raw_1key_ms = 3.0 * 1e3 / 1e9  # misprice only, no correction

client = DenseDpfPirClient.create(len(records), lambda pt, ci: pt)
reqs = [client.create_plain_requests([i])[0] for i in range(8)]
oracle_server = DenseDpfPirServer.create_plain(database)
oracle = [oracle_server.handle_plain_request(r)
          .dpf_pir_response.masked_response for r in reqs]

journal = default_journal()
config = ServingConfig(max_batch_size=1, max_wait_ms=1.0)
with PlainSession(database, config) as session:
    results = [None] * len(reqs)

    def worker(i):
        results[i] = session.handle_request(reqs[i])

    threads = [threading.Thread(target=worker, args=(i,))
               for i in range(len(reqs))]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    for got, want in zip(results, oracle):
        assert got.dpf_pir_response.masked_response == want, \
            "responses changed under mispricing"
    with AdminServer(registry=session.metrics,
                     capacity=session.capacity_accuracy) as admin:
        base = "http://127.0.0.1:%d" % admin.port
        state = json.load(
            urllib.request.urlopen(base + "/capacityz?format=json"))
        pir_cells = {k: v for k, v in state["ledger"]["cells"].items()
                     if k.startswith("pir/")}
        assert pir_cells, state["ledger"]["cells"]
        for c in pir_cells.values():
            assert c["samples"] >= 1, c
            assert np.isfinite(c["residual_p50"]), c
    drifts = [e for e in journal.tail(n=64, kind="capacity.drift")
              if e.get("workload") == "pir"]
    assert drifts and drifts[0]["state"] == "drifting", drifts
    gauge = session.metrics.export()["gauges"][DRIFT_GAUGE]
    assert gauge >= 1.0, gauge
    rec = session.capacity_accuracy.recalibrator
    factor = rec.factor("pir")
    assert 1.0 < factor <= 2.0, factor
    priced = model.price_pir_keys(1).device_ms
    assert abs(priced - factor * raw_1key_ms) < 1e-12, (priced, factor)
    os.environ[KILL_SWITCH_ENV] = "0"
    try:
        reverted = model.price_pir_keys(1).device_ms
        assert abs(reverted - raw_1key_ms) < 1e-12, reverted
        assert journal.tail(kind="capacity.correction_reverted"), \
            "no revert event"
    finally:
        del os.environ[KILL_SWITCH_ENV]
    resumed = model.price_pir_keys(1).device_ms
    assert abs(resumed - factor * raw_1key_ms) < 1e-12, resumed
os.unlink(cal.name)
print("capacity-accuracy-smoke: OK (%d pir cells, drift journaled, "
      "gauge %.0f, correction clamped at %.2fx, kill switch "
      "reverted and resumed)" % (len(pir_cells), gauge, factor))
'

# --- rotation-smoke: rotate the database twice under live closed-loop
# traffic with a delay failpoint armed on snapshot.flip (stretching the
# Helper-first/Leader-last window), and prove the PR 12 contract: the
# prober stays bit-identical across both flips (goldens rotate with the
# generation), no response ever mixes generations, the q/s dip is
# bounded, and throughput recovers after the last flip.
stage rotation-smoke env JAX_PLATFORMS=cpu python -c '
import threading, time
import numpy as np
from distributed_point_functions_tpu.pir import (
    DenseDpfPirClient, DenseDpfPirDatabase, messages,
)
from distributed_point_functions_tpu.pir.server import DenseDpfPirServer
from distributed_point_functions_tpu.robustness import failpoints
from distributed_point_functions_tpu.serving import (
    HelperSession, InProcessTransport, LeaderSession,
    RotationCoordinator, ServingConfig, SnapshotManager,
    SnapshotMismatch,
)
from distributed_point_functions_tpu.serving.prober import Prober
from distributed_point_functions_tpu.testing import encrypt_decrypt

NUM, NBYTES, FLIP_DELAY_MS = 32, 8, 25.0
rng = np.random.default_rng(12)
base = [bytes(rng.integers(0, 256, NBYTES, dtype=np.uint8))
        for _ in range(NUM)]
# Per-generation XOR masks differ pairwise, so records differ between
# any two generations at every byte: a torn cross-generation XOR can
# match no oracle.
recs = {g: [bytes(b ^ m for b in r) for r in base]
        for g, m in enumerate((0x00, 0xA5, 0x3C))}

def build(records):
    b = DenseDpfPirDatabase.Builder()
    for r in records:
        b.insert(r)
    return b.build()

def delta(prev, records):
    b = DenseDpfPirDatabase.Builder()
    for i, r in enumerate(records):
        b.update(i, r)
    return b.build_from(prev)

# Warm the jit buckets so a cold compile cannot masquerade as a dip.
warm = DenseDpfPirServer.create_plain(build(base))
keys = list(DenseDpfPirClient.create(NUM, lambda pt, ci: pt)
            .create_plain_requests([0])[0].plain_request.dpf_keys)
for b in (1, 2):
    warm.handle_plain_request(messages.PirRequest(
        plain_request=messages.PlainRequest(dpf_keys=keys * b)))

config = ServingConfig(max_batch_size=2, max_wait_ms=1.0)
helper = HelperSession(build(recs[0]), encrypt_decrypt.decrypt, config)
leader = LeaderSession(
    build(recs[0]), InProcessTransport(helper.handle_wire), config)
leader_mgr = SnapshotManager(leader)
helper_mgr = SnapshotManager(helper)
coordinator = RotationCoordinator(leader_mgr, helper_mgr)
prober = Prober(leader, recs[0], encrypter=encrypt_decrypt.encrypt,
                period_s=0.1, indices=[0, 7, 31])
prober.bind_snapshots(leader_mgr, records_provider=lambda g: recs[g])
prober.bind_snapshots(helper_mgr)

client = DenseDpfPirClient.create(NUM, encrypt_decrypt.encrypt)
lock = threading.Lock()
stats = {"completed": 0, "torn": 0, "refusals": 0}
times = []
stop = threading.Event()

def worker(tid):
    i = tid
    while not stop.is_set():
        idx = (7 * i) % NUM
        i += 2
        try:
            request, state = client.create_request([idx])
            got = client.handle_response(
                leader.handle_request(request), state)[0]
            now = time.monotonic()
            with lock:
                stats["completed"] += 1
                if not any(got == r[idx] for r in recs.values()):
                    stats["torn"] += 1
                times.append(now)
        except SnapshotMismatch:
            with lock:
                stats["refusals"] += 1

def qps(t0, t1):
    with lock:
        return sum(1 for t in times if t0 <= t < t1) / max(t1 - t0, 1e-9)

with helper, leader:
    assert all(r["status"] == "pass" for r in prober.run_cycle())
    threads = [threading.Thread(target=worker, args=(t,))
               for t in range(2)]
    for t in threads:
        t.start()
    t0 = time.monotonic()
    time.sleep(1.0)
    t1 = time.monotonic()
    failpoints.default_failpoints().arm(
        "snapshot.flip", "delay", times=4, delay_ms=FLIP_DELAY_MS)
    windows, staleness = [], []
    for gen in (1, 2):
        ldb = delta(leader.server.database, recs[gen])
        hdb = delta(helper.server.database, recs[gen])
        r0 = time.monotonic()
        report = coordinator.rotate(ldb, hdb)
        windows.append((r0, max(time.monotonic(), r0 + 0.25)))
        staleness.append(report["staleness_ms"])
        time.sleep(0.4)
    t2 = time.monotonic()
    time.sleep(1.0)
    t3 = time.monotonic()
    stop.set()
    for t in threads:
        t.join(timeout=30.0)
    # Goldens rotated with the flips: every probe passes on gen 2.
    results = prober.run_cycle()
    assert all(r["status"] == "pass" for r in results), results
    assert prober.export()["generation"] == 2, prober.export()

base_qps = qps(t0, t1)
rec_qps = qps(t2, t3)
worst = min(qps(w0, w1) for w0, w1 in windows)
dip_pct = max(0.0, (base_qps - worst) / base_qps * 100.0)
assert stats["torn"] == 0, stats
assert stats["completed"] > 0 and base_qps > 0, stats
# The armed delay stretched the window but it stayed bounded...
assert all(s >= FLIP_DELAY_MS * 0.8 for s in staleness), staleness
assert all(s < 5000.0 for s in staleness), staleness
# ...the dip is bounded (traffic never stopped) and recovers fully.
assert worst > 0, "throughput hit zero during rotation"
assert rec_qps >= 0.3 * base_qps, (rec_qps, base_qps)
snap = leader_mgr.export()
assert snap["serving_generation"] == 2 and snap["flips"] == 2, snap
assert snap["aborts"] == 0 and snap["retired_awaiting_drain"] == [], snap
completed = stats["completed"]
print("rotation-smoke: OK (2 rotations under load: staleness "
      f"{max(staleness):.1f} ms with {FLIP_DELAY_MS:.0f} ms flip delay "
      f"armed, dip {dip_pct:.0f}% of {base_qps:.0f} q/s baseline, "
      f"recovery {rec_qps:.0f} q/s, {completed} completed, 0 torn, "
      "prober bit-identical on generation 2)")
'

# --- shard-smoke: one logical Leader/Helper party served from a 2-D
# device mesh (4 database shards x 2 key lanes over 8 forced host
# devices), closed-loop traffic, one snapshot rotation at a batch
# boundary mid-traffic. Proves the PR 13 contract: every response is
# bit-identical to one generation's oracle (the 0xA5 mask makes a
# cross-generation mix match neither), the blackbox prober stays green
# through the flip with goldens rotating, and the flipped-to staging
# is fully sharded (all shards generation N+1, never a partial flip).
stage shard-smoke env JAX_PLATFORMS=cpu \
    XLA_FLAGS=--xla_force_host_platform_device_count=8 python -c '
import json, threading, time, urllib.request
import numpy as np
import jax
from distributed_point_functions_tpu.observability.admin import AdminServer
from distributed_point_functions_tpu.parallel.sharded import make_mesh2d
from distributed_point_functions_tpu.pir import (
    DenseDpfPirClient, DenseDpfPirDatabase,
)
from distributed_point_functions_tpu.prng import xor_bytes
from distributed_point_functions_tpu.serving import (
    PlainSession, ServingConfig, SnapshotManager,
)
from distributed_point_functions_tpu.serving.prober import Prober

assert len(jax.devices()) == 8, jax.devices()
NUM, NBYTES = 512, 16
rng = np.random.default_rng(13)
base = [bytes(rng.integers(0, 256, NBYTES, dtype=np.uint8))
        for _ in range(NUM)]
recs = {0: base, 1: [bytes(b ^ 0xA5 for b in r) for r in base]}

def build(records):
    b = DenseDpfPirDatabase.Builder()
    for r in records:
        b.insert(r)
    return b.build()

def delta(prev, records):
    b = DenseDpfPirDatabase.Builder()
    for i, r in enumerate(records):
        b.update(i, r)
    return b.build_from(prev)

mesh = make_mesh2d(4, 2)
config = ServingConfig(max_batch_size=4, max_wait_ms=1.0)
client = DenseDpfPirClient(NUM, lambda pt, info: pt)
lock = threading.Lock()
stats = {"completed": 0, "torn": 0}
stop = threading.Event()

with PlainSession(build(recs[0]), config, mesh=mesh) as session:
    mgr = SnapshotManager(session)
    prober = Prober(session, recs[0], period_s=0.1, indices=[0, 7, 501])
    prober.bind_snapshots(mgr, records_provider=lambda g: recs[g])

    def query(indices):
        r0, r1 = client.create_plain_requests(indices)
        a = session.handle_request(r0).dpf_pir_response.masked_response
        b = session.handle_request(r1).dpf_pir_response.masked_response
        return [xor_bytes(x, y) for x, y in zip(a, b)]

    # Warm every mesh jit bucket traffic and probes can form, so the
    # flip below lands at a fast steady-state batch boundary instead of
    # queueing behind a cold multi-device compile.
    assert query([3])[0] == recs[0][3]
    query([3, 500])
    query([3, 500, 7, 101])
    assert session.server._mesh_plan is not None, \
        "mesh server fell back to single-device"
    assert all(r["status"] == "pass" for r in prober.run_cycle())

    def worker(tid):
        i = tid
        while not stop.is_set():
            idx = (7 * i) % NUM
            i += 2
            got = query([idx])[0]
            with lock:
                stats["completed"] += 1
                if not any(got == r[idx] for r in recs.values()):
                    stats["torn"] += 1
            stop.wait(0.01)

    threads = [threading.Thread(target=worker, args=(t,))
               for t in range(2)]
    for t in threads:
        t.start()
    with prober:
        time.sleep(0.5)
        staged = mgr.stage(delta(session.server.database, recs[1]))
        assert staged > 0, "mesh staging transferred nothing"
        mgr.flip(timeout=60.0)
        time.sleep(0.5)
    stop.set()
    for t in threads:
        t.join(timeout=30.0)
    # Goldens rotated with the flip: the prober stays green on gen 1.
    results = prober.run_cycle()
    assert all(r["status"] == "pass" for r in results), results
    export = prober.export()
    assert export["mismatches"] == 0 and export["errors"] == 0, export
    snap = mgr.export()
    assert snap["serving_generation"] == 1 and snap["flips"] == 1, snap
    assert stats["torn"] == 0 and stats["completed"] > 0, stats
    assert query([3])[0] == recs[1][3]
    info = session.server.mesh_export()
    assert info["staging"]["generation"] == 1, info["staging"]
    # One row per device: 4 chunk shards x 2 key-axis replicas.
    per_dev = info["staging"]["shards"]
    assert len(per_dev) == 8, info["staging"]
    assert len({(s["chunk_start"], s["chunk_stop"]) for s in per_dev}) == 4
    assert session.server._mesh_plan is not None, "fell back post-flip"
    # The utilization ledger saw the mesh: every dispatch credits each
    # of the 4 chunk shards, so /utilz grows one busy row per shard.
    with AdminServer(registry=session.metrics) as admin:
        url = "http://127.0.0.1:%d/utilz?format=json" % admin.port
        util = json.load(urllib.request.urlopen(url))
        shard_rows = util["shards"]
        assert len(shard_rows) == 4, shard_rows
        assert all(row["busy_s"] > 0.0 for row in shard_rows.values()), \
            shard_rows
    completed = stats["completed"]
print("shard-smoke: OK (mesh 4x2 over 8 forced devices, 1 rotation "
      f"under load, {completed} completed, 0 torn, prober green on "
      "generation 1, staging sharded 4-ways, 4 shard rows on /utilz)")
'

# --- pipeline-smoke: the hot-path pipelining contract (ISSUE 14) end
# to end: closed-loop traffic through a depth-2 pipelined batcher with
# pipelined double-buffered staging on, one rotation fed by a
# `Builder.build_from` delta build (a handful of touched rows), and
# the observable signatures — zero prober failures through the flip,
# `/statusz` showing nonzero hidden (overlapped) transfer time with
# fewer db_staging syncs than copies, and the rotation prestage saving
# bytes over a full-image staging (`rotation_prestage_bytes_saved`).
stage pipeline-smoke env JAX_PLATFORMS=cpu \
    DPF_TPU_PIPELINED_STAGING=1 python -c '
import json, threading, time, urllib.request
import numpy as np
from distributed_point_functions_tpu.observability.admin import AdminServer
from distributed_point_functions_tpu.pir import (
    DenseDpfPirClient, DenseDpfPirDatabase,
)
from distributed_point_functions_tpu.prng import xor_bytes
from distributed_point_functions_tpu.serving import (
    PlainSession, ServingConfig, SnapshotManager,
)
from distributed_point_functions_tpu.serving.prober import Prober

NUM, NBYTES, TOUCHED = 256, 16, 12
rng = np.random.default_rng(14)
base = [bytes(rng.integers(0, 256, NBYTES, dtype=np.uint8))
        for _ in range(NUM)]
# Generation 1 rewrites only TOUCHED rows — the delta prestage must
# ship just those (plus the index vector), not the full image. The
# updated rows differ from their gen-0 bytes everywhere (XOR 0x5A), so
# a torn read of an updated row matches neither oracle.
updated = sorted(rng.choice(NUM, size=TOUCHED, replace=False).tolist())
recs = {0: base, 1: list(base)}
for i in updated:
    recs[1][i] = bytes(b ^ 0x5A for b in base[i])

def build(records):
    b = DenseDpfPirDatabase.Builder()
    for r in records:
        b.insert(r)
    return b.build()

def delta(prev):
    b = DenseDpfPirDatabase.Builder()
    for i in updated:
        b.update(i, recs[1][i])
    return b.build_from(prev)

config = ServingConfig(max_batch_size=4, max_wait_ms=1.0,
                       pipeline_depth=2)
client = DenseDpfPirClient(NUM, lambda pt, info: pt)
lock = threading.Lock()
stats = {"completed": 0, "torn": 0}
stop = threading.Event()

with PlainSession(build(recs[0]), config) as session:
    mgr = SnapshotManager(session)
    prober = Prober(session, recs[0], period_s=0.1,
                    indices=[0, updated[0], NUM - 1])
    prober.bind_snapshots(mgr, records_provider=lambda g: recs[g])

    def query(indices):
        r0, r1 = client.create_plain_requests(indices)
        a = session.handle_request(r0).dpf_pir_response.masked_response
        b = session.handle_request(r1).dpf_pir_response.masked_response
        return [xor_bytes(x, y) for x, y in zip(a, b)]

    # Warm the jit buckets, then confirm the batcher really is
    # pipelined (depth-2 completion thread, not the serial fallback).
    assert query([3])[0] == recs[0][3]
    query([3, updated[0], 7, 101])
    gauges = session.metrics.export()["gauges"]
    assert gauges.get("plain.batcher.pipeline_depth") == 2.0, gauges
    assert all(r["status"] == "pass" for r in prober.run_cycle())

    def worker(tid):
        i = tid
        while not stop.is_set():
            idx = (7 * i) % NUM
            i += 2
            got = query([idx])[0]
            with lock:
                stats["completed"] += 1
                if not any(got == r[idx] for r in recs.values()):
                    stats["torn"] += 1
            stop.wait(0.01)

    threads = [threading.Thread(target=worker, args=(t,))
               for t in range(2)]
    for t in threads:
        t.start()
    with prober:
        time.sleep(0.4)
        staged = mgr.stage(delta(session.server.database))
        assert staged > 0, "delta prestage transferred nothing"
        mgr.flip(timeout=60.0)
        time.sleep(0.4)
    stop.set()
    for t in threads:
        t.join(timeout=30.0)
    # Zero prober failures: green before, during (bound cycles), after.
    results = prober.run_cycle()
    assert all(r["status"] == "pass" for r in results), results
    export = prober.export()
    assert export["mismatches"] == 0 and export["errors"] == 0, export
    snap = mgr.export()
    assert snap["serving_generation"] == 1 and snap["flips"] == 1, snap
    assert stats["torn"] == 0 and stats["completed"] > 0, stats
    assert query([updated[0]])[0] == recs[1][updated[0]]
    # rotation_prestage_bytes_saved > 0: the delta rotation shipped a
    # fraction of the full image and SnapshotManager surfaced it.
    last_stage = snap["last_stage"]
    assert last_stage is not None, snap
    assert last_stage["mode"] == "delta", last_stage
    assert last_stage["bytes_saved"] > 0, last_stage
    assert last_stage["bytes_staged"] + last_stage["bytes_saved"] == \
        last_stage["bytes_full_image"], last_stage
    # /statusz shows the pipelined-staging signature: nonzero hidden
    # (overlapped) ms and strictly fewer db_staging syncs than copies.
    with AdminServer(registry=session.metrics, snapshots=mgr,
                     prober=prober) as admin:
        url = "http://127.0.0.1:%d/statusz" % admin.port
        state = json.load(urllib.request.urlopen(url + "?format=json"))
        transfers = state["device"]["transfers"]
        assert transfers["totals"]["overlapped_ms"] > 0.0, \
            transfers["totals"]
        db_phase = transfers["phases"]["db_staging"]
        assert db_phase["syncs"] < db_phase["h2d_copies"], db_phase
        html = urllib.request.urlopen(url).read().decode()
        assert "hidden behind host work" in html
    completed = stats["completed"]
    saved = last_stage["bytes_saved"]
    full_image = last_stage["bytes_full_image"]
    hidden_ms = transfers["totals"]["overlapped_ms"]
print("pipeline-smoke: OK (depth-2 batcher, 1 delta rotation under "
      f"load, {completed} completed, 0 torn, prober green on "
      f"generation 1, prestage saved {saved} of {full_image} bytes, "
      f"overlapped {hidden_ms:.1f} ms hidden)")
'

# --- util-smoke: the device-seconds ledger (ISSUE 15) end to end.
# Closed-loop traffic with think-time gaps through a real Leader /
# Helper pair must land a nonzero duty cycle on /utilz whose bubble
# breakdown is populated with typed causes summing to the measured
# idle; the flight-data sampler (driven on a synthetic 1 Hz clock so
# the stage is fast and deterministic) accrues >= 60 s of history; an
# injected 80 ms delay failpoint on the in-process helper leg spikes
# the helper-latency p99 past the anomaly watch band and must journal
# a util.anomaly; and a debug bundle captured after the stall must
# carry the full time-series history plus the anomaly in its journal
# tail.
stage util-smoke env JAX_PLATFORMS=cpu python -c '
import json, os, time, urllib.request
import numpy as np
from distributed_point_functions_tpu.observability import (
    events as events_mod,
)
from distributed_point_functions_tpu.observability.admin import AdminServer
from distributed_point_functions_tpu.observability.bundle import (
    BundleManager,
)
from distributed_point_functions_tpu.observability.timeseries import (
    MetricsSampler,
)
from distributed_point_functions_tpu.observability.utilization import (
    default_utilization_tracker,
)
from distributed_point_functions_tpu.pir import (
    DenseDpfPirClient, DenseDpfPirDatabase,
)
from distributed_point_functions_tpu.robustness.failpoints import (
    default_failpoints,
)
from distributed_point_functions_tpu.serving import (
    HelperSession, LeaderSession, ServingConfig,
)
from distributed_point_functions_tpu.serving.transport import (
    InProcessTransport,
)
from distributed_point_functions_tpu.testing import encrypt_decrypt

NUM, NBYTES = 256, 16
rng = np.random.default_rng(15)
recs = [bytes(rng.integers(0, 256, NBYTES, dtype=np.uint8))
        for _ in range(NUM)]
builder = DenseDpfPirDatabase.Builder()
for r in recs:
    builder.insert(r)
db = builder.build()

config = ServingConfig(max_batch_size=4, max_wait_ms=1.0)
tracker = default_utilization_tracker()
with HelperSession(db, encrypt_decrypt.decrypt, config) as helper, \
        LeaderSession(db, InProcessTransport(helper.handle_wire),
                      config) as leader:
    client = DenseDpfPirClient.create(NUM, encrypt_decrypt.encrypt)

    def query(indices):
        request, state = client.create_request(indices)
        return client.handle_response(
            leader.handle_request(request), state
        )

    assert query([3]) == [recs[3]]
    # Closed-loop traffic with think-time gaps: the worker sees both
    # busy evals and typed idle bubbles (empty_queue / batch_wait).
    for i in range(24):
        idx = (7 * i) % NUM
        assert query([idx]) == [recs[idx]]
        time.sleep(0.005)

    # Flight-data sampler on a synthetic 1 Hz clock: fake timestamps
    # end at the real monotonic clock so ring-horizon checks against
    # the live clock keep every point. The helper_net phase reservoir
    # is the stall-sensitive series: unlike the end-to-end latency
    # histograms (whose p99 is pinned at the ~seconds first-compile
    # outlier), its p99 sits at ~1 ms until the failpoint fires.
    sampler = MetricsSampler(
        registry=leader.metrics, utilization=tracker, jitter_frac=0.0,
        include=("util.", "leader.", "phase_ms{phase=helper_net"),
    )
    base = time.monotonic() - 75.0
    for i in range(70):
        if i % 10 == 0:
            query([(3 * i) % NUM])
        sampler.sample_once(now=base + i)

    # Inject the stall: 80 ms on every in-process helper roundtrip,
    # >> the ~1 ms baseline, so helper-latency p99 blows through the
    # 3x trailing-mean anomaly band on the next sample.
    fps = default_failpoints()
    fps.arm("transport.inproc.roundtrip", action="delay",
            delay_ms=80.0, times=3)
    try:
        for i in range(3):
            query([i])
    finally:
        fps.disarm("transport.inproc.roundtrip")
    sampler.sample_once(now=base + 71.0)

    anoms = events_mod.default_journal().tail(50, kind="util.anomaly")
    assert anoms, "injected stall journaled no util.anomaly"
    assert any(
        "helper_net" in e.get("series", "")
        and e.get("direction") == "spike"
        for e in anoms
    ), anoms

    bundles = BundleManager(cooldown_s=0.0, max_bundles=2)
    with AdminServer(registry=leader.metrics, utilization=tracker,
                     timeseries=sampler, bundles=bundles) as admin:
        url = "http://127.0.0.1:%d" % admin.port
        snap = json.load(
            urllib.request.urlopen(url + "/utilz?format=json")
        )
        totals = snap["totals"]
        duty = totals["duty_cycle_pct"]
        assert duty is not None and duty > 0.0, totals
        causes = totals["idle_s"]
        assert causes, "bubble breakdown empty"
        assert set(causes) & {"empty_queue", "batch_wait",
                              "admission_shed"}, causes
        # Attribution is complete: typed causes sum to measured idle
        # (each cause rounds independently, hence the tolerance).
        assert abs(sum(causes.values()) - totals["idle_total_s"]) \
            < 1e-3, totals
        ts = json.load(
            urllib.request.urlopen(url + "/timeseriesz?format=json")
        )
        assert ts["store"]["series_count"] > 0, ts

        entry = bundles.trigger(
            "injected_stall",
            {"site": "transport.inproc.roundtrip"},
        )
        assert entry is not None, "bundle capture suppressed"
        assert entry["sources"].get("timeseries") == "ok", entry
        with open(os.path.join(entry["path"], "timeseries.json")) as f:
            hist = json.load(f)
        spans = [
            pts[-1][0] - pts[0][0]
            for tiers in hist["store"]["series"].values()
            if len(pts := tiers.get("1s", [])) >= 2
        ]
        assert spans and max(spans) >= 60.0, \
            "bundle carries < 60 s of history"
        with open(os.path.join(entry["path"], "events.json")) as f:
            journal_tail = json.load(f)
        assert any(
            e.get("kind") == "util.anomaly"
            for e in journal_tail["events"]
        ), "anomaly missing from bundle journal tail"
        history_s = max(spans)
print(f"util-smoke: OK (duty cycle {duty:.1f}%, "
      f"{len(causes)} bubble causes summing to idle, util.anomaly "
      f"journaled after 80 ms injected stall, bundle carries "
      f"{history_s:.0f} s of flight data)")
'

stage sparse-smoke env JAX_PLATFORMS=cpu python -c '
import threading, time
from distributed_point_functions_tpu.pir.cuckoo_database import (
    CuckooHashedDpfPirDatabase,
)
from distributed_point_functions_tpu.pir.sparse_client import KeyNotFound
from distributed_point_functions_tpu.pir.sparse_server import (
    CuckooHashingSparseDpfPirServer,
)
from distributed_point_functions_tpu.serving import (
    ServingConfig, SnapshotManager, SparsePlainSession,
    make_sparse_client, sparse_lookup_plain,
)
from distributed_point_functions_tpu.serving.prober import Prober

NUM = 48
# Fixed-width keys and values: a delta rotation preserves the packed
# row width of each dense store, so upserts must stay in-width.
records = {b"key_%02d" % i: b"val_%02d" % i for i in range(NUM)}
params = CuckooHashingSparseDpfPirServer.generate_params(
    NUM, seed=b"0123456789abcdef"
)
builder = CuckooHashedDpfPirDatabase.Builder().set_params(params)
for kv in records.items():
    builder.insert(kv)
db = builder.build()

session = SparsePlainSession(
    params, db, ServingConfig(max_batch_size=8, max_wait_ms=1.0)
)
client = make_sparse_client(session)
manager = SnapshotManager(session)
new_records = dict(records)
new_records[b"key_02"] = b"VAL_02"
new_records[b"new_01"] = b"val_99"
prober = Prober(session, sparse_records=records, period_s=0.1)
prober.bind_snapshots(manager, lambda gen: new_records)

# Warm: makes the gen-0 stagings resident (prereq for a delta
# prestage) and pays the jit compile outside the loaded window.
warm = sparse_lookup_plain(session, client, [b"key_05", b"absent"])
assert warm[0] == b"val_05" and isinstance(warm[1], KeyNotFound), warm

stop = threading.Event()
failures, served = [], [0]

def traffic():
    while not stop.is_set():
        # Two-share lookups pin the manager so the armed flip cannot
        # land between the shares (cross-generation XOR is garbage).
        with manager.pin():
            out = sparse_lookup_plain(
                session, client, [b"key_05", b"absent"]
            )
        if out[0] != b"val_05" or not isinstance(out[1], KeyNotFound):
            failures.append(out)
            return
        served[0] += 2
        time.sleep(0.02)

threads = [threading.Thread(target=traffic) for _ in range(2)]
for t in threads:
    t.start()
try:
    assert all(
        r["status"] == "pass" for r in prober.run_cycle()
    ), prober.export()
    delta = CuckooHashedDpfPirDatabase.Builder()
    delta.insert((b"key_02", b"VAL_02"))
    delta.insert((b"new_01", b"val_99"))
    db1 = delta.build_from(db)
    staged = manager.stage(db1)
    assert staged > 0
    stats = db1.last_prestage_stats
    assert stats is not None and stats["mode"] == "delta", stats
    assert stats["bytes_saved"] > 0, stats
    assert (
        stats["bytes_staged"] + stats["bytes_saved"]
        == stats["bytes_full_image"]
    ), stats
    manager.flip(timeout=120.0)
    assert all(
        r["status"] == "pass" for r in prober.run_cycle()
    ), prober.export()
finally:
    stop.set()
    for t in threads:
        t.join(timeout=60)
assert not failures, failures[:1]
assert manager.serving_generation() == 1

out = sparse_lookup_plain(
    session, client, [b"key_02", b"new_01", b"key_07", b"absent"]
)
assert out[0] == b"VAL_02" and out[1] == b"val_99", out
assert out[2] == b"val_07", out
assert isinstance(out[3], KeyNotFound) and not out[3], out

export = prober.export()
assert export["mismatches"] == 0 and export["errors"] == 0, export
assert export["generation"] == 1, export
kinds = set(export["freshness"])
assert kinds == {"sparse_kv", "sparse_absent"}, kinds
assert all(v["identity"] for v in export["freshness"].values())
snap = manager.export()
assert snap["serving_generation"] == 1 and snap["flips"] == 1, snap
print(
    "sparse-smoke: OK (%d lookups under load, delta rotation saved "
    "%d of %d bytes, %d probes all green, absent key stayed typed "
    "not-found)" % (
        served[0], stats["bytes_saved"], stats["bytes_full_image"],
        export["probes"],
    )
)
'

# --- fleet-smoke: ISSUE 18 end to end. A 3-replica in-process fleet
# serves closed-loop tenants through the price-aware front door while
# one quorum rotation runs with a replica killed mid-stage (failpoint
# on its per-replica chaos site). Asserts: zero wrong bits ever served
# (every reconstruction matches the oracle of SOME single generation),
# quorum held (2/3) so the fleet committed, the laggard was shed,
# converged party by party, and readmitted, and /fleetz reflects the
# final state — 3 serving replicas all at the new generation.
stage fleet-smoke env JAX_PLATFORMS=cpu python -c '
import contextlib, json, threading, time, urllib.request
import numpy as np
from distributed_point_functions_tpu.fleet import (
    FleetRotationCoordinator, FleetRouter, Replica, ReplicaSet,
)
from distributed_point_functions_tpu.observability import AdminServer
from distributed_point_functions_tpu.pir.client import DenseDpfPirClient
from distributed_point_functions_tpu.pir.database import (
    DenseDpfPirDatabase,
)
from distributed_point_functions_tpu.prng import xor_bytes
from distributed_point_functions_tpu.robustness import failpoints
from distributed_point_functions_tpu.serving import (
    PlainSession, ServingConfig, SnapshotManager,
)
from distributed_point_functions_tpu.serving.batcher import Overloaded

NUM, NB = 64, 16
rng = np.random.default_rng(77)
R0 = [bytes(rng.integers(0, 256, NB, dtype=np.uint8)) for _ in range(NUM)]
R1 = [bytes(b ^ 0xA5 for b in r) for r in R0]  # differs at every byte

def full(records):
    b = DenseDpfPirDatabase.Builder()
    for r in records:
        b.insert(r)
    return b.build()

def delta(prev, records):
    b = DenseDpfPirDatabase.Builder()
    for i, r in enumerate(records):
        b.update(i, r)
    return b.build_from(prev)

cfg = ServingConfig(max_batch_size=8, max_wait_ms=2.0)
rs = ReplicaSet()
reps = []
for i in range(3):
    s = PlainSession(full(R0), cfg)
    reps.append(
        rs.add(Replica("r%d" % i, s, leader_snapshots=SnapshotManager(s)))
    )
router = FleetRouter(rs)
client = DenseDpfPirClient(NUM, lambda pt, info: pt)
w0, w1 = client.create_plain_requests([0])
for r in reps:  # warm the jit bucket on every replica
    r.leader.handle_request(w0)
    r.leader.handle_request(w1)

oracles = [R0, R1]
stats = {"done": 0, "wrong": 0, "sheds": 0}
lock = threading.Lock()
stop = threading.Event()

def worker(tid):
    tenant = "t%d" % tid
    i = tid
    while not stop.is_set():
        idx = (i * 7) % NUM
        i += 1
        try:
            rep = router.pick(tenant)
            q0, q1 = client.create_plain_requests([idx])
            # Pin the replica so both halves of the golden pair answer
            # from ONE generation (cross-generation XOR is garbage).
            with contextlib.ExitStack() as st:
                for m in rep.managers():
                    st.enter_context(m.pin())
                a = rep.leader.handle_request(q0)
                b = rep.leader.handle_request(q1)
            got = xor_bytes(
                a.dpf_pir_response.masked_response[0],
                b.dpf_pir_response.masked_response[0],
            )
            with lock:
                stats["done"] += 1
                if not any(got == recs[idx] for recs in oracles):
                    stats["wrong"] += 1
        except Overloaded:
            with lock:
                stats["sheds"] += 1
            time.sleep(0.002)
        time.sleep(0.001)  # unpinned window: never starve the flip

threads = [
    threading.Thread(target=worker, args=(t,)) for t in range(3)
]
for t in threads:
    t.start()
time.sleep(0.3)

# One quorum rotation with r1 killed mid-stage: quorum 2/3 holds, the
# laggard is shed, converged, and readmitted while traffic flows.
failpoints.default_failpoints().arm("fleet.stage.r1", "error", times=1)
coord = FleetRotationCoordinator(rs)
report = coord.rotate(
    lambda rep: (delta(rep.leader.server.database, R1), None)
)
assert report["to_generation"] == 1, report
assert sorted(report["acked"]) == ["r0", "r2"], report
assert report["laggards"] == {"r1": "recovered"}, report
time.sleep(0.3)
stop.set()
for t in threads:
    t.join(timeout=10)
failpoints.default_failpoints().clear()

assert stats["done"] > 0 and stats["wrong"] == 0, stats
export = rs.export()
assert export["sheds"] == 1 and export["readmissions"] == 1, export
assert all(r.serving_generation() == 1 for r in reps)
with AdminServer(fleet=rs) as admin:
    url = "http://127.0.0.1:%d/fleetz" % admin.port
    state = json.loads(urllib.request.urlopen(url, timeout=10).read())
assert state["counts"] == {
    "serving": 3, "staging": 0, "draining": 0, "dead": 0
}, state["counts"]
assert all(
    row["serving_generation"] == 1
    for row in state["replicas"].values()
), state["replicas"]
for r in reps:
    r.leader.close()
print(
    "fleet-smoke: OK (%d lookups across 3 replicas, 0 wrong bits, "
    "quorum rotation -> generation 1 with r1 killed mid-stage: "
    "laggard shed + readmitted, /fleetz all serving)" % stats["done"]
)
'

# --- fleet-obs-smoke: ISSUE 19 end to end. The same 3-replica fleet
# under closed-loop traffic, now with the fleet telemetry plane
# attached: per-replica scopes feeding one aggregator, a quorum
# rotation with a DELAY failpoint on r1's stage site, then a forced
# divergence (r2 staged different records at the same generation).
# Asserts: /fleet-statusz?format=json carries per-replica rows AND the
# merged view; the merged /fleet-timelinez tells the rotation story
# causally (every replica's snapshot.flip before the fleet.rotation
# commit event, each attributed to its replica); and the divergence
# produces EXACTLY ONE fleet-wide debug bundle holding all three
# replicas' sections plus the merged timeline.
stage fleet-obs-smoke env JAX_PLATFORMS=cpu python -c '
import contextlib, json, tempfile, threading, time, urllib.request
import numpy as np
from distributed_point_functions_tpu.fleet import (
    FleetRotationCoordinator, FleetRouter, FleetTelemetry, Replica,
    ReplicaSet,
)
from distributed_point_functions_tpu.observability import AdminServer
from distributed_point_functions_tpu.observability.bundle import (
    BundleManager,
)
from distributed_point_functions_tpu.observability.events import (
    EventJournal,
)
from distributed_point_functions_tpu.pir.client import DenseDpfPirClient
from distributed_point_functions_tpu.pir.database import (
    DenseDpfPirDatabase,
)
from distributed_point_functions_tpu.prng import xor_bytes
from distributed_point_functions_tpu.robustness import failpoints
from distributed_point_functions_tpu.serving import (
    PlainSession, ServingConfig, SnapshotManager,
)
from distributed_point_functions_tpu.serving.batcher import Overloaded
from distributed_point_functions_tpu.serving.metrics import MetricsRegistry
from distributed_point_functions_tpu.serving.prober import CrossReplicaProbe

NUM, NB = 64, 16
rng = np.random.default_rng(19)
R0 = [bytes(rng.integers(0, 256, NB, dtype=np.uint8)) for _ in range(NUM)]
R1 = [bytes(b ^ 0xA5 for b in r) for r in R0]
R1_BAD = [bytes(b ^ 0x3C for b in r) for r in R0]  # r2s forced skew

def full(records):
    b = DenseDpfPirDatabase.Builder()
    for r in records:
        b.insert(r)
    return b.build()

def delta(prev, records):
    b = DenseDpfPirDatabase.Builder()
    for i, r in enumerate(records):
        b.update(i, r)
    return b.build_from(prev)

cfg = ServingConfig(max_batch_size=8, max_wait_ms=2.0)
journal = EventJournal(capacity=256)
rs = ReplicaSet(journal=journal)
reps = []
for i in range(3):
    s = PlainSession(full(R0), cfg)
    reps.append(
        rs.add(Replica("r%d" % i, s, leader_snapshots=SnapshotManager(s)))
    )
fleet_registry = MetricsRegistry()
router = FleetRouter(rs, journal=journal, metrics=fleet_registry)
probe = CrossReplicaProbe(
    rs.healthy, R0,
    records_provider=lambda gen: {0: R0, 1: R1}.get(gen),
    journal=journal,
)
telemetry = FleetTelemetry(
    rs, router=router, probe=probe, journal=journal,
    registry=fleet_registry,
)
for r in reps:
    telemetry.scope(r)
bundle_dir = tempfile.mkdtemp(prefix="fleet-obs-smoke-")
bundles = BundleManager(
    directory=bundle_dir, cooldown_s=60.0, journal=journal,
)
telemetry.wire_bundles(bundles)

client = DenseDpfPirClient(NUM, lambda pt, info: pt)
w0, w1 = client.create_plain_requests([0])
for r in reps:  # warm the jit bucket on every replica
    r.leader.handle_request(w0)
    r.leader.handle_request(w1)
assert probe.run_cycle()["status"] == "pass"

stats = {"done": 0, "wrong": 0, "sheds": 0}
lock = threading.Lock()
stop = threading.Event()

def worker(tid):
    tenant = "t%d" % tid
    i = tid
    while not stop.is_set():
        idx = (i * 7) % NUM
        i += 1
        try:
            rep = router.pick(tenant)
            q0, q1 = client.create_plain_requests([idx])
            with contextlib.ExitStack() as st:
                for m in rep.managers():
                    st.enter_context(m.pin())
                a = rep.leader.handle_request(q0)
                b = rep.leader.handle_request(q1)
            got = xor_bytes(
                a.dpf_pir_response.masked_response[0],
                b.dpf_pir_response.masked_response[0],
            )
            with lock:
                stats["done"] += 1
                if not any(got == recs[idx] for recs in (R0, R1)):
                    stats["wrong"] += 1
        except Overloaded:
            with lock:
                stats["sheds"] += 1
            time.sleep(0.002)
        time.sleep(0.001)

threads = [
    threading.Thread(target=worker, args=(t,), daemon=True)
    for t in range(3)
]
for t in threads:
    t.start()
telemetry.sample()
time.sleep(0.3)

# One quorum rotation with a DELAY failpoint on r1s stage site: a
# latency spike is not a fault, so the fleet commits. Under closed-
# loop traffic a replica may still miss the drain window and lag --
# acceptable only if the coordinator converged and readmitted it
# before returning. The telemetry resample hooked to the coordinator
# refreshes staleness right at the commit.
failpoints.default_failpoints().arm(
    "fleet.stage.r1", "delay", delay_ms=100, times=1
)
coord = FleetRotationCoordinator(rs, journal=journal)
coord.set_telemetry(telemetry)
report = coord.rotate(
    lambda rep: (delta(rep.leader.server.database, R1), None)
)
assert report["to_generation"] == 1, report
assert set(report["laggards"].values()) <= {"recovered"}, report
time.sleep(0.3)
telemetry.sample()
stop.set()
for t in threads:
    t.join(timeout=10)
failpoints.default_failpoints().clear()
assert stats["done"] > 0 and stats["wrong"] == 0, stats
assert probe.run_cycle()["status"] == "pass"

with AdminServer(fleet=rs, fleet_telemetry=telemetry) as admin:
    base = "http://127.0.0.1:%d" % admin.port
    state = json.loads(urllib.request.urlopen(
        base + "/fleet-statusz?format=json", timeout=10).read())
    # Per-replica rows AND the merged view, in one document.
    assert sorted(state["replicas"]) == ["r0", "r1", "r2"], sorted(
        state["replicas"])
    per_replica_counts = {}
    for rid, scrape in state["replicas"].items():
        assert scrape["state"] == "serving", (rid, scrape["state"])
        hist = scrape["metrics"]["histograms"]["plain.request_ms"]
        per_replica_counts[rid] = hist["count"]
        assert hist["count"] > 0, (rid, hist)
    merged_hist = state["merged"]["histograms"]["plain.request_ms"]
    assert merged_hist["count"] == sum(per_replica_counts.values())
    assert merged_hist["replicas"] == ["r0", "r1", "r2"]
    assert state["verdict"]["status"] == "ok", state["verdict"]
    slo_states = {
        o["name"]: o["state"] for o in state["slo"]["objectives"]
    }
    assert slo_states["fleet_routable_floor"] == "ok", slo_states

    # The merged timeline tells the rotation story causally: every
    # replica flipped (each snapshot.flip attributed to its replica)
    # BEFORE the fleet.rotation commit event.
    timeline = json.loads(urllib.request.urlopen(
        base + "/fleet-timelinez?format=json&n=256", timeout=10).read())
    events = timeline["events"]
    flips = {
        e["replica"]: i for i, e in enumerate(events)
        if e["kind"] == "snapshot.flip"
    }
    commits = [
        i for i, e in enumerate(events) if e["kind"] == "fleet.rotation"
    ]
    assert sorted(flips) == ["r0", "r1", "r2"], flips
    assert len(commits) == 1, commits
    assert all(i < commits[0] for i in flips.values()), (flips, commits)
    text = urllib.request.urlopen(
        base + "/fleet-timelinez?n=64", timeout=10).read().decode()
    assert "fleet.rotation" in text and "r1" in text

# Forced divergence: r2 stages DIFFERENT records and flips to the same
# generation number the quorum is about to reach -- two replicas now
# answer generation 2 with different bytes. The probe must catch it
# and the plane must capture EXACTLY ONE fleet-wide bundle.
coord.rotate(
    lambda rep: (
        delta(
            rep.leader.server.database,
            R1_BAD if rep.replica_id == "r2" else R0,
        ),
        None,
    )
)
result = probe.run_cycle()
assert result["status"] == "mismatch", result
probe.run_cycle()  # a second divergent cycle lands in the cooldown
export = bundles.export()
assert export["fired"] == 1, export
assert export["suppressed_cooldown"] >= 1, export
entry = export["bundles"][0]
assert entry["reason"] == "probe_failure", entry
for source in (
    "replica_r0", "replica_r1", "replica_r2",
    "fleet_timeline", "fleet_status",
):
    assert entry["sources"][source] == "ok", (source, entry["sources"])
with open(entry["path"] + "/fleet_timeline.json") as f:
    bundled = json.load(f)
assert any(
    e["kind"] == "fleet.divergence" for e in bundled["events"]
), [e["kind"] for e in bundled["events"]][-8:]
for r in reps:
    r.leader.close()
print(
    "fleet-obs-smoke: OK (%d lookups, /fleet-statusz per-replica+merged"
    ", causal rotation timeline, forced divergence -> 1 fleet bundle "
    "with all 3 replica sections)" % stats["done"]
)
'

# --- forecast-smoke: the act-before-burn loop end to end. Synthetic
# load ramps toward a deliberately lowered calibrated capacity on an
# injected clock (deterministic Holt fit); the page must land in the
# journal while the hard SLO has never burned, the governor must
# tighten visibly and revert exactly, and every response served during
# the drill must stay bit-identical to the oracle.
stage forecast-smoke env JAX_PLATFORMS=cpu python -c '
import json
import urllib.request
import numpy as np
from distributed_point_functions_tpu.capacity import TenantPolicy
from distributed_point_functions_tpu.capacity.admission import (
    PredictiveGovernor,
)
from distributed_point_functions_tpu.observability import (
    AdminServer, EventJournal, Forecaster, SloObjective, SloTracker,
    TimeSeriesStore, WorkloadObservatory,
)
from distributed_point_functions_tpu.pir import (
    DenseDpfPirClient, DenseDpfPirDatabase,
)
from distributed_point_functions_tpu.serving import (
    PlainSession, ServingConfig,
)

t = [0.0]
clock = lambda: t[0]

builder = DenseDpfPirDatabase.Builder()
rng = np.random.default_rng(2)
for _ in range(16):
    builder.insert(bytes(rng.integers(0, 256, 8, dtype=np.uint8)))
db = builder.build()
client = DenseDpfPirClient.create(16, lambda pt, ci: pt)
request = client.create_plain_requests([5])[0]

config = ServingConfig(
    max_batch_size=4, max_wait_ms=1.0, admission_enabled=True
)
journal = EventJournal(capacity=128, clock=clock)
store = TimeSeriesStore(tiers=((1.0, 240),), max_series=8, clock=clock)

with PlainSession(db, config) as session:
    want = session.handle_request(request).dpf_pir_response.masked_response
    observatory = session.attach_workload(WorkloadObservatory())
    session.set_tenant("ramp", TenantPolicy(rate_qps=500.0))

    # The deliberately lowered calibrated capacity: 1% of the model.
    calibrated = session.admission.model.serving_queries_per_sec()
    lowered = max(1.0, 0.01 * calibrated)

    forecaster = Forecaster(
        store, window_s=60.0, horizon_s=120.0, page_horizon_s=120.0,
        min_points=8, registry=session.metrics, journal=journal,
        clock=clock,
    )
    forecaster.watch(
        "load.rate_qps", ceiling_source=lambda: lowered,
        label="offered load vs lowered calibrated capacity",
    )
    governor = PredictiveGovernor(
        session.admission, forecaster.min_time_to_breach_s,
        horizon_s=120.0, floor=0.25, metrics=session.metrics,
        clock=clock,
    )
    # The hard SLO the prediction must beat: offered load at or above
    # the lowered capacity.
    hard = SloTracker(
        [SloObjective(
            name="load_ceiling", kind="gauge_max",
            metric="load.rate_qps", threshold=lowered, severity="hard",
        )],
        session.metrics, clock=clock,
    )

    served = [0]
    def tick(rate):
        t[0] += 1.0
        store.record("load.rate_qps", rate)
        session.metrics.gauge("load.rate_qps").set(rate)
        got = session.handle_request(request, tenant="ramp")
        assert got.dpf_pir_response.masked_response == want
        served[0] += 1

    assert governor.update() == 1.0  # calm: policy as declared

    # --- the ramp: 60 synthetic seconds climbing from 20% to 70% of
    # the lowered capacity — never touching it. -----------------------
    for i in range(60):
        tick(lowered * (0.2 + 0.5 * i / 59.0))
    state = forecaster.run()
    ttb = state["min_time_to_breach_s"]
    assert ttb is not None and 0.0 < ttb < forecaster.horizon_s, state
    predicted = journal.tail(10, kind="forecast.breach_predicted")
    assert predicted, "breach_predicted missing from the journal"
    (burn,) = hard.evaluate()
    assert burn["state"] == "ok" and burn["burn_s"] == 0.0, burn

    # --- the governor tightens, visibly. -----------------------------
    scale = governor.update()
    assert scale < 1.0, scale
    adm = session.admission.export()
    assert adm["rate_scale"] == scale, adm
    assert adm["tenants"]["ramp"]["effective_rate_qps"] < 500.0, adm
    with AdminServer(registry=session.metrics, forecast=forecaster,
                     governor=governor) as admin:
        base = "http://127.0.0.1:%d" % admin.port
        fz = json.load(
            urllib.request.urlopen(base + "/forecastz?format=json")
        )
        assert fz["min_time_to_breach_s"] is not None, fz
        assert fz["min_time_to_breach_s"] < 120.0, fz
        assert fz["governor"]["scale"] < 1.0, fz
        cz = urllib.request.urlopen(base + "/capacityz").read().decode()
        assert "predictive governor: scale" in cz, cz
        assert "ramp: rate 500.0 ->" in cz, cz

    # --- the ramp recedes: forecast clears, exact revert. ------------
    for _ in range(70):
        tick(lowered * 0.2)
    assert forecaster.min_time_to_breach_s() is None
    assert governor.update() == 1.0
    adm = session.admission.export()
    assert adm["rate_scale"] == 1.0, adm
    assert adm["tenants"]["ramp"]["effective_rate_qps"] == 500.0, adm
    got = session.handle_request(request, tenant="ramp")
    assert got.dpf_pir_response.masked_response == want
    assert observatory.export()["observations"] >= served[0]
print(
    "forecast-smoke: OK (breach predicted %.0fs out with 0s of hard "
    "burn, governor tightened to x%.2f on /capacityz and reverted, "
    "%d bit-identical responses)" % (ttb, scale, served[0] + 2)
)
'

stage perf-gate python -m benchmarks.regression_gate --check-only \
    --history benchmarks/fixtures/history_fixture.jsonl

stage dryrun make -s dryrun

if [ "${FULL:-0}" = "1" ]; then
    stage test-full python -m pytest tests/ -q
fi

echo "presubmit: $([ $fail -eq 0 ] && echo PASS || echo FAIL)"
exit $fail
