#!/usr/bin/env bash
# Presubmit pipeline — the TPU build's equivalent of the reference's
# .bazelci/presubmit.yml:15-34 (two-compiler matrix, benchmark-tagged
# targets excluded). Stages:
#   1. lint        — stdlib AST lint (tools/lint.py)
#   2. layers      — serving -> pir -> ops layer DAG + import-cycle
#                    check (tools/check_layers.py)
#   3. protos      — generated *_pb2.py match protos/*.proto
#   4. native      — C++ oracle kernels build (g++)
#   5. test-fast   — <5 min hermetic signal tier (incl. tiny-shape
#                    interpret cases of every serving Pallas kernel)
#   6. hh-smoke    — heavy-hitters sweep end to end (tiny domain,
#                    2 levels, in-process transport, plaintext check)
#   7. admin-smoke — operator telemetry endpoint: serve one traced
#                    request, then scrape /healthz, /metrics (Prometheus
#                    text with exemplars), /statusz (compile counts, HBM
#                    watermarks, SLO burn, phase waterfall, transfer
#                    ledger, auto-captured profiles) and /tracez off a
#                    live AdminServer, check a hard SLO breach degrades
#                    /healthz to 503, and check a synthetic latency-SLO
#                    burn produces exactly one auto-capture entry
#   8. perf-gate   — benchmarks/regression_gate.py --check-only against
#                    the committed history fixture (CPU-safe: judges
#                    records, runs no bench)
#   9. dryrun      — 8-virtual-device multichip compile+step
# Benchmarks are excluded exactly as the reference excludes
# `--test_tag_filters=-benchmark`. `FULL=1` appends the whole suite.
set -u -o pipefail
cd "$(dirname "$0")/.."
fail=0

stage() {
    echo "=== presubmit: $1 ==="
    shift
    "$@" || { echo "FAILED: $*"; fail=1; }
}

stage lint python tools/lint.py

stage layers python tools/check_layers.py

stage protoc-check bash -c '
    tmp=$(mktemp -d) &&
    protoc --python_out="$tmp" -Iprotos \
        protos/distributed_point_function.proto \
        protos/distributed_comparison_function.proto \
        protos/multiple_interval_containment.proto \
        protos/private_information_retrieval.proto \
        protos/hash_family_config.proto &&
    ok=0 &&
    for f in "$tmp"/*_pb2.py; do
        name=$(basename "$f")
        cmp -s "$f" "distributed_point_functions_tpu/protos/$name" \
            || { echo "stale generated proto: $name"; ok=1; }
    done; rm -rf "$tmp"; exit $ok'

stage native bash -c 'cd native && bash build.sh'

stage test-fast make -s test-fast

stage hh-smoke env JAX_PLATFORMS=cpu \
    python examples/heavy_hitters_demo.py --smoke

stage admin-smoke env JAX_PLATFORMS=cpu python -c '
import json, urllib.error, urllib.request
from distributed_point_functions_tpu import observability as obs
from distributed_point_functions_tpu.observability import phases as pm
from distributed_point_functions_tpu.observability.slo import (
    SloObjective, SloTracker,
)
from distributed_point_functions_tpu.serving.metrics import MetricsRegistry

reg = MetricsRegistry()
rec = obs.tracing.FlightRecorder()
dev = obs.DeviceTelemetry(registry=reg)
phases = obs.PhaseRecorder()
with obs.tracing.trace_request("smoke.request", recorder=rec):
    with reg.timed("smoke.request_ms"):
        with phases.request("smoke"):
            with pm.phase("h2d_transfer"):
                dev.transfers.record_h2d(4096, "key_staging")
            pm.record("device_compute", 2.5)
            with obs.tracing.span("device_compute"):
                with dev.hbm.phase("db_staging"):
                    dev.hbm.sample()
with dev.compile_tracker.dispatch("smoke.evaluate", "q64.b8192"):
    pass
with dev.compile_tracker.dispatch("smoke.evaluate", "q64.b8192"):
    pass
slo = SloTracker(
    [SloObjective(name="smoke_p99", kind="p99_ms_max",
                  metric="smoke.request_ms", threshold=1e-9)],
    registry=reg,
)
prof = obs.AutoProfiler(
    slo, capture_fn=lambda r: {"log_dir": "/tmp/smoke-capture"},
    async_capture=False,
)
slo.evaluate()  # synthetic latency-SLO burn -> one inline capture
slo.evaluate()  # continuing breach must NOT re-fire
assert len(prof.captures()) == 1, prof.export()
with obs.AdminServer(registry=reg, recorder=rec, device=dev,
                     slo=slo, phases=phases,
                     autoprofiler=prof) as admin:
    base = f"http://127.0.0.1:{admin.port}"
    text = urllib.request.urlopen(base + "/metrics").read().decode()
    assert "# TYPE dpf_smoke_request_ms histogram" in text, text
    assert "dpf_smoke_request_ms_bucket" in text, text
    assert "# {trace_id=" in text, text  # exemplar on a bucket line
    assert "dpf_device_compiles" in text, text
    statusz = urllib.request.urlopen(base + "/statusz").read().decode()
    for needle in ("smoke.evaluate", "q64.b8192", "db_staging",
                   "SLO burn", "smoke_p99",
                   "Phase waterfall", "h2d_transfer", "device_compute",
                   "transfers", "key_staging",
                   "Auto-captured profiles", "/tmp/smoke-capture"):
        assert needle in statusz, (needle, statusz)
    sz_json = json.load(
        urllib.request.urlopen(base + "/statusz?format=json")
    )
    assert sz_json["phases"]["smoke"]["requests"] == 1, sz_json["phases"]
    led = sz_json["device"]["transfers"]["phases"]["key_staging"]
    assert led["h2d_copies"] == 1 and led["h2d_bytes"] == 4096, led
    assert len(sz_json["profiles"]["captures"]) == 1, sz_json["profiles"]
    sz = json.load(urllib.request.urlopen(base + "/statusz?format=json"))
    site = sz["device"]["compile"]["sites"]["smoke.evaluate"]
    assert site["compiles"] == 1 and site["hits"] == 1, site
    tracez = json.load(urllib.request.urlopen(base + "/tracez"))
    assert tracez["recorded"] == 1 and tracez["slowest"], tracez
    spans = [s["name"] for s in tracez["slowest"][0]["spans"]]
    assert "device_compute" in spans, spans
    try:
        urllib.request.urlopen(base + "/healthz")
        raise AssertionError("breached SLO did not degrade /healthz")
    except urllib.error.HTTPError as e:
        assert e.code == 503, e.code
        body = e.read().decode()
        assert "slo breach: smoke_p99" in body, body
    reg.reset()  # breach clears -> next probe recovers
    assert urllib.request.urlopen(base + "/healthz").read() == b"ok\n"
assert len(prof.captures()) == 1, prof.export()  # still exactly one
print("admin-smoke: OK (/metrics incl. exemplars, /statusz incl. phase "
      "waterfall + transfer ledger + auto-captures, /tracez, /healthz "
      "incl. SLO degrade+recover, one capture per burn)")
'

stage perf-gate python -m benchmarks.regression_gate --check-only \
    --history benchmarks/fixtures/history_fixture.jsonl

stage dryrun make -s dryrun

if [ "${FULL:-0}" = "1" ]; then
    stage test-full python -m pytest tests/ -q
fi

echo "presubmit: $([ $fail -eq 0 ] && echo PASS || echo FAIL)"
exit $fail
