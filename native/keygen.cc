// Native batched dense-PIR key generation.
//
// The reference's key generation is C++ (`GenerateKeysIncremental` /
// `GenerateNext`, dpf/distributed_point_function.cc:121-222, 642-707);
// this is the framework's native equivalent for the dense-PIR key shape
// (single hierarchy level, 128-bit XOR values): the per-level recurrence
// is run for a whole batch of keys in lockstep, with the AES-NI-batched
// MMO hash doing 2n blocks per (level, PRG key) call. Randomness (the
// root seeds) is supplied by the caller, keeping all crypto-random
// sourcing in one place (Python `secrets`).
//
// Bit/byte conventions match the Python engine exactly (16-byte
// little-endian blocks, control bit in byte 0 bit 0), so the output is
// bit-identical to `DistributedPointFunction.generate_keys_batch` given
// the same root seeds — which is how it is differentially tested.

#include <cstdint>
#include <cstring>
#include <vector>

#include "aes128.h"

namespace {

using dpf_native::Aes128Key;
using dpf_native::Aes128KeyExpand;
using dpf_native::Aes128MmoHash;

inline void Xor16(const uint8_t* a, const uint8_t* b, uint8_t* out) {
  for (int i = 0; i < 16; ++i) out[i] = a[i] ^ b[i];
}

}  // namespace

extern "C" {

// All output arrays are caller-allocated:
//   root_seeds: [2][n][16] (party-major; input)
//   alphas:     [n] (alpha < 2^levels <= 2^63)
//   betas:      [n][16]
//   cw_seeds:   [levels][n][16]  (out)
//   cw_ctrl:    [levels][n][2]   (out: left, right)
//   last_vc:    [n][16]          (out)
void dpf_keygen_batch_dense(const uint8_t key_left[16],
                            const uint8_t key_right[16],
                            const uint8_t key_value[16],
                            const uint8_t* root_seeds, const uint64_t* alphas,
                            const uint8_t* betas, int levels, int64_t n,
                            uint8_t* cw_seeds, uint8_t* cw_ctrl,
                            uint8_t* last_vc) {
  Aes128Key kl, kr, kv;
  Aes128KeyExpand(key_left, &kl);
  Aes128KeyExpand(key_right, &kr);
  Aes128KeyExpand(key_value, &kv);

  // seeds: [2n][16], parties interleaved as [party][key] (party-major).
  std::vector<uint8_t> seeds(root_seeds, root_seeds + 2 * n * 16);
  std::vector<uint8_t> control(2 * n, 0);
  for (int64_t i = 0; i < n; ++i) control[n + i] = 1;  // party 1

  std::vector<uint8_t> hl(2 * n * 16), hr(2 * n * 16);
  for (int level = 1; level <= levels; ++level) {
    Aes128MmoHash(kl, seeds.data(), hl.data(), 2 * n);
    Aes128MmoHash(kr, seeds.data(), hr.data(), 2 * n);
    const int bit_pos = levels - level;
    for (int64_t i = 0; i < n; ++i) {
      uint8_t* l0 = hl.data() + 16 * i;
      uint8_t* l1 = hl.data() + 16 * (n + i);
      uint8_t* r0 = hr.data() + 16 * i;
      uint8_t* r1 = hr.data() + 16 * (n + i);
      const uint8_t t_l0 = l0[0] & 1, t_l1 = l1[0] & 1;
      const uint8_t t_r0 = r0[0] & 1, t_r1 = r1[0] & 1;
      l0[0] &= 0xFE; l1[0] &= 0xFE; r0[0] &= 0xFE; r1[0] &= 0xFE;

      const uint8_t bit = (alphas[i] >> bit_pos) & 1;
      uint8_t* cw = cw_seeds + 16 * ((level - 1) * n + i);
      // lose = 1 - bit: XOR the two parties' hashes on the lose branch.
      if (bit) Xor16(l0, l1, cw); else Xor16(r0, r1, cw);
      const uint8_t cw_left = t_l0 ^ t_l1 ^ bit ^ 1;
      const uint8_t cw_right = t_r0 ^ t_r1 ^ bit;
      uint8_t* ctrl = cw_ctrl + 2 * ((level - 1) * n + i);
      ctrl[0] = cw_left;
      ctrl[1] = cw_right;
      const uint8_t cw_keep = bit ? cw_right : cw_left;

      for (int b = 0; b < 2; ++b) {
        const uint8_t* keep = bit ? (b ? r1 : r0) : (b ? l1 : l0);
        const uint8_t keep_t = bit ? (b ? t_r1 : t_r0) : (b ? t_l1 : t_l0);
        uint8_t* dst = seeds.data() + 16 * (b * n + i);
        if (control[b * n + i]) {
          Xor16(keep, cw, dst);
        } else {
          std::memcpy(dst, keep, 16);
        }
        control[b * n + i] = keep_t ^ (control[b * n + i] & cw_keep);
      }
    }
  }

  // Last-level value correction: H_value(s0) ^ H_value(s1) ^ beta
  // (both group ops are XOR for 128-bit XOR shares; party negation is the
  // identity — ComputeValueCorrection, distributed_point_function.cc:81-117).
  std::vector<uint8_t> ha(n * 16), hb(n * 16);
  Aes128MmoHash(kv, seeds.data(), ha.data(), n);
  Aes128MmoHash(kv, seeds.data() + 16 * n, hb.data(), n);
  for (int64_t i = 0; i < n; ++i) {
    uint8_t* out = last_vc + 16 * i;
    Xor16(ha.data() + 16 * i, hb.data() + 16 * i, out);
    Xor16(out, betas + 16 * i, out);
  }
}

}  // extern "C"
