// AES-128 core for the native CPU kernels.
//
// Role: the CPU reference/oracle mirroring the reference library's OpenSSL
// `Aes128FixedKeyHash` (dpf/aes_128_fixed_key_hash.{h,cc}) and the scalar
// fallback of its Highway kernels (dpf/internal/evaluate_prg_hwy.cc:552-634).
// Table-free, constant-time-ish bytewise implementation — this path is for
// correctness oracles and host-side work, not the hot loop (the hot loop
// lives on the TPU).
//
// Block convention: 16 bytes little-endian, matching the framework's
// uint32[4] limb layout (see distributed_point_functions_tpu/ops/aes.py).

#ifndef DPF_NATIVE_AES128_H_
#define DPF_NATIVE_AES128_H_

#include <cstdint>
#include <cstring>

namespace dpf_native {

struct Aes128Key {
  // Expanded round keys: 11 x 16 bytes.
  uint8_t rk[11][16];
};

// Expands a 16-byte key into round keys.
void Aes128KeyExpand(const uint8_t key[16], Aes128Key* out);

// Encrypts `num_blocks` 16-byte blocks in ECB mode (in-place allowed).
void Aes128EncryptBlocks(const Aes128Key& key, const uint8_t* in, uint8_t* out,
                         int64_t num_blocks);

// sigma(x) = (hi ^ lo, hi): the circular-correlation-robust linear map of
// the MMO construction (dpf/aes_128_fixed_key_hash.h:28-39). Bytes 0..7 are
// `lo`, bytes 8..15 `hi` (little-endian).
inline void Sigma(const uint8_t in[16], uint8_t out[16]) {
  for (int i = 0; i < 8; ++i) {
    out[i] = in[8 + i];           // low half  <- hi
    out[8 + i] = in[8 + i] ^ in[i];  // high half <- hi ^ lo
  }
}

// H(x) = AES_k(sigma(x)) ^ sigma(x), batched.
void Aes128MmoHash(const Aes128Key& key, const uint8_t* in, uint8_t* out,
                   int64_t num_blocks);


// AES-NI fast path (aesni.cc, compiled with -maes). Gate on
// AesNiSupported() before calling.
bool AesNiSupported();
void Aes128EncryptBlocksNi(const Aes128Key& key, const uint8_t* in,
                           uint8_t* out, int64_t num_blocks);

}  // namespace dpf_native

#endif  // DPF_NATIVE_AES128_H_
