#include "aes128.h"
#include <vector>

namespace dpf_native {
namespace {

// S-box generated at startup from the GF(2^8) inverse + affine map, so no
// table constants are copied from anywhere.
struct SboxTable {
  uint8_t sbox[256];
  SboxTable() {
    auto gf_mul = [](int a, int b) {
      int r = 0;
      while (b) {
        if (b & 1) r ^= a;
        a <<= 1;
        if (a & 0x100) a ^= 0x11B;
        b >>= 1;
      }
      return r;
    };
    uint8_t inv[256] = {0};
    for (int x = 1; x < 256; ++x) {
      for (int y = 1; y < 256; ++y) {
        if (gf_mul(x, y) == 1) {
          inv[x] = static_cast<uint8_t>(y);
          break;
        }
      }
    }
    for (int x = 0; x < 256; ++x) {
      int b = inv[x];
      int res = 0;
      for (int i = 0; i < 8; ++i) {
        int bit = ((b >> i) ^ (b >> ((i + 4) % 8)) ^ (b >> ((i + 5) % 8)) ^
                   (b >> ((i + 6) % 8)) ^ (b >> ((i + 7) % 8)) ^ (0x63 >> i)) &
                  1;
        res |= bit << i;
      }
      sbox[x] = static_cast<uint8_t>(res);
    }
  }
};

const SboxTable kTables;

inline uint8_t XTime(uint8_t b) {
  return static_cast<uint8_t>((b << 1) ^ ((b >> 7) * 0x1B));
}

inline void MixColumns(uint8_t s[16]) {
  for (int c = 0; c < 4; ++c) {
    uint8_t* col = s + 4 * c;
    uint8_t t = col[0] ^ col[1] ^ col[2] ^ col[3];
    uint8_t s0 = col[0];
    uint8_t tmp0 = col[0] ^ t ^ XTime(static_cast<uint8_t>(col[0] ^ col[1]));
    uint8_t tmp1 = col[1] ^ t ^ XTime(static_cast<uint8_t>(col[1] ^ col[2]));
    uint8_t tmp2 = col[2] ^ t ^ XTime(static_cast<uint8_t>(col[2] ^ col[3]));
    uint8_t tmp3 = col[3] ^ t ^ XTime(static_cast<uint8_t>(col[3] ^ s0));
    col[0] = tmp0;
    col[1] = tmp1;
    col[2] = tmp2;
    col[3] = tmp3;
  }
}

inline void ShiftRows(uint8_t s[16]) {
  // Flat index r + 4c; row r rotates left by r.
  uint8_t tmp[16];
  for (int c = 0; c < 4; ++c) {
    for (int r = 0; r < 4; ++r) {
      tmp[r + 4 * c] = s[r + 4 * ((c + r) % 4)];
    }
  }
  std::memcpy(s, tmp, 16);
}

inline void SubBytes(uint8_t s[16]) {
  for (int i = 0; i < 16; ++i) s[i] = kTables.sbox[s[i]];
}

inline void AddRoundKey(uint8_t s[16], const uint8_t rk[16]) {
  for (int i = 0; i < 16; ++i) s[i] ^= rk[i];
}

}  // namespace

void Aes128KeyExpand(const uint8_t key[16], Aes128Key* out) {
  static const uint8_t rcon[10] = {0x01, 0x02, 0x04, 0x08, 0x10,
                                   0x20, 0x40, 0x80, 0x1B, 0x36};
  uint8_t w[44][4];
  std::memcpy(w, key, 16);
  for (int i = 4; i < 44; ++i) {
    uint8_t temp[4];
    std::memcpy(temp, w[i - 1], 4);
    if (i % 4 == 0) {
      uint8_t t0 = temp[0];
      temp[0] = static_cast<uint8_t>(kTables.sbox[temp[1]] ^ rcon[i / 4 - 1]);
      temp[1] = kTables.sbox[temp[2]];
      temp[2] = kTables.sbox[temp[3]];
      temp[3] = kTables.sbox[t0];
    }
    for (int j = 0; j < 4; ++j) w[i][j] = w[i - 4][j] ^ temp[j];
  }
  std::memcpy(out->rk, w, 176);
}

void Aes128EncryptBlocks(const Aes128Key& key, const uint8_t* in, uint8_t* out,
                         int64_t num_blocks) {
  for (int64_t b = 0; b < num_blocks; ++b) {
    uint8_t s[16];
    std::memcpy(s, in + 16 * b, 16);
    AddRoundKey(s, key.rk[0]);
    for (int r = 1; r < 10; ++r) {
      SubBytes(s);
      ShiftRows(s);
      MixColumns(s);
      AddRoundKey(s, key.rk[r]);
    }
    SubBytes(s);
    ShiftRows(s);
    AddRoundKey(s, key.rk[10]);
    std::memcpy(out + 16 * b, s, 16);
  }
}

void Aes128MmoHash(const Aes128Key& key, const uint8_t* in, uint8_t* out,
                   int64_t num_blocks) {
  // Hardware AES when available (cached probe); the bytewise path below is
  // the oracle/fallback. Batched: sigma all blocks, one pipelined encrypt
  // pass, then the feed-forward XOR.
  static const bool have_ni = AesNiSupported();
  if (have_ni && num_blocks > 1) {
    std::vector<uint8_t> sig(16 * num_blocks);
    for (int64_t b = 0; b < num_blocks; ++b) Sigma(in + 16 * b, sig.data() + 16 * b);
    Aes128EncryptBlocksNi(key, sig.data(), out, num_blocks);
    for (int64_t i = 0; i < 16 * num_blocks; ++i) out[i] ^= sig[i];
    return;
  }
  for (int64_t b = 0; b < num_blocks; ++b) {
    uint8_t sig[16];
    Sigma(in + 16 * b, sig);
    uint8_t enc[16];
    Aes128EncryptBlocks(key, sig, enc, 1);
    for (int i = 0; i < 16; ++i) out[16 * b + i] = enc[i] ^ sig[i];
  }
}

}  // namespace dpf_native
