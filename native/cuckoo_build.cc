// Native cuckoo-table builder for the sparse-PIR database.
//
// The Python insertion loop (hash + random-eviction per key,
// `hashing/cuckoo_hash_table.py`, mirroring the reference's
// `pir/hashing/cuckoo_hash_table.cc:66-91`) costs ~23 minutes at the
// 2^24-key BASELINE config; this builder does the same job natively:
// per key, `num_hashes` bucket indices from SHA256(seed_i || key)
// reduced mod num_buckets exactly like the Python/reference semantics
// (digest as a little-endian 256-bit integer,
// `hashing/sha256_hash_family.py`), then cuckoo insertion with random
// eviction. The produced table layout need not (and does not) match the
// Python builder bit-for-bit — any legal assignment serves the protocol;
// tests check legality (every key in one of its buckets) and end-to-end
// serving.
//
// C API (ctypes, see distributed_point_functions_tpu/native.py):
//   dpf_cuckoo_hash_buckets: per-key bucket indices only (shared by the
//     client-side differential tests).
//   dpf_cuckoo_build: full build; out_slots[num_buckets] holds the key
//     index occupying each bucket, or -1. Returns 0, or -1 when a key
//     cannot be placed within max_relocations, -2 on bad arguments.

#include <cstdint>
#include <random>
#include <vector>

#include "sha256.h"

namespace {

using dpf_native::Sha256;

int64_t BucketOf(const uint8_t* seed, size_t seed_len, const uint8_t* key,
                 size_t key_len, int64_t num_buckets) {
  uint8_t digest[32];
  Sha256 ctx;
  ctx.Update(seed, seed_len);
  ctx.Update(key, key_len);
  ctx.Final(digest);
  // Little-endian 256-bit value mod num_buckets, high words first:
  // value = sum_k w_k * 2^(64k), w_k = LE uint64 at digest[8k].
  unsigned __int128 r = 0;
  for (int k = 3; k >= 0; --k) {
    uint64_t w = 0;
    for (int b = 7; b >= 0; --b) {
      w = (w << 8) | digest[8 * k + b];
    }
    r = ((r << 64) | w) % (unsigned __int128)num_buckets;
  }
  return (int64_t)r;
}

}  // namespace

extern "C" {

// Per-key bucket indices: out[k * num_hashes + i] = hash_i(key_k).
// seeds_concat/seed_offsets frame the per-hash seed byte strings
// (seed i = bytes [seed_offsets[i], seed_offsets[i+1])).
int dpf_cuckoo_hash_buckets(const uint8_t* keys_concat,
                            const uint64_t* key_offsets, int64_t num_keys,
                            const uint8_t* seeds_concat,
                            const uint64_t* seed_offsets, int num_hashes,
                            int64_t num_buckets, int64_t* out) {
  if (num_keys < 0 || num_hashes <= 0 || num_buckets <= 0) return -2;
  for (int64_t k = 0; k < num_keys; ++k) {
    const uint8_t* key = keys_concat + key_offsets[k];
    size_t key_len = key_offsets[k + 1] - key_offsets[k];
    for (int i = 0; i < num_hashes; ++i) {
      const uint8_t* seed = seeds_concat + seed_offsets[i];
      size_t seed_len = seed_offsets[i + 1] - seed_offsets[i];
      out[k * num_hashes + i] =
          BucketOf(seed, seed_len, key, key_len, num_buckets);
    }
  }
  return 0;
}

int dpf_cuckoo_build(const uint8_t* keys_concat, const uint64_t* key_offsets,
                     int64_t num_keys, const uint8_t* seeds_concat,
                     const uint64_t* seed_offsets, int num_hashes,
                     int64_t num_buckets, int64_t max_relocations,
                     uint64_t rng_seed, int64_t* out_slots) {
  if (num_keys < 0 || num_hashes < 2 || num_buckets <= 0 ||
      max_relocations < 0) {
    return -2;
  }
  std::vector<int64_t> buckets((size_t)num_keys * num_hashes);
  int rc = dpf_cuckoo_hash_buckets(keys_concat, key_offsets, num_keys,
                                   seeds_concat, seed_offsets, num_hashes,
                                   num_buckets, buckets.data());
  if (rc != 0) return rc;
  for (int64_t b = 0; b < num_buckets; ++b) out_slots[b] = -1;

  std::mt19937_64 rng(rng_seed);
  for (int64_t k = 0; k < num_keys; ++k) {
    int64_t current = k;
    int64_t hops = 0;
    for (;;) {
      const int64_t* cand = &buckets[(size_t)current * num_hashes];
      bool placed = false;
      for (int i = 0; i < num_hashes; ++i) {
        if (out_slots[cand[i]] < 0) {
          out_slots[cand[i]] = current;
          placed = true;
          break;
        }
      }
      if (placed) break;
      if (hops++ >= max_relocations) return -1;
      int64_t victim_bucket = cand[rng() % num_hashes];
      int64_t evicted = out_slots[victim_bucket];
      out_slots[victim_bucket] = current;
      current = evicted;
    }
  }
  return 0;
}

}  // extern "C"
