// AES-NI fast path for the native CPU kernels.
//
// The reference's CPU hot loop rides OpenSSL/Highway AES-NI
// (dpf/internal/aes_128_fixed_key_hash_hwy.h); this translation unit is
// the equivalent for the framework's native library: hardware AES rounds,
// 8 blocks in flight to fill the aesenc pipeline. Compiled with -maes
// (see build.sh); callers must gate on AesNiSupported().
//
// Block and round-key layout match aes128.h (16-byte little-endian blocks,
// standard expanded schedule), so this slots under Aes128MmoHash as a
// drop-in accelerated body.

#include "aes128.h"

#include <cstdint>
#include <cstring>
#include <wmmintrin.h>

namespace dpf_native {

bool AesNiSupported() {
#if defined(__GNUC__)
  return __builtin_cpu_supports("aes");
#else
  return false;
#endif
}

namespace {

inline __m128i RoundKey(const Aes128Key& key, int r) {
  return _mm_loadu_si128(reinterpret_cast<const __m128i*>(key.rk[r]));
}

template <int N>
inline void EncryptLanes(const Aes128Key& key, __m128i b[N]) {
  const __m128i k0 = RoundKey(key, 0);
  for (int j = 0; j < N; ++j) b[j] = _mm_xor_si128(b[j], k0);
  for (int r = 1; r < 10; ++r) {
    const __m128i kr = RoundKey(key, r);
    for (int j = 0; j < N; ++j) b[j] = _mm_aesenc_si128(b[j], kr);
  }
  const __m128i k10 = RoundKey(key, 10);
  for (int j = 0; j < N; ++j) b[j] = _mm_aesenclast_si128(b[j], k10);
}

}  // namespace

void Aes128EncryptBlocksNi(const Aes128Key& key, const uint8_t* in,
                           uint8_t* out, int64_t num_blocks) {
  int64_t i = 0;
  for (; i + 8 <= num_blocks; i += 8) {
    __m128i b[8];
    for (int j = 0; j < 8; ++j)
      b[j] = _mm_loadu_si128(
          reinterpret_cast<const __m128i*>(in + (i + j) * 16));
    EncryptLanes<8>(key, b);
    for (int j = 0; j < 8; ++j)
      _mm_storeu_si128(reinterpret_cast<__m128i*>(out + (i + j) * 16), b[j]);
  }
  for (; i < num_blocks; ++i) {
    __m128i b[1] = {
        _mm_loadu_si128(reinterpret_cast<const __m128i*>(in + i * 16))};
    EncryptLanes<1>(key, b);
    _mm_storeu_si128(reinterpret_cast<__m128i*>(out + i * 16), b[0]);
  }
}

}  // namespace dpf_native
