// SHA-256 (FIPS 180-4) — compact single-header implementation for the
// native cuckoo builder. Implemented from the spec (message schedule +
// 64-round compression with the standard K constants); no third-party
// code. Verified against hashlib by the Python differential tests
// (tests/test_native_cuckoo.py).
#pragma once

#include <cstdint>
#include <cstring>

namespace dpf_native {

struct Sha256 {
  uint32_t h[8];
  uint64_t bytes = 0;
  uint8_t buf[64];

  Sha256() {
    static const uint32_t init[8] = {
        0x6a09e667u, 0xbb67ae85u, 0x3c6ef372u, 0xa54ff53au,
        0x510e527fu, 0x9b05688cu, 0x1f83d9abu, 0x5be0cd19u};
    std::memcpy(h, init, sizeof(h));
  }

  static uint32_t Rotr(uint32_t x, int n) {
    return (x >> n) | (x << (32 - n));
  }

  void Compress(const uint8_t* p) {
    static const uint32_t k[64] = {
        0x428a2f98u, 0x71374491u, 0xb5c0fbcfu, 0xe9b5dba5u, 0x3956c25bu,
        0x59f111f1u, 0x923f82a4u, 0xab1c5ed5u, 0xd807aa98u, 0x12835b01u,
        0x243185beu, 0x550c7dc3u, 0x72be5d74u, 0x80deb1feu, 0x9bdc06a7u,
        0xc19bf174u, 0xe49b69c1u, 0xefbe4786u, 0x0fc19dc6u, 0x240ca1ccu,
        0x2de92c6fu, 0x4a7484aau, 0x5cb0a9dcu, 0x76f988dau, 0x983e5152u,
        0xa831c66du, 0xb00327c8u, 0xbf597fc7u, 0xc6e00bf3u, 0xd5a79147u,
        0x06ca6351u, 0x14292967u, 0x27b70a85u, 0x2e1b2138u, 0x4d2c6dfcu,
        0x53380d13u, 0x650a7354u, 0x766a0abbu, 0x81c2c92eu, 0x92722c85u,
        0xa2bfe8a1u, 0xa81a664bu, 0xc24b8b70u, 0xc76c51a3u, 0xd192e819u,
        0xd6990624u, 0xf40e3585u, 0x106aa070u, 0x19a4c116u, 0x1e376c08u,
        0x2748774cu, 0x34b0bcb5u, 0x391c0cb3u, 0x4ed8aa4au, 0x5b9cca4fu,
        0x682e6ff3u, 0x748f82eeu, 0x78a5636fu, 0x84c87814u, 0x8cc70208u,
        0x90befffau, 0xa4506cebu, 0xbef9a3f7u, 0xc67178f2u};
    uint32_t w[64];
    for (int i = 0; i < 16; ++i) {
      w[i] = (uint32_t(p[4 * i]) << 24) | (uint32_t(p[4 * i + 1]) << 16) |
             (uint32_t(p[4 * i + 2]) << 8) | uint32_t(p[4 * i + 3]);
    }
    for (int i = 16; i < 64; ++i) {
      uint32_t s0 = Rotr(w[i - 15], 7) ^ Rotr(w[i - 15], 18) ^
                    (w[i - 15] >> 3);
      uint32_t s1 = Rotr(w[i - 2], 17) ^ Rotr(w[i - 2], 19) ^
                    (w[i - 2] >> 10);
      w[i] = w[i - 16] + s0 + w[i - 7] + s1;
    }
    uint32_t a = h[0], b = h[1], c = h[2], d = h[3], e = h[4], f = h[5],
             g = h[6], hh = h[7];
    for (int i = 0; i < 64; ++i) {
      uint32_t s1 = Rotr(e, 6) ^ Rotr(e, 11) ^ Rotr(e, 25);
      uint32_t ch = (e & f) ^ (~e & g);
      uint32_t t1 = hh + s1 + ch + k[i] + w[i];
      uint32_t s0 = Rotr(a, 2) ^ Rotr(a, 13) ^ Rotr(a, 22);
      uint32_t maj = (a & b) ^ (a & c) ^ (b & c);
      uint32_t t2 = s0 + maj;
      hh = g;
      g = f;
      f = e;
      e = d + t1;
      d = c;
      c = b;
      b = a;
      a = t1 + t2;
    }
    h[0] += a;
    h[1] += b;
    h[2] += c;
    h[3] += d;
    h[4] += e;
    h[5] += f;
    h[6] += g;
    h[7] += hh;
  }

  void Update(const uint8_t* data, size_t len) {
    size_t fill = bytes % 64;
    bytes += len;
    if (fill) {
      size_t need = 64 - fill;
      if (len < need) {
        std::memcpy(buf + fill, data, len);
        return;
      }
      std::memcpy(buf + fill, data, need);
      Compress(buf);
      data += need;
      len -= need;
    }
    while (len >= 64) {
      Compress(data);
      data += 64;
      len -= 64;
    }
    if (len) std::memcpy(buf, data, len);
  }

  void Final(uint8_t out[32]) {
    uint64_t bitlen = bytes * 8;
    uint8_t pad[72];
    size_t fill = bytes % 64;
    size_t padlen = (fill < 56) ? 56 - fill : 120 - fill;
    pad[0] = 0x80;
    std::memset(pad + 1, 0, padlen - 1);
    for (int i = 0; i < 8; ++i) {
      pad[padlen + i] = uint8_t(bitlen >> (56 - 8 * i));
    }
    Update(pad, padlen + 8);
    for (int i = 0; i < 8; ++i) {
      out[4 * i] = uint8_t(h[i] >> 24);
      out[4 * i + 1] = uint8_t(h[i] >> 16);
      out[4 * i + 2] = uint8_t(h[i] >> 8);
      out[4 * i + 3] = uint8_t(h[i]);
    }
  }
};

}  // namespace dpf_native
