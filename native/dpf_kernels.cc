// Native CPU kernels + C API (ctypes binding surface).
//
// The three performance-relevant primitives of the reference, as scalar C++
// oracles for the TPU kernels (mirroring the NoHwy role of
// dpf/internal/evaluate_prg_hwy.cc:552-634 and
// pir/internal/inner_product_hwy.cc:270-296):
//
//  * dpf_expand_level   — one breadth-first tree level (ExpandSeeds inner
//                         loop, dpf/distributed_point_function.cc:327-370)
//  * dpf_evaluate_seeds — multi-level batch point evaluation with shared or
//                         per-seed correction words
//                         (dpf/internal/evaluate_prg_hwy.h:58-77)
//  * dpf_inner_product  — packed-XOR database inner product
//                         (pir/internal/inner_product_hwy.cc:300-334)
//
// All block buffers are 16-byte little-endian AES blocks; control bits ride
// in the seeds' LSBs exactly like the reference's ExtractAndClearLowestBit
// convention (evaluate_prg_hwy.h:32-36) at the API boundary, but this C API
// keeps them in separate byte arrays for clarity.

#include <cstdint>
#include <cstring>

#include "aes128.h"

namespace {

using dpf_native::Aes128Key;

struct FixedKeys {
  Aes128Key left, right, value;
};

inline bool GetBit(const uint8_t* block, int bit_index) {
  if (bit_index < 0 || bit_index >= 128) return false;
  return (block[bit_index / 8] >> (bit_index % 8)) & 1;
}

}  // namespace

extern "C" {

// Opaque fixed-key context.
void* dpf_create_keys(const uint8_t key_left[16], const uint8_t key_right[16],
                      const uint8_t key_value[16]) {
  auto* keys = new FixedKeys();
  dpf_native::Aes128KeyExpand(key_left, &keys->left);
  dpf_native::Aes128KeyExpand(key_right, &keys->right);
  dpf_native::Aes128KeyExpand(key_value, &keys->value);
  return keys;
}

void dpf_free_keys(void* ctx) { delete static_cast<FixedKeys*>(ctx); }

void dpf_mmo_hash(void* ctx, int which, const uint8_t* in, uint8_t* out,
                  int64_t num_blocks) {
  auto* keys = static_cast<FixedKeys*>(ctx);
  const Aes128Key& k = which == 0   ? keys->left
                       : which == 1 ? keys->right
                                    : keys->value;
  dpf_native::Aes128MmoHash(k, in, out, num_blocks);
}

// One breadth-first expansion level: seeds[n] -> seeds_out[2n], interleaved
// (left_i, right_i). control bits are 0/1 bytes.
void dpf_expand_level(void* ctx, const uint8_t* seeds, const uint8_t* control,
                      const uint8_t cw_seed[16], uint8_t cw_left,
                      uint8_t cw_right, uint8_t* seeds_out,
                      uint8_t* control_out, int64_t n) {
  auto* keys = static_cast<FixedKeys*>(ctx);
  for (int64_t i = 0; i < n; ++i) {
    const uint8_t* seed = seeds + 16 * i;
    for (int branch = 0; branch < 2; ++branch) {
      uint8_t* out = seeds_out + 16 * (2 * i + branch);
      dpf_native::Aes128MmoHash(branch == 0 ? keys->left : keys->right, seed,
                                out, 1);
      if (control[i]) {
        for (int j = 0; j < 16; ++j) out[j] ^= cw_seed[j];
      }
      uint8_t t = out[0] & 1;
      out[0] &= 0xFE;
      t ^= control[i] & (branch == 0 ? cw_left : cw_right);
      control_out[2 * i + branch] = t;
    }
  }
}

// Batch point evaluation: walk `num_levels` levels for each of `n` seeds.
// paths: n x 16-byte blocks; the path bit for level j is bit
// (num_levels - 1 - j + paths_rightshift) of the path.
// cw_seeds: [num_levels * cw_stride] blocks, cw_stride == 1 for shared
// correction words or == n for per-seed (the multi-key batch mode of
// evaluate_prg_hwy.h:58-65). cw_left/right: same layout, one byte each.
void dpf_evaluate_seeds(void* ctx, uint8_t* seeds, uint8_t* control,
                        const uint8_t* paths, const uint8_t* cw_seeds,
                        const uint8_t* cw_left, const uint8_t* cw_right,
                        int64_t n, int num_levels, int64_t cw_stride,
                        int paths_rightshift) {
  auto* keys = static_cast<FixedKeys*>(ctx);
  for (int64_t i = 0; i < n; ++i) {
    uint8_t* seed = seeds + 16 * i;
    uint8_t t = control[i];
    for (int level = 0; level < num_levels; ++level) {
      int bit_index = num_levels - 1 - level + paths_rightshift;
      bool path_bit = GetBit(paths + 16 * i, bit_index);
      int64_t cw_index =
          static_cast<int64_t>(level) * cw_stride + (cw_stride == 1 ? 0 : i);
      uint8_t h[16];
      dpf_native::Aes128MmoHash(path_bit ? keys->right : keys->left, seed, h,
                                1);
      if (t) {
        for (int j = 0; j < 16; ++j) h[j] ^= cw_seeds[16 * cw_index + j];
      }
      uint8_t t_new = h[0] & 1;
      h[0] &= 0xFE;
      t_new ^= t & (path_bit ? cw_right[cw_index] : cw_left[cw_index]);
      std::memcpy(seed, h, 16);
      t = t_new;
    }
    control[i] = t;
  }
}

// Value hash: out[i*blocks + j] = H_value(seed_i + j), the output PRG of
// HashExpandedSeeds (dpf/distributed_point_function.cc:523-547).
void dpf_value_hash(void* ctx, const uint8_t* seeds, uint8_t* out, int64_t n,
                    int num_blocks) {
  auto* keys = static_cast<FixedKeys*>(ctx);
  for (int64_t i = 0; i < n; ++i) {
    for (int j = 0; j < num_blocks; ++j) {
      // seed + j as a 128-bit little-endian integer.
      uint8_t block[16];
      std::memcpy(block, seeds + 16 * i, 16);
      uint64_t carry = static_cast<uint64_t>(j);
      for (int b = 0; b < 16 && carry; ++b) {
        uint64_t v = block[b] + (carry & 0xFF);
        block[b] = static_cast<uint8_t>(v);
        carry = (carry >> 8) + (v >> 8);
      }
      dpf_native::Aes128MmoHash(keys->value, block,
                                out + 16 * (i * num_blocks + j), 1);
    }
  }
}

// Packed-XOR inner product. db: num_records x record_words uint32 rows
// (little-endian); selections: nq x num_blocks x 16 bytes; the bit for
// record r is bit (r % 128) of block (r / 128). out: nq x record_words.
void dpf_inner_product(const uint32_t* db, int64_t num_records,
                       int64_t record_words, const uint8_t* selections,
                       int64_t nq, int64_t num_blocks, uint32_t* out) {
  std::memset(out, 0, sizeof(uint32_t) * nq * record_words);
  for (int64_t q = 0; q < nq; ++q) {
    const uint8_t* sel = selections + q * num_blocks * 16;
    uint32_t* acc = out + q * record_words;
    for (int64_t r = 0; r < num_records; ++r) {
      int64_t block = r / 128;
      if (block >= num_blocks) break;
      int bit = static_cast<int>(r % 128);
      if ((sel[block * 16 + bit / 8] >> (bit % 8)) & 1) {
        const uint32_t* row = db + r * record_words;
        for (int64_t w = 0; w < record_words; ++w) acc[w] ^= row[w];
      }
    }
  }
}

}  // extern "C"
