#!/bin/sh
# Build libdpf_native.so (the CPU oracle kernels + ctypes C API).
set -e
cd "$(dirname "$0")"
# aesni.cc is the only unit built with -maes; callers gate on
# AesNiSupported() so the library still loads on machines without AES-NI.
g++ -O2 -fPIC -maes -std=c++17 -c aesni.cc -o aesni.o
g++ -O2 -fPIC -std=c++17 -c aes128.cc -o aes128.o
g++ -O2 -fPIC -std=c++17 -c dpf_kernels.cc -o dpf_kernels.o
g++ -O2 -fPIC -std=c++17 -c keygen.cc -o keygen.o
g++ -O2 -fPIC -std=c++17 -c cuckoo_build.cc -o cuckoo_build.o
g++ -shared -o libdpf_native.so aes128.o aesni.o dpf_kernels.o keygen.o cuckoo_build.o
rm -f aes128.o aesni.o dpf_kernels.o keygen.o cuckoo_build.o
echo "built $(pwd)/libdpf_native.so"
