#!/bin/sh
# Build libdpf_native.so (the CPU oracle kernels + ctypes C API).
set -e
cd "$(dirname "$0")"
g++ -O2 -fPIC -shared -std=c++17 -o libdpf_native.so aes128.cc dpf_kernels.cc
echo "built $(pwd)/libdpf_native.so"
