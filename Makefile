# One-command entries for the TPU-native DPF framework.
#
# The reference drives everything through Bazel + .bazelci/presubmit.yml;
# here the equivalents are pytest (hermetic CPU, 8 virtual devices),
# protoc codegen, the native C++ oracle build, and the benchmark suites.

PY ?= python

.PHONY: test test-fast protos native bench bench-tpu sweeps dryrun lint ci

test:          ## full hermetic suite (CPU, virtual 8-device mesh)
	$(PY) -m pytest tests/ -q

test-fast:     ## ~8 min hermetic signal incl. core invariants + tiny Pallas
	$(PY) -m pytest tests/test_aes.py tests/test_aes_sbox_tower.py \
	    tests/test_proto_validator.py tests/test_hybrid_crypto.py \
	    tests/test_serialization.py tests/test_farm_hash.py \
	    tests/test_native.py tests/test_native_cuckoo.py \
	    tests/test_testing_utils.py tests/test_demo.py \
	    tests/test_core_fast.py \
	    tests/test_serving_batcher.py tests/test_serving_transport.py \
	    tests/test_serving_service.py tests/test_observability.py \
	    tests/test_device_observability.py tests/test_slo.py \
	    tests/test_phase_recorder.py tests/test_transfer_ledger.py \
	    tests/test_critical_path.py \
	    tests/test_autoprofile.py \
	    tests/test_events.py tests/test_debug_bundle.py \
	    tests/test_prober.py \
	    tests/test_regression_gate.py \
	    tests/test_robustness.py tests/test_chaos.py \
	    tests/test_snapshots.py \
	    tests/test_pipelined_staging.py tests/test_pipelined_batcher.py \
	    tests/test_capacity.py tests/test_overload.py \
	    tests/test_heavy_hitters.py tests/test_incremental_reuse.py \
	    tests/test_mesh_serving.py \
	    tests/test_fleet.py tests/test_fleet_rotation.py \
	    tests/test_fleet_consistency.py \
	    tests/test_federation.py tests/test_fleet_telemetry.py \
	    tests/test_single_device_donation.py \
	    tests/test_sparse_degraded.py \
	    tests/test_pallas_fast.py tests/test_bench_ladder.py -q

protos:        ## regenerate *_pb2.py from protos/*.proto
	cd protos && ./generate.sh

native:        ## build the C++ oracle kernels (ctypes-loaded)
	cd native && ./build.sh

bench:         ## headline benchmark (real TPU; emits one JSON line)
	$(PY) bench.py

bench-tpu:     ## full hardware capture into benchmarks/results/
	bash benchmarks/capture_tpu.sh

sweeps:        ## reference-mirroring benchmark sweeps (small shapes)
	$(PY) benchmarks/run_benchmarks.py

dryrun:        ## driver-style multichip dryrun on 8 virtual CPU devices
	JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
	    $(PY) -c "import __graft_entry__ as g; g.dryrun_multichip(8)"

lint:          ## stdlib AST lint (no flake8/ruff in this image)
	$(PY) tools/lint.py

ci:            ## presubmit: lint + protoc-check + native + test-fast + dryrun
	bash ci/presubmit.sh
