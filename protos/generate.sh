#!/bin/sh
# Regenerate the Python proto modules into distributed_point_functions_tpu/protos/.
set -e
cd "$(dirname "$0")"
protoc -I . --python_out=../distributed_point_functions_tpu/protos \
  distributed_point_function.proto \
  hash_family_config.proto \
  distributed_comparison_function.proto \
  multiple_interval_containment.proto \
  private_information_retrieval.proto
echo "generated into ../distributed_point_functions_tpu/protos"
