"""Benchmark suite mirroring the reference's benchmark binaries (SURVEY.md §2.7)."""
